"""Adversarial convergence simulator CLI (docs/simulation.md).

    python -m crdt_enc_tpu.tools.sim run --seed 42 --replicas 8 \
        --steps 500 --faults all [--backend memory|fs] [--deltas] \
        [--daemon] [--shrink OUT.json]
    python -m crdt_enc_tpu.tools.sim explore --seeds 0:20 --replicas 4 \
        --steps 120 --faults all [--population P] [--budget-s N] \
        [--coverage-out f.json] [--shrink OUT.json]
    python -m crdt_enc_tpu.tools.sim replay tests/data/sim [FILE.json ...]

``run`` executes one seeded schedule and checks every quiescence
invariant; on failure, ``--shrink`` delta-debugs the schedule to a
minimal reproducer and writes a replayable fixture.  ``explore`` sweeps
a seed range — ``--population P`` runs P schedules concurrently through
one shared substrate (bit-identical results, docs/simulation.md
"Population runs"), ``--budget-s N`` keeps the population full by
refilling finished lanes with the next seed until the wall-clock budget
expires, and ``--coverage-out`` dumps the fault×vocabulary co-fire
matrix.  ``replay`` runs committed fixtures (directories expand
to their ``*.json``) and exits non-zero if any regresses — every file
under ``tests/data/sim/`` is a fixed bug's permanent regression test,
and a non-fixture file in that directory is an error (nothing in the
fixture dir may be silently unreferenced).

Exit codes: 0 all invariants held, 1 violation (or fixture regression),
2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def _build_faults(spec: str):
    from ..sim import FaultConfig

    if spec == "all":
        return FaultConfig.all_faults()
    if spec == "none":
        return FaultConfig.none()
    chosen = [c.strip() for c in spec.split(",") if c.strip()]
    full = FaultConfig.all_faults()
    cfg = FaultConfig.none()
    for c in chosen:
        if c not in FaultConfig.CLASSES:
            raise SystemExit(
                f"unknown fault class {c!r}; choose from "
                f"{', '.join(FaultConfig.CLASSES)}, or all/none"
            )
        setattr(cfg, c, getattr(full, c))
    cfg.delay_max_ticks = full.delay_max_ticks
    return cfg


def _execute(schedule):
    """One schedule run; fs schedules get a fresh scratch dir (a reused
    dir would leak one run's remote into the next)."""
    from ..sim import run_schedule

    if schedule.backend == "fs":
        with tempfile.TemporaryDirectory(prefix="crdt-sim-") as td:
            return run_schedule(schedule, tmpdir=td)
    return run_schedule(schedule)


def _report(tag: str, schedule, result) -> None:
    stats = ", ".join(
        f"{k}={v}" for k, v in sorted(result.fault_stats.items())
    ) or "none"
    print(
        f"{tag}: seed={schedule.seed} replicas={schedule.n_replicas} "
        f"steps={result.steps_run} checks={result.checks_run} "
        f"service_cycles={result.service_cycles} "
        f"daemon_cycles={result.daemon_cycles} "
        f"strong_reads={result.strong_reads} "
        f"strong_timeouts={result.strong_timeouts} "
        f"quarantined={result.quarantined} faults[{stats}]"
    )
    if result.violation is not None:
        v = result.violation
        print(f"  VIOLATION [{v.invariant}] at step {v.step}: {v.detail}")


def _cmd_run(args) -> int:
    from ..sim import generate, shrink, to_fixture

    faults = _build_faults(args.faults)
    schedule = generate(
        args.seed, args.replicas, args.steps, faults,
        members=args.members, backend=args.backend, deltas=args.deltas,
        daemon=args.daemon, strong_reads=args.strong_reads,
    )
    result = _execute(schedule)
    _report("run", schedule, result)
    if result.ok:
        return 0
    if args.shrink:
        small, violation = shrink(
            schedule, result.violation, _execute, max_runs=args.shrink_budget
        )
        fixture = to_fixture(small, violation)
        with open(args.shrink, "w") as f:
            json.dump(fixture, f, indent=1)
            f.write("\n")
        print(
            f"  shrunk to {len(small.steps)} steps / "
            f"{small.n_replicas} replicas / faults "
            f"{small.faults.enabled_classes()} -> {args.shrink}"
        )
    return 1


def _cmd_explore(args) -> int:
    from ..sim import generate

    try:
        lo, hi = (int(x) for x in args.seeds.split(":"))
    except ValueError:
        raise SystemExit(f"--seeds wants LO:HI, got {args.seeds!r}")
    faults = _build_faults(args.faults)
    if (args.population > 1 or args.budget_s) and args.backend != "memory":
        raise SystemExit(
            "--population/--budget-s need --backend memory: population "
            "runs are bound by the serial-equality contract, which the "
            "fs backend's thread-pool timing cannot honor"
        )

    def make_schedule(seed):
        return generate(
            seed, args.replicas, args.steps, faults,
            members=args.members, backend=args.backend, deltas=args.deltas,
            daemon=args.daemon, strong_reads=args.strong_reads,
        )

    pairs = []  # (schedule, result), seed order
    if args.budget_s:
        # wall-clock budget mode: keep the population full (a finished
        # lane refills with the next seed) until the budget expires —
        # seeds start at LO and the HI bound is ignored, the budget IS
        # the bound
        from ..sim import run_budget

        rep = run_budget(
            make_schedule, budget_s=args.budget_s,
            population=max(1, args.population), start_seed=lo,
        )
        pairs = list(zip(rep.schedules, rep.results))
        for schedule, result in pairs:
            _report(f"seed {schedule.seed}", schedule, result)
        print(
            f"explore: {len(pairs)} schedules in {rep.wall_s:.1f}s "
            f"(budget {args.budget_s:g}s, {rep.refills} refill(s)), "
            f"{sum(1 for _, r in pairs if not r.ok)} failure(s)"
        )
    elif args.population > 1:
        from ..sim import run_population

        rep = run_population(
            [make_schedule(s) for s in range(lo, hi)],
            population=args.population,
        )
        pairs = list(zip(rep.schedules, rep.results))
        for schedule, result in pairs:
            _report(f"seed {schedule.seed}", schedule, result)
        print(
            f"explore: {len(pairs)} schedules in {rep.wall_s:.1f}s "
            f"(population {args.population}), "
            f"{sum(1 for _, r in pairs if not r.ok)} failure(s)"
        )
    else:
        for seed in range(lo, hi):
            schedule = make_schedule(seed)
            result = _execute(schedule)
            _report(f"seed {seed}", schedule, result)
            pairs.append((schedule, result))
        print(
            f"explore: {len(pairs)} schedules, "
            f"{sum(1 for _, r in pairs if not r.ok)} failure(s)"
        )

    if args.coverage_out:
        from ..sim import CoFireMatrix

        matrix = CoFireMatrix()
        for schedule, result in pairs:
            matrix.record(schedule, result)
        matrix.dump(args.coverage_out)
        print(f"coverage matrix ({matrix.runs} runs) -> {args.coverage_out}")

    failing = [(s, r) for s, r in pairs if not r.ok]
    if failing and args.shrink:
        # same ddmin flow as `run --shrink`, applied to the FIRST
        # failure: the shrinker replays serially, so a violation found
        # inside a population shrinks to the same replayable fixture
        from ..sim import shrink, to_fixture

        schedule, result = failing[0]
        small, violation = shrink(
            schedule, result.violation, _execute, max_runs=args.shrink_budget
        )
        fixture = to_fixture(small, violation)
        with open(args.shrink, "w") as f:
            json.dump(fixture, f, indent=1)
            f.write("\n")
        print(
            f"  shrunk seed {schedule.seed} to {len(small.steps)} steps / "
            f"{small.n_replicas} replicas / faults "
            f"{small.faults.enabled_classes()} -> {args.shrink}"
        )
    return 1 if failing else 0


def _expand_fixtures(paths: list[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            entries = sorted(os.listdir(p))
            stray = [e for e in entries if not e.endswith(".json")]
            if stray:
                raise SystemExit(
                    f"non-fixture files in {p}: {stray} — every file in a "
                    "fixture dir must be a replayable .json schedule"
                )
            out.extend(os.path.join(p, e) for e in entries)
        else:
            out.append(p)
    return out


def _cmd_replay(args) -> int:
    from ..sim import Schedule

    files = _expand_fixtures(args.fixtures)
    if not files:
        print("replay: no fixtures found", file=sys.stderr)
        return 2
    regressions = 0
    for path in files:
        try:
            with open(path) as f:
                obj = json.load(f)
            schedule = Schedule.from_obj(obj)
        except (OSError, ValueError, KeyError) as e:
            print(f"{path}: unreadable fixture: {e!r}", file=sys.stderr)
            return 2
        result = _execute(schedule)
        was = obj.get("violation", {}).get("invariant", "?")
        if result.ok:
            print(f"{path}: PASS (was: {was})")
        else:
            regressions += 1
            v = result.violation
            print(
                f"{path}: REGRESSED [{v.invariant}] {v.detail}",
                file=sys.stderr,
            )
    print(f"replay: {len(files)} fixture(s), {regressions} regression(s)")
    return 1 if regressions else 0


def main(argv=None) -> int:
    # protocol-level simulation: tiny states, thousands of dispatches —
    # the CPU backend is the right tool even on a TPU box (override by
    # exporting JAX_PLATFORMS yourself)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m crdt_enc_tpu.tools.sim", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--replicas", type=int, default=4)
        p.add_argument("--steps", type=int, default=120)
        p.add_argument("--members", type=int, default=12)
        p.add_argument("--faults", default="all",
                       help="all | none | comma-list of fault classes")
        p.add_argument("--backend", choices=("memory", "fs"),
                       default="memory")
        p.add_argument("--deltas", action="store_true",
                       help="enable delta-state replication on every "
                       "replica + the dseal/dread/dgc step vocabulary "
                       "(docs/delta.md)")
        p.add_argument("--daemon", action="store_true",
                       help="enable the daemon/ddrain step vocabulary: "
                       "a persistent FleetDaemon cycles inside the "
                       "schedule (docs/multitenant.md)")
        p.add_argument("--strong-reads", action="store_true",
                       help="enable the read_strong/await_stable step "
                       "vocabulary + the linearizability checker "
                       "(docs/strong_reads.md)")

    p_run = sub.add_parser("run", help="one seeded schedule + checks")
    p_run.add_argument("--seed", type=int, default=0)
    common(p_run)
    p_run.add_argument("--shrink", metavar="OUT.json",
                       help="on failure, ddmin to a minimal fixture")
    p_run.add_argument("--shrink-budget", type=int, default=200)
    p_run.set_defaults(fn=_cmd_run)

    p_exp = sub.add_parser("explore", help="sweep a seed range")
    p_exp.add_argument("--seeds", default="0:10", metavar="LO:HI")
    common(p_exp)
    p_exp.add_argument("--population", type=int, default=1, metavar="P",
                       help="run P schedules concurrently through one "
                       "shared substrate (sim/population.py); results "
                       "are bit-identical to serial runs — the "
                       "determinism law docs/simulation.md pins")
    p_exp.add_argument("--budget-s", type=float, default=0.0, metavar="N",
                       help="wall-clock budget mode: keep the population "
                       "full, refilling finished lanes with the next "
                       "seed (starting at LO; HI is ignored) until N "
                       "seconds elapse — in-flight schedules finish")
    p_exp.add_argument("--coverage-out", metavar="F.json",
                       help="dump the fault-class × vocabulary co-fire "
                       "matrix (render with obs_report simcov)")
    p_exp.add_argument("--shrink", metavar="OUT.json",
                       help="on failure, ddmin the first failing "
                       "schedule to a minimal fixture")
    p_exp.add_argument("--shrink-budget", type=int, default=200)
    p_exp.set_defaults(fn=_cmd_explore)

    p_rep = sub.add_parser("replay", help="replay committed fixtures")
    p_rep.add_argument("fixtures", nargs="+",
                       help="fixture .json files and/or directories")
    p_rep.set_defaults(fn=_cmd_replay)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

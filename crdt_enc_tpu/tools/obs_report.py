"""Observability CLI: phase tables, timelines, diffs, fleet reports.

Consumes the JSONL files the metrics sink writes (``obs.sink``, env
``CRDT_OBS_SINK``) and the obs snapshots embedded in
``BENCH_LOCAL.jsonl`` records::

    python -m crdt_enc_tpu.tools.obs_report report RUN.jsonl
    python -m crdt_enc_tpu.tools.obs_report export-trace RUN.jsonl \\
        -o trace.json [--check-overlap stream.ingest:stream.reduce]
    python -m crdt_enc_tpu.tools.obs_report diff OLD.jsonl NEW.jsonl
    python -m crdt_enc_tpu.tools.obs_report prom RUN.jsonl [--timestamp]
    python -m crdt_enc_tpu.tools.obs_report fleet DEV1.jsonl DEV2.jsonl ...
    python -m crdt_enc_tpu.tools.obs_report trend BENCH_LOCAL.jsonl \\
        [--metric M] [--fail-on-regression PCT]
    python -m crdt_enc_tpu.tools.obs_report gap BENCH_LOCAL.jsonl \\
        [--metric M]
    python -m crdt_enc_tpu.tools.obs_report slo RUN.jsonl [--window S] \\
        [--fail-on-burn]

* **report** — the per-phase table (totals, counts, p50/p95/p99/max)
  plus counters and gauges for one record.
* **export-trace** — Chrome-trace/Perfetto JSON from a record's event
  log (per-thread lanes, chunk args, counter tracks); with
  ``--check-overlap A:B`` the exit code asserts chunk k+1's stage A
  overlapped chunk k's stage B — the streaming pipeline's overlap proof,
  mechanized (exit 1 when the recorded run was serialized).
* **diff** — phase-by-phase seconds/count/quantile deltas between two
  runs (regression triage: which stage got slower, by how much).
* **prom** — the record in Prometheus text exposition format
  (``# HELP``/``# TYPE`` per family; ``--timestamp`` stamps samples
  with the record's ``ts``).
* **fleet** — merge several devices' sink files (``obs.fleet``): the
  fleet stable watermark, per-device convergence lag distribution, and
  backlog quantiles, grouped by remote.  Exit 2 when an input cannot
  contribute (no replication record, unreadable sink schema).
* **trend** — the per-config ops/s trajectory over BENCH_LOCAL.jsonl;
  ``--fail-on-regression PCT`` exits 1 when any config's latest run is
  more than PCT percent below its best earlier run — the CI gate that
  makes perf regressions visible instead of living only in the JSONL.
* **gap** — cycle attribution (``obs.attribution``): stage marginals
  (decrypt/decode/h2d/fold/scatter/seal), overlap efficiency, the
  critical-path stage, and the e2e-vs-fold-marginal gap ratio with the
  dominant stage named.  Reads bench records (the ``obs`` snapshot +
  wall/ops fields) and sink records alike.
* **slo** — freshness/seal-latency SLO burn accounting
  (``obs.slo``) over sink files: per-window violation fractions vs the
  error budget; ``--fail-on-burn`` exits 1 when a spec's overall
  budget burn exceeds 1.0×.

Record selection: ``--label`` filters by snapshot label, ``--index``
picks among matches (default -1, the newest).  Records without the
requested field (e.g. no ``events`` for export-trace) are reported as
such, exit 2.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..obs import attribution as obs_attribution
from ..obs import fleet as obs_fleet
from ..obs import record as obs_record
from ..obs import sink as obs_sink
from ..obs import slo as obs_slo
from ..obs import timeline as obs_timeline

# one parse for the file format, shared with obs.fleet (obs.sink owns it)
load_records = obs_sink.read_records


def pick_record(records: list[dict], label: str | None, index: int) -> dict:
    """One record by label filter + index; the embedded ``obs`` dict of a
    bench record is hoisted so BENCH_LOCAL.jsonl works directly."""
    if label is not None:
        records = [r for r in records if r.get("label") == label]
    if not records:
        raise SystemExit(f"no matching records (label={label!r})")
    try:
        rec = records[index]
    except IndexError:
        raise SystemExit(
            f"index {index} out of range ({len(records)} matching records)"
        ) from None
    if "spans" not in rec and isinstance(rec.get("obs"), dict):
        rec = {**rec["obs"], "label": rec.get("metric", "bench")}
    return rec


def _fmt_label(rec: dict) -> str:
    lab = rec.get("label", "?")
    ts = rec.get("ts")
    return f"{lab} @ {ts}" if ts else str(lab)


def cmd_report(args) -> int:
    rec = pick_record(load_records(args.file), args.label, args.index)
    print(f"# {_fmt_label(rec)}")
    print(obs_record.format_snapshot(rec))
    return 0


def cmd_prom(args) -> int:
    rec = pick_record(load_records(args.file), args.label, args.index)
    ts = rec.get("ts") if args.timestamp else None
    sys.stdout.write(obs_sink.to_prometheus(rec, timestamp=ts))
    return 0


def cmd_fleet(args) -> int:
    try:
        summaries = obs_fleet.device_summaries(args.files)
    except (obs_fleet.FleetInputError, obs_sink.SinkSchemaError, OSError) as e:
        print(e, file=sys.stderr)
        return 2
    report = obs_fleet.fleet_report(summaries)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(obs_fleet.format_fleet(report))
    return 0


def cmd_trend(args) -> int:
    try:
        records = load_records(args.file)
        obs_sink.check_schema(records, source=args.file)
    except (obs_sink.SinkSchemaError, OSError) as e:
        print(e, file=sys.stderr)
        return 2
    trend = obs_fleet.bench_trend(records, metric=args.metric)
    regressed = (
        obs_fleet.trend_regressions(trend, args.fail_on_regression)
        if args.fail_on_regression is not None
        else []
    )
    if args.json:
        print(json.dumps({"trend": trend, "regressions": regressed},
                         sort_keys=True))
    else:
        print(obs_fleet.format_trend(trend, regressed))
    if regressed:
        print(
            f"{len(regressed)} config(s) regressed more than "
            f"{args.fail_on_regression}% vs prior best",
            file=sys.stderr,
        )
        return 1
    return 0


def cmd_gap(args) -> int:
    try:
        records = load_records(args.file)
        # refuse newer sink schemas loudly instead of attributing a
        # format this build cannot read (same contract as slo/trend)
        obs_sink.check_schema(records, source=args.file)
    except (obs_sink.SinkSchemaError, OSError) as e:
        print(e, file=sys.stderr)
        return 2
    if args.label is not None:
        records = [r for r in records if r.get("label") == args.label]
    if args.metric is not None:
        records = [r for r in records if r.get("metric") == args.metric]
    # attribution needs a snapshot: a bench record's "obs" or a sink
    # record's top-level spans
    records = [
        r for r in records
        if isinstance(r.get("obs"), dict) or "spans" in r
    ]
    if not records:
        print(
            f"no attributable records (label={args.label!r}, "
            f"metric={args.metric!r}) — need an 'obs' snapshot or "
            "top-level spans",
            file=sys.stderr,
        )
        return 2
    try:
        rec = records[args.index]
    except IndexError:
        print(
            f"index {args.index} out of range "
            f"({len(records)} matching records)",
            file=sys.stderr,
        )
        return 2
    report = obs_attribution.from_record(rec)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(f"# {_fmt_label(rec) if 'label' in rec else rec.get('metric', '?')}")
        print(obs_attribution.format_attribution(report))
    return 0


def cmd_slo(args) -> int:
    records = []
    try:
        for path in args.files:
            recs = load_records(path)
            obs_sink.check_schema(recs, source=path)
            records.extend(recs)
    except (obs_sink.SinkSchemaError, OSError) as e:
        print(e, file=sys.stderr)
        return 2
    report = obs_slo.burn_report(records, window_s=args.window)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(obs_slo.format_burn(report))
    if args.fail_on_burn:
        burning = [
            s["name"] for s in report["specs"]
            if s.get("budget_burn", 0.0) > 1.0
        ]
        if burning:
            print(
                f"SLO budget burn > 1.0x for: {', '.join(burning)}",
                file=sys.stderr,
            )
            return 1
    return 0


def cmd_export_trace(args) -> int:
    rec = pick_record(load_records(args.file), args.label, args.index)
    events = rec.get("events")
    if not events:
        print(
            "record has no event log (run with trace.enable_events() / "
            "CRDT_OBS_SINK and events on)",
            file=sys.stderr,
        )
        return 2
    trace_obj = obs_timeline.export_chrome_trace(args.output, events)
    n = len(trace_obj["traceEvents"])
    print(f"wrote {n} trace events to {args.output}")
    if args.check_overlap:
        earlier, _, later = args.check_overlap.partition(":")
        ks = obs_timeline.chunk_overlaps(trace_obj, earlier, later or earlier)
        if not ks:
            print(
                f"NO overlap: no chunk's {earlier} started before the "
                f"previous chunk's {later} finished",
                file=sys.stderr,
            )
            return 1
        print(
            f"overlap proof: chunk k+1 {earlier} started inside chunk k "
            f"{later} for k in {ks}"
        )
    return 0


def cmd_diff(args) -> int:
    a = pick_record(load_records(args.old), args.label, args.index)
    b = pick_record(load_records(args.new), args.label, args.index)
    print(f"# old: {_fmt_label(a)}\n# new: {_fmt_label(b)}")
    names = sorted(set(a.get("spans", {})) | set(b.get("spans", {})))
    if names:
        w = max(len(n) for n in names)
        print(
            f"{'span':<{w}}  {'old s':>10}  {'new s':>10}  {'Δ%':>8}"
            f"  {'count':>11}  {'p99 ms':>17}"
        )
        for n in names:
            sa = a.get("spans", {}).get(n, {})
            sb = b.get("spans", {}).get(n, {})
            va, vb = sa.get("seconds", 0.0), sb.get("seconds", 0.0)
            pct = f"{100.0 * (vb - va) / va:+.1f}%" if va else "new"
            cnt = f"{sa.get('count', 0)}->{sb.get('count', 0)}"
            p99 = (
                f"{sa.get('p99_ms', 0.0):.3f}->{sb.get('p99_ms', 0.0):.3f}"
            )
            print(
                f"{n:<{w}}  {va:>10.4f}  {vb:>10.4f}  {pct:>8}"
                f"  {cnt:>11}  {p99:>17}"
            )
    cnames = sorted(set(a.get("counters", {})) | set(b.get("counters", {})))
    for n in cnames:
        va = a.get("counters", {}).get(n, 0)
        vb = b.get("counters", {}).get(n, 0)
        if va != vb:
            print(f"{n} = {va} -> {vb} ({vb - va:+d})")
    return 0


def cmd_simcov(args) -> int:
    from ..sim import CoFireMatrix

    try:
        matrix = CoFireMatrix.load(args.file)
    except (OSError, ValueError, KeyError) as e:
        print(f"{args.file}: unreadable coverage matrix: {e!r}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(matrix.to_obj(), sort_keys=True))
    else:
        print(matrix.render())
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="crdt_enc_tpu.tools.obs_report",
        description=__doc__.splitlines()[0],
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--label", help="filter records by snapshot label")
        p.add_argument(
            "--index", type=int, default=-1,
            help="which matching record (default -1, the newest)",
        )

    p = sub.add_parser("report", help="per-phase table for one record")
    p.add_argument("file")
    common(p)
    p.set_defaults(fn=cmd_report)

    p = sub.add_parser(
        "export-trace", help="Chrome-trace/Perfetto JSON from a record"
    )
    p.add_argument("file")
    p.add_argument("-o", "--output", required=True)
    p.add_argument(
        "--check-overlap", metavar="EARLIER:LATER",
        help="exit 1 unless chunk k+1's EARLIER span overlaps chunk k's "
        "LATER span (e.g. stream.ingest:stream.reduce)",
    )
    common(p)
    p.set_defaults(fn=cmd_export_trace)

    p = sub.add_parser("diff", help="phase deltas between two runs")
    p.add_argument("old")
    p.add_argument("new")
    common(p)
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser("prom", help="Prometheus text exposition")
    p.add_argument("file")
    p.add_argument(
        "--timestamp", action="store_true",
        help="stamp every sample with the record's ts (ms epoch)",
    )
    common(p)
    p.set_defaults(fn=cmd_prom)

    p = sub.add_parser(
        "gap",
        help="cycle attribution + e2e-vs-fold-marginal gap report",
    )
    p.add_argument("file")
    p.add_argument("--metric", help="filter bench records by metric")
    p.add_argument("--json", action="store_true", help="machine output")
    common(p)
    p.set_defaults(fn=cmd_gap)

    p = sub.add_parser(
        "slo", help="SLO burn accounting over sink files"
    )
    p.add_argument("files", nargs="+", metavar="RUN.jsonl")
    p.add_argument(
        "--window", type=float, default=obs_slo.DEFAULT_WINDOW_S,
        help="burn window in seconds (default %(default)s)",
    )
    p.add_argument(
        "--fail-on-burn", action="store_true",
        help="exit 1 when any spec's overall budget burn exceeds 1.0x",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser(
        "fleet", help="aggregate devices' sink files into one fleet report"
    )
    p.add_argument("files", nargs="+", metavar="DEVICE.jsonl")
    p.add_argument("--json", action="store_true", help="machine output")
    p.set_defaults(fn=cmd_fleet)

    p = sub.add_parser(
        "simcov",
        help="render a fault-class × vocabulary co-fire matrix "
        "(tools.sim explore --coverage-out)",
    )
    p.add_argument("file", metavar="COVERAGE.json")
    p.add_argument("--json", action="store_true", help="machine output")
    p.set_defaults(fn=cmd_simcov)

    p = sub.add_parser(
        "trend", help="per-config perf trajectory over BENCH_LOCAL.jsonl"
    )
    p.add_argument("file")
    p.add_argument("--metric", help="only configs of this metric")
    p.add_argument(
        "--fail-on-regression", type=float, metavar="PCT",
        help="exit 1 when a config's latest run is more than PCT%% below "
        "its best earlier run",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    p.set_defaults(fn=cmd_trend)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    try:
        code = main()
    except BrokenPipeError:  # e.g. `obs_report report … | head`
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        code = 0
    raise SystemExit(code)

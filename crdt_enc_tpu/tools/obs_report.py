"""Observability CLI: phase tables, timeline export, run diffs.

Consumes the JSONL files the metrics sink writes (``obs.sink``, env
``CRDT_OBS_SINK``) and the obs snapshots embedded in
``BENCH_LOCAL.jsonl`` records::

    python -m crdt_enc_tpu.tools.obs_report report RUN.jsonl
    python -m crdt_enc_tpu.tools.obs_report export-trace RUN.jsonl \\
        -o trace.json [--check-overlap stream.ingest:stream.reduce]
    python -m crdt_enc_tpu.tools.obs_report diff OLD.jsonl NEW.jsonl
    python -m crdt_enc_tpu.tools.obs_report prom RUN.jsonl

* **report** — the per-phase table (totals, counts, p50/p95/p99/max)
  plus counters and gauges for one record.
* **export-trace** — Chrome-trace/Perfetto JSON from a record's event
  log (per-thread lanes, chunk args, counter tracks); with
  ``--check-overlap A:B`` the exit code asserts chunk k+1's stage A
  overlapped chunk k's stage B — the streaming pipeline's overlap proof,
  mechanized (exit 1 when the recorded run was serialized).
* **diff** — phase-by-phase seconds/count/quantile deltas between two
  runs (regression triage: which stage got slower, by how much).
* **prom** — the record in Prometheus text exposition format.

Record selection: ``--label`` filters by snapshot label, ``--index``
picks among matches (default -1, the newest).  Records without the
requested field (e.g. no ``events`` for export-trace) are reported as
such, exit 2.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..obs import record as obs_record
from ..obs import sink as obs_sink
from ..obs import timeline as obs_timeline


def load_records(path: str) -> list[dict]:
    records = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                continue  # truncated final append from a killed run
            if isinstance(rec, dict):
                records.append(rec)
    return records


def pick_record(records: list[dict], label: str | None, index: int) -> dict:
    """One record by label filter + index; the embedded ``obs`` dict of a
    bench record is hoisted so BENCH_LOCAL.jsonl works directly."""
    if label is not None:
        records = [r for r in records if r.get("label") == label]
    if not records:
        raise SystemExit(f"no matching records (label={label!r})")
    try:
        rec = records[index]
    except IndexError:
        raise SystemExit(
            f"index {index} out of range ({len(records)} matching records)"
        ) from None
    if "spans" not in rec and isinstance(rec.get("obs"), dict):
        rec = {**rec["obs"], "label": rec.get("metric", "bench")}
    return rec


def _fmt_label(rec: dict) -> str:
    lab = rec.get("label", "?")
    ts = rec.get("ts")
    return f"{lab} @ {ts}" if ts else str(lab)


def cmd_report(args) -> int:
    rec = pick_record(load_records(args.file), args.label, args.index)
    print(f"# {_fmt_label(rec)}")
    print(obs_record.format_snapshot(rec))
    return 0


def cmd_prom(args) -> int:
    rec = pick_record(load_records(args.file), args.label, args.index)
    sys.stdout.write(obs_sink.to_prometheus(rec))
    return 0


def cmd_export_trace(args) -> int:
    rec = pick_record(load_records(args.file), args.label, args.index)
    events = rec.get("events")
    if not events:
        print(
            "record has no event log (run with trace.enable_events() / "
            "CRDT_OBS_SINK and events on)",
            file=sys.stderr,
        )
        return 2
    trace_obj = obs_timeline.export_chrome_trace(args.output, events)
    n = len(trace_obj["traceEvents"])
    print(f"wrote {n} trace events to {args.output}")
    if args.check_overlap:
        earlier, _, later = args.check_overlap.partition(":")
        ks = obs_timeline.chunk_overlaps(trace_obj, earlier, later or earlier)
        if not ks:
            print(
                f"NO overlap: no chunk's {earlier} started before the "
                f"previous chunk's {later} finished",
                file=sys.stderr,
            )
            return 1
        print(
            f"overlap proof: chunk k+1 {earlier} started inside chunk k "
            f"{later} for k in {ks}"
        )
    return 0


def cmd_diff(args) -> int:
    a = pick_record(load_records(args.old), args.label, args.index)
    b = pick_record(load_records(args.new), args.label, args.index)
    print(f"# old: {_fmt_label(a)}\n# new: {_fmt_label(b)}")
    names = sorted(set(a.get("spans", {})) | set(b.get("spans", {})))
    if names:
        w = max(len(n) for n in names)
        print(
            f"{'span':<{w}}  {'old s':>10}  {'new s':>10}  {'Δ%':>8}"
            f"  {'count':>11}  {'p99 ms':>17}"
        )
        for n in names:
            sa = a.get("spans", {}).get(n, {})
            sb = b.get("spans", {}).get(n, {})
            va, vb = sa.get("seconds", 0.0), sb.get("seconds", 0.0)
            pct = f"{100.0 * (vb - va) / va:+.1f}%" if va else "new"
            cnt = f"{sa.get('count', 0)}->{sb.get('count', 0)}"
            p99 = (
                f"{sa.get('p99_ms', 0.0):.3f}->{sb.get('p99_ms', 0.0):.3f}"
            )
            print(
                f"{n:<{w}}  {va:>10.4f}  {vb:>10.4f}  {pct:>8}"
                f"  {cnt:>11}  {p99:>17}"
            )
    cnames = sorted(set(a.get("counters", {})) | set(b.get("counters", {})))
    for n in cnames:
        va = a.get("counters", {}).get(n, 0)
        vb = b.get("counters", {}).get(n, 0)
        if va != vb:
            print(f"{n} = {va} -> {vb} ({vb - va:+d})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="crdt_enc_tpu.tools.obs_report",
        description=__doc__.splitlines()[0],
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--label", help="filter records by snapshot label")
        p.add_argument(
            "--index", type=int, default=-1,
            help="which matching record (default -1, the newest)",
        )

    p = sub.add_parser("report", help="per-phase table for one record")
    p.add_argument("file")
    common(p)
    p.set_defaults(fn=cmd_report)

    p = sub.add_parser(
        "export-trace", help="Chrome-trace/Perfetto JSON from a record"
    )
    p.add_argument("file")
    p.add_argument("-o", "--output", required=True)
    p.add_argument(
        "--check-overlap", metavar="EARLIER:LATER",
        help="exit 1 unless chunk k+1's EARLIER span overlaps chunk k's "
        "LATER span (e.g. stream.ingest:stream.reduce)",
    )
    common(p)
    p.set_defaults(fn=cmd_export_trace)

    p = sub.add_parser("diff", help="phase deltas between two runs")
    p.add_argument("old")
    p.add_argument("new")
    common(p)
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser("prom", help="Prometheus text exposition")
    p.add_argument("file")
    common(p)
    p.set_defaults(fn=cmd_prom)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    try:
        code = main()
    except BrokenPipeError:  # e.g. `obs_report report … | head`
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        code = 0
    raise SystemExit(code)

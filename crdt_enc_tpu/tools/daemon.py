"""Fleet daemon CLI (docs/GUIDE.md "Running the daemon").

    python -m crdt_enc_tpu.tools.daemon run \\
        --tenant /var/crdt/localA=/mnt/remoteA \\
        --tenant /var/crdt/localB=/mnt/remoteB \\
        [--port 9464] [--interval 1.0] [--cycles 0] [--deltas]

    python -m crdt_enc_tpu.tools.daemon selftest \\
        [--tenants 6] [--cycles 6] [--faulty 2] [--seed 0] \\
        [--mesh dp=8[,mp=M]]

``run`` opens one fs-backed :class:`~crdt_enc_tpu.core.Core` per
``--tenant LOCAL=REMOTE`` pair (XChaCha data cryptor, plain key wrap —
the bench stack), admits them into a
:class:`~crdt_enc_tpu.serve.FleetDaemon`, and runs the supervised loop
until SIGTERM/SIGINT, which drains gracefully: the in-flight cycle
finishes, every tenant seals a warm-open checkpoint, the live endpoint
stops.  ``--cycles N`` bounds the loop (smoke runs).  ``--port`` serves
``/metrics`` + ``/healthz`` (with the ``daemon`` control-plane section)
from the daemon's own live telemetry server.

``selftest`` is the CI smoke (tools/run_checks.sh): an in-memory fleet
with the PR-9 fault injector armed on some tenants runs N supervised
cycles — tenant errors must be isolated into backoff/quarantine while
healthy tenants keep sealing — then the faults heal, the fleet
recovers, the daemon drains, and every remote must fsck clean AND
refold (cold) byte-identical to the daemon's live tenant state.  Exit 0
on a clean pass, 1 on any failed expectation.  ``--mesh dp=N[,mp=M]``
runs the whole smoke through a MESH-backed service (the sharded
mega-folds of docs/multitenant.md) — on a CPU box export
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` first, the
virtual mesh the tier-1 differential tests use.

Exit codes: 0 clean, 1 failed expectation / fatal error, 2 usage.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import sys

logger = logging.getLogger("crdt_enc_tpu.tools.daemon")


def _parse_mesh(spec: str | None):
    """``dp=N[,mp=M]`` → a (dp, mp) Mesh, or None when no spec.
    Exits 2 on malformed specs, degenerate (size < 2) meshes, or too
    few devices (usage errors) — the shared ``parse_mesh_spec``
    validation, so ``--mesh dp=1`` can never silently smoke the
    UNsharded path while claiming mesh coverage."""
    if not spec:
        return None
    from ..parallel.mesh import parse_mesh_spec

    try:
        dp, mp = parse_mesh_spec(spec)
    except ValueError as e:
        print(f"--mesh: {e} (got {spec!r})", file=sys.stderr)
        raise SystemExit(2)
    import jax

    from ..parallel.mesh import make_mesh

    if len(jax.devices()) < dp * mp:
        print(
            f"--mesh dp={dp},mp={mp} needs {dp * mp} devices, found "
            f"{len(jax.devices())}; on a CPU box set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8",
            file=sys.stderr,
        )
        raise SystemExit(2)
    return make_mesh((dp, mp))


def _open_opts(storage, *, create: bool, deltas: bool, identity: bool = False):
    from ..backends import PlainKeyCryptor, XChaChaCryptor
    from ..backends.identity_crypto import IdentityCryptor
    from ..core import OpenOptions, orset_adapter
    from ..parallel import TpuAccelerator
    from ..utils.versions import DEFAULT_DATA_VERSION_1

    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor() if identity else XChaChaCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
        accelerator=TpuAccelerator(min_device_batch=1),
        delta=deltas,
    )


# ---------------------------------------------------------------- run
async def _run(args) -> int:
    from ..backends import FsStorage
    from ..core import Core
    from ..serve import DaemonConfig, FleetDaemon

    pairs = []
    for spec in args.tenant:
        local, sep, remote = spec.partition("=")
        if not sep or not local or not remote:
            print(f"--tenant wants LOCAL=REMOTE, got {spec!r}",
                  file=sys.stderr)
            return 2
        pairs.append((local, remote))
    if not pairs:
        print("run: at least one --tenant LOCAL=REMOTE required",
              file=sys.stderr)
        return 2

    cores = [
        await Core.open(_open_opts(
            FsStorage(local, remote), create=True, deltas=args.deltas,
        ))
        for local, remote in pairs
    ]
    cfg = DaemonConfig(interval_s=args.interval)
    daemon = FleetDaemon(cores, cfg, live_port=args.port)
    if daemon.service.live is not None:
        print(f"live telemetry on :{daemon.service.live.port} "
              "(/metrics /healthz /snapshot)", file=sys.stderr)

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, daemon.request_drain)
        except NotImplementedError:  # non-unix
            pass
    await daemon.run_forever(max_cycles=args.cycles)
    h = daemon.health()
    print(
        f"drained after {h['cycles']} cycle(s): {h['tenants']} tenant(s), "
        f"{h['quarantined']} quarantined, degraded={h['degraded']}",
        file=sys.stderr,
    )
    return 0


def _cmd_run(args) -> int:
    return asyncio.run(_run(args))


# ----------------------------------------------------------- selftest
async def _selftest(args) -> int:
    from ..backends import MemoryRemote, MemoryStorage, PlainKeyCryptor
    from ..core import Core
    from ..models import canonical_bytes
    from ..serve import DaemonConfig, FleetDaemon, ServeConfig
    from ..sim import DeterministicCryptor, FaultConfig, FaultyStorage
    from ..tools.fsck import fsck_remote

    class _FlakyStorage:
        """Deterministic outage: tenant 0's remote refuses listings
        while ``broken`` — the guaranteed-error half of the smoke (the
        seeded FaultyStorage half exercises the survivable damage
        classes, whose tenant-level escalation is probabilistic)."""

        def __init__(self, inner):
            self._inner = inner
            self.broken = False

        def __getattr__(self, name):
            return getattr(self._inner, name)

        async def list_op_actors(self):
            if self.broken:
                raise OSError("selftest: remote unreachable")
            return await self._inner.list_op_actors()

    T, faulty = args.tenants, min(1 + args.faulty, args.tenants)
    remotes = [MemoryRemote() for _ in range(T)]
    cores = []
    wrappers = []
    flaky = None
    for t, remote in enumerate(remotes):
        writer = await Core.open(_open_opts(
            MemoryStorage(remote), create=True, deltas=True, identity=True,
        ))
        for i in range(24):
            m = b"t%d-%d" % (t, i % 11)
            await writer.update(
                lambda s, m=m: s.add_ctx(writer.actor_id, m)
            )
        storage = MemoryStorage(remote)
        if t == 0:
            storage = flaky = _FlakyStorage(storage)
        elif t < faulty:
            storage = FaultyStorage(
                storage, FaultConfig.all_faults(),
                seed=args.seed, name=f"t{t}",
            )
            storage.heal()  # open clean; arm once admitted
            wrappers.append(storage)
        cores.append(await Core.open(_open_opts(
            storage, create=True, deltas=True, identity=True,
        )))

    cfg = DaemonConfig(
        interval_s=0.0, max_idle_cycles=1, quarantine_after=2,
        quarantine_probe_every=3, backoff_base=1.0, backoff_cap=2.0,
        breaker_after=T + 1, serve=ServeConfig(seal_empty=False),
    )
    daemon = FleetDaemon(
        cores, cfg, seed=args.seed, mesh=_parse_mesh(args.mesh)
    )
    for w in wrappers:
        w.arm()
    flaky.broken = True

    failures: list[str] = []
    for _ in range(args.cycles):
        report = await daemon.run_cycle()
        h = daemon.health()
        print(
            f"cycle {report['cycle']}: selected={len(report['selected'])} "
            f"errors={h['last_cycle']['errors']} backoff={h['backoff']} "
            f"quarantined={h['quarantined']}", file=sys.stderr,
        )
    # isolation checks: the flaky tenant must have failed into the
    # backoff/quarantine machine, and every HEALTHY tenant must have
    # kept sealing through the fault phase — tenant failures never
    # poison the cycle
    t0 = daemon.entry("t0")
    if t0.failures == 0 and t0.state == "active":
        failures.append("flaky tenant t0 never entered backoff/quarantine")
    for t in range(faulty, T):
        entry = daemon.entry(f"t{t}")
        if entry is None or entry.last_sealed < 0:
            failures.append(
                f"healthy tenant t{t} never sealed while peers faulted"
            )

    # heal: the transient faults clear, the backoff re-probe path must
    # bring every tenant back to sealing
    flaky.broken = False
    for w in wrappers:
        w.heal()
    for _ in range(max(6, 2 * cfg.quarantine_probe_every)):
        await daemon.run_cycle()
        if all(
            daemon.entry(tid).state == "active"
            and daemon.entry(tid).last_sealed >= 0
            for tid in daemon.tenant_ids
        ):
            break
    else:
        failures.append("fleet did not recover to all-active after heal")

    await daemon.drain()
    if daemon.state != "drained":
        failures.append(f"drain left state {daemon.state!r}")

    # post-drain audit: every remote fscks clean and refolds cold to the
    # daemon tenant's live state (the no-divergence oracle)
    for t, (core, remote) in enumerate(zip(cores, remotes)):
        report = await fsck_remote(
            MemoryStorage(remote), DeterministicCryptor(f"selftest{t}"),
            PlainKeyCryptor(), deep=True,
        )
        if not report.ok:
            failures.append(f"tenant {t}: fsck errors: {report.issues[:3]}")
        cold = await Core.open(_open_opts(
            MemoryStorage(remote), create=True, deltas=False, identity=True,
        ))
        await cold.read_remote()
        if cold.with_state(canonical_bytes) != core.with_state(
            canonical_bytes
        ):
            failures.append(f"tenant {t}: cold refold diverges from daemon")

    for line in failures:
        print(f"SELFTEST FAIL: {line}", file=sys.stderr)
    if not failures:
        print(
            f"selftest OK: {T} tenants ({faulty} faulted), "
            f"{daemon.cycle} cycles, drained, fsck clean",
            file=sys.stderr,
        )
    return 1 if failures else 0


def _cmd_selftest(args) -> int:
    return asyncio.run(_selftest(args))


def main(argv=None) -> int:
    # the daemon's fleets are many small tenants: protocol-bound work
    # where the CPU backend is the right default even on a device box
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    ap = argparse.ArgumentParser(
        prog="python -m crdt_enc_tpu.tools.daemon", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="run a fleet daemon over fs remotes")
    p_run.add_argument(
        "--tenant", action="append", default=[], metavar="LOCAL=REMOTE",
        help="one tenant's local dir + remote dir (repeatable)",
    )
    p_run.add_argument("--port", type=int, default=None,
                       help="live telemetry port (0 = ephemeral)")
    p_run.add_argument("--interval", type=float, default=1.0,
                       help="seconds between supervised cycles")
    p_run.add_argument("--cycles", type=int, default=0,
                       help="stop after N cycles (0 = run until SIGTERM)")
    p_run.add_argument("--deltas", action="store_true",
                       help="delta-state replication on every tenant")
    p_run.set_defaults(fn=_cmd_run)

    p_st = sub.add_parser(
        "selftest", help="bounded in-memory smoke with injected faults"
    )
    p_st.add_argument("--tenants", type=int, default=6)
    p_st.add_argument("--cycles", type=int, default=6)
    p_st.add_argument("--faulty", type=int, default=2,
                      help="tenants wrapped in the all-fault injector")
    p_st.add_argument("--seed", type=int, default=0)
    p_st.add_argument("--mesh", default=None, metavar="dp=N[,mp=M]",
                      help="run the smoke through a mesh-backed service")
    p_st.set_defaults(fn=_cmd_selftest)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

"""Operational tools: migration and maintenance utilities around the core."""

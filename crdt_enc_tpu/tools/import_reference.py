"""One-shot importer for remotes written by the reference implementation.

Takes over a deployment of the reference (chpio/crdt-enc): reads its
remote directory layout, decrypts its op files with a supplied data key,
translates the ops, and re-seals everything into THIS framework's wire
format under a destination replica — after which the reference remote can
be retired and every replica switched over.

Reference format facts this importer implements (all pinned by reference
source in-tree):

* op dirs are named by the actor UUID's hyphenated Display form and op
  files by version counting from **0**
  (crdt-enc-tokio/src/lib.rs:249-257, 280-288; the version comes from
  ``next_op_versions.get`` *before* inc, crdt-enc/src/lib.rs:697-716);
* an op file is three nested layers (crdt-enc/src/lib.rs:670-695):
  raw ``VersionBytes`` = 16-byte container UUID ‖ payload (outer, no key
  id — the reference decrypts everything with one key, its ``// TODO:
  add key id`` at lib.rs:687-693); the payload is the cipher envelope
  ``rmp_serde::to_vec_named(VersionBytesRef(DATA_VERSION, EncBox))``
  (crdt-enc-xchacha20poly1305/src/lib.rs:59-68) — msgpack
  ``[bin16-uuid, bin(encbox)]`` with ``encbox = {"nonce": bin24,
  "enc_data": bin}``; the cleartext is another raw VersionBytes tagged
  with the app data version around ``rmp(Vec<Op>)``;
* state snapshot files are NOT imported: the reference's own compaction
  writes a layering its own reader rejects (SURVEY.md §3.4 defect 1) and
  its example never calls compact, so a real reference remote holds only
  op files — any state file present is warned about and skipped;
* remote meta files carry the reference's plugin registers (Keys CRDT in
  the gpgme slot); the key material inside is the external ``crdts``
  crate's serde encoding, so this importer asks for the 32-byte data key
  explicitly instead of guessing that format.

Op payloads are app-defined (serde of ``Vec<S::Op>``); translation to
this framework's op objects is pluggable via ``translator``.  A tolerant
translator for the reference example's state type (``MVReg<_, Uuid>``,
examples/test/src/main.rs:12-26) ships here; other deployments supply
their own ``bytes -> list[op]`` callable.
"""

from __future__ import annotations

import logging
import os
import uuid as uuidm
from dataclasses import dataclass, field

from ..models import MVRegOp, VClock
from ..utils import codec

logger = logging.getLogger("crdt_enc_tpu.import_reference")

# crdt-enc/src/lib.rs:26
REF_CONTAINER_VERSION = uuidm.UUID("e834d789-101b-4634-9823-9de990a9051f").bytes
# crdt-enc-xchacha20poly1305/src/lib.rs:11-13
REF_CIPHER_DATA_VERSION = uuidm.UUID("c7f269be-0ff5-4a77-99c3-7c23c96d5cb4").bytes
REF_KEY_VERSION = uuidm.UUID("5df28591-439a-4cef-8ca6-8433276cc9ed").bytes

KEY_LEN = 32
NONCE_LEN = 24
TAG_LEN = 16


class ReferenceFormatError(Exception):
    """The file does not parse as the reference's wire format."""


def open_reference_blob(key: bytes, raw: bytes) -> tuple[bytes, bytes]:
    """Unwrap one reference-sealed blob: outer raw VersionBytes → msgpack
    cipher envelope → XChaCha20-Poly1305 → inner raw VersionBytes.
    Returns ``(app_data_version, payload)``."""
    from ..backends import xchacha

    if len(key) != KEY_LEN:
        raise ReferenceFormatError(f"data key must be {KEY_LEN} bytes")
    raw = bytes(raw)
    if len(raw) < 16 or raw[:16] != REF_CONTAINER_VERSION:
        raise ReferenceFormatError(
            "outer container version is not the reference's "
            f"({uuidm.UUID(bytes=raw[:16]) if len(raw) >= 16 else 'short'})"
        )
    try:
        ver, enc_box_bytes = codec.unpack(raw[16:])
        ver = bytes(ver)
    except Exception as e:
        raise ReferenceFormatError(f"malformed cipher envelope: {e}") from e
    if ver != REF_CIPHER_DATA_VERSION:
        raise ReferenceFormatError(
            f"cipher envelope version {uuidm.UUID(bytes=ver)} is not the "
            "reference XChaCha backend's"
        )
    try:
        box = codec.unpack(enc_box_bytes)
        if isinstance(box, dict):  # rmp to_vec_named: {"nonce":…, "enc_data":…}
            nonce = bytes(box[b"nonce"] if b"nonce" in box else box["nonce"])
            ct = bytes(
                box[b"enc_data"] if b"enc_data" in box else box["enc_data"]
            )
        else:  # tolerate the positional (to_vec) form
            nonce, ct = bytes(box[0]), bytes(box[1])
    except Exception as e:
        raise ReferenceFormatError(f"malformed EncBox: {e}") from e
    if len(nonce) != NONCE_LEN or len(ct) < TAG_LEN:
        raise ReferenceFormatError("malformed EncBox (nonce/ct lengths)")
    # same AEAD, shared primitive: raw XChaCha20-Poly1305 open
    clear = xchacha.open_raw(key, nonce, ct)
    if len(clear) < 16:
        raise ReferenceFormatError("inner VersionBytes too short")
    return clear[:16], clear[16:]


def _vclock_from_ref(obj) -> VClock:
    """crdts ``VClock`` serde forms: ``{"dots": {actor: counter}}``
    (to_vec_named) or a bare map (tolerated)."""
    if isinstance(obj, dict) and (b"dots" in obj or "dots" in obj):
        obj = obj.get(b"dots", obj.get("dots"))
    if not isinstance(obj, dict):
        raise ReferenceFormatError(f"unrecognized VClock encoding: {obj!r}")
    return VClock({bytes(a): int(c) for a, c in obj.items()})


def mvreg_translator(payload: bytes) -> list:
    """Ops of the reference example's state type ``MVReg<V, Uuid>``
    (crdts v7 ``mvreg::Op { clock, val }``; named-map and positional
    encodings both accepted) → this framework's ``MVRegOp``."""
    ops = codec.unpack(payload)
    out = []
    for o in ops:
        if isinstance(o, dict):
            clock = o.get(b"clock", o.get("clock"))
            val = o.get(b"val", o.get("val"))
        elif isinstance(o, (list, tuple)) and len(o) == 2:
            clock, val = o
        else:
            raise ReferenceFormatError(f"unrecognized MVReg op encoding: {o!r}")
        out.append(MVRegOp(_vclock_from_ref(clock), val))
    return out


@dataclass
class ImportStats:
    actors: int = 0
    op_files: int = 0
    ops: int = 0
    skipped_states: int = 0
    skipped_metas: int = 0
    data_versions: set = field(default_factory=set)


async def import_reference_remote(
    src_remote: str | os.PathLike,
    dest,
    key: bytes,
    translator=mvreg_translator,
    compact: bool = False,
) -> ImportStats:
    """Migrate a reference-format remote into ``dest`` (an opened
    ``Core``): every source op file is decrypted, translated, re-sealed
    with the destination's wire format/keys, and written under the SAME
    source actor at version+1 (the reference counts files from 0, this
    framework from 1) — per-actor history and causal structure survive,
    so replicas joining the new remote converge exactly as they would
    have on the old one.  Ends with ``dest.read_remote()`` (and
    optionally ``compact``) so the destination state is folded.

    Returns an :class:`ImportStats`.  The source is never written to.
    """
    src = os.fspath(src_remote)
    stats = ImportStats()

    states_dir = os.path.join(src, "states")
    if os.path.isdir(states_dir):
        stats.skipped_states = len(os.listdir(states_dir))
        if stats.skipped_states:
            logger.warning(
                "skipping %d reference state file(s): the reference's own "
                "compaction output is unreadable by its own reader "
                "(SURVEY.md §3.4 defect 1)", stats.skipped_states,
            )
    meta_dir = os.path.join(src, "meta")
    if os.path.isdir(meta_dir):
        stats.skipped_metas = len(os.listdir(meta_dir))

    ops_root = os.path.join(src, "ops")
    actors: list[tuple[bytes, str]] = []
    if os.path.isdir(ops_root):
        for name in sorted(os.listdir(ops_root)):
            try:
                actors.append((uuidm.UUID(name).bytes, name))
            except ValueError:
                logger.warning("ignoring non-actor dir %r in ops/", name)
    if not actors:
        raise ReferenceFormatError(f"no reference op directories under {ops_root}")

    for actor, dirname in actors:
        stats.actors += 1
        d = os.path.join(ops_root, dirname)
        version = 0  # the reference's first op file is version 0
        while True:
            path = os.path.join(d, str(version))
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except FileNotFoundError:
                break
            data_version, payload = open_reference_blob(key, raw)
            stats.data_versions.add(data_version)
            ops = translator(payload)
            blob = await dest._seal([dest.adapter.op_to_obj(op) for op in ops])
            # +1: this framework's dense per-actor scan starts at version 1
            await dest.storage.store_ops(actor, version + 1, blob)
            stats.op_files += 1
            stats.ops += len(ops)
            version += 1
        # a gap would silently strand every file beyond it — the reference's
        # log is dense by contract, so leftovers mean corruption: fail loudly
        # rather than let the operator retire a partially-migrated source
        leftover = [
            n for n in os.listdir(d)
            if n.isdigit() and int(n) >= version
        ]
        if leftover:
            raise ReferenceFormatError(
                f"actor {dirname} has op files beyond a gap at version "
                f"{version} ({sorted(leftover, key=int)[:5]}…); the source "
                "log is not dense — refusing a partial import"
            )

    await dest.read_remote()
    if compact:
        await dest.compact()
    return stats


def main(argv=None) -> int:
    """CLI: ``python -m crdt_enc_tpu.tools.import_reference SRC_REMOTE
    DEST_LOCAL DEST_REMOTE --key-hex <64 hex chars> [--compact]``.
    The destination opens with the XChaCha cryptor + plain key cryptor
    and the MVReg adapter (the reference example's state type)."""
    import argparse
    import asyncio

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("src_remote", help="reference remote directory (read-only)")
    ap.add_argument("dest_local", help="destination replica's local dir")
    ap.add_argument("dest_remote", help="destination remote directory")
    ap.add_argument(
        "--key-hex", required=True,
        help="the reference deployment's 32-byte data key, hex-encoded",
    )
    ap.add_argument("--compact", action="store_true",
                    help="compact the destination after import")
    args = ap.parse_args(argv)

    from ..backends import FsStorage, PlainKeyCryptor, XChaChaCryptor
    from ..core import Core, OpenOptions, mvreg_adapter
    from ..utils.versions import DEFAULT_DATA_VERSION_1

    key = bytes.fromhex(args.key_hex)

    async def go():
        dest = await Core.open(OpenOptions(
            storage=FsStorage(args.dest_local, args.dest_remote),
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=mvreg_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
        ))
        stats = await import_reference_remote(
            args.src_remote, dest, key, compact=args.compact
        )
        print(
            f"imported {stats.ops} ops in {stats.op_files} files from "
            f"{stats.actors} actors; skipped {stats.skipped_states} state "
            f"and {stats.skipped_metas} meta file(s)"
        )

    asyncio.run(go())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

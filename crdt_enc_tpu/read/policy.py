"""Expected-replica membership policy for the strong-read tier.

The stability watermark is a pointwise min over every known replica's
published cursor (obs/replication.py), which makes it *observationally
sound* but operationally fragile in exactly one way: **one silent
replica collapses it forever**.  A replica that crashed for good, was
decommissioned without ceremony, or simply never compacts again keeps
its last published cursor in every peer's matrix — and the min never
moves past it.  Silence is indistinguishable from lag, so the math
cannot fix this; only an explicit membership decision can
(arXiv:1905.08733's strong-read precondition includes pinned
membership).  This module is that decision, made loudly:

* ``expected=...`` **pins the denominator**: the watermark is the min
  over exactly ``expected ∪ {self}``.  A replica outside the set may
  still produce ops (they surface in the union and stabilize once every
  expected replica folds them) but its cursor no longer caps the
  watermark; an expected replica that has never published holds the
  watermark at zero — the honest wedge, not a silent skip.
* ``silent_after=N`` **decays provably-silent replicas**: a replica
  whose published cursor has not advanced for N policy observations is
  QUARANTINED out of the denominator until it advances again.  Every
  transition logs a warning and counts ``read_membership_quarantines``;
  the current exclusion set rides on every strong read's status, into
  ``/healthz`` (the ``membership`` key) and ``obs_report fleet`` —
  an operator can always see whose data the fleet stopped waiting for.

Excluding a replica is a real guarantee trade, stated in
docs/strong_reads.md: strong reads stay monotone, exact folds of a
consistent cut, but an excluded replica's state no longer provably
descends from every exposed read.  Both knobs default OFF — with no
policy the denominator is the observed replica set, the PR-6 math
unchanged.

Determinism seam: observations tick a counter by default, so the
simulator replays policies bit-for-bit; pass ``clock=`` for wall-time
decay in production.
"""

from __future__ import annotations

import logging

from ..models.vclock import Actor
from ..utils import trace

logger = logging.getLogger("crdt_enc_tpu.read")


class MembershipPolicy:
    """The watermark-denominator policy (module docs).

    One instance per Core (``OpenOptions.membership``); ``observe`` is
    called by every strong-read/stable-prefix computation with the
    replica's current knowledge and returns the effective denominator.
    """

    def __init__(
        self,
        expected=None,
        *,
        silent_after: int = 0,
        clock=None,
    ):
        self.expected: frozenset | None = (
            frozenset(bytes(a) for a in expected)
            if expected is not None
            else None
        )
        self.silent_after = int(silent_after)
        self._clock = clock  # None = observation-count ticks
        self._tick = 0
        # replica -> (last tick/time its published cursor advanced,
        #             total versions in that cursor at the time)
        self._last_advance: dict[Actor, tuple[float, int]] = {}
        self.excluded: frozenset = frozenset()

    def _now(self) -> float:
        if self._clock is not None:
            return float(self._clock())
        self._tick += 1
        return float(self._tick)

    def denominator(
        self, actor_id: Actor, cursor_matrix: dict, union
    ) -> set:
        """The replica set the watermark mins over BEFORE silence decay:
        ``expected ∪ {self}`` when pinned, else the observed set (every
        published cursor + every op producer — the PR-6 construction)."""
        if self.expected is not None:
            return set(self.expected) | {actor_id}
        return set(cursor_matrix) | set(union.counters) | {actor_id}

    def observe(self, actor_id: Actor, cursor_matrix: dict, union) -> set:
        """One policy observation: update silence bookkeeping, apply the
        decay, and return the EFFECTIVE denominator (pinned-or-observed
        minus quarantined; never excludes ``actor_id`` itself).  The
        exclusion set is kept on ``self.excluded`` for status/health
        surfacing."""
        replicas = self.denominator(actor_id, cursor_matrix, union)
        if self.silent_after <= 0:
            self.excluded = frozenset()
            return replicas
        now = self._now()
        excluded = set()
        for r in replicas:
            if r == actor_id:
                continue  # self is never silent to itself
            row = cursor_matrix.get(r)
            total = (
                sum(c for c in row.counters.values()) if row is not None
                else 0
            )
            seen = self._last_advance.get(r)
            if seen is None or total > seen[1]:
                self._last_advance[r] = (now, total)
            elif now - seen[0] > self.silent_after:
                excluded.add(r)
        newly = excluded - set(self.excluded)
        for r in sorted(newly):
            trace.add("read_membership_quarantines", 1)
            logger.warning(
                "membership policy quarantined silent replica %s out of "
                "the watermark denominator (no cursor advance for > %d "
                "observations); strong reads no longer wait for it",
                r.hex(), self.silent_after,
            )
        for r in sorted(set(self.excluded) - excluded):
            logger.info(
                "membership policy re-admitted replica %s (cursor "
                "advanced)", r.hex(),
            )
        self.excluded = frozenset(excluded)
        trace.gauge("read_membership_excluded", len(excluded))
        return replicas - excluded

    def summary(self) -> dict:
        """The loud surface: rides on strong-read statuses and — via
        ``Core.replication_status`` — into ``/healthz`` and
        ``obs_report fleet``.  Sorted hex, byte-stable."""
        return {
            "expected": (
                sorted(a.hex() for a in self.expected)
                if self.expected is not None
                else None
            ),
            "silent_after": self.silent_after,
            "excluded": sorted(a.hex() for a in self.excluded),
        }

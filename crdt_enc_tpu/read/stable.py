"""The stable prefix: a monotone state folded only from stable history.

``Core``'s live state folds everything it has seen — including ops no
other replica may hold yet, which is why eventual reads can "unsee"
nothing but guarantee nothing either.  The stable prefix is the second
state the strong-read tier maintains per replica: the fold of exactly
the ops covered by the **causal stability watermark** (obs/replication)
under the active :class:`~crdt_enc_tpu.read.policy.MembershipPolicy`.
Every replica in the denominator has provably ingested everything in
it, so its value can never be rolled back, reordered, or contradicted
by any future merge — the strong-read precondition of
arXiv:1905.08733.

Materialization reuses the system's own invariant: a sealed snapshot is
byte-exactly the fold of the op prefix its cursor names (the compaction
contract every differential test pins), so the prefix advances by

1. merging any listed snapshot whose cursor is pointwise ≤ the
   watermark (a *stable snapshot* — only stable ops inside), and
2. folding op files from the prefix cursor up to the watermark, dense
   per actor, with the core's quarantine discipline (a torn file holds
   the cursor; a GC'd hole wedges that actor until a stable snapshot
   covers past it — recorded per actor in ``wedged``, never silent).

Both moves only grow the prefix, so it is monotone by construction
(reads can never go backwards within an incarnation) and checkpointable
(it rides the warm-open checkpoint as the observational ``b"sp"`` slot:
a warm reopen resumes the exposed frontier, a cold reopen rebuilds from
scratch and the session guarantee restarts).

The refusal taxonomy is :class:`StalenessError` — ``reason`` is one of
``lag_exceeded`` (watermark too far behind the union for the caller's
``max_lag``), ``uncovered_target`` (``min_cursor``/read-your-writes
target not yet stable), or ``timeout`` (``await_stable`` gave up) —
each message naming the holdout replicas so an operator knows WHO the
fleet is waiting for.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass

from ..models.vclock import Actor, Dot, VClock
from ..utils import trace

logger = logging.getLogger("crdt_enc_tpu.read")


class StalenessError(Exception):
    """A linearizable read (or freshness wait) could not be served
    within the caller's constraints.  ``reason`` is the taxonomy key
    (module docs); ``status`` carries the watermark/lag/holdout detail
    the message summarizes.  Deliberately NOT a silent fallback: the
    caller chooses ``consistency="eventual"`` explicitly (Core.read
    with ``linearizable=False``), never gets it by surprise."""

    def __init__(self, reason: str, message: str, *, status: dict | None = None):
        super().__init__(message)
        self.reason = reason
        self.status = status or {}


@dataclass(frozen=True)
class StableView:
    """One advance's summary: the exposed frontier and how it relates
    to everything known to exist.  All actor ids are raw bytes in
    ``cursor`` (a VClock) and hex strings in the reporting fields."""

    cursor: VClock  # the materialized stable prefix frontier
    watermark: dict  # Actor -> int, the effective (policy) watermark
    lag: int  # versions the union is ahead of the PREFIX cursor
    watermark_lag: int  # versions the union is ahead of the watermark
    excluded: tuple  # hex: replicas the policy quarantined
    holdouts: tuple  # hex: replicas whose cursors cap the watermark
    wedged: dict  # actor hex -> reason ("gc_gap" | "torn")

    def covers(self, target: VClock) -> bool:
        return all(
            self.cursor.get(a) >= c for a, c in target.counters.items()
        )


@dataclass(frozen=True)
class ReadResult:
    """What ``Core.read`` returns: the state's object form, which
    consistency tier actually served it, and the frontier it reflects.
    ``obj`` may alias live structures — treat it as read-only."""

    obj: object
    consistency: str  # "strong" | "eventual"
    cursor: VClock
    view: StableView | None = None


class StablePrefix:
    """The per-replica stable prefix state + frontier (module docs).
    Owned by a Core (created lazily on first strong read, or restored
    from the warm-open checkpoint); all mutation happens inside
    :meth:`advance` under one asyncio lock, in sync sections between
    awaits — concurrent strong reads serialize their advances and both
    observe a monotone frontier."""

    def __init__(self, adapter):
        self.adapter = adapter
        self.state = adapter.new()
        self.cursor = VClock()
        self.consumed: set[str] = set()  # stable snapshot names merged
        self.wedged: dict[Actor, str] = {}
        self._lock = asyncio.Lock()

    # ---------------------------------------------------------- advance
    async def advance(self, core, watermark: dict) -> None:
        """Grow the prefix toward ``watermark`` (never past it, never
        backwards): stable snapshots first (they may jump the cursor
        over GC'd op history), then dense op tails."""
        async with self._lock:
            with trace.span("read.advance"):
                await self._merge_stable_snapshots(core, watermark)
                await self._fold_stable_ops(core, watermark)

    async def _merge_stable_snapshots(self, core, watermark: dict) -> None:
        from ..core.core import MissingKeyError

        names = await core.storage.list_state_names()
        new = [n for n in names if n not in self.consumed]
        # consumed names that vanished were GC'd; forgetting them is
        # safe — content-addressed names re-merge idempotently
        self.consumed.intersection_update(names)
        if not new:
            return
        loaded = await core.storage.load_states(new)
        for name, raw in loaded:
            try:
                obj = await core._open_sealed(raw)
                cursor = VClock.from_obj(obj[1])
            except MissingKeyError:
                raise  # key metadata not synced: loud, not damage
            except Exception:
                # torn snapshot: skip, NOT consumed — a repaired sync
                # retries it (the core's quarantine discipline)
                logger.debug(
                    "stable prefix: snapshot %s unreadable; skipped",
                    name, exc_info=True,
                )
                continue
            if any(
                c > watermark.get(a, 0) for a, c in cursor.counters.items()
            ):
                continue  # folds unstable ops; retried once covered
            # sync section: a snapshot IS the fold of its cursor's
            # prefix (compaction contract), so merging it keeps the
            # prefix == fold-of-cursor-cut invariant
            state = core.adapter.state_from_obj(obj[0])
            self.state.merge(state)
            self.cursor.merge(cursor)
            self.consumed.add(name)
            for a in cursor.counters:
                if self.cursor.get(a) >= watermark.get(a, 0):
                    self.wedged.pop(a, None)
            trace.add("read_stable_snapshots", 1)

    async def _fold_stable_ops(self, core, watermark: dict) -> None:
        from ..core.core import MissingKeyError

        wanted = []
        for a, hi in sorted(watermark.items()):
            lo = self.cursor.get(a) + 1
            if hi >= lo:
                wanted.append((a, lo))
            else:
                self.wedged.pop(a, None)
        if not wanted:
            return
        files = await core.storage.load_ops(wanted)
        folded = 0
        cut: set[Actor] = set()
        for actor, version, raw in files:
            if actor in cut or version > watermark.get(actor, 0):
                continue
            expected = self.cursor.get(actor) + 1
            if version < expected:
                continue  # a stable snapshot already covered it
            if version > expected:
                # a hole below the watermark: the file was GC'd into a
                # snapshot we cannot use yet (its cursor exceeds the
                # watermark).  Wedge the actor — honest staleness, the
                # snapshot merges the moment the watermark covers it.
                self.wedged[actor] = "gc_gap"
                cut.add(actor)
                continue
            try:
                payload = await core._open_sealed(raw)
            except MissingKeyError:
                raise
            except Exception:
                # torn op file: cursor holds, dense run ends here
                self.wedged[actor] = "torn"
                cut.add(actor)
                continue
            # sync section: host fold in version order (the causal-
            # delivery contract; cross-actor order is CmRDT-free)
            for o in payload:
                self.state.apply(core.adapter.op_from_obj(o))
            self.cursor.apply(Dot(actor, version))
            self.wedged.pop(actor, None)
            folded += 1
        # load_ops' dense-scan contract stops at the first missing
        # version, so an actor whose NEXT stable op was GC'd returns
        # nothing at all — record the wedge for observability
        got = {a for a, _, _ in files}
        for a, lo in wanted:
            if a not in got and a not in cut and watermark.get(a, 0) >= lo:
                self.wedged[a] = "gc_gap"
        if folded:
            trace.add("read_stable_ops", folded)

    # ------------------------------------------------------- checkpoint
    def to_obj(self) -> dict:
        """The observational ``b"sp"`` checkpoint slot: generic adapter
        state form + frontier + consumed snapshot names.  Never part of
        the checkpoint fingerprint — a missing or malformed slot only
        costs a cold prefix rebuild, never a wrong read."""
        return {
            b"state": self.adapter.state_to_obj(self.state),
            b"cursor": self.cursor.to_obj(),
            b"names": sorted(self.consumed),
        }

    @classmethod
    def from_obj(cls, adapter, obj) -> "StablePrefix":
        prefix = cls(adapter)
        prefix.state = adapter.state_from_obj(obj[b"state"])
        prefix.cursor = VClock.from_obj(obj[b"cursor"])
        prefix.consumed = {str(n) for n in obj[b"names"]}
        return prefix


# --------------------------------------------------------------- helpers
def effective_watermark(core, *, policy=None):
    """The (policy-adjusted) stability watermark from a core's CURRENT
    knowledge — no storage probe; callers refresh via ``read_remote``
    first when they need liveness.  Returns ``(watermark, union,
    denominator, excluded)``."""
    from ..obs.replication import stability_watermark

    d = core._data
    union = d.next_op_versions.copy()
    for clock in d.cursor_matrix.values():
        union.merge(clock)
    if policy is None:
        replicas = (
            set(d.cursor_matrix) | set(union.counters) | {core.actor_id}
        )
        excluded: frozenset = frozenset()
    else:
        replicas = policy.observe(core.actor_id, d.cursor_matrix, union)
        excluded = policy.excluded
    wm = stability_watermark(
        core.actor_id, d.next_op_versions, d.cursor_matrix, union,
        replicas=replicas,
    )
    return wm, union, replicas, excluded


def find_holdouts(core, watermark: dict, union: VClock, replicas) -> list:
    """The replicas whose published cursors cap the watermark at its
    lagging entries — WHO the fleet is waiting for.  These are exactly
    the laggards the daemon's cadence scheduler should visit first, and
    the names a :class:`StalenessError` message carries."""
    d = core._data
    holdouts: set[Actor] = set()
    for a, c in union.counters.items():
        lo = watermark.get(a, 0)
        if lo >= c:
            continue
        for r in replicas:
            if r == core.actor_id:
                k = d.next_op_versions.get(a)
            else:
                row = d.cursor_matrix.get(r)
                k = row.get(a) if row is not None else 0
            if r == a:
                k = max(k, union.get(a))
            if k <= lo:
                holdouts.add(r)
    return sorted(h.hex() for h in holdouts)

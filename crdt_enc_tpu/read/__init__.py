"""Strong-read tier: linearizable point reads at the stability watermark.

The measurement substrate has existed since PR 6 (the causal stability
watermark, ``obs.replication``) and PR 10 seals it into every delta's
wire tag — this package is the first READ API that consumes it
(docs/strong_reads.md, ROADMAP item 3, per "Linearizable State Machine
Replication of State-Based CRDTs without Logs", arXiv:1905.08733):

* :mod:`.stable` — the **stable prefix**: a second, monotone state per
  replica folded ONLY from ops/snapshots covered by the stability
  watermark.  ``Core.stable_prefix()`` advances and views it,
  ``Core.read(linearizable=True)`` / ``contains`` / ``value`` answer
  from it, and :class:`StalenessError` is the honest refusal taxonomy
  when the watermark cannot cover the request.
* :mod:`.policy` — :class:`MembershipPolicy`: the membership problem
  handled explicitly.  One silent replica collapses the watermark
  forever (silence is indistinguishable from lag); the policy pins an
  expected replica set and/or decays provably-silent replicas out of
  the watermark denominator — LOUDLY (surfaced in ``/healthz``,
  ``obs_report fleet``, and every strong read's status), never as a
  silent drop.

The freshness-wait protocol (``Core.await_stable`` — block/poll until
the watermark covers a target clock, e.g. the caller's own last write:
read-your-writes made strong) and the serving/daemon integration
(``FoldService.read_strong``, ``FleetDaemon.await_stable``) build on
these two pieces; the PR-9 simulator checks the guarantee under
all-fault schedules via ``read_strong``/``await_stable`` steps and the
:mod:`crdt_enc_tpu.sim.linearize` checker.
"""

from .policy import MembershipPolicy
from .stable import ReadResult, StableView, StablePrefix, StalenessError

__all__ = [
    "MembershipPolicy",
    "ReadResult",
    "StablePrefix",
    "StableView",
    "StalenessError",
]

"""Quiescence invariants: what must hold once the adversary stops.

The simulator's acceptance bar at every quiescence point (schedule end
and every explicit ``quiesce`` step), after faults heal and reads reach
a fixed point:

1. **byte equality** — every replica's canonical serialization is
   byte-identical (the paper's convergence claim, SURVEY §4);
2. **oracle refold** — a fresh host-reference Core joining the remote
   cold refolds to the same bytes (the remote itself, not just the
   survivors' memories, carries the state);
3. **warm ≡ cold** — reopening a replica from its warm-open checkpoint
   equals a cold refold (docs/checkpointing.md's contract under fire);
4. **replication monotonicity** — per replica incarnation, the local
   clock, the union clock, and every cursor-matrix row only advance;
   the stability watermark is pointwise monotone *while the known
   replica set is unchanged* (membership growth may legitimately
   collapse it — a newly heard-from silent replica drags the min down,
   exactly as obs/replication.py documents — so the baseline resets
   when the known set grows);
5. **fsck cleanliness** — the healed remote passes a deep
   ``tools.fsck`` walk (no torn survivors, no op-log gaps, addresses
   match content).

This module is the pure half (comparisons over status dicts and state
bytes — exactly unit-testable); :mod:`crdt_enc_tpu.sim.runner` gathers
the inputs and raises :class:`InvariantViolation`.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Violation:
    """One invariant failure, serializable into a shrunk fixture."""

    invariant: str  # "divergence" | "oracle" | "warm_cold" | "monotonicity"
    #               | "fsck" | "no_quiescence" | "step_error" | "service_error"
    detail: str
    step: int = -1  # schedule step index at/after which it was detected

    def to_obj(self) -> dict:
        return {
            "invariant": self.invariant,
            "detail": self.detail,
            "step": self.step,
        }


class InvariantViolation(AssertionError):
    def __init__(self, violation: Violation):
        super().__init__(
            f"[{violation.invariant} @ step {violation.step}] {violation.detail}"
        )
        self.violation = violation


def clock_regressions(prev: dict, cur: dict) -> list[str]:
    """Hex-keyed clock entries that moved backwards (prev > cur)."""
    return sorted(a for a, v in prev.items() if cur.get(a, 0) < v)


def known_replica_set(status: dict) -> frozenset:
    """The replica set a status' watermark minimized over: self, every
    published cursor row, every op producer in the union clock — the
    same construction as obs.replication.compute_status."""
    return frozenset(
        {status["actor"]} | set(status["matrix"]) | set(status["union_clock"])
    )


def replication_regression(prev: dict | None, cur: dict) -> str | None:
    """Compare two replication statuses of ONE replica incarnation.
    Returns a human-readable defect description, or None when every
    monotone quantity advanced (see module docs for which are monotone
    under membership growth and which are not)."""
    if prev is None:
        return None
    bad = clock_regressions(prev["local_clock"], cur["local_clock"])
    if bad:
        return f"local_clock regressed for {bad}"
    bad = clock_regressions(prev["union_clock"], cur["union_clock"])
    if bad:
        return f"union_clock regressed for {bad}"
    for r, row in prev["matrix"].items():
        bad = clock_regressions(row, cur["matrix"].get(r, {}))
        if bad:
            return f"cursor matrix row {r} regressed for {bad}"
    if known_replica_set(cur) <= known_replica_set(prev):
        bad = clock_regressions(prev["watermark"], cur["watermark"])
        if bad:
            return (
                "stability watermark regressed with no membership growth "
                f"for {bad}"
            )
    return None


def divergence_detail(blobs: list[tuple[str, bytes]]) -> str | None:
    """None when all canonical serializations agree, else which
    replicas disagree with the first."""
    if not blobs:
        return None
    ref_label, ref = blobs[0]
    off = [label for label, b in blobs[1:] if b != ref]
    if not off:
        return None
    return f"{off} diverged from {ref_label} ({len(blobs)} replicas)"

"""Schedule execution: real Cores, one hostile remote, full checks.

Runs a :class:`~crdt_enc_tpu.sim.schedule.Schedule` against a fleet of
REAL :class:`~crdt_enc_tpu.core.Core` instances — host-oracle replicas,
``TpuAccelerator`` replicas, and :class:`~crdt_enc_tpu.serve.FoldService`
cycles all in the same history — sharing one remote through per-replica
:class:`~crdt_enc_tpu.sim.faults.FaultyStorage` wrappers.  No mocks on
the system-under-test side: every byte travels the production wire
format and every fold runs the production paths.

Determinism: with the default memory backend the whole run is a pure
function of the schedule.  Besides the seeded fault rolls, the two real
entropy sources are patched for the run's duration — ``uuid.uuid4``
(actor and key ids) draws from a schedule-seeded stream, and key
material comes from :class:`DeterministicCryptor` — so fault patterns,
file names, and final states replay bit-for-bit
(``SimResult.fingerprint`` pins it).  The fs backend keeps thread-pool
timing, so it is exercised for coverage, not replay fidelity.

Error discipline while faults are active:

* :class:`SimCrash` from a write step = that replica crashed — its Core
  is discarded and storage keeps whatever landed (later ``reopen``);
* :class:`MissingKeyError` / :class:`StaleWriterError` /
  :class:`IngestDecryptError` = documented loud-but-transient states
  (key metadata, own history, or a whole batch of blobs not yet synced
  intact); the step is a no-op and the occurrence is counted;
* anything else is a **violation** (kind ``step_error``): the fault
  classes are all survivable by design, so an unexpected exception is a
  robustness bug, exactly what the simulator hunts.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import hashlib
import json
import logging
import os
import random
import uuid
from collections import Counter
from dataclasses import dataclass, field

from ..backends.identity_crypto import IdentityCryptor
from ..core import (
    Core,
    IngestDecryptError,
    MissingKeyError,
    OpenOptions,
    StaleWriterError,
)
from ..utils import trace
from ..utils.versions import IDENTITY_KEY_VERSION_1
from .check import (
    InvariantViolation,
    Violation,
    divergence_detail,
    known_replica_set,
    replication_regression,
)
from .faults import FaultyStorage, SimCrash
from .schedule import Schedule

logger = logging.getLogger("crdt_enc_tpu.sim")

QUIESCE_MAX_ROUNDS = 8
WARM_COLD_SAMPLES = 2  # replicas per quiescence given the warm≡cold check


class DeterministicCryptor(IdentityCryptor):
    """Identity cryptor with seeded key material, so key registers —
    and therefore every content-addressed file name — replay exactly."""

    def __init__(self, seed: str):
        self._rng = random.Random(f"crdt-sim-key-{seed}")

    async def gen_key(self):
        from ..utils import VersionBytes

        return VersionBytes(
            IDENTITY_KEY_VERSION_1, self._rng.getrandbits(256).to_bytes(32, "big")
        )


# The per-run uuid stream lives in a ContextVar, not a bare global: a
# population run (sim/population.py) executes P schedules concurrently in
# one event loop, and each lane's task context — inherited by every child
# task and to_thread hop it spawns — carries its OWN schedule-seeded
# stream.  A serial run sees exactly the historical single stream, and
# code outside any sim context falls through to the real uuid4.
_UUID_RNG: contextvars.ContextVar = contextvars.ContextVar(
    "crdt_sim_uuid_rng", default=None
)
_uuid_orig = None
_uuid_patches = 0


def _context_uuid4():
    rng = _UUID_RNG.get()
    if rng is None:
        return _uuid_orig()
    return uuid.UUID(int=rng.getrandbits(128), version=4)


@contextlib.contextmanager
def _deterministic_uuid(seed: int):
    """Route ``uuid.uuid4`` to a schedule-seeded stream for the run:
    actor ids and key ids are the only remaining entropy behind file
    names and sort orders.  The stream is context-local (see above);
    the global ``uuid.uuid4`` patch is refcounted so overlapping
    population lanes install it once and the real uuid4 is restored
    when the last lane exits.  The event loop is single-threaded, so
    the refcount needs no lock."""
    global _uuid_orig, _uuid_patches
    rng = random.Random(f"crdt-sim-uuid-{seed}")
    token = _UUID_RNG.set(rng)
    if _uuid_patches == 0:
        _uuid_orig = uuid.uuid4
        uuid.uuid4 = _context_uuid4
    _uuid_patches += 1
    try:
        yield
    finally:
        _uuid_patches -= 1
        if _uuid_patches == 0:
            uuid.uuid4 = _uuid_orig
            _uuid_orig = None
        _UUID_RNG.reset(token)


@dataclass
class SimResult:
    violation: Violation | None
    steps_run: int = 0
    checks_run: int = 0
    fault_stats: Counter = field(default_factory=Counter)
    transient_missing_key: int = 0
    service_cycles: int = 0
    daemon_cycles: int = 0
    quarantined: int = 0
    strong_reads: int = 0
    strong_timeouts: int = 0
    fingerprint: str = ""

    @property
    def ok(self) -> bool:
        return self.violation is None


@dataclass
class _Replica:
    idx: int
    storage: FaultyStorage
    core: Core | None = None
    incarnation: int = 0
    last_status: dict | None = None  # per-incarnation monotonicity baseline
    actor_id: bytes | None = None  # survives crashes (dgc targets it)
    # strong-read session baseline (sim/linearize.py): the previous
    # strong cursor of THIS incarnation (a cold reopen starts a new
    # session — docs/strong_reads.md), and the last clock a SUCCESSFUL
    # await_stable promised coverage of (the read-your-writes oblig.)
    last_strong: object | None = None
    awaited: object | None = None


class _TapStorage:
    """The oracle's recording seam: wraps a replica's REAL (inner)
    storage so every op file that durably lands is captured as
    plaintext the moment it is written — BEFORE compaction GC can erase
    it and INSIDE the fault wrapper (a crash-before never reaches the
    tap, a crash-after raises only after the tap recorded the landed
    file).  Decryption happens eagerly with the writing core's own key
    material (the sealer necessarily holds its sealing key), so key
    rotation mid-history costs the oracle nothing.  Everything else
    delegates untouched — the system under test sees its normal
    storage."""

    def __init__(self, inner, oplog: dict):
        self.inner = inner
        self._oplog = oplog
        self.core = None  # set by the runner after Core.open

    def __getattr__(self, name):
        return getattr(self.inner, name)

    async def store_ops(self, actor, version, data):
        await self.inner.store_ops(actor, version, data)
        core = self.core
        if core is not None:
            from ..core.core import open_sealed_blob

            payload = await open_sealed_blob(
                core._data.keys, core.cryptor, data, None
            )
            self._oplog[(bytes(actor), int(version))] = payload


class SimRunner:
    """One schedule execution.  ``tmpdir`` is required for the fs
    backend (the shared remote + per-replica local dirs live under it);
    the memory backend ignores it."""

    def __init__(self, schedule: Schedule, *, tmpdir: str | None = None,
                 mesh=None, substrate=None):
        self.schedule = schedule
        self.tmpdir = tmpdir
        # population mode (sim/population.py): the shared substrate
        # supplies the ONE process-wide accelerator and FoldService
        # every lane folds through — compile classes and warm tiers are
        # fleet-wide, while storage, fault rolls, cryptors, and the
        # uuid stream stay strictly per-lane
        self.substrate = substrate
        if mesh is None and substrate is not None:
            mesh = substrate.mesh
        self.mesh = mesh  # service/daemon cycles run mesh-backed folds
        self.replicas: list[_Replica] = []
        self.members = [
            f"member-{i}".encode() for i in range(schedule.members)
        ]
        self.transient_missing_key = 0
        self.service_cycles = 0
        self.daemon_cycles = 0
        self.checks_run = 0
        # strong-read oracle (sim/linearize.py): plaintext of every op
        # file that ever landed, recorded by the _TapStorage seam —
        # compaction GC cannot erase the checker's evidence
        self._oplog: dict = {}
        self.strong_count = 0
        self.strong_timeouts = 0
        self._remote = None  # memory backend's shared MemoryRemote
        # persistent FleetDaemon for the daemon/ddrain vocabulary: one
        # control-plane instance lives ACROSS steps (that is the point —
        # its backoff/quarantine state meets the same hostile history
        # the replicas do); created lazily at the first daemon step
        self._daemon = None
        # ONE FoldService reused across every `service` step (the sim
        # fast path, ROADMAP item 5): service construction — warm tier,
        # config, telemetry wiring — was per-step overhead; run_cycle's
        # tenant-subset override cycles exactly the step's replicas, and
        # the shared warm tier's identity×epoch guard keeps reuse
        # byte-exact across the hostile history
        self._service_pool = None

    # ----------------------------------------------------------- plumbing
    def _inner_storage(self, idx: int):
        if self.schedule.backend == "memory":
            from ..backends.memory import MemoryRemote, MemoryStorage

            if self._remote is None:
                self._remote = MemoryRemote()
            return MemoryStorage(self._remote)
        if self.tmpdir is None:
            raise ValueError("fs backend needs a tmpdir")
        from ..backends.fs import FsStorage

        return FsStorage(
            os.path.join(self.tmpdir, f"r{idx}"),
            os.path.join(self.tmpdir, "remote"),
        )

    def _clean_storage(self, label: str):
        """A fresh, fault-free storage over the same remote (oracle,
        fsck): its local side is private scratch."""
        if self.schedule.backend == "memory":
            from ..backends.memory import MemoryStorage

            return MemoryStorage(self._remote)
        from ..backends.fs import FsStorage

        return FsStorage(
            os.path.join(self.tmpdir, f"check-{label}"),
            os.path.join(self.tmpdir, "remote"),
        )

    def _accel(self, idx: int):
        # odd replicas fold on the accelerator, even on the host
        # reference — both execution paths face every history
        if idx % 2 == 1:
            if self.substrate is not None:
                # one accelerator for the whole population: its plane
                # cache is state-identity keyed (never aliases across
                # lanes) and its vocab bucketing lands every lane's
                # folds in shared power-of-two compile classes
                return {"accelerator": self.substrate.accel}
            from ..parallel import TpuAccelerator

            return {"accelerator": TpuAccelerator(min_device_batch=1)}
        return {}

    def _opts(self, rep: _Replica, *, create: bool, storage=None,
              checkpoint: bool = True, host: bool = False) -> OpenOptions:
        from ..core import orset_adapter
        from ..backends.plain_keys import PlainKeyCryptor
        from ..utils.versions import DEFAULT_DATA_VERSION_1

        accel = {} if host else self._accel(rep.idx)
        return OpenOptions(
            storage=storage if storage is not None else rep.storage,
            cryptor=DeterministicCryptor(f"{self.schedule.seed}:{rep.idx}"),
            key_cryptor=PlainKeyCryptor(),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=create,
            checkpoint=checkpoint,
            delta=self.schedule.deltas,
            **accel,
        )

    async def _open(self, rep: _Replica, *, create: bool) -> None:
        rep.core = await Core.open(self._opts(rep, create=create))
        rep.actor_id = rep.core.actor_id
        rep.incarnation += 1
        rep.last_status = None  # monotonicity holds per incarnation
        # a reopen starts a new strong-read session: a cold rebuild may
        # legitimately expose an older frontier (docs/strong_reads.md),
        # and any read-your-writes obligation died with the session
        rep.last_strong = None
        rep.awaited = None
        tap = getattr(rep.storage, "inner", None)
        if isinstance(tap, _TapStorage):
            tap.core = rep.core

    # --------------------------------------------------------------- run
    def run(self) -> SimResult:
        """Execute the schedule + final quiescence check.  Returns a
        :class:`SimResult`; protocol violations land on
        ``result.violation`` instead of raising, so the shrinker and
        the CLI share one calling convention."""
        return asyncio.run(self.run_async())

    async def run_async(self) -> SimResult:
        """Population entry (sim/population.py): the same run, awaited
        inside an already-running event loop so P lanes share one loop
        and one substrate.  The uuid stream installs into THIS task's
        context only — concurrent lanes never see each other's draws."""
        with _deterministic_uuid(self.schedule.seed):
            return await self._run()

    async def _run(self) -> SimResult:
        sched = self.schedule
        trace.add("sim_schedules", 1)
        result = SimResult(violation=None)
        with trace.span("sim.run", meta=sched.seed):
            for i in range(sched.n_replicas):
                inner = self._inner_storage(i)
                if sched.strong_reads:
                    # the tap sits INSIDE the fault wrapper: it records
                    # exactly the files that durably land (crash-before
                    # never reaches it, crash-after raises only after
                    # it recorded).  Only strong-read schedules pay it,
                    # so every earlier fixture replays untouched.
                    inner = _TapStorage(inner, self._oplog)
                wrapper = FaultyStorage(
                    inner, sched.faults, seed=sched.seed, name=f"r{i}"
                )
                rep = _Replica(i, wrapper)
                self.replicas.append(rep)
            # bootstrap with faults off: a fleet that cannot even form
            # (e.g. every replica's key bootstrap crashes) explores
            # nothing — the adversary starts once the fleet exists
            for rep in self.replicas:
                rep.storage.heal()
            for rep in self.replicas:
                await self._open(rep, create=True)
            for rep in self.replicas:
                rep.storage.arm()

            # per-run quarantine tally via a context-local counter tap:
            # the registry's ingest_quarantined is process-wide, and a
            # population interleaves P runs' increments — the tap sees
            # exactly the increments made by THIS run's task tree
            with trace.counter_tap() as tap:
                try:
                    for step_idx, step in enumerate(sched.steps):
                        result.steps_run = step_idx + 1
                        trace.add("sim_steps", 1)
                        with trace.span("sim.step", meta=step_idx):
                            violation = await self._exec(step, step_idx)
                        if violation is not None:
                            result.violation = violation
                            break
                    if result.violation is None:
                        try:
                            result.violation = await self._quiesce_and_check(
                                len(sched.steps)
                            )
                        except InvariantViolation:
                            raise
                        except Exception as e:
                            result.violation = Violation(
                                "check_error", repr(e), len(sched.steps)
                            )
                except InvariantViolation as iv:
                    result.violation = iv.violation
        for rep in self.replicas:
            result.fault_stats.update(rep.storage.stats)
        trace.add(
            "sim_faults_injected", sum(result.fault_stats.values())
        )
        if result.violation is not None:
            trace.add("sim_violations", 1)
        result.transient_missing_key = self.transient_missing_key
        result.strong_reads = self.strong_count
        result.strong_timeouts = self.strong_timeouts
        result.service_cycles = self.service_cycles
        result.daemon_cycles = self.daemon_cycles
        result.checks_run = self.checks_run
        result.quarantined = int(tap.get("ingest_quarantined", 0))
        result.fingerprint = self._fingerprint(result)
        return result

    def _fingerprint(self, result: SimResult) -> str:
        """Digest of everything a deterministic replay must reproduce:
        final states, cursors, and the injected-fault tallies."""
        from ..models import canonical_bytes

        h = hashlib.sha256()
        for rep in self.replicas:
            if rep.core is not None:
                h.update(rep.core.with_state(canonical_bytes))
                h.update(
                    json.dumps(
                        sorted(
                            (a.hex(), v)
                            for a, v in
                            rep.core.info().next_op_versions.counters.items()
                        )
                    ).encode()
                )
        h.update(json.dumps(sorted(result.fault_stats.items())).encode())
        return h.hexdigest()

    # -------------------------------------------------------------- steps
    async def _exec(self, step, step_idx: int) -> Violation | None:
        rep = self.replicas[step.replica] if step.replica < len(self.replicas) else None
        kind = step.kind
        if kind == "tick":
            for r in self.replicas:
                r.storage.tick()
            return None
        if kind == "quiesce":
            try:
                violation = await self._quiesce_and_check(step_idx)
            except InvariantViolation:
                raise
            except Exception as e:
                # a checker that cannot even run (open crashes on a
                # corrupt remote) is itself a finding — surface it as a
                # shrinkable violation, never a harness traceback
                violation = Violation("check_error", repr(e), step_idx)
            for r in self.replicas:
                r.storage.arm()
            return violation
        if kind == "dgc":
            # GC-mid-chain: collect the target sealer's whole delta log
            # out from under every consumer — they must fall back to
            # the snapshot path, never diverge or stall (docs/delta.md)
            target = self.replicas[step.arg]
            if target.actor_id is not None:
                await self._clean_storage(
                    f"dgc{step_idx}"
                ).remove_deltas([(target.actor_id, 1 << 62)])
            return None
        if kind == "daemon":
            return await self._daemon_step(step_idx)
        if kind == "ddrain":
            return await self._daemon_drain(step_idx)
        if kind == "reopen":
            if rep.core is None:
                try:
                    await self._open(rep, create=False)
                except SimCrash:
                    pass  # crashed again mid-reopen; stays dead
                except MissingKeyError:
                    self.transient_missing_key += 1
            return None
        if rep is None or rep.core is None:
            return None  # steps on dead replicas are no-ops (shrink-safe)
        if kind == "crash":
            # the process dies mid-anything: memory state discarded,
            # storage keeps exactly what landed
            rep.core = None
            return None
        try:
            if kind == "add":
                m = self.members[step.arg % len(self.members)]
                core = rep.core
                await core.update(lambda s: s.add_ctx(core.actor_id, m))
            elif kind == "rm":
                m = self.members[step.arg % len(self.members)]
                await rep.core.update(
                    lambda s: s.rm_ctx(m) if s.contains(m) else None
                )
            elif kind in ("read", "dread"):
                await rep.core.read_remote()
            elif kind in ("compact", "dseal"):
                await rep.core.compact()
            elif kind == "rotate":
                await rep.core.rotate_key()
            elif kind == "compact2":
                return await self._compact2(rep, step.arg, step_idx)
            elif kind == "service":
                return await self._service(rep, step.arg, step_idx)
            elif kind == "read_strong":
                return await self._read_strong(rep, step_idx)
            elif kind == "await_stable":
                return await self._await_stable(rep, step_idx)
            else:
                raise ValueError(f"unknown step kind {kind!r}")
        except SimCrash:
            rep.core = None
        except (MissingKeyError, StaleWriterError, IngestDecryptError):
            # documented loud-but-transient states: key metadata / own
            # history not yet visible, or a whole batch of torn blobs
            # (the escalation rule fires loudly; the sim's tears ARE
            # transient, so the step is simply retried later)
            self.transient_missing_key += 1
        except Exception as e:
            logger.warning(
                "sim step %d (%s on r%d) failed", step_idx, kind, rep.idx,
                exc_info=True,
            )
            return Violation("step_error", f"{kind} on r{rep.idx}: {e!r}", step_idx)
        return None

    async def _compact2(self, rep, peer_idx: int, step_idx: int) -> Violation | None:
        """Two replicas compact the same remote CONCURRENTLY."""
        peer = self.replicas[peer_idx]
        targets = [rep] if peer.core is None or peer is rep else [rep, peer]
        outcomes = await asyncio.gather(
            *(r.core.compact() for r in targets), return_exceptions=True
        )
        for r, out in zip(targets, outcomes):
            if isinstance(out, SimCrash):
                r.core = None
            elif isinstance(out, (MissingKeyError, IngestDecryptError)):
                self.transient_missing_key += 1
            elif isinstance(out, BaseException):
                logger.warning(
                    "sim step %d concurrent compact on r%d failed: %r",
                    step_idx, r.idx, out,
                )
                return Violation(
                    "step_error",
                    f"concurrent compact on r{r.idx}: {out!r}",
                    step_idx,
                )
        return None

    async def _service(self, rep, peer_idx: int, step_idx: int) -> Violation | None:
        """A FoldService cycle compacts 1-2 replicas as tenants — the
        serving layer's sealing path in the same hostile history as the
        solo compactors."""
        from ..serve import FoldService, ServeConfig

        peer = self.replicas[peer_idx]
        tenants = [rep]
        if peer is not rep and peer.core is not None:
            tenants.append(peer)
        if self._service_pool is None:
            if self.substrate is not None:
                self._service_pool = self.substrate.service
            else:
                self._service_pool = FoldService(
                    [], ServeConfig(seal_empty=True), mesh=self.mesh
                )
        cores = [t.core for t in tenants]
        if self.substrate is not None:
            # shared-owner entry: overlapping lanes queue; each queued
            # cycle touches only this lane's tenants, so the lane's
            # cycle is byte-identical to the private-service cycle
            results = await self._service_pool.run_cycle_shared(cores)
        else:
            results = await self._service_pool.run_cycle(cores)
        self.service_cycles += 1
        for t, res in zip(tenants, results):
            if res.error is None:
                continue
            if "SimCrash" in res.error:
                t.core = None
            elif (
                "MissingKeyError" in res.error
                or "IngestDecryptError" in res.error
            ):
                self.transient_missing_key += 1
            else:
                return Violation(
                    "service_error",
                    f"tenant r{t.idx}: {res.error}",
                    step_idx,
                )
        if self.schedule.strong_reads:
            # served tenants get the same guarantee: a strong read
            # through the service's per-tenant endpoint, validated by
            # the same checker (refresh=False — the cycle just ingested)
            for t, res in zip(tenants, results):
                if res.error is None and t.core is not None:
                    v = await self._read_strong(
                        t, step_idx, service=True
                    )
                    if v is not None:
                        return v
        return None

    # ------------------------------------------------------ strong reads
    async def _read_strong(self, rep, step_idx: int, *,
                           service: bool = False) -> Violation | None:
        """One linearizable read + the full checker
        (sim/linearize.py): exactness against the oracle fold of its
        cut, durability, session monotonicity, and any pending
        read-your-writes obligation.  ``service=True`` routes through
        the FoldService per-tenant endpoint instead of the core —
        same guarantee, same checker."""
        from .linearize import check_strong_read

        if service:
            res = await self._service_pool.read_strong(
                rep.core, refresh=False
            )
        else:
            res = await rep.core.read(linearizable=True)
        self.strong_count += 1
        defect = check_strong_read(
            self._oplog, res, rep.last_strong, ryw_target=rep.awaited
        )
        rep.awaited = None  # the obligation is checked exactly once
        if defect is not None:
            return Violation(
                "linearizability", f"r{rep.idx}: {defect}", step_idx
            )
        rep.last_strong = res.cursor
        return None

    async def _await_stable(self, rep, step_idx: int) -> Violation | None:
        """The freshness-wait protocol on the replica's own last-write
        clock.  Determinism seams: polling advances every replica's
        sync ticks (delayed files move toward visibility) and the
        timeout counts polls, not wall time.  A timeout under faults is
        loud-but-transient (a silent or crashed peer legitimately holds
        the watermark); a SUCCESS creates the read-your-writes
        obligation the follow-up strong read is checked against."""
        from ..models.vclock import VClock
        from ..read.stable import StalenessError

        lm = rep.core._local_meta
        if lm is None or lm.last_op_version == 0:
            return None  # never wrote: nothing to await
        target = VClock({rep.core.actor_id: lm.last_op_version})

        async def on_poll():
            for r in self.replicas:
                r.storage.tick()

        polls = [0.0]

        def clock():
            polls[0] += 1.0
            return polls[0]

        try:
            await rep.core.await_stable(
                target, timeout_s=6.0, on_poll=on_poll, clock=clock
            )
        except StalenessError:
            self.strong_timeouts += 1
            return None
        rep.awaited = target
        return await self._read_strong(rep, step_idx)

    # ------------------------------------------------------------ daemon
    def _daemon_transient(self, err: str) -> bool:
        return any(
            t in err
            for t in ("MissingKeyError", "StaleWriterError",
                      "IngestDecryptError")
        )

    async def _daemon_step(self, step_idx: int) -> Violation | None:
        """One supervised FleetDaemon cycle over the alive fleet: the
        always-on control plane (serve/daemon.py) inside the hostile
        history.  The daemon instance persists across steps; its tenant
        set is synced to replica liveness before the cycle (crashed
        replicas are discarded, reopened ones re-admitted), and its
        per-tenant error reprs follow the same discipline as the
        ``service`` step: crash kills the replica, the documented
        transient classes are counted, anything else is a violation."""
        from ..serve import DaemonConfig, FleetDaemon, ServeConfig

        if self._daemon is None:
            self._daemon = FleetDaemon(
                config=DaemonConfig(
                    max_idle_cycles=1,
                    backoff_base=1.0, backoff_cap=4.0,
                    quarantine_after=3, quarantine_probe_every=2,
                    breaker_after=4, breaker_probe_every=2,
                    serve=ServeConfig(seal_empty=True),
                ),
                seed=self.schedule.seed,
                mesh=self.mesh,
                # the deterministic-clock seam: daemon wall-time reads
                # (uptime, SLO burn window) count cycles instead of
                # reading the host clock, so replays stay bit-for-bit
                clock=lambda: float(self.daemon_cycles),
            )
        daemon = self._daemon
        await self._daemon_sync(daemon)
        report = await daemon.run_cycle()
        self.daemon_cycles += 1
        for tid, res in report["results"].items():
            err = res.get("error")
            if not err:
                continue
            rep = self.replicas[int(tid[1:])]
            if "SimCrash" in err:
                rep.core = None
                await daemon.discard(tid)
            elif self._daemon_transient(err):
                self.transient_missing_key += 1
            else:
                return Violation(
                    "daemon_error", f"tenant {tid}: {err}", step_idx
                )
        return None

    async def _daemon_sync(self, daemon) -> None:
        """Sync the daemon's tenant set to replica liveness: crashed
        replicas are discarded (their core handles are dead
        incarnations the crash model says are gone), reopened ones
        re-admitted.  Runs before every cycle AND before a drain — a
        drain must never checkpoint a dead incarnation's handle."""
        for rep in self.replicas:
            tid = f"r{rep.idx}"
            entry = daemon.entry(tid)
            if rep.core is None:
                if entry is not None:
                    await daemon.discard(tid)
            elif entry is None:
                await daemon.admit(rep.core, tid=tid)
            elif entry.core is not rep.core:
                await daemon.discard(tid)
                await daemon.admit(rep.core, tid=tid)

    async def _daemon_drain(self, step_idx: int) -> Violation | None:
        """Graceful drain: checkpoint every tenant, stop the instance.
        The next ``daemon`` step starts fresh — reopening the fleet's
        control plane through the checkpoints just sealed."""
        if self._daemon is None:
            return None
        daemon, self._daemon = self._daemon, None
        await self._daemon_sync(daemon)
        errors = await daemon.drain()
        for tid, err in errors.items():
            if "SimCrash" in err:
                # the checkpoint write crashed the replica's process
                self.replicas[int(tid[1:])].core = None
            else:
                # a failed drain checkpoint is survivable by design —
                # the next open falls back cold — never a violation
                self.transient_missing_key += 1
        return None

    # -------------------------------------------------------- quiescence
    async def _quiesce_and_check(self, step_idx: int) -> Violation | None:
        """Heal, drain to a read fixed point, run every invariant."""
        from ..models import canonical_bytes

        with trace.span("sim.check", meta=step_idx):
            self.checks_run += 1
            for rep in self.replicas:
                rep.storage.heal()
            for rep in self.replicas:
                if rep.core is None:
                    try:
                        await self._open(rep, create=False)
                    except MissingKeyError:
                        return Violation(
                            "step_error",
                            f"r{rep.idx} missing key AFTER heal",
                            step_idx,
                        )
                    except Exception as e:
                        # e.g. DanglingLatestKey: corruption must become
                        # a shrinkable VIOLATION, never a harness crash
                        return Violation(
                            "step_error",
                            f"r{rep.idx} reopen after heal: {e!r}",
                            step_idx,
                        )
            prev = None
            for _ in range(QUIESCE_MAX_ROUNDS):
                # batched host-reference reads: the whole fleet's drain
                # round fans out in one gather instead of N serial
                # awaits (the sim fast path's second half) — reads are
                # idempotent merges over a healed, quiet remote, and
                # each replica's own call stream stays ordered, so the
                # fixed point and the fault-roll streams are unchanged
                await asyncio.gather(
                    *(rep.core.read_remote() for rep in self.replicas)
                )
                snap = [
                    (
                        rep.core.with_state(canonical_bytes),
                        tuple(
                            sorted(
                                rep.core.info().next_op_versions.counters.items()
                            )
                        ),
                    )
                    for rep in self.replicas
                ]
                if snap == prev and len({s[0] for s in snap}) == 1:
                    break
                prev = snap
            else:
                detail = divergence_detail(
                    [
                        (f"r{rep.idx}", rep.core.with_state(canonical_bytes))
                        for rep in self.replicas
                    ]
                )
                return Violation(
                    "no_quiescence",
                    detail or "reads never reached a fixed point",
                    step_idx,
                )
            blobs = [
                (f"r{rep.idx}", rep.core.with_state(canonical_bytes))
                for rep in self.replicas
            ]
            detail = divergence_detail(blobs)
            if detail is not None:
                return Violation("divergence", detail, step_idx)
            reference = blobs[0][1]

            v = await self._check_oracle(reference, step_idx)
            if v is None:
                v = await self._check_warm_cold(reference, step_idx)
            if v is None:
                v = await self._check_monotonicity(step_idx)
            if v is None:
                v = await self._check_fsck(step_idx)
            return v

    async def _check_oracle(self, reference: bytes, step_idx: int):
        from ..models import canonical_bytes

        rep0 = self.replicas[0]
        oracle = await Core.open(
            self._opts(
                rep0, create=True,
                storage=self._clean_storage(f"oracle{self.checks_run}"),
                checkpoint=False, host=True,
            )
        )
        await oracle.read_remote()
        if oracle.with_state(canonical_bytes) != reference:
            return Violation(
                "oracle",
                "fresh host refold of the remote diverges from the fleet",
                step_idx,
            )
        return None

    async def _check_warm_cold(self, reference: bytes, step_idx: int):
        """Warm-open vs cold-open byte identity for the sampled
        replicas.  The per-replica (warm → cold) pair fans out ACROSS
        replicas in one gather (the sim fast path's second slice —
        the same argument as the drain loop's: each replica's own
        storage-call stream keeps its order inside its coroutine, and
        the fault-roll RNG streams are per-storage, so cross-replica
        interleaving cannot move a single tally); the violation scan
        stays serial in replica order, so the FIRST violation reported
        is deterministic."""
        from ..models import canonical_bytes

        async def one(rep):
            warm = await Core.open(self._opts(rep, create=False))
            await warm.read_remote()
            cold = await Core.open(
                self._opts(rep, create=False, checkpoint=False)
            )
            await cold.read_remote()
            return (
                warm.with_state(canonical_bytes),
                cold.with_state(canonical_bytes),
                warm.checkpoint_fallback_reason,
            )

        sampled = self.replicas[:WARM_COLD_SAMPLES]
        results = await asyncio.gather(*(one(rep) for rep in sampled))
        for rep, (wb, cb, fallback) in zip(sampled, results):
            if wb != cb or wb != reference:
                return Violation(
                    "warm_cold",
                    f"r{rep.idx}: warm-open {'==' if wb == cb else '!='} "
                    f"cold-open, fleet match warm={wb == reference} "
                    f"cold={cb == reference} "
                    f"(fallback={fallback})",
                    step_idx,
                )
        return None

    async def _check_monotonicity(self, step_idx: int):
        """Replication-status sampling fans out across replicas in one
        gather (same per-replica stream argument as above); the
        regression comparison and the ``last_status`` update run
        serially in replica order afterwards, so both the violation
        choice and the stored baselines are deterministic."""
        statuses = await asyncio.gather(
            *(rep.core.replication_status() for rep in self.replicas)
        )
        for rep, status in zip(self.replicas, statuses):
            defect = replication_regression(rep.last_status, status)
            if defect is not None:
                return Violation(
                    "monotonicity", f"r{rep.idx}: {defect}", step_idx
                )
            if rep.last_status is not None and known_replica_set(
                status
            ) < known_replica_set(rep.last_status):
                return Violation(
                    "monotonicity",
                    f"r{rep.idx}: known replica set shrank",
                    step_idx,
                )
            rep.last_status = status
        return None

    async def _check_fsck(self, step_idx: int):
        from ..backends.plain_keys import PlainKeyCryptor
        from ..tools.fsck import fsck_remote

        report = await fsck_remote(
            self._clean_storage(f"fsck{self.checks_run}"),
            DeterministicCryptor("fsck"),
            PlainKeyCryptor(),
            deep=True,
        )
        if not report.ok:
            issues = "; ".join(
                str(i) for i in report.issues if i.severity == "error"
            )
            return Violation("fsck", issues[:500], step_idx)
        return None


def run_schedule(schedule: Schedule, *, tmpdir: str | None = None) -> SimResult:
    """Convenience front door: one runner, one result."""
    return SimRunner(schedule, tmpdir=tmpdir).run()

"""Seeded schedules: the explorable space of replica interleavings.

A :class:`Schedule` is a fully materialized, JSON-serializable program
for the simulator: N replicas, a step list, and a fault configuration.
:func:`generate` derives one deterministically from a seed — no wall
clock, no global RNG — so ``(seed, replicas, steps, faults)`` names one
exact history and a failure found at fleet scale replays bit-for-bit
from four numbers.  Shrunk failures serialize through
:meth:`Schedule.to_obj` into the committed fixtures under
``tests/data/sim/`` (docs/simulation.md).

Step kinds (``Step.kind``):

========== ==================================================================
``add``     replica adds member ``arg`` to the OR-Set
``rm``      replica removes member ``arg`` (no-op when absent)
``read``    replica ``read_remote()``
``compact`` replica ``compact()``
``compact2`` replicas ``replica`` and ``arg`` compact CONCURRENTLY
``service`` a :class:`~crdt_enc_tpu.serve.FoldService` cycle compacts
            replica ``replica`` (and ``arg`` when different) as tenants
``rotate``  replica rotates the data key mid-sync
``crash``   replica crashes (Core discarded; storage keeps what landed)
``reopen``  replica reopens from its local dir (warm checkpoint in play)
``tick``    one sync tick on every replica's fault wrapper (delayed
            files move toward visibility)
``quiesce`` mid-run quiescence point: heal, drain, run the full
            invariant check, then re-arm the faults
``dseal``   seal-delta: replica compacts with delta-state replication
            in play (generated only for ``deltas`` schedules)
``dread``   read-delta-chain: replica ``read_remote()`` — with deltas
            on, the chain-first consumer path (docs/delta.md)
``dgc``     GC-mid-chain: replica ``arg``'s whole delta log is removed
            out from under every consumer (the hostile move that
            forces the fallback-to-snapshot path)
``daemon``  one supervised :class:`~crdt_enc_tpu.serve.FleetDaemon`
            cycle over a PERSISTENT daemon instance whose tenants are
            the currently-alive replicas (admitted/evicted to match
            liveness before the cycle runs) — staleness scheduling,
            backoff and quarantine all face the same hostile storage
``ddrain``  graceful daemon drain (checkpoints every tenant, stops the
            instance); the next ``daemon`` step starts a fresh daemon
            that reopens through the checkpoints
``read_strong`` replica serves a linearizable read from its stable
            prefix (``Core.read(linearizable=True)``); the result is
            validated on the spot by the linearizability checker
            (sim/linearize.py) — exactness against the oracle fold of
            its cut, session monotonicity, durability
``await_stable`` replica runs the freshness-wait protocol on its own
            last-write clock (read-your-writes made strong): a timeout
            under faults is loud-but-transient, a SUCCESS obligates the
            follow-up strong read to cover the awaited clock — checked
========== ==================================================================

``Schedule.deltas`` turns delta-state replication on for every
replica's ``OpenOptions``; it defaults OFF so pre-delta fixtures
replay bit-for-bit, and the generator only emits the ``d*`` step
kinds (and only perturbs its RNG stream) when it is on.
``Schedule.daemon`` does the same for the ``daemon``/``ddrain``
vocabulary (ISSUE 12): default OFF, so every pre-daemon fixture and
seed replays untouched.  ``Schedule.strong_reads`` gates the
``read_strong``/``await_stable`` vocabulary (ISSUE 15) under the same
RNG-stream preservation rule.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from .faults import FaultConfig

SCHEDULE_VERSION = 1

STEP_KINDS = (
    "add",
    "rm",
    "read",
    "compact",
    "compact2",
    "service",
    "rotate",
    "crash",
    "reopen",
    "tick",
    "quiesce",
    "dseal",
    "dread",
    "dgc",
    "daemon",
    "ddrain",
    "read_strong",
    "await_stable",
)


@dataclass
class Step:
    kind: str
    replica: int = 0
    arg: int = 0

    def to_obj(self):
        return [self.kind, self.replica, self.arg]

    @classmethod
    def from_obj(cls, obj) -> "Step":
        kind, replica, arg = obj
        if kind not in STEP_KINDS:
            raise ValueError(f"unknown step kind {kind!r}")
        return cls(str(kind), int(replica), int(arg))


@dataclass
class Schedule:
    seed: int
    n_replicas: int
    steps: list = field(default_factory=list)
    faults: FaultConfig = field(default_factory=FaultConfig)
    members: int = 12
    backend: str = "memory"  # "memory" (deterministic) | "fs"
    deltas: bool = False  # delta-state replication on every replica
    daemon: bool = False  # daemon/ddrain vocabulary (FleetDaemon runs)
    strong_reads: bool = False  # read_strong/await_stable vocabulary
    note: str = ""

    def to_obj(self) -> dict:
        return {
            "v": SCHEDULE_VERSION,
            "seed": self.seed,
            "replicas": self.n_replicas,
            "members": self.members,
            "backend": self.backend,
            "deltas": self.deltas,
            "daemon": self.daemon,
            "strong": self.strong_reads,
            "faults": self.faults.to_obj(),
            "steps": [s.to_obj() for s in self.steps],
            "note": self.note,
        }

    @classmethod
    def from_obj(cls, obj: dict) -> "Schedule":
        v = obj.get("v")
        if v != SCHEDULE_VERSION:
            raise ValueError(f"unsupported schedule version {v!r}")
        backend = obj.get("backend", "memory")
        if backend not in ("memory", "fs"):
            raise ValueError(f"unknown backend {backend!r}")
        sched = cls(
            seed=int(obj["seed"]),
            n_replicas=int(obj["replicas"]),
            steps=[Step.from_obj(s) for s in obj["steps"]],
            faults=FaultConfig.from_obj(obj.get("faults", {})),
            members=int(obj.get("members", 12)),
            backend=backend,
            deltas=bool(obj.get("deltas", False)),
            daemon=bool(obj.get("daemon", False)),
            strong_reads=bool(obj.get("strong", False)),
            note=str(obj.get("note", "")),
        )
        bad = [
            s for s in sched.steps
            if not (0 <= s.replica < sched.n_replicas)
            or (s.kind in ("compact2", "service", "dgc")
                and not (0 <= s.arg < sched.n_replicas))
        ]
        if bad:
            raise ValueError(f"steps reference replicas out of range: {bad[:3]}")
        return sched

    def with_steps(self, steps: list) -> "Schedule":
        return Schedule(
            seed=self.seed,
            n_replicas=self.n_replicas,
            steps=list(steps),
            faults=self.faults,
            members=self.members,
            backend=self.backend,
            deltas=self.deltas,
            daemon=self.daemon,
            strong_reads=self.strong_reads,
            note=self.note,
        )

    def with_faults(self, faults: FaultConfig) -> "Schedule":
        sched = self.with_steps(self.steps)
        sched.faults = faults
        return sched


# step-kind weights: mostly writes and syncs, a steady trickle of the
# hostile moves (concurrent compactors, service cycles, rotation,
# crashes).  ``reopen`` weight applies only while someone is dead —
# the generator tracks liveness so schedules stay well-formed.
_WEIGHTS = [
    ("add", 0.34),
    ("rm", 0.10),
    ("read", 0.16),
    ("compact", 0.09),
    ("compact2", 0.03),
    ("service", 0.04),
    ("rotate", 0.02),
    ("crash", 0.03),
    ("reopen", 0.05),
    ("tick", 0.12),
    ("quiesce", 0.02),
]

# extra vocabulary for delta-enabled schedules (ROADMAP item-5
# "Remaining"): explicit seal-delta / read-delta-chain traffic plus the
# GC-mid-chain hostile move.  Appended ONLY when deltas are on, so the
# RNG stream — and therefore every pre-delta seed — is untouched.
_DELTA_WEIGHTS = [
    ("dseal", 0.06),
    ("dread", 0.06),
    ("dgc", 0.02),
]

# daemon vocabulary (ISSUE 12): a steady trickle of supervised control-
# plane cycles plus the occasional graceful drain (the next daemon step
# restarts through checkpoints).  Appended only when the daemon flag is
# on — same RNG-stream preservation rule as the delta vocabulary.
_DAEMON_WEIGHTS = [
    ("daemon", 0.06),
    ("ddrain", 0.01),
]

# strong-read vocabulary (ISSUE 15): a steady stream of linearizable
# reads plus occasional freshness waits on the reader's own last write.
# Appended only when the strong_reads flag is on — same RNG-stream
# preservation rule, so every earlier fixture and seed replays
# untouched.
_STRONG_WEIGHTS = [
    ("read_strong", 0.08),
    ("await_stable", 0.03),
]


def generate(
    seed: int,
    n_replicas: int,
    n_steps: int,
    faults: FaultConfig,
    *,
    members: int = 12,
    backend: str = "memory",
    deltas: bool = False,
    daemon: bool = False,
    strong_reads: bool = False,
) -> Schedule:
    """One deterministic schedule from a seed.  Every replica both
    writes and syncs; dead replicas receive only ``reopen`` steps; the
    final step list always ends in enough reopens that the quiescence
    phase starts with a full fleet."""
    rng = random.Random(f"crdt-sim-{seed}")
    table = (
        _WEIGHTS
        + (_DELTA_WEIGHTS if deltas else [])
        + (_DAEMON_WEIGHTS if daemon else [])
        + (_STRONG_WEIGHTS if strong_reads else [])
    )
    kinds = [k for k, _ in table]
    weights = [w for _, w in table]
    dead: set[int] = set()
    steps: list[Step] = []
    for _ in range(n_steps):
        kind = rng.choices(kinds, weights)[0]
        if kind == "reopen":
            if not dead:
                kind = "read"
        elif kind == "crash" and len(dead) >= max(1, n_replicas // 2):
            kind = "tick"  # keep a quorum alive so histories stay dense
        if kind == "tick":
            steps.append(Step("tick"))
            continue
        if kind == "quiesce":
            steps.append(Step("quiesce"))
            dead.clear()  # quiescence reopens every dead replica
            continue
        if kind in ("daemon", "ddrain"):
            # global control-plane steps: the replica field is unused
            # (the daemon's tenants are whatever is alive at execution)
            steps.append(Step(kind))
            continue
        if kind == "reopen":
            r = rng.choice(sorted(dead))
            dead.discard(r)
            steps.append(Step("reopen", r))
            continue
        alive = [i for i in range(n_replicas) if i not in dead]
        if not alive:
            steps.append(Step("tick"))
            continue
        r = rng.choice(alive)
        if kind == "crash":
            dead.add(r)
            steps.append(Step("crash", r))
        elif kind in ("add", "rm"):
            steps.append(Step(kind, r, rng.randrange(members)))
        elif kind in ("compact2", "service"):
            peer = rng.choice(alive)
            steps.append(Step(kind, r, peer))
        elif kind == "dgc":
            # arg names the sealer whose delta log gets collected
            steps.append(Step(kind, r, rng.choice(alive)))
        else:
            steps.append(Step(kind, r))
    for r in sorted(dead):
        steps.append(Step("reopen", r))
    return Schedule(
        seed=seed,
        n_replicas=n_replicas,
        steps=steps,
        faults=faults,
        members=members,
        backend=backend,
        deltas=deltas,
        daemon=daemon,
        strong_reads=strong_reads,
    )

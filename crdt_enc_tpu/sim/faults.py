"""Fault-injecting storage wrapper: the adversarial sync tool.

The replication substrate is a *passively synced directory* — the system
never sees the sync tool, only its effects.  :class:`FaultyStorage` wraps
any :class:`~crdt_enc_tpu.core.storage.Storage` and plays the hostile
version of that tool, injecting every damage class the survey and the
fsck taxonomy name (docs/simulation.md):

* **torn reads** — an op/state/meta blob comes back truncated (a sync
  caught mid-transfer; the bytes on the remote are fine, so a retry
  after repair succeeds);
* **partial listings** — a listing omits a subset of names (only part of
  the directory has synced);
* **delayed visibility** — a file another replica stored becomes visible
  only after a number of sync *ticks* (:meth:`tick`), modelling transfer
  lag; a replica always sees its own writes immediately;
* **duplicate delivery** — an op load re-delivers already-consumed
  versions (the reader's concurrent-read tolerance must skip them);
* **write crashes** — a store/remove raises :class:`SimCrash` either
  *before* or *after* the inner write takes effect (crash-during-seal:
  the caller cannot know which);
* **stale checkpoints** — ``load_local_checkpoint`` serves the previous
  generation (cursor skew: the resume point lags the durable history).

Every decision is a pure function of ``(seed, family, per-family call
counter)`` via SHA-256 — no wall clock, no shared RNG stream — so a
schedule replay against the same storage call sequence injects the same
faults.  :meth:`heal` ends the adversarial phase (the "sync completed"
fixed point the quiescence checker needs); :attr:`stats` counts every
injected fault per class so runs can report fault-survival totals.

The wrapper is simulation infrastructure, not a production path — but it
only uses the public Storage port, so anything that survives it survives
a real misbehaving sync tool with the same failure envelope.
"""

from __future__ import annotations

import hashlib
from collections import Counter
from dataclasses import dataclass, fields

from ..core.storage import Storage
from ..models.vclock import Actor


class SimCrash(Exception):
    """An injected crash at a write step.  The simulator treats the
    owning replica as dead (its Core is discarded, later reopened);
    production code never sees this type."""


@dataclass
class FaultConfig:
    """Per-class fault probabilities (0 disables a class).  The class
    names double as the schedule-JSON fault keys and the shrinker's
    dimensions — ``python -m crdt_enc_tpu.tools.sim run --faults all``
    enables every class at its default adversarial rate."""

    torn_read: float = 0.0
    partial_list: float = 0.0
    delay_visibility: float = 0.0
    delay_max_ticks: int = 3
    dup_delivery: float = 0.0
    write_crash: float = 0.0
    stale_checkpoint: float = 0.0

    CLASSES = (
        "torn_read",
        "partial_list",
        "delay_visibility",
        "dup_delivery",
        "write_crash",
        "stale_checkpoint",
    )

    @classmethod
    def all_faults(cls) -> "FaultConfig":
        """Every fault class on, at rates convergence can still survive
        within a few hundred steps (the defaults the fleet run uses)."""
        return cls(
            torn_read=0.08,
            partial_list=0.10,
            delay_visibility=0.25,
            delay_max_ticks=3,
            dup_delivery=0.10,
            write_crash=0.04,
            stale_checkpoint=0.20,
        )

    @classmethod
    def none(cls) -> "FaultConfig":
        return cls()

    def to_obj(self) -> dict:
        return {f.name: getattr(self, f.name) for f in fields(self)}

    @classmethod
    def from_obj(cls, obj: dict) -> "FaultConfig":
        known = {f.name for f in fields(cls)}
        unknown = set(obj) - known
        if unknown:
            raise ValueError(f"unknown fault keys: {sorted(unknown)}")
        return cls(**{k: v for k, v in obj.items()})

    def without(self, name: str) -> "FaultConfig":
        """A copy with one fault class disabled — the shrinker's
        fault-dimension move."""
        obj = self.to_obj()
        if name not in obj:
            raise ValueError(f"unknown fault class {name!r}")
        obj[name] = 0.0 if name != "delay_max_ticks" else 0
        return self.from_obj(obj)

    def enabled_classes(self) -> list[str]:
        return [c for c in self.CLASSES if getattr(self, c)]


class FaultyStorage(Storage):
    """Wrap ``inner`` with deterministic fault injection (module docs).

    ``name`` keys this wrapper's decision stream (one per replica);
    ``seed`` keys the whole run.  All faults are *transient*: after
    :meth:`heal`, every call passes through clean and every delayed
    file is visible — the quiescence contract."""

    def __init__(self, inner: Storage, cfg: FaultConfig, *, seed: int, name: str):
        self.inner = inner
        self.cfg = cfg
        self.seed = int(seed)
        self.name = str(name)
        self.active = True
        self.ticks = 0
        self.stats: Counter = Counter()
        self._counters: Counter = Counter()
        # delayed visibility: key -> tick at which it becomes visible.
        # Keys are listing names for metas/states and (actor, version)
        # for op files; a key stored THROUGH this wrapper is its own
        # write and registers as immediately visible.
        self._reveal: dict = {}
        # last two checkpoint generations (stale-checkpoint fault)
        self._ckpt_prev: bytes | None = None

    # ------------------------------------------------------------ control
    def tick(self) -> None:
        """One sync tick: delayed files whose reveal time has come become
        visible on the next listing/load."""
        self.ticks += 1

    def heal(self) -> None:
        """End the adversarial phase: no new faults, everything visible."""
        self.active = False

    def arm(self) -> None:
        """Re-enable fault injection after a mid-run quiescence check."""
        self.active = True

    # ---------------------------------------------------------- decisions
    def _roll(self, family: str, extra: int = 0) -> tuple[float, float]:
        """Two uniform [0,1) draws for the next decision in ``family`` —
        a pure function of (seed, wrapper name, family, call counter),
        so the injection pattern is independent of everything but the
        storage call sequence itself."""
        self._counters[family] += 1
        h = hashlib.sha256(
            f"{self.seed}:{self.name}:{family}:{self._counters[family]}:{extra}".encode()
        ).digest()
        return (
            int.from_bytes(h[:8], "big") / 2**64,
            int.from_bytes(h[8:16], "big") / 2**64,
        )

    def _maybe_tear(self, family: str, raw: bytes) -> bytes:
        if not self.active or not self.cfg.torn_read or len(raw) < 2:
            return raw
        p, frac = self._roll(f"tear.{family}")
        if p >= self.cfg.torn_read:
            return raw
        self.stats["torn_read"] += 1
        return raw[: max(1, int(len(raw) * frac))]

    def _filter_listing(self, family: str, names: list) -> list:
        if not self.active:
            return names
        out = []
        for n in names:
            if not self._visible(family, n):
                continue
            if self.cfg.partial_list:
                p, _ = self._roll(f"list.{family}")
                if p < self.cfg.partial_list:
                    self.stats["partial_list"] += 1
                    continue
            out.append(n)
        return out

    def _visible(self, family: str, key) -> bool:
        """Delayed-visibility gate: first sighting of a foreign key rolls
        a reveal tick; until then the key does not exist for this
        replica.  Healing reveals everything."""
        if not self.active:
            return True
        if not self.cfg.delay_visibility:
            return True
        k = (family, key)
        reveal = self._reveal.get(k)
        if reveal is None:
            p, d = self._roll(f"delay.{family}")
            if p < self.cfg.delay_visibility:
                delay = 1 + int(d * max(1, self.cfg.delay_max_ticks))
                self.stats["delay_visibility"] += 1
            else:
                delay = 0
            reveal = self.ticks + delay
            self._reveal[k] = reveal
        return reveal <= self.ticks

    def _note_own(self, family: str, key) -> None:
        self._reveal[(family, key)] = 0  # own writes: always visible

    def _maybe_crash(self, family: str) -> bool:
        """Roll a write-crash decision: raises :class:`SimCrash`
        immediately for crash-BEFORE, returns True when the inner write
        should land first and THEN crash (crash-AFTER), False for no
        fault."""
        if not self.active or not self.cfg.write_crash:
            return False
        p, which = self._roll(f"crash.{family}")
        if p >= self.cfg.write_crash:
            return False
        self.stats["write_crash"] += 1
        if which < 0.5:
            raise SimCrash(f"injected crash before {family}")
        return True  # crash after the inner call

    async def _write(self, family: str, thunk, landed=None):
        """Run one inner write under the crash fault.  ``thunk`` builds
        the inner coroutine — created only AFTER the crash roll, so a
        crash-before leaves no never-awaited coroutine behind.
        ``landed(result)`` runs whenever the inner write took effect —
        INCLUDING before a crash-AFTER raise — so bookkeeping that
        mirrors durable state (own-write visibility, checkpoint
        generations) can never desynchronize from it: a replica always
        sees its own landed writes, crash or no crash."""
        after = self._maybe_crash(family)
        result = await thunk()
        if landed is not None:
            landed(result)
        if after:
            raise SimCrash(f"injected crash after {family}")
        return result

    # -------------------------------------------------------- local meta
    async def load_local_meta(self) -> bytes | None:
        return await self.inner.load_local_meta()

    async def store_local_meta(self, data: bytes) -> None:
        await self._write(
            "store_local_meta", lambda: self.inner.store_local_meta(data)
        )

    # -------------------------------------------------------- checkpoints
    async def load_local_checkpoint(self) -> bytes | None:
        cur = await self.inner.load_local_checkpoint()
        if (
            self.active
            and self.cfg.stale_checkpoint
            and self._ckpt_prev is not None
        ):
            p, _ = self._roll("stale_checkpoint")
            if p < self.cfg.stale_checkpoint:
                self.stats["stale_checkpoint"] += 1
                return self._ckpt_prev
        return cur

    async def store_local_checkpoint(self, data: bytes) -> None:
        prev = await self.inner.load_local_checkpoint()

        def landed(_res):
            if prev is not None:
                self._ckpt_prev = prev

        await self._write(
            "store_local_checkpoint",
            lambda: self.inner.store_local_checkpoint(data),
            landed=landed,
        )

    async def remove_local_checkpoint(self) -> None:
        await self.inner.remove_local_checkpoint()

    # ------------------------------------------------------ remote metas
    async def list_remote_meta_names(self) -> list[str]:
        return self._filter_listing("meta", await self.inner.list_remote_meta_names())

    async def load_remote_metas(self, names: list[str]) -> list[tuple[str, bytes]]:
        loaded = await self.inner.load_remote_metas(
            [n for n in names if self._visible("meta", n)]
        )
        # remote meta is the key/config register: tearing it yields
        # MissingKeyError storms that the schedule cannot heal mid-run,
        # so the torn-read class covers states and ops (the payload
        # families) and leaves the tiny meta blobs intact — the same
        # asymmetry a real sync tool shows (meta files are ~100 bytes).
        return loaded

    async def store_remote_meta(self, data: bytes) -> str:
        return await self._write(
            "store_remote_meta",
            lambda: self.inner.store_remote_meta(data),
            landed=lambda name: self._note_own("meta", name),
        )

    async def remove_remote_metas(self, names: list[str]) -> None:
        await self._write(
            "remove_remote_metas", lambda: self.inner.remove_remote_metas(names)
        )

    # ------------------------------------------------------------ states
    async def list_state_names(self) -> list[str]:
        return self._filter_listing("states", await self.inner.list_state_names())

    async def load_states(self, names: list[str]) -> list[tuple[str, bytes]]:
        loaded = await self.inner.load_states(
            [n for n in names if self._visible("states", n)]
        )
        return [(n, self._maybe_tear("states", raw)) for n, raw in loaded]

    async def store_state(self, data: bytes) -> str:
        return await self._write(
            "store_state",
            lambda: self.inner.store_state(data),
            landed=lambda name: self._note_own("states", name),
        )

    async def remove_states(self, names: list[str]) -> None:
        await self._write(
            "remove_states", lambda: self.inner.remove_states(names)
        )

    # --------------------------------------------------------------- ops
    async def list_op_actors(self) -> list[Actor]:
        return self._filter_listing("actors", await self.inner.list_op_actors())

    def _dup_first(self, actor: Actor, first: int) -> int:
        if not self.active or not self.cfg.dup_delivery or first <= 1:
            return first
        p, back = self._roll("dup")
        if p >= self.cfg.dup_delivery:
            return first
        self.stats["dup_delivery"] += 1
        return max(1, first - 1 - int(back * 2))

    def _censor_ops(
        self, files: list[tuple[Actor, int, bytes]], cut: set | None = None,
        family: str = "ops",
    ) -> list[tuple[Actor, int, bytes]]:
        """Apply visibility + torn reads to a dense op run.  A hidden
        file ends its actor's run (density: nothing past it may be
        delivered); ``cut`` carries ended actors across chunks.  The
        visibility roll is evaluated for EVERY file — even ones already
        behind a cut — so reveal clocks start at first delivery attempt
        and a run un-hides within ``delay_max_ticks`` instead of one
        file per tick (a cascade no real sync tool exhibits).  The
        delta family shares the censor (``family="deltas"``): hiding a
        link mid-log models a half-synced chain, which consumers must
        survive by falling back to the snapshot path."""
        out = []
        ended: set = cut if cut is not None else set()
        for actor, version, raw in files:
            visible = self._visible(family, (actor, version))
            if actor in ended:
                continue
            if not visible:
                ended.add(actor)
                continue
            out.append((actor, version, self._maybe_tear(family, raw)))
        return out

    async def load_ops(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, bytes]]:
        wanted = [
            (a, self._dup_first(a, first)) for a, first in actor_first_versions
        ]
        return self._censor_ops(await self.inner.load_ops(wanted))

    async def stat_ops(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, int]]:
        # observational probe: visibility applies (a hidden file is not
        # backlog yet), tearing/dup do not (sizes come from stat)
        out = []
        ended: set = set()
        for actor, version, nbytes in await self.inner.stat_ops(
            actor_first_versions
        ):
            visible = self._visible("ops", (actor, version))
            if actor in ended:
                continue
            if not visible:
                ended.add(actor)
                continue
            out.append((actor, version, nbytes))
        return out

    async def iter_op_chunks(
        self,
        actor_first_versions: list[tuple[Actor, int]],
        max_bytes: int = 64 << 20,
    ):
        cut: set = set()
        async for files in self.inner.iter_op_chunks(
            actor_first_versions, max_bytes
        ):
            censored = self._censor_ops(files, cut)
            if censored:
                yield censored

    async def store_ops(self, actor: Actor, version: int, data: bytes) -> None:
        await self._write(
            "store_ops",
            lambda: self.inner.store_ops(actor, version, data),
            landed=lambda _res: self._note_own("ops", (actor, version)),
        )

    async def remove_ops(self, actor_last_versions: list[tuple[Actor, int]]) -> None:
        await self._write(
            "remove_ops", lambda: self.inner.remove_ops(actor_last_versions)
        )

    # ------------------------------------------------------------- deltas
    # The delta family inherits the op family's whole failure envelope:
    # partial actor listings, delayed visibility per file, torn reads,
    # crash-before/after on publishes and GC.  Deltas are an OPTIMIZATION
    # layer — every injected fault here must at worst force the consumer
    # back onto the snapshot path, never diverge it (docs/delta.md).
    @property
    def has_deltas(self) -> bool:
        return getattr(self.inner, "has_deltas", False)

    async def list_delta_actors(self) -> list[Actor]:
        return self._filter_listing(
            "dactors", await self.inner.list_delta_actors()
        )

    async def load_deltas(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, bytes]]:
        return self._censor_ops(
            await self.inner.load_deltas(actor_first_versions),
            family="deltas",
        )

    async def store_delta(self, actor: Actor, version: int, data: bytes) -> None:
        await self._write(
            "store_delta",
            lambda: self.inner.store_delta(actor, version, data),
            landed=lambda _res: self._note_own("deltas", (actor, version)),
        )

    async def remove_deltas(
        self, actor_last_versions: list[tuple[Actor, int]]
    ) -> None:
        await self._write(
            "remove_deltas",
            lambda: self.inner.remove_deltas(actor_last_versions),
        )

    # --------------------------------------------------------- lifecycle
    async def init(self, core) -> None:
        await self.inner.init(core)

    async def set_remote_meta(self, meta) -> None:
        await self.inner.set_remote_meta(meta)

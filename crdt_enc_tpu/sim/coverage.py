"""Fault-class × vocabulary co-fire accounting (ROADMAP item 5a's bias
substrate — reporting only, no generator bias yet).

A schedule exercises a *vocabulary* (the base step set, plus the deltas
/ daemon / strong-reads extensions its flags enable) and its storage
wrappers *fire* fault classes.  A bug that needs, say, a torn read
during a delta-chain walk can only be found by runs where that pair
co-occurs — so the honest first step toward coverage-guided generation
is the map of what has actually co-fired, accumulated across an explore
sweep and rendered without any editorializing.  A cell counts the runs
in which vocabulary V was enabled AND fault class F fired at least once
(``SimResult.fault_stats``, the injected-fault tallies); a zero cell is
a hole no nightly has ever tested.

``python -m crdt_enc_tpu.tools.sim explore --coverage-out f.json`` dumps
the matrix; ``python -m crdt_enc_tpu.tools.obs_report simcov f.json``
renders it.  The matrix deliberately lives OUTSIDE the schedule
generator: recording must never perturb the RNG streams (the
seed-replay and fixture contracts), so it only ever reads results.
"""

from __future__ import annotations

import json

from .faults import FaultConfig

# vocabulary columns: the base vocabulary is always on; the extensions
# mirror the generate() flags exactly (schedule.py's weight tables)
VOCABULARIES = ("base", "deltas", "daemon", "strong_reads")

COVERAGE_VERSION = 1


class CoFireMatrix:
    """Accumulates (fault class × vocabulary) co-fire counts per run."""

    def __init__(self):
        self.runs = 0
        self.cells = {
            (f, v): 0 for f in FaultConfig.CLASSES for v in VOCABULARIES
        }

    def record(self, schedule, result) -> None:
        """Fold one finished run in: every fault class that FIRED
        (tally > 0, not merely enabled) co-fires with every vocabulary
        the schedule had enabled."""
        self.runs += 1
        vocabs = ["base"] + [
            v
            for v in ("deltas", "daemon", "strong_reads")
            if getattr(schedule, v, False)
        ]
        for f in FaultConfig.CLASSES:
            if result.fault_stats.get(f, 0) > 0:
                for v in vocabs:
                    self.cells[(f, v)] += 1

    def holes(self) -> list[tuple[str, str]]:
        """The never-co-fired pairs — what the map is FOR."""
        return [fv for fv in sorted(self.cells) if self.cells[fv] == 0]

    # ------------------------------------------------------------- wire
    def to_obj(self) -> dict:
        return {
            "version": COVERAGE_VERSION,
            "runs": self.runs,
            "faults": list(FaultConfig.CLASSES),
            "vocabularies": list(VOCABULARIES),
            "cells": {f"{f}:{v}": n for (f, v), n in self.cells.items()},
        }

    @classmethod
    def from_obj(cls, obj: dict) -> "CoFireMatrix":
        if obj.get("version") != COVERAGE_VERSION:
            raise ValueError(
                f"unsupported coverage version {obj.get('version')!r}"
            )
        m = cls()
        m.runs = int(obj.get("runs", 0))
        for key, n in obj.get("cells", {}).items():
            f, _, v = key.partition(":")
            if (f, v) in m.cells:
                m.cells[(f, v)] = int(n)
        return m

    def dump(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_obj(), fh, indent=1)
            fh.write("\n")

    @classmethod
    def load(cls, path: str) -> "CoFireMatrix":
        with open(path) as fh:
            return cls.from_obj(json.load(fh))

    # ----------------------------------------------------------- render
    def render(self) -> str:
        """Plain table, faults down, vocabularies across; '.' marks a
        hole (never co-fired), so holes jump out of a wall of counts."""
        w = max(len(f) for f in FaultConfig.CLASSES)
        cols = [max(len(v), 6) for v in VOCABULARIES]
        lines = [
            f"{'':<{w}}  "
            + "  ".join(f"{v:>{c}}" for v, c in zip(VOCABULARIES, cols))
        ]
        for f in FaultConfig.CLASSES:
            cells = []
            for v, c in zip(VOCABULARIES, cols):
                n = self.cells[(f, v)]
                cells.append(f"{n if n else '.':>{c}}")
            lines.append(f"{f:<{w}}  " + "  ".join(cells))
        holes = self.holes()
        lines.append(
            f"{self.runs} run(s); "
            + (
                f"{len(holes)} never-co-fired pair(s): "
                + ", ".join(f"{f}×{v}" for f, v in holes)
                if holes
                else "every fault×vocabulary pair has co-fired"
            )
        )
        return "\n".join(lines)

"""Adversarial convergence simulator (docs/simulation.md).

Machine-checks the PROTOCOL invariants — convergence, oracle equality,
warm≡cold reopen, replication monotonicity, fsck cleanliness — under
hostile, deterministic, seeded schedules of replica activity over a
fault-injecting storage layer, the way the analysis engine (PR 5)
machine-checks code invariants.  Failures shrink to minimal replayable
fixtures committed under ``tests/data/sim/``.

Front doors: :func:`generate` a schedule, :func:`run_schedule` it,
:func:`shrink` a failure; ``python -m crdt_enc_tpu.tools.sim`` is the
CLI over the same calls.
"""

from .check import InvariantViolation, Violation
from .coverage import CoFireMatrix
from .faults import FaultConfig, FaultyStorage, SimCrash
from .population import (
    PopulationReport,
    PopulationSubstrate,
    run_budget,
    run_population,
    verify_serial_equality,
)
from .runner import DeterministicCryptor, SimResult, SimRunner, run_schedule
from .schedule import STEP_KINDS, Schedule, Step, generate
from .shrink import shrink, to_fixture

__all__ = [
    "CoFireMatrix",
    "FaultConfig",
    "FaultyStorage",
    "InvariantViolation",
    "DeterministicCryptor",
    "PopulationReport",
    "PopulationSubstrate",
    "STEP_KINDS",
    "Schedule",
    "SimCrash",
    "SimResult",
    "SimRunner",
    "Step",
    "Violation",
    "generate",
    "run_budget",
    "run_population",
    "run_schedule",
    "shrink",
    "to_fixture",
    "verify_serial_equality",
]

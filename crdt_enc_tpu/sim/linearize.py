"""The linearizability checker for strong reads (docs/strong_reads.md).

The strong-read tier's contract is checked, not asserted: every
``read_strong`` the simulator issues is validated against an **oracle
op log** — the plaintext of every op file that ever became durable,
recorded at the storage seam the moment it landed (so compaction GC
cannot erase the evidence).  Four properties, each a
machine-checkable piece of the documented guarantee:

1. **consistent-cut exactness** — the returned value must be
   byte-identical to a fresh host fold of *exactly* the op prefix named
   by the read's stable cursor (per-actor dense, version order).  This
   is the linearization witness: the read IS the fold of one
   causally-closed cut of the history, not an approximation of it —
   "the oracle fold at some point" where points are the consistent cuts
   of the partial order, the CRDT generalization of an instant.
2. **durability** — every op in the cut landed before the read
   returned (a cut naming an op the oracle never saw is a phantom).
3. **session monotonicity** — within a replica incarnation, successive
   strong reads return pointwise-monotone cursors (reads never travel
   back in time; warm reopens keep the frontier via the checkpointed
   prefix, cold reopens start a new session — both per the docs).
4. **read-your-writes** — a strong read issued after a successful
   ``await_stable(target)`` must cover ``target`` (the freshness-wait
   protocol's whole point).

The oracle fold and all comparisons are pure and synchronous; the
runner gathers inputs (and decrypts tapped blobs with the writer's own
key material at the moment of the write, so key rotation mid-history
changes nothing).  A failed property becomes an ordinary
``Violation("linearizability", ...)`` — ddmin-shrinkable into a
committed fixture like any other simulator finding.
"""

from __future__ import annotations

from ..models import ORSet, canonical_bytes
from ..models.orset import op_from_obj
from ..models.vclock import VClock


def oracle_fold(oplog: dict, cursor: VClock):
    """Fold exactly the cut named by ``cursor`` from the plaintext op
    log ``{(actor, version): [op_obj, ...]}``: per-actor dense version
    order (the causal-delivery contract; cross-actor order is free by
    CmRDT commutativity).  Returns ``(state, missing)`` — ``missing``
    non-empty means the cut names ops that never landed."""
    state = ORSet()
    missing = []
    for actor in sorted(cursor.counters):
        for version in range(1, cursor.get(actor) + 1):
            payload = oplog.get((actor, version))
            if payload is None:
                missing.append((actor.hex(), version))
                continue
            for obj in payload:
                state.apply(op_from_obj(obj))
    return state, missing


def check_strong_read(
    oplog: dict,
    result,
    prev_cursor: VClock | None,
    *,
    ryw_target: VClock | None = None,
) -> str | None:
    """Validate one strong read against the oracle (module docs).
    ``result`` is the ``ReadResult`` a ``Core.read(linearizable=True)``
    returned; ``prev_cursor`` the same incarnation's previous strong
    cursor (None for the first); ``ryw_target`` the clock a preceding
    successful ``await_stable`` promised coverage of.  Returns a defect
    description, or None when every property holds."""
    cursor = result.cursor
    # 3: session monotonicity
    if prev_cursor is not None:
        regressed = sorted(
            a.hex()
            for a, c in prev_cursor.counters.items()
            if cursor.get(a) < c
        )
        if regressed:
            return (
                "strong-read cursor regressed within an incarnation "
                f"for actors {regressed}"
            )
    # 4: read-your-writes after a successful freshness wait
    if ryw_target is not None:
        uncovered = sorted(
            a.hex()
            for a, c in ryw_target.counters.items()
            if cursor.get(a) < c
        )
        if uncovered:
            return (
                "await_stable succeeded but the following strong read "
                f"does not cover the awaited clock for {uncovered}"
            )
    # 1 + 2: exactness against the oracle fold of the cut (+ phantoms)
    oracle, missing = oracle_fold(oplog, cursor)
    if missing:
        return (
            "strong-read cursor names ops that never became durable: "
            f"{missing[:4]}"
        )
    got = canonical_bytes(ORSet.from_obj(result.obj))
    want = canonical_bytes(oracle)
    if got != want:
        return (
            "strong read diverges from the oracle fold of its own cut "
            f"(cursor {sorted((a.hex()[:8], c) for a, c in cursor.counters.items())})"
        )
    return None

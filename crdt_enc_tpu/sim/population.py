"""Population runner: P independent schedules through ONE shared substrate.

ROADMAP item 5's scale move: schedules never interact, so they batch the
way tenants batch (the PR-14 mega-fold law, applied one level up).  P
:class:`~crdt_enc_tpu.sim.runner.SimRunner` lanes run concurrently in one
event loop, all folding through a single process-wide
:class:`PopulationSubstrate` — one ``TpuAccelerator`` (vocab-bucketed, so
every lane's folds land in the same power-of-two compile classes and P
schedules warm one set of jitted programs) and one
:class:`~crdt_enc_tpu.serve.FoldService` whose ``run_cycle_shared``
queues overlapping lane cycles.

**The determinism law** (docs/simulation.md "Population runs"): every
RNG stream stays strictly per-(schedule, replica, family, counter) —
fault rolls are pure functions of those four, the uuid stream is
context-local to the lane's task tree, cryptors are seeded per
(schedule, replica), storage is a per-lane ``MemoryRemote``, and the
daemon clock counts lane-local cycles.  Cooperative scheduling preserves
each lane's own call order, so cross-lane interleaving cannot move a
single draw: **each schedule's fingerprint is bit-identical to its
serial run**.  That equality is the correctness contract, not an
aspiration — :func:`verify_serial_equality` checks it in-tree, the bench
refuses to record without it, and the CI smoke asserts it on every push.

The fs backend keeps thread-pool timing and cannot honor the contract,
so population runs are memory-backend only (the same fidelity line
drawn in sim/runner.py's module docs).

Front doors: :func:`run_population` (a fixed schedule list),
:func:`run_budget` (wall-clock budgeted, lanes refilled with the next
seed — ``tools.sim explore --budget-s``), :func:`verify_serial_equality`
(the contract checker).
"""

from __future__ import annotations

import asyncio
import itertools
import time
from dataclasses import dataclass, field

from ..utils import trace
from .runner import SimResult, SimRunner
from .schedule import Schedule


class PopulationSubstrate:
    """The shared serving substrate every lane folds through.

    One accelerator: its plane cache is keyed by state identity (weakref
    validated), so lanes never alias; ``bucket_vocab=True`` lifts every
    lane's fold/merge shapes to power-of-two classes, which is what
    makes steady-state XLA compiles CONSTANT as P grows — the compile
    classes are fleet properties, not schedule properties.  One
    FoldService: ``run_cycle_shared`` serializes overlapping owners, and
    the identity-keyed warm tier gives each lane the same byte-exact
    reuse a private service would.  Nothing in here is schedule-keyed:
    RNG streams, storage, fault counters, and cryptors all stay in the
    lanes (module docs: the determinism law)."""

    def __init__(self, *, mesh=None, bucket_vocab: bool = True):
        from ..parallel import TpuAccelerator
        from ..serve import FoldService, ServeConfig

        self.mesh = mesh
        self.accel = TpuAccelerator(
            min_device_batch=1, bucket_vocab=bucket_vocab
        )
        self.service = FoldService(
            [], ServeConfig(seal_empty=True), mesh=mesh
        )

    def close(self) -> None:
        self.service.close()


@dataclass
class PopulationReport:
    """One population run's outcome: ``schedules[i]`` produced
    ``results[i]`` (index-aligned; a budget run orders by seed)."""

    schedules: list[Schedule] = field(default_factory=list)
    results: list[SimResult] = field(default_factory=list)
    wall_s: float = 0.0
    refills: int = 0

    @property
    def violations(self) -> list[tuple[Schedule, SimResult]]:
        return [
            (s, r)
            for s, r in zip(self.schedules, self.results)
            if not r.ok
        ]

    @property
    def steps_run(self) -> int:
        return sum(r.steps_run for r in self.results)


def _require_memory(schedule: Schedule) -> None:
    if schedule.backend != "memory":
        raise ValueError(
            "population runs are memory-backend only: the fs backend "
            "keeps thread-pool timing and cannot honor the serial-"
            "equality contract (sim/population.py module docs)"
        )


async def run_population_async(
    schedules, *, population: int | None = None, substrate=None
) -> PopulationReport:
    """Run every schedule, at most ``population`` lanes concurrently
    (default: all).  A lane that finishes pulls the next schedule —
    ``sim_lane_refills`` counts those pulls — so the population stays
    full until the work list drains.  Violations land on the results,
    never raise (the CLI/shrink calling convention, unchanged)."""
    schedules = list(schedules)
    for s in schedules:
        _require_memory(s)
    n = len(schedules)
    lanes = max(1, min(population or n, n)) if n else 0
    own = substrate is None
    if own:
        substrate = PopulationSubstrate()
    results: list[SimResult | None] = [None] * n
    t0 = time.perf_counter()
    try:
        with trace.span("sim.population", meta=n):
            trace.gauge("sim_population", lanes)
            # a plain iterator is a safe work queue here: the loop is
            # single-threaded and next() runs between awaits, atomically
            work = iter(range(n))

            async def lane():
                first = True
                for i in work:
                    if not first:
                        trace.add("sim_lane_refills", 1)
                    first = False
                    runner = SimRunner(schedules[i], substrate=substrate)
                    results[i] = await runner.run_async()

            await asyncio.gather(*(lane() for _ in range(lanes)))
    finally:
        if own:
            substrate.close()
    return PopulationReport(
        schedules=schedules,
        results=results,
        wall_s=time.perf_counter() - t0,
        refills=max(0, n - lanes),
    )


def run_population(
    schedules, *, population: int | None = None, substrate=None
) -> PopulationReport:
    """Sync front door over :func:`run_population_async`."""
    return asyncio.run(
        run_population_async(
            schedules, population=population, substrate=substrate
        )
    )


async def run_budget_async(
    make_schedule,
    *,
    budget_s: float,
    population: int,
    start_seed: int = 0,
    substrate=None,
) -> PopulationReport:
    """Wall-clock budgeted exploration: keep ``population`` lanes full —
    a finished lane immediately refills with ``make_schedule(next
    seed)`` — until the budget expires.  The budget gates STARTS, never
    kills a lane mid-run, so the overshoot is bounded by one schedule's
    duration per lane (the ±1-cycle contract the CLI test pins).  The
    wall clock is harness control flow only; nothing inside a lane ever
    reads it, so every schedule that runs is still a pure function of
    its seed."""
    t0 = time.perf_counter()
    own = substrate is None
    if own:
        substrate = PopulationSubstrate()
    lanes = max(1, int(population))
    seeds = itertools.count(start_seed)
    done: list[tuple[Schedule, SimResult]] = []
    refills = 0
    try:
        with trace.span("sim.population", meta=lanes):
            trace.gauge("sim_population", lanes)

            async def lane():
                nonlocal refills
                first = True
                while time.perf_counter() - t0 < budget_s:
                    if not first:
                        trace.add("sim_lane_refills", 1)
                        refills += 1
                    first = False
                    sched = make_schedule(next(seeds))
                    _require_memory(sched)
                    runner = SimRunner(sched, substrate=substrate)
                    done.append((sched, await runner.run_async()))

            await asyncio.gather(*(lane() for _ in range(lanes)))
    finally:
        if own:
            substrate.close()
    done.sort(key=lambda sr: sr[0].seed)
    return PopulationReport(
        schedules=[s for s, _ in done],
        results=[r for _, r in done],
        wall_s=time.perf_counter() - t0,
        refills=refills,
    )


def run_budget(
    make_schedule, *, budget_s: float, population: int,
    start_seed: int = 0, substrate=None,
) -> PopulationReport:
    """Sync front door over :func:`run_budget_async`."""
    return asyncio.run(
        run_budget_async(
            make_schedule, budget_s=budget_s, population=population,
            start_seed=start_seed, substrate=substrate,
        )
    )


def verify_serial_equality(report: PopulationReport) -> list[str]:
    """THE contract check: re-run each schedule serially — private
    substrate, the historical single-lane path — and compare
    fingerprints and fault tallies.  Returns human-readable mismatch
    lines (empty = the law held).  Deliberately the dumbest possible
    implementation: any cleverness shared with the population path
    could hide the very divergence it must catch."""
    problems = []
    for sched, res in zip(report.schedules, report.results):
        serial = SimRunner(sched).run()
        if serial.fingerprint != res.fingerprint:
            problems.append(
                f"seed {sched.seed}: population fingerprint "
                f"{res.fingerprint[:16]} != serial {serial.fingerprint[:16]}"
            )
        elif serial.fault_stats != res.fault_stats:
            problems.append(
                f"seed {sched.seed}: fault tallies diverge: "
                f"population {sorted(res.fault_stats.items())} != "
                f"serial {sorted(serial.fault_stats.items())}"
            )
    return problems

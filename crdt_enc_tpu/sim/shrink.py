"""Delta-debugging shrink: a fleet-scale failure → a minimal fixture.

A violation found in a 500-step, 8-replica schedule is unreadable as a
bug report.  :func:`shrink` reduces it to (nearly) the smallest
schedule that still reproduces the SAME invariant violation:

1. **steps** — classic ddmin over the step list (remove chunks at
   halving granularity; keep any removal that still reproduces);
2. **faults** — try disabling each fault class entirely; keep it off
   when the failure survives (the surviving classes name the trigger);
3. **replicas** — try dropping the highest-indexed replicas whose steps
   all vanished during ddmin (renumbering is not attempted — a gap-free
   fleet keeps fixtures readable).

Reproduction compares ``Violation.invariant`` — a shrunk schedule that
fails a *different* invariant is a different bug and is not accepted as
a reduction (it would silently swap the regression being pinned).

Every candidate run is a full deterministic simulation, so the budget
matters: ``max_runs`` bounds the search and the best-so-far schedule is
returned when it runs out.  Shrunk schedules serialize into replayable
JSON fixtures (``tests/data/sim/``) via :func:`to_fixture` — the
workflow docs/simulation.md walks through.
"""

from __future__ import annotations

from .check import Violation
from .schedule import Schedule, Step
from ..utils import trace


def _reproduces(
    candidate: Schedule, want: str, run_fn, budget: list
) -> Violation | None:
    """Run one candidate (respecting the run budget); returns its
    violation when it reproduces the wanted invariant."""
    if budget[0] <= 0:
        return None
    budget[0] -= 1
    result = run_fn(candidate)
    v = result.violation
    if v is not None and v.invariant == want:
        return v
    return None


def _ddmin_steps(
    schedule: Schedule, want: str, run_fn, budget: list
) -> Schedule:
    steps = list(schedule.steps)
    n = 2
    while len(steps) >= 2:
        chunk = max(1, len(steps) // n)
        reduced = False
        start = 0
        while start < len(steps):
            candidate_steps = steps[:start] + steps[start + chunk:]
            if not candidate_steps:
                start += chunk
                continue
            cand = schedule.with_steps(candidate_steps)
            if _reproduces(cand, want, run_fn, budget) is not None:
                steps = candidate_steps
                n = max(n - 1, 2)
                reduced = True
                # restart the scan: earlier chunks may now be removable
                start = 0
            else:
                start += chunk
            if budget[0] <= 0:
                return schedule.with_steps(steps)
        if not reduced:
            if chunk <= 1:
                break
            n = min(n * 2, len(steps))
    return schedule.with_steps(steps)


def _shrink_faults(
    schedule: Schedule, want: str, run_fn, budget: list
) -> Schedule:
    best = schedule
    for name in schedule.faults.CLASSES:
        if not getattr(best.faults, name):
            continue
        cand = best.with_faults(best.faults.without(name))
        if _reproduces(cand, want, run_fn, budget) is not None:
            best = cand
        if budget[0] <= 0:
            break
    return best


def _shrink_replicas(
    schedule: Schedule, want: str, run_fn, budget: list
) -> Schedule:
    best = schedule
    while best.n_replicas > 2:
        hi = best.n_replicas - 1
        if any(
            s.replica == hi or (s.kind in ("compact2", "service") and s.arg == hi)
            for s in best.steps
        ):
            break
        cand = Schedule(
            seed=best.seed,
            n_replicas=hi,
            steps=list(best.steps),
            faults=best.faults,
            members=best.members,
            backend=best.backend,
            note=best.note,
        )
        if _reproduces(cand, want, run_fn, budget) is None:
            break
        best = cand
        if budget[0] <= 0:
            break
    return best


def shrink(
    schedule: Schedule,
    violation: Violation,
    run_fn,
    *,
    max_runs: int = 200,
) -> tuple[Schedule, Violation]:
    """Reduce ``schedule`` (which produced ``violation``) to a minimal
    reproducer of the same invariant.  ``run_fn(schedule) -> SimResult``
    executes candidates (the caller chooses tmpdirs etc.).  Returns the
    shrunk schedule and the violation it produces."""
    want = violation.invariant
    budget = [max_runs]
    with trace.span("sim.shrink"):
        best = _ddmin_steps(schedule, want, run_fn, budget)
        best = _shrink_faults(best, want, run_fn, budget)
        best = _ddmin_steps(best, want, run_fn, budget)
        best = _shrink_replicas(best, want, run_fn, budget)
        final = _reproduces(best, want, run_fn, [1])
    if final is None:
        # the budget ran dry mid-move; fall back to the original, which
        # is known-good as a reproducer
        return schedule, violation
    return best, final


def to_fixture(schedule: Schedule, violation: Violation, note: str = "") -> dict:
    """The committed-fixture JSON shape: the shrunk schedule plus what
    it USED to violate.  Replay asserts the schedule now passes — every
    fixture is a fixed bug's permanent regression test."""
    obj = schedule.to_obj()
    obj["violation"] = violation.to_obj()
    if note:
        obj["note"] = note
    return obj

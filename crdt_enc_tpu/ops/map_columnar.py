"""Columnar bulk fold for the causal reset-remove map (CrdtMap<orset>).

The map's apply semantics (models/crdtmap.py) decompose into four row
families — key births, key-remove horizons, child adds, child-remove
horizons — folded as masked scatter-maxes over two plane sets:

* key planes ``(K, R)``: births, key horizons, child clocks;
* pair planes ``(P, R)`` over the *touched* (key, member) pairs (a
  compact vocabulary, never the dense K·M product): child entries and
  child horizons, coupled to the key planes by one gather
  (``eff_rm = max(child_rm, key_horizon[key_of_pair])``).

Order-independence holds for the same reasons as the ORSet kernel
(per-actor dot monotonicity under the core's delivery contract, removes
derived from observed reads), extended by the map's shared-dot
discipline: one dot authorizes both the key birth and the child
mutation, which the native decoder verifies row by row (declining any
payload whose child-add dot differs from its map dot).  The suppression
and reset rules all become "≤ horizon dies", evaluated against the
batch+state horizon maxima — the same final state every sequential
interleaving reaches.  Parity with the host fold is fuzzed in
tests/test_map_columnar.py.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .. import native
from ..models import ORSet, VClock
from ..models.crdtmap import CrdtMap
from .columnar import Vocab
from .native_decode import intern_spans

_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)


def decode_map_payload_batch(payloads: list, actors_sorted: list):
    """Native decode of CrdtMap<orset> op payloads → the four row
    families, with key/member spans interned.  Returns None to request
    the per-op fallback."""
    lib = native.load()
    if not payloads:
        empty = {
            "koff": np.zeros(0, np.uint64), "klen": np.zeros(0, np.uint64),
            "actor": np.zeros(0, np.int32), "ctr": np.zeros(0, np.int32),
            "moff": np.zeros(0, np.uint64), "mlen": np.zeros(0, np.uint64),
            "key": np.zeros(0, np.int32), "member": np.zeros(0, np.int32),
            "mactor": np.zeros(0, np.int32), "mctr": np.zeros(0, np.int32),
            "group": np.zeros(0, np.int32),
        }
        return dict(empty), dict(empty), dict(empty), dict(empty), [], []
    big = b"".join(payloads)
    buf = np.frombuffer(big, np.uint8)
    bp = buf.ctypes.data_as(native.u8p)
    actors_flat = b"".join(actors_sorted)
    ap, _a = native.in_ptr(actors_flat)

    lens = np.array([len(p) for p in payloads], np.uint64)
    bases = np.zeros(len(payloads), np.uint64)
    np.cumsum(lens[:-1], out=bases[1:])

    counts = np.zeros(4, np.int64)
    total = lib.map_count_rows_batch(
        bp, bases.ctypes.data_as(native.u64p),
        lens.ctypes.data_as(native.u64p), len(payloads),
        counts.ctypes.data_as(_i64p),
    )
    if total < 0:
        return None
    nb, na, nr, nk = (int(c) for c in counts)

    def alloc(n, with_member):
        d = {
            "koff": np.zeros(n, np.uint64), "klen": np.zeros(n, np.uint64),
            "actor": np.zeros(n, np.int32), "ctr": np.zeros(n, np.int32),
        }
        if with_member:
            d["moff"] = np.zeros(n, np.uint64)
            d["mlen"] = np.zeros(n, np.uint64)
        return d

    B = alloc(nb, False)
    A = alloc(na, True)
    Rm = alloc(nr, True)
    Rm["mactor"] = np.zeros(nr, np.int32)  # the Up's MAP dot (replay gate)
    Rm["mctr"] = np.zeros(nr, np.int32)
    K = alloc(nk, False)
    K["group"] = np.zeros(nk, np.int32)  # originating Rm op: fire-or-defer
    # is decided per WHOLE remove (the crdts-crate deferral discipline)
    u64 = native.u64p
    got = lib.map_decode_batch(
        bp, bases.ctypes.data_as(u64), lens.ctypes.data_as(u64),
        len(payloads), ap, len(actors_sorted),
        B["koff"].ctypes.data_as(u64), B["klen"].ctypes.data_as(u64),
        B["actor"].ctypes.data_as(_i32p), B["ctr"].ctypes.data_as(_i32p),
        A["koff"].ctypes.data_as(u64), A["klen"].ctypes.data_as(u64),
        A["moff"].ctypes.data_as(u64), A["mlen"].ctypes.data_as(u64),
        A["actor"].ctypes.data_as(_i32p), A["ctr"].ctypes.data_as(_i32p),
        Rm["koff"].ctypes.data_as(u64), Rm["klen"].ctypes.data_as(u64),
        Rm["moff"].ctypes.data_as(u64), Rm["mlen"].ctypes.data_as(u64),
        Rm["actor"].ctypes.data_as(_i32p), Rm["ctr"].ctypes.data_as(_i32p),
        Rm["mactor"].ctypes.data_as(_i32p), Rm["mctr"].ctypes.data_as(_i32p),
        K["koff"].ctypes.data_as(u64), K["klen"].ctypes.data_as(u64),
        K["actor"].ctypes.data_as(_i32p), K["ctr"].ctypes.data_as(_i32p),
        K["group"].ctypes.data_as(_i32p),
    )
    if got != total:
        return None

    # intern every key span across the four families in one pass, then
    # member spans across the two child families
    all_koff = np.concatenate([B["koff"], A["koff"], Rm["koff"], K["koff"]])
    all_klen = np.concatenate([B["klen"], A["klen"], Rm["klen"], K["klen"]])
    kidx_all, key_objs = intern_spans(buf, all_koff, all_klen)
    B["key"] = kidx_all[:nb]
    A["key"] = kidx_all[nb : nb + na]
    Rm["key"] = kidx_all[nb + na : nb + na + nr]
    K["key"] = kidx_all[nb + na + nr :]

    all_moff = np.concatenate([A["moff"], Rm["moff"]])
    all_mlen = np.concatenate([A["mlen"], Rm["mlen"]])
    midx_all, member_objs = intern_spans(buf, all_moff, all_mlen)
    A["member"] = midx_all[:na]
    Rm["member"] = midx_all[na:]
    return B, A, Rm, K, key_objs, member_objs



def _host_scatter_phase(
    clock0, births0, cclk0, cadd0, crm0, key_of_pair,
    B, A, Rm, K, b_pair_a, b_pair_r, NK, R, n_groups,
):
    """The numpy scatter phase — the semantics reference the device twin
    (ops/map_device.py) is fuzzed against."""

    def smax(target, rows_k, rows_a, rows_c, gate=None):
        if len(rows_k) == 0:
            return
        sel = slice(None)
        if gate is not None:
            sel = rows_c > clock0[rows_a]
        np.maximum.at(target, (rows_k[sel], rows_a[sel]), rows_c[sel])

    birth_new = np.zeros((NK, R), np.int64)
    # every Up advances the clock
    smax(birth_new, np.asarray(B["key"], np.int64), B["actor"], B["ctr"])
    clock = np.maximum(clock0, birth_new.max(axis=0, initial=0))

    # fire-or-defer per WHOLE remove: a remove applies only when every
    # dot its context cites has arrived (the final clock covers it);
    # otherwise the whole (ctx, keys) op defers verbatim.  End-of-batch
    # firing is sequential-equivalent: once the clock covers the ctx, no
    # dot ≤ ctx can re-enter (the replay gate holds it out).
    group_ok = np.ones(max(n_groups, 1), bool)
    if len(K["group"]):
        beyond = K["ctr"] > clock[K["actor"]]
        np.minimum.at(group_ok, K["group"], ~beyond)
    applicable = group_ok[K["group"]] if len(K["group"]) else np.zeros(0, bool)

    keyhz = np.zeros((NK, R), np.int64)
    if applicable.any():
        np.maximum.at(
            keyhz,
            (np.asarray(K["key"], np.int64)[applicable],
             K["actor"][applicable]),
            K["ctr"][applicable],
        )

    births = births0.copy()
    smax(births, np.asarray(B["key"], np.int64), B["actor"], B["ctr"], gate=True)
    births = np.where(births > keyhz, births, 0)

    # child clocks advance only on child ADDS (ORSet removes never touch
    # the clock; a child-rm Up advances the MAP clock alone); fired
    # removes reset them
    cclk = cclk0.copy()
    smax(cclk, np.asarray(A["key"], np.int64), A["actor"], A["ctr"], gate=True)
    cclk = np.where(cclk > keyhz, cclk, 0)

    cadd = cadd0.copy()
    smax(cadd, b_pair_a, A["actor"], A["ctr"], gate=True)
    # child removes apply with their Up (replay-gated on the map dot)
    crm = crm0.copy()
    if len(b_pair_r):
        live_up = Rm["mctr"] > clock0[Rm["mactor"]]
        np.maximum.at(
            crm,
            (b_pair_r[live_up], Rm["actor"][live_up]),
            Rm["ctr"][live_up],
        )

    eff_rm = np.maximum(crm, keyhz[key_of_pair])
    cadd = np.where(cadd > eff_rm, cadd, 0)
    # child horizons: reset by fired key removes, retired by the MAP
    # clock (which subsumes the child clock — see
    # CrdtMap._retire_child_horizons)
    crm = np.where(crm > keyhz[key_of_pair], crm, 0)
    crm = np.where(crm > clock[None, :], crm, 0)
    return clock, births, cclk, cadd, crm, group_ok


def crdtmap_fold_host(
    state: CrdtMap, B, A, Rm, K, keys: Vocab, members: Vocab, replicas: Vocab,
    fold_impl: str = "host",
    mesh=None,
) -> CrdtMap:
    """Vectorized fold of the decoded row families into ``state``
    (CrdtMap<orset>), equal to applying the batch per-op in any
    per-actor-order-preserving interleaving.

    ``fold_impl="device"`` routes the scatter phase (the four
    scatter-max families + normalization) through the jitted kernel in
    ops/map_device.py — same planes, same values (fuzzed equal in
    tests/test_map_columnar.py); state↔planes conversion stays host."""
    R = len(replicas)
    aidx = replicas.index

    # ---- state → planes --------------------------------------------------
    for k in state.births:
        keys.intern(k)
    for k in state.vals:  # residue-only keys (dead key, live horizons)
        keys.intern(k)
    NK = len(keys)
    clock0 = np.zeros(max(R, 1), np.int64)
    for a, c in state.clock.counters.items():
        clock0[aidx[a]] = c
    births0 = np.zeros((NK, R), np.int64)
    cclk0 = np.zeros((NK, R), np.int64)
    for k, birth in state.births.items():
        ki = keys.index[k]
        for a, c in birth.items():
            births0[ki, aidx[a]] = c

    # compact (key, member) pair ids — batch + state.  Pure arithmetic
    # (key * NM + member) densified with one np.unique, so the batch rows
    # map to pair rows without per-row Python.
    for k, child in state.vals.items():
        keys.intern(k)
        for m in child.entries:
            members.intern(m)
        for m in child.deferred:
            members.intern(m)
    NM = len(members)
    NMx = max(NM, 1)
    state_pair_ids = []
    for k, child in state.vals.items():
        ki = keys.index[k]
        for a, c in child.clock.counters.items():
            cclk0[ki, aidx[a]] = c
        for m in child.entries:
            state_pair_ids.append(ki * NMx + members.index[m])
        for m in child.deferred:
            state_pair_ids.append(ki * NMx + members.index[m])
    a_ids = (
        np.asarray(A["key"], np.int64) * NMx + A["member"]
        if len(A["key"]) else np.zeros(0, np.int64)
    )
    r_ids = (
        np.asarray(Rm["key"], np.int64) * NMx + Rm["member"]
        if len(Rm["key"]) else np.zeros(0, np.int64)
    )
    uniq_pairs = np.unique(np.concatenate([
        np.asarray(state_pair_ids, np.int64), a_ids, r_ids
    ]))
    b_pair_a = np.searchsorted(uniq_pairs, a_ids)
    b_pair_r = np.searchsorted(uniq_pairs, r_ids)
    NP = len(uniq_pairs)
    cadd0 = np.zeros((NP, R), np.int64)
    crm0 = np.zeros((NP, R), np.int64)
    for k, child in state.vals.items():
        ki = keys.index[k]
        for m, entry in child.entries.items():
            p = int(np.searchsorted(uniq_pairs, ki * NMx + members.index[m]))
            for a, c in entry.items():
                cadd0[p, aidx[a]] = c
        for m, dfr in child.deferred.items():
            p = int(np.searchsorted(uniq_pairs, ki * NMx + members.index[m]))
            for a, c in dfr.items():
                crm0[p, aidx[a]] = c
    key_of_pair = uniq_pairs // NMx

    # ---- batch scatter-maxes --------------------------------------------
    n_groups = int(K["group"].max()) + 1 if len(K["group"]) else 0
    if fold_impl == "device":
        from .map_device import crdtmap_scatter_device

        clock, births, cclk, cadd, crm, group_ok = crdtmap_scatter_device(
            clock0, births0, cclk0, cadd0, crm0, key_of_pair,
            B, {**A, "pair": b_pair_a}, {**Rm, "pair": b_pair_r}, K,
            n_groups, mesh=mesh,
        )
        group_ok_pad = np.ones(max(n_groups, 1), bool)
        group_ok_pad[:n_groups] = group_ok
        group_ok = group_ok_pad
    else:
        clock, births, cclk, cadd, crm, group_ok = _host_scatter_phase(
            clock0, births0, cclk0, cadd0, crm0, key_of_pair,
            B, A, Rm, K, b_pair_a, b_pair_r, NK, R, n_groups,
        )

    # ---- planes → state --------------------------------------------------
    state._mut += 1  # writeback mutates the state outside its methods
    robj = replicas.items
    state.clock = VClock(
        {robj[r]: int(clock[r]) for r in np.nonzero(clock)[0]}
    )
    new_births: dict = {}
    new_vals: dict = {}
    live_key = births.any(axis=1)
    for ki in np.nonzero(live_key)[0].tolist():
        ko = keys.items[ki]
        new_births[ko] = {
            robj[r]: int(births[ki, r]) for r in np.nonzero(births[ki])[0]
        }
        child = ORSet()
        child.clock = VClock(
            {robj[r]: int(cclk[ki, r]) for r in np.nonzero(cclk[ki])[0]}
        )
        new_vals[ko] = child
    # child content rides on pairs; surviving horizons of DEAD keys are
    # residue (models/crdtmap.py _rm_now) and keep a vals entry too
    ks_p, rs_p = np.nonzero(cadd)
    for p, r in zip(ks_p.tolist(), rs_p.tolist()):
        ki = int(key_of_pair[p])
        if not live_key[ki]:
            continue
        mo = members.items[int(uniq_pairs[p]) % NMx]
        new_vals[keys.items[ki]].entries.setdefault(mo, {})[robj[r]] = int(
            cadd[p, r]
        )
    ks_p, rs_p = np.nonzero(crm)
    for p, r in zip(ks_p.tolist(), rs_p.tolist()):
        ki = int(key_of_pair[p])
        ko = keys.items[ki]
        child = new_vals.get(ko)
        if child is None:
            child = new_vals[ko] = ORSet()  # residue-only key
        mo = members.items[int(uniq_pairs[p]) % NMx]
        child.deferred.setdefault(mo, {})[robj[r]] = int(crm[p, r])
    state.births = new_births
    state.vals = new_vals
    # batch removes that could not fire defer as WHOLE ops (ctx + keys),
    # joining the state's pending ones; anything the batch unblocked
    # fires through the model's own flush
    if len(K["group"]) and not group_ok.all():
        kk = np.asarray(K["key"], np.int64)
        for g in np.nonzero(~group_ok[: max(n_groups, 1)])[0].tolist():
            rows = np.nonzero(K["group"] == g)[0]
            ctx = VClock()
            gkeys = set()
            for i in rows.tolist():
                a = robj[int(K["actor"][i])]
                c = int(K["ctr"][i])
                if c > ctx.get(a):
                    ctx.counters[a] = c
                gkeys.add(keys.items[int(kk[i])])
            state._defer(ctx, gkeys)
    state._flush_deferred()
    return state

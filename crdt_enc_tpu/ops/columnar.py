"""Columnar (structure-of-arrays) encodings bridging host CRDTs and kernels.

The TPU consumes dense tensors; CRDT states and op logs are sparse,
dict-shaped host objects.  This module owns the conversion:

* **interning**: replica UUIDs and set members become dense indices via a
  ``Vocab`` (order of first appearance; canonical output never depends on
  intern order because serialization re-sorts),
* **op columns**: a batch of CRDT ops flattens to parallel int arrays — one
  row per add-dot or per (remove × context-actor),
* **state planes**: an ORSet becomes ``(clock[R], add[E,R], rm[E,R])`` int32
  matrices and back, losslessly.

The batched-tensor fold these feed is the rebuild's replacement for the
reference's per-op host loops (HOT LOOPS #1/#2, reference
crdt-enc/src/lib.rs:458-466 and :533-539).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import numpy as np

from ..models import AddOp, ORSet, RmOp, VClock
from ..models.counters import NEG, POS
from ..models.vclock import Dot
from ..utils import codec, trace

logger = logging.getLogger("crdt_enc_tpu.columnar")

_warned_no_native_state = False


def _warn_no_native_state(exc: Exception) -> None:
    """Log the state-assembly native fallback ONCE per process: losing
    statebuild.cpp silently costs ~4x on fresh folds and checkpoint
    unpacks (EXC001 — the bytes_lens_join regression class), but a box
    that cannot build the C-API library must not warn per call."""
    global _warned_no_native_state
    if not _warned_no_native_state:
        _warned_no_native_state = True
        logger.warning(
            "native state assembly unavailable (%r); using the "
            "numpy/Python fallback for fresh folds and checkpoint "
            "unpacks", exc
        )

KIND_ADD = 0
KIND_RM = 1


def pad_orset_rows(cols: "OrsetColumns", target: int, num_replicas: int):
    """Pad flattened op columns to ``target`` rows with sentinel no-ops
    (``actor == num_replicas`` marks padding — the single invariant every
    fold kernel keys on).  Shared by bucket padding (recompilation bound)
    and mesh padding (dp divisibility)."""
    n = len(cols.kind)
    padn = target - n
    if padn > 0:
        cols.kind = np.concatenate([cols.kind, np.zeros(padn, np.int8)])
        cols.member = np.concatenate([cols.member, np.zeros(padn, np.int32)])
        cols.actor = np.concatenate(
            [cols.actor, np.full(padn, num_replicas, np.int32)]
        )
        cols.counter = np.concatenate([cols.counter, np.zeros(padn, np.int32)])
    return cols


def strictly_sorted(seq) -> bool:
    """True iff ``seq`` is strictly ascending (⇒ unique).  C-level
    pairwise compare — ~3ms at 100k byte-string actors vs ~10ms for an
    index-based genexp; this sits ahead of every bulk ingest, where a
    storage listing that is already the sorted actor table lets callers
    skip a set union + re-sort of 100k keys."""
    import operator
    from itertools import islice

    return all(map(operator.lt, seq, islice(seq, 1, None)))


class Vocab:
    """Interning table: object → dense index (first-appearance order)."""

    __slots__ = ("items", "_index")

    def __init__(self, items=()):
        items = list(items)
        index = dict(zip(items, range(len(items))))
        if len(index) == len(items):  # no duplicates: one bulk dict build
            self._index: dict | None = index
            self.items: list = items
        else:
            self._index = {}
            self.items = []
            for it in items:
                self.intern(it)

    @classmethod
    def presorted_unique(cls, items) -> "Vocab":
        """Vocab over items the CALLER guarantees unique (e.g. a
        strictly-sorted actor table).  Skips the eager index build —
        hashing 100k byte-string keys costs ~10ms and the bulk fold
        paths only read ``items`` positionally; the index still builds
        lazily on first ``intern``/lookup."""
        v = cls.__new__(cls)
        v.items = list(items)
        v._index = None
        return v

    @property
    def index(self) -> dict:
        if self._index is None:
            self._index = dict(zip(self.items, range(len(self.items))))
        return self._index

    def intern(self, item) -> int:
        index = self.index
        idx = index.get(item)
        if idx is None:
            idx = len(self.items)
            index[item] = idx
            self.items.append(item)
        return idx

    def __len__(self) -> int:
        return len(self.items)


@dataclass
class OrsetColumns:
    """Flattened ORSet op batch (one row per dot / per rm-context entry)."""

    kind: np.ndarray  # int8  — KIND_ADD | KIND_RM
    member: np.ndarray  # int32 — index into members vocab
    actor: np.ndarray  # int32 — index into replicas vocab
    counter: np.ndarray  # int32 — dot counter / remove horizon
    members: Vocab = field(default_factory=Vocab)
    replicas: Vocab = field(default_factory=Vocab)


def orset_ops_to_columns(
    ops, members: Vocab | None = None, replicas: Vocab | None = None
) -> OrsetColumns:
    members = members if members is not None else Vocab()
    replicas = replicas if replicas is not None else Vocab()
    kind, member, actor, counter = [], [], [], []
    for op in ops:
        if isinstance(op, (list, tuple)):
            from ..models.orset import op_from_obj

            op = op_from_obj(op)
        if isinstance(op, AddOp):
            kind.append(KIND_ADD)
            member.append(members.intern(op.member))
            actor.append(replicas.intern(op.dot.actor))
            counter.append(op.dot.counter)
        elif isinstance(op, RmOp):
            m = members.intern(op.member)
            # sorted-actor order matches the canonical packed form the
            # native decoder walks, so both flattenings are positionally equal
            for r, c in sorted(op.ctx.counters.items()):
                kind.append(KIND_RM)
                member.append(m)
                actor.append(replicas.intern(r))
                counter.append(c)
        else:
            raise TypeError(f"bad ORSet op {op!r}")
    return OrsetColumns(
        np.asarray(kind, np.int8),
        np.asarray(member, np.int32),
        np.asarray(actor, np.int32),
        np.asarray(counter, np.int32),
        members,
        replicas,
    )


def orset_scan_vocab(state: ORSet, members: Vocab, replicas: Vocab) -> None:
    """Grow the vocabularies with everything the state mentions, without
    building planes — the cheap first pass when densifying many states to a
    shared vocabulary.

    Actors collect through C-level ``set.update`` per entry dict and new
    ones append in sorted order (deterministic), instead of one ``intern``
    call per dot — at ~1M dots the per-dot Python calls cost ~0.5s of
    every warm-open tail ingest and every fold's vocab pass."""
    if not state.entries and not state.deferred and not state.clock.counters:
        # an empty state mentions nothing — in particular do NOT touch
        # ``replicas.index``, whose lazy build over a 100k-actor table
        # costs ~10ms and is pure waste on the fresh streaming shape
        return
    actor_set: set = set()
    for m, entry in state.entries.items():
        members.intern(m)
        actor_set.update(entry)
    for m, dfr in state.deferred.items():
        members.intern(m)
        actor_set.update(dfr)
    actor_set.update(state.clock.counters)
    index = replicas.index
    new = [r for r in actor_set if r not in index]
    try:
        new.sort()
    except TypeError:  # mixed-type actor ids: sort by canonical bytes
        new.sort(key=codec.pack)
    for r in new:
        replicas.intern(r)


def orset_state_to_planes(
    state: ORSet, members: Vocab, replicas: Vocab, *, scanned: bool = False
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Dense ``(clock[R], add[E,R], rm[E,R])`` planes (int32).

    The vocabs are extended in place with anything the state mentions;
    pass ``scanned=True`` when ``orset_scan_vocab`` already ran for this
    state (skips a redundant sparse pass).
    """
    if not scanned:
        orset_scan_vocab(state, members, replicas)
    E, R = len(members), len(replicas)
    clock = np.zeros(R, np.int32)
    add = np.zeros((E, R), np.int32)
    rm = np.zeros((E, R), np.int32)
    for r, c in state.clock.counters.items():
        clock[replicas.index[r]] = c
    for m, entry in state.entries.items():
        e = members.index[m]
        for r, c in entry.items():
            add[e, replicas.index[r]] = c
    for m, dfr in state.deferred.items():
        e = members.index[m]
        for r, c in dfr.items():
            rm[e, replicas.index[r]] = c
    return clock, add, rm


def _grouped_rows_dicts_native(
    m_idx: np.ndarray, a_idx: np.ndarray, ctr: np.ndarray,
    members: list, actors: list, target: dict,
) -> bool:
    """ONE home for the native ``grouped_rows_dicts`` invocation
    (statebuild.cpp): member-contiguous int32/int32/int64 rows → nested
    ``{member: {actor: counter}}`` dicts in one C pass.  Returns False
    — with ``target`` left EMPTY (a partial fill is cleared) — when the
    native library is unavailable or declines; callers then run their
    own Python fallback.  Shared by the checkpoint unpack and the plane
    writeback, so the ABI and the partial-fill recovery can never
    drift between them."""
    try:
        import ctypes

        from .. import native

        lib = native.load_state()
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        rc = lib.grouped_rows_dicts(
            np.ascontiguousarray(m_idx, np.int32).ctypes.data_as(i32p),
            np.ascontiguousarray(a_idx, np.int32).ctypes.data_as(i32p),
            np.ascontiguousarray(ctr, np.int64).ctypes.data_as(i64p),
            len(m_idx), members, actors, target,
        )
        if rc == 0:
            return True
        target.clear()  # partial native fill: rebuild from scratch
    except Exception as e:
        _warn_no_native_state(e)
    return False


def _fill_dicts_from_plane(plane: np.ndarray, members: Vocab,
                           replicas: Vocab, target: dict) -> None:
    """Nonzero plane cells → nested ``{member: {actor: counter}}`` dicts.

    ``np.nonzero`` yields rows in row-major order, i.e. grouped by
    member — exactly the contiguous-groups contract of the native
    ``grouped_rows_dicts`` pass, so the dict assembly that dominated
    the plane writeback at fleet scale (~0.6ms per small tenant, ×
    every tenant × every service cycle — and every solo session
    finish) runs as one C call.  The Python loop remains as the
    no-native fallback, byte-identical."""
    es, rs = np.nonzero(plane)
    if not len(es):
        return
    if _grouped_rows_dicts_native(
        es, rs, plane[es, rs], members.items, replicas.items, target
    ):
        return
    for e, r in zip(es.tolist(), rs.tolist()):
        target.setdefault(members.items[e], {})[replicas.items[r]] = int(
            plane[e, r]
        )


def orset_planes_to_state(
    clock: np.ndarray, add: np.ndarray, rm: np.ndarray, members: Vocab, replicas: Vocab
) -> ORSet:
    """Inverse of ``orset_state_to_planes`` (planes must be normalized:
    entries killed where add ≤ rm, rm zeroed where rm ≤ clock)."""
    clock = np.asarray(clock)
    add = np.asarray(add)
    rm = np.asarray(rm)
    state = ORSet()
    state.clock = VClock(
        {replicas.items[r]: int(clock[r]) for r in np.nonzero(clock)[0]}
    )
    _fill_dicts_from_plane(add, members, replicas, state.entries)
    _fill_dicts_from_plane(rm, members, replicas, state.deferred)
    return state


def orset_fold_sparse_host(
    state: ORSet,
    kind: np.ndarray,
    member: np.ndarray,
    actor: np.ndarray,
    counter: np.ndarray,
    members: Vocab,
    replicas: Vocab,
) -> ORSet:
    """Vectorized-numpy sparse fold: the host twin of ``orset_fold_coo``.

    Same aggregation (per-segment max of live-add dots and remove
    horizons, stale-filter against the state clock) via ``np.lexsort``
    run-boundaries instead of a device sort.  Exists because TPU sorts
    are bitonic and slow for this shape (measured 0.7s for 256k rows vs
    29ms in numpy — sorting is not MXU work), and the sparse regime is
    N ≪ E·R where the device has nothing else to offer; the jitted
    ``orset_fold_coo`` remains for compositions that are already
    device-resident.  int64 keys — no ``2·E·R < 2^31`` bound.
    """
    state._mut += 1  # invalidate any device-resident plane cache
    # dense clock FIRST: it may intern clock actors into `replicas`, and
    # the segment keys below must be encoded with the final R or
    # orset_apply_coo would decode them against a different modulus
    clock0 = vclock_to_dense(state.clock, replicas).astype(np.int64)
    E, R = len(members), len(replicas)
    if not state.entries and not state.deferred and E and R:
        # the streaming shape (one combined fold into an empty state):
        # native sort + dict assembly (statebuild.cpp) replaces the numpy
        # lexsort and the Python writeback — measured ~5x on the config-5
        # wall.  Falls through on any native unavailability or a shape
        # past the packed-sort bound.
        folded = _orset_fresh_fold_native(
            state, kind, member, actor, counter, members, replicas, clock0
        )
        if folded is not None:
            return folded
    kind = np.asarray(kind)
    member = np.asarray(member, np.int64)
    actor = np.asarray(actor, np.int64)
    counter = np.asarray(counter, np.int64)
    pad = actor >= R
    a_ix = np.minimum(actor, R - 1)
    is_add = (kind == KIND_ADD) & ~pad
    is_rm = (kind == KIND_RM) & ~pad
    live = is_add & (counter > clock0[a_ix])
    valid = live | is_rm
    seg = member * R + a_ix
    key = np.where(is_rm, seg + E * R, seg)[valid]
    c = counter[valid]
    order = np.lexsort((c, key))
    sk = key[order]
    sc = c[order]
    is_last = np.ones(len(sk), bool)
    if len(sk) > 1:
        is_last[:-1] = sk[:-1] != sk[1:]
    clock = clock0.copy()
    np.maximum.at(clock, a_ix[live], counter[live])
    # int64 throughout: narrowing here would silently wrap a > 2^31
    # clock (apply_coo and dense_to_vclock are dtype-agnostic)
    return orset_apply_coo(
        state, clock, sk, sc, is_last, members, replicas
    )


#: rows below this skip the checkpoint-stash bookkeeping — repacking a
#: tiny state from its dicts costs less than carrying the row arrays
CKPT_STASH_MIN_ROWS = 4096


def _orset_fresh_fold_native(
    state, kind, member, actor, counter, members, replicas, clock0
):
    """Attempt the native fresh-state sparse fold (statebuild.cpp),
    byte-identical to the numpy/Python path below.  Returns the folded
    state, or None when the native library is unavailable or the shape
    overflows the packed sort (caller falls through to the Python path).

    Split protocol (``orset_fold_rows`` → ``grouped_rows_dicts``): the
    pure-C FOLD — gate + packed-u64 radix sort + dedup + survivor
    filter — runs under its own ``session.sparse_fold`` span, and the
    CPython dict WRITEBACK under ``session.writeback``, so the gap
    report's fold marginal stops absorbing dict-assembly time.  The
    surviving rows come out member-contiguous in the
    ``orset_pack_checkpoint`` layout and are stashed on the state
    (mut-epoch-guarded) so the compaction's warm-open checkpoint seals
    straight from them — zero dict re-walk (core.py
    ``_pack_checkpoint_state``).  Falls back to the fused
    ``orset_fresh_fold`` (one call, dicts built inside) when the split
    entry points are missing (older .so)."""
    import ctypes

    from .. import native

    try:
        lib = native.load_state()
    except Exception as e:
        _warn_no_native_state(e)
        return None
    # self-protecting epoch bump (MUT001): the caller bumps too, but the
    # native writeback below mutates entries/deferred/clock directly and
    # must not depend on every future caller remembering to
    state._mut += 1
    E, R = len(members), len(replicas)
    kind = np.ascontiguousarray(kind, np.int8)
    member32 = np.ascontiguousarray(member, np.int32)
    actor32 = np.ascontiguousarray(np.minimum(actor, R), np.int32)
    counter32 = np.ascontiguousarray(counter, np.int32)
    if len(member32) and (
        int(counter32.max(initial=0)) != int(np.asarray(counter).max(initial=0))
        or int(member32.max(initial=0)) >= E
    ):
        return None  # int32 narrowing lost information — Python path
    if len(clock0) and int(np.asarray(clock0).max(initial=0)) > 2 ** 31 - 1:
        return None  # an int64 clock would wrap through the int32 gate
    clock = np.ascontiguousarray(clock0, np.int32)
    i8p = ctypes.POINTER(ctypes.c_int8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    if not hasattr(lib, "orset_fold_rows"):
        # stale .so without the split protocol: fused fold+writeback
        rc = lib.orset_fresh_fold(
            kind.ctypes.data_as(i8p),
            member32.ctypes.data_as(i32p),
            actor32.ctypes.data_as(i32p),
            counter32.ctypes.data_as(i32p),
            len(kind), E, R,
            clock.ctypes.data_as(i32p),
            members.items, replicas.items,
            state.entries, state.deferred,
        )
        if rc == -2:
            raise RuntimeError("native orset_fresh_fold failed")
        if rc != 0:
            return None
        clock_dict = lib.dense_clock_dict(
            clock.ctypes.data_as(i32p), R, replicas.items
        )
        state.clock = VClock(clock_dict)
        return state
    with trace.span("session.sparse_fold"):
        counts = np.zeros(2, np.int64)
        handle = lib.orset_fold_rows(
            kind.ctypes.data_as(i8p),
            member32.ctypes.data_as(i32p),
            actor32.ctypes.data_as(i32p),
            counter32.ctypes.data_as(i32p),
            len(kind), E, R,
            clock.ctypes.data_as(i32p),
            counts.ctypes.data_as(i64p),
        )
        if not handle:
            return None  # packed-sort overflow / alloc failure
        n_a, n_d = int(counts[0]), int(counts[1])
        taken = False
        try:
            am = np.zeros(n_a, np.int32)
            aa = np.zeros(n_a, np.int32)
            ac = np.zeros(n_a, np.int64)
            dm = np.zeros(n_d, np.int32)
            da = np.zeros(n_d, np.int32)
            dc = np.zeros(n_d, np.int64)
            taken = True  # take() frees even if a later copy would fail
            rc = lib.orset_fold_rows_take(
                handle,
                am.ctypes.data_as(i32p), aa.ctypes.data_as(i32p),
                ac.ctypes.data_as(i64p), n_a,
                dm.ctypes.data_as(i32p), da.ctypes.data_as(i32p),
                dc.ctypes.data_as(i64p), n_d,
            )
            if rc != 0:
                raise RuntimeError(
                    "orset_fold_rows_take capacity mismatch"
                )
        finally:
            if not taken:  # e.g. MemoryError sizing the output arrays
                lib.orset_fold_rows_drop(handle)
    with trace.span("session.writeback"):
        if n_a and not _grouped_rows_dicts_native(
            am, aa, ac, members.items, replicas.items, state.entries
        ):
            _fill_dicts_from_rows(
                am, aa, ac, members, replicas, state.entries
            )
        if n_d and not _grouped_rows_dicts_native(
            dm, da, dc, members.items, replicas.items, state.deferred
        ):
            _fill_dicts_from_rows(
                dm, da, dc, members, replicas, state.deferred
            )
        clock_dict = lib.dense_clock_dict(
            clock.ctypes.data_as(i32p), R, replicas.items
        )
        state.clock = VClock(clock_dict)
    if n_a + n_d >= CKPT_STASH_MIN_ROWS:
        state._ckpt_rows = (
            getattr(state, "_mut", None),
            (clock.copy(), am, aa, ac, dm, da, dc, members, replicas),
        )
    return state


def _fill_dicts_from_rows(m_idx, a_idx, ctr, members: Vocab,
                          replicas: Vocab, target: dict) -> None:
    """Python fallback for the member-contiguous rows → nested-dicts
    writeback (the ``grouped_rows_dicts`` contract) — byte-identical."""
    a_l = a_idx.tolist()
    c_l = ctr.tolist()
    starts = np.flatnonzero(np.r_[True, np.diff(m_idx) != 0])
    ends = np.r_[starts[1:], len(m_idx)]
    for s, e in zip(starts.tolist(), ends.tolist()):
        target[members.items[int(m_idx[s])]] = {
            replicas.items[a_l[t]]: c_l[t] for t in range(s, e)
        }


def orset_pack_checkpoint_rows(
    clock: np.ndarray, am, aa, ac, dm, da, dc,
    members: Vocab, replicas: Vocab,
) -> dict:
    """:func:`orset_pack_checkpoint` computed from the fresh fold's
    surviving ROW columns (``_orset_fresh_fold_native``'s stash) — the
    zero-copy decode→planes tail: the checkpoint payload falls out of
    vectorized index remaps over arrays the fold already produced, with
    no walk of the dicts the state also materialized.  Same wire keys
    and invariants as the sparse pack (clock actors first and aligned
    with ``cc``, member groups contiguous, only referenced objects
    listed); table/row ORDER may differ from the dict walk — legal, the
    checkpoint is a local cache and ``orset_unpack_checkpoint`` is
    order-agnostic beyond group contiguity (the
    ``orset_pack_checkpoint_planes`` precedent)."""
    clock = np.asarray(clock)
    cnz = np.nonzero(clock)[0]
    used = np.union1d(np.union1d(cnz, aa), da)
    a_order = np.concatenate([cnz, np.setdiff1d(used, cnz)])
    a_perm = np.zeros((int(a_order.max()) + 1) if len(a_order) else 1,
                      np.int32)
    a_perm[a_order] = np.arange(len(a_order), dtype=np.int32)
    em = np.unique(am)
    m_order = np.concatenate([em, np.setdiff1d(np.unique(dm), em)])
    m_perm = np.zeros((int(m_order.max()) + 1) if len(m_order) else 1,
                      np.int32)
    m_perm[m_order] = np.arange(len(m_order), dtype=np.int32)
    aobj, mobj = replicas.items, members.items
    return {
        b"actors": [aobj[int(i)] for i in a_order],
        b"members": [mobj[int(i)] for i in m_order],
        b"nc": len(cnz),
        b"cc": clock[cnz].astype(np.int64).tobytes(),
        b"em": m_perm[am].tobytes(),
        b"ea": a_perm[aa].tobytes(),
        b"ec": np.asarray(ac, np.int64).tobytes(),
        b"dm": m_perm[dm].tobytes(),
        b"da": a_perm[da].tobytes(),
        b"dc": np.asarray(dc, np.int64).tobytes(),
    }


def orset_apply_coo(
    state: ORSet,
    clock_dense: np.ndarray,
    seg_keys: np.ndarray,
    seg_max: np.ndarray,
    is_seg_max: np.ndarray,
    members: Vocab,
    replicas: Vocab,
) -> ORSet:
    """Fold ``orset_fold_coo`` results into sparse host state.

    Applies exactly the dense kernel's semantics without planes: per
    touched segment, entry ``= max(entry, add-dot)``, remove horizon
    ``= max(horizon, batch horizon)``, then the normalization rules —
    entries killed where ``entry ≤ horizon``, horizons dropped where
    ``≤ clock`` — via the state's own ``_normalize_member`` (the single
    host implementation of those rules).  Touched members plus every
    member holding deferred horizons are normalized: the batch may have
    advanced clocks that retire horizons the batch never mentioned.
    """
    state._mut += 1  # invalidate any device-resident plane cache
    E, R = len(members), len(replicas)
    sel = np.asarray(is_seg_max)
    k = np.asarray(seg_keys)[sel].astype(np.int64)
    c = np.asarray(seg_max)[sel]
    mobj = members.items
    aobj_arr = np.asarray(replicas.items, dtype=object)

    # keys are sorted: adds (key < E·R) form the prefix, removes the
    # suffix, and within each side rows are member-major — so members are
    # contiguous groups and fresh entries build as one dict(zip(...))
    split = int(np.searchsorted(k, E * R))
    ak, ac = k[:split], c[:split]
    rk, rc = k[split:] - E * R, c[split:]
    a_m, a_a = ak // R, ak % R
    r_m, r_a = rk // R, rk % R

    # Members absent from BOTH state.entries and state.deferred take a
    # fully vectorized path: for them the post-merge dicts are exactly the
    # batch segments with the normalization rules applied column-wise —
    # adds killed where ≤ the batch horizon on the same (member, actor)
    # segment, horizons dropped where ≤ the merged clock — so no per-member
    # Python normalize is needed.  On a fresh ingest that is every member.
    clock_arr = np.asarray(clock_dense, np.int64)
    if not state.entries and not state.deferred:
        fresh = None  # all members fresh
        a_fresh = np.ones(len(ak), bool)
        r_fresh = np.ones(len(rk), bool)
        pre_deferred: list = []
    else:
        existing = set(state.entries)
        existing.update(state.deferred)
        # pre-existing horizons re-normalize below even when the batch
        # never mentions them: the batch may have advanced clocks that
        # retire them
        pre_deferred = list(state.deferred)
        fresh = np.fromiter(
            (mo not in existing for mo in mobj), bool, count=E
        )
        a_fresh = fresh[a_m]
        r_fresh = fresh[r_m]

    def build_fresh(m_idx, a_idx, vals, target: dict):
        if not len(m_idx):
            return
        starts = np.flatnonzero(np.r_[True, np.diff(m_idx) != 0])
        ends = np.r_[starts[1:], len(m_idx)]
        a_objs = aobj_arr[a_idx].tolist()
        vv = vals.tolist()
        for s, e in zip(starts.tolist(), ends.tolist()):
            target[mobj[int(m_idx[s])]] = dict(zip(a_objs[s:e], vv[s:e]))

    # fresh adds: survive the batch horizon for their own (m, a) segment
    # (strict >: an equal horizon observed the dot — it dies)
    if len(rk):
        pos = np.minimum(np.searchsorted(rk, ak), len(rk) - 1)
        horizon = np.where(rk[pos] == ak, rc[pos], 0)
        keep_add = a_fresh & (ac > horizon)
    else:
        keep_add = a_fresh
    build_fresh(a_m[keep_add], a_a[keep_add], ac[keep_add], state.entries)
    # fresh horizons: only those the merged clock has not caught up with
    keep_rm = r_fresh & (rc > clock_arr[r_a])
    build_fresh(r_m[keep_rm], r_a[keep_rm], rc[keep_rm], state.deferred)

    # members with pre-existing state merge by max, then normalize
    touched: set = set()

    aobj = replicas.items

    def fold_groups(m_idx, a_idx, vals, target: dict):
        a_idx = a_idx.tolist()
        vals = vals.tolist()
        starts = np.flatnonzero(np.r_[True, np.diff(m_idx) != 0])
        ends = np.r_[starts[1:], len(m_idx)]
        for s, e in zip(starts.tolist(), ends.tolist()):
            mo = mobj[int(m_idx[s])]
            touched.add(mo)
            slot = target.setdefault(mo, {})
            for x, cc in zip(a_idx[s:e], vals[s:e]):
                ao = aobj[x]
                if cc > slot.get(ao, 0):
                    slot[ao] = cc

    if fresh is not None:
        stale_a = ~a_fresh
        if stale_a.any():
            fold_groups(a_m[stale_a], a_a[stale_a], ac[stale_a], state.entries)
        stale_r = ~r_fresh
        if stale_r.any():
            fold_groups(r_m[stale_r], r_a[stale_r], rc[stale_r], state.deferred)

    state.clock = dense_to_vclock(clock_dense, replicas)
    touched.update(pre_deferred)
    for mo in touched:
        state._normalize_member(mo)
    return state


# ---- checkpoint pack/unpack ----------------------------------------------


def orset_pack_checkpoint(state: ORSet) -> dict | None:
    """Columnar encoding of one ORSet for the local fold checkpoint
    (core.py ``save_checkpoint``): the three sparse tables flatten to raw
    int row buffers over interned actor/member tables, so a 100k-replica
    clock packs and loads as ``np.frombuffer`` + one zip instead of a
    per-key msgpack map walk.  Lossless by value; byte-identity of the
    canonical serialization follows because ``codec.pack`` re-sorts maps.

    Returns None when any counter falls outside int64 (precision must
    never be lost — the caller then uses the generic ``state_to_obj``
    encoding instead).
    """
    actors = Vocab()
    members = Vocab()
    for r in state.clock.counters:
        actors.intern(r)

    def rows(table: dict):
        m_idx, a_idx, ctr = [], [], []
        for m, slots in table.items():
            e = members.intern(m)
            for r, c in slots.items():
                m_idx.append(e)
                a_idx.append(actors.intern(r))
                ctr.append(c)
        return (
            np.asarray(m_idx, np.int32),
            np.asarray(a_idx, np.int32),
            np.asarray(ctr, np.int64),
        )

    try:
        clock_ctr = np.asarray(
            list(state.clock.counters.values()), np.int64
        )
        em, ea, ec = rows(state.entries)
        dm, da, dc = rows(state.deferred)
    except OverflowError:
        return None
    return {
        b"actors": list(actors.items),
        b"members": list(members.items),
        b"nc": len(state.clock.counters),
        b"cc": clock_ctr.tobytes(),
        b"em": em.tobytes(), b"ea": ea.tobytes(), b"ec": ec.tobytes(),
        b"dm": dm.tobytes(), b"da": da.tobytes(), b"dc": dc.tobytes(),
    }


def orset_unpack_checkpoint(obj) -> ORSet:
    """Inverse of :func:`orset_pack_checkpoint`."""
    state = ORSet()
    actors = list(obj[b"actors"])
    members = list(obj[b"members"])
    nc = int(obj[b"nc"])
    cc = np.frombuffer(bytes(obj[b"cc"]), np.int64)
    state.clock = VClock(dict(zip(actors[:nc], cc.tolist())))

    def build(mi, ai, ci, target: dict):
        m_idx = np.frombuffer(bytes(obj[mi]), np.int32)
        if not len(m_idx):
            return
        a_idx = np.frombuffer(bytes(obj[ai]), np.int32)
        ctr = np.frombuffer(bytes(obj[ci]), np.int64)
        # rows were emitted in one walk of the source dict, so each
        # member's rows are contiguous.  Native fast path: one C pass
        # builds all the nested dicts (statebuild.cpp) — the Python
        # grouping below cost ~0.5s of every 1M-dot warm open.
        if _grouped_rows_dicts_native(
            m_idx, a_idx, ctr, members, actors, target
        ):
            return
        a_l = a_idx.tolist()
        c_l = ctr.tolist()
        starts = np.flatnonzero(np.r_[True, np.diff(m_idx) != 0])
        ends = np.r_[starts[1:], len(m_idx)]
        for s, e in zip(starts.tolist(), ends.tolist()):
            target[members[int(m_idx[s])]] = {
                actors[a_l[t]]: c_l[t] for t in range(s, e)
            }

    build(b"em", b"ea", b"ec", state.entries)
    build(b"dm", b"da", b"dc", state.deferred)
    return state


def orset_pack_checkpoint_planes(
    clock: np.ndarray, add: np.ndarray, rm: np.ndarray,
    members: Vocab, replicas: Vocab,
) -> dict:
    """:func:`orset_pack_checkpoint` computed from dense planes instead
    of the sparse state — all row buffers fall out of ``np.nonzero``
    with no per-dot Python (the fold service already HOLDS each
    tenant's folded planes, and the sparse pack walk was its single
    biggest seal-phase CPU item at fleet scale).  Same wire keys and
    invariants as the sparse pack: ``actors[:nc]`` are exactly the
    clock's actors (aligned with ``cc``), row groups are
    member-contiguous (the unpack contract — here by ``np.nonzero``'s
    row-major order), tables list only referenced actors/members.  The
    encodings differ in table/row ORDER (plane order vs dict walk) —
    legal, the checkpoint is a local cache and ``orset_unpack_
    checkpoint`` is order-agnostic beyond group contiguity; equality is
    pinned semantically in tests.  Planes may be bucket-padded: padded
    cells are zero, so no index past the vocabularies can appear.
    Counters are int32 by plane construction, so the sparse pack's
    int64-overflow decline cannot arise.

    Implementation: ``np.nonzero`` flattens the planes to the entry /
    deferred row columns (row-major ⇒ member-contiguous), then the ONE
    row-layout packer (:func:`orset_pack_checkpoint_rows`) builds the
    payload — the two plane/row entry points cannot drift."""
    clock = np.asarray(clock)
    add = np.asarray(add)
    rm = np.asarray(rm)
    es, rs = np.nonzero(add)
    ds, qs = np.nonzero(rm)
    return orset_pack_checkpoint_rows(
        clock, es, rs, add[es, rs], ds, qs, rm[ds, qs], members, replicas
    )


# ---- counters ------------------------------------------------------------


@dataclass
class CounterColumns:
    sign: np.ndarray  # int8 — POS | NEG (always POS for G-Counter)
    actor: np.ndarray  # int32
    counter: np.ndarray  # int32
    replicas: Vocab = field(default_factory=Vocab)


def counter_ops_to_columns(ops, replicas: Vocab | None = None) -> CounterColumns:
    """Flatten G-Counter (Dot) or PN-Counter ((dir, Dot)) op batches."""
    replicas = replicas if replicas is not None else Vocab()
    sign, actor, counter = [], [], []
    for op in ops:
        if isinstance(op, Dot):
            direction, dot = POS, op
        else:
            direction, dot = op
            if not isinstance(dot, Dot):
                dot = Dot.from_obj(dot)
        if direction not in (POS, NEG):
            raise ValueError(f"bad counter op direction {direction!r}")
        sign.append(direction)
        actor.append(replicas.intern(dot.actor))
        counter.append(dot.counter)
    return CounterColumns(
        np.asarray(sign, np.int8),
        np.asarray(actor, np.int32),
        np.asarray(counter, np.int32),
        replicas,
    )


def vclock_to_dense(clock: VClock, replicas: Vocab) -> np.ndarray:
    for r in clock.counters:
        replicas.intern(r)
    # int64 when any counter needs it: the sparse host path supports the
    # full counter range (device paths bound counters to int32 upstream)
    wide = any(c > 2 ** 31 - 1 for c in clock.counters.values())
    out = np.zeros(len(replicas), np.int64 if wide else np.int32)
    for r, c in clock.counters.items():
        out[replicas.index[r]] = c
    return out


def dense_to_vclock(arr: np.ndarray, replicas: Vocab) -> VClock:
    arr = np.asarray(arr)
    nz = np.nonzero(arr)[0]
    robj = np.asarray(replicas.items, dtype=object)[nz].tolist()
    return VClock(dict(zip(robj, arr[nz].tolist())))


# ---- LWW -----------------------------------------------------------------


@dataclass
class LwwColumns:
    key: np.ndarray  # int32 — index into keys vocab
    ts_hi: np.ndarray  # int32 — timestamp high 31 bits
    ts_lo: np.ndarray  # int32 — timestamp low 31 bits
    actor: np.ndarray  # int32 — index into actor-rank vocab (see below)
    value: np.ndarray  # int32 — index into values list (rank-ordered)
    tombstone: np.ndarray  # bool
    keys: Vocab = field(default_factory=Vocab)
    actors_sorted: list = field(default_factory=list)  # rank → actor bytes
    values_sorted: list = field(default_factory=list)  # rank → value object


def lww_ops_to_columns(ops, keys: Vocab | None = None) -> LwwColumns:
    """Flatten LWW ops.  Actors and values are *rank*-interned (sorted by
    bytes) so integer comparison on the device reproduces the host's
    lexicographic tie-breaks exactly."""
    from ..models.lwwmap import LWWOp

    ops = [LWWOp.from_obj(o) if isinstance(o, (list, tuple)) else o for o in ops]
    keys = keys if keys is not None else Vocab()
    actors = sorted({op.actor for op in ops})
    actor_rank = {a: i for i, a in enumerate(actors)}
    packed_vals = {}
    for op in ops:
        v = None if op.tombstone else op.value
        packed_vals[codec.pack(v)] = v
    values_sorted = [packed_vals[k] for k in sorted(packed_vals)]
    value_rank = {k: i for i, k in enumerate(sorted(packed_vals))}
    key_col, ts_col, actor_col, value_col, tomb_col = [], [], [], [], []
    for op in ops:
        key_col.append(keys.intern(op.key))
        ts_col.append(op.ts)
        actor_col.append(actor_rank[op.actor])
        v = None if op.tombstone else op.value
        value_col.append(value_rank[codec.pack(v)])
        tomb_col.append(op.tombstone)
    from .lww import ts_split

    ts_hi, ts_lo = ts_split(np.asarray(ts_col, np.int64).reshape(-1))
    return LwwColumns(
        np.asarray(key_col, np.int32),
        ts_hi,
        ts_lo,
        np.asarray(actor_col, np.int32),
        np.asarray(value_col, np.int32),
        np.asarray(tomb_col, bool),
        keys,
        actors,
        values_sorted,
    )

"""Counter fold kernels: segment-max over replica ids.

G-Counter compaction is the minimal end-to-end TPU slice (SURVEY.md §7): a
batch of increment dots collapses to per-replica maxima in one scatter-max.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..models.counters import NEG, POS


def sum_wide(x: jax.Array) -> jax.Array:
    """Plane sum in the widest integer the runtime actually has.

    The device-side counter ``value`` is advisory — the authoritative
    value is derived host-side from the returned planes (numpy int64,
    see models/counters.py).  Under the default x64-disabled config an
    ``astype(int64)`` silently truncates to int32 *with a UserWarning
    per trace*; this helper makes that truncation explicit and silent,
    and uses real int64 when the caller enabled x64."""
    wide = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return jnp.sum(x.astype(wide))


@partial(jax.jit, static_argnames=("num_replicas",))
def gcounter_fold(
    clock0: jax.Array,  # (R,) int32
    actor: jax.Array,  # (N,) int32  (== num_replicas ⇒ padding row)
    counter: jax.Array,  # (N,) int32
    *,
    num_replicas: int,
):
    """Fold increment dots into the per-replica clock; value = sum(clock)."""
    R = num_replicas
    pad = actor >= R
    new = jax.ops.segment_max(
        jnp.where(pad, 0, counter), jnp.minimum(actor, R - 1), num_segments=R
    )
    clock = jnp.maximum(clock0, jnp.maximum(new, 0))
    return clock, sum_wide(clock)


@partial(jax.jit, static_argnames=("num_replicas",))
def pncounter_fold(
    p0: jax.Array,  # (R,) int32
    n0: jax.Array,  # (R,) int32
    sign: jax.Array,  # (N,) int8 — POS | NEG
    actor: jax.Array,  # (N,) int32
    counter: jax.Array,  # (N,) int32
    *,
    num_replicas: int,
):
    R = num_replicas
    pad = actor >= R
    actor_ix = jnp.minimum(actor, R - 1)
    p_new = jax.ops.segment_max(
        jnp.where(~pad & (sign == POS), counter, 0), actor_ix, num_segments=R
    )
    n_new = jax.ops.segment_max(
        jnp.where(~pad & (sign == NEG), counter, 0), actor_ix, num_segments=R
    )
    p = jnp.maximum(p0, jnp.maximum(p_new, 0))
    n = jnp.maximum(n0, jnp.maximum(n_new, 0))
    value = sum_wide(p) - sum_wide(n)
    return p, n, value


@jax.jit
def vclock_merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Elementwise-max merge of dense vector clocks (same replica vocab)."""
    return jnp.maximum(a, b)

"""Counter fold kernels: segment-max over replica ids.

G-Counter compaction is the minimal end-to-end TPU slice (SURVEY.md §7): a
batch of increment dots collapses to per-replica maxima in one scatter-max.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..models.counters import NEG, POS


def sum_wide(x: jax.Array) -> jax.Array:
    """Plane sum in the widest integer the runtime actually has.

    The device-side counter ``value`` is advisory — the authoritative
    value is derived host-side from the returned planes (numpy int64,
    see models/counters.py).  Under the default x64-disabled config an
    ``astype(int64)`` silently truncates to int32 *with a UserWarning
    per trace*; this helper makes that truncation explicit and silent,
    and uses real int64 when the caller enabled x64."""
    wide = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return jnp.sum(x.astype(wide))


# Below this many rows the one serialized scatter-max is cheaper than a
# bitonic sort pass (measured round 5: the sort path wins ~3-4× at the
# config-2 shape 100k×1k; tiny batches like config 1's 1k×4 are
# dispatch-bound either way, where the scatter's lower op count wins).
SORTED_MIN_ROWS = 1 << 13


def _sorted_segment_max(key, val, n_segments: int):
    """Segment max with NO scatter: sort (key, value) pairs — each
    segment's maximum lands at its run end — then one searchsorted over
    the segment bounds and an (n_segments,)-element gather read every
    result.  TPUs have no fast random scatter (``jax.ops.segment_max``
    lowers to a ~9ns/row serialized loop; the round-5 profile put the
    config-2 fold at 1.75ms for 0.9MB of traffic), but their bitonic
    sort is fast and the run-end gather is tiny.  Same move as the
    flagship ORSet fold's sort phase (ops/pallas_fold.py), shrunk to the
    1-D counter planes.  Keys ≥ n_segments act as padding sentinels."""
    skey, sval = jax.lax.sort((key, val), num_keys=2)
    dt = key.dtype
    edges = jnp.searchsorted(skey, jnp.arange(n_segments + 1, dtype=dt))
    start, stop = edges[:-1], edges[1:]
    last = jnp.maximum(stop - 1, 0)
    return jnp.where(stop > start, sval[last], 0)


@partial(jax.jit, static_argnames=("num_replicas",))
def gcounter_fold(
    clock0: jax.Array,  # (R,) int32
    actor: jax.Array,  # (N,) int32  (== num_replicas ⇒ padding row)
    counter: jax.Array,  # (N,) int32
    *,
    num_replicas: int,
):
    """Fold increment dots into the per-replica clock; value = sum(clock)."""
    R = num_replicas
    pad = actor >= R
    if actor.shape[0] >= SORTED_MIN_ROWS:
        key = jnp.where(pad, R, actor)
        new = _sorted_segment_max(key, jnp.where(pad, 0, counter), R)
    else:
        new = jax.ops.segment_max(
            jnp.where(pad, 0, counter), jnp.minimum(actor, R - 1),
            num_segments=R,
        )
    clock = jnp.maximum(clock0, jnp.maximum(new, 0))
    return clock, sum_wide(clock)


@partial(jax.jit, static_argnames=("num_replicas",))
def pncounter_fold(
    p0: jax.Array,  # (R,) int32
    n0: jax.Array,  # (R,) int32
    sign: jax.Array,  # (N,) int8 — POS | NEG
    actor: jax.Array,  # (N,) int32
    counter: jax.Array,  # (N,) int32
    *,
    num_replicas: int,
):
    R = num_replicas
    pad = actor >= R
    actor_ix = jnp.minimum(actor, R - 1)
    if actor.shape[0] >= SORTED_MIN_ROWS:
        # ONE sort serves both planes: key interleaves (actor, plane),
        # pads AND out-of-domain signs sort to the 2R sentinel (the
        # scatter route drops sign ∉ {POS, NEG} — both routes must)
        valid = ~pad & ((sign == POS) | (sign == NEG))
        key = jnp.where(
            valid, actor_ix * 2 + (sign == NEG).astype(jnp.int32), 2 * R
        )
        both = _sorted_segment_max(
            key, jnp.where(valid, counter, 0), 2 * R
        ).reshape(R, 2)
        p_new, n_new = both[:, 0], both[:, 1]
    else:
        p_new = jax.ops.segment_max(
            jnp.where(~pad & (sign == POS), counter, 0), actor_ix,
            num_segments=R,
        )
        n_new = jax.ops.segment_max(
            jnp.where(~pad & (sign == NEG), counter, 0), actor_ix,
            num_segments=R,
        )
    p = jnp.maximum(p0, jnp.maximum(p_new, 0))
    n = jnp.maximum(n0, jnp.maximum(n_new, 0))
    value = sum_wide(p) - sum_wide(n)
    return p, n, value


@partial(jax.jit, static_argnames=("num_replicas",))
def gcounter_fold_tenants(
    clock0: jax.Array,  # (T, R) int32 — per-tenant clocks
    actor: jax.Array,  # (T, N) int32  (== num_replicas ⇒ padding row)
    counter: jax.Array,  # (T, N) int32
    *,
    num_replicas: int,
):
    """Multi-tenant G-Counter fold: :func:`gcounter_fold` vmapped over a
    tenant axis — one dispatch folds a whole bucket of small tenants
    (see ``ops.orset.orset_fold_tenants`` for the serving rationale).
    Reusing the solo kernel keeps BOTH scatter regimes (the
    ``SORTED_MIN_ROWS`` sort route included — per-lane rows can reach
    the serving row cap, where the serialized scatter loses); its value
    scalar is discarded here (XLA DCEs it) — the per-tenant value is
    derived host-side from the sparse writeback exactly as the solo
    path does, so no wide-sum truncation question arises."""

    def one(c, a, ct):
        clock, _value = gcounter_fold(c, a, ct, num_replicas=num_replicas)
        return clock

    return jax.vmap(one)(clock0, actor, counter)


@jax.jit
def vclock_merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Elementwise-max merge of dense vector clocks (same replica vocab)."""
    return jnp.maximum(a, b)

"""ORSet fold and merge as jitted tensor programs — the north-star kernels.

These replace the reference's per-op/per-state host loops (HOT LOOP #2
``state.apply(op)`` at crdt-enc/src/lib.rs:533-539 and HOT LOOP #1
``state.merge`` at lib.rs:458-466) with batched XLA reductions:

* **fold**: a whole op batch (adds as dots, removes flattened to per-replica
  horizon rows) collapses into the state planes via two ``segment_max``
  scatters and elementwise masks.  Order-independence of the dense formulas
  (max over monotone per-replica counters) is exactly why this is legal — the
  property tests in tests/test_crdt_laws.py pin the host semantics and
  tests/test_ops_kernels.py pins host≡TPU byte-equality.
* **merge**: the Orswot clock-filter merge as pure elementwise arithmetic
  over ``(E, R)`` planes.

All shapes are static under jit; ragged op batches are padded with no-op rows
(``actor = R`` sentinel column, masked out) so recompilation is bounded by
shape buckets, not batch contents.  Counters are int32 and always ≥ 1 for
real dots, so 0 is the universal "absent" value and empty ``segment_max``
segments (dtype-min) clamp back to 0.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import trace
from .columnar import KIND_ADD, KIND_RM


@partial(
    jax.jit,
    static_argnames=(
        "num_members", "num_replicas", "sort_segments", "impl",
        "small_counters", "retire_rm",
    ),
)
def orset_fold(
    clock0: jax.Array,  # (R,) int32
    add0: jax.Array,  # (E, R) int32
    rm0: jax.Array,  # (E, R) int32
    kind: jax.Array,  # (N,) int8
    member: jax.Array,  # (N,) int32
    actor: jax.Array,  # (N,) int32  (== num_replicas ⇒ padding row)
    counter: jax.Array,  # (N,) int32
    *,
    num_members: int,
    num_replicas: int,
    sort_segments: bool = False,
    impl: str = "fused",
    small_counters: bool = False,
    retire_rm: bool = True,
):
    """Fold an op batch into normalized ORSet planes.

    ``retire_rm=False`` keeps remove horizons un-retired (no
    ``rm > clock`` zeroing): required when the planes are a PARTIAL
    reduction to be combined with a pre-existing state later — a horizon
    retired against the batch-local clock would lose its kill-effect on
    state entries it never met (the streaming session's combine retires
    once, against the true merged clock).

    Returns ``(clock, add, rm)`` in canonical/normalized form: entries
    zeroed where ``add ≤ rm``, horizons zeroed where ``rm ≤ clock``.

    ``impl`` selects the scatter strategy (hardware-benchmarked on v5e,
    see bench.py):

    * ``"fused"`` (default) — ONE combined scatter-max: removes land at a
      ``E*R`` offset in a ``(2, E, R)`` target, so XLA initializes and
      sweeps the 2·E·R scatter target once instead of twice
      (31ms → 23ms on the 1M-op / 10k-replica north-star config).
      With ``small_counters=True`` (caller asserts all counters
      < 2**15) the scatter runs on int16 values, halving the scatter
      target's HBM footprint (→ 21ms).
    * ``"two_pass"`` — the original pair of ``segment_max`` calls;
      ``sort_segments=True`` additionally sorts the batch by segment id
      and tells XLA the indices are sorted (workload-dependent; loses on
      the north-star config).

    ``small_counters`` only affects ``"fused"`` and ``sort_segments``
    only affects ``"two_pass"``; a flag passed to the other impl raises.
    """
    if small_counters and impl != "fused":
        raise ValueError("small_counters requires impl='fused'")
    if sort_segments and impl != "two_pass":
        raise ValueError("sort_segments requires impl='two_pass'")
    E, R = num_members, num_replicas
    pad = actor >= R  # sentinel rows from bucket padding
    is_add = (kind == KIND_ADD) & ~pad
    is_rm = (kind == KIND_RM) & ~pad
    actor_ix = jnp.minimum(actor, R - 1)

    seg = member * R + actor_ix
    if impl == "fused":
        # Removes scatter into the second (E, R) plane of one flat target.
        seg2 = jnp.where(is_rm, seg + E * R, seg)
        vals = jnp.where(is_add | is_rm, counter, 0)
        if small_counters:
            z = jnp.zeros((2 * E * R,), jnp.int16)
            both = z.at[seg2].max(vals.astype(jnp.int16), mode="drop")
            both = both.astype(jnp.int32).reshape(2, E, R)
        else:
            z = jnp.zeros((2 * E * R,), jnp.int32)
            both = z.at[seg2].max(vals, mode="drop").reshape(2, E, R)
        add_new, rm_new = both[0], both[1]
    elif impl == "two_pass":
        vals_add = jnp.where(is_add, counter, 0)
        vals_rm = jnp.where(is_rm, counter, 0)
        if sort_segments:
            order = jnp.argsort(seg)
            seg_s = seg[order]
            add_new = jax.ops.segment_max(
                vals_add[order], seg_s, num_segments=E * R,
                indices_are_sorted=True,
            )
            rm_new = jax.ops.segment_max(
                vals_rm[order], seg_s, num_segments=E * R,
                indices_are_sorted=True,
            )
        else:
            add_new = jax.ops.segment_max(vals_add, seg, num_segments=E * R)
            rm_new = jax.ops.segment_max(vals_rm, seg, num_segments=E * R)
        # clamp empty segments (dtype-min fill) back to "absent"
        add_new = jnp.maximum(add_new, 0).reshape(E, R)
        rm_new = jnp.maximum(rm_new, 0).reshape(E, R)
    else:
        raise ValueError(f"unknown fold impl {impl!r}; use 'fused' or 'two_pass'")

    # Stale-add replay gate, lifted from row level to CELL level: dots
    # are monotone per actor, so a cell whose scattered max is ≤ the
    # incoming clock held ONLY stale adds — zeroing it equals excluding
    # each stale row from the scatter (the round-2 kernels gated per row,
    # which cost a 1M-element clock gather per fold; measured ~6ms of the
    # old 19.6ms marginal).
    add_new = jnp.where(add_new > clock0[None, :], add_new, 0)

    # Adds advance the global clock; removes never do.  The batch's max
    # live-add counter per actor is already in add_new — a dense column
    # reduction instead of a third scatter.
    clock = jnp.maximum(clock0, jnp.max(add_new, axis=0, initial=0))

    add = jnp.maximum(add0, add_new)
    rm = jnp.maximum(rm0, rm_new)

    # Normalize: a horizon kills every dot it covers; a horizon the clock
    # caught up with has fully applied.
    add = jnp.where(add > rm, add, 0)
    if retire_rm:
        rm = jnp.where(rm > clock[None, :], rm, 0)
    return clock, add, rm


@partial(jax.jit, static_argnames=("num_members", "num_replicas"))
def orset_fold_coo(
    clock0: jax.Array,  # (R,) int32
    kind: jax.Array,  # (N,) int8
    member: jax.Array,  # (N,) int32
    actor: jax.Array,  # (N,) int32  (== num_replicas ⇒ padding row)
    counter: jax.Array,  # (N,) int32
    *,
    num_members: int,
    num_replicas: int,
):
    """Sparse fold: aggregate an op batch WITHOUT materializing the dense
    ``(E, R)`` planes.

    The dense ``orset_fold`` initializes and sweeps a ``2·E·R`` scatter
    target per call — at the 100k-replica streaming scale that is ~800MB
    of HBM traffic for a few hundred thousand updates (measured 46s/fold,
    N ≪ E·R).  Here the batch is sorted by segment key and per-segment
    maxima fall out of run boundaries: O(N log N) work, independent of
    E·R.  Returns ``(clock, seg_keys, seg_max, is_seg_max)`` where rows
    with ``is_seg_max`` hold each touched segment's aggregated value
    (key < E·R: live-add dot max; key ≥ E·R: remove-horizon max — same
    aggregation the dense kernel's two scatter planes perform).  Feed to
    ``ops.columnar.orset_apply_coo`` to fold into sparse host state with
    the dense kernel's exact normalization semantics.

    Requires ``2·E·R < 2^31`` (int32 keys; same bound the dense kernel's
    flat scatter target imposes).
    """
    E, R = num_members, num_replicas
    if 2 * E * R >= 2 ** 31:
        raise ValueError("segment key space exceeds int32; shard members first")
    pad = actor >= R
    actor_ix = jnp.minimum(actor, R - 1)
    is_add = (kind == KIND_ADD) & ~pad
    is_rm = (kind == KIND_RM) & ~pad
    seen = counter <= clock0[actor_ix]
    live_add = is_add & ~seen
    valid = live_add | is_rm
    seg = member * R + actor_ix
    key = jnp.where(valid, jnp.where(is_rm, seg + E * R, seg), 2 * E * R)
    skey, scounter = jax.lax.sort((key, counter), num_keys=2)
    # lexicographic sort ⇒ the last row of every key-run is that segment's max
    nxt = jnp.concatenate([skey[1:], jnp.full((1,), -1, skey.dtype)])
    is_seg_max = (skey != nxt) & (skey < 2 * E * R)
    clock_new = jax.ops.segment_max(
        jnp.where(live_add, counter, 0), actor_ix, num_segments=R
    )
    clock = jnp.maximum(clock0, jnp.maximum(clock_new, 0))
    return clock, skey, scounter, is_seg_max


@jax.jit
def orset_apply_batch_planes(
    clock0: jax.Array,  # (R,) int32 — CURRENT state clock
    add0: jax.Array,  # (E, R) int32 — current state planes
    rm0: jax.Array,
    add_b: jax.Array,  # (E, R) int32 — batch-reduced planes (leaf fold)
    rm_b: jax.Array,
):
    """Apply pre-reduced op-batch planes to the state planes: the tail of
    :func:`orset_fold` after the scatter phase, with the stale-add mask
    lifted to cell level — ``add_b`` cells not beyond the CURRENT clock
    are replays (per-actor dot counters are monotone, so a stale cell max
    means every dot in the cell was stale) and drop, exactly as the
    kernel's row-level ``seen`` mask would have dropped them.  Evaluating
    the mask against the clock *now* (not at session start) keeps the
    combine correct when concurrent applies or state merges advanced the
    state while chunks were being reduced.  NOT the CvRDT state merge
    (``orset_merge``) — batch rows are ops, so no clock-filter survivor
    rule applies to them."""
    add_b = jnp.where(add_b > clock0[None, :], add_b, 0)
    clock = jnp.maximum(clock0, jnp.max(add_b, axis=0, initial=0))
    add = jnp.maximum(add0, add_b)
    rm = jnp.maximum(rm0, rm_b)
    add = jnp.where(add > rm, add, 0)
    rm = jnp.where(rm > clock[None, :], rm, 0)
    return clock, add, rm


@partial(jax.jit, static_argnames=("num_members", "num_replicas"))
def orset_fold_tenants(
    clock0: jax.Array,  # (T, R) int32 — per-tenant state clocks
    add0: jax.Array,  # (T, E, R) int32 — per-tenant state planes
    rm0: jax.Array,  # (T, E, R) int32
    kind: jax.Array,  # (T, N) int8 — per-tenant op rows
    member: jax.Array,  # (T, N) int32
    actor: jax.Array,  # (T, N) int32  (== num_replicas ⇒ padding row)
    counter: jax.Array,  # (T, N) int32
    *,
    num_members: int,
    num_replicas: int,
):
    """The multi-tenant mega-fold: :func:`orset_fold` with the tenant
    batch as one more fold axis (``vmap`` over the leading dim), so a
    whole bucket of small tenants collapses in ONE device dispatch
    instead of T dispatch+compile-amortization rounds (ROADMAP item 1,
    the serving shape — millions of *small* remotes, not one huge one).

    Tenants never interact: every scatter segment id is tenant-local by
    construction of the vmap, so the result planes are exactly what T
    independent ``orset_fold`` calls would produce — the serving layer's
    byte-identity differential (tests/test_serve.py) pins it against the
    solo ``Core.compact`` path end to end.  Shapes are quantized by the
    serving layer's bucket planner (crdt_enc_tpu/serve/bucketing.py), so
    compilation count is bounded by size classes, not tenant mixes.
    Padding rows use the same ``actor == num_replicas`` sentinel as every
    other fold; dummy tenant slots are all-sentinel rows over zero
    planes."""

    def one(c, a, r, k, m, ac, ct):
        return orset_fold(
            c, a, r, k, m, ac, ct,
            num_members=num_members, num_replicas=num_replicas,
        )

    return jax.vmap(one)(clock0, add0, rm0, kind, member, actor, counter)


# Diff-row code bits (orset_plane_diff): which wire-form map a diff cell
# feeds — the Orswot window delta's ``e`` / ``x`` / ``t`` keys
# (delta/codec.orset_delta_diff).  A cell can set the add and horizon
# bits together in principle (they read different planes); add and
# removed are mutually exclusive by construction (``add_n > clock_b``
# needs ``add_n > 0``, removed needs ``add_n == 0``).
DIFF_ADD = 1  # surviving window dot: add_n > base clock (new adds AND
#               confirmations that keep a window dot alive)
DIFF_REMOVED = 2  # dot-exact removal: base slot absent from new
DIFF_HORIZON = 4  # remove horizon raised past the base's


@jax.jit
def orset_plane_diff(clock_b, add_b, rm_b, clock_n, add_n, rm_n):
    """Device cut of the Orswot window delta (docs/delta.md): compare a
    sealed BASE state's planes against the post-fold NEW planes and mark
    every cell the host dict-walk ``delta.codec.orset_delta_diff`` would
    emit.  Returns ``(code, count)`` — an int8 code plane (DIFF_* bits)
    and the number of nonzero cells — so the caller can size the
    O(diff-rows) gather (:func:`orset_plane_diff_rows`) and D2H only the
    rows that feed the wire form, never the full planes.

    Both plane sets must be canonical (the fold/merge kernels' output
    law: entries killed where add ≤ rm, rm zeroed where rm ≤ clock) and
    indexed by ONE shared vocabulary; zero-padded cells are absent in
    both states and can never mark.  The bit conditions are exactly the
    host walk's comprehensions:

    * add: ``add_n > clock_b[r]`` — slots in ``new.entries`` whose dot
      lies past the base clock (``c > bc.get(r)``), including unchanged
      survivors (the confirmations);
    * removed: ``add_b > 0 and add_n == 0`` — base slots with no slot in
      ``new`` (``not new_slots.get(r, 0)``), dot-exact with the base
      counter as the value;
    * horizon: ``rm_n > rm_b and rm_n > clock_n[r]`` — deferred removes
      raised past the base's (``h > base_hs.get(r, 0)``) and still ahead
      of the new clock (canonical planes imply the second clause; it is
      kept so the kernel never depends on the caller normalizing).
    """
    add_bit = (add_n > clock_b[None, :]).astype(jnp.int8) * DIFF_ADD
    rm_bit = (
        (add_b > 0) & (add_n == 0)
    ).astype(jnp.int8) * DIFF_REMOVED
    hz_bit = (
        (rm_n > rm_b) & (rm_n > clock_n[None, :])
    ).astype(jnp.int8) * DIFF_HORIZON
    code = add_bit | rm_bit | hz_bit
    return code, jnp.sum(code != 0, dtype=jnp.int32)


@jax.jit
def orset_plane_diff_tenants(clock_b, add_b, rm_b, clock_n, add_n, rm_n):
    """The serving layer's batched twin of :func:`orset_plane_diff`:
    one dispatch marks a whole bucket's diff cells (``vmap`` over the
    tenant axis, the mega-fold discipline), and the per-tenant counts
    come home in one (T,) D2H instead of T scalar syncs."""
    return jax.vmap(orset_plane_diff)(
        clock_b, add_b, rm_b, clock_n, add_n, rm_n
    )


@partial(jax.jit, static_argnames=("size",))
def orset_plane_diff_rows(code, add_b, add_n, rm_n, *, size):
    """Gather ONE tenant's diff rows from its code plane: the flat cell
    indices (row-major, so ``divmod(idx, R)`` recovers ``(e, r)``) plus
    the code and the three counter values the wire builder needs
    (``delta.codec.orset_delta_from_rows``).  ``size`` is the static
    row capacity — the caller quantizes the phase-1 count through the
    repo's ``_bucket`` law, so compile classes stay bounded by
    log(E·R), not by diff contents.  Slots past the real count carry
    ``idx == code.size`` (out of range) and zero values."""
    flat = code.ravel()
    n = flat.shape[0]
    (idx,) = jnp.nonzero(flat, size=size, fill_value=n)
    safe = jnp.minimum(idx, n - 1)
    live = idx < n

    def take(plane):
        return jnp.where(live, plane.ravel()[safe], 0)

    return (
        idx,
        take(flat),
        take(add_b),
        take(add_n),
        take(rm_n),
    )


def merge_rule(clock_a, add_a, rm_a, clock_b, add_b, rm_b, clock_merged):
    """The clock-filter merge on raw arrays (clocks already row-broadcast
    ready, ``clock_merged = max(clock_a, clock_b)`` supplied by the
    caller).  Single source of truth for the Orswot merge semantics —
    used by ``orset_merge`` AND the Pallas streaming kernel
    (ops/pallas_merge.py), which must never diverge."""
    same = add_a == add_b
    surv_a = jnp.where(same | (add_a > clock_b), add_a, 0)
    surv_b = jnp.where(same | (add_b > clock_a), add_b, 0)
    add = jnp.maximum(surv_a, surv_b)
    rm = jnp.maximum(rm_a, rm_b)
    add = jnp.where(add > rm, add, 0)
    rm = jnp.where(rm > clock_merged, rm, 0)
    return add, rm


@jax.jit
def orset_merge(
    clock_a: jax.Array,
    add_a: jax.Array,
    rm_a: jax.Array,
    clock_b: jax.Array,
    add_b: jax.Array,
    rm_b: jax.Array,
):
    """CvRDT merge of two dense ORSet states over the same (members,
    replicas) vocabularies.  Pure elementwise — the tombstone-free
    clock-filter rule (see crdt_enc_tpu/models/orset.py module docs)."""
    clock = jnp.maximum(clock_a, clock_b)
    add, rm = merge_rule(
        clock_a[None, :], add_a, rm_a, clock_b[None, :], add_b, rm_b,
        clock[None, :],
    )
    return clock, add, rm


@jax.jit
def _merge_halves(c1, a1, r1, c2, a2, r2):
    return jax.vmap(orset_merge)(c1, a1, r1, c2, a2, r2)


def orset_merge_many(
    clocks: jax.Array, adds: jax.Array, rms: jax.Array, impl: str | None = None
):
    """Merge a stacked batch of S states ``(S,R) / (S,E,R)`` into one.

    ``impl``: ``"tree"`` = ⌈log2 S⌉ rounds of the pairwise merge (XLA);
    ``"pallas"`` = single-HBM-pass streaming kernel (ops/pallas_merge.py);
    None = pallas on TPU for batches worth streaming, tree elsewhere.
    Merge associativity (tests/test_crdt_laws.py) makes any order legal.
    """
    # host-resident stacks upload here; device inputs re-wrap for free
    trace.add("h2d_bytes", sum(
        x.nbytes for x in (clocks, adds, rms) if isinstance(x, np.ndarray)
    ))
    c, a, r = jnp.asarray(clocks), jnp.asarray(adds), jnp.asarray(rms)
    if impl is None:
        on_tpu = jax.default_backend() == "tpu"
        impl = "pallas" if on_tpu and c.shape[0] >= 4 else "tree"
    if impl == "pallas":
        from .pallas_merge import orset_merge_many_pallas

        return orset_merge_many_pallas(
            c, a, r, interpret=jax.default_backend() != "tpu"
        )
    if impl != "tree":
        raise ValueError(f"unknown merge impl {impl!r}; use 'tree' or 'pallas'")
    while c.shape[0] > 1:
        s = c.shape[0]
        half = s // 2
        cm, am, rmm = _merge_halves(
            c[:half], a[:half], r[:half], c[half : 2 * half], a[half : 2 * half], r[half : 2 * half]
        )
        if s % 2:
            cm = jnp.concatenate([cm, c[-1:]])
            am = jnp.concatenate([am, a[-1:]])
            rmm = jnp.concatenate([rmm, r[-1:]])
        c, a, r = cm, am, rmm
    return c[0], a[0], r[0]

"""Batched native decode: decrypted op payloads → columnar arrays.

The bulk front end (SURVEY.md §7 step 6, §2.2 "decode op files directly
into pre-allocated arrays without Python-object churn"): each payload is
the msgpack body of one op file; the C++ decoder flattens every payload
into shared (kind, member-span, actor, counter) arrays, and member spans
are interned *vectorized* — grouped by span length, ``np.unique(axis=0)``
over byte matrices — so no per-row Python executes on the million-op path.

Returns None when a payload defeats the native decoder (unknown actor,
non-canonical encoding); callers fall back to the per-op Python path.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .. import native
from ..utils import codec

_i8p = ctypes.POINTER(ctypes.c_int8)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)


def decode_orset_payload_batch(payloads: list, actors_sorted: list):
    """Decode many ORSet op payloads against a sorted actor table.

    Returns ``(kind, member_idx, actor_idx, counter, members)`` — flat
    int arrays over all payloads' rows plus the interned member-object
    list (first-appearance order) — or None to request Python fallback.
    """
    part = decode_orset_payload_spans(payloads, actors_sorted)
    if part is None:
        return None
    return combine_orset_spans([part])


def _shared_buffer_of(payloads):
    """The single object every memoryview payload slices, or None.

    The batch decrypt hands out zero-copy views of one cleartext buffer
    (``decrypt_blobs``); spotting that here lets the decoder skip
    re-joining what is already contiguous memory."""
    first = payloads[0] if payloads else None
    if type(first) is not memoryview:
        return None
    obj = first.obj
    for p in payloads:
        if type(p) is not memoryview or p.obj is not obj or not p.contiguous:
            return None
    return obj


def decode_orset_payload_spans(payloads, actors_sorted: list, cache=None):
    """Native two-pass decode of one payload chunk to raw span columns.

    ``payloads`` is a list of blob bytes, or a packed ``(buffer,
    offsets)`` pair straight from ``decrypt_blobs_packed`` — the packed
    form skips materializing and re-joining per-blob Python objects (at
    100k-tiny-file scale that overhead dwarfed the decrypt itself).

    ``cache`` (optional dict the caller owns for the life of one actor
    table, e.g. a payload stream): reuses the flattened actor table and
    its native hash index across chunks — rebuilding both per chunk at
    100k actors costs more than the decode.

    Returns ``(buf, kind, moff, mlen, actor, counter)`` — member values
    stay as (offset, length) spans into ``buf`` so chunks decoded at
    different times can be combined and interned once
    (``combine_orset_spans``) — or None to request Python fallback.
    """
    lib = native.load()
    packed = isinstance(payloads, tuple)
    if packed:
        big, offs = payloads
        n_payloads = len(offs) - 1
    else:
        n_payloads = len(payloads)
    if n_payloads == 0:
        return (
            np.zeros(0, np.uint8),
            np.zeros(0, np.int8),
            np.zeros(0, np.uint64),
            np.zeros(0, np.uint64),
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
        )
    if packed:
        bases = offs[:-1].astype(np.uint64, copy=True)
        lens = np.diff(offs).astype(np.uint64)
    else:
        big = _shared_buffer_of(payloads)
        if big is not None:
            # every payload is a view into ONE buffer (the batch
            # decrypt's packed cleartext): address arithmetic recovers
            # the offsets — no join of the whole chunk
            lens = np.array([len(p) for p in payloads], np.uint64)
            base0 = np.frombuffer(big, np.uint8).ctypes.data
            bases = np.fromiter(
                (np.frombuffer(p, np.uint8).ctypes.data - base0
                 for p in payloads),
                np.uint64, count=n_payloads,
            )
        else:
            big = b"".join(payloads)
            lens = np.array([len(p) for p in payloads], np.uint64)
            bases = np.zeros(n_payloads, np.uint64)
            np.cumsum(lens[:-1], out=bases[1:])
    buf = np.frombuffer(big, np.uint8)
    bp = buf.ctypes.data_as(native.u8p)
    if cache is not None and "actors" in cache:
        actors_flat, slots = cache["actors"]
    else:
        actors_flat = b"".join(actors_sorted)
        # hash index over the actor table: one probe per op instead of a
        # 17-deep binary search at 100k actors (~2x the decode cost)
        n_slots = 8
        while n_slots < 2 * max(len(actors_sorted), 1):
            n_slots *= 2
        slots = np.empty(n_slots, np.int32)
        lib.actor_hash_build(
            native.in_ptr(actors_flat)[0], len(actors_sorted),
            slots.ctypes.data_as(_i32p), n_slots,
        )
        if cache is not None:
            cache["actors"] = (actors_flat, slots)
    ap, _a = native.in_ptr(actors_flat)
    basep = bases.ctypes.data_as(native.u64p)
    lenp = lens.ctypes.data_as(native.u64p)

    # single-pass growable decode: validates framing and emits rows in
    # one msgpack walk (the old count+decode protocol parsed everything
    # twice — ~half the decode cost at 100k-file scale)
    n_rows = np.zeros(1, np.int64)
    handle = lib.orset_decode_batch_grow(
        bp, basep, lenp, n_payloads, ap, len(actors_sorted),
        slots.ctypes.data_as(_i32p), len(slots),
        n_rows.ctypes.data_as(_i64p),
    )
    if not handle:
        return None
    taken = False
    try:
        total = int(n_rows[0])
        kind = np.zeros(total, np.int8)
        moff = np.zeros(total, np.uint64)
        mlen = np.zeros(total, np.uint64)
        actor = np.zeros(total, np.int32)
        counter = np.zeros(total, np.int32)
        taken = True  # take() frees the handle even if a copy would fail
        lib.orset_decode_take(
            handle,
            kind.ctypes.data_as(_i8p),
            moff.ctypes.data_as(native.u64p),
            mlen.ctypes.data_as(native.u64p),
            actor.ctypes.data_as(_i32p),
            counter.ctypes.data_as(_i32p),
        )
    finally:
        if not taken:  # e.g. MemoryError sizing the output arrays
            lib.orset_decode_drop(handle)
    return buf, kind, moff, mlen, actor, counter


def combine_orset_spans(parts: list, *, with_bytes: bool = False):
    """Concatenate span chunks from ``decode_orset_payload_spans`` and
    intern the member spans once.  Returns the same tuple as
    ``decode_orset_payload_batch``; with ``with_bytes`` a sixth element
    carries each unique member's WIRE bytes (the interning key), so a
    session-level remap can recognize an already-seen member with one
    bytes-dict hit instead of an object intern + canonical re-pack per
    chunk."""
    if not parts:
        kind = np.zeros(0, np.int8)
        actor = counter = np.zeros(0, np.int32)
        if with_bytes:
            return kind, np.zeros(0, np.int32), actor, counter, [], []
        return kind, np.zeros(0, np.int32), actor, counter, []
    if len(parts) == 1:
        buf, kind, moff, mlen, actor, counter = parts[0]
    else:
        bufs = [p[0] for p in parts]
        base = np.zeros(len(bufs), np.uint64)
        np.cumsum([len(b) for b in bufs[:-1]], out=base[1:])
        buf = np.concatenate(bufs) if bufs else np.zeros(0, np.uint8)
        kind = np.concatenate([p[1] for p in parts])
        moff = np.concatenate([p[2] + b for p, b in zip(parts, base)])
        mlen = np.concatenate([p[3] for p in parts])
        actor = np.concatenate([p[4] for p in parts])
        counter = np.concatenate([p[5] for p in parts])
    if len(kind) == 0:
        if with_bytes:
            return kind, np.zeros(0, np.int32), actor, counter, [], []
        return kind, np.zeros(0, np.int32), actor, counter, []
    if with_bytes:
        member_idx, members, member_bytes = intern_spans(
            buf, moff, mlen, return_bytes=True
        )
        return kind, member_idx, actor, counter, members, member_bytes
    member_idx, members = intern_spans(buf, moff, mlen)
    return kind, member_idx, actor, counter, members


def intern_spans(buf: np.ndarray, off: np.ndarray, length: np.ndarray,
                 *, return_bytes: bool = False):
    """Span interning: rows → dense member indices + decoded unique member
    objects.  The native open-addressing hash pass costs one linear scan
    (the numpy fallback below sorts 8 bytes per row — measured ~8× slower
    at the 8M-row e2e scale); unique spans then decode via codec, a few
    thousand objects at most.  ``return_bytes`` adds the unique spans'
    raw wire bytes as a third element (one small ``bytes`` per unique
    member — the caller's cross-chunk dedup key)."""
    n = len(off)
    if n == 0:
        if return_bytes:
            return np.zeros(0, np.int32), [], []
        return np.zeros(0, np.int32), []
    if (np.asarray(length) == 0).any():
        raise ValueError("empty member span")
    try:
        lib = native.load()
        off64 = np.ascontiguousarray(off, np.uint64)
        len64 = np.ascontiguousarray(length, np.uint64)
        cap = 1 << max(11, (2 * n - 1).bit_length())
        table = np.full(cap, -1, np.int64)
        idx = np.zeros(n, np.int32)
        uniq_off = np.zeros(n, np.uint64)
        uniq_len = np.zeros(n, np.uint64)
        bp = buf.ctypes.data_as(native.u8p)
        got = lib.intern_spans_native(
            bp, off64.ctypes.data_as(native.u64p),
            len64.ctypes.data_as(native.u64p), n,
            table.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap,
            idx.ctypes.data_as(_i32p),
            uniq_off.ctypes.data_as(native.u64p),
            uniq_len.ctypes.data_as(native.u64p), n,
        )
    except RuntimeError:  # native lib unavailable
        got = -1
    if got >= 0:
        if return_bytes:
            # bytes-only mode: do NOT decode the unique spans — the
            # session remap recognizes seen spans by bytes and decodes
            # only genuinely new members (codec.unpack per distinct
            # member per STREAM, not per chunk — measured ~10ms of the
            # config-5 wall as pure re-decode of already-known members)
            mv = memoryview(np.ascontiguousarray(buf))
            spans = [
                bytes(mv[int(o) : int(o) + int(ln)])
                for o, ln in zip(
                    uniq_off[:got].tolist(), uniq_len[:got].tolist()
                )
            ]
            return idx, None, spans
        mv = memoryview(np.ascontiguousarray(buf))
        members = [
            codec.unpack(mv[int(o) : int(o) + int(ln)])
            for o, ln in zip(uniq_off[:got].tolist(), uniq_len[:got].tolist())
        ]
        return idx, members
    if return_bytes:
        idx, members, spans = _intern_spans_numpy(
            buf, off, length, return_bytes=True
        )
        return idx, members, spans
    return _intern_spans_numpy(buf, off, length)


def _intern_spans_numpy(buf: np.ndarray, off: np.ndarray, length: np.ndarray,
                        *, return_bytes: bool = False):
    """Vectorized fallback: groups rows by span length; spans of ≤ 8 bytes
    (the overwhelmingly common case — small ints, short bytes) pack into
    uint64 so ``np.unique`` sorts scalars (~10× faster than the byte-matrix
    ``axis=0`` path, which argsorts rows); longer spans take the matrix
    path."""
    n = len(off)
    member_idx = np.zeros(n, np.int32)
    members: list = []
    spans: list = []
    off = off.astype(np.int64)
    length = length.astype(np.int64)
    for L in np.unique(length):
        Li = int(L)
        sel = np.flatnonzero(length == L)
        if Li == 0:
            # zero-length span cannot be valid msgpack; caller's decoder
            # never emits it, but guard anyway
            raise ValueError("empty member span")
        # gather rows × L bytes in one fancy index
        mat = buf[off[sel][:, None] + np.arange(Li)[None, :]]
        base = len(members)
        if Li <= 8:
            # pack the L bytes big-endian into one uint64 per row (same
            # order as byte-wise comparison, so unique order matches)
            packed = np.zeros(len(sel), np.uint64)
            for b in range(Li):
                packed = (packed << np.uint64(8)) | mat[:, b].astype(np.uint64)
            uniq, inv = np.unique(packed, return_inverse=True)
            for u in uniq:
                raw = int(u).to_bytes(Li, "big")
                members.append(codec.unpack(raw))
                spans.append(raw)
        else:
            uniq, inv = np.unique(mat, axis=0, return_inverse=True)
            for u in uniq:
                raw = u.tobytes()
                members.append(codec.unpack(raw))
                spans.append(raw)
        member_idx[sel] = base + inv.astype(np.int32)
    if return_bytes:
        return member_idx, members, spans
    return member_idx, members


def decode_counter_payload_batch(payloads: list, actors_sorted: list):
    """Decode many counter op payloads.  Returns ``(sign, actor_idx,
    counter)`` flat arrays or None for Python fallback."""
    lib = native.load()
    if not payloads:
        return np.zeros(0, np.int8), np.zeros(0, np.int32), np.zeros(0, np.int32)
    big = b"".join(payloads)
    buf = np.frombuffer(big, np.uint8)
    actors_flat = b"".join(actors_sorted)
    ap, _a = native.in_ptr(actors_flat)

    lens = np.array([len(p) for p in payloads], np.uint64)
    bases = np.zeros(len(payloads), np.uint64)
    np.cumsum(lens[:-1], out=bases[1:])

    # one native call; every op costs >1 encoded byte, so total payload
    # bytes bounds the row count
    cap = max(len(big), 1)
    sign = np.zeros(cap, np.int8)
    actor = np.zeros(cap, np.int32)
    counter = np.zeros(cap, np.int32)
    got = lib.counter_decode_batch(
        buf.ctypes.data_as(native.u8p),
        bases.ctypes.data_as(native.u64p),
        lens.ctypes.data_as(native.u64p),
        len(payloads),
        ap,
        len(actors_sorted),
        sign.ctypes.data_as(_i8p),
        actor.ctypes.data_as(_i32p),
        counter.ctypes.data_as(_i32p),
    )
    if got < 0:
        return None
    return sign[:got], actor[:got], counter[:got]

"""Batched native decode: decrypted op payloads → columnar arrays.

The bulk front end (SURVEY.md §7 step 6, §2.2 "decode op files directly
into pre-allocated arrays without Python-object churn"): each payload is
the msgpack body of one op file; the C++ decoder flattens every payload
into shared (kind, member-span, actor, counter) arrays, and member spans
are interned *vectorized* — grouped by span length, ``np.unique(axis=0)``
over byte matrices — so no per-row Python executes on the million-op path.

Returns None when a payload defeats the native decoder (unknown actor,
non-canonical encoding); callers fall back to the per-op Python path.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .. import native
from ..utils import codec

_i8p = ctypes.POINTER(ctypes.c_int8)
_i32p = ctypes.POINTER(ctypes.c_int32)


def decode_orset_payload_batch(payloads: list, actors_sorted: list):
    """Decode many ORSet op payloads against a sorted actor table.

    Returns ``(kind, member_idx, actor_idx, counter, members)`` — flat
    int arrays over all payloads' rows plus the interned member-object
    list (first-appearance order) — or None to request Python fallback.
    """
    lib = native.load()
    if not payloads:
        return (
            np.zeros(0, np.int8),
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
            [],
        )
    big = b"".join(payloads)
    buf = np.frombuffer(big, np.uint8)
    bp = buf.ctypes.data_as(native.u8p)
    actors_flat = b"".join(actors_sorted)
    ap, _a = native.in_ptr(actors_flat)

    # pass 1: row counts (also validates framing)
    bases = np.zeros(len(payloads) + 1, np.int64)
    counts = np.zeros(len(payloads), np.int64)
    off = 0
    for i, p in enumerate(payloads):
        bases[i] = off
        n = lib.orset_count_rows(
            buf[off:].ctypes.data_as(native.u8p), len(p)
        )
        if n < 0:
            return None
        counts[i] = n
        off += len(p)
    bases[len(payloads)] = off
    total = int(counts.sum())
    if total == 0:
        return (
            np.zeros(0, np.int8),
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
            [],
        )

    kind = np.zeros(total, np.int8)
    moff = np.zeros(total, np.uint64)
    mlen = np.zeros(total, np.uint64)
    actor = np.zeros(total, np.int32)
    counter = np.zeros(total, np.int32)

    # pass 2: decode each payload into its row slice
    row = 0
    for i, p in enumerate(payloads):
        n = int(counts[i])
        if n == 0:
            continue
        got = lib.orset_decode(
            buf[int(bases[i]) :].ctypes.data_as(native.u8p),
            len(p),
            ap,
            len(actors_sorted),
            kind[row:].ctypes.data_as(_i8p),
            moff[row:].ctypes.data_as(native.u64p),
            mlen[row:].ctypes.data_as(native.u64p),
            actor[row:].ctypes.data_as(_i32p),
            counter[row:].ctypes.data_as(_i32p),
        )
        if got != n:
            return None
        moff[row : row + n] += np.uint64(bases[i])
        row += n

    member_idx, members = intern_spans(buf, moff, mlen)
    return kind, member_idx, actor, counter, members


def intern_spans(buf: np.ndarray, off: np.ndarray, length: np.ndarray):
    """Vectorized span interning: rows → dense member indices + decoded
    unique member objects.  Groups rows by span length; within a group the
    spans become an (n, L) byte matrix and ``np.unique`` assigns ids."""
    n = len(off)
    member_idx = np.zeros(n, np.int32)
    members: list = []
    off = off.astype(np.int64)
    length = length.astype(np.int64)
    for L in np.unique(length):
        Li = int(L)
        sel = np.flatnonzero(length == L)
        if Li == 0:
            # zero-length span cannot be valid msgpack; caller's decoder
            # never emits it, but guard anyway
            raise ValueError("empty member span")
        # gather rows × L bytes in one fancy index
        mat = buf[off[sel][:, None] + np.arange(Li)[None, :]]
        uniq, inv = np.unique(mat, axis=0, return_inverse=True)
        base = len(members)
        for u in uniq:
            members.append(codec.unpack(u.tobytes()))
        member_idx[sel] = base + inv.astype(np.int32)
    return member_idx, members


def decode_counter_payload_batch(payloads: list, actors_sorted: list):
    """Decode many counter op payloads.  Returns ``(sign, actor_idx,
    counter)`` flat arrays or None for Python fallback."""
    lib = native.load()
    if not payloads:
        return np.zeros(0, np.int8), np.zeros(0, np.int32), np.zeros(0, np.int32)
    big = b"".join(payloads)
    buf = np.frombuffer(big, np.uint8)
    actors_flat = b"".join(actors_sorted)
    ap, _a = native.in_ptr(actors_flat)

    signs, actors, counters = [], [], []
    off = 0
    for p in payloads:
        # counter payloads are op arrays: rows == top-level array length,
        # obtained by decoding directly (counter_decode validates fully)
        cap = max(len(p), 1)  # rows ≤ payload bytes
        sign = np.zeros(cap, np.int8)
        actor = np.zeros(cap, np.int32)
        counter = np.zeros(cap, np.int32)
        got = lib.counter_decode(
            buf[off:].ctypes.data_as(native.u8p),
            len(p),
            ap,
            len(actors_sorted),
            sign.ctypes.data_as(_i8p),
            actor.ctypes.data_as(_i32p),
            counter.ctypes.data_as(_i32p),
        )
        if got < 0:
            return None
        signs.append(sign[:got])
        actors.append(actor[:got])
        counters.append(counter[:got])
        off += len(p)
    return (
        np.concatenate(signs),
        np.concatenate(actors),
        np.concatenate(counters),
    )

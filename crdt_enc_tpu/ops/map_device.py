"""Device (jit) scatter phase for the causal reset-remove map fold.

``ops/map_columnar.py`` decomposes a CrdtMap<orset> op batch into four
row families folded over two plane sets — key planes ``(NK, R)`` and
touched-pair planes ``(NP, R)``.  Its scatter phase is masked
scatter-max / segment-min work structurally identical to the ORSet
kernel (``ops/orset.py``), so this module jits it with the same
conventions: int32 planes, 0 = absent, sentinel ``actor == R`` padding
rows, bucket-padded static shapes.

The host numpy phase in map_columnar stays the semantics reference; the
wrapper here is routed by ``TpuAccelerator._fold_map_payloads`` for
device-worthy batches and fuzz-checked equal in
tests/test_map_columnar.py.

Reference analogue: the composite-CRDT merge discipline of
crdt-enc/src/key_cryptor.rs:35-52 (MVReg+Orswot `Keys`), generalized to
the crdts-crate Map's reset-remove semantics.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(
    jax.jit,
    static_argnames=(
        "num_keys", "num_pairs", "num_replicas", "num_groups", "axis_name",
    ),
)
def crdtmap_scatter_phase(
    clock0,  # (R,) int32
    births0,  # (NK, R) int32
    cclk0,  # (NK, R) int32
    cadd0,  # (NP, R) int32
    crm0,  # (NP, R) int32
    key_of_pair,  # (NP,) int32
    b_key, b_actor, b_ctr,  # births (Up dots); actor == R ⇒ padding
    k_key, k_actor, k_ctr, k_group,  # key-remove horizon rows
    a_key, a_pair, a_actor, a_ctr,  # child adds (shared map dot)
    r_pair, r_actor, r_ctr, r_mactor, r_mctr,  # child-remove horizons
    *,
    num_keys: int,
    num_pairs: int,
    num_replicas: int,
    num_groups: int,
    axis_name: str | None = None,
):
    """The batch scatter-maxes + normalization of ``crdtmap_fold_host``
    (map_columnar.py), one jitted program.  Returns
    ``(clock, births, cclk, cadd, crm, group_ok)`` with the same
    values the host numpy phase computes (int32).

    ``axis_name``: when set, the caller runs this body under
    ``shard_map`` with the ROW families sharded over that axis and the
    planes replicated; each scatter's partial result combines across the
    axis with ``pmax`` (``pmin`` for the remove-group applicability),
    after which the replicated normalization is identical on every
    device — the same partial-fold/​combine shape as the sharded ORSet
    fold (mesh.py)."""
    NK, NP, R = num_keys, num_pairs, num_replicas

    def smax(shape_cells, rows_seg, rows_c, gate):
        vals = jnp.where(gate, rows_c, 0)
        z = jnp.zeros((shape_cells,), jnp.int32)
        out = z.at[rows_seg].max(vals, mode="drop")
        if axis_name is not None:
            out = jax.lax.pmax(out, axis_name)
        return out

    def seg(key_col, actor_col):
        a_ix = jnp.minimum(actor_col, R - 1)
        return key_col * R + a_ix

    b_pad = b_actor >= R
    k_pad = k_actor >= R
    a_pad = a_actor >= R
    r_pad = r_actor >= R

    # 1. every Up advances the clock (ungated birth scatter)
    birth_new = smax(NK * R, seg(b_key, b_actor), b_ctr, ~b_pad).reshape(NK, R)
    clock = jnp.maximum(clock0, jnp.max(birth_new, axis=0, initial=0))

    # 2. fire-or-defer per WHOLE remove: segment-min over each remove
    #    group of "the final clock covers this ctx dot"
    k_actor_ix = jnp.minimum(k_actor, R - 1)
    beyond = (k_ctr > clock[k_actor_ix]) & ~k_pad
    g_ix = jnp.where(k_pad, num_groups, k_group)
    ok_i = jnp.ones((num_groups,), jnp.int32).at[g_ix].min(
        jnp.where(beyond, 0, 1), mode="drop"
    )
    if axis_name is not None:
        ok_i = jax.lax.pmin(ok_i, axis_name)
    group_ok = ok_i.astype(bool)
    applicable = group_ok[jnp.minimum(k_group, num_groups - 1)] & ~k_pad \
        if num_groups else jnp.zeros_like(k_pad)

    # 3. fired key-remove horizons
    keyhz = smax(
        NK * R, seg(k_key, k_actor), k_ctr, applicable
    ).reshape(NK, R)

    # 4. births: replay-gated on the ORIGINAL clock, reset by horizons
    b_gate = ~b_pad & (b_ctr > clock0[jnp.minimum(b_actor, R - 1)])
    births = jnp.maximum(
        births0, smax(NK * R, seg(b_key, b_actor), b_ctr, b_gate).reshape(NK, R)
    )
    births = jnp.where(births > keyhz, births, 0)

    # 5. child clocks advance on child ADDS only; fired removes reset them
    a_gate = ~a_pad & (a_ctr > clock0[jnp.minimum(a_actor, R - 1)])
    cclk = jnp.maximum(
        cclk0, smax(NK * R, seg(a_key, a_actor), a_ctr, a_gate).reshape(NK, R)
    )
    cclk = jnp.where(cclk > keyhz, cclk, 0)

    # 6. child entries (pair planes), same replay gate
    cadd = jnp.maximum(
        cadd0, smax(NP * R, seg(a_pair, a_actor), a_ctr, a_gate).reshape(NP, R)
    )

    # 7. child-remove horizons apply with their Up (gated on the MAP dot)
    live_up = ~r_pad & (r_mctr > clock0[jnp.minimum(r_mactor, R - 1)])
    crm = jnp.maximum(
        crm0, smax(NP * R, seg(r_pair, r_actor), r_ctr, live_up).reshape(NP, R)
    )

    # 8. normalization: fired key horizons kill covered child content;
    #    the MAP clock retires child horizons
    hz_of_pair = keyhz[jnp.minimum(key_of_pair, NK - 1)]
    eff_rm = jnp.maximum(crm, hz_of_pair)
    cadd = jnp.where(cadd > eff_rm, cadd, 0)
    crm = jnp.where(crm > hz_of_pair, crm, 0)
    crm = jnp.where(crm > clock[None, :], crm, 0)
    return clock, births, cclk, cadd, crm, group_ok


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def _pad_rows(arrs, n_to, fills):
    out = []
    for a, fill in zip(arrs, fills):
        a = np.asarray(a, np.int32)
        padn = n_to - len(a)
        out.append(np.concatenate([a, np.full(padn, fill, np.int32)])
                   if padn else a)
    return out


def crdtmap_scatter_device(
    clock0, births0, cclk0, cadd0, crm0, key_of_pair, B, A, Rm, K,
    n_groups: int,
    mesh=None,
):
    """Bucket-pad the planes/rows (bounded recompiles) and run the jitted
    scatter phase — single-device, or SPMD over ``mesh`` (rows sharded
    dp, planes replicated; parallel/mesh.crdtmap_scatter_sharded).
    Inputs are the host fold's numpy planes (any integer dtype that fits
    int32) and the four decoded row-family dicts; returns int64 planes +
    group_ok, shaped exactly as the host phase's."""
    NK, R = births0.shape
    NP = cadd0.shape[0] if cadd0.size else 0
    NKp, NPp = _bucket(max(NK, 1)), _bucket(max(NP, 1))
    Rp = _bucket(R)
    clock0p = np.zeros(Rp, np.int32)
    clock0p[:R] = clock0
    def pad2(p, nk):
        p = np.asarray(p, np.int32)
        out = np.zeros((nk, Rp), np.int32)
        if p.size:
            out[: p.shape[0], :R] = p
        return out

    births0p = pad2(births0, NKp)
    cclk0p = pad2(cclk0, NKp)
    cadd0p = pad2(cadd0, NPp)
    crm0p = pad2(crm0, NPp)
    kop = np.zeros(NPp, np.int32)
    if NP:
        kop[:NP] = key_of_pair

    dp = mesh.shape["dp"] if mesh is not None else 1

    def rows(d, names, fills, n):
        nb = _bucket(max(n, 1), floor=8)
        nb = -(-nb // dp) * dp
        return _pad_rows([d[x] for x in names], nb, fills)

    b_rows = rows(B, ("key", "actor", "ctr"), (0, Rp, 0), len(B["actor"]))
    k_rows = rows(
        K, ("key", "actor", "ctr", "group"), (0, Rp, 0, 0), len(K["actor"])
    )
    a_rows = rows(
        A, ("key", "pair", "actor", "ctr"), (0, 0, Rp, 0), len(A["actor"])
    )
    r_rows = rows(
        Rm, ("pair", "actor", "ctr", "mactor", "mctr"), (0, Rp, 0, Rp, 0),
        len(Rm["actor"]),
    )
    ngp = max(_bucket(max(n_groups, 1), floor=1), 1)
    if mesh is not None and mesh.size > 1:
        from ..parallel import mesh as pmesh

        out = pmesh.crdtmap_scatter_sharded(
            mesh, clock0p, births0p, cclk0p, cadd0p, crm0p, kop,
            b_rows, k_rows, a_rows, r_rows, num_groups=ngp,
        )
    else:
        out = crdtmap_scatter_phase(
            clock0p, births0p, cclk0p, cadd0p, crm0p, kop,
            *b_rows, *k_rows, *a_rows, *r_rows,
            num_keys=NKp, num_pairs=NPp, num_replicas=Rp, num_groups=ngp,
        )
    clock, births, cclk, cadd, crm, group_ok = (np.asarray(x) for x in out)
    return (
        clock[:R].astype(np.int64),
        births[:NK, :R].astype(np.int64),
        cclk[:NK, :R].astype(np.int64),
        cadd[:NP, :R].astype(np.int64),
        crm[:NP, :R].astype(np.int64),
        group_ok[:n_groups].astype(bool) if n_groups else group_ok[:0].astype(bool),
    )

"""Device-side op decode — the ``CRDT_DEVICE_DECODE=1`` experiment.

ROADMAP item 1 asks whether the op-file decode belongs ON DEVICE: after
bulk AEAD the cleartext is a dense byte stream whose canonical
msgpack-subset framing is *fixed-width* for the overwhelmingly common
op shape, so the field extraction is pure strided gather + integer
bit-twiddling — exactly what an accelerator does at memory bandwidth,
and it would let the decode ride under the fold like the H2D transfers
already do.

Scope: the **fixed-stride add op** — the canonical encoding of
``[KIND_ADD, member, [actor16, counter]]`` with a positive-fixint
member and counter::

    0x93 0x00 <member> 0x92 0xc4 0x10 <actor · 16 bytes> <counter>

i.e. 23 bytes per op, preceded per payload by the canonical array
header (fixarray or array16).  A chunk qualifies only when EVERY
payload is a pure run of such ops (host-side vectorized validation —
one strided numpy pass, no Python per op); anything else returns None
and the caller uses the native host decoder.  Removes, wide counters,
and non-fixint members are deliberately out of scope: the experiment
measures the best case for the device, and the host decoder keeps the
general case.

The device kernel (:func:`decode_adds_device`) uploads the cleartext
buffer once (h2d accounted), gathers member/counter bytes and the
16-byte actor as two big-endian u64 lanes with ``jnp.take``, and pulls
the four small result columns back.  Actor-lane → table-index
resolution stays host-side (a 128-bit searchsorted has no single-array
device form); it is vectorized numpy over the sorted actor table.

**Honest verdict** (bench.py ``--device-decode``, this box: CPU backend,
1 core — "device" is the same silicon): the gather kernel pays dispatch
+ transfer and loses to the native C walk ~4.8× at the 200k-op shape
(the committed BENCH_LOCAL record).  The experiment stays committed
behind the env flag
as the measurement harness for a real TPU round, where the transfer
already happens (the fold needs the rows on device) and the gathers are
HBM-bandwidth work; docs/streaming_pipeline.md records the numbers.
"""

from __future__ import annotations

import os

import numpy as np

from ..utils import trace

#: fixed-stride add-op width (bytes) — module docstring layout
OP_STRIDE = 23


def device_decode_enabled() -> bool:
    return os.environ.get("CRDT_DEVICE_DECODE", "") == "1"


def _op_bases(buf: np.ndarray, offs: np.ndarray):
    """Per-op base offsets for a packed payload buffer, or None when any
    payload is not a pure fixed-stride add run.  Vectorized: header
    classification, length validation, and the constant-byte checks all
    run as strided numpy passes."""
    n_payloads = len(offs) - 1
    if n_payloads == 0 or len(buf) == 0:
        return None
    starts = offs[:-1].astype(np.int64)
    lens = np.diff(offs).astype(np.int64)
    if (lens < 1).any():
        return None
    if len(buf) > 2**31 - 1:
        return None  # the device gather indexes with int32 lanes
    first = buf[starts]
    fix = (first & 0xF0) == 0x90
    a16 = first == 0xDC
    if not (fix | a16).all():
        return None
    hdr = np.where(fix, 1, 3)
    if (lens < hdr).any():
        return None
    counts = np.where(fix, first & 0x0F, 0).astype(np.int64)
    if a16.any():
        i = starts[a16]
        if (i + 2 >= offs[-1]).any():
            return None
        counts[a16] = (
            buf[i + 1].astype(np.int64) << 8
        ) | buf[i + 2].astype(np.int64)
    if (lens != hdr + OP_STRIDE * counts).any():
        return None
    total = int(counts.sum())
    if total == 0:
        return None
    # grouped arange: base[i] = payload_start + hdr + 23 * (op index
    # within payload), flattened across payloads in one cumsum trick
    op_starts = np.repeat(starts + hdr, counts)
    ends = np.cumsum(counts)
    within = np.arange(total, dtype=np.int64) - np.repeat(
        ends - counts, counts
    )
    base = op_starts + OP_STRIDE * within
    # constant-byte + fixint validation, one gather each
    if (buf[base] != 0x93).any() or (buf[base + 1] != 0x00).any():
        return None
    if (buf[base + 3] != 0x92).any() or (buf[base + 4] != 0xC4).any():
        return None
    if (buf[base + 5] != 0x10).any():
        return None
    if (buf[base + 2] > 0x7F).any() or (buf[base + 22] > 0x7F).any():
        return None
    return base


def _resolve_actors(hi: np.ndarray, lo: np.ndarray, actors_sorted: list):
    """Rows' (hi, lo) big-endian actor lanes → indices into the sorted
    16-byte actor table, or None when any actor is unknown.  Vectorized
    two-stage searchsorted (hi first, lo refines the rare hi-collision
    runs)."""
    R = len(actors_sorted)
    if R == 0:
        return None
    try:
        table = np.frombuffer(
            b"".join(actors_sorted), np.uint8
        ).reshape(R, 16)
    except (TypeError, ValueError):
        # non-bytes or non-16-byte actor ids in the table: this corpus
        # cannot resolve here — decline to the host decoder (the
        # module's contract), never crash the fold
        return None
    w = (256 ** np.arange(7, -1, -1, dtype=np.uint64)).astype(np.uint64)
    t_hi = (table[:, :8].astype(np.uint64) * w).sum(axis=1, dtype=np.uint64)
    t_lo = (table[:, 8:].astype(np.uint64) * w).sum(axis=1, dtype=np.uint64)
    idx = np.searchsorted(t_hi, hi)
    if (idx >= R).any():
        return None
    ok = t_hi[idx] == hi
    if not ok.all():
        return None
    exact = t_lo[idx] == lo
    if not exact.all():
        # hi collision (distinct actors sharing 8 leading bytes): walk
        # the tied run per affected row — rare by construction (uuid4)
        bad = np.flatnonzero(~exact)
        for r in bad.tolist():
            j = int(idx[r])
            while j < R and t_hi[j] == hi[r] and t_lo[j] != lo[r]:
                j += 1
            if j >= R or t_hi[j] != hi[r] or t_lo[j] != lo[r]:
                return None
            idx[r] = j
    return idx.astype(np.int32)


def decode_adds_device(packed, actors_sorted: list):
    """Decode a packed cleartext chunk of fixed-stride add ops on
    device.  ``packed`` is the ``(buffer, offsets)`` pair the batch
    decrypt emits.  Returns the 6-tuple the fold-session remap consumes
    — ``(kind, member_idx, actor_idx, counter, members, member_bytes)``
    — or None when the chunk does not qualify (caller falls back to the
    native host decoder; this is the expected path for anything but the
    all-adds benchmark corpus)."""
    buf, offs = packed
    buf = np.frombuffer(buf, np.uint8) if not isinstance(
        buf, np.ndarray
    ) else buf
    base = _op_bases(buf, np.asarray(offs))
    if base is None:
        return None
    import jax
    import jax.numpy as jnp

    with trace.span("device.decode"):
        # one upload of the cleartext + the gather index column; h2d
        # accounted at issue (the fold would re-upload rows anyway — on
        # a real TPU this transfer replaces that one)
        trace.add("h2d_bytes", buf.nbytes + base.nbytes)
        dbuf = jax.device_put(buf)
        dbase = jax.device_put(base.astype(np.int32))
        member = jnp.take(dbuf, dbase + 2).astype(jnp.int32)
        counter = jnp.take(dbuf, dbase + 22).astype(jnp.int32)
        # the 16 actor bytes fold to FOUR big-endian u32 words on
        # device (default jax has no 64-bit lanes — uint64 would
        # silently truncate); the host pairs them into (hi, lo) u64
        actor_mat = jnp.take(
            dbuf, dbase[:, None] + (6 + jnp.arange(16))[None, :]
        ).astype(jnp.uint32)
        w4 = jnp.asarray(  # lint: disable=OBS001 — 4 constant words
            [1 << 24, 1 << 16, 1 << 8, 1], jnp.uint32
        )
        words = (
            actor_mat.reshape(-1, 4, 4) * w4[None, None, :]
        ).sum(axis=2, dtype=jnp.uint32)
        member, counter, words = (
            np.asarray(member), np.asarray(counter), np.asarray(words),
        )
    w64 = words.astype(np.uint64)
    hi = (w64[:, 0] << np.uint64(32)) | w64[:, 1]
    lo = (w64[:, 2] << np.uint64(32)) | w64[:, 3]
    actor_idx = _resolve_actors(hi, lo, actors_sorted)
    if actor_idx is None:
        return None
    uniq, member_idx = np.unique(member, return_inverse=True)
    members = [int(v) for v in uniq.tolist()]
    member_bytes = [bytes([v]) for v in uniq.tolist()]
    kind = np.zeros(len(base), np.int8)
    return (
        kind, member_idx.astype(np.int32), actor_idx,
        counter.astype(np.int32), members, member_bytes,
    )


def decode_adds_host(packed, actors_sorted: list):
    """The same fixed-stride extraction with numpy on host — the
    experiment's control arm: identical eligibility, identical output,
    no device round-trip.  (The PRODUCT host path is the native C
    decoder in ops/native_decode.py, which also handles the general
    framing; this exists so the bench isolates "where does the gather
    run" from "who parses msgpack".)"""
    buf, offs = packed
    buf = np.frombuffer(buf, np.uint8) if not isinstance(
        buf, np.ndarray
    ) else buf
    base = _op_bases(buf, np.asarray(offs))
    if base is None:
        return None
    member = buf[base + 2].astype(np.int32)
    counter = buf[base + 22].astype(np.int32)
    w = (256 ** np.arange(7, -1, -1, dtype=np.uint64)).astype(np.uint64)
    actor_mat = buf[base[:, None] + (6 + np.arange(16))[None, :]]
    hi = (actor_mat[:, :8].astype(np.uint64) * w).sum(
        axis=1, dtype=np.uint64
    )
    lo = (actor_mat[:, 8:].astype(np.uint64) * w).sum(
        axis=1, dtype=np.uint64
    )
    actor_idx = _resolve_actors(hi, lo, actors_sorted)
    if actor_idx is None:
        return None
    uniq, member_idx = np.unique(member, return_inverse=True)
    members = [int(v) for v in uniq.tolist()]
    member_bytes = [bytes([v]) for v in uniq.tolist()]
    kind = np.zeros(len(base), np.int8)
    return (
        kind, member_idx.astype(np.int32), actor_idx,
        counter.astype(np.int32), members, member_bytes,
    )

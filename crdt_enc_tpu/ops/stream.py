"""Chunked (bounded-memory) folds for op streams larger than device memory.

The long-context story (SURVEY.md §2.3): a replica's op log is the
framework's "sequence", and because the fold is associative the log can be
folded blockwise — the same trick ring attention uses for its associative
accumulator.  A 100M-op compaction therefore never materializes the whole
batch on device: fixed-size chunks stream through one compiled fold whose
state planes are **donated** (`jax.jit(donate_argnums=...)`), so XLA reuses
the plane buffers in place and device memory stays at
``one chunk + one set of planes`` regardless of stream length.

Exactness: chunked ≡ whole-batch under the causal-delivery contract the
core guarantees (per-actor op files apply in version order, core.py
``_read_remote_ops``) — each chunk's stale-dot filter then sees a clock
that only ever rejects true replays.  The per-op host loop is precisely
the chunk-size-1 instance of this fold, so the existing host-equality
tests pin the semantics at both extremes.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np

from .orset import orset_fold


@partial(
    jax.jit,
    static_argnames=(
        "num_members", "num_replicas", "impl", "small_counters", "retire_rm",
    ),
    donate_argnums=(0, 1, 2),
)
def _fold_donated(
    clock, add, rm, kind, member, actor, counter,
    *, num_members, num_replicas, impl, small_counters, retire_rm=True,
):
    return orset_fold(
        clock, add, rm, kind, member, actor, counter,
        num_members=num_members, num_replicas=num_replicas,
        impl=impl, small_counters=small_counters, retire_rm=retire_rm,
    )


@partial(
    jax.jit,
    static_argnames=("num_members", "num_replicas", "tile_cap", "interpret",
                     "retire_rm"),
    donate_argnums=(0, 1, 2),
)
def _fold_donated_pallas(
    clock, add, rm, kind, member, actor, counter,
    *, num_members, num_replicas, tile_cap, interpret, retire_rm=True,
):
    from .pallas_fold import orset_fold_pallas

    return orset_fold_pallas(
        clock, add, rm, kind, member, actor, counter,
        num_members=num_members, num_replicas=num_replicas,
        tile_cap=tile_cap, interpret=interpret, retire_rm=retire_rm,
    )


def iter_orset_chunks(kind, member, actor, counter, chunk_rows: int, num_replicas: int):
    """Slice flat op columns into fixed-shape chunks (the tail is padded
    with ``actor == num_replicas`` sentinel rows, which every kernel
    masks out) — one shape ⇒ one compilation for the whole stream."""
    n = len(kind)
    for lo in range(0, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        pad = chunk_rows - (hi - lo)
        k = np.asarray(kind[lo:hi], np.int8)
        m = np.asarray(member[lo:hi], np.int32)
        a = np.asarray(actor[lo:hi], np.int32)
        c = np.asarray(counter[lo:hi], np.int32)
        if pad:
            k = np.concatenate([k, np.zeros(pad, np.int8)])
            m = np.concatenate([m, np.zeros(pad, np.int32)])
            a = np.concatenate([a, np.full(pad, num_replicas, np.int32)])
            c = np.concatenate([c, np.zeros(pad, np.int32)])
        yield k, m, a, c


def orset_fold_stream(
    clock0,
    add0,
    rm0,
    chunks,
    *,
    num_members: int,
    num_replicas: int,
    impl: str = "fused",
    small_counters: bool = False,
    tile_cap: int | None = None,
):
    """Fold an iterable of fixed-shape op chunks into the state planes.

    ``chunks`` yields ``(kind, member, actor, counter)`` tuples of one
    common row count (see :func:`iter_orset_chunks`).  Returns the folded
    ``(clock, add, rm)`` device arrays.  The planes are donated between
    chunks — do not reuse the input arrays after calling.

    ``impl="pallas"`` runs each chunk through the MXU fold
    (ops/pallas_fold.py); pass ``tile_cap`` computed over the WHOLE
    member column (``fold_cap``) so every chunk compiles once — a
    per-chunk cap is bounded by the global one.
    """
    clock = jax.device_put(np.asarray(clock0, np.int32))
    add = jax.device_put(np.asarray(add0, np.int32))
    rm = jax.device_put(np.asarray(rm0, np.int32))
    if impl == "pallas":
        if tile_cap is None:
            # a per-chunk fold_cap here would recompile the donated fold
            # for every distinct cap — the caller computes ONE cap over
            # the whole member column (which bounds every chunk's)
            raise ValueError(
                "impl='pallas' requires tile_cap (fold_cap over the whole "
                "member column)"
            )
        interpret = jax.default_backend() != "tpu"
        for kind, member, actor, counter in chunks:
            clock, add, rm = _fold_donated_pallas(
                clock, add, rm, kind, member, actor, counter,
                num_members=num_members, num_replicas=num_replicas,
                tile_cap=tile_cap, interpret=interpret,
            )
        return clock, add, rm
    for kind, member, actor, counter in chunks:
        clock, add, rm = _fold_donated(
            clock, add, rm, kind, member, actor, counter,
            num_members=num_members, num_replicas=num_replicas,
            impl=impl, small_counters=small_counters,
        )
    return clock, add, rm

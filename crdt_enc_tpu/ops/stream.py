"""Chunked (bounded-memory) folds and the overlapped streaming-compaction
pipeline.

The long-context story (SURVEY.md §2.3): a replica's op log is the
framework's "sequence", and because the fold is associative the log can be
folded blockwise — the same trick ring attention uses for its associative
accumulator.  A 100M-op compaction therefore never materializes the whole
batch on device: fixed-size chunks stream through one compiled fold whose
state planes are **donated** (`jax.jit(donate_argnums=...)`), so XLA reuses
the plane buffers in place and device memory stays at
``one chunk + one set of planes`` regardless of stream length.

**Overlap** (this module's second job): the host-side front end — AEAD
decrypt, native decode, columnarization, H2D staging — dominates a full
single-dispatch compaction by ~40× (BASELINE config #5), so the pipeline
here runs it CONCURRENTLY with the device fold:

* a producer pool (N threads pulling span indices from a shared cursor;
  the decrypt/decode calls are native and release the GIL, so the
  workers genuinely run in parallel) ingests chunks ahead of the fold
  while a sequencer re-emits them to the consumer in STRICT chunk-index
  order — the reduction order, and therefore the folded state bytes,
  are identical at any N (:func:`run_ingest_pipeline`,
  backpressure-bounded so at most ``depth`` chunks of host memory are
  ever live — default ``producers + 1``: one chunk per worker in flight
  plus one being reduced; :func:`stream_producer_count` auto-tunes N
  from the core count with a ``CRDT_STREAM_PRODUCERS`` override);
* the consumer issues the async ``jax.device_put`` of chunk k+1 BEFORE
  dispatching the donated fold of chunk k, so the H2D transfer rides
  under the previous fold's device execution
  (:func:`fold_chunks_overlapped`);
* column staging reuses pre-allocated fixed-shape buffers
  (:class:`ChunkPool`) instead of allocating per chunk — the host buffer
  for chunk k is recycled the moment its transfer lands.

Every stage is timed through ``utils.trace`` spans (``stream.decrypt``,
``stream.decode``, ``stream.ingest``, ``stream.h2d``, ``stream.fold``,
``stream.reduce``, ``stream.d2h``, plus ``stream.producer.wait`` /
``stream.sequence`` and the ``stream_producers`` gauge for the fan-out
stage) with the chunk index as span ``meta``,
so the overlap is auditable from the event log
(``trace.enable_events()``) — tests/test_streaming_pipeline.py pins that
chunk k+1's ingest starts before chunk k's fold completes, and
``bench.py --e2e-streaming`` publishes the per-stage marginals.

Exactness: chunked ≡ whole-batch under the causal-delivery contract the
core guarantees (per-actor op files apply in version order, core.py
``_read_remote_ops``) — each chunk's stale-dot filter then sees a clock
that only ever rejects true replays.  The per-op host loop is precisely
the chunk-size-1 instance of this fold, so the existing host-equality
tests pin the semantics at both extremes.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
from functools import partial

import jax
import numpy as np

from ..obs import runtime as obs_runtime
from ..utils import trace
from .orset import orset_fold

def stream_producer_count(requested: int = 0) -> int:
    """Resolve the ingest fan-out width (the N in the N-producer
    pipeline): an explicit positive ``requested`` wins, then the
    ``CRDT_STREAM_PRODUCERS`` env override, then an auto-tune from
    ``os.cpu_count()``.

    Auto-tune policy: **one producer per core, minus one core reserved
    for the consumer** (columnarize + fold dispatch), floor 1.  The old
    cap of 4 predated file-granular stripe claiming — with producers
    cooperating on one chunk's stripes through the unified work queue
    (:func:`run_striped_ingest_pipeline`) the decrypt front end scales
    with the cores actually present, and an idle 32-core host should
    not be throttled to 4 lanes.  Boxes where wide fan-out genuinely
    thrashes (shared/throttled cgroups) pin ``CRDT_STREAM_PRODUCERS``
    instead of everyone paying a global ceiling."""
    if requested > 0:
        return int(requested)
    env = os.environ.get("CRDT_STREAM_PRODUCERS", "")
    if env.strip():
        try:
            n = int(env)
        except ValueError:
            n = 0
        if n > 0:
            return n
    cpus = os.cpu_count() or 1
    return max(1, cpus - 1)


@partial(
    jax.jit,
    static_argnames=(
        "num_members", "num_replicas", "impl", "small_counters", "retire_rm",
    ),
    donate_argnums=(0, 1, 2),
)
def _fold_donated(
    clock, add, rm, kind, member, actor, counter,
    *, num_members, num_replicas, impl, small_counters, retire_rm=True,
):
    return orset_fold(
        clock, add, rm, kind, member, actor, counter,
        num_members=num_members, num_replicas=num_replicas,
        impl=impl, small_counters=small_counters, retire_rm=retire_rm,
    )


@partial(
    jax.jit,
    static_argnames=("num_members", "num_replicas", "tile_cap", "interpret",
                     "retire_rm"),
    donate_argnums=(0, 1, 2),
)
def _fold_donated_pallas(
    clock, add, rm, kind, member, actor, counter,
    *, num_members, num_replicas, tile_cap, interpret, retire_rm=True,
):
    from .pallas_fold import orset_fold_pallas

    return orset_fold_pallas(
        clock, add, rm, kind, member, actor, counter,
        num_members=num_members, num_replicas=num_replicas,
        tile_cap=tile_cap, interpret=interpret, retire_rm=retire_rm,
    )


class ChunkPool:
    """Pre-allocated fixed-shape op-column staging buffers.

    The pipeline's ONLY host staging memory: ``depth`` buffer sets of
    ``(kind int8, member/actor/counter int32) × chunk_rows``.
    ``acquire()`` blocks while every set is out — together with the
    ingest semaphore this is what bounds live host memory to ``depth``
    chunks however long the stream runs.  Release a set only after its
    H2D transfer has completed (``fold_chunks_overlapped`` does): on the
    CPU backend ``jax.device_put`` may alias the host buffer, and on any
    backend the async copy reads it after the call returns.
    """

    def __init__(self, chunk_rows: int, depth: int = 2):
        if depth < 2:
            # the overlapped consumer holds one buffer in `pending` while
            # the chunk iterator acquires the next — a single-buffer pool
            # would deadlock there (and on aliasing backends the pending
            # buffer cannot be released until its fold completes)
            raise ValueError(f"ChunkPool needs depth >= 2, got {depth}")
        self.chunk_rows = chunk_rows
        self.depth = depth
        self._free: _queue.Queue = _queue.Queue()
        for _ in range(depth):
            self._free.put((
                np.zeros(chunk_rows, np.int8),
                np.zeros(chunk_rows, np.int32),
                np.zeros(chunk_rows, np.int32),
                np.zeros(chunk_rows, np.int32),
            ))

    def acquire(self) -> tuple:
        return self._free.get()

    def release(self, bufs: tuple) -> None:
        self._free.put(bufs)


def columnarize_into(
    bufs, kind, member, actor, counter, lo: int, hi: int, num_replicas: int
):
    """Copy rows ``[lo:hi)`` of the flat columns into a pool buffer set,
    sentinel-padding the tail (``actor == num_replicas`` rows, which every
    kernel masks out).  Returns ``bufs``."""
    k, m, a, c = bufs
    n = hi - lo
    np.copyto(k[:n], kind[lo:hi], casting="unsafe")
    np.copyto(m[:n], member[lo:hi], casting="unsafe")
    np.copyto(a[:n], actor[lo:hi], casting="unsafe")
    np.copyto(c[:n], counter[lo:hi], casting="unsafe")
    if n < len(k):
        k[n:] = 0
        m[n:] = 0
        a[n:] = num_replicas
        c[n:] = 0
    return bufs


def iter_orset_chunks(
    kind, member, actor, counter, chunk_rows: int, num_replicas: int,
    pool: ChunkPool | None = None,
):
    """Slice flat op columns into fixed-shape chunks (the tail is padded
    with ``actor == num_replicas`` sentinel rows, which every kernel
    masks out) — one shape ⇒ one compilation for the whole stream.

    With a ``pool`` the chunks are columnarized into its pre-allocated
    buffers instead of fresh arrays; the consumer MUST release each
    buffer set back (``fold_chunks_overlapped(..., pool=pool)`` does)
    and ``pool.chunk_rows`` must equal ``chunk_rows``."""
    n = len(kind)
    if pool is not None:
        assert pool.chunk_rows == chunk_rows, "pool shape mismatch"
        kind = np.asarray(kind)
        member = np.asarray(member)
        actor = np.asarray(actor)
        counter = np.asarray(counter)
        for lo in range(0, n, chunk_rows):
            hi = min(lo + chunk_rows, n)
            with trace.span("stream.columnarize", meta=lo // chunk_rows):
                bufs = columnarize_into(
                    pool.acquire(), kind, member, actor, counter,
                    lo, hi, num_replicas,
                )
            yield bufs
        return
    for lo in range(0, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        pad = chunk_rows - (hi - lo)
        k = np.asarray(kind[lo:hi], np.int8)
        m = np.asarray(member[lo:hi], np.int32)
        a = np.asarray(actor[lo:hi], np.int32)
        c = np.asarray(counter[lo:hi], np.int32)
        if pad:
            k = np.concatenate([k, np.zeros(pad, np.int8)])
            m = np.concatenate([m, np.zeros(pad, np.int32)])
            a = np.concatenate([a, np.full(pad, num_replicas, np.int32)])
            c = np.concatenate([c, np.zeros(pad, np.int32)])
        yield k, m, a, c


def fold_chunks_overlapped(planes, chunks, fold_step, *, pool=None, put=None):
    """The overlapped consumer loop: fold an iterable of host column
    chunks into device ``planes`` with one-chunk H2D lookahead.

    Per cycle: the async ``jax.device_put`` of chunk k+1 is issued FIRST,
    then the donated ``fold_step(planes, dev_chunk_k)`` is dispatched
    (async), then the loop blocks on chunk k+1's transfer — which
    therefore rides under fold k's device execution — and recycles the
    host buffer to ``pool``.  ``fold_step`` must donate the planes and
    may be the jitted folds above or a test double.  ``put`` overrides
    the per-array transfer (default ``jax.device_put``) — the sharded
    streaming branch passes a ``NamedSharding``-targeted put so chunk
    k+1's rows land dp-sharded across the mesh, still under chunk k's
    fold.

    Returns the final device planes (NOT blocked: callers overlap their
    own epilogue, or block + pull under a ``stream.d2h`` span via
    :func:`planes_to_host`).

    Buffer recycling: on accelerators the H2D copy is real, so chunk k's
    staging buffer recycles as soon as its transfer lands (which happens
    under fold k-1's execution).  On the CPU backend ``jax.device_put``
    may ALIAS the host buffer zero-copy for the array's whole lifetime —
    there the buffer is held until the fold that consumes it completes
    (no overlap lost: host and "device" are the same silicon)."""
    if put is None:
        put = jax.device_put
    aliasing = pool is not None and jax.default_backend() == "cpu"
    pending = None  # device-resident chunk awaiting its fold dispatch
    pending_host = None  # its staging buffers (aliasing backends only)
    k = 0
    for host_chunk in chunks:
        with trace.span("stream.h2d", meta=k):
            trace.add(
                "h2d_bytes",
                sum(getattr(x, "nbytes", 0) for x in host_chunk),
            )
            dev_chunk = tuple(put(x) for x in host_chunk)
        if pending is not None:
            with trace.span("stream.fold", meta=k - 1):
                planes = fold_step(planes, pending)
            if aliasing:
                # fold k-1 has fully consumed its (possibly aliased)
                # staging buffers once its output is materialized
                jax.block_until_ready(planes)
                pool.release(pending_host)
        if pool is not None and not aliasing:
            # block on THIS chunk's transfer (it runs under fold k-1),
            # then the staging buffer is safely reusable
            jax.block_until_ready(dev_chunk)
            pool.release(host_chunk)
        pending = dev_chunk
        pending_host = host_chunk
        k += 1
    if pending is not None:
        with trace.span("stream.fold", meta=k - 1):
            planes = fold_step(planes, pending)
        if aliasing:
            jax.block_until_ready(planes)
            pool.release(pending_host)
    # fold boundary: the bounded-device-memory claim (one chunk + donated
    # planes), observable — a no-op on backends without allocator stats
    obs_runtime.sample_device_memory()
    return planes


def planes_to_host(planes):
    """Block on the in-flight folds and pull the planes to host, under
    the pipeline's ``stream.d2h`` span."""
    with trace.span("stream.d2h"):
        jax.block_until_ready(planes)
        return tuple(np.asarray(x) for x in planes)


def orset_fold_stream(
    clock0,
    add0,
    rm0,
    chunks,
    *,
    num_members: int,
    num_replicas: int,
    impl: str = "fused",
    small_counters: bool = False,
    tile_cap: int | None = None,
    h2d_lookahead: bool = True,
    pool: ChunkPool | None = None,
):
    """Fold an iterable of fixed-shape op chunks into the state planes.

    ``chunks`` yields ``(kind, member, actor, counter)`` tuples of one
    common row count (see :func:`iter_orset_chunks`).  Returns the folded
    ``(clock, add, rm)`` device arrays.  The planes are donated between
    chunks — do not reuse the input arrays after calling.

    ``h2d_lookahead`` (default on) runs the overlapped consumer loop:
    chunk k+1's transfer is issued while chunk k's fold is in flight
    (:func:`fold_chunks_overlapped`); pass ``pool`` when the chunk
    iterator stages into a :class:`ChunkPool` so buffers recycle.

    ``impl="pallas"`` runs each chunk through the MXU fold
    (ops/pallas_fold.py); pass ``tile_cap`` computed over the WHOLE
    member column (``fold_cap``) so every chunk compiles once — a
    per-chunk cap is bounded by the global one.
    """
    clock0 = np.asarray(clock0, np.int32)
    add0 = np.asarray(add0, np.int32)
    rm0 = np.asarray(rm0, np.int32)
    trace.add("h2d_bytes", clock0.nbytes + add0.nbytes + rm0.nbytes)
    clock = jax.device_put(clock0)
    add = jax.device_put(add0)
    rm = jax.device_put(rm0)
    if impl == "pallas":
        if tile_cap is None:
            # a per-chunk fold_cap here would recompile the donated fold
            # for every distinct cap — the caller computes ONE cap over
            # the whole member column (which bounds every chunk's)
            raise ValueError(
                "impl='pallas' requires tile_cap (fold_cap over the whole "
                "member column)"
            )
        interpret = jax.default_backend() != "tpu"

        def fold_step(planes, chunk):
            return _fold_donated_pallas(
                *planes, *chunk,
                num_members=num_members, num_replicas=num_replicas,
                tile_cap=tile_cap, interpret=interpret,
            )
    else:
        def fold_step(planes, chunk):
            return _fold_donated(
                *planes, *chunk,
                num_members=num_members, num_replicas=num_replicas,
                impl=impl, small_counters=small_counters,
            )

    if h2d_lookahead:
        return fold_chunks_overlapped(
            (clock, add, rm), chunks, fold_step, pool=pool
        )
    planes = (clock, add, rm)
    for chunk in chunks:
        planes = fold_step(planes, chunk)
        if pool is not None:
            jax.block_until_ready(planes)
            pool.release(chunk)
    return planes


class PipelineError(Exception):
    """A producer-stage failure, re-raised in the consumer with the
    original exception as ``__cause__``."""


def run_ingest_pipeline(
    spans, ingest_fn, reduce_fn, *, depth: int = 0, producers: int = 1,
    thread_prefix: str = "crdt-ingest-producer",
):
    """Ordered fan-out pipeline over ``spans`` (any sequence of work
    items — encrypted-blob slices for one remote's chunked ingest, or
    whole tenants for the multi-tenant serving layer's cross-tenant
    decode fan-out, crdt_enc_tpu/serve/service.py).

    ``producers`` worker threads pull span indices from a shared cursor
    and run ``ingest_fn(span, k)`` — decrypt + decode; host work whose
    native calls release the GIL — concurrently, while the calling
    thread runs ``reduce_fn(ingested, k)`` — columnarize + fold.  A
    sequencer on the calling thread re-emits completed chunks in STRICT
    chunk-index order, so the reduction order — and therefore the
    donated-fold planes and the resulting state bytes — is identical to
    the single-producer pipeline whatever the workers' finish order.

    Backpressure: a ``BoundedSemaphore(depth)`` is acquired BEFORE a
    chunk is claimed and released only after its reduce completes, so at
    most ``depth`` chunks are ever live host-side — including chunks the
    sequencer is holding back.  ``depth=0`` auto-sizes to
    ``max(2, producers + 1)``: one chunk per worker in flight plus one
    being reduced (the N-producer generalization of the double buffer).
    No deadlock is possible: indices are claimed in increasing order
    immediately after a slot acquire, so the chunk the sequencer waits
    for is always either unclaimed with a free slot on its way, or
    already being ingested by a live worker.

    Stage timing: each ingest runs under a ``stream.ingest`` span and
    each reduce under ``stream.reduce``, both with ``meta=k``; workers
    are named ``<thread_prefix>-<i>`` (default ``crdt-ingest-producer``;
    the serving layer passes ``crdt-serve-producer`` so its lanes stay
    distinguishable in a timeline export) so the timeline export gives
    each its own lane.  ``stream.producer.wait`` (meta = producer index)
    times a worker's backpressure stall, ``stream.sequence`` (meta = k)
    times the sequencer's wait for the next in-order chunk, and the
    ``stream_producers`` gauge records the pool width of the run.

    Errors: the first failing producer sets the shared stop flag — its
    peers cancel at their next claim or slot poll, never claiming new
    chunks — and the failure surfaces here as :class:`PipelineError`
    (original as ``__cause__``) once every chunk BEFORE the failed index
    has been reduced (chunks after it are discarded, releasing their
    pending sequencer slots).  A consumer exception stops all producers
    at their next poll and re-raises unchanged.  Either way the worker
    threads are joined before this function returns.
    """
    spans = list(spans)
    n_spans = len(spans)
    producers = max(1, int(producers))
    if depth <= 0:
        depth = max(2, producers + 1)
    trace.gauge("stream_producers", producers)
    if n_spans == 0:
        return
    slots = threading.BoundedSemaphore(depth)
    out_q: _queue.Queue = _queue.Queue()
    stop = threading.Event()
    cursor_lock = threading.Lock()
    next_index = [0]

    def produce(pid: int):
        k = None
        try:
            while True:
                # backpressure BEFORE claiming an index: a worker must
                # never sit on a claimed chunk while waiting for memory,
                # or the sequencer could stall behind an unstarted chunk
                # (poll so a dead consumer can't strand this thread)
                with trace.span("stream.producer.wait", meta=pid):
                    while not slots.acquire(timeout=0.1):
                        if stop.is_set():
                            return
                if stop.is_set():
                    slots.release()
                    return
                with cursor_lock:
                    k = next_index[0]
                    next_index[0] += 1
                if k >= n_spans:
                    slots.release()
                    return
                with trace.span("stream.ingest", meta=k):
                    item = ingest_fn(spans[k], k)
                out_q.put(("chunk", k, item))
                k = None
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            stop.set()  # first failure cancels the peers
            out_q.put(("error", k if k is not None else -1, e))

    workers = [
        threading.Thread(
            target=produce, args=(i,),
            name=f"{thread_prefix}-{i}", daemon=True,
        )
        for i in range(producers)
    ]
    for w in workers:
        w.start()
    stash: dict[int, object] = {}
    failures: dict[int, BaseException] = {}
    expected = 0
    try:
        while expected < n_spans:
            if failures and expected >= min(failures):
                k = min(failures)
                raise PipelineError(
                    f"ingest producer failed at chunk {k}"
                ) from failures[k]
            if expected in stash:
                item = stash.pop(expected)
            else:
                with trace.span("stream.sequence", meta=expected):
                    while True:
                        tag, k, item = out_q.get()
                        if tag == "error":
                            failures[k] = item
                            break
                        if k == expected:
                            break
                        stash[k] = item  # holds its slot until reduced
                if tag == "error":
                    continue  # drain the pre-failure prefix, then raise
            try:
                with trace.span("stream.reduce", meta=expected):
                    reduce_fn(item, expected)
            finally:
                slots.release()
            expected += 1
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=30.0)


class _ChunkWork:
    """One claimed chunk on the unified work queue: its stripe list, the
    claim cursor, the landed parts, and the remaining-stripe count."""

    __slots__ = ("span", "stripes", "next_stripe", "remaining", "parts")

    def __init__(self, span, stripes):
        self.span = span
        self.stripes = stripes
        self.next_stripe = 0
        self.remaining = len(stripes)
        self.parts = [None] * len(stripes)


def run_striped_ingest_pipeline(
    spans, split_fn, stripe_fn, assemble_fn, reduce_fn, *,
    depth: int = 0, producers: int = 1, inline: bool | None = None,
    thread_prefix: str = "crdt-ingest-producer",
):
    """File-granular fan-out over ``spans``: the unified work queue.

    The chunk-granular pipeline above assigns each producer a WHOLE
    chunk — one oversized op file then serializes its lane while its
    peers idle, and the only recourse was a nested native decrypt pool
    inside the chunk (threads × threads oversubscription).  Here the
    work unit is a **stripe** (a file subrange of one chunk,
    ``split_fn(span, k) -> [stripe, ...]``): producers claim stripes
    from a single shared queue — preferring the OLDEST in-flight
    chunk's unclaimed stripes, opening a new chunk (in index order,
    after a backpressure-slot acquire, exactly the chunk-pipeline
    discipline) only when none are left — so a giant file occupies one
    worker while the rest of the pool keeps the pipeline full, and the
    in-chunk thread pool is gone.

    ``stripe_fn(stripe, k, s) -> part`` runs concurrently (decrypt —
    native, GIL released).  The worker that lands a chunk's LAST stripe
    runs ``assemble_fn(parts, span, k) -> item`` (decode) and emits it;
    the calling thread reduces items in STRICT chunk order via the same
    sequencer as :func:`run_ingest_pipeline`, so the folded bytes are
    identical at any producer count and any stripe split.  Backpressure
    bounds live chunks to ``depth`` (0 = ``producers + 1``).

    ``inline`` (None = auto): with one producer on a single-core host
    the worker thread cannot overlap anything real — it only adds
    queue/GIL handoffs — so the whole pipeline runs inline on the
    calling thread, byte-identically.  Explicit ``inline=False`` forces
    the threaded path (tests exercise the seams on any box).

    Error contract: the first stripe/assemble failure stops the pool
    and raises :class:`PipelineError` (original as ``__cause__``)
    WITHOUT draining earlier chunks — a stopped pool may have orphaned
    their unclaimed stripes, so unlike the chunk pipeline no pre-failure
    prefix is guaranteed reduced.  The only caller
    (``TpuAccelerator.fold_encrypted_stream``) feeds a fold session
    that mutates nothing until ``finish``, so a raise discards cleanly.
    A consumer (reduce) exception re-raises unchanged; workers are
    always joined before returning."""
    spans = list(spans)
    n_spans = len(spans)
    producers = max(1, int(producers))
    if depth <= 0:
        depth = max(2, producers + 1)
    trace.gauge("stream_producers", producers)
    if n_spans == 0:
        return
    if inline is None:
        inline = producers == 1 and (os.cpu_count() or 1) <= 1
    if inline:
        for k, span in enumerate(spans):
            stripes = split_fn(span, k)
            with trace.span("stream.ingest", meta=k):
                parts = [
                    stripe_fn(stripe, k, s)
                    for s, stripe in enumerate(stripes)
                ]
                item = assemble_fn(parts, span, k)
            with trace.span("stream.reduce", meta=k):
                reduce_fn(item, k)
        return

    slots = threading.BoundedSemaphore(depth)
    out_q: _queue.Queue = _queue.Queue()
    stop = threading.Event()
    lock = threading.Lock()
    next_chunk = [0]
    active: dict[int, _ChunkWork] = {}  # insertion order = chunk order

    def claim():
        """The next (work, k, s) stripe claim, preferring the oldest
        in-flight chunk, or ``"new"`` when a fresh chunk must be opened
        (slot acquire happens OUTSIDE the lock), or ``None`` when no
        work remains."""
        with lock:
            for k, work in active.items():
                if work.next_stripe < len(work.stripes):
                    s = work.next_stripe
                    work.next_stripe += 1
                    return work, k, s
            if next_chunk[0] < n_spans:
                return "new"
        return None

    def open_chunk():
        """Claim the next chunk index and register its stripes; returns
        a stripe claim from it, ``"raced"`` when another worker took the
        last index, or ``None`` when exhausted.  The caller already
        holds a backpressure slot; it is returned on non-claims."""
        with lock:
            k = next_chunk[0]
            if k >= n_spans:
                return None
            next_chunk[0] += 1
        stripes = split_fn(spans[k], k)
        with lock:
            work = _ChunkWork(spans[k], stripes)
            if not stripes:
                # empty chunk: complete immediately (no stripe will land)
                pass
            else:
                work.next_stripe = 1
                active[k] = work
                return work, k, 0
        out_q.put(("chunk", k, assemble_fn([], spans[k], k)))
        return "empty"

    def finish_stripe(work, k, s, part):
        with lock:
            work.parts[s] = part
            work.remaining -= 1
            done = work.remaining == 0
            if done:
                active.pop(k, None)
        if done:
            with trace.span("stream.ingest", meta=k):
                item = assemble_fn(work.parts, work.span, k)
            out_q.put(("chunk", k, item))

    def produce(pid: int):
        k = None
        try:
            while True:
                if stop.is_set():
                    return
                got = claim()
                if got is None:
                    return
                if got == "new":
                    # backpressure BEFORE opening a chunk (poll so a dead
                    # consumer can't strand this thread); stripes of
                    # already-open chunks need no slot — their chunk holds one
                    with trace.span("stream.producer.wait", meta=pid):
                        while not slots.acquire(timeout=0.1):
                            if stop.is_set():
                                return
                    if stop.is_set():
                        slots.release()
                        return
                    got = open_chunk()
                    if got is None:
                        slots.release()
                        return
                    if got == "empty":
                        continue  # slot rides with the emitted chunk
                work, k, s = got
                with trace.span("stream.stripe", meta=k):
                    part = stripe_fn(work.stripes[s], k, s)
                finish_stripe(work, k, s, part)
                k = None
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            stop.set()
            out_q.put(("error", k if k is not None else -1, e))

    workers = [
        threading.Thread(
            target=produce, args=(i,),
            name=f"{thread_prefix}-{i}", daemon=True,
        )
        for i in range(producers)
    ]
    for w in workers:
        w.start()
    stash: dict[int, object] = {}
    expected = 0
    try:
        while expected < n_spans:
            if expected in stash:
                item = stash.pop(expected)
            else:
                with trace.span("stream.sequence", meta=expected):
                    while True:
                        tag, k, item = out_q.get()
                        if tag == "error":
                            raise PipelineError(
                                f"striped ingest failed at chunk {k}"
                            ) from item
                        if k == expected:
                            break
                        stash[k] = item  # holds its slot until reduced
            try:
                with trace.span("stream.reduce", meta=expected):
                    reduce_fn(item, expected)
            finally:
                slots.release()
            expected += 1
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=30.0)

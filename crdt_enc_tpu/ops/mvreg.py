"""MVReg dominance filter as a tensor program.

Given V candidate values with dense clocks ``(V, R)``, keep each value whose
clock is not strictly dominated by another candidate's clock — the CvRDT
merge rule of crdt_enc_tpu/models/mvreg.py, O(V²R) pairwise but fully
parallel.  Production caller: ``TpuAccelerator._merge_mvregs`` collapses a
whole batch of MVReg snapshots (compaction over a register state type) to
the global anti-chain in one call once the candidate count clears the
dispatch threshold; below it the host pairwise merge wins.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def mvreg_dominance_keep(clocks: jax.Array, valid: jax.Array) -> jax.Array:
    """``clocks``: (V, R) int32; ``valid``: (V,) bool mask of real rows.
    Returns (V,) bool — rows that survive the dominance filter.

    Caller contract: rows are distinct (clock, value) pairs — dedup of
    identical pairs happens host-side (models/mvreg.py _canonicalize), since
    value identity is not visible to this kernel.  Identical clocks with
    different values are concurrent and both survive.
    """
    ge = jnp.all(clocks[:, None, :] >= clocks[None, :, :], axis=-1)  # (V, V)
    gt = jnp.any(clocks[:, None, :] > clocks[None, :, :], axis=-1)
    dominates = ge & gt  # [j, i]: j strictly dominates i
    dominated = jnp.any(dominates & valid[:, None], axis=0)
    return valid & ~dominated

"""LWW-map fold: per-key lexicographic argmax over (ts, actor, value).

The host tie-break order (timestamp, then actor bytes, then canonical value
bytes — crdt_enc_tpu/models/lwwmap.py) is reproduced on device by *rank
interning*: actors and values are pre-sorted host-side so integer comparison
matches byte comparison.  Timestamps arrive split into hi/lo 31-bit halves
(``ts_split``) so arbitrary 62-bit timestamps work without x64 mode on TPU.
Four cascaded segment-max passes implement the lexicographic order.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

TS_SPLIT_BITS = 31
TS_SPLIT_MASK = (1 << TS_SPLIT_BITS) - 1


def ts_split(ts):
    """Split non-negative int timestamps (< 2^62) into (hi, lo) int32."""
    import numpy as np

    ts = np.asarray(ts, np.int64)
    if (ts < 0).any() or (ts >= (1 << 62)).any():
        raise ValueError("timestamps must be in [0, 2^62)")
    return (ts >> TS_SPLIT_BITS).astype(np.int32), (ts & TS_SPLIT_MASK).astype(
        np.int32
    )


@partial(jax.jit, static_argnames=("num_keys", "num_values"))
def lww_fold(
    key: jax.Array,  # (N,) int32   (== num_keys ⇒ padding row)
    ts_hi: jax.Array,  # (N,) int32
    ts_lo: jax.Array,  # (N,) int32
    actor: jax.Array,  # (N,) int32  rank-interned
    value: jax.Array,  # (N,) int32  rank-interned (tombstone included)
    *,
    num_keys: int,
    num_values: int | None = None,
):
    """Per-key winner selection.  Returns ``(win_hi, win_lo, win_actor,
    win_value, present)``; ``present[k]`` is False for keys with no rows
    (possible when folding into an existing key vocabulary).

    ``num_values``: when given AND ``max_actor_rank * num_values +
    num_values`` fits int32 (caller's responsibility — the accelerator and
    benchmarks check ``R * V < 2**31``), the (actor, value) tie-breaks
    collapse into ONE packed-rank cascade: ``av = actor * num_values +
    value`` preserves the lexicographic order, cutting the segment-max
    passes (the kernel's scatter-bound hot cost) from 4 to 3."""
    K = num_keys
    pad = key >= K
    key_ix = jnp.minimum(key, K - 1)

    def cascade(elig, col):
        masked = jnp.where(elig, col, -1)
        m = jnp.maximum(jax.ops.segment_max(masked, key_ix, num_segments=K), -1)
        return elig & (col == m[key_ix]), m

    elig = ~pad
    elig, m_hi = cascade(elig, ts_hi)
    elig, m_lo = cascade(elig, ts_lo)
    if num_values is not None:
        _, m_av = cascade(elig, actor * num_values + value)
        present = m_hi > -1
        m_actor = jnp.where(present, m_av // num_values, -1)
        m_value = jnp.where(present, m_av % num_values, -1)
    else:
        elig, m_actor = cascade(elig, actor)
        _, m_value = cascade(elig, value)
        present = m_hi > -1
    return m_hi, m_lo, m_actor, m_value, present


def lww_table_wins(a: tuple, b: tuple):
    """Elementwise: where winner-table row ``a`` beats ``b`` — present
    beats absent; both present resolve by the (ts_hi, ts_lo, actor, value)
    lexicographic order (the host tie-break, models/lwwmap.py)."""
    a_hi, a_lo, a_ac, a_va, a_p = a
    b_hi, b_lo, b_ac, b_va, b_p = b
    gt = a_hi > b_hi
    eq = a_hi == b_hi
    gt = gt | (eq & (a_lo > b_lo))
    eq = eq & (a_lo == b_lo)
    gt = gt | (eq & (a_ac > b_ac))
    eq = eq & (a_ac == b_ac)
    gt = gt | (eq & (a_va > b_va))
    return (a_p & ~b_p) | (a_p & b_p & gt)


def lww_table_merge(a: tuple, b: tuple) -> tuple:
    """Merge two (K,)-shaped winner tables elementwise (pure VPU work —
    no scatters).  Ties keep ``b``, matching segment-max semantics where
    identical tuples are indistinguishable."""
    take_a = lww_table_wins(a, b)
    out = tuple(jnp.where(take_a, x, y) for x, y in zip(a[:4], b[:4]))
    return (*out, a[4] | b[4])


@partial(
    jax.jit,
    static_argnames=("num_keys", "num_values", "impl", "tile_cap",
                     "interpret", "limbs"),
)
def lww_fold_into(
    win: tuple,  # (win_hi, win_lo, win_actor, win_value, present) — (K,) each
    key: jax.Array,
    ts_hi: jax.Array,
    ts_lo: jax.Array,
    actor: jax.Array,
    value: jax.Array,
    *,
    num_keys: int,
    num_values: int | None = None,
    impl: str = "xla",  # "xla" (cascaded segment-max) | "pallas" (MXU)
    tile_cap: int = 1 << 14,  # pallas only: ops/pallas_lww.lww_tile_cap
    interpret: bool = False,
    limbs: tuple | None = None,  # pallas only: static per-column limb
    #   counts (ops/pallas_lww.lww_limbs) — measured ~4x the kernel at
    #   the config-4 shape vs the data-dependent limb conds
):
    """Incremental fold: new rows compete against an existing winner table.

    The new rows fold to their own per-key winners, which then merge with
    the existing table **elementwise** (``lww_table_merge``) — the carried
    winners never re-enter the scatter path, so the incremental cost is
    the new rows plus one O(K) VPU pass.  The LWW tie-break is a total
    order, so ``fold_into(fold(A), B) == fold(A ++ B)`` (associativity) —
    this is the merge step for folding op batches that arrive in waves.

    ``impl="pallas"`` runs the new-row winner selection on the MXU
    (ops/pallas_lww.py — requires ``num_values`` and ``ts_hi+1`` inside
    int32, the caller's eligibility check); the merge is VPU either way.
    """
    if impl == "pallas":
        from .pallas_lww import lww_fold_pallas

        new = lww_fold_pallas(
            key, ts_hi, ts_lo, actor, value,
            num_keys=num_keys, num_values=num_values,
            tile_cap=tile_cap, interpret=interpret, limbs=limbs,
        )
    else:
        new = lww_fold(
            key, ts_hi, ts_lo, actor, value,
            num_keys=num_keys, num_values=num_values,
        )
    return lww_table_merge(new, win)

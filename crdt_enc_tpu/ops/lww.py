"""LWW-map fold: per-key lexicographic argmax over (ts, actor, value).

The host tie-break order (timestamp, then actor bytes, then canonical value
bytes — crdt_enc_tpu/models/lwwmap.py) is reproduced on device by *rank
interning*: actors and values are pre-sorted host-side so integer comparison
matches byte comparison.  Timestamps arrive split into hi/lo 31-bit halves
(``ts_split``) so arbitrary 62-bit timestamps work without x64 mode on TPU.
Four cascaded segment-max passes implement the lexicographic order.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

TS_SPLIT_BITS = 31
TS_SPLIT_MASK = (1 << TS_SPLIT_BITS) - 1


def ts_split(ts):
    """Split non-negative int timestamps (< 2^62) into (hi, lo) int32."""
    import numpy as np

    ts = np.asarray(ts, np.int64)
    if (ts < 0).any() or (ts >= (1 << 62)).any():
        raise ValueError("timestamps must be in [0, 2^62)")
    return (ts >> TS_SPLIT_BITS).astype(np.int32), (ts & TS_SPLIT_MASK).astype(
        np.int32
    )


@partial(jax.jit, static_argnames=("num_keys",))
def lww_fold(
    key: jax.Array,  # (N,) int32   (== num_keys ⇒ padding row)
    ts_hi: jax.Array,  # (N,) int32
    ts_lo: jax.Array,  # (N,) int32
    actor: jax.Array,  # (N,) int32  rank-interned
    value: jax.Array,  # (N,) int32  rank-interned (tombstone included)
    *,
    num_keys: int,
):
    """Per-key winner selection.  Returns ``(win_hi, win_lo, win_actor,
    win_value, present)``; ``present[k]`` is False for keys with no rows
    (possible when folding into an existing key vocabulary)."""
    K = num_keys
    pad = key >= K
    key_ix = jnp.minimum(key, K - 1)

    def cascade(elig, col):
        masked = jnp.where(elig, col, -1)
        m = jnp.maximum(jax.ops.segment_max(masked, key_ix, num_segments=K), -1)
        return elig & (col == m[key_ix]), m

    elig = ~pad
    elig, m_hi = cascade(elig, ts_hi)
    elig, m_lo = cascade(elig, ts_lo)
    elig, m_actor = cascade(elig, actor)
    elig, m_value = cascade(elig, value)
    present = m_hi > -1
    return m_hi, m_lo, m_actor, m_value, present


@partial(jax.jit, static_argnames=("num_keys",))
def lww_fold_into(
    win: tuple,  # (win_hi, win_lo, win_actor, win_value, present) — (K,) each
    key: jax.Array,
    ts_hi: jax.Array,
    ts_lo: jax.Array,
    actor: jax.Array,
    value: jax.Array,
    *,
    num_keys: int,
):
    """Incremental fold: new rows compete against an existing winner table.

    The current winners re-enter as candidate rows (absent keys as padding),
    so ``fold_into(fold(A), B) == fold(A ++ B)`` — the LWW tie-break is a
    total order, making the fold associative.  This is the merge step for
    folding op batches that arrive in waves (and the data dependence the
    benchmark's chained timing needs)."""
    K = num_keys
    w_hi, w_lo, w_actor, w_value, present = win
    prev_key = jnp.where(present, jnp.arange(K, dtype=key.dtype), K)
    return lww_fold(
        jnp.concatenate([key, prev_key]),
        jnp.concatenate([ts_hi, w_hi]),
        jnp.concatenate([ts_lo, w_lo]),
        jnp.concatenate([actor, w_actor]),
        jnp.concatenate([value, w_value]),
        num_keys=K,
    )

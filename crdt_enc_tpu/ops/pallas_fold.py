"""Pallas TPU kernel: the ORSet fold with the scatter reformulated as
sorted one-hot matmuls on the MXU — the round-3 north-star attack.

The dense fold (``ops/orset.py orset_fold``) spends its wall in XLA's
scatter-max: random (member, actor) updates serialize at ~9ns/row
(measured: 10.3ms of the 17.1ms fused-i16 fold for 1M rows, against a
~1.2ms bandwidth roofline for the planes it touches).  TPUs have no fast
random scatter — but they have a fast *sort* (1M rows in ~1.9ms,
measured) and a fast *matmul*.  So this kernel restructures the scatter
as dense linear algebra, the idiomatic TPU answer (the same move that
turns embedding lookups into MXU work):

1. **Sort** op rows by a tile-major segment key
   ``(member-tile, plane, member%8, actor)`` with the gated counter as
   a secondary sort key (one XLA bitonic sort, 2 operands).
2. **Dedup**: after the sort the last row of every key-run holds that
   segment's max value; every other row's value is zeroed.  Each
   (member, actor) cell now receives AT MOST ONE nonzero value, so a
   *sum* equals the segment *max* — and a sum of one-hot rows is a
   matmul.
3. **Bin** purely by index arithmetic: per-tile [start, mid, end) row
   ranges from one searchsorted over the sorted keys.  No gather, no
   per-tile padded copy (a round-2 prototype's gather cost more than
   the scatter it replaced) — the kernel reads the sorted arrays in
   place at SUB-aligned offsets and masks boundary rows by position; a
   straddling chunk is visited by both neighbouring tiles, each keeping
   only its own rows.
4. **Pallas kernel**, grid over member tiles: each SUB-row chunk
   becomes transposed one-hot matrices contracted on the MXU —
   ``A_T (8H, SUB) = onehot(member%8 · H + actor//128)``,
   ``B (128, SUB) = onehot(actor%128) · limb(value)`` — accumulating
   the tile's ``(8, R)`` add/rm planes in VMEM, one HBM write per tile.
   Values split into two 7-bit limbs so bf16 MXU passes are exact
   (limbs < 128 ≤ bf16's 8-bit mantissa); requires counters < 2^14
   (``MAX_COUNTER``), which the routing layer checks.
5. The normalize tail (clock advance, ``add>rm`` masking, horizon
   retirement) is the same elementwise XLA pass as ``orset_fold`` —
   bandwidth-bound, fused by XLA.

Staleness (the replay gate against the incoming clock) is applied to the
sorted *values*, not the keys: within a (member, actor, plane) run
staleness is monotone in the counter, so the run-max of gated values is
the max live counter — and the sort/bin/matmul structure stays
independent of the carried clock, which keeps chained benchmark folds
honest (no degenerate cheap iterations at the clock fixpoint).

Byte-equality with ``orset_fold`` (and therefore with the host
reference) is pinned by tests/test_pallas_fold.py; bench.py runs this
as the ``pallas_bf16`` variant of the north-star config.

Reference analogue: the per-op hot loop at
/root/reference/crdt-enc/src/lib.rs:533-539.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .columnar import KIND_ADD, KIND_RM

TILE_E = 8  # members per tile (int32 sublane tile)
LANE = 128
SUB = 1024  # rows per in-kernel matmul chunk

# 7-bit limb split keeps bf16 one-hot matmuls exact; counters must fit.
MAX_COUNTER = 1 << 14
# Sort + window working-set bound; callers chunk bigger batches.
MAX_ROWS = 1 << 22


def _fold_tile_kernel(
    starts_ref, mids_ref, ends_ref,  # scalar prefetch: (T,) row ranges
    klo_ref, khi_ref, vlo_ref, vhi_ref,  # (1, BLK) windows of sorted rows
    out_add_ref, out_rm_ref,  # (1, 8H, 128) int32
    *, H: int, R: int, BLK: int, dot_dtype,
):
    t = pl.program_id(0)
    start, mid, end = starts_ref[t], mids_ref[t], ends_ref[t]
    eightH = TILE_E * H
    base = t * (2 * TILE_E * R)  # tile's key origin
    w0 = (start // BLK) * BLK  # absolute row index of klo/vlo window start

    out_add_ref[...] = jnp.zeros(out_add_ref.shape, jnp.int32)
    out_rm_ref[...] = jnp.zeros(out_rm_ref.shape, jnp.int32)

    # "rows along lanes" orientation throughout: keys/values load as
    # (1, SUB) lane vectors and the one-hot matrices are built directly
    # transposed — no sublane/lane relayouts anywhere in the kernel
    col_iota = jax.lax.broadcasted_iota(jnp.int32, (eightH, SUB), 0)
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (LANE, SUB), 0)
    pos_iota = jax.lax.broadcasted_iota(jnp.int32, (1, SUB), 1)

    acc_t = jnp.int32 if dot_dtype == jnp.int8 else jnp.float32
    dims = (((1,), (1,)), ((), ()))  # contract the SUB axis of both

    def chunk(j, lo, hi, plane_base):
        """Rows [j·SUB, (j+1)·SUB) of the sorted batch, masked to this
        tile's [lo, hi) range: transposed one-hots → limb matmuls →
        an (8H, 128) partial plane.  A chunk never straddles the two
        windows (SUB | BLK), so one select picks its window."""
        off = pl.multiple_of(j * SUB, SUB)
        local = off - w0
        in_hi = local >= BLK
        local = pl.multiple_of(jnp.where(in_hi, local - BLK, local), SUB)

        def load(ref_lo, ref_hi):
            return jax.lax.cond(
                in_hi,
                lambda: ref_hi[0, pl.ds(local, SUB)],
                lambda: ref_lo[0, pl.ds(local, SUB)],
            ).reshape(1, SUB)

        k = load(klo_ref, khi_ref)
        v = load(vlo_ref, vhi_ref)
        pos = pos_iota + off
        ok = (pos >= lo) & (pos < hi)
        rel = k - (base + plane_base)  # = m_local*R + actor for this plane
        m_local = rel // R
        a = rel - m_local * R
        col = jnp.where(ok, m_local * H + (a // LANE), -1)
        a_lo = jnp.where(ok, a % LANE, -1)
        A_T = (col == col_iota).astype(dot_dtype)  # (8H, SUB) 0/1
        hot = a_lo == lane_iota  # (128, SUB)
        v_ok = jnp.where(ok, v, 0)
        B_lo = hot * (v & 127).astype(dot_dtype)
        p_lo = jax.lax.dot_general(A_T, B_lo, dims, preferred_element_type=acc_t)
        # the hi limb is zero for values < 128 — common for dot counters —
        # so its matmul runs only when some row in the chunk needs it
        def with_hi(_):
            p_hi = jax.lax.dot_general(
                A_T, hot * (v >> 7).astype(dot_dtype), dims,
                preferred_element_type=acc_t,
            )
            return (p_hi.astype(jnp.int32) << 7) + p_lo.astype(jnp.int32)

        return jax.lax.cond(
            jnp.max(v_ok) >= 128, with_hi,
            lambda _: p_lo.astype(jnp.int32), None,
        )

    def add_body(j, _):
        out_add_ref[0] += chunk(j, start, mid, 0)
        return 0

    def rm_body(j, _):
        out_rm_ref[0] += chunk(j, mid, end, TILE_E * R)
        return 0

    jax.lax.fori_loop(start // SUB, pl.cdiv(mid, SUB), add_body, 0)
    jax.lax.fori_loop(mid // SUB, pl.cdiv(end, SUB), rm_body, 0)


@partial(
    jax.jit,
    static_argnames=("num_members", "num_replicas", "tile_cap", "retire_rm",
                     "dot_impl", "interpret"),
)
def orset_fold_pallas(
    clock0: jax.Array,  # (R,) int32
    add0: jax.Array,  # (E, R) int32
    rm0: jax.Array,
    kind: jax.Array,  # (N,) int8
    member: jax.Array,  # (N,) int32
    actor: jax.Array,  # (N,) int32  (== num_replicas ⇒ padding row)
    counter: jax.Array,  # (N,) int32  (all < 2^14 — caller asserts)
    *,
    num_members: int,
    num_replicas: int,
    tile_cap: int = 1 << 14,  # ≥ max op rows in any 8-member tile (fold_cap)
    retire_rm: bool = True,
    dot_impl: str = "bf16",  # "bf16" (always exact ≤ 2^14); "int8" reserved
    interpret: bool = False,
):
    """Drop-in replacement for ``orset_fold`` (same contract, same
    normalized output) with the scatter phase on the MXU.  Handles any
    member-tile skew (loop bounds come from the sorted ranges, not a
    padded per-tile capacity); batches beyond ``MAX_ROWS`` must be
    chunked by the caller (the sorted columns are held in VMEM whole)."""
    E, R = num_members, num_replicas
    Ep = -(-E // TILE_E) * TILE_E
    T = Ep // TILE_E
    H = -(-R // LANE)
    N = kind.shape[0]
    if N > MAX_ROWS:
        raise ValueError(
            f"batch of {N} rows exceeds MAX_ROWS={MAX_ROWS}; chunk it"
        )

    pad = actor >= R
    actor_ix = jnp.minimum(actor, R - 1)
    is_add = (kind == KIND_ADD) & ~pad
    is_rm = (kind == KIND_RM) & ~pad

    tile = member // TILE_E
    m_local = member - tile * TILE_E
    plane = is_rm.astype(jnp.int32)
    sentinel = T * (2 * TILE_E * R)
    key = jnp.where(
        is_add | is_rm,
        (tile * 2 + plane) * (TILE_E * R) + m_local * R + actor_ix,
        sentinel,
    )
    gval = jnp.where(is_add | is_rm, counter, 0)
    skey, sval = jax.lax.sort((key, gval), num_keys=2)
    # last-of-run holds the segment max; zeroing the rest makes the
    # one-hot SUM equal the segment MAX (≤ one nonzero per cell)
    nxt = jnp.concatenate([skey[1:], jnp.full((1,), -1, skey.dtype)])
    sval = jnp.where((skey != nxt) & (skey < sentinel), sval, 0)

    # per-tile [start, mid, end): one searchsorted over tile/plane bounds
    bounds = jnp.arange(2 * T + 1, dtype=jnp.int32) * (TILE_E * R)
    edges = jnp.searchsorted(skey, bounds).astype(jnp.int32)
    starts, mids, ends = edges[0:-1:2], edges[1::2], edges[2::2]

    # Window size: each grid step sees two consecutive BLK-blocks of the
    # sorted columns, chosen by scalar-prefetched block indices — a tiny
    # sliding window instead of the whole batch resident (or re-DMA'd)
    # per step.  Two blocks cover any tile with ≤ BLK rows, so BLK is
    # the bucketed per-tile row maximum (fold_cap).
    BLK = SUB
    while BLK < tile_cap:
        BLK *= 2
    # pad to a BLK multiple plus one spare block (the +1 window of the
    # last tile); padding rows are sentinels with zero values
    Np = (-(-N // BLK) + 1) * BLK
    skey = jnp.concatenate([skey, jnp.full((Np - N,), sentinel, jnp.int32)])
    sval = jnp.concatenate([sval, jnp.zeros((Np - N,), jnp.int32)])
    skey = skey.reshape(1, Np)
    sval = sval.reshape(1, Np)

    dot_dtype = jnp.int8 if dot_impl == "int8" else jnp.bfloat16
    win_lo = pl.BlockSpec(
        (1, BLK), lambda t, s, m, e: (0, s[t] // BLK),
        memory_space=pltpu.VMEM,
    )
    # clamp: a tile whose start == N (empty trailing tile) would index
    # one past the padded array; its loops never read the window, so any
    # in-bounds block is fine
    last_blk = Np // BLK - 1
    win_hi = pl.BlockSpec(
        (1, BLK),
        lambda t, s, m, e: (0, jnp.minimum(s[t] // BLK + 1, last_blk)),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(T,),
        in_specs=[win_lo, win_hi, win_lo, win_hi],
        out_specs=[
            pl.BlockSpec((1, TILE_E * H, LANE), lambda t, s, m, e: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_E * H, LANE), lambda t, s, m, e: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
    )
    out_add, out_rm = pl.pallas_call(
        partial(_fold_tile_kernel, H=H, R=R, BLK=BLK, dot_dtype=dot_dtype),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((T, TILE_E * H, LANE), jnp.int32),
            jax.ShapeDtypeStruct((T, TILE_E * H, LANE), jnp.int32),
        ],
        interpret=interpret,
    )(starts, mids, ends, skey, skey, sval, sval)

    # (T, 8H, 128) row-major ≡ (Ep, H·128) row-major: free reshape
    add_new = out_add.reshape(Ep, H * LANE)[:E, :R]
    rm_new = out_rm.reshape(Ep, H * LANE)[:E, :R]

    # the orset_fold tail, verbatim semantics (cell-level replay gate:
    # see the ops/orset.py fold — equivalent to row gating by per-actor
    # dot monotonicity, without the 1M-row clock gather)
    add_new = jnp.where(add_new > clock0[None, :], add_new, 0)
    clock = jnp.maximum(clock0, jnp.max(add_new, axis=0, initial=0))
    add = jnp.maximum(add0, add_new)
    rm = jnp.maximum(rm0, rm_new)
    add = jnp.where(add > rm, add, 0)
    if retire_rm:
        rm = jnp.where(rm > clock[None, :], rm, 0)
    return clock, add, rm


def fold_cap(member, num_members: int) -> int:
    """``tile_cap`` for ``orset_fold_pallas``: the max op-row count over
    8-member tiles (conservative: counts every row, including ones the
    kernel will sort out as padding), bucketed to a power of two so
    recompiles stay bounded.  Determines the kernel's sliding-window
    size; correctness requires the true per-tile count never exceed it,
    which counting every row guarantees."""
    import numpy as np

    E = num_members
    T = max(-(-E // TILE_E), 1)
    counts = np.bincount(
        np.minimum(np.asarray(member) // TILE_E, T - 1), minlength=T
    )
    need = int(counts.max(initial=0))
    cap = SUB
    while cap < need:
        cap *= 2
    return cap

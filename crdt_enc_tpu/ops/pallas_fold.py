"""Pallas TPU kernel: the ORSet fold with the scatter reformulated as
sorted one-hot matmuls on the MXU — the north-star attack (rounds 3-4).

The dense fold (``ops/orset.py orset_fold``) spends its wall in XLA's
scatter-max: random (member, actor) updates serialize at ~9ns/row
(measured: 10.3ms of the 17.1ms fused-i16 fold for 1M rows, against a
~1.2ms bandwidth roofline for the planes it touches).  TPUs have no fast
random scatter — but they have a fast *sort* (1M rows in ~1.9ms,
measured) and a fast *matmul*.  So this kernel restructures the scatter
as dense linear algebra, the idiomatic TPU answer (the same move that
turns embedding lookups into MXU work):

1. **Sort** op rows by a segment-major key with the gated counter as a
   secondary sort key (one XLA bitonic sort, 2 operands).
2. **Dedup**: after the sort the last row of every key-run holds that
   segment's max value; every other row's value is zeroed.  Each
   (member, actor) cell now receives AT MOST ONE nonzero value, so a
   *sum* equals the segment *max* — and a sum of one-hot rows is a
   matmul.
3. **Bin** purely by index arithmetic: per-segment [start, end) row
   ranges from one searchsorted over the sorted keys.  No gather, no
   per-tile padded copy — the kernel reads the sorted arrays in place
   at SUB-aligned offsets and masks boundary rows by position; a
   straddling chunk is visited by both neighbouring segments, each
   keeping only its own rows.
4. **Pallas kernel**, grid over member tiles: each SUB-row chunk
   becomes transposed one-hot matrices contracted on the MXU,
   accumulating the tile's planes in VMEM, one HBM write per tile.
   Values split into two 7-bit limbs so bf16 MXU passes are exact
   (limbs < 128 ≤ bf16's 8-bit mantissa); requires counters < 2^14
   (``MAX_COUNTER``), which the routing layer checks.
5. The normalize tail (clock advance, ``add>rm`` masking, horizon
   retirement) is the same elementwise XLA pass as ``orset_fold`` —
   bandwidth-bound, fused by XLA.

Two kernel layouts (``layout=``):

- ``"ablk"`` (default, round 4): the segment key additionally blocks
  the actor-hi dimension into ``H_BLK``-sized groups, so each chunk's
  contraction is ``(8·H_BLK=128, SUB) × (SUB, 128) → (128, 128)`` —
  a perfect MXU shape.  The wide layout's chunk contraction is
  ``(8·H, SUB) × (SUB, 128)`` with ``H = R/128`` (632 rows at R=10k):
  ~5× the FLOPs and one-hot build work for the same rows, which made
  the matmul phase MXU-bound (~2.5-4ms of the 6.1ms round-3 fold).
  The (128, 128) partial lands in the accumulator as 8 static
  ``H_BLK``-row slice-adds (member-major accumulator rows keep the
  final plane reshape free — a blocked-major layout would need a
  328MB transpose at the end).
- ``"wide"`` (round 3): kept for A/B measurement on hardware.

Staleness (the replay gate against the incoming clock) is applied to the
sorted *values*, not the keys: within a (member, actor, plane) run
staleness is monotone in the counter, so the run-max of gated values is
the max live counter — and the sort/bin/matmul structure stays
independent of the carried clock, which keeps chained benchmark folds
honest (no degenerate cheap iterations at the clock fixpoint).

Byte-equality with ``orset_fold`` (and therefore with the host
reference) is pinned by tests/test_pallas_fold.py for both layouts;
bench.py runs these as the ``pallas_bf16`` / ``pallas_wide`` variants
of the north-star config.

Reference analogue: the per-op hot loop at
/root/reference/crdt-enc/src/lib.rs:533-539.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .columnar import KIND_ADD, KIND_RM

TILE_E = 8  # members per tile (int32 sublane tile)
LANE = 128
SUB = 1024  # rows per in-kernel matmul chunk (wide layout)
SUB_ABLK = 256  # rows per chunk (ablk layout: segments are smaller)

# 7-bit limb split keeps bf16 one-hot matmuls exact; counters must fit.
MAX_COUNTER = 1 << 14
# Sort + window working-set bound; callers chunk bigger batches.
MAX_ROWS = 1 << 22


# --------------------------------------------------------------------------
# wide layout (round 3): one segment per (tile, plane), chunk contraction
# (8H, SUB) x (SUB, 128)
# --------------------------------------------------------------------------


def _fold_tile_kernel_wide(
    starts_ref, mids_ref, ends_ref,  # scalar prefetch: (T,) row ranges
    klo_ref, khi_ref, vlo_ref, vhi_ref,  # (1, BLK) windows of sorted rows
    out_add_ref, out_rm_ref,  # (1, 8H, 128) int32
    *, H: int, R: int, BLK: int, dot_dtype,
):
    t = pl.program_id(0)
    start, mid, end = starts_ref[t], mids_ref[t], ends_ref[t]
    eightH = TILE_E * H
    base = t * (2 * TILE_E * R)  # tile's key origin
    w0 = (start // BLK) * BLK  # absolute row index of klo/vlo window start

    out_add_ref[...] = jnp.zeros(out_add_ref.shape, jnp.int32)
    out_rm_ref[...] = jnp.zeros(out_rm_ref.shape, jnp.int32)

    # "rows along lanes" orientation throughout: keys/values load as
    # (1, SUB) lane vectors and the one-hot matrices are built directly
    # transposed — no sublane/lane relayouts anywhere in the kernel
    col_iota = jax.lax.broadcasted_iota(jnp.int32, (eightH, SUB), 0)
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (LANE, SUB), 0)
    pos_iota = jax.lax.broadcasted_iota(jnp.int32, (1, SUB), 1)

    acc_t = jnp.int32 if dot_dtype == jnp.int8 else jnp.float32
    dims = (((1,), (1,)), ((), ()))  # contract the SUB axis of both

    def chunk(j, lo, hi, plane_base):
        """Rows [j·SUB, (j+1)·SUB) of the sorted batch, masked to this
        tile's [lo, hi) range: transposed one-hots → limb matmuls →
        an (8H, 128) partial plane.  A chunk never straddles the two
        windows (SUB | BLK), so one select picks its window."""
        off = pl.multiple_of(j * SUB, SUB)
        local = off - w0
        in_hi = local >= BLK
        local = pl.multiple_of(jnp.where(in_hi, local - BLK, local), SUB)

        def load(ref_lo, ref_hi):
            return jax.lax.cond(
                in_hi,
                lambda: ref_hi[0, pl.ds(local, SUB)],
                lambda: ref_lo[0, pl.ds(local, SUB)],
            ).reshape(1, SUB)

        k = load(klo_ref, khi_ref)
        v = load(vlo_ref, vhi_ref)
        pos = pos_iota + off
        ok = (pos >= lo) & (pos < hi)
        rel = k - (base + plane_base)  # = m_local*R + actor for this plane
        m_local = rel // R
        a = rel - m_local * R
        col = jnp.where(ok, m_local * H + (a // LANE), -1)
        a_lo = jnp.where(ok, a % LANE, -1)
        A_T = (col == col_iota).astype(dot_dtype)  # (8H, SUB) 0/1
        hot = a_lo == lane_iota  # (128, SUB)
        v_ok = jnp.where(ok, v, 0)
        B_lo = hot * (v & 127).astype(dot_dtype)
        p_lo = jax.lax.dot_general(A_T, B_lo, dims, preferred_element_type=acc_t)
        # the hi limb is zero for values < 128 — common for dot counters —
        # so its matmul runs only when some row in the chunk needs it
        def with_hi(_):
            p_hi = jax.lax.dot_general(
                A_T, hot * (v >> 7).astype(dot_dtype), dims,
                preferred_element_type=acc_t,
            )
            return (p_hi.astype(jnp.int32) << 7) + p_lo.astype(jnp.int32)

        return jax.lax.cond(
            jnp.max(v_ok) >= 128, with_hi,
            lambda _: p_lo.astype(jnp.int32), None,
        )

    def add_body(j, _):
        out_add_ref[0] += chunk(j, start, mid, 0)
        return 0

    def rm_body(j, _):
        out_rm_ref[0] += chunk(j, mid, end, TILE_E * R)
        return 0

    jax.lax.fori_loop(start // SUB, pl.cdiv(mid, SUB), add_body, 0)
    jax.lax.fori_loop(mid // SUB, pl.cdiv(end, SUB), rm_body, 0)


@partial(
    jax.jit,
    static_argnames=("num_members", "num_replicas", "tile_cap", "retire_rm",
                     "dot_impl", "interpret"),
)
def _fold_wide(
    clock0, add0, rm0, kind, member, actor, counter,
    *, num_members, num_replicas, tile_cap, retire_rm, dot_impl, interpret,
):
    E, R = num_members, num_replicas
    Ep = -(-E // TILE_E) * TILE_E
    T = Ep // TILE_E
    H = -(-R // LANE)
    N = kind.shape[0]

    pad = actor >= R
    actor_ix = jnp.minimum(actor, R - 1)
    is_add = (kind == KIND_ADD) & ~pad
    is_rm = (kind == KIND_RM) & ~pad

    tile = member // TILE_E
    m_local = member - tile * TILE_E
    plane = is_rm.astype(jnp.int32)
    sentinel = T * (2 * TILE_E * R)
    key = jnp.where(
        is_add | is_rm,
        (tile * 2 + plane) * (TILE_E * R) + m_local * R + actor_ix,
        sentinel,
    )
    gval = jnp.where(is_add | is_rm, counter, 0)
    skey, sval = jax.lax.sort((key, gval), num_keys=2)
    # last-of-run holds the segment max; zeroing the rest makes the
    # one-hot SUM equal the segment MAX (≤ one nonzero per cell)
    nxt = jnp.concatenate([skey[1:], jnp.full((1,), -1, skey.dtype)])
    sval = jnp.where((skey != nxt) & (skey < sentinel), sval, 0)

    # per-tile [start, mid, end): one searchsorted over tile/plane bounds
    bounds = jnp.arange(2 * T + 1, dtype=jnp.int32) * (TILE_E * R)
    edges = jnp.searchsorted(skey, bounds).astype(jnp.int32)
    starts, mids, ends = edges[0:-1:2], edges[1::2], edges[2::2]

    # Window size: each grid step sees two consecutive BLK-blocks of the
    # sorted columns, chosen by scalar-prefetched block indices — a tiny
    # sliding window instead of the whole batch resident (or re-DMA'd)
    # per step.  Two blocks cover any tile with ≤ BLK rows, so BLK is
    # the bucketed per-tile row maximum (fold_cap).
    BLK = SUB
    while BLK < tile_cap:
        BLK *= 2
    # pad to a BLK multiple plus one spare block (the +1 window of the
    # last tile); padding rows are sentinels with zero values
    Np = (-(-N // BLK) + 1) * BLK
    skey = jnp.concatenate([skey, jnp.full((Np - N,), sentinel, jnp.int32)])
    sval = jnp.concatenate([sval, jnp.zeros((Np - N,), jnp.int32)])
    skey = skey.reshape(1, Np)
    sval = sval.reshape(1, Np)

    dot_dtype = jnp.int8 if dot_impl == "int8" else jnp.bfloat16
    win_lo = pl.BlockSpec(
        (1, BLK), lambda t, s, m, e: (0, s[t] // BLK),
        memory_space=pltpu.VMEM,
    )
    # clamp: a tile whose start == N (empty trailing tile) would index
    # one past the padded array; its loops never read the window, so any
    # in-bounds block is fine
    last_blk = Np // BLK - 1
    win_hi = pl.BlockSpec(
        (1, BLK),
        lambda t, s, m, e: (0, jnp.minimum(s[t] // BLK + 1, last_blk)),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(T,),
        in_specs=[win_lo, win_hi, win_lo, win_hi],
        out_specs=[
            pl.BlockSpec((1, TILE_E * H, LANE), lambda t, s, m, e: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_E * H, LANE), lambda t, s, m, e: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
    )
    out_add, out_rm = pl.pallas_call(
        partial(_fold_tile_kernel_wide, H=H, R=R, BLK=BLK, dot_dtype=dot_dtype),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((T, TILE_E * H, LANE), jnp.int32),
            jax.ShapeDtypeStruct((T, TILE_E * H, LANE), jnp.int32),
        ],
        interpret=interpret,
    )(starts, mids, ends, skey, skey, sval, sval)

    # (T, 8H, 128) row-major ≡ (Ep, H·128) row-major: free reshape
    add_new = out_add.reshape(Ep, H * LANE)[:E, :R]
    rm_new = out_rm.reshape(Ep, H * LANE)[:E, :R]
    return _normalize_tail(clock0, add0, rm0, add_new, rm_new, retire_rm)


# --------------------------------------------------------------------------
# ablk layout (round 4): segments block the actor-hi dimension so every
# chunk contraction is (128, SUB) x (SUB, 128) — the native MXU shape
# --------------------------------------------------------------------------


def _fold_tile_kernel_ablk(
    edges_ref,  # scalar prefetch: (n_segs+1,) segment row ranges
    klo_ref, khi_ref, vlo_ref, vhi_ref,  # (1, BLK) windows of sorted rows
    out_add_ref, out_rm_ref,  # (1, 8·Hp, 128) int32
    *, Hp: int, H_BLK: int, A_BLK: int, BLK: int, SUBK: int, dot_dtype,
    hi_mode: str = "cond", win_mode: str = "select",
    acc_mode: str = "member", dedup_mode: str = "sorted",
    limb_bits: int = 7,
):
    t = pl.program_id(0)
    nseg_t = 2 * A_BLK
    base_seg = t * nseg_t
    SEG = TILE_E * H_BLK * LANE  # key span of one segment
    tile_start = edges_ref[base_seg]
    w0 = (tile_start // BLK) * BLK

    out_add_ref[...] = jnp.zeros(out_add_ref.shape, jnp.int32)
    out_rm_ref[...] = jnp.zeros(out_rm_ref.shape, jnp.int32)

    rows = TILE_E * H_BLK  # 128 when H_BLK=16: the MXU-native height
    row_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, SUBK), 0)
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (LANE, SUBK), 0)
    pos_iota = jax.lax.broadcasted_iota(jnp.int32, (1, SUBK), 1)

    acc_t = jnp.int32 if dot_dtype == jnp.int8 else jnp.float32
    dims = (((1,), (1,)), ((), ()))  # contract the SUBK axis of both

    def shift_r(x, d, fill):
        # along lanes: out[0, i] = x[0, i-d] (fill for i < d)
        return jnp.concatenate(
            [jnp.full((1, d), fill, x.dtype), x[:, : SUBK - d]], axis=1
        )

    def shift_l(x, d, fill):
        return jnp.concatenate(
            [x[:, d:], jnp.full((1, d), fill, x.dtype)], axis=1
        )

    def chunk(j, lo, hi, seg_base, carry):
        """Rows [j·SUBK, (j+1)·SUBK) of the sorted batch, masked to this
        segment's [lo, hi) range: (rows, SUBK) × (SUBK, 128) limb
        matmuls → a (rows, 128) partial.  Keys outside the segment
        decode to a one-hot row outside [0, rows), zeroing their A_T
        column; the position mask besides zeroes their value.

        ``dedup_mode="kernel"``: the prologue sorted by KEY ONLY
        (num_keys=1 — the 2-operand comparator was ~1ms of the sort),
        so run-max dedup happens here: a segmented Hillis-Steele max
        scan along lanes (legal because keys are sorted: equal endpoint
        keys ⇒ the whole span shares the key), seeded by the loop
        carry (last key, its max-so-far), emitting at run ends the
        TELESCOPED delta ``run_max − already_emitted`` — the
        accumulator sums deltas per cell, so the sum still equals the
        final max, even for runs spanning many chunks."""
        off = pl.multiple_of(j * SUBK, SUBK)
        local = off - w0
        in_hi = local >= BLK
        local = pl.multiple_of(jnp.where(in_hi, local - BLK, local), SUBK)

        if win_mode == "select":
            # branchless: load both windows at the (already-adjusted)
            # offset and vector-select; the wrong window's load is
            # in-bounds garbage that the select discards
            def load(ref_lo, ref_hi):
                lo_v = ref_lo[0, pl.ds(local, SUBK)]
                hi_v = ref_hi[0, pl.ds(local, SUBK)]
                return jnp.where(in_hi, hi_v, lo_v).reshape(1, SUBK)
        else:
            def load(ref_lo, ref_hi):
                return jax.lax.cond(
                    in_hi,
                    lambda: ref_hi[0, pl.ds(local, SUBK)],
                    lambda: ref_lo[0, pl.ds(local, SUBK)],
                ).reshape(1, SUBK)

        k = load(klo_ref, khi_ref)
        v = load(vlo_ref, vhi_ref)
        pos = pos_iota + off
        ok = (pos >= lo) & (pos < hi)
        rel = k - seg_base  # = (m_local·H_BLK + a_hi_local)·128 + a_lo
        row = jnp.where(ok, rel >> 7, -1)
        a_lo = jnp.where(ok, rel & (LANE - 1), -1)
        A_T = (row == row_iota).astype(dot_dtype)  # (rows, SUBK) 0/1
        hot = a_lo == lane_iota  # (128, SUBK)

        if dedup_mode == "kernel":
            ck, cm = carry  # (1, 1) int32: last key, its emitted max
            # masked lanes get unique pseudo-keys (≤ -2) so no run can
            # cross them; masked lanes are only a prefix (first chunk)
            # or suffix (last chunk) of the segment's range
            kk = jnp.where(ok, k, -(pos + 2))
            m = jnp.where(ok, v, 0)
            # prefix-of-first-run flag as int32 0/1 — Mosaic cannot
            # shift/concat i1 mask vectors ("invalid vector register
            # cast" on the i1 bitcast), so the AND-scan runs as min
            f = (kk == ck).astype(jnp.int32)
            d = 1
            while d < SUBK:
                kp = shift_r(kk, d, jnp.int32(-1))
                mp = shift_r(m, d, jnp.int32(0))
                m = jnp.where(kk == kp, jnp.maximum(m, mp), m)
                f = jnp.minimum(f, shift_r(f, d, jnp.int32(1)))
                d *= 2
            fb = f > 0
            # seed the carried run's prefix with its max-so-far
            m = jnp.where(fb, jnp.maximum(m, cm), m)
            run_end = (kk != shift_l(kk, 1, jnp.int32(-9))) & ok
            v_ok = jnp.where(run_end, m - jnp.where(fb, cm, 0), 0)
            carry = (kk[:, SUBK - 1:], m[:, SUBK - 1:])
        else:
            v_ok = jnp.where(ok, v, 0)
        # limb split: bf16 carries 8 significant bits, so integer limbs up
        # to 2^8 are exact — limb_bits=8 halves the skip threshold's
        # strictness vs the round-3/4 conservative 7
        lmask = (1 << limb_bits) - 1
        B_lo = hot * (v_ok & lmask).astype(dot_dtype)

        if hi_mode == "skip":
            # caller statically guarantees every counter < 2^limb_bits
            p_lo = jax.lax.dot_general(
                A_T, B_lo, dims, preferred_element_type=acc_t
            )
            return p_lo.astype(jnp.int32), carry

        if hi_mode == "fused":
            # one MXU call: stack the two limb operands along the output
            # lanes — no scalar reduce, no branch; ~2× the lo-only FLOPs
            # but the matmul phase is far from the wall at these shapes
            B2 = jnp.concatenate(
                [B_lo, hot * (v_ok >> limb_bits).astype(dot_dtype)], axis=0
            )  # (2·LANE, SUBK)
            p2 = jax.lax.dot_general(
                A_T, B2, dims, preferred_element_type=acc_t
            )
            return (
                (p2[:, LANE:].astype(jnp.int32) << limb_bits)
                + p2[:, :LANE].astype(jnp.int32)
            ), carry

        p_lo = jax.lax.dot_general(A_T, B_lo, dims, preferred_element_type=acc_t)

        def with_hi(_):
            p_hi = jax.lax.dot_general(
                A_T, hot * (v_ok >> limb_bits).astype(dot_dtype), dims,
                preferred_element_type=acc_t,
            )
            return (p_hi.astype(jnp.int32) << limb_bits) + p_lo.astype(jnp.int32)

        return jax.lax.cond(
            jnp.max(v_ok) >= (1 << limb_bits), with_hi,
            lambda _: p_lo.astype(jnp.int32), None,
        ), carry

    # planes and actor-hi blocks are static → fully unrolled; only the
    # chunk index inside each segment is a dynamic loop
    carry0 = (
        jnp.full((1, 1), -1, jnp.int32),  # no real key is negative
        jnp.zeros((1, 1), jnp.int32),
    )
    for p, out_ref in ((0, out_add_ref), (1, out_rm_ref)):
        for b in range(A_BLK):
            s = base_seg + p * A_BLK + b
            lo = edges_ref[s]
            hi = edges_ref[s + 1]
            seg_base = (t * nseg_t + p * A_BLK + b) * SEG

            def body(j, car, lo=lo, hi=hi, seg_base=seg_base,
                     out_ref=out_ref, b=b):
                part, car = chunk(j, lo, hi, seg_base, car)
                if acc_mode == "blocked":
                    # one contiguous 128-row add; the accumulator is
                    # block-major and the caller transposes once in XLA
                    # (fused into the normalize tail's first read)
                    r0 = b * (TILE_E * H_BLK)
                    out_ref[0, r0:r0 + TILE_E * H_BLK, :] += part
                else:
                    # scatter the (8·H_BLK, 128) partial into the
                    # member-major accumulator as 8 static slice-adds
                    for m in range(TILE_E):
                        r0 = m * Hp + b * H_BLK
                        out_ref[0, r0:r0 + H_BLK, :] += (
                            part[m * H_BLK:(m + 1) * H_BLK, :]
                        )
                return car

            start_j = lo // SUBK
            end_j = jnp.where(lo == hi, start_j, pl.cdiv(hi, SUBK))
            jax.lax.fori_loop(start_j, end_j, body, carry0)


@partial(
    jax.jit,
    static_argnames=("num_members", "num_replicas", "tile_cap", "retire_rm",
                     "dot_impl", "interpret", "sub_rows", "hi_mode",
                     "win_mode"),
)
def _fold_ablk(
    clock0, add0, rm0, kind, member, actor, counter,
    *, num_members, num_replicas, tile_cap, retire_rm, dot_impl, interpret,
    sub_rows=SUB_ABLK, hi_mode="cond", win_mode="select",
):
    add_new, rm_new = orset_scatter_pallas(
        kind, member, actor, counter, num_members=num_members,
        num_replicas=num_replicas, tile_cap=tile_cap, dot_impl=dot_impl,
        interpret=interpret, sub_rows=sub_rows, hi_mode=hi_mode,
        win_mode=win_mode,
    )
    return _normalize_tail(clock0, add0, rm0, add_new, rm_new, retire_rm)


class _AblkGeom:
    """Static geometry of the ablk layout for (E, R) — one place, used
    by the standalone scatter, the fused-tail fold, and the padded-plane
    helpers."""

    def __init__(self, E: int, R: int, h_blk: int | None = None):
        self.E, self.R = E, R
        self.Ep = -(-E // TILE_E) * TILE_E
        self.T = self.Ep // TILE_E
        self.H = -(-R // LANE)
        # actor-hi blocking: H_BLK=16 makes 8·H_BLK = 128 one-hot rows —
        # the MXU-native matmul height.  Small R degenerates to one
        # block.  Larger blocks trade one-hot height (extra VPU compares
        # + MXU FLOPs, both far from the wall) for fewer segments and
        # thus fewer boundary chunk visits — the round-5 sweep measured
        # the visit count, not the FLOPs, as the kernel's cost driver.
        if h_blk is None:
            h_blk = 16 if self.H > 8 else 8
        self.H_BLK = h_blk
        self.Hp = -(-self.H // self.H_BLK) * self.H_BLK
        self.A_BLK = self.Hp // self.H_BLK
        self.SEG = TILE_E * self.H_BLK * LANE
        self.n_segs = 2 * self.T * self.A_BLK
        self.Rp = self.Hp * LANE  # padded actor width of the planes

    def fits_int32(self) -> bool:
        """Whether this geometry's segment keys fit int32."""
        return 2 * self.Ep * self.Hp * LANE < 2 ** 31


def _ablk_prologue(g: _AblkGeom, kind, member, actor, counter,
                   *, tile_cap, sub_rows, dedup_mode="sorted"):
    """The XLA front half shared by every ablk path: segment keys, the
    (key, counter) sort, run-max dedup, per-segment edges, and the
    window padding.  Returns (edges, skey, sval, BLK, Np)."""
    R, SEG, n_segs = g.R, g.SEG, g.n_segs
    N = kind.shape[0]

    pad = actor >= R
    actor_ix = jnp.minimum(actor, R - 1)
    is_add = (kind == KIND_ADD) & ~pad
    is_rm = (kind == KIND_RM) & ~pad

    tile = member // TILE_E
    m_local = member - tile * TILE_E
    plane = is_rm.astype(jnp.int32)
    a_hi = actor_ix // LANE
    a_lo = actor_ix - a_hi * LANE
    blk = a_hi // g.H_BLK
    a_hil = a_hi - blk * g.H_BLK
    seg_id = (tile * 2 + plane) * g.A_BLK + blk
    within = (m_local * g.H_BLK + a_hil) * LANE + a_lo
    sentinel = n_segs * SEG
    key = jnp.where(is_add | is_rm, seg_id * SEG + within, sentinel)
    gval = jnp.where(is_add | is_rm, counter, 0)
    # (a single-operand key·2^14+counter packed sort would halve the
    # comparator's operand traffic, but int64 is unavailable under the
    # default x64-disabled config and the key space overflows int32)
    if dedup_mode == "kernel":
        # key-only comparator; run-max dedup happens inside the kernel
        # via a segmented scan.  Measured 2× SLOWER than the 2-key sort
        # on hardware (2026-07-31, round-5 A/B) — kept for the record.
        skey, sval = jax.lax.sort((key, gval), num_keys=1)
    else:
        skey, sval = jax.lax.sort((key, gval), num_keys=2)
        nxt = jnp.concatenate([skey[1:], jnp.full((1,), -1, skey.dtype)])
        sval = jnp.where((skey != nxt) & (skey < sentinel), sval, 0)

    # per-segment [start, end): one searchsorted over segment bounds
    bounds = jnp.arange(n_segs + 1, dtype=jnp.int32) * SEG
    edges = jnp.searchsorted(skey, bounds).astype(jnp.int32)

    BLK = sub_rows
    while BLK < tile_cap:
        BLK *= 2
    Np = (-(-N // BLK) + 1) * BLK
    skey = jnp.concatenate([skey, jnp.full((Np - N,), sentinel, jnp.int32)])
    sval = jnp.concatenate([sval, jnp.zeros((Np - N,), jnp.int32)])
    return edges, skey.reshape(1, Np), sval.reshape(1, Np), BLK, Np


def _ablk_window_specs(g: _AblkGeom, BLK: int, Np: int):
    """The four sliding-window BlockSpecs (key lo/hi, val lo/hi)."""
    nseg_t = 2 * g.A_BLK
    win_lo = pl.BlockSpec(
        (1, BLK), lambda t, e: (0, e[t * nseg_t] // BLK),
        memory_space=pltpu.VMEM,
    )
    last_blk = Np // BLK - 1
    win_hi = pl.BlockSpec(
        (1, BLK),
        lambda t, e: (0, jnp.minimum(e[t * nseg_t] // BLK + 1, last_blk)),
        memory_space=pltpu.VMEM,
    )
    return [win_lo, win_hi, win_lo, win_hi]


def orset_scatter_pallas(
    kind, member, actor, counter,
    *, num_members, num_replicas, tile_cap, dot_impl="bf16",
    interpret=False, sub_rows=SUB_ABLK, hi_mode="cond", win_mode="select",
    acc_mode="member", dedup_mode="sorted", limb_bits=7,
):
    """The ablk layout's scatter phase alone: raw segment-max planes
    ``(add_new, rm_new)`` with no replay gate or normalization.  The
    sharded fold (parallel/mesh.py) calls this per device inside
    shard_map — partials combine across ``dp`` with a ``pmax`` and the
    normalize tail runs once after — so a mesh compaction runs the same
    flagship kernel as a single chip.  Traceable (no data-dependent
    Python); ``tile_cap`` must be the caller's static bound."""
    E, R = num_members, num_replicas
    if not ablk_key_space_fits(E, R):
        # the front door (orset_fold_pallas) reroutes to the wide layout
        # past this bound; direct callers (the sharded fold) must gate
        raise ValueError(
            f"E={E}, R={R} overflows the ablk layout's int32 segment "
            "keys; route this shape to the XLA fold"
        )
    g = _AblkGeom(E, R)
    T, Hp = g.T, g.Hp
    edges, skey, sval, BLK, Np = _ablk_prologue(
        g, kind, member, actor, counter,
        tile_cap=tile_cap, sub_rows=sub_rows, dedup_mode=dedup_mode,
    )

    dot_dtype = jnp.int8 if dot_impl == "int8" else jnp.bfloat16
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(T,),
        in_specs=_ablk_window_specs(g, BLK, Np),
        out_specs=[
            pl.BlockSpec((1, TILE_E * Hp, LANE), lambda t, e: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_E * Hp, LANE), lambda t, e: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
    )
    out_add, out_rm = pl.pallas_call(
        partial(_fold_tile_kernel_ablk, Hp=Hp, H_BLK=g.H_BLK, A_BLK=g.A_BLK,
                BLK=BLK, SUBK=sub_rows, dot_dtype=dot_dtype,
                hi_mode=hi_mode, win_mode=win_mode, acc_mode=acc_mode,
                dedup_mode=dedup_mode, limb_bits=limb_bits),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((T, TILE_E * Hp, LANE), jnp.int32),
            jax.ShapeDtypeStruct((T, TILE_E * Hp, LANE), jnp.int32),
        ],
        interpret=interpret,
    )(edges, skey, skey, sval, sval)

    if acc_mode == "blocked":
        # block-major accumulator rows (blk, m_local, a_hil): one XLA
        # transpose back to member-major — fused into the consumer's
        # first elementwise read in the common case
        def decode(o):
            o = o.reshape(T, g.A_BLK, TILE_E, g.H_BLK, LANE)
            o = o.transpose(0, 2, 1, 3, 4)
            return o.reshape(g.Ep, Hp * LANE)[:E, :R]

        return decode(out_add), decode(out_rm)

    # accumulator rows are member-major (m_local·Hp + a_hi), so
    # (T, 8·Hp, 128) row-major ≡ (Ep, Hp·128) row-major: free reshape
    add_new = out_add.reshape(g.Ep, Hp * LANE)[:E, :R]
    rm_new = out_rm.reshape(g.Ep, Hp * LANE)[:E, :R]
    return add_new, rm_new


# --------------------------------------------------------------------------
# fused-tail path (round 5): the normalize tail runs in the kernel epilogue
#
# Round-5 phase profile (TPU v5 lite, 2026-07-31): full fold 7.0ms =
# scatter-incl-prologue 3.4ms + XLA normalize tail ~3.6ms — the tail's
# elementwise pass over four (E, R) planes was the wall, ~3× its ~1.2ms
# traffic roofline (XLA materializes the gated intermediate and the
# axis-0 clock reduce as separate passes).  The fused path applies the
# replay gate, the clock max-reduce, and the add/rm merge in the kernel
# epilogue while each tile's accumulator is still in VMEM: the planes
# are then read once (add0/rm0 input blocks) and written once.  Only rm
# retirement stays in XLA — it needs the globally-reduced clock.
#
# The planes live PADDED in this path — (Ep, Hp·128) with zero padding,
# clock (Hp·128,) — matching the accumulator layout so the reshape
# between XLA and kernel stays free; chained folds (compaction sessions,
# the bench chain) carry padded planes and pad/slice once per session
# via orset_pad_state / orset_unpad_state.
# --------------------------------------------------------------------------


def _fold_tile_kernel_ablk_fused(
    edges_ref,  # scalar prefetch: (n_segs+1,) segment row ranges
    klo_ref, khi_ref, vlo_ref, vhi_ref,  # (1, BLK) windows of sorted rows
    clock0_ref,  # (Hp, 128) int32 — padded incoming clock
    add0_ref, rm0_ref,  # (1, 8·Hp, 128) int32 — this tile's prior planes
    add_out_ref, rm_out_ref,  # (1, 8·Hp, 128) int32 — final add, pre-retire rm
    clock_out_ref,  # (Hp, 128) int32 — max-accumulated across tiles
    acc_add, acc_rm,  # VMEM scratch (1, 8·Hp, 128) int32: raw segment maxes
    *, Hp: int, H_BLK: int, A_BLK: int, BLK: int, SUBK: int, dot_dtype,
    hi_mode: str, win_mode: str, limb_bits: int,
):
    # scatter phase into scratch — the unfused kernel body, verbatim
    _fold_tile_kernel_ablk(
        edges_ref, klo_ref, khi_ref, vlo_ref, vhi_ref, acc_add, acc_rm,
        Hp=Hp, H_BLK=H_BLK, A_BLK=A_BLK, BLK=BLK, SUBK=SUBK,
        dot_dtype=dot_dtype, hi_mode=hi_mode, win_mode=win_mode,
        acc_mode="member", dedup_mode="sorted", limb_bits=limb_bits,
    )
    # epilogue: _normalize_tail minus rm retirement, per member row-group
    t = pl.program_id(0)
    ck = clock0_ref[...]  # (Hp, LANE)

    @pl.when(t == 0)
    def _init():
        clock_out_ref[...] = ck

    contrib = None
    for m in range(TILE_E):
        r0 = m * Hp
        a_new = acc_add[0, r0:r0 + Hp, :]
        gated = jnp.where(a_new > ck, a_new, 0)  # cell-level replay gate
        contrib = gated if contrib is None else jnp.maximum(contrib, gated)
        a_m = jnp.maximum(add0_ref[0, r0:r0 + Hp, :], gated)
        # retire-on-read: identity on well-formed (retired) rm0, and on
        # a deferred-chain carry it reconstructs exactly the rm the
        # eager chain would have carried — so chains may skip the
        # per-fold XLA retire pass and finalize once (orset_retire)
        r_prev = rm0_ref[0, r0:r0 + Hp, :]
        r_prev = jnp.where(r_prev > ck, r_prev, 0)
        r_m = jnp.maximum(r_prev, acc_rm[0, r0:r0 + Hp, :])
        add_out_ref[0, r0:r0 + Hp, :] = jnp.where(a_m > r_m, a_m, 0)
        rm_out_ref[0, r0:r0 + Hp, :] = r_m
    clock_out_ref[...] = jnp.maximum(clock_out_ref[...], contrib)


def orset_pad_state(clock0, add0, rm0, *, num_members, num_replicas,
                    h_blk=None):
    """Pad ``(clock (R,), add (E,R), rm (E,R))`` to the fused path's
    carried layout ``(clock (Hp·128,), planes (Ep, Hp·128))`` — zeros in
    the pad region, which every fused fold preserves."""
    g = _AblkGeom(num_members, num_replicas, h_blk)
    cp = jnp.pad(clock0, (0, g.Rp - g.R))
    ap = jnp.pad(add0, ((0, g.Ep - g.E), (0, g.Rp - g.R)))
    rp = jnp.pad(rm0, ((0, g.Ep - g.E), (0, g.Rp - g.R)))
    return cp, ap, rp


def orset_unpad_state(clockp, addp, rmp, *, num_members, num_replicas):
    """Inverse of ``orset_pad_state``."""
    E, R = num_members, num_replicas
    return clockp[:R], addp[:E, :R], rmp[:E, :R]


def fused_defaults(num_members: int, num_replicas: int,
                   counter_max: int) -> dict:
    """Host-side routing for the fused fold's static knobs (round-5
    sweep, TPU v5 lite): h_blk=32 at large R cuts the segment count —
    and thus the boundary chunk visits, the measured cost driver — 22%
    over h_blk=16; limb_bits=8 is exact in bf16 (integers ≤ 2^8), and
    when the batch's max counter is known < 256 the hi-limb branch is
    provably dead, so ``hi_mode="skip"`` drops the per-chunk max-reduce
    + cond entirely (4.70ms vs 6.08ms at h_blk=16 on the north-star
    shape).  Callers know the batch max (decode layers track it; dense
    callers take one np.max)."""
    H = -(-num_replicas // LANE)
    h_blk = 32 if H > 16 else (16 if H > 8 else 8)
    # a larger block pads Hp up — fall back if that padding overflows
    # the int32 key space on a shape the default geometry accepts
    while h_blk > 8 and not _AblkGeom(
            num_members, num_replicas, h_blk).fits_int32():
        h_blk //= 2
    hi_mode = "skip" if counter_max < 256 else "cond"
    return dict(h_blk=h_blk, hi_mode=hi_mode, limb_bits=8)


def orset_retire(clockp, rmp):
    """Finalize a deferred chain: the rm retirement the chain's folds
    skipped (``retire_rm=False``).  One elementwise pass; byte-equal to
    the eager chain's final rm (proof: retire-on-read in the epilogue
    reconstructs the eager carry at every step)."""
    return jnp.where(rmp > clockp[None, :], rmp, 0)


@partial(
    jax.jit,
    static_argnames=("num_members", "num_replicas", "tile_cap", "retire_rm",
                     "dot_impl", "interpret", "sub_rows", "hi_mode",
                     "win_mode", "limb_bits", "h_blk"),
)
def orset_fold_pallas_fused(
    clockp, addp, rmp,  # PADDED state: (Hp·128,), (Ep, Hp·128) ×2
    kind, member, actor, counter,
    *, num_members, num_replicas, tile_cap, retire_rm=True,
    dot_impl="bf16", interpret=False, sub_rows=SUB_ABLK,
    hi_mode="cond", win_mode="select", limb_bits=7, h_blk=None,
):
    """The flagship fold with the normalize tail fused into the kernel
    epilogue.  Same output as ``orset_fold_pallas`` under
    ``orset_pad_state``/``orset_unpad_state`` (byte-equality pinned in
    tests/test_pallas_fold.py).  ``hi_mode="skip"`` is legal only when
    every counter < 2^limb_bits (host-routed; decode layers know the
    batch max).  With ``retire_rm=False`` the output rm is DEFERRED
    (unretired); chain folds that way and finalize with
    ``orset_retire`` — byte-equal to the eager chain.  ``rm0`` must be
    retired w.r.t. ``clock0`` or a deferred-chain carry (the epilogue
    retires it on read).  Reference analogue: the per-op hot loop
    /root/reference/crdt-enc/src/lib.rs:533-539."""
    E, R = num_members, num_replicas
    g = _AblkGeom(E, R, h_blk)
    if not g.fits_int32():
        raise ValueError(
            f"E={E}, R={R} overflows the ablk layout's int32 segment "
            "keys; route this shape through orset_fold_pallas"
        )
    T, Hp = g.T, g.Hp
    edges, skey, sval, BLK, Np = _ablk_prologue(
        g, kind, member, actor, counter,
        tile_cap=tile_cap, sub_rows=sub_rows,
    )

    clock2d = clockp.reshape(Hp, LANE)
    add0t = addp.reshape(T, TILE_E * Hp, LANE)  # free: member-major rows
    rm0t = rmp.reshape(T, TILE_E * Hp, LANE)

    dot_dtype = jnp.int8 if dot_impl == "int8" else jnp.bfloat16
    plane_in = pl.BlockSpec((1, TILE_E * Hp, LANE), lambda t, e: (t, 0, 0),
                            memory_space=pltpu.VMEM)
    clock_spec = pl.BlockSpec((Hp, LANE), lambda t, e: (0, 0),
                              memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(T,),
        in_specs=_ablk_window_specs(g, BLK, Np)
        + [clock_spec, plane_in, plane_in],
        out_specs=[plane_in, plane_in, clock_spec],
        scratch_shapes=[
            pltpu.VMEM((1, TILE_E * Hp, LANE), jnp.int32),
            pltpu.VMEM((1, TILE_E * Hp, LANE), jnp.int32),
        ],
    )
    add_out, rm_pre, clock_out = pl.pallas_call(
        partial(_fold_tile_kernel_ablk_fused, Hp=Hp, H_BLK=g.H_BLK,
                A_BLK=g.A_BLK, BLK=BLK, SUBK=sub_rows, dot_dtype=dot_dtype,
                hi_mode=hi_mode, win_mode=win_mode, limb_bits=limb_bits),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((T, TILE_E * Hp, LANE), jnp.int32),
            jax.ShapeDtypeStruct((T, TILE_E * Hp, LANE), jnp.int32),
            jax.ShapeDtypeStruct((Hp, LANE), jnp.int32),
        ],
        interpret=interpret,
    )(edges, skey, skey, sval, sval, clock2d, add0t, rm0t)

    clockp_new = clock_out.reshape(g.Rp)
    addp_new = add_out.reshape(g.Ep, g.Rp)
    rmp_new = rm_pre.reshape(g.Ep, g.Rp)
    if retire_rm:
        # the one tail step that needs the globally-reduced clock
        rmp_new = orset_retire(clockp_new, rmp_new)
    return clockp_new, addp_new, rmp_new


def _normalize_tail(clock0, add0, rm0, add_new, rm_new, retire_rm):
    """The orset_fold tail, verbatim semantics (cell-level replay gate:
    see the ops/orset.py fold — equivalent to row gating by per-actor
    dot monotonicity, without the 1M-row clock gather)."""
    add_new = jnp.where(add_new > clock0[None, :], add_new, 0)
    clock = jnp.maximum(clock0, jnp.max(add_new, axis=0, initial=0))
    add = jnp.maximum(add0, add_new)
    rm = jnp.maximum(rm0, rm_new)
    add = jnp.where(add > rm, add, 0)
    if retire_rm:
        rm = jnp.where(rm > clock[None, :], rm, 0)
    return clock, add, rm


def orset_fold_pallas(
    clock0: jax.Array,  # (R,) int32
    add0: jax.Array,  # (E, R) int32
    rm0: jax.Array,
    kind: jax.Array,  # (N,) int8
    member: jax.Array,  # (N,) int32
    actor: jax.Array,  # (N,) int32  (== num_replicas ⇒ padding row)
    counter: jax.Array,  # (N,) int32  (all < 2^14 — caller asserts)
    *,
    num_members: int,
    num_replicas: int,
    tile_cap: int | None = None,  # ≥ max op rows in any 8-member tile
    retire_rm: bool = True,
    dot_impl: str = "bf16",  # "bf16" (always exact ≤ 2^14); "int8" reserved
    interpret: bool = False,
    layout: str = "ablk",  # "ablk" (round 4, default) | "wide" (round 3)
    hi_mode: str = "cond",  # "cond" | "fused" | "skip" (ablk only; "skip"
    #   is legal ONLY when every counter < 128 — caller's static promise)
    win_mode: str = "select",  # "select" | "cond" (ablk only).  Default
    #   is the branchless dual-load + vector select: measured 5.08ms vs
    #   7.68ms scatter phase on the north-star shape (2026-07-31) — the
    #   per-chunk window cond was a third of the kernel's wall.  A
    #   "fused" hi_mode measured FASTER than "cond" alone (5.33) but
    #   REGRESSED combined with select (7.42): Mosaic scheduling, not
    #   arithmetic — so the data-dependent hi-limb cond stays default.
):
    """Drop-in replacement for ``orset_fold`` (same contract, same
    normalized output) with the scatter phase on the MXU.  Handles any
    member-tile skew (loop bounds come from the sorted ranges, not a
    padded per-tile capacity); batches beyond ``MAX_ROWS`` must be
    chunked by the caller (the sorted columns are held in VMEM whole).

    ``tile_cap`` bounds the sliding window; a cap below the densest
    tile's row count would silently drop rows, so concrete callers get
    it computed here when omitted; an explicit cap is trusted (derive it
    with ``fold_cap``) and callers inside a jit trace MUST pass one."""
    E, R = num_members, num_replicas
    N = kind.shape[0]
    if N > MAX_ROWS:
        raise ValueError(
            f"batch of {N} rows exceeds MAX_ROWS={MAX_ROWS}; chunk it"
        )
    if tile_cap is None:
        if isinstance(member, jax.core.Tracer):
            raise ValueError(
                "orset_fold_pallas under jit needs an explicit static "
                "tile_cap (compute it host-side with fold_cap)"
            )
        import numpy as _np

        # computed here for concrete callers; an explicit cap is trusted
        # (every in-repo caller derives it from fold_cap — re-validating
        # would re-run the O(N) bincount on the flagship path)
        tile_cap = fold_cap(_np.asarray(member), E)
    # both layouts' key spaces are ~2·Ep·(R padded): guard int32
    if layout == "ablk" and not ablk_key_space_fits(E, R):
        # NOTE: the wide kernel has no hi_mode/win_mode knobs — a
        # caller's non-default modes (e.g. a hi_mode="skip" promise) are
        # intentionally dropped by this reroute, not silently honored
        layout = "wide"  # tighter padding; its own guard below
    Ep = -(-E // TILE_E) * TILE_E
    if (Ep // TILE_E) * (2 * TILE_E * R) + 2 * TILE_E * R >= 2 ** 31:
        raise ValueError("E·R too large for int32 segment keys; shard first")
    kw = dict(
        num_members=E, num_replicas=R, tile_cap=tile_cap,
        retire_rm=retire_rm, dot_impl=dot_impl, interpret=interpret,
    )
    args = (clock0, add0, rm0, kind, member, actor, counter)
    if layout == "wide":
        return _fold_wide(*args, **kw)
    return _fold_ablk(*args, hi_mode=hi_mode, win_mode=win_mode, **kw)


def ablk_key_space_fits(num_members: int, num_replicas: int) -> bool:
    """Whether the ablk layout's int32 segment keys can encode (E, R) —
    the ONE predicate every routing site must use (the front door, the
    sharded fold's eligibility gate, the streaming session)."""
    return _AblkGeom(num_members, num_replicas).fits_int32()


def fold_cap(member, num_members: int) -> int:
    """``tile_cap`` for ``orset_fold_pallas``: the max op-row count over
    8-member tiles (conservative: counts every row, including ones the
    kernel will sort out as padding), bucketed to a power of two so
    recompiles stay bounded.  Determines the kernel's sliding-window
    size; correctness requires the true per-tile count never exceed it,
    which counting every row guarantees."""
    import numpy as np

    E = num_members
    T = max(-(-E // TILE_E), 1)
    counts = np.bincount(
        np.minimum(np.asarray(member) // TILE_E, T - 1), minlength=T
    )
    need = int(counts.max(initial=0))
    cap = SUB
    while cap < need:
        cap *= 2
    return cap

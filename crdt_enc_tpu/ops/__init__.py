from .columnar import (
    KIND_ADD,
    KIND_RM,
    CounterColumns,
    LwwColumns,
    OrsetColumns,
    Vocab,
    counter_ops_to_columns,
    dense_to_vclock,
    lww_ops_to_columns,
    orset_ops_to_columns,
    orset_planes_to_state,
    orset_scan_vocab,
    orset_state_to_planes,
    pad_orset_rows,
    vclock_to_dense,
)
from .counters import gcounter_fold, pncounter_fold, vclock_merge
from .lww import lww_fold, lww_fold_into
from .mvreg import mvreg_dominance_keep
from .orset import orset_fold, orset_merge, orset_merge_many

__all__ = [
    "KIND_ADD",
    "KIND_RM",
    "CounterColumns",
    "LwwColumns",
    "OrsetColumns",
    "Vocab",
    "counter_ops_to_columns",
    "dense_to_vclock",
    "gcounter_fold",
    "lww_fold",
    "lww_fold_into",
    "lww_ops_to_columns",
    "mvreg_dominance_keep",
    "orset_fold",
    "orset_merge",
    "orset_merge_many",
    "orset_ops_to_columns",
    "orset_scan_vocab",
    "pad_orset_rows",
    "orset_planes_to_state",
    "orset_state_to_planes",
    "pncounter_fold",
    "vclock_merge",
    "vclock_to_dense",
]

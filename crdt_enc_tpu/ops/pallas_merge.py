"""Pallas TPU kernel: single-pass S-way ORSet merge.

``orset_merge_many`` (ops/orset.py) reduces S stacked states as a
⌈log2 S⌉-level tree; every level reads two plane sets from HBM and writes
one, so total HBM traffic is ≈3× the input.  Snapshot-heavy compactions
(hundreds of state files, SURVEY.md §3.3 HOT LOOP #1) are pure bandwidth,
so this kernel streams all S states through VMEM **once**: grid =
(member-tiles, S), the output block for a member tile stays resident in
VMEM across the S steps, and each step applies exactly the pairwise
clock-filter merge + normalization of ``orset_merge`` (left fold; legal
for any order because merge is associative — tests/test_crdt_laws.py).

Inputs are the stacked planes ``clocks (S, R) int32``, ``adds/rms
(S, E, R) int32``.  The wrapper precomputes the running clock prefix-max
(cummax over S) host-of-kernel — it is S×R, negligible — because step s
of the fold needs ``clock(acc after s-1)`` for the survival rule.

On non-TPU backends the kernel runs in interpreter mode (slow, for
tests); ``orset_merge_many`` only routes here on TPU by default.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_E = 8  # sublane tile for the member axis (int32 min tile is (8, 128))
LANE = 128


def _merge_step_kernel(clocks_ref, prev_run_ref, run_ref, adds_ref, rms_ref,
                       out_add_ref, out_rm_ref):
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _():
        out_add_ref[...] = adds_ref[0]
        out_rm_ref[...] = rms_ref[0]

    @pl.when(s > 0)
    def _():
        from .orset import merge_rule

        # clock blocks arrive (1, 1, R) — the singleton middle axis exists
        # only to satisfy the TPU (8,128) tiling rule on the last two block
        # dims; [0] yields (1, R), broadcasting over the member sublanes.
        # prev_run is the clock of the accumulated left fold, run the
        # merged clock after this step
        add, rm = merge_rule(
            prev_run_ref[0], out_add_ref[...], out_rm_ref[...],
            clocks_ref[0], adds_ref[0], rms_ref[0],
            run_ref[0],
        )
        out_add_ref[...] = add
        out_rm_ref[...] = rm


def _pad_to(x, axis, mult):
    n = x.shape[axis]
    padn = (-n) % mult
    if padn == 0:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, padn)
    return jnp.pad(x, pads)


@partial(jax.jit, static_argnames=("interpret",))
def orset_merge_many_pallas(clocks, adds, rms, *, interpret: bool = False):
    """Merge S stacked ORSet states in one HBM pass.  Returns
    ``(clock, add, rm)`` identical to ``orset_merge_many``."""
    clocks = jnp.asarray(clocks, jnp.int32)
    adds = jnp.asarray(adds, jnp.int32)
    rms = jnp.asarray(rms, jnp.int32)
    S, E, R = adds.shape

    run = jax.lax.cummax(clocks, axis=0)  # (S, R) running merged clock
    prev_run = jnp.concatenate([jnp.zeros((1, R), jnp.int32), run[:-1]], axis=0)

    # pad E to the sublane tile and R to the lane width; padded members and
    # replicas are all-zero — absent everywhere, invisible to the merge rule
    adds_p = _pad_to(_pad_to(adds, 1, TILE_E), 2, LANE)
    rms_p = _pad_to(_pad_to(rms, 1, TILE_E), 2, LANE)
    clocks_p = _pad_to(clocks, 1, LANE)
    run_p = _pad_to(run, 1, LANE)
    prev_run_p = _pad_to(prev_run, 1, LANE)
    Ep, Rp = adds_p.shape[1], adds_p.shape[2]

    # clocks get a singleton middle axis: a (1, 1, Rp) block's last two
    # dims equal the array dims, which the TPU tiling rule accepts (a
    # (1, Rp) block over (S, Rp) does not — 1 is neither divisible by 8
    # nor equal to S)
    clocks_p = clocks_p[:, None, :]
    run_p = run_p[:, None, :]
    prev_run_p = prev_run_p[:, None, :]

    grid = (Ep // TILE_E, S)
    clock_spec = pl.BlockSpec(
        (1, 1, Rp), lambda e, s: (s, 0, 0), memory_space=pltpu.VMEM
    )
    plane_spec = pl.BlockSpec(
        (1, TILE_E, Rp), lambda e, s: (s, e, 0), memory_space=pltpu.VMEM
    )
    out_spec = pl.BlockSpec(
        (TILE_E, Rp), lambda e, s: (e, 0), memory_space=pltpu.VMEM
    )
    out_add, out_rm = pl.pallas_call(
        _merge_step_kernel,
        grid=grid,
        in_specs=[clock_spec, clock_spec, clock_spec, plane_spec, plane_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((Ep, Rp), jnp.int32),
            jax.ShapeDtypeStruct((Ep, Rp), jnp.int32),
        ],
        interpret=interpret,
    )(clocks_p, prev_run_p, run_p, adds_p, rms_p)
    return run[-1], out_add[:E, :R], out_rm[:E, :R]

"""Pallas TPU kernel: the LWW winner-selection fold as sorted one-hot
matmuls on the MXU (round 4) — the same reformulation that took the
ORSet scatter onto the MXU (ops/pallas_fold.py), applied to config 4's
scatter wall.

``ops/lww.py lww_fold`` implements the per-key lexicographic argmax with
3 cascaded ``segment_max`` scatters (~9ns/row each on TPU — the fold's
entire marginal cost at the 1M-key shape).  This kernel replaces all
three with ONE sort + one matmul materialization pass:

1. **Sort** rows by ``(key, ts_hi, ts_lo, av)`` (4-operand XLA sort,
   ``av = actor·V + value`` — the packed rank the XLA path also uses).
   The LAST row of every key run is that key's lexicographic winner.
2. **Emit columns**: non-winner rows' output columns are zeroed; the
   ts columns emit raw values and the packed-rank column emits
   ``av + 1`` — present-ness is carried by that column alone (``av+1``
   cannot wrap int32 under the packed-rank bound, while a +1 on a full
   31-bit timestamp would).  Each key now has AT MOST ONE nonzero row
   per column, so a one-hot SUM materializes the winner table — and a
   sum of one-hot rows is a matmul.
3. **Kernel**, grid over 16384-key tiles: each SUB-row chunk builds one
   transposed key one-hot ``A_T (128, SUB)`` (row = in-tile key >> 7)
   shared by all columns, and per column a lane one-hot weighted by an
   8-bit limb of the emitted value — ``(128, SUB) × (SUB, 128)`` MXU
   contractions, 4 limbs per 32-bit column with high limbs skipped per
   chunk when no row needs them (timestamps with small ``ts_hi`` and
   packed ranks below 2^16 skip most of the work).
4. The winner table decodes elementwise: ``present = out_av > 0``,
   ``av = out_av - 1``, ``m_actor = av // V``, ``m_value = av % V`` —
   exactly ``lww_fold``'s packed-cascade contract.

Byte-level parity with ``lww_fold`` is pinned by
tests/test_pallas_lww.py; the table-merge step (``lww_table_merge``)
stays elementwise VPU work, so ``lww_fold_into`` composes unchanged.

Reference analogue: the per-op hot loop crdt-enc/src/lib.rs:533-539
(LWW values ride the same op files; the reference folds them one
``state.apply`` at a time).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
SEG_KEYS = 128 * 128  # keys per grid tile
SUB = 256  # rows per in-kernel matmul chunk
MAX_ROWS = 1 << 22  # sort working-set bound, as in pallas_fold

_LIMB = 8  # bits per one-hot matmul limb (exact in bf16/f32: limbs < 256)


def _lww_tile_kernel(
    edges_ref,  # scalar prefetch: (T+1,) per-tile row ranges
    klo_ref, khi_ref,  # (1, BLK) windows of sorted keys
    e1lo_ref, e1hi_ref, e2lo_ref, e2hi_ref, e3lo_ref, e3hi_ref,  # columns
    out1_ref, out2_ref, out3_ref,  # (1, 128, 128) int32
    *, BLK: int, dot_dtype, win_mode: str = "cond",
    limbs: tuple | None = None,
):
    t = pl.program_id(0)
    lo = edges_ref[t]
    hi = edges_ref[t + 1]
    w0 = (lo // BLK) * BLK

    out1_ref[...] = jnp.zeros(out1_ref.shape, jnp.int32)
    out2_ref[...] = jnp.zeros(out2_ref.shape, jnp.int32)
    out3_ref[...] = jnp.zeros(out3_ref.shape, jnp.int32)

    # one iota serves both one-hots: rows and lanes both index the
    # sublane axis of a (128, SUB) comparison
    iota128 = jax.lax.broadcasted_iota(jnp.int32, (LANE, SUB), 0)
    pos_iota = jax.lax.broadcasted_iota(jnp.int32, (1, SUB), 1)
    dims = (((1,), (1,)), ((), ()))

    if win_mode == "select":
        # branchless dual-load + vector select (see pallas_fold.py —
        # measured ~2.6ms faster than the cond on the ORSet kernel's
        # north-star shape; the wrong window's load is in-bounds garbage)
        def load(ref_lo, ref_hi, local, in_hi):
            lo_v = ref_lo[0, pl.ds(local, SUB)]
            hi_v = ref_hi[0, pl.ds(local, SUB)]
            return jnp.where(in_hi, hi_v, lo_v).reshape(1, SUB)
    else:
        def load(ref_lo, ref_hi, local, in_hi):
            return jax.lax.cond(
                in_hi,
                lambda: ref_hi[0, pl.ds(local, SUB)],
                lambda: ref_lo[0, pl.ds(local, SUB)],
            ).reshape(1, SUB)

    def body(j, _):
        off = pl.multiple_of(j * SUB, SUB)
        local = off - w0
        in_hi = local >= BLK
        local = pl.multiple_of(jnp.where(in_hi, local - BLK, local), SUB)
        k = load(klo_ref, khi_ref, local, in_hi)
        pos = pos_iota + off
        ok = (pos >= lo) & (pos < hi)
        rel = k - t * SEG_KEYS
        row = jnp.where(ok, rel >> 7, -1)
        lane = jnp.where(ok, rel & (LANE - 1), -1)
        A_T = (row == iota128).astype(dot_dtype)  # shared by all columns
        hot = lane == iota128

        def col(e_lo, e_hi, out_ref, n_limbs):
            v = jnp.where(ok, load(e_lo, e_hi, local, in_hi), 0)

            def limb(shift):
                piece = hot * ((v >> shift) & 0xFF).astype(dot_dtype)
                p = jax.lax.dot_general(
                    A_T, piece, dims, preferred_element_type=jnp.float32
                )
                return p.astype(jnp.int32) << shift

            if n_limbs is not None:
                # static limb count (round 5): the caller knows each
                # column's max host-side, so the 3 per-column conds +
                # max-reduce per chunk — 12 serializing branches per
                # visit — compile away entirely
                acc = limb(0)
                for i in range(1, n_limbs):
                    acc = acc + limb(i * _LIMB)
                out_ref[0] += acc
                return

            vmax = jnp.max(v)
            # limb 0 always; higher limbs only when some row needs them
            acc = limb(0)
            acc = jax.lax.cond(
                vmax >= (1 << _LIMB),
                lambda a: a + limb(_LIMB),
                lambda a: a, acc,
            )
            acc = jax.lax.cond(
                vmax >= (1 << (2 * _LIMB)),
                lambda a: a + limb(2 * _LIMB),
                lambda a: a, acc,
            )
            acc = jax.lax.cond(
                vmax >= (1 << (3 * _LIMB)),
                lambda a: a + limb(3 * _LIMB),
                lambda a: a, acc,
            )
            out_ref[0] += acc

        lb = limbs or (None, None, None)
        col(e1lo_ref, e1hi_ref, out1_ref, lb[0])
        col(e2lo_ref, e2hi_ref, out2_ref, lb[1])
        col(e3lo_ref, e3hi_ref, out3_ref, lb[2])
        return 0

    start_j = lo // SUB
    end_j = jnp.where(lo == hi, start_j, pl.cdiv(hi, SUB))
    jax.lax.fori_loop(start_j, end_j, body, 0)


def lww_fold_pallas(
    key,  # (N,) int32   (== num_keys ⇒ padding row)
    ts_hi,  # (N,) int32
    ts_lo,  # (N,) int32
    actor,  # (N,) int32  rank-interned
    value,  # (N,) int32  rank-interned
    *,
    num_keys: int,
    num_values: int,
    tile_cap: int | None = None,  # ≥ max rows in any 16384-key tile
    interpret: bool = False,
    win_mode: str = "cond",  # "cond" | "select" (branchless window loads)
    limbs: tuple | None = None,  # static per-column limb counts
    #   (hi, lo, av) from lww_limbs — kills 12 serializing in-kernel
    #   branches per chunk; None keeps the data-dependent conds
):
    """Drop-in for ``lww_fold(..., num_values=V)`` (same contract,
    including the packed (actor, value) rank cascade — the caller
    guarantees ``max_actor_rank · V + V < 2^31``).  Returns
    ``(win_hi, win_lo, win_actor, win_value, present)``.

    ``tile_cap`` bounds the kernel's sliding window; a cap below the
    densest tile's row count silently drops rows, so concrete callers
    get it computed here when omitted; an explicit cap is trusted
    (derive it with ``lww_tile_cap``) and callers inside a jit trace
    MUST pass one."""
    import numpy as np

    if tile_cap is None:
        if isinstance(key, jax.core.Tracer):
            raise ValueError(
                "lww_fold_pallas under jit needs an explicit static "
                "tile_cap (compute it host-side with lww_tile_cap)"
            )
        # computed here for concrete callers; an explicit cap is trusted
        # (in-repo callers derive it from lww_tile_cap — re-validating
        # would re-run the O(N) bincount per fold)
        tile_cap = lww_tile_cap(np.asarray(key), num_keys)
    return _lww_fold_pallas_impl(
        key, ts_hi, ts_lo, actor, value, num_keys=num_keys,
        num_values=num_values, tile_cap=tile_cap, interpret=interpret,
        win_mode=win_mode, limbs=limbs,
    )


# Limb counts are quantized into [1, _LIMB_COUNT_MAX]: the columns are
# int32 (≤ 31 significant bits), so ceil(31 / _LIMB) limbs always suffice
# and the (hi, lo, av) static-arg tuple space is provably ≤ 4³ = 64 —
# varying batch maxima can trigger at most that many Pallas compiles per
# process, never an unbounded stream of them (ADVICE r5, low;
# regression-pinned in tests/test_pallas_lww.py).
_LIMB_COUNT_MAX = -(-31 // 8)  # == 4 at the 8-bit limb width


def lww_limbs_from_maxima(m_hi: int, m_lo: int, m_av: int) -> tuple:
    """(hi, lo, av) limb counts from column maxima, each quantized into
    ``[1, _LIMB_COUNT_MAX]`` (upper bounds are fine — extra limbs cost
    matmuls, missing limbs would corrupt, so bounds only round UP).

    A maximum past ``_LIMB_COUNT_MAX`` limbs raises: quantization must
    bound recompiles, never silently drop high bits — the kernel's
    int32 contract (and accel.py's rank-product gate) keeps in-repo
    callers inside the bound."""
    def nl(mx: int) -> int:
        mx = int(mx)
        if mx >= 1 << (_LIMB * _LIMB_COUNT_MAX):
            raise ValueError(
                f"column maximum {mx} needs more than {_LIMB_COUNT_MAX} "
                f"{_LIMB}-bit limbs; the Pallas LWW fold is int32-only"
            )
        return max(1, min((mx.bit_length() + _LIMB - 1) // _LIMB,
                          _LIMB_COUNT_MAX))

    return (nl(m_hi), nl(m_lo), nl(m_av))


def lww_column_maxima(ts_hi, ts_lo, actor, num_values: int) -> tuple:
    """The three host-side column maxima ``lww_limbs`` quantizes — one
    O(N) pass each; callers reusing columns across folds can cache this
    tuple and go through :func:`lww_limbs_from_maxima` directly."""
    import numpy as np

    m_hi = int(np.max(ts_hi, initial=0))
    m_lo = int(np.max(ts_lo, initial=0))
    m_av = (int(np.max(actor, initial=0)) + 1) * num_values  # ≥ max av+1
    return (m_hi, m_lo, m_av)


def lww_limbs(ts_hi, ts_lo, actor, num_values: int, maxima=None) -> tuple:
    """Static per-column limb counts for ``lww_fold_pallas`` from the
    batch's host-side maxima (``maxima``: a cached
    :func:`lww_column_maxima` tuple, to skip the three O(N) passes when
    the columns are reused)."""
    if maxima is None:
        maxima = lww_column_maxima(ts_hi, ts_lo, actor, num_values)
    return lww_limbs_from_maxima(*maxima)


@partial(
    jax.jit,
    static_argnames=("num_keys", "num_values", "tile_cap", "interpret",
                     "win_mode", "limbs"),
)
def _lww_fold_pallas_impl(
    key, ts_hi, ts_lo, actor, value,
    *, num_keys, num_values, tile_cap, interpret, win_mode="cond",
    limbs=None,
):
    K, V = num_keys, num_values
    N = key.shape[0]
    if N > MAX_ROWS:
        raise ValueError(f"batch of {N} rows exceeds MAX_ROWS={MAX_ROWS}")
    T = -(-K // SEG_KEYS)
    sentinel = T * SEG_KEYS

    pad = key >= K
    key_ix = jnp.where(pad, sentinel, key)
    av = actor * V + value
    skey, s_hi, s_lo, s_av = jax.lax.sort(
        (key_ix, ts_hi, ts_lo, av), num_keys=4
    )
    # Last of each key run is the lexicographic winner; everyone else
    # emits 0.  Present-ness is carried by the av column ALONE: winners
    # emit av+1 (safe — av ≤ R·V-1 ≤ 2^31-2 by the caller's packed-rank
    # bound), while the ts columns emit their raw values, so a full
    # 31-bit ts_hi/ts_lo cannot wrap (a +1 there overflowed int32 at
    # ts_lo = 2^31-1, the maximum ts_split emits).
    nxt = jnp.concatenate([skey[1:], jnp.full((1,), -1, skey.dtype)])
    win = (skey != nxt) & (skey < sentinel)
    e_hi = jnp.where(win, s_hi, 0)
    e_lo = jnp.where(win, s_lo, 0)
    e_av = jnp.where(win, s_av + 1, 0)

    bounds = jnp.arange(T + 1, dtype=jnp.int32) * SEG_KEYS
    edges = jnp.searchsorted(skey, bounds).astype(jnp.int32)

    BLK = SUB
    while BLK < tile_cap:
        BLK *= 2
    Np = (-(-N // BLK) + 1) * BLK

    def padto(x, fill):
        return jnp.concatenate(
            [x, jnp.full((Np - N,), fill, jnp.int32)]
        ).reshape(1, Np)

    skey = padto(skey, sentinel)
    e_hi = padto(e_hi, 0)
    e_lo = padto(e_lo, 0)
    e_av = padto(e_av, 0)

    win_lo_spec = pl.BlockSpec(
        (1, BLK), lambda t, e: (0, e[t] // BLK), memory_space=pltpu.VMEM
    )
    last_blk = Np // BLK - 1
    win_hi_spec = pl.BlockSpec(
        (1, BLK),
        lambda t, e: (0, jnp.minimum(e[t] // BLK + 1, last_blk)),
        memory_space=pltpu.VMEM,
    )
    out_spec = pl.BlockSpec(
        (1, LANE, LANE), lambda t, e: (t, 0, 0), memory_space=pltpu.VMEM
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(T,),
        in_specs=[win_lo_spec, win_hi_spec] * 4,
        out_specs=[out_spec] * 3,
    )
    out_hi, out_lo, out_av = pl.pallas_call(
        partial(_lww_tile_kernel, BLK=BLK, dot_dtype=jnp.bfloat16,
                win_mode=win_mode, limbs=limbs),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((T, LANE, LANE), jnp.int32)] * 3,
        interpret=interpret,
    )(edges, skey, skey, e_hi, e_hi, e_lo, e_lo, e_av, e_av)

    # (T, 128, 128) row-major ≡ (T·16384,): key order — free reshape
    out_hi = out_hi.reshape(T * SEG_KEYS)[:K]
    out_lo = out_lo.reshape(T * SEG_KEYS)[:K]
    out_av = out_av.reshape(T * SEG_KEYS)[:K]
    present = out_av > 0
    m_hi = jnp.where(present, out_hi, -1)
    m_lo = jnp.where(present, out_lo, -1)
    av = out_av - 1
    m_actor = jnp.where(present, av // V, -1)
    m_value = jnp.where(present, av % V, -1)
    return m_hi, m_lo, m_actor, m_value, present


def lww_tile_cap(key, num_keys: int) -> int:
    """Max row count over 16384-key tiles, bucketed to a power of two —
    the kernel's sliding-window size (conservative: counts every row)."""
    import numpy as np

    T = max(-(-num_keys // SEG_KEYS), 1)
    counts = np.bincount(
        np.minimum(np.asarray(key) // SEG_KEYS, T - 1), minlength=T
    )
    need = int(counts.max(initial=0))
    cap = SUB
    while cap < need:
        cap *= 2
    return cap

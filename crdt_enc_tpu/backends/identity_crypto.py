"""Identity cryptor: the envelope layering without the cipher — for tests
and for deployments delegating confidentiality to the transport.  Keeps the
exact three-layer wire shape so swapping in a real AEAD changes no formats."""

from __future__ import annotations

import secrets

from ..core.cryptor import Cryptor
from ..utils import VersionBytes
from ..utils.versions import IDENTITY_DATA_VERSION_1, IDENTITY_KEY_VERSION_1


class IdentityCryptor(Cryptor):
    async def gen_key(self) -> VersionBytes:
        return VersionBytes(IDENTITY_KEY_VERSION_1, secrets.token_bytes(32))

    async def encrypt(self, key: VersionBytes, data: bytes) -> bytes:
        key.ensure_version(IDENTITY_KEY_VERSION_1)
        return VersionBytes(IDENTITY_DATA_VERSION_1, data).serialize()

    async def decrypt(self, key: VersionBytes, data: bytes) -> bytes:
        key.ensure_version(IDENTITY_KEY_VERSION_1)
        return (
            VersionBytes.deserialize(data)
            .ensure_version(IDENTITY_DATA_VERSION_1)
            .content
        )

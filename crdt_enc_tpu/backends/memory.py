"""In-memory storage backend — the fake the reference's trait-object design
enables but never shipped (SURVEY.md §4).  Multi-replica tests share one
``MemoryRemote`` the way real replicas share a synced directory."""

from __future__ import annotations

import base64
import hashlib
from dataclasses import dataclass, field

from ..core.storage import Storage
from ..models.vclock import Actor


def content_name(data: bytes) -> str:
    """SHA3-256 → base32-nopad, the reference's content addressing
    (crdt-enc-tokio/src/lib.rs:403-432)."""
    digest = hashlib.sha3_256(data).digest()
    return base64.b32encode(digest).decode().rstrip("=")


@dataclass
class MemoryRemote:
    """The shared 'remote' directory tree."""

    metas: dict = field(default_factory=dict)  # name -> bytes
    states: dict = field(default_factory=dict)  # name -> bytes
    ops: dict = field(default_factory=dict)  # actor -> {version: bytes}
    deltas: dict = field(default_factory=dict)  # actor -> {version: bytes}


class MemoryStorage(Storage):
    def __init__(self, remote: MemoryRemote | None = None):
        self.remote = remote if remote is not None else MemoryRemote()
        self._local_meta: bytes | None = None
        self._local_checkpoint: bytes | None = None

    # -- local meta --------------------------------------------------------
    async def load_local_meta(self) -> bytes | None:
        return self._local_meta

    async def store_local_meta(self, data: bytes) -> None:
        self._local_meta = bytes(data)

    # -- local fold checkpoint ---------------------------------------------
    async def load_local_checkpoint(self) -> bytes | None:
        return self._local_checkpoint

    async def store_local_checkpoint(self, data: bytes) -> None:
        self._local_checkpoint = bytes(data)

    async def remove_local_checkpoint(self) -> None:
        self._local_checkpoint = None

    # -- remote metas ------------------------------------------------------
    async def list_remote_meta_names(self) -> list[str]:
        return sorted(self.remote.metas)

    async def load_remote_metas(self, names: list[str]) -> list[tuple[str, bytes]]:
        return [(n, self.remote.metas[n]) for n in names if n in self.remote.metas]

    async def store_remote_meta(self, data: bytes) -> str:
        name = content_name(data)
        self.remote.metas.setdefault(name, bytes(data))
        return name

    async def remove_remote_metas(self, names: list[str]) -> None:
        for n in names:
            self.remote.metas.pop(n, None)

    # -- states ------------------------------------------------------------
    async def list_state_names(self) -> list[str]:
        return sorted(self.remote.states)

    async def load_states(self, names: list[str]) -> list[tuple[str, bytes]]:
        return [(n, self.remote.states[n]) for n in names if n in self.remote.states]

    async def store_state(self, data: bytes) -> str:
        name = content_name(data)
        self.remote.states.setdefault(name, bytes(data))
        return name

    async def remove_states(self, names: list[str]) -> None:
        for n in names:
            self.remote.states.pop(n, None)

    # -- ops ---------------------------------------------------------------
    async def list_op_actors(self) -> list[Actor]:
        return sorted(self.remote.ops)

    async def load_ops(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, bytes]]:
        out = []
        for actor, first in actor_first_versions:
            log = self.remote.ops.get(actor, {})
            v = first
            while v in log:  # gap-free scan (crdt-enc-tokio lib.rs:254-269)
                out.append((actor, v, log[v]))
                v += 1
        return out

    async def stat_ops(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, int]]:
        out = []
        for actor, first in actor_first_versions:
            log = self.remote.ops.get(actor, {})
            v = first
            while v in log:
                out.append((actor, v, len(log[v])))
                v += 1
        return out

    async def store_ops(self, actor: Actor, version: int, data: bytes) -> None:
        log = self.remote.ops.setdefault(actor, {})
        if version in log:
            raise FileExistsError(f"op v{version} already exists for this actor")
        log[version] = bytes(data)

    async def remove_ops(self, actor_last_versions: list[tuple[Actor, int]]) -> None:
        for actor, last in actor_last_versions:
            log = self.remote.ops.get(actor)
            if not log:
                continue
            for v in [v for v in log if v <= last]:
                del log[v]
            if not log:
                del self.remote.ops[actor]

    # -- delta snapshots ---------------------------------------------------
    has_deltas = True

    async def list_delta_actors(self) -> list[Actor]:
        return sorted(self.remote.deltas)

    async def load_deltas(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, bytes]]:
        out = []
        for actor, first in actor_first_versions:
            log = self.remote.deltas.get(actor, {})
            # sorted, holes tolerated: density is not part of the delta
            # contract (chain validity comes from the base-name links)
            for v in sorted(v for v in log if v >= first):
                out.append((actor, v, log[v]))
        return out

    async def store_delta(self, actor: Actor, version: int, data: bytes) -> None:
        log = self.remote.deltas.setdefault(actor, {})
        if version in log:
            raise FileExistsError(f"delta v{version} already exists for this actor")
        log[version] = bytes(data)

    async def remove_deltas(
        self, actor_last_versions: list[tuple[Actor, int]]
    ) -> None:
        for actor, last in actor_last_versions:
            log = self.remote.deltas.get(actor)
            if not log:
                continue
            for v in [v for v in log if v <= last]:
                del log[v]
            if not log:
                del self.remote.deltas[actor]

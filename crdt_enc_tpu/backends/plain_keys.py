"""Plaintext key-cryptor backend.

Structurally the reference's gpgme backend (crdt-enc-gpgme/src/lib.rs:34-129)
— own remote-meta MVReg, decode-on-notify, encode-and-persist on set_keys —
with identity key protection, which is exactly what the reference's WIP
backend does too (its PGP calls are commented out, lib.rs:95-98, 118-121).
A real asymmetric backend only has to override the two transforms.
"""

from __future__ import annotations

from ..core.key_cryptor import KeyCryptor, Keys
from ..models import MVReg
from ..utils.mvreg_codec import (
    decode_version_bytes_mvreg,
    encode_version_bytes_mvreg,
)
from ..utils.versions import KEYS_META_VERSION_1, SUPPORTED_KEYS_META_VERSIONS


class PlainKeyCryptor(KeyCryptor):
    # Subclasses that really protect the blob stamp their own meta version so
    # a reader with the wrong backend fails the version check, not the parse.
    META_VERSION = KEYS_META_VERSION_1
    SUPPORTED_META_VERSIONS = SUPPORTED_KEYS_META_VERSIONS
    # Exception types from _unprotect that skip just that register value
    # (some backends cannot open every concurrent value, e.g. a blob
    # sealed to a recipient set this replica is not in); an entirely
    # unreadable register still raises.
    DECODE_TOLERATES: tuple = ()

    def __init__(self):
        self._reg = MVReg()
        self._core = None

    async def init(self, core) -> None:
        self._core = core

    async def _protect(self, raw: bytes) -> bytes:
        """Hook: encrypt the serialized Keys blob (identity here)."""
        return raw

    async def _unprotect(self, vb) -> bytes:
        """Hook: decrypt a Keys blob (identity here)."""
        return vb.content

    def _trust_epoch(self):
        """Hook: a value that changes whenever ``_unprotect`` learns to open
        blobs it previously could not (e.g. a grown recipient roster).
        Backends with monotone trust growth return something comparable so
        ``set_remote_meta`` can re-decode to a fixpoint; the identity
        backend's trust never changes."""
        return None

    async def set_remote_meta(self, reg: MVReg) -> None:
        """Converged key metadata arrived: fold into our register, decode the
        Keys CRDT, install on the core (gpgme lib.rs:79-105).

        Decoding runs to a trust fixpoint: one register value's roster may
        introduce the identity that signed ANOTHER concurrent value, and
        MVReg iteration order is arbitrary — a single pass would tolerate-skip
        the not-yet-trusted value and silently drop its key material (e.g. a
        rotated latest key).  Trust growth is monotone, so re-running the
        decode whenever a pass grew trust terminates."""
        self._reg.merge(reg)
        while True:
            epoch = self._trust_epoch()
            keys = await decode_version_bytes_mvreg(
                self._reg, self.SUPPORTED_META_VERSIONS, Keys,
                transform=self._unprotect, tolerate=self.DECODE_TOLERATES,
            )
            if self._trust_epoch() == epoch:
                break
        if keys is not None and self._core is not None:
            self._core.set_keys(keys)

    async def set_keys(self, keys: Keys) -> None:
        """Encode the key set into our register, re-notify ourselves, and
        hand the register to the core for persistence (gpgme lib.rs:107-129)."""
        if self._core is None:
            raise RuntimeError("key cryptor not initialized")
        await encode_version_bytes_mvreg(
            self._reg,
            keys,
            self._core.actor_id,
            self.META_VERSION,
            transform=self._protect,
        )
        snapshot = MVReg.from_obj(self._reg.to_obj())
        await self.set_remote_meta(snapshot)
        await self._core.set_remote_meta_key_cryptor(
            MVReg.from_obj(self._reg.to_obj())
        )

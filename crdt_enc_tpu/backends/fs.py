"""Filesystem storage backend — the production port over a synced directory.

Rebuilds crdt-enc-tokio (crdt-enc-tokio/src/lib.rs) on asyncio + thread
offload:

* layout: ``local/meta-data.msgpack`` (lib.rs:51), ``remote/meta/<hash>``
  (lib.rs:79), ``remote/states/<hash>`` (lib.rs:139),
  ``remote/ops/<actor-hex>/<N>`` (lib.rs:247-257);
* immutable content-addressed writes: SHA3-256 of the blob, base32-nopad
  name, ``O_CREAT|O_EXCL`` then fsync of file and directory
  (write_content_addressible_file, lib.rs:403-432) — a replay of the same
  content is a no-op, a name collision with different content is an error;
* op logs scan densely from the first requested version until the first
  missing file (lib.rs:254-269); actors fan out concurrently (lib.rs:274);
* missing directories/files read as empty/None and removes tolerate
  already-gone files (lib.rs:376-401, 434-440) — the sync tool may race us.

Durability beyond the reference: op-file writes go through a same-directory
tmp file + fsync + atomic rename (the reference left this as a TODO,
lib.rs:343-344), so a crash mid-write can never leave a torn op file where
the dense version scan would find it.
"""

from __future__ import annotations

import asyncio
import logging
import os
import uuid

from ..core.storage import Storage
from ..models.vclock import Actor
from .memory import content_name

FS_CONCURRENCY = 32  # reference buffer_unordered(32), crdt-enc-tokio lib.rs:112

logger = logging.getLogger("crdt_enc_tpu.fs")

_warned_native_scan = False  # the no-toolchain fallback warns once, not per scan


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_tmp(d: str, data: bytes) -> str:
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".tmp-{uuid.uuid4().hex}")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
    return tmp


def _write_file_atomic(path: str, data: bytes) -> None:
    """tmp + fsync + rename (last-writer-wins — for the mutable local meta)."""
    d = os.path.dirname(path)
    tmp = _write_tmp(d, data)
    os.rename(tmp, path)
    _fsync_dir(d)


def _write_file_new(
    path: str, data: bytes, *, relink_vanished_collider: bool = True
) -> None:
    """Immutable publish: tmp + fsync, then ``os.link`` — which fails with
    EEXIST atomically, unlike an exists-check + rename (TOCTOU) or rename
    itself (silent clobber).  An existing file with identical content is an
    idempotent content-addressed replay; different content is an error.

    Concurrent-GC tolerance: another replica's compactor may remove the
    colliding file — or the whole emptied directory (``remove_ops``
    rmdir's an emptied actor dir) — between any two steps here.  A
    vanished DIRECTORY always retries (``makedirs`` recreates it; the
    name was never observable with other content).  A vanished
    COLLIDER retries only for content-addressed names
    (``relink_vanished_collider=True``: same name ⇒ same bytes, so the
    relink republishes identical content).  Version-addressed op files
    pass False: the collider existed moments ago, so a peer may have
    folded it into a snapshot — republishing DIFFERENT content at that
    version would be invisible to every cursor already past it, a
    silent write loss; the burned version surfaces as
    ``FileExistsError`` and the producer's probe loop picks the next
    one.  The retry is bounded — each round needs a fresh removal, and
    removals need fresh content to collect."""
    d = os.path.dirname(path)
    for _ in range(8):
        try:
            tmp = _write_tmp(d, data)
        except FileNotFoundError:
            continue  # dir rmdir'd between makedirs and the tmp open
        try:
            try:
                os.link(tmp, path)
            except FileExistsError:
                try:
                    with open(path, "rb") as f:
                        if f.read() == data:
                            return
                except FileNotFoundError:
                    if relink_vanished_collider:
                        continue  # content-addressed: relink same bytes
                    raise FileExistsError(
                        f"{path}: version burned by a GC'd concurrent "
                        "write; probe forward"
                    ) from None
                raise FileExistsError(
                    f"{path} exists with different content"
                ) from None
            except FileNotFoundError:
                continue  # dir rmdir'd between the tmp write and link
        finally:
            _remove_quiet(tmp)
        try:
            _fsync_dir(d)
        except FileNotFoundError:
            # the directory — and with it our freshly linked file — was
            # emptied and rmdir'd by a concurrent compactor after the
            # link: the write happened and was legitimately collected,
            # exactly the observable world of write-then-GC.
            pass
        return
    raise OSError(f"could not publish {path}: directory kept vanishing")


def _read_file(path: str) -> bytes | None:
    try:
        with open(path, "rb") as f:
            return f.read()
    except FileNotFoundError:
        return None


def _list_dir(path: str) -> list[str]:
    try:
        return [n for n in os.listdir(path) if not n.startswith(".tmp-")]
    except FileNotFoundError:
        return []


def _remove_quiet(path: str) -> None:
    try:
        os.remove(path)
    except FileNotFoundError:
        pass


class FsStorage(Storage):
    def __init__(self, local_path: str, remote_path: str):
        self.local = os.fspath(local_path)
        self.remote = os.fspath(remote_path)
        self._sem = asyncio.Semaphore(FS_CONCURRENCY)

    async def _run(self, fn, *args):
        async with self._sem:
            return await asyncio.to_thread(fn, *args)

    # paths
    def _local_meta_path(self) -> str:
        return os.path.join(self.local, "meta-data.msgpack")

    def _local_checkpoint_path(self) -> str:
        return os.path.join(self.local, "checkpoint.msgpack")

    def _meta_dir(self) -> str:
        return os.path.join(self.remote, "meta")

    def _states_dir(self) -> str:
        return os.path.join(self.remote, "states")

    def _ops_dir(self, actor: Actor | None = None) -> str:
        base = os.path.join(self.remote, "ops")
        return os.path.join(base, actor.hex()) if actor is not None else base

    def _deltas_dir(self, actor: Actor | None = None) -> str:
        base = os.path.join(self.remote, "deltas")
        return os.path.join(base, actor.hex()) if actor is not None else base

    # -- local meta --------------------------------------------------------
    async def load_local_meta(self) -> bytes | None:
        return await self._run(_read_file, self._local_meta_path())

    async def store_local_meta(self, data: bytes) -> None:
        await self._run(_write_file_atomic, self._local_meta_path(), bytes(data))

    # -- local fold checkpoint ---------------------------------------------
    # Same durability discipline as the local meta: tmp + fsync + atomic
    # rename, so a crash mid-write leaves the previous checkpoint (or
    # none) — never a torn blob the dense warm-open path could trust.
    async def load_local_checkpoint(self) -> bytes | None:
        return await self._run(_read_file, self._local_checkpoint_path())

    async def store_local_checkpoint(self, data: bytes) -> None:
        await self._run(
            _write_file_atomic, self._local_checkpoint_path(), bytes(data)
        )

    async def remove_local_checkpoint(self) -> None:
        await self._run(_remove_quiet, self._local_checkpoint_path())

    # -- content-addressed families ---------------------------------------
    async def _list_ca(self, d: str) -> list[str]:
        return sorted(await self._run(_list_dir, d))

    async def _load_ca(self, d: str, names: list[str]) -> list[tuple[str, bytes]]:
        async def one(n):
            raw = await self._run(_read_file, os.path.join(d, n))
            return (n, raw) if raw is not None else None

        loaded = await asyncio.gather(*(one(n) for n in names))
        return [x for x in loaded if x is not None]

    async def _store_ca(self, d: str, data: bytes) -> str:
        name = content_name(data)
        await self._run(_write_file_new, os.path.join(d, name), bytes(data))
        return name

    async def _remove_ca(self, d: str, names: list[str]) -> None:
        await asyncio.gather(
            *(self._run(_remove_quiet, os.path.join(d, n)) for n in names)
        )

    async def list_remote_meta_names(self) -> list[str]:
        return await self._list_ca(self._meta_dir())

    async def load_remote_metas(self, names: list[str]) -> list[tuple[str, bytes]]:
        return await self._load_ca(self._meta_dir(), names)

    async def store_remote_meta(self, data: bytes) -> str:
        return await self._store_ca(self._meta_dir(), data)

    async def remove_remote_metas(self, names: list[str]) -> None:
        await self._remove_ca(self._meta_dir(), names)

    async def list_state_names(self) -> list[str]:
        return await self._list_ca(self._states_dir())

    async def load_states(self, names: list[str]) -> list[tuple[str, bytes]]:
        return await self._load_ca(self._states_dir(), names)

    async def store_state(self, data: bytes) -> str:
        return await self._store_ca(self._states_dir(), data)

    async def remove_states(self, names: list[str]) -> None:
        await self._remove_ca(self._states_dir(), names)

    # -- op logs -----------------------------------------------------------
    async def list_op_actors(self) -> list[Actor]:
        names = await self._run(_list_dir, self._ops_dir())
        actors = []
        for n in names:
            try:
                actors.append(bytes.fromhex(n))
            except ValueError:
                continue  # foreign junk in the synced dir is not ours to judge
        return sorted(a for a in actors if len(a) == 16)

    # One C++ call scans/reads a whole dense per-actor run (SURVEY.md §2.2:
    # the bulk load path gets a native reader) — per-file Python open/read
    # costs ~10-20µs of interpreter overhead, which dominates at
    # compaction scale.  Each round is capped in files AND bytes so one
    # gigantic log never demands an unbounded flat buffer; the loop
    # continues where the previous round stopped.
    NATIVE_SCAN_BATCH = 65_536
    NATIVE_SCAN_BYTES = 256 << 20
    # Chunk budget for the pipelined ingest (iter_op_chunks): small enough
    # that a few in-flight chunks bound host memory AND the read/decrypt/
    # decode/reduce stages get real overlap, large enough that the batched
    # decrypt/decode amortize.
    CHUNK_BYTES = 24 << 20

    class _ScanRace(Exception):
        """A file in the round shrank/vanished/errored between the two
        native passes; the round starting at ``.version`` needs a per-file
        re-probe."""

        def __init__(self, version: int):
            self.version = version

    def _scan_sizes_native(self, lib, d: bytes, v: int):
        """One bounded native size-only pass (``scan_op_sizes``): the
        dense per-file sizes from version ``v``, as ``(sizes[:n],
        exhausted)`` — ``exhausted`` means the directory ran out inside
        this round.  The single encoding of the native scan calling
        convention; the bulk reader and ``stat_ops`` both build on it."""
        import ctypes

        import numpy as np

        i64p = ctypes.POINTER(ctypes.c_int64)
        sizes = np.zeros(self.NATIVE_SCAN_BATCH, np.int64)
        n = int(lib.scan_op_sizes(
            d, v, self.NATIVE_SCAN_BATCH, sizes.ctypes.data_as(i64p)
        ))
        n = max(n, 0)
        return sizes[:n], n < self.NATIVE_SCAN_BATCH

    def _scan_round_native(self, lib, d: bytes, actor: Actor, v: int, max_bytes: int):
        """One bounded native round.  Returns ``(files, next_v, done)``;
        raises :class:`_ScanRace` on a mid-round race (nothing consumed)
        and lets native-load/ctypes errors propagate to the caller."""
        import ctypes

        import numpy as np

        from .. import native

        i64p = ctypes.POINTER(ctypes.c_int64)
        sizes, exhausted = self._scan_sizes_native(lib, d, v)
        n = len(sizes)
        if n == 0:
            return [], v, True
        scanned = n
        # byte cap: shrink this round to the prefix that fits (but always
        # take at least one file so progress is guaranteed)
        cum = np.cumsum(sizes)
        if cum[-1] > max_bytes:
            n = max(1, int(np.searchsorted(cum, max_bytes, "right")))
            sizes = sizes[:n]
        offsets = np.zeros(n, np.int64)
        np.cumsum(sizes[:-1], out=offsets[1:])
        buf = np.empty(int(sizes.sum()), np.uint8)
        got = lib.read_op_files(
            d, v, n,
            offsets.ctypes.data_as(i64p),
            sizes.ctypes.data_as(i64p),
            buf.ctypes.data_as(native.u8p),
        )
        if got != n:
            logger.debug(
                "native bulk read raced at actor %s v%d; "
                "re-probing round per-file", actor.hex(), v,
            )
            raise self._ScanRace(v)
        files = [
            (
                actor,
                v + i,
                buf[int(offsets[i]) : int(offsets[i]) + int(sizes[i])].tobytes(),
            )
            for i in range(n)
        ]
        done = exhausted and n == scanned
        return files, v + n, done

    @staticmethod
    def _warn_native_unavailable() -> None:
        """Fall back to the per-file Python scan, but not silently — a
        failure here on every load would mask a real native-path bug.  The
        expected permanent case (no C toolchain: native.load() re-raises
        its cached build error per call) warns only once."""
        global _warned_native_scan
        if not _warned_native_scan:
            _warned_native_scan = True
            logger.warning(
                "native op scan unavailable; using per-file scans "
                "(logged once)", exc_info=True,
            )
        else:
            logger.debug("native op scan failed", exc_info=True)

    def _scan_native(self, actor: Actor, first: int):
        """Dense scan via the native reader.

        Returns ``None`` (native path unavailable → Python scans from
        ``first``) or ``(files, resume_v)`` where ``resume_v`` is None for a
        completed run, or the version the Python scan should continue from —
        the start of a round whose bulk read failed (``read_op_files``
        reports only -1, so the whole round is re-read; it is bounded by the
        batch/byte caps).  The per-file re-scan then distinguishes a benign
        race (file gone → clean dense end) from a real defect (file present
        but unreadable → loud error), so neither case is masked."""
        from .. import native

        out: list[tuple[Actor, int, bytes]] = []
        v = first
        try:
            lib = native.load()
            d = self._ops_dir(actor).encode()
            while True:
                try:
                    files, v, done = self._scan_round_native(
                        lib, d, actor, v, self.NATIVE_SCAN_BYTES
                    )
                except self._ScanRace as race:
                    return out, race.version
                out.extend(files)
                if done:
                    return out, None
        except Exception:
            self._warn_native_unavailable()
            return (out, v) if out else None

    def _chunk_round(self, actor: Actor, v: int, max_bytes: int):
        """One bounded round for the chunk iterator: native fast path with
        a per-file Python continuation on race or native unavailability.
        Returns ``(files, next_v, done)``."""
        from .. import native

        files: list[tuple[Actor, int, bytes]] = []
        size = 0
        try:
            lib = native.load()
            d = self._ops_dir(actor).encode()
            try:
                return self._scan_round_native(lib, d, actor, v, max_bytes)
            except self._ScanRace:
                pass  # re-probe this round per file below
        except Exception:
            self._warn_native_unavailable()
        dd = self._ops_dir(actor)
        while size < max_bytes:
            raw = _read_file(os.path.join(dd, str(v)))
            if raw is None:
                return files, v, True
            files.append((actor, v, raw))
            size += len(raw)
            v += 1
        return files, v, False

    def _probe_actors(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int]]:
        """Prefilter for the scan fan-out: keep only actors whose NEXT
        wanted op file exists.  The dense scan reads nothing for the
        others (their log is fully consumed or GC'd), but the per-actor
        task/queue/thread machinery below costs ~1ms each — at 10k
        replicas a warm-open tail ingest was spending seconds
        discovering that 99% of actors had nothing new.  One stat per
        actor replaces all of it; the stats are dirfd-relative (resolve
        two path components, not the whole remote prefix) because on
        containerized kernels every path walk costs ~100µs+ — this
        probe IS the warm-open floor, measured, not guessed."""
        n = len(actor_first_versions)
        if n > 64:  # the C loop only pays off past its setup cost
            try:
                import numpy as np

                from .. import native

                lib = native.load()
                rel = b"\0".join(
                    f"{actor.hex()}/{first}".encode()
                    for actor, first in actor_first_versions
                ) + b"\0"
                mask = np.zeros(n, np.uint8)
                got = lib.probe_op_files(
                    self._ops_dir().encode(), n, rel,
                    mask.ctypes.data_as(native.u8p),
                )
                if got == n:
                    keep = np.flatnonzero(mask)
                    return [actor_first_versions[i] for i in keep.tolist()]
                if got == -1:
                    return []  # no ops directory at all
            except Exception:
                self._warn_native_unavailable()
        try:
            dfd = os.open(self._ops_dir(), os.O_RDONLY)
        except FileNotFoundError:
            return []
        out = []
        try:
            for pair in actor_first_versions:
                actor, first = pair
                try:
                    os.stat(f"{actor.hex()}/{first}", dir_fd=dfd)
                except OSError:
                    continue
                out.append(pair)
        finally:
            os.close(dfd)
        return out

    # how many actors scan concurrently ahead of the emitter; in-flight
    # memory is bounded by ~window × 2 × CHUNK_BYTES (one queued + one
    # in-progress round per actor)
    CHUNK_SCAN_WINDOW = 4

    async def iter_op_chunks(
        self,
        actor_first_versions: list[tuple[Actor, int]],
        max_bytes: int | None = None,
    ):
        """Bounded-memory op reading for the pipelined ingest: yields
        ``(actor, version, raw)`` lists of ~max_bytes, per-actor version
        order preserved across chunks (a chunk may end mid-actor).

        Actors scan concurrently (a window of CHUNK_SCAN_WINDOW, FIFO, so
        the per-file Python fallback on a high-latency remote does not
        serialize the whole read stage) while emission stays in actor
        order."""
        max_bytes = max_bytes if max_bytes is not None else self.CHUNK_BYTES
        actor_first_versions = await self._run(
            self._probe_actors, actor_first_versions
        )
        window = asyncio.Semaphore(self.CHUNK_SCAN_WINDOW)

        async def scan_actor(actor: Actor, first: int, out_q: asyncio.Queue):
            # the semaphore is held for the actor's whole scan; waiters are
            # FIFO, so the window always covers the actor being emitted —
            # no deadlock against the bounded queues
            try:
                async with window:
                    v, done = first, False
                    while not done:
                        files, v, done = await self._run(
                            self._chunk_round, actor, v, max_bytes
                        )
                        if files:
                            await out_q.put(files)
                    await out_q.put(None)
            except Exception as e:
                # the emitter must never block forever on a dead scanner —
                # deliver the failure in-position and let it re-raise
                await out_q.put(e)

        queues: list[asyncio.Queue] = []
        tasks: list[asyncio.Task] = []
        for actor, first in actor_first_versions:
            out_q: asyncio.Queue = asyncio.Queue(maxsize=1)
            queues.append(out_q)
            tasks.append(asyncio.create_task(scan_actor(actor, first, out_q)))
        chunk: list[tuple[Actor, int, bytes]] = []
        size = 0
        try:
            for out_q in queues:
                while True:
                    files = await out_q.get()
                    if files is None:
                        break
                    if isinstance(files, Exception):
                        raise files
                    for item in files:
                        chunk.append(item)
                        size += len(item[2])
                        if size >= max_bytes:
                            yield chunk
                            chunk, size = [], 0
            if chunk:
                yield chunk
        finally:
            for t in tasks:
                t.cancel()

    async def load_ops(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, bytes]]:
        actor_first_versions = await self._run(
            self._probe_actors, actor_first_versions
        )

        def scan(actor: Actor, first: int) -> list[tuple[Actor, int, bytes]]:
            res = self._scan_native(actor, first)
            if res is None:
                out, v = [], first
            else:
                out, v = res
                if v is None:
                    return out
            d = self._ops_dir(actor)
            while True:
                raw = _read_file(os.path.join(d, str(v)))
                if raw is None:
                    return out
                out.append((actor, v, raw))
                v += 1

        per_actor = await asyncio.gather(
            *(self._run(scan, a, f) for a, f in actor_first_versions)
        )
        return [item for chunk in per_actor for item in chunk]

    async def stat_ops(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, int]]:
        """Dense tail sizing for the replication-status backlog probe:
        the native ``scan_op_sizes`` pass (one C call per round — the
        same first pass the bulk reader uses, without the read), with a
        per-file ``os.stat`` continuation when the native path is
        unavailable.  Probe-prefiltered like ``load_ops``, so a fully
        consumed log costs one stat per actor, not a scan."""
        actor_first_versions = await self._run(
            self._probe_actors, actor_first_versions
        )

        def scan(actor: Actor, first: int) -> list[tuple[Actor, int, int]]:
            out: list[tuple[Actor, int, int]] = []
            v = first
            try:
                from .. import native

                lib = native.load()
                d = self._ops_dir(actor).encode()
                while True:
                    sizes, exhausted = self._scan_sizes_native(lib, d, v)
                    out.extend(
                        (actor, v + i, int(s)) for i, s in enumerate(sizes)
                    )
                    v += len(sizes)
                    if exhausted:
                        return out
            except Exception:
                self._warn_native_unavailable()
            # per-file stat continuation from wherever the native pass
            # stopped (or from ``first`` when it never started)
            dd = self._ops_dir(actor)
            while True:
                try:
                    st = os.stat(os.path.join(dd, str(v)))
                except OSError:
                    return out
                out.append((actor, v, int(st.st_size)))
                v += 1

        per_actor = await asyncio.gather(
            *(self._run(scan, a, f) for a, f in actor_first_versions)
        )
        return [item for chunk in per_actor for item in chunk]

    async def store_ops(self, actor: Actor, version: int, data: bytes) -> None:
        import functools

        path = os.path.join(self._ops_dir(actor), str(version))
        # version-addressed: a vanished collider BURNS the version (the
        # caller probes forward) — see _write_file_new's contract
        await self._run(
            functools.partial(
                _write_file_new, path, bytes(data),
                relink_vanished_collider=False,
            )
        )

    async def remove_ops(self, actor_last_versions: list[tuple[Actor, int]]) -> None:
        def rm(actor: Actor, last: int) -> None:
            d = self._ops_dir(actor)
            for n in _list_dir(d):
                try:
                    v = int(n)
                except ValueError:
                    continue
                if v <= last:
                    _remove_quiet(os.path.join(d, n))
            try:
                os.rmdir(d)  # tidy an emptied actor dir; fails if ops remain
            except OSError:
                pass

        await asyncio.gather(*(self._run(rm, a, last) for a, last in actor_last_versions))

    # -- delta snapshots ---------------------------------------------------
    # Same layout idiom as the op logs (``remote/deltas/<actor-hex>/<N>``)
    # but a simpler read contract: logs are MAX_CHAIN-bounded and files
    # are deltas (small by construction), so a plain listdir+read per
    # actor is the whole fast path — no native scan, no probe prefilter.
    has_deltas = True

    async def list_delta_actors(self) -> list[Actor]:
        names = await self._run(_list_dir, self._deltas_dir())
        actors = []
        for n in names:
            try:
                actors.append(bytes.fromhex(n))
            except ValueError:
                continue  # foreign junk in the synced dir is not ours to judge
        return sorted(a for a in actors if len(a) == 16)

    async def load_deltas(
        self, actor_first_versions: list[tuple[Actor, int]]
    ) -> list[tuple[Actor, int, bytes]]:
        def scan(actor: Actor, first: int) -> list[tuple[Actor, int, bytes]]:
            d = self._deltas_dir(actor)
            versions = sorted(
                v for v in (
                    int(n) for n in _list_dir(d) if n.isdigit()
                ) if v >= first
            )
            out = []
            for v in versions:
                raw = _read_file(os.path.join(d, str(v)))
                if raw is not None:  # racing GC may collect mid-walk
                    out.append((actor, v, raw))
            return out

        per_actor = await asyncio.gather(
            *(self._run(scan, a, f) for a, f in actor_first_versions)
        )
        return [item for chunk in per_actor for item in chunk]

    async def store_delta(self, actor: Actor, version: int, data: bytes) -> None:
        import functools

        path = os.path.join(self._deltas_dir(actor), str(version))
        # version-addressed like op files: a vanished collider burns the
        # version (the producer probes forward) — _write_file_new's contract
        await self._run(
            functools.partial(
                _write_file_new, path, bytes(data),
                relink_vanished_collider=False,
            )
        )

    async def remove_deltas(
        self, actor_last_versions: list[tuple[Actor, int]]
    ) -> None:
        def rm(actor: Actor, last: int) -> None:
            d = self._deltas_dir(actor)
            for n in _list_dir(d):
                try:
                    v = int(n)
                except ValueError:
                    continue
                if v <= last:
                    _remove_quiet(os.path.join(d, n))
            try:
                os.rmdir(d)
            except OSError:
                pass

        await asyncio.gather(
            *(self._run(rm, a, last) for a, last in actor_last_versions)
        )

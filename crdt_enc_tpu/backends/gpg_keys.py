"""OpenPGP key-cryptor backend — real PGP recipient management.

The interop the reference's gpgme plugin declared and never shipped: its
``KeyHandler`` holds gpgme context fields and a recipient ``Meta`` CRDT,
but the actual encrypt/decrypt calls are commented out and the installed
transforms are identity functions (crdt-enc-gpgme/src/lib.rs:95-98,
118-121, 131-175).  This backend does the real thing through the ``gpg``
binary: the serialized Keys CRDT is sealed as a standard OpenPGP message
to a set of recipient key fingerprints (and optionally signed), so any
OpenPGP implementation can audit or decrypt the key metadata, and
recipient management is ordinary keyring management.

Each replica needs a GnuPG home with its own secret key and the public
keys of every recipient.  ``recipients`` are fingerprints (or any gpg
user-id selector); the local secret key decrypts inbound blobs.  Trust is
delegated to gpg's keyring (``--trust-model always`` scoped to the given
home): importing a public key into the home IS the authorization act,
playing the roster role the reference's unused ``Meta`` CRDT sketched.

A register may hold concurrent values sealed to recipient sets this
replica is not in — those are tolerated per value exactly like the
X25519 backend (``DECODE_TOLERATES``).
"""

from __future__ import annotations

import asyncio
import os
import shutil
import subprocess

from ..utils.versions import (
    GPG_KEYS_META_VERSION_1,
    SUPPORTED_GPG_KEYS_META_VERSIONS,
)
from .plain_keys import PlainKeyCryptor


class GpgError(Exception):
    """gpg invocation failed (missing binary, bad keyring, agent trouble,
    unknown recipient, …) — an ENVIRONMENTAL problem, never tolerated as
    a per-value skip."""


class NotDecryptable(GpgError):
    """This replica's keyring genuinely cannot open the blob (not a
    recipient / no secret key) or a required signature is missing — the
    only gpg failures the register decode may tolerate per value."""


class _GpgExit(GpgError):
    """Internal: nonzero gpg exit with the machine-readable status kept
    so callers can classify the failure."""

    def __init__(self, msg: str, status: bytes):
        super().__init__(msg)
        self.status = status


def gpg_available() -> bool:
    return shutil.which("gpg") is not None


def _status_has(status: bytes, keyword: str) -> bool:
    """True iff a status LINE carries ``keyword`` — never substring-match
    the whole buffer: parts of it (e.g. the PLAINTEXT filename field) are
    attacker-controlled content."""
    prefix = b"[GNUPG:] " + keyword.encode()
    return any(
        line == prefix or line.startswith(prefix + b" ")
        for line in status.splitlines()
    )


def _run_gpg(
    args: list[str], data: bytes, gnupg_home: str | None
) -> tuple[bytes, bytes]:
    """Run gpg with ``data`` on stdin; returns (stdout, status_bytes).
    ``--status-fd`` goes to a dedicated pipe (drained concurrently — gpg
    must never block on an unread status write) so machine-readable
    status is never confused with human stderr.  Nonzero exit raises
    :class:`_GpgExit` carrying the status for classification."""
    import threading

    env = dict(os.environ)
    if gnupg_home is not None:
        env["GNUPGHOME"] = os.fspath(gnupg_home)
    status_r, status_w = os.pipe()
    chunks: list[bytes] = []

    def drain():
        while True:
            chunk = os.read(status_r, 65536)
            if not chunk:
                return
            chunks.append(chunk)

    reader = threading.Thread(target=drain, daemon=True)
    reader.start()
    try:
        cmd = ["gpg", "--batch", "--yes", "--quiet", "--no-tty",
               "--pinentry-mode", "loopback",
               "--status-fd", str(status_w)] + args
        try:
            proc = subprocess.run(
                cmd, input=data, capture_output=True, env=env, timeout=120,
                pass_fds=(status_w,),
            )
        except FileNotFoundError as e:
            raise GpgError("gpg binary not found") from e
        except subprocess.TimeoutExpired as e:
            raise GpgError("gpg timed out") from e
    finally:
        os.close(status_w)
        reader.join(timeout=10)
        os.close(status_r)
    status = b"".join(chunks)
    if proc.returncode != 0:
        raise _GpgExit(
            f"gpg exited {proc.returncode}: "
            f"{proc.stderr.decode(errors='replace').strip()}",
            status,
        )
    return proc.stdout, status


class GpgKeyCryptor(PlainKeyCryptor):
    """Key management sealed as OpenPGP messages via the ``gpg`` binary.

    ``recipients``: gpg key selectors (fingerprints preferred) the Keys
    blob is encrypted to — include this replica's own key so it can read
    back its own writes.  ``gnupg_home``: the GnuPG home holding this
    replica's secret key and the recipients' public keys (None = gpg's
    default).  ``sign_with``: optional secret-key selector to sign blobs
    with (recipients should then verify; gpg rejects bad signatures on
    decrypt when ``require_signature`` is set)."""

    META_VERSION = GPG_KEYS_META_VERSION_1
    SUPPORTED_META_VERSIONS = SUPPORTED_GPG_KEYS_META_VERSIONS
    DECODE_TOLERATES = (NotDecryptable,)

    def __init__(
        self,
        recipients: list[str],
        gnupg_home: str | None = None,
        sign_with: str | None = None,
        require_signature: bool = False,
    ):
        super().__init__()
        if not recipients:
            raise ValueError("at least one OpenPGP recipient required")
        if require_signature and not sign_with:
            raise ValueError(
                "require_signature without sign_with would reject this "
                "replica's own (unsigned) writes"
            )
        self._recipients = [str(r) for r in recipients]
        self._home = gnupg_home
        self._sign_with = sign_with
        self._require_signature = require_signature

    async def _protect(self, raw: bytes) -> bytes:
        args = ["--encrypt", "--trust-model", "always", "--output", "-"]
        for r in self._recipients:
            args += ["--recipient", r]
        if self._sign_with:
            args += ["--sign", "--local-user", self._sign_with]
        try:
            out, _status = await asyncio.to_thread(
                _run_gpg, args, raw, self._home
            )
        except _GpgExit as e:
            raise GpgError(f"OpenPGP encrypt failed: {e}") from e
        return out

    async def _unprotect(self, vb) -> bytes:
        try:
            clear, status = await asyncio.to_thread(
                _run_gpg, ["--decrypt", "--output", "-"], bytes(vb.content),
                self._home,
            )
        except _GpgExit as e:
            # ONLY genuine can't-open outcomes may be tolerated per value;
            # environmental failures (agent, keyring lock, …) must stay
            # loud or a transient error could silently drop key material
            if _status_has(e.status, "DECRYPTION_FAILED") or _status_has(
                e.status, "NO_SECKEY"
            ):
                raise NotDecryptable(str(e)) from e
            raise GpgError(f"OpenPGP decrypt failed: {e}") from e
        if self._require_signature and not _status_has(status, "GOODSIG"):
            # gpg verifies embedded signatures during --decrypt; this turns
            # an UNSIGNED (or unverifiable-signer) blob from a pass-through
            # into a per-value rejection.  GOODSIG is matched as a status
            # LINE — the status buffer also carries attacker-controlled
            # content (e.g. the PLAINTEXT filename field)
            raise NotDecryptable(
                "blob is not signed by a key this keyring can verify"
            )
        return clear

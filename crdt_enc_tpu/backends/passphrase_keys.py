"""Passphrase-protected key-cryptor backend.

Fills the slot the reference's gpgme backend stubs out (its PGP
encrypt/decrypt are identity TODOs, crdt-enc-gpgme/src/lib.rs:95-98,
118-121): here the serialized Keys CRDT really is sealed before it enters
the converged remote metadata, so the data keys are never stored in the
clear.  Protection is symmetric — a passphrase every replica shares —
which is the LUKS model the reference's README describes (README.md:19-25):
rotating the passphrase re-wraps only the small Keys blob, never the data.

Wrap format (the content under ``PASSPHRASE_KEYS_META_VERSION_1``):

    msgpack([salt, log2_n, r, p, sealed])

where ``sealed`` is the XChaCha20-Poly1305 EncBox envelope (same bytes the
data path produces, backends/xchacha.py) under ``scrypt(passphrase, salt,
N=2**log2_n, r, p, dklen=32)``.  A fresh salt is drawn per write, so two
replicas writing the same Keys produce distinct blobs — convergence happens
at the CRDT layer after unwrap, exactly like the plain backend.

KDF work runs in the default thread pool (``asyncio.to_thread``); the AEAD
itself is the native C++ path releasing the GIL.
"""

from __future__ import annotations

import asyncio
import hashlib
import secrets
import threading

from ..utils import codec
from ..utils.versions import (
    PASSPHRASE_KEYS_META_VERSION_1,
    SUPPORTED_PASSPHRASE_KEYS_META_VERSIONS,
)
from . import xchacha
from .plain_keys import PlainKeyCryptor

SALT_LEN = 16
KDF_LOG2_N = 14  # scrypt N = 2**14: interactive-grade, ~50ms
KDF_R = 8
KDF_P = 1
# scrypt memory ceiling for the *decode* side: accept attacker-supplied KDF
# params only up to a bounded work factor, or a hostile meta blob could
# demand gigabytes (128 * N * r bytes) before authentication runs.  The
# bounds also keep 128*N*r*2 under OpenSSL's 2**31-1 maxmem cap, so every
# in-bounds parameter set is actually computable.
MAX_LOG2_N = 20
MAX_R = 8
MAX_P = 4


class WrongPassphrase(Exception):
    """The sealed Keys blob failed authentication under this passphrase."""


def _params_in_bounds(log2_n: int, r: int, p: int) -> bool:
    return 0 < log2_n <= MAX_LOG2_N and 0 < r <= MAX_R and 0 < p <= MAX_P


def _derive(passphrase: bytes, salt: bytes, log2_n: int, r: int, p: int) -> bytes:
    # scrypt uses 128*N*r bytes for the V array plus 128*r*p for the
    # per-lane blocks; 32 MiB slack covers overhead.  With the
    # _params_in_bounds bounds the worst case (log2_n=20, r=8, p=4) is
    # 2**30 + 36 MiB — comfortably under OpenSSL's 2**31-1 maxmem cap.
    maxmem = 128 * (1 << log2_n) * r + 128 * r * p + (1 << 25)
    return hashlib.scrypt(
        passphrase, salt=salt, n=1 << log2_n, r=r, p=p,
        maxmem=maxmem, dklen=xchacha.KEY_LEN,
    )


def wrap_blob(passphrase: bytes, raw: bytes, *, log2_n: int = KDF_LOG2_N,
              r: int = KDF_R, p: int = KDF_P, derive=_derive) -> bytes:
    if not _params_in_bounds(log2_n, r, p):
        raise ValueError(
            f"KDF params out of bounds (log2_n={log2_n}, r={r}, p={p}); "
            f"max log2_n={MAX_LOG2_N}, r={MAX_R}, p={MAX_P}"
        )
    salt = secrets.token_bytes(SALT_LEN)
    key = derive(passphrase, salt, log2_n, r, p)
    sealed = xchacha.encrypt_blob(key, raw)
    return codec.pack([salt, log2_n, r, p, sealed])


def unwrap_blob(passphrase: bytes, blob: bytes, *, derive=_derive) -> bytes:
    try:
        salt, log2_n, r, p, sealed = codec.unpack(blob)
        # type-check, never coerce: bytes(attacker_int) would zero-allocate
        # that many bytes before any validation runs
        if not isinstance(salt, (bytes, bytearray)) or not isinstance(
            sealed, (bytes, bytearray)
        ):
            raise TypeError("salt/sealed must be binary")
        salt, sealed = bytes(salt), bytes(sealed)
        log2_n, r, p = int(log2_n), int(r), int(p)
    except Exception as e:
        raise WrongPassphrase(f"malformed passphrase wrap: {e}") from e
    if not _params_in_bounds(log2_n, r, p):
        raise WrongPassphrase(
            f"KDF params out of bounds (log2_n={log2_n}, r={r}, p={p})"
        )
    try:
        key = derive(passphrase, salt, log2_n, r, p)
    except ValueError as e:  # hostile blob must never escape the error contract
        raise WrongPassphrase(f"KDF failed: {e}") from e
    try:
        return xchacha.decrypt_blob(key, sealed)
    except xchacha.AeadError as e:
        raise WrongPassphrase(str(e)) from e


class PassphraseKeyCryptor(PlainKeyCryptor):
    """Key management with a shared passphrase sealing the Keys CRDT."""

    META_VERSION = PASSPHRASE_KEYS_META_VERSION_1
    SUPPORTED_META_VERSIONS = SUPPORTED_PASSPHRASE_KEYS_META_VERSIONS

    def __init__(self, passphrase: bytes | str, *, kdf_log2_n: int = KDF_LOG2_N,
                 kdf_r: int = KDF_R, kdf_p: int = KDF_P):
        super().__init__()
        if isinstance(passphrase, str):
            passphrase = passphrase.encode()
        if not _params_in_bounds(kdf_log2_n, kdf_r, kdf_p):
            raise ValueError(
                f"KDF params out of bounds (log2_n={kdf_log2_n}, r={kdf_r}, "
                f"p={kdf_p}); max log2_n={MAX_LOG2_N}, r={MAX_R}, p={MAX_P}"
            )
        self._passphrase = passphrase
        self._kdf = (kdf_log2_n, kdf_r, kdf_p)
        # (salt, log2_n, r, p) -> derived key: set_keys unwraps the blob it
        # just wrapped, and every meta notification re-unwraps unchanged
        # blobs — the cache makes repeat derivations free without touching
        # the fresh-salt-per-write property
        self._kdf_cache: dict = {}
        self._kdf_cache_lock = threading.Lock()

    def _derive_cached(self, passphrase, salt, log2_n, r, p):
        ck = (salt, log2_n, r, p)
        with self._kdf_cache_lock:
            key = self._kdf_cache.get(ck)
        if key is None:
            key = _derive(passphrase, salt, log2_n, r, p)
            # concurrent to_thread workers share the cache; the lock keeps
            # the evict-then-insert pair atomic (a double-pop would raise)
            with self._kdf_cache_lock:
                # evict only on real growth: a concurrent duplicate insert
                # must not push out an unrelated cached derivation
                if ck not in self._kdf_cache and len(self._kdf_cache) >= 64:
                    self._kdf_cache.pop(next(iter(self._kdf_cache)), None)
                self._kdf_cache[ck] = key
        return key

    async def _protect(self, raw: bytes) -> bytes:
        log2_n, r, p = self._kdf
        return await asyncio.to_thread(
            wrap_blob, self._passphrase, raw,
            log2_n=log2_n, r=r, p=p, derive=self._derive_cached,
        )

    async def _unprotect(self, vb) -> bytes:
        return await asyncio.to_thread(
            unwrap_blob, self._passphrase, vb.content, derive=self._derive_cached
        )

from .fs import FsStorage
from .identity_crypto import IdentityCryptor
from .memory import MemoryRemote, MemoryStorage, content_name
from .passphrase_keys import PassphraseKeyCryptor, WrongPassphrase
from .plain_keys import PlainKeyCryptor
from .xchacha import AeadError, XChaChaCryptor

__all__ = [
    "AeadError",
    "FsStorage",
    "IdentityCryptor",
    "MemoryRemote",
    "MemoryStorage",
    "PassphraseKeyCryptor",
    "PlainKeyCryptor",
    "WrongPassphrase",
    "XChaChaCryptor",
    "content_name",
]

from .fs import FsStorage
from .identity_crypto import IdentityCryptor
from .memory import MemoryRemote, MemoryStorage, content_name
from .plain_keys import PlainKeyCryptor

__all__ = [
    "FsStorage",
    "IdentityCryptor",
    "MemoryRemote",
    "MemoryStorage",
    "PlainKeyCryptor",
    "content_name",
]

from .fs import FsStorage
from .identity_crypto import IdentityCryptor
from .memory import MemoryRemote, MemoryStorage, content_name
from .plain_keys import PlainKeyCryptor
from .xchacha import AeadError, XChaChaCryptor

__all__ = [
    "AeadError",
    "FsStorage",
    "IdentityCryptor",
    "MemoryRemote",
    "MemoryStorage",
    "PlainKeyCryptor",
    "XChaChaCryptor",
    "content_name",
]

from .fs import FsStorage
from .gpg_keys import GpgError, GpgKeyCryptor, NotDecryptable, gpg_available
from .identity_crypto import IdentityCryptor
from .memory import MemoryRemote, MemoryStorage, content_name
from .passphrase_keys import PassphraseKeyCryptor, WrongPassphrase
from .plain_keys import PlainKeyCryptor
from .xchacha import AeadError, XChaChaCryptor

# The X25519 backend needs the third-party `cryptography` package; load it
# lazily (PEP 562) so environments without it keep every other backend.
# Deliberately NOT in __all__: star-imports must keep working without the
# optional dependency.
_X25519_NAMES = (
    "NotARecipient",
    "UntrustedSigner",
    "X25519KeyCryptor",
    "generate_identity",
)


def __getattr__(name):
    if name in _X25519_NAMES:
        from . import x25519_keys

        return getattr(x25519_keys, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_X25519_NAMES))


__all__ = [
    "AeadError",
    "FsStorage",
    "GpgError",
    "GpgKeyCryptor",
    "IdentityCryptor",
    "NotDecryptable",
    "gpg_available",
    "MemoryRemote",
    "MemoryStorage",
    "PassphraseKeyCryptor",
    "PlainKeyCryptor",
    "WrongPassphrase",
    "XChaChaCryptor",
    "content_name",
]

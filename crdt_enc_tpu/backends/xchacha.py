"""XChaCha20-Poly1305 cryptor backend over the native C++ implementation.

Wire format mirrors the reference cipher backend
(crdt-enc-xchacha20poly1305/src/lib.rs:40-102): a 32-byte random key tagged
with the key version; encrypt draws a random 24-byte XNonce, seals with
XChaCha20-Poly1305, and wraps ``EncBox{nonce, enc_data}`` as msgpack inside a
version-tagged envelope.  Crypto runs off the event loop in the default
thread pool (the reference's spawn_blocking, lib.rs:30,48,81); the C call
holds no Python state so threads scale to the pool width.
"""

from __future__ import annotations

import asyncio
import secrets

from .. import native
from ..core.cryptor import Cryptor
from ..utils import VersionBytes, codec
from ..utils.versions import XCHACHA_DATA_VERSION_1, XCHACHA_KEY_VERSION_1

KEY_LEN = 32
NONCE_LEN = 24
TAG_LEN = 16


class AeadError(Exception):
    """Authentication failed: wrong key or tampered ciphertext."""


def _check_key(key: bytes) -> None:
    # the native code reads exactly 32 bytes; a short corrupt key blob must
    # fail here, not read past the buffer (reference errors the same way,
    # crdt-enc-xchacha20poly1305 lib.rs:43-45)
    if len(key) != KEY_LEN:
        raise AeadError(f"invalid key length {len(key)}; expected {KEY_LEN}")


def encrypt_blob(key: bytes, data: bytes) -> bytes:
    """Synchronous seal: data → raw-serialized versioned EncBox envelope."""
    _check_key(key)
    lib = native.load()
    nonce = secrets.token_bytes(NONCE_LEN)
    kp, _k = native.in_ptr(key)
    np_, _n = native.in_ptr(nonce)
    pp, _p = native.in_ptr(data)
    op, out = native.out_buf(len(data) + TAG_LEN)
    lib.xchacha20poly1305_encrypt(kp, np_, None, 0, pp, len(data), op)
    box = codec.pack([nonce, out.tobytes()])
    return VersionBytes(XCHACHA_DATA_VERSION_1, box).serialize()


def decrypt_blob(key: bytes, blob: bytes) -> bytes:
    """Synchronous open: raises AeadError on tag mismatch."""
    _check_key(key)
    lib = native.load()
    # any malformed framing is an auth failure to callers — attacker-shaped
    # input must surface as AeadError, never a raw msgpack/codec exception
    try:
        vb = VersionBytes.deserialize(blob).ensure_version(XCHACHA_DATA_VERSION_1)
        nonce, ct = codec.unpack(vb.content)
        nonce, ct = bytes(nonce), bytes(ct)
    except Exception as e:
        raise AeadError(f"malformed EncBox: {e}") from e
    if len(nonce) != NONCE_LEN or len(ct) < TAG_LEN:
        raise AeadError("malformed EncBox")
    kp, _k = native.in_ptr(key)
    np_, _n = native.in_ptr(nonce)
    cp, _c = native.in_ptr(ct)
    op, out = native.out_buf(len(ct) - TAG_LEN)
    rc = lib.xchacha20poly1305_decrypt(kp, np_, None, 0, cp, len(ct), op)
    if rc != 0:
        raise AeadError("authentication failed (wrong key or tampered data)")
    return out.tobytes()


class XChaChaCryptor(Cryptor):
    async def gen_key(self) -> VersionBytes:
        return VersionBytes(XCHACHA_KEY_VERSION_1, secrets.token_bytes(KEY_LEN))

    async def encrypt(self, key: VersionBytes, data: bytes) -> bytes:
        key.ensure_version(XCHACHA_KEY_VERSION_1)
        return await asyncio.to_thread(encrypt_blob, key.content, data)

    async def decrypt(self, key: VersionBytes, data: bytes) -> bytes:
        key.ensure_version(XCHACHA_KEY_VERSION_1)
        return await asyncio.to_thread(decrypt_blob, key.content, data)

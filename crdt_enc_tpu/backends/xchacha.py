"""XChaCha20-Poly1305 cryptor backend over the native C++ implementation.

Wire format mirrors the reference cipher backend
(crdt-enc-xchacha20poly1305/src/lib.rs:40-102): a 32-byte random key tagged
with the key version; encrypt draws a random 24-byte XNonce, seals with
XChaCha20-Poly1305, and wraps ``EncBox{nonce, enc_data}`` as msgpack inside a
version-tagged envelope.  Crypto runs off the event loop in the default
thread pool (the reference's spawn_blocking, lib.rs:30,48,81); the C call
holds no Python state so threads scale to the pool width.
"""

from __future__ import annotations

import asyncio
import logging
import os
import secrets

from .. import native

logger = logging.getLogger("crdt_enc_tpu.xchacha")

_warned_no_native_lens = False


def _warn_no_native_lens(exc: Exception) -> None:
    """Log the native-lengths-pass fallback ONCE per process: the slow
    path must be visible (a binding regression would otherwise silently
    erase the optimization — ADVICE r5), but a box that simply cannot
    build the C-API library must not spam every bulk decrypt."""
    global _warned_no_native_lens
    if not _warned_no_native_lens:
        _warned_no_native_lens = True
        logger.warning(
            "native bytes_lens_join unavailable (%r); using the Python "
            "lengths/join fallback for bulk decrypt", exc
        )
from ..core.cryptor import Cryptor
from ..utils import VersionBytes, codec
from ..utils.versions import XCHACHA_DATA_VERSION_1, XCHACHA_KEY_VERSION_1

KEY_LEN = 32
NONCE_LEN = 24
TAG_LEN = 16


class AeadError(Exception):
    """Authentication failed: wrong key or tampered ciphertext."""


def _check_key(key: bytes) -> None:
    # the native code reads exactly 32 bytes; a short corrupt key blob must
    # fail here, not read past the buffer (reference errors the same way,
    # crdt-enc-xchacha20poly1305 lib.rs:43-45)
    if len(key) != KEY_LEN:
        raise AeadError(f"invalid key length {len(key)}; expected {KEY_LEN}")


def seal_raw(key: bytes, nonce: bytes, data: bytes) -> bytes:
    """The bare AEAD: XChaCha20-Poly1305 seal with an explicit nonce,
    returning ``ct ‖ tag`` (no envelope) — for callers speaking a foreign
    framing, e.g. the reference-remote importer."""
    _check_key(key)
    if len(nonce) != NONCE_LEN:
        raise AeadError(f"invalid nonce length {len(nonce)}")
    lib = native.load()
    kp, _k = native.in_ptr(key)
    np_, _n = native.in_ptr(nonce)
    pp, _p = native.in_ptr(data)
    op, out = native.out_buf(len(data) + TAG_LEN)
    lib.xchacha20poly1305_encrypt(kp, np_, None, 0, pp, len(data), op)
    return out.tobytes()


def open_raw(key: bytes, nonce: bytes, ct: bytes) -> bytes:
    """Inverse of :func:`seal_raw`; raises AeadError on tag mismatch."""
    _check_key(key)
    if len(nonce) != NONCE_LEN or len(ct) < TAG_LEN:
        raise AeadError("malformed nonce/ciphertext")
    lib = native.load()
    kp, _k = native.in_ptr(key)
    np_, _n = native.in_ptr(nonce)
    cp, _c = native.in_ptr(ct)
    op, out = native.out_buf(len(ct) - TAG_LEN)
    rc = lib.xchacha20poly1305_decrypt(kp, np_, None, 0, cp, len(ct), op)
    if rc != 0:
        raise AeadError("authentication failed (wrong key or tampered data)")
    return out.tobytes()


def encrypt_blob(key: bytes, data: bytes) -> bytes:
    """Synchronous seal: data → raw-serialized versioned EncBox envelope."""
    _check_key(key)
    lib = native.load()
    nonce = secrets.token_bytes(NONCE_LEN)
    kp, _k = native.in_ptr(key)
    np_, _n = native.in_ptr(nonce)
    pp, _p = native.in_ptr(data)
    op, out = native.out_buf(len(data) + TAG_LEN)
    lib.xchacha20poly1305_encrypt(kp, np_, None, 0, pp, len(data), op)
    box = codec.pack([nonce, out.tobytes()])
    return VersionBytes(XCHACHA_DATA_VERSION_1, box).serialize()


def decrypt_blob(key: bytes, blob: bytes) -> bytes:
    """Synchronous open: raises AeadError on tag mismatch."""
    _check_key(key)
    lib = native.load()
    # any malformed framing is an auth failure to callers — attacker-shaped
    # input must surface as AeadError, never a raw msgpack/codec exception
    try:
        vb = VersionBytes.deserialize(blob).ensure_version(XCHACHA_DATA_VERSION_1)
        nonce, ct = codec.unpack(vb.content)
        nonce, ct = bytes(nonce), bytes(ct)
    except Exception as e:
        raise AeadError(f"malformed EncBox: {e}") from e
    if len(nonce) != NONCE_LEN or len(ct) < TAG_LEN:
        raise AeadError("malformed EncBox")
    kp, _k = native.in_ptr(key)
    np_, _n = native.in_ptr(nonce)
    cp, _c = native.in_ptr(ct)
    op, out = native.out_buf(len(ct) - TAG_LEN)
    rc = lib.xchacha20poly1305_decrypt(kp, np_, None, 0, cp, len(ct), op)
    if rc != 0:
        raise AeadError("authentication failed (wrong key or tampered data)")
    return out.tobytes()


def decrypt_blobs_packed(key: bytes, blobs: list, n_threads: int = 0):
    """Bulk open to ONE cleartext buffer: ``(buffer, offsets)`` with
    ``offsets`` a ``(n+1,)`` uint64 array (blob i's cleartext is
    ``buffer[offsets[i]:offsets[i+1]]``).  This is the zero-overhead
    shape — the columnar decoders take a packed buffer directly, so at
    100k-tiny-file scale nothing materializes 100k Python objects
    between decrypt and decode (measured: the per-blob memoryview list
    cost ~4x the crypto itself).  Returns None to request the per-blob
    fallback in ``decrypt_blobs``."""
    import numpy as np

    _check_key(key)
    lib = native.load()
    n = len(blobs)
    if n == 0:
        return b"", np.zeros(1, np.uint64)
    if n_threads <= 0:
        n_threads = min(32, os.cpu_count() or 1)

    nonce_offs = np.zeros(n, np.uint64)
    ct_offs = np.zeros(n, np.uint64)
    ct_lens = np.zeros(n, np.uint64)
    vp, _v = native.in_ptr(XCHACHA_DATA_VERSION_1)
    blens = np.empty(n, np.uint64)
    total_in = -1
    try:  # one C-API pass for the lengths (round 5: np.fromiter over
        # 83k Python len() calls cost ~5ms of the config-5 decrypt).
        # expected_n bounds the blens write: a list grown since len()
        # was taken returns -1 instead of running past the array
        slib = native.load_state()
        total_in = int(slib.bytes_lens_join(
            blobs, blens.ctypes.data_as(native.u64p), None, 0, n
        ))
    except (OSError, AttributeError, RuntimeError) as e:
        # expected unavailability only (dlopen/build failure, missing
        # symbol) — anything else is a regression that must surface, not
        # silently retire the fast path (ADVICE r5, low)
        _warn_no_native_lens(e)
    if total_in < 0:  # non-bytes elements or no native lib
        blens = np.fromiter((len(b) for b in blobs), np.uint64, count=n)
    # Pointer-array vs join: skipping the join is a pure memcpy win for
    # LARGE blobs (~40ms per 60MB on this host), but TINY blobs decrypt
    # ~1.3x FASTER from one contiguous buffer (scattered 300B heap reads
    # lose on cache/TLB locality — measured both ways).  Gate on mean
    # blob size; 8KB is comfortably past the crossover.
    use_ptrs = (
        int(blens.sum()) >= 8192 * n
        and all(type(b) is bytes for b in blobs)
    )
    if use_ptrs:
        # pointer-array parse: blobs stay in their own buffers — no join
        # of the whole batch.  The parse emits ABSOLUTE addresses; the
        # scatter below resolves them against a NULL base.
        import ctypes

        ptrs = (ctypes.c_char_p * n)(*blobs)
        total_clear = int(lib.encbox_parse_batch_ptrs(
            ptrs, blens.ctypes.data_as(native.u64p), n, vp,
            nonce_offs.ctypes.data_as(native.u64p),
            ct_offs.ctypes.data_as(native.u64p),
            ct_lens.ctypes.data_as(native.u64p),
        ))
        bp = ctypes.cast(0, native.u8p)
        _b = blobs  # keep every blob alive through the scatter call
    else:
        if total_in >= 0:
            # native join straight into one buffer (skips b"".join's
            # second list walk; same single-memcpy-per-blob cost).  The
            # join is element-count- and capacity-bounded and its return
            # is verified against the lengths pass: pure Python ran
            # between the two ctypes calls, so a caller that mutated
            # ``blobs`` in that window must land on a clean restart, not
            # a heap overrun or a partially-filled buffer (ADVICE r5,
            # medium)
            big = np.empty(total_in, np.uint8)
            joined = int(slib.bytes_lens_join(
                blobs, blens.ctypes.data_as(native.u64p),
                big.ctypes.data_as(native.u8p), total_in, n,
            ))
            if joined != total_in:
                # blobs changed between the passes: EVERY derived array
                # above (blens, n itself) is stale — restart on a
                # private snapshot of the list (the bytes elements are
                # immutable, so the snapshot cannot race again)
                return decrypt_blobs_packed(key, list(blobs), n_threads)
            bp = big.ctypes.data_as(native.u8p)
            _b = big
        else:
            big = b"".join(blobs)
            bp, _b = native.in_ptr(big)
        # offsets AFTER the join, from the same pass that packed the
        # buffer (the join refreshes blens in place): even a mutation
        # that preserved n and the total cannot leave boffs misaligned
        # with big — the frames parse exactly as packed
        boffs = np.zeros(n + 1, np.uint64)
        np.cumsum(blens, out=boffs[1:])
        total_clear = int(lib.encbox_parse_batch(
            bp, boffs.ctypes.data_as(native.u64p), n, vp,
            nonce_offs.ctypes.data_as(native.u64p),
            ct_offs.ctypes.data_as(native.u64p),
            ct_lens.ctypes.data_as(native.u64p),
        ))
    if total_clear >= 0:
        out_offs = np.zeros(n, np.uint64)
        np.cumsum(ct_lens[:-1] - TAG_LEN, out=out_offs[1:])
        op, out = native.out_buf(total_clear)
        kp, _k = native.in_ptr(key)
        ok = np.zeros(n, np.uint8)
        failures = lib.encbox_decrypt_scatter_mt(
            kp, bp,
            nonce_offs.ctypes.data_as(native.u64p),
            ct_offs.ctypes.data_as(native.u64p),
            ct_lens.ctypes.data_as(native.u64p),
            n, op,
            out_offs.ctypes.data_as(native.u64p),
            ok.ctypes.data_as(native.u8p), n_threads,
        )
        if failures:
            bad = int(np.flatnonzero(ok == 0)[0])
            raise AeadError(
                f"authentication failed on {failures}/{n} blobs (first: #{bad})"
            )
        offs = np.zeros(n + 1, np.uint64)
        np.cumsum(ct_lens - TAG_LEN, out=offs[1:])
        return out, offs
    return None


def decrypt_blobs(key: bytes, blobs: list, n_threads: int = 0) -> list:
    """Bulk open: parse every EncBox envelope and decrypt, all natively.

    Returns a list of **memoryviews** (both paths, so callers can't come
    to depend on bytes by accident): zero-copy slices of one shared
    cleartext buffer.  Treat them as transient — each view pins the whole
    buffer, and they are unhashable — and ``bytes(view)`` anything you
    keep.  Bulk pipelines should prefer ``decrypt_blobs_packed``, which
    skips this per-blob view materialization entirely."""
    import numpy as np

    _check_key(key)
    lib = native.load()
    n = len(blobs)
    if n == 0:
        return []
    packed = decrypt_blobs_packed(key, blobs, n_threads)
    if packed is not None:
        out, offs = packed
        view = memoryview(out)
        lo_hi = offs.tolist()
        return [
            view[int(lo_hi[i]) : int(lo_hi[i + 1])] for i in range(n)
        ]
    if n_threads <= 0:
        n_threads = min(32, os.cpu_count() or 1)

    # slow path: per-blob parse with index-precise errors
    nonces = bytearray(NONCE_LEN * n)
    cts = []
    offsets = np.zeros(n + 1, np.uint64)
    out_offsets = np.zeros(n, np.uint64)
    total_ct = 0
    for i, blob in enumerate(blobs):
        try:
            vb = VersionBytes.deserialize(blob).ensure_version(
                XCHACHA_DATA_VERSION_1
            )
            nonce, ct = codec.unpack(vb.content)
            nonce, ct = bytes(nonce), bytes(ct)
        except Exception as e:
            raise AeadError(f"malformed EncBox at index {i}: {e}") from e
        if len(nonce) != NONCE_LEN or len(ct) < TAG_LEN:
            raise AeadError(f"malformed EncBox at index {i}")
        nonces[i * NONCE_LEN : (i + 1) * NONCE_LEN] = nonce
        cts.append(ct)
        out_offsets[i] = total_ct - TAG_LEN * i
        total_ct += len(ct)
        offsets[i + 1] = total_ct
    ct_buf = b"".join(cts)
    kp, _k = native.in_ptr(key)
    np_, _n = native.in_ptr(bytes(nonces))
    cp, _c = native.in_ptr(ct_buf)
    op, out = native.out_buf(total_ct - TAG_LEN * n)
    ok = np.zeros(n, np.uint8)
    failures = lib.xchacha20poly1305_decrypt_batch_mt(
        kp,
        np_,
        cp,
        offsets.ctypes.data_as(native.u64p),
        n,
        op,
        out_offsets.ctypes.data_as(native.u64p),
        ok.ctypes.data_as(native.u8p),
        n_threads,
    )
    if failures:
        bad = int(np.flatnonzero(ok == 0)[0])
        raise AeadError(
            f"authentication failed on {failures}/{n} blobs (first: #{bad})"
        )
    res = []
    for i in range(n):
        lo = int(out_offsets[i])
        hi = lo + (int(offsets[i + 1] - offsets[i]) - TAG_LEN)
        res.append(memoryview(out)[lo:hi])
    return res


def decrypt_blobs_chunked(
    key: bytes, blobs: list, *, chunk_blobs: int = 0, n_chunks: int = 8,
    n_threads: int = 0,
):
    """Yield decrypted chunks with one-chunk lookahead: chunk i+1 decrypts
    on a worker thread (the native batch call releases the GIL) while the
    consumer decodes/folds chunk i.  Feeds
    ``TpuAccelerator.fold_payload_stream``; same error semantics as
    ``decrypt_blobs``, surfaced at the failing chunk."""
    from concurrent.futures import ThreadPoolExecutor

    n = len(blobs)
    if n == 0:
        return
    if chunk_blobs <= 0:
        chunk_blobs = max(1, -(-n // max(n_chunks, 1)))
    spans = [blobs[i : i + chunk_blobs] for i in range(0, n, chunk_blobs)]

    def open_chunk(span):
        packed = decrypt_blobs_packed(key, span, n_threads)
        return packed if packed is not None else decrypt_blobs(
            key, span, n_threads
        )

    if (os.cpu_count() or 1) <= 1:
        # one core: the lookahead thread cannot overlap anything real —
        # it only adds executor/context-switch overhead (~8ms at the
        # config-5 shape, measured round 5) — so decrypt synchronously
        for span in spans:
            yield open_chunk(span)
        return

    with ThreadPoolExecutor(1) as ex:
        fut = ex.submit(open_chunk, spans[0])
        for i in range(len(spans)):
            nxt = (
                ex.submit(open_chunk, spans[i + 1])
                if i + 1 < len(spans)
                else None
            )
            yield fut.result()
            fut = nxt


class XChaChaCryptor(Cryptor):
    async def gen_key(self) -> VersionBytes:
        return VersionBytes(XCHACHA_KEY_VERSION_1, secrets.token_bytes(KEY_LEN))

    async def encrypt(self, key: VersionBytes, data: bytes) -> bytes:
        key.ensure_version(XCHACHA_KEY_VERSION_1)
        return await asyncio.to_thread(encrypt_blob, key.content, data)

    async def decrypt(self, key: VersionBytes, data: bytes) -> bytes:
        key.ensure_version(XCHACHA_KEY_VERSION_1)
        return await asyncio.to_thread(decrypt_blob, key.content, data)

    async def decrypt_batch(self, key: VersionBytes, blobs: list) -> list:
        key.ensure_version(XCHACHA_KEY_VERSION_1)
        return await asyncio.to_thread(decrypt_blobs, key.content, blobs)

    def decrypt_batch_fn(self, key: VersionBytes):
        """Sync bulk-decrypt twin for the fold service (one thread hop
        for many tenants); identical bytes to ``decrypt_batch``."""
        key.ensure_version(XCHACHA_KEY_VERSION_1)
        material = key.content

        def call(blobs: list) -> list:
            return decrypt_blobs(material, blobs)

        return call

"""Recipient-keyed (asymmetric) key-cryptor backend with signed blobs.

The real version of what the reference's gpgme backend intended and left as
a stub (crdt-enc-gpgme/src/lib.rs:131-175: the PGP encrypt-to-recipients
calls are commented out; its unused ``Meta`` CRDT at lib.rs:51-66 was a set
of recipient fingerprints): the serialized Keys CRDT is sealed *to a set of
recipient identities* and *signed by the writer*, so replicas never share a
secret out of band — each holds its own identity keypair, and adding a
device means adding its public identity to the roster.

Threat model: the storage layer is UNTRUSTED (a synced directory anyone may
write to).  Confidentiality comes from the recipient seal; integrity and
roster trust come from the signature: a blob is accepted only if signed by
an identity this replica already trusts, so hostile storage can neither
tamper with blobs (signature breaks), forge Keys metadata (no trusted
signing key), nor poison the roster (recipients are unioned only from
blobs whose signature verified).  Trust is anchored at the locally
configured roster and grows only through blobs trusted identities signed
— a grow-only trust chain, the converged recipient-set CRDT the reference
declared but never used.

Identity = X25519 (sealing) + Ed25519 (signing); ``generate_identity()``
returns 64-byte (private, public) bundles (x ‖ ed halves).

Wrap format (content under ``X25519_KEYS_META_VERSION_1``):

    msgpack([body, signer_pub_bundle, signature])
    body = msgpack([eph_pub, sealed, roster, {x_pub: nonce ‖ wrapped_key}])

One random 32-byte blob key seals the Keys blob through the native
XChaCha20-Poly1305 envelope; per recipient the blob key is wrapped under
ChaCha20-Poly1305 with ``HKDF-SHA256(X25519(eph_priv, recipient_x_pub),
info = tag ‖ eph_pub ‖ recipient_x_pub)``.  ``roster`` is the full list of
recipient public identity bundles (public data); the Ed25519 signature
covers the whole body, binding roster and wraps together.  The ephemeral
keypair is fresh per write, so identical Keys produce distinct blobs —
convergence happens at the CRDT layer after unwrap.

Revocation: construct with ``pin_recipients=True`` (no roster growth),
drop the revoked identity, and ``core.rotate_key()`` — the revoked device
never receives keys sealed from then on (it keeps those it already saw).
"""

from __future__ import annotations

import secrets

from cryptography.exceptions import InvalidSignature, InvalidTag
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

from ..utils import codec
from ..utils.versions import (
    SUPPORTED_X25519_KEYS_META_VERSIONS,
    X25519_KEYS_META_VERSION_1,
)
from . import xchacha
from .plain_keys import PlainKeyCryptor

_HKDF_TAG = b"crdt-enc-tpu x25519 keys v1"
HALF_LEN = 32
BUNDLE_LEN = 64  # x25519 half ‖ ed25519 half
_NONCE_LEN = 12


class NotARecipient(Exception):
    """This replica's identity is not in the blob's recipient set (or the
    blob is malformed / fails AEAD authentication)."""


class UntrustedSigner(Exception):
    """The blob's signature is missing/invalid, or the signer is not a
    trusted identity."""


def generate_identity() -> tuple[bytes, bytes]:
    """A fresh identity: 64-byte (private, public) bundles, each the
    X25519 half followed by the Ed25519 half."""
    x = X25519PrivateKey.generate()
    ed = Ed25519PrivateKey.generate()
    priv = x.private_bytes_raw() + ed.private_bytes_raw()
    pub = (
        x.public_key().public_bytes_raw()
        + ed.public_key().public_bytes_raw()
    )
    return priv, pub


def _split(bundle: bytes, what: str) -> tuple[bytes, bytes]:
    bundle = bytes(bundle)
    if len(bundle) != BUNDLE_LEN:
        raise ValueError(f"{what} bundle must be {BUNDLE_LEN} bytes")
    return bundle[:HALF_LEN], bundle[HALF_LEN:]


def _kek(shared: bytes, eph_pub: bytes, recipient_x_pub: bytes) -> bytes:
    return HKDF(
        algorithm=hashes.SHA256(),
        length=32,
        salt=None,
        info=_HKDF_TAG + eph_pub + recipient_x_pub,
    ).derive(shared)


def wrap_blob(raw: bytes, recipients: list[bytes], signer_priv: bytes) -> bytes:
    """Seal ``raw`` to every recipient identity and sign as ``signer_priv``."""
    if not recipients:
        raise ValueError("at least one recipient identity required")
    sx_priv, sed_priv = _split(signer_priv, "signer private")
    blob_key = secrets.token_bytes(xchacha.KEY_LEN)
    sealed = xchacha.encrypt_blob(blob_key, raw)
    eph = X25519PrivateKey.generate()
    eph_pub = eph.public_key().public_bytes_raw()
    roster = []
    wraps = {}
    for bundle in recipients:
        x_pub, _ = _split(bundle, "recipient public")
        roster.append(bytes(bundle))
        shared = eph.exchange(X25519PublicKey.from_public_bytes(x_pub))
        nonce = secrets.token_bytes(_NONCE_LEN)
        wraps[x_pub] = nonce + ChaCha20Poly1305(
            _kek(shared, eph_pub, x_pub)
        ).encrypt(nonce, blob_key, b"")
    body = codec.pack([eph_pub, sealed, roster, wraps])
    ed = Ed25519PrivateKey.from_private_bytes(sed_priv)
    signer_pub = (
        X25519PrivateKey.from_private_bytes(sx_priv)
        .public_key()
        .public_bytes_raw()
        + ed.public_key().public_bytes_raw()
    )
    return codec.pack([body, signer_pub, ed.sign(body)])


def unwrap_blob(
    private_bundle: bytes, blob: bytes, trusted: set[bytes] | frozenset[bytes]
) -> tuple[bytes, list[bytes], bytes]:
    """Open a sealed Keys blob: verify the signer is trusted and the
    signature covers the body, then decrypt this replica's entry.

    Returns ``(cleartext, roster, signer_pub_bundle)`` — the verified
    recipient identity list, safe to union into a trust set.
    """
    my_x_priv, _ = _split(private_bundle, "private")
    try:
        body, signer_pub, sig = codec.unpack(blob)
        body, signer_pub, sig = bytes(body), bytes(signer_pub), bytes(sig)
        _, signer_ed = _split(signer_pub, "signer public")
    except Exception as e:
        raise UntrustedSigner(f"malformed signed wrap: {e}") from e
    if signer_pub not in trusted:
        raise UntrustedSigner("blob signed by an identity this replica does not trust")
    try:
        Ed25519PublicKey.from_public_bytes(signer_ed).verify(sig, body)
    except InvalidSignature as e:
        raise UntrustedSigner("signature verification failed") from e

    priv = X25519PrivateKey.from_private_bytes(my_x_priv)
    my_x_pub = priv.public_key().public_bytes_raw()
    try:
        eph_pub, sealed, roster, wraps = codec.unpack(body)
        eph_pub, sealed = bytes(eph_pub), bytes(sealed)
        if len(eph_pub) != HALF_LEN:
            raise ValueError("bad ephemeral public key length")
        roster = [bytes(b) for b in roster]
        if any(len(b) != BUNDLE_LEN for b in roster):
            raise ValueError("bad roster bundle length")
        entry = wraps.get(my_x_pub)
    except Exception as e:
        raise NotARecipient(f"malformed recipient wrap: {e}") from e
    if entry is None:
        raise NotARecipient(
            "this replica's identity is not in the blob's recipient set"
        )
    entry = bytes(entry)
    if len(entry) < _NONCE_LEN + 16:
        raise NotARecipient("recipient wrap entry too short")
    shared = priv.exchange(X25519PublicKey.from_public_bytes(eph_pub))
    try:
        blob_key = ChaCha20Poly1305(_kek(shared, eph_pub, my_x_pub)).decrypt(
            entry[:_NONCE_LEN], entry[_NONCE_LEN:], b""
        )
        return xchacha.decrypt_blob(blob_key, sealed), roster, signer_pub
    except (InvalidTag, xchacha.AeadError) as e:
        raise NotARecipient(f"authentication failed: {e}") from e


class X25519KeyCryptor(PlainKeyCryptor):
    """Key management sealed to recipient identities and signed by the
    writer (no shared secret).

    ``private_bundle`` is this replica's 64-byte private identity
    (``generate_identity()``); ``recipients`` are the public identity
    bundles allowed to read key material — this replica's own identity is
    included automatically, so a lone replica needs no roster at all.

    Trust & roster converge grow-only by default: a blob is only accepted
    if signed by an already-trusted identity, and the rosters of accepted
    blobs are unioned in — so a device restarted with a stale config
    cannot lock peers out, while hostile storage can never inject
    identities (it holds no trusted signing key).  ``pin_recipients=True``
    freezes the roster for deliberate revocation (follow with
    ``core.rotate_key()``).
    """

    META_VERSION = X25519_KEYS_META_VERSION_1
    SUPPORTED_META_VERSIONS = SUPPORTED_X25519_KEYS_META_VERSIONS

    def __init__(
        self,
        private_bundle: bytes,
        recipients: list[bytes] = (),
        *,
        pin_recipients: bool = False,
    ):
        super().__init__()
        self._priv = bytes(private_bundle)
        _split(self._priv, "private")  # validate early
        my_pub = self.public_identity
        pubs = [bytes(p) for p in recipients]
        for p in pubs:
            _split(p, "recipient public")
        if my_pub not in pubs:
            pubs.append(my_pub)
        self._recipients = pubs
        self._pinned = pin_recipients

    @property
    def public_identity(self) -> bytes:
        x, ed = _split(self._priv, "private")
        return (
            X25519PrivateKey.from_private_bytes(x)
            .public_key()
            .public_bytes_raw()
            + Ed25519PrivateKey.from_private_bytes(ed)
            .public_key()
            .public_bytes_raw()
        )

    @property
    def recipients(self) -> tuple[bytes, ...]:
        return tuple(self._recipients)

    async def _protect(self, raw: bytes) -> bytes:
        return wrap_blob(raw, self._recipients, self._priv)

    async def _unprotect(self, vb) -> bytes:
        clear, roster, _signer = unwrap_blob(
            self._priv, vb.content, trusted=set(self._recipients)
        )
        if not self._pinned:
            for pub in roster:
                if pub not in self._recipients:
                    self._recipients.append(pub)
        return clear

    def _trust_epoch(self):
        # roster growth is monotone (append-only unless pinned), so the
        # length is a valid fixpoint epoch for set_remote_meta's re-decode
        return len(self._recipients)

    # A register may hold concurrent values some of which this replica
    # cannot open (e.g. one written by a stale process sealing only to
    # itself).  Readable values must still decode — skipping the
    # unreadable value is safe because its writer re-converges its own
    # keys on its next write.
    DECODE_TOLERATES = (NotARecipient, UntrustedSigner)

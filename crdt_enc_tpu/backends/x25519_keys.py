"""Recipient-keyed (asymmetric) key-cryptor backend.

The real version of what the reference's gpgme backend intended and left as
a stub (crdt-enc-gpgme/src/lib.rs:131-175: the PGP encrypt-to-recipients
calls are commented out; its unused ``Meta`` CRDT at lib.rs:51-66 was a set
of recipient fingerprints): the serialized Keys CRDT is sealed *to a set of
recipient public keys*, so replicas never share a secret out of band — each
holds its own X25519 private key, and adding a device means adding its
public key to the recipient set, not re-encrypting any data.

Wrap format (content under ``X25519_KEYS_META_VERSION_1``):

    msgpack([eph_pub, sealed, {recipient_pub: nonce ‖ wrapped_blob_key}])

One random 32-byte blob key seals the Keys blob through the native
XChaCha20-Poly1305 envelope (same bytes as the data path); for each
recipient the blob key is wrapped under ChaCha20-Poly1305 with a key from
``HKDF-SHA256(X25519(eph_priv, recipient_pub), info = tag ‖ eph_pub ‖
recipient_pub)``.  The ephemeral keypair is fresh per write, so two
replicas writing the same Keys produce distinct blobs — convergence
happens at the CRDT layer after unwrap, like the other key backends.

The recipient set itself converges grow-only: the wrap map is keyed by the
full recipient public keys (they are public), and every blob a replica
successfully opens unions its recipients into the local roster — so a
replica restarted with a stale roster cannot silently lock peers out of
future key material (this realizes the converged recipient-set ``Meta``
CRDT the reference's gpgme backend declared but never used,
crdt-enc-gpgme/src/lib.rs:51-66).  Deliberate revocation opts out with
``pin_recipients=True`` + a key rotation.
"""

from __future__ import annotations

import secrets

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

from ..utils import codec
from ..utils.versions import (
    SUPPORTED_X25519_KEYS_META_VERSIONS,
    X25519_KEYS_META_VERSION_1,
)
from . import xchacha
from .plain_keys import PlainKeyCryptor

_HKDF_TAG = b"crdt-enc-tpu x25519 keys v1"
PUB_LEN = 32
_NONCE_LEN = 12


class NotARecipient(Exception):
    """This replica's public key is not in the blob's recipient set (or the
    blob is malformed / fails authentication)."""


def generate_keypair() -> tuple[bytes, bytes]:
    """A fresh (private, public) raw-byte X25519 pair."""
    priv = X25519PrivateKey.generate()
    return (
        priv.private_bytes_raw(),
        priv.public_key().public_bytes_raw(),
    )


def _kek(shared: bytes, eph_pub: bytes, recipient_pub: bytes) -> bytes:
    return HKDF(
        algorithm=hashes.SHA256(),
        length=32,
        salt=None,
        info=_HKDF_TAG + eph_pub + recipient_pub,
    ).derive(shared)


def wrap_blob(raw: bytes, recipients: list[bytes]) -> bytes:
    """Seal ``raw`` to every recipient public key."""
    if not recipients:
        raise ValueError("at least one recipient public key required")
    blob_key = secrets.token_bytes(xchacha.KEY_LEN)
    sealed = xchacha.encrypt_blob(blob_key, raw)
    eph = X25519PrivateKey.generate()
    eph_pub = eph.public_key().public_bytes_raw()
    wraps = {}
    for pub in recipients:
        pub = bytes(pub)
        if len(pub) != PUB_LEN:
            raise ValueError(f"recipient public key must be {PUB_LEN} bytes")
        shared = eph.exchange(X25519PublicKey.from_public_bytes(pub))
        nonce = secrets.token_bytes(_NONCE_LEN)
        wrapped = ChaCha20Poly1305(_kek(shared, eph_pub, pub)).encrypt(
            nonce, blob_key, b""
        )
        wraps[pub] = nonce + wrapped
    return codec.pack([eph_pub, sealed, wraps])


def unwrap_blob(private_key: bytes, blob: bytes) -> tuple[bytes, list[bytes]]:
    """Open a sealed Keys blob with this replica's private key.

    Returns ``(cleartext, recipients)`` — the blob's recipient public keys,
    so callers can converge their roster."""
    priv = X25519PrivateKey.from_private_bytes(private_key)
    my_pub = priv.public_key().public_bytes_raw()
    try:
        eph_pub, sealed, wraps = codec.unpack(blob)
        if not isinstance(eph_pub, (bytes, bytearray)) or not isinstance(
            sealed, (bytes, bytearray)
        ):
            raise TypeError("eph_pub/sealed must be binary")
        eph_pub, sealed = bytes(eph_pub), bytes(sealed)
        if len(eph_pub) != PUB_LEN:
            raise ValueError("bad ephemeral public key length")
        recipients = [bytes(p) for p in wraps]
        if any(len(p) != PUB_LEN for p in recipients):
            raise ValueError("bad recipient public key length")
        entry = wraps.get(my_pub)
    except NotARecipient:
        raise
    except Exception as e:
        raise NotARecipient(f"malformed recipient wrap: {e}") from e
    if entry is None:
        raise NotARecipient(
            "this replica's key is not in the blob's recipient set"
        )
    entry = bytes(entry)
    if len(entry) < _NONCE_LEN + 16:
        raise NotARecipient("recipient wrap entry too short")
    shared = priv.exchange(X25519PublicKey.from_public_bytes(eph_pub))
    try:
        blob_key = ChaCha20Poly1305(_kek(shared, eph_pub, my_pub)).decrypt(
            entry[:_NONCE_LEN], entry[_NONCE_LEN:], b""
        )
        return xchacha.decrypt_blob(blob_key, sealed), recipients
    except (InvalidTag, xchacha.AeadError) as e:
        raise NotARecipient(f"authentication failed: {e}") from e


class X25519KeyCryptor(PlainKeyCryptor):
    """Key management sealed to recipient public keys (no shared secret).

    ``private_key`` is this replica's raw 32-byte X25519 private key
    (``generate_keypair()``); ``recipients`` are the public keys allowed to
    read the key material — this replica's own public key is included
    automatically, so a lone replica needs no recipient list at all.

    The roster converges grow-only by default: recipients of every blob
    this replica successfully opens are unioned in, so a device restarted
    with a stale config cannot seal future key material away from peers an
    earlier writer admitted.  ``pin_recipients=True`` disables the union
    for deliberate revocation (follow with ``core.rotate_key()`` so a new
    key exists that the revoked device never receives; it keeps the old
    keys it already saw).
    """

    META_VERSION = X25519_KEYS_META_VERSION_1
    SUPPORTED_META_VERSIONS = SUPPORTED_X25519_KEYS_META_VERSIONS

    def __init__(
        self,
        private_key: bytes,
        recipients: list[bytes] = (),
        *,
        pin_recipients: bool = False,
    ):
        super().__init__()
        self._priv = bytes(private_key)
        my_pub = X25519PrivateKey.from_private_bytes(
            self._priv
        ).public_key().public_bytes_raw()
        pubs = [bytes(p) for p in recipients]
        if my_pub not in pubs:
            pubs.append(my_pub)
        self._recipients = pubs
        self._pinned = pin_recipients

    @property
    def public_key(self) -> bytes:
        return X25519PrivateKey.from_private_bytes(
            self._priv
        ).public_key().public_bytes_raw()

    @property
    def recipients(self) -> tuple[bytes, ...]:
        return tuple(self._recipients)

    async def _protect(self, raw: bytes) -> bytes:
        return wrap_blob(raw, self._recipients)

    async def _unprotect(self, vb) -> bytes:
        clear, seen = unwrap_blob(self._priv, vb.content)
        if not self._pinned:
            for pub in seen:
                if pub not in self._recipients:
                    self._recipients.append(pub)
        return clear

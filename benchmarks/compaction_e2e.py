"""True end-to-end compaction wall-clock over real encrypted files.

The BASELINE metric is "ops merged/sec + compaction wall-clock": this
harness measures the REAL thing — a populated remote directory of sealed
op files, then a fresh replica's ``open → read_remote → compact`` timed
wall-to-wall (listing, reading, decrypting, decoding, folding, sealing the
snapshot, GC), once with the host accelerator and once with the TPU
accelerator against byte-identical copies of the same remote.

Run:  python benchmarks/compaction_e2e.py [--files N] [--ops-per-file K]
Prints one JSON line: end-to-end ops/sec for both accelerators and the
speedup, plus a byte-equality check of the two compacted snapshots.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time
import uuid
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


async def build_remote(root: Path, n_writers: int, files_per_writer: int,
                       ops_per_file: int, n_members: int) -> int:
    """Writers populate the shared remote through the real product path."""
    from crdt_enc_tpu.backends import FsStorage, PlainKeyCryptor, XChaChaCryptor
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    total = 0
    for w in range(n_writers):
        core = await Core.open(OpenOptions(
            storage=FsStorage(str(root / f"w{w}"), str(root / "remote")),
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
        ))
        for _ in range(files_per_writer):
            def build(s, w=w):
                ops = []
                for j in range(ops_per_file):
                    m = (total + j * 7 + w) % n_members
                    if j % 9 == 8 and s.contains(m):
                        ops.append(s.rm_ctx(m))
                    else:
                        op = s.add_ctx(core.actor_id, m)
                        ops.append(op)
                    s.apply(ops[-1])
                return ops
            ops = await core.update(build)
            total += len(ops)
    return total


async def timed_compact(root: Path, remote: Path, accel) -> tuple[float, bytes]:
    from crdt_enc_tpu.backends import FsStorage, PlainKeyCryptor, XChaChaCryptor
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.models import canonical_bytes
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    kw = {"accelerator": accel} if accel is not None else {}
    t0 = time.perf_counter()
    core = await Core.open(OpenOptions(
        storage=FsStorage(str(root), str(remote)),
        cryptor=XChaChaCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
        **kw,
    ))
    await core.compact()
    wall = time.perf_counter() - t0
    return wall, core.with_state(canonical_bytes)


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--writers", type=int, default=32)
    ap.add_argument("--files", type=int, default=64, help="files per writer")
    ap.add_argument("--ops-per-file", type=int, default=48)
    ap.add_argument("--members", type=int, default=512)
    ap.add_argument("--build-into", help="(internal) build the remote under this dir and exit")
    ap.add_argument(
        "--skip-host", action="store_true",
        help="profiling mode: skip the (minutes-long at full scale) host "
        "compaction; byte equality is then cold==warm only",
    )
    ap.add_argument(
        "--compact-one", nargs=3, metavar=("LOCAL", "REMOTE", "ACCEL"),
        help="(internal) run one timed compaction (ACCEL: host|tpu) and print JSON",
    )
    args = ap.parse_args()

    if args.build_into:
        total = await build_remote(
            Path(args.build_into), args.writers, args.files,
            args.ops_per_file, args.members,
        )
        print(total)
        return

    if args.compact_one:
        import hashlib
        import os
        import resource

        import crdt_enc_tpu
        from crdt_enc_tpu.parallel import TpuAccelerator
        from crdt_enc_tpu.utils import trace

        crdt_enc_tpu.enable_compilation_cache()
        local, remote, kind = args.compact_one
        accel = TpuAccelerator() if kind == "tpu" else None
        profile = os.environ.get("COMPACT_PROFILE") == "1"
        if profile:
            trace.reset()
        wall, state_bytes = await timed_compact(Path(local), Path(remote), accel)
        rec = {
            "wall": wall,
            "rss_mb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024,
            "digest": hashlib.sha256(state_bytes).hexdigest(),
        }
        if profile:
            snap = trace.snapshot()
            rec["spans"] = {
                k: round(v["seconds"], 3)
                for k, v in sorted(snap["spans"].items())
            }
            rec["counters"] = snap["counters"]
            log(trace.report())
        print(json.dumps(rec))
        return

    base = Path(tempfile.mkdtemp(prefix="compact-e2e-"))
    log(f"building remote: {args.writers} writers x {args.files} files "
        f"x {args.ops_per_file} ops …")
    # the builder holds millions of live op objects — run it in a child so
    # this process's peak RSS measures the COMPACTIONS, not the synthesis
    import subprocess

    build = subprocess.run(
        [sys.executable, __file__, "--build-into", str(base),
         "--writers", str(args.writers), "--files", str(args.files),
         "--ops-per-file", str(args.ops_per_file),
         "--members", str(args.members)],
        capture_output=True, text=True,
    )
    if build.returncode != 0:
        log(build.stderr)
        raise RuntimeError("remote build failed")
    total = int(build.stdout.strip().splitlines()[-1])
    n_files = args.writers * args.files
    log(f"remote ready: {n_files} op files, {total} ops")

    # byte-identical remote copies: each compaction consumes (GCs) its
    # remote, so every measurement needs a fresh copy.  Each measurement
    # runs in its OWN child process so its peak RSS is its own — the TPU
    # pipelined ingest's bounded-memory claim is only checkable that way.
    # The TPU path runs twice — the first pays per-process jit tracing
    # (compiles come from the persistent cache) and warms it; the second
    # is the steady state a long-lived compactor sees.  Both are reported.
    remote_host = base / "remote"
    remote_tpu_cold = base / "remote-tpu-cold"
    remote_tpu_warm = base / "remote-tpu-warm"
    shutil.copytree(remote_host, remote_tpu_cold)
    shutil.copytree(remote_host, remote_tpu_warm)

    def compact_child(local: Path, remote: Path, kind: str) -> dict:
        r = subprocess.run(
            [sys.executable, __file__, "--compact-one", str(local),
             str(remote), kind],
            capture_output=True, text=True,
        )
        if r.returncode != 0:
            log(r.stderr)
            raise RuntimeError(f"{kind} compaction child failed")
        if os.environ.get("COMPACT_PROFILE") == "1":
            for ln in r.stderr.splitlines():  # the span table
                log(f"  [{kind}] {ln}")
        return json.loads(r.stdout.strip().splitlines()[-1])

    if args.skip_host:
        host = None
    else:
        host = compact_child(base / "reader-host", remote_host, "host")
        log(f"host compact: {host['wall']:.2f}s -> "
            f"{total / host['wall']:,.0f} ops/s e2e ({host['rss_mb']:.0f}MB)")
    cold = compact_child(base / "reader-tpu-cold", remote_tpu_cold, "tpu")
    log(f"tpu  compact (cold process): {cold['wall']:.2f}s")
    warm = compact_child(base / "reader-tpu", remote_tpu_warm, "tpu")
    log(f"tpu  compact (warm): {warm['wall']:.2f}s -> "
        f"{total / warm['wall']:,.0f} ops/s e2e ({warm['rss_mb']:.0f}MB)")

    equal = cold["digest"] == warm["digest"] and (
        host is None or host["digest"] == cold["digest"]
    )
    shutil.rmtree(base, ignore_errors=True)
    rec = {
        "metric": "compaction_e2e_ops_per_sec",
        "n_files": n_files,
        "n_ops": total,
        "tpu_wall_s": round(warm["wall"], 3),
        "tpu_cold_wall_s": round(cold["wall"], 3),
        "value": round(total / warm["wall"], 1),
        "unit": "ops/s",
        "byte_equal": bool(equal),
        "tpu_rss_mb": round(warm["rss_mb"], 1),
    }
    if host is not None:
        rec.update(
            host_wall_s=round(host["wall"], 3),
            vs_baseline=round(host["wall"] / warm["wall"], 2),
            host_rss_mb=round(host["rss_mb"], 1),
        )
    if "spans" in warm:
        rec["tpu_spans"] = warm["spans"]
    print(json.dumps(rec))


if __name__ == "__main__":
    asyncio.run(main())

"""True end-to-end compaction wall-clock over real encrypted files.

The BASELINE metric is "ops merged/sec + compaction wall-clock": this
harness measures the REAL thing — a populated remote directory of sealed
op files, then a fresh replica's ``open → read_remote → compact`` timed
wall-to-wall (listing, reading, decrypting, decoding, folding, sealing the
snapshot, GC), once with the host accelerator and once with the TPU
accelerator against byte-identical copies of the same remote.

Run:  python benchmarks/compaction_e2e.py [--files N] [--ops-per-file K]
Prints one JSON line: end-to-end ops/sec for both accelerators and the
speedup, plus a byte-equality check of the two compacted snapshots.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import shutil
import sys
import tempfile
import time
import uuid
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


async def build_remote(root: Path, n_writers: int, files_per_writer: int,
                       ops_per_file: int, n_members: int) -> int:
    """Writers populate the shared remote through the real product path."""
    from crdt_enc_tpu.backends import FsStorage, PlainKeyCryptor, XChaChaCryptor
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    total = 0
    for w in range(n_writers):
        core = await Core.open(OpenOptions(
            storage=FsStorage(str(root / f"w{w}"), str(root / "remote")),
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
        ))
        for _ in range(files_per_writer):
            def build(s, w=w):
                ops = []
                for j in range(ops_per_file):
                    m = (total + j * 7 + w) % n_members
                    if j % 9 == 8 and s.contains(m):
                        ops.append(s.rm_ctx(m))
                    else:
                        op = s.add_ctx(core.actor_id, m)
                        ops.append(op)
                    s.apply(ops[-1])
                return ops
            ops = await core.update(build)
            total += len(ops)
    return total


async def timed_compact(root: Path, remote: Path, accel) -> tuple[float, bytes]:
    from crdt_enc_tpu.backends import FsStorage, PlainKeyCryptor, XChaChaCryptor
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.models import canonical_bytes
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    kw = {"accelerator": accel} if accel is not None else {}
    t0 = time.perf_counter()
    core = await Core.open(OpenOptions(
        storage=FsStorage(str(root), str(remote)),
        cryptor=XChaChaCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
        **kw,
    ))
    await core.compact()
    wall = time.perf_counter() - t0
    return wall, core.with_state(canonical_bytes)


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--writers", type=int, default=32)
    ap.add_argument("--files", type=int, default=64, help="files per writer")
    ap.add_argument("--ops-per-file", type=int, default=48)
    ap.add_argument("--members", type=int, default=512)
    args = ap.parse_args()

    import crdt_enc_tpu
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.utils import trace

    # persistent compile cache: short-lived compaction jobs must not pay
    # the tens-of-seconds TPU compile on every run (first run still does)
    cache = crdt_enc_tpu.enable_compilation_cache()
    log(f"jax compilation cache: {cache}")

    base = Path(tempfile.mkdtemp(prefix="compact-e2e-"))
    log(f"building remote: {args.writers} writers x {args.files} files "
        f"x {args.ops_per_file} ops …")
    total = await build_remote(
        base, args.writers, args.files, args.ops_per_file, args.members
    )
    n_files = args.writers * args.files
    log(f"remote ready: {n_files} op files, {total} ops")

    # byte-identical remote copies: each compaction consumes (GCs) its
    # remote, so every measurement needs a fresh copy.  The TPU path runs
    # twice — the first pays per-process jit tracing (compiles come from
    # the persistent cache) and warms it; the second is the steady state a
    # long-lived compactor sees.  Both are reported.
    remote_host = base / "remote"
    remote_tpu_cold = base / "remote-tpu-cold"
    remote_tpu_warm = base / "remote-tpu-warm"
    shutil.copytree(remote_host, remote_tpu_cold)
    shutil.copytree(remote_host, remote_tpu_warm)

    wall_host, state_host = await timed_compact(
        base / "reader-host", remote_host, None
    )
    log(f"host compact: {wall_host:.2f}s -> {total / wall_host:,.0f} ops/s e2e")

    wall_cold, state_cold = await timed_compact(
        base / "reader-tpu-cold", remote_tpu_cold, TpuAccelerator()
    )
    log(f"tpu  compact (cold process): {wall_cold:.2f}s")
    trace.reset()
    wall_tpu, state_tpu = await timed_compact(
        base / "reader-tpu", remote_tpu_warm, TpuAccelerator()
    )
    log(f"tpu  compact (warm): {wall_tpu:.2f}s -> {total / wall_tpu:,.0f} ops/s e2e")
    log(trace.report())

    equal = state_host == state_tpu == state_cold
    shutil.rmtree(base, ignore_errors=True)
    print(json.dumps({
        "metric": "compaction_e2e_ops_per_sec",
        "n_files": n_files,
        "n_ops": total,
        "host_wall_s": round(wall_host, 3),
        "tpu_wall_s": round(wall_tpu, 3),
        "tpu_cold_wall_s": round(wall_cold, 3),
        "value": round(total / wall_tpu, 1),
        "unit": "ops/s",
        "vs_baseline": round(wall_host / wall_tpu, 2),
        "byte_equal": bool(equal),
    }))


if __name__ == "__main__":
    asyncio.run(main())

"""Hardware lowering smoke: run EVERY jitted kernel once on the real TPU.

CI runs the test suite on a virtual CPU mesh, which cannot catch
TPU-only lowering failures — Mosaic tiling rules, scatter lowering,
donation — as the Pallas merge block-spec bug proved (broken on hardware
for months of CPU-green tests).  This script compiles and runs each
kernel at small shapes on the real chip and byte-checks results against
the host reference where one exists.  Run it whenever kernels change:

    python benchmarks/tpu_smoke.py

Exits non-zero on any failure; prints one OK line per kernel family.
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    import jax

    from crdt_enc_tpu import ops as K
    from crdt_enc_tpu.parallel import mesh as pmesh

    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr)
    if dev.platform != "tpu":
        print("WARNING: not a TPU — this smoke only proves CPU lowering",
              file=sys.stderr)
    rng = np.random.default_rng(0)
    failures = []

    def check(name, fn):
        try:
            fn()
            print(f"OK   {name}")
        except Exception as e:  # noqa: BLE001 — report every failure
            failures.append(name)
            print(f"FAIL {name}: {type(e).__name__}: {str(e)[:300]}")

    E, R, N = 64, 48, 512
    kind = (rng.random(N) < 0.2).astype(np.int8)
    member = rng.integers(0, E, N).astype(np.int32)
    actor = rng.integers(0, R + 1, N).astype(np.int32)
    counter = rng.integers(1, 20, N).astype(np.int32)
    c0 = np.zeros(R, np.int32)
    p0 = np.zeros((E, R), np.int32)

    def orset_folds():
        outs = []
        for kw in (dict(), dict(impl="two_pass"),
                   dict(impl="two_pass", sort_segments=True),
                   dict(impl="fused", small_counters=True)):
            outs.append(K.orset_fold(
                c0, p0, p0, kind, member, actor, counter,
                num_members=E, num_replicas=R, **kw,
            ))
        ref = [np.asarray(x) for x in outs[0]]
        for o in outs[1:]:
            assert all(np.array_equal(np.asarray(a), b) for a, b in zip(o, ref))

    check("orset_fold (all variants agree)", orset_folds)

    def orset_coo():
        clock, sk, sc, last = K.orset_fold_coo(
            c0, kind, member, actor, counter, num_members=E, num_replicas=R
        )
        jax.block_until_ready((clock, sk, sc, last))

    check("orset_fold_coo", orset_coo)

    def orset_merges():
        a = np.asarray(K.orset_fold(
            c0, p0, p0, kind, member, actor, counter,
            num_members=E, num_replicas=R)[1])
        clocks = np.stack([c0 + i for i in range(4)])
        adds = np.stack([a] * 4)
        rms = np.stack([np.zeros_like(a)] * 4)
        t = K.orset_merge_many(clocks, adds, rms, impl="tree")
        p = K.orset_merge_many(clocks, adds, rms, impl="pallas")
        assert all(np.array_equal(np.asarray(x), np.asarray(y))
                   for x, y in zip(t, p)), "pallas != tree"

    check("orset_merge / merge_many / pallas", orset_merges)

    def stream():
        # chunked ≡ whole-batch only under the per-actor causal-delivery
        # contract (ops/stream.py): use monotone per-actor counters
        seen = np.zeros(R + 1, np.int32)
        c_causal = np.zeros(N, np.int32)
        for i in range(N):
            if kind[i] == 0:
                seen[actor[i]] += 1
            c_causal[i] = seen[actor[i]]
        out = K.orset_fold_stream(
            c0, p0, p0,
            K.iter_orset_chunks(kind, member, actor, c_causal, 128, R),
            num_members=E, num_replicas=R,
        )
        whole = K.orset_fold(c0, p0, p0, kind, member, actor, c_causal,
                             num_members=E, num_replicas=R)
        assert all(np.array_equal(np.asarray(a), np.asarray(b))
                   for a, b in zip(out, whole))

    check("orset_fold_stream (donated)", stream)

    def counters():
        K.gcounter_fold(c0, actor, counter, num_replicas=R)[0].block_until_ready()
        sign = (rng.random(N) < 0.4).astype(np.int8)
        K.pncounter_fold(c0, c0, sign, actor, counter, num_replicas=R)[0].block_until_ready()
        K.vclock_merge(c0, c0).block_until_ready()

    check("gcounter/pncounter/vclock", counters)

    def lww():
        Kk = 32
        key = rng.integers(0, Kk, N).astype(np.int32)
        hi = rng.integers(0, 4, N).astype(np.int32)
        lo = rng.integers(0, 100, N).astype(np.int32)
        val = rng.integers(0, 50, N).astype(np.int32)
        win = K.lww_fold(key, hi, lo, actor, val, num_keys=Kk)
        K.lww_fold_into(win, key, hi, lo, actor, val, num_keys=Kk)[0].block_until_ready()

    check("lww_fold / lww_fold_into", lww)

    def sharded():
        # single-device mesh on the real chip: shard_map must lower on TPU
        mesh = pmesh.make_mesh((1, 1))
        out = pmesh.orset_fold_sharded(
            mesh, c0, p0, p0, kind, member, actor, counter
        )
        whole = K.orset_fold(c0, p0, p0, kind, member, actor, counter,
                             num_members=E, num_replicas=R)
        assert all(np.array_equal(np.asarray(a), np.asarray(b))
                   for a, b in zip(out, whole))
        pmesh.orset_merge_sharded(mesh, *out, *out)
        sign = (rng.random(N) < 0.4).astype(np.int8)
        sp, sn, sv = pmesh.pncounter_fold_sharded(mesh, c0, c0, sign, actor, counter)
        wp, wn, wv = K.pncounter_fold(c0, c0, sign, actor, counter, num_replicas=R)
        assert np.array_equal(np.asarray(sp), np.asarray(wp))
        assert np.array_equal(np.asarray(sn), np.asarray(wn))
        assert int(sv) == int(wv)
        gc, gt = pmesh.gcounter_fold_sharded(mesh, c0, actor, counter)
        wc, wt = K.gcounter_fold(c0, actor, counter, num_replicas=R)
        assert np.array_equal(np.asarray(gc), np.asarray(wc)) and int(gt) == int(wt)
        Kk = 32
        key = rng.integers(0, Kk, N).astype(np.int32)
        hi = rng.integers(0, 4, N).astype(np.int32)
        lo = rng.integers(0, 100, N).astype(np.int32)
        val = rng.integers(0, 50, N).astype(np.int32)
        sw = pmesh.lww_fold_sharded(mesh, key, hi, lo, actor, val, num_keys=Kk)
        ww = K.lww_fold(key, hi, lo, actor, val, num_keys=Kk)
        assert all(np.array_equal(np.asarray(a), np.asarray(b))
                   for a, b in zip(sw, ww))

    check("shard_map folds (orset/counters/lww, 1x1 mesh)", sharded)

    if failures:
        print(f"\n{len(failures)} kernel(s) FAILED on this hardware: {failures}")
        return 1
    print("\nall kernels lower and run on this device")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Config 5 phase budget (VERDICT r4 item 3): name the post-round-4 wall.

Measures, at the exact suite config-5 workload (100k replicas, ~190k
ops, 83k encrypted files):

  decrypt   — batch AEAD open of every payload (no decode)
  decode    — native columnar decode of pre-decrypted chunks (feed)
  fold+wb   — the combined sparse fold + state writeback (finish),
              sub-split by the trace spans underneath
  e2e       — the real overlapped pipeline (decrypt lookahead ‖ decode)

Prints one JSON line with the table; run on an idle box.
"""

from __future__ import annotations

import json
import secrets
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from benchmarks.suite import _build_encrypted_files  # noqa: E402
from crdt_enc_tpu.backends.xchacha import (  # noqa: E402
    decrypt_blobs, decrypt_blobs_chunked,
)
from crdt_enc_tpu.models import ORSet  # noqa: E402
from crdt_enc_tpu.parallel import TpuAccelerator  # noqa: E402
from crdt_enc_tpu.utils import codec, trace  # noqa: E402


def best_of(fn, iters=3):
    out = None
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def main():
    N, R, E, ops_per_file = 200_000, 100_000, 1024, 48
    key = secrets.token_bytes(32)
    payloads, plain, headers, actors = _build_encrypted_files(
        N, R, E, ops_per_file, key, n_headers=6
    )
    total_ops = sum(len(codec.unpack(p)) for p in plain)
    accel = TpuAccelerator()
    actors_sorted = sorted(actors)
    print(f"files={len(payloads)} ops={total_ops}", file=sys.stderr)

    # ---- decrypt alone, in the pipeline's own form: per-chunk PACKED
    # batch open (one cleartext buffer + offsets per chunk — the shape
    # decrypt_blobs_chunked yields to the stream)
    from crdt_enc_tpu.backends.xchacha import decrypt_blobs_packed

    n_chunks = 8
    chunk_blobs = max(1, -(-len(payloads) // n_chunks))
    spans_b = [payloads[i:i + chunk_blobs]
               for i in range(0, len(payloads), chunk_blobs)]

    def decrypt_packed():
        return [decrypt_blobs_packed(key, s) for s in spans_b]

    t_decrypt, packed_chunks = best_of(decrypt_packed)

    # ---- decode alone: feed the pre-decrypted packed chunks, no finish
    def decode_only():
        stream = accel.open_payload_stream(ORSet(), actors_hint=actors_sorted)
        for ch in packed_chunks:
            assert stream.feed(ch)
        return stream

    t_decode, stream = best_of(decode_only)

    # ---- fold + writeback: finish() on a fed stream, trace-sub-split
    def fold_wb():
        st = decode_only()
        trace.reset()
        t0 = time.perf_counter()
        assert st.finish()
        return time.perf_counter() - t0

    t_finish = min(fold_wb() for _ in range(3))
    spans = {
        k: round(v["seconds"], 4)
        for k, v in trace.snapshot().get("spans", {}).items()
    }

    # ---- real overlapped pipeline (the suite's device path)
    def pipeline():
        folded = ORSet()
        ch = decrypt_blobs_chunked(key, payloads, n_chunks=n_chunks)
        assert accel.fold_payload_stream(folded, ch, actors_hint=actors_sorted)
        return folded

    pipeline()  # warm
    t_e2e, folded = best_of(pipeline)

    table = {
        "config": "mixed_streaming_100k_phases",
        "files": len(payloads),
        "ops": total_ops,
        "decrypt_s": round(t_decrypt, 4),
        "decode_s": round(t_decode, 4),
        "fold_writeback_s": round(t_finish, 4),
        "fold_spans_s": spans,
        "e2e_overlapped_s": round(t_e2e, 4),
        "e2e_rate_ops_s": round(total_ops / t_e2e, 1),
        "sum_phases_s": round(t_decrypt + t_decode + t_finish, 4),
    }
    print(json.dumps(table))


if __name__ == "__main__":
    main()

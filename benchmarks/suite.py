"""The five BASELINE.json benchmark configs, host-reference vs device.

Each config measures: single-core host-reference fold rate (the per-op
loop the reference runs, capped to a subsample for the big configs — the
loop is O(n) so per-op rate transfers), device fold rate, and a
byte-equality check of the folded state against the host reference on a
common subsample.

Configs 1-4 time the fold as the MARGINAL cost inside a chained
``lax.scan`` (``timeit_marginal``) so the ~100ms tunnel dispatch latency
cancels; config 5 is an end-to-end streaming pipeline (decrypt → decode →
fold) timed wall-clock, dispatch latency included — there the host-side
crypto/decode dominates and end-to-end is the honest number.

Run:  python benchmarks/suite.py [--smoke] [--config N] [--cpu]
Prints one JSON line per config and a trailing summary line.

Sizes are env-tunable (SUITE_SCALE=0.1 scales every N down 10x).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import uuid
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def running_count(group: np.ndarray, n_groups: int) -> np.ndarray:
    """1-based running occurrence count per group id, in row order."""
    n = len(group)
    order = np.argsort(group, kind="stable")
    g = group[order]
    cum = np.arange(1, n + 1, dtype=np.int64)
    starts = np.searchsorted(g, np.arange(n_groups))
    base = starts[g]
    within = cum - base
    out = np.empty(n, np.int64)
    out[order] = within
    return out.astype(np.int32)


# Pinned host-baseline protocol — the single implementation lives in
# bench.py (median-of-BENCH_HOST_RUNS with raw samples recorded); every
# config here measures through it so the two harnesses cannot drift.
from bench import host_median, host_stats, load_pinned  # noqa: E402


def _host_only_record(config, n_ops, shape, t_host, host_times):
    """What the pinning tool (pin_baselines.py) needs: the config's host
    rate under the exact workload the suite runs, with raw samples."""
    return dict(
        config=config, host_only=True, n_ops=n_ops, shape=shape,
        host_rate=n_ops / t_host, median_s=t_host,
        **host_stats(host_times),
    )


def timeit(fn, iters: int) -> float:
    import jax

    from bench import force_completion

    jax.block_until_ready(fn())  # compile + warmup
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        force_completion(out)
        best = min(best, time.perf_counter() - t0)
    return best


def timeit_marginal(make_chained, iters: int, chain: int) -> tuple[float, str]:
    """Per-fold device time as the marginal cost inside a chained scan.

    ``make_chained(n)`` returns a zero-arg callable running n
    data-dependent folds in ONE dispatch.  The TPU here sits behind a
    tunnel with ~100ms fixed dispatch latency, so single-dispatch timing
    overstates small folds ~5-100x; the chained difference cancels the
    latency (same method and jitter constant as bench.py).  Falls back to
    single-dispatch wall-clock (latency INCLUDED — a strict over-estimate)
    when the marginal signal is below the jitter noise floor.

    Returns ``(seconds_per_fold, method)`` where method is
    ``"marginal_chain"`` or ``"single_dispatch_upper_bound"``."""
    from bench import TUNNEL_JITTER_S

    t1 = timeit(make_chained(1), iters)
    # escalate the chain until the marginal signal clears the jitter floor
    # (folds keep getting faster; a fixed chain length goes deaf), bounded
    # so a pathological near-zero marginal can't spin forever
    max_chain = max(chain * 100, 1_000_000)
    while True:
        tk = timeit(make_chained(1 + chain), iters)
        marginal = (tk - t1) / chain
        floor = TUNNEL_JITTER_S / chain
        if marginal > floor:
            return marginal, "marginal_chain"
        if chain * 10 > max_chain:
            log(
                f"  marginal {marginal * 1e3:.3f}ms/fold below noise floor "
                f"{floor * 1e3:.3f}ms at chain={chain}; using single-dispatch "
                f"{t1 * 1e3:.1f}ms (tunnel latency included)"
            )
            return t1, "single_dispatch_upper_bound"
        log(
            f"  chain={chain} below noise floor "
            f"({marginal * 1e3:.4f}ms ≤ {floor * 1e3:.4f}ms); escalating"
        )
        chain *= 10


def actor_bytes_table(R: int) -> list:
    """R actor ids whose byte order equals their index order."""
    return [uuid.UUID(int=i + 1).bytes for i in range(R)]


# --------------------------------------------------------------- config 1+2


def bench_gcounter(N: int, R: int, iters: int, cmul: int = 1,
                   host_only: bool = False) -> dict:
    """Config 1: G-Counter, 4 replicas, 1k increment ops."""
    import jax
    import jax.numpy as jnp

    from crdt_enc_tpu import ops as K
    from crdt_enc_tpu.models import GCounter
    from crdt_enc_tpu.models.vclock import Dot

    rng = np.random.default_rng(1)
    actor = rng.integers(0, R, N, dtype=np.int32)
    counter = running_count(actor, R)
    actors = actor_bytes_table(R)

    def host_once():
        state = GCounter()
        t0 = time.perf_counter()
        for a, c in zip(actor.tolist(), counter.tolist()):
            state.apply(Dot(actors[a], c))
        return time.perf_counter() - t0, state

    t_host, host_times, state = host_median(host_once)
    if host_only:
        return _host_only_record(
            "gcounter_4x1k", N, dict(N=N, R=R), t_host, host_times)

    clock0 = np.zeros(R, np.int32)
    dev_args = [jax.device_put(x) for x in (clock0, actor, counter)]

    def make_chained(n):
        @jax.jit
        def run(clock0, actor, counter):
            def body(carry, _):
                # anchor the batch to the carry: min(clock[0], 0) is 0 at
                # runtime (counters are ≥ 0) but XLA cannot prove it, so
                # the scatter cannot be hoisted out of the loop — without
                # this the chain times only the elementwise tail
                # (measured: marginal flat in N, >HBM-peak "rates")
                c2 = counter + jnp.minimum(carry[0], 0)
                clock, total = K.gcounter_fold(carry, actor, c2, num_replicas=R)
                return clock, total
            return jax.lax.scan(body, clock0, None, length=n)
        return lambda: run(*dev_args)

    # sub-µs fold: only a very long chain resolves it above the jitter
    t_dev, timing = timeit_marginal(make_chained, iters, chain=500_000)
    clock, total = K.gcounter_fold(*dev_args, num_replicas=R)
    dev_clock = {actors[i]: int(c) for i, c in enumerate(np.asarray(clock)) if c}
    equal = dev_clock == state.clock.counters and int(total) == state.read()
    return dict(
        config="gcounter_4x1k", metric="ops_folded_per_sec", N=N, R=R,
        _pin_shape=dict(N=N, R=R),
        host_rate=N / t_host, device_rate=N / t_dev, byte_equal=bool(equal),
        timing=timing, bytes_model=8 * N + 2 * 4 * R, **host_stats(host_times),
    )


def bench_pncounter(N: int, R: int, iters: int, cmul: int = 1,
                    host_only: bool = False) -> dict:
    """Config 2: PN-Counter, 1k replicas, 100k mixed inc/dec ops."""
    import jax
    import jax.numpy as jnp

    from crdt_enc_tpu import ops as K
    from crdt_enc_tpu.models import PNCounter
    from crdt_enc_tpu.models.counters import NEG, POS
    from crdt_enc_tpu.models.vclock import Dot

    rng = np.random.default_rng(2)
    actor = rng.integers(0, R, N, dtype=np.int32)
    sign = (rng.random(N) < 0.3).astype(np.int8)  # ~30% decrements
    counter = running_count(actor * 2 + sign, R * 2)
    actors = actor_bytes_table(R)

    n_host = min(N, 200_000)

    def host_once():
        state = PNCounter()
        t0 = time.perf_counter()
        for a, s, c in zip(
            actor[:n_host].tolist(), sign[:n_host].tolist(),
            counter[:n_host].tolist(),
        ):
            state.apply((int(s), Dot(actors[a], c)))
        return time.perf_counter() - t0, state

    t_host, host_times, state = host_median(host_once)
    if host_only:
        return _host_only_record(
            "pncounter_1kx100k", n_host, dict(N=N, R=R, n_host=n_host),
            t_host, host_times)

    p0 = np.zeros(R, np.int32)
    n0 = np.zeros(R, np.int32)
    dev_args = [jax.device_put(x) for x in (p0, n0, sign, actor, counter)]

    def make_chained(n):
        @jax.jit
        def run(p0, n0, sign, actor, counter):
            def body(carry, _):
                # carry-anchor the batch so the segment-max cannot be
                # hoisted out of the loop (see bench_gcounter)
                c2 = counter + jnp.minimum(carry[0][0], 0)
                p, nn, value = K.pncounter_fold(
                    *carry, sign, actor, c2, num_replicas=R
                )
                return (p, nn), value
            return jax.lax.scan(body, (p0, n0), None, length=n)
        return lambda: run(*dev_args)

    t_dev, timing = timeit_marginal(make_chained, iters, chain=5_000 * cmul)
    # byte equality on the host subsample
    ps, ns, val = K.pncounter_fold(
        p0, n0, sign[:n_host], actor[:n_host], counter[:n_host], num_replicas=R
    )
    dev_p = {actors[i]: int(c) for i, c in enumerate(np.asarray(ps)) if c}
    dev_n = {actors[i]: int(c) for i, c in enumerate(np.asarray(ns)) if c}
    equal = (
        dev_p == state.p.clock.counters
        and dev_n == state.n.clock.counters
        and int(val) == state.read()
    )
    return dict(
        config="pncounter_1kx100k", metric="ops_folded_per_sec", N=N, R=R,
        _pin_shape=dict(N=N, R=R, n_host=n_host),
        host_rate=n_host / t_host, device_rate=N / t_dev, byte_equal=bool(equal),
        timing=timing, bytes_model=9 * N + 4 * 4 * R,
        **host_stats(host_times),
    )


# ----------------------------------------------------------------- config 3


from bench import orset_fold_bytes_model as _orset_bytes_model


def bench_orset(N: int, R: int, E: int, n_host: int, iters: int, cmul: int = 1,
                host_only: bool = False) -> dict:
    """Config 3 (north star): OR-Set, 10k replicas, 1M add/remove ops."""
    import jax

    import bench as north

    from crdt_enc_tpu import ops as K
    from crdt_enc_tpu.ops.columnar import Vocab, orset_planes_to_state
    from crdt_enc_tpu.utils import codec

    kind, member, actor, counter = north.gen_columns(N, R, E)
    if host_only:
        def host_once():
            state, t = north.host_fold(
                kind[:n_host], member[:n_host], actor[:n_host],
                counter[:n_host], R)
            return t, state

        t_host, host_times, _ = host_median(host_once)
        return _host_only_record(
            "orset_10kx1M", n_host, dict(N=N, R=R, E=E, n_host=n_host),
            t_host, host_times)

    # the Pallas sorted one-hot-matmul fold when eligible (the north-star
    # winner, see bench.py), else the fused XLA scatter
    from crdt_enc_tpu.ops.pallas_fold import (
        MAX_COUNTER, MAX_ROWS, fold_cap, orset_fold_pallas,
    )

    interpret = jax.default_backend() != "tpu"
    use_pallas = counter.max() < MAX_COUNTER and N <= MAX_ROWS
    if use_pallas:
        # round 5: the fused-tail kernel with host-routed defaults —
        # the same flagship path bench.py publishes (pad/unpad ride
        # inside the fold here; the bench's padded chain amortizes them)
        from crdt_enc_tpu.ops.pallas_fold import (
            fused_defaults, orset_fold_pallas_fused, orset_pad_state,
            orset_unpad_state,
        )

        tile_cap = fold_cap(member, E)
        fd = fused_defaults(E, R, int(counter.max()))

        def fold(c, a, r, kind, member, actor, counter):
            cp, ap, rp = orset_pad_state(
                c, a, r, num_members=E, num_replicas=R, h_blk=fd["h_blk"])
            out = orset_fold_pallas_fused(
                cp, ap, rp, kind, member, actor, counter,
                num_members=E, num_replicas=R, tile_cap=tile_cap,
                interpret=interpret, **fd)
            return orset_unpad_state(*out, num_members=E, num_replicas=R)
    else:
        def fold(c, a, r, kind, member, actor, counter):
            return K.orset_fold(
                c, a, r, kind, member, actor, counter,
                num_members=E, num_replicas=R,
            )

    n_chk = min(N, 20_000)
    h_state, _ = north.host_fold(
        kind[:n_chk], member[:n_chk], actor[:n_chk], counter[:n_chk], R
    )
    c0 = np.zeros(R, np.int32)
    a0 = np.zeros((E, R), np.int32)
    r0 = np.zeros((E, R), np.int32)
    ck, ad, rm = fold(
        c0, a0, r0, kind[:n_chk], member[:n_chk], actor[:n_chk], counter[:n_chk]
    )
    t_state = orset_planes_to_state(
        np.asarray(ck), np.asarray(ad), np.asarray(rm), Vocab(range(E)), Vocab(range(R))
    )
    equal = codec.pack(t_state.to_obj()) == codec.pack(h_state.to_obj())

    def host_once():
        state, t = north.host_fold(
            kind[:n_host], member[:n_host], actor[:n_host], counter[:n_host], R
        )
        return t, state

    t_host, host_times, _ = host_median(host_once)
    args = [jax.device_put(x) for x in (c0, a0, r0, kind, member, actor, counter)]

    def make_chained(n):
        import jax.numpy as jnp

        if use_pallas:
            from crdt_enc_tpu.ops.pallas_fold import orset_retire

            @jax.jit
            def run(c, a, r, kind, member, actor, counter):
                # padded-plane deferred chain, identical to bench.py's
                # pallas_fused protocol: pad once, deferred rm
                # retirement inside, one finalize after the scan
                cp, ap, rp = orset_pad_state(
                    c, a, r, num_members=E, num_replicas=R,
                    h_blk=fd["h_blk"])

                def body(carry, _):
                    shift = (carry[0][0] + carry[1][0, 0]) % jnp.int32(
                        kind.shape[0]
                    )
                    rolled = [
                        jnp.roll(x, shift)
                        for x in (kind, member, actor, counter)
                    ]
                    out = orset_fold_pallas_fused(
                        cp, ap, rp, *rolled,
                        num_members=E, num_replicas=R, tile_cap=tile_cap,
                        interpret=interpret, retire_rm=False, **fd)
                    return out, ()
                carry, _ = jax.lax.scan(
                    body, (cp, ap, rp), None, length=n)
                ck, ad, rmv = carry
                return orset_unpad_state(
                    ck, ad, orset_retire(ck, rmv),
                    num_members=E, num_replicas=R)
            return lambda: run(*args)

        @jax.jit
        def run(c, a, r, kind, member, actor, counter):
            # roll-anchored chain (see bench.py): fixed initial planes,
            # carry-derived row permutation — every iteration does the
            # full live-add workload and nothing can hoist
            def body(carry, _):
                shift = (carry[0][0] + carry[1][0, 0]) % jnp.int32(
                    kind.shape[0]
                )
                rolled = [
                    jnp.roll(x, shift)
                    for x in (kind, member, actor, counter)
                ]
                return fold(c, a, r, *rolled), ()
            carry, _ = jax.lax.scan(body, (c, a, r), None, length=n)
            return carry
        return lambda: run(*args)

    t_dev, timing = timeit_marginal(make_chained, iters, chain=20 * cmul)
    return dict(
        config="orset_10kx1M", metric="ops_folded_per_sec", N=N, R=R, E=E,
        _pin_shape=dict(N=N, R=R, E=E, n_host=n_host),
        host_rate=n_host / t_host, device_rate=N / t_dev, byte_equal=bool(equal),
        timing=timing, bytes_model=_orset_bytes_model(N, E, R),
        **host_stats(host_times),
    )


# ----------------------------------------------------------------- config 4


def bench_lwwmap(N: int, K_keys: int, R: int, n_host: int, iters: int,
                 cmul: int = 1, host_only: bool = False) -> dict:
    """Config 4: LWW-map, 1M keys, 10k replicas, timestamped writes."""
    import jax
    import jax.numpy as jnp

    from crdt_enc_tpu import ops as K
    from crdt_enc_tpu.models import LWWMap
    from crdt_enc_tpu.models.lwwmap import LWWOp
    from crdt_enc_tpu.ops.lww import ts_split

    rng = np.random.default_rng(4)
    key = rng.integers(0, K_keys, N, dtype=np.int32)
    ts = rng.integers(1, 1 << 40, N, dtype=np.int64)
    actor = rng.integers(0, R, N, dtype=np.int32)
    # single-byte msgpack domain so value rank == numeric value
    value = rng.integers(0, 100, N, dtype=np.int32)
    hi, lo = ts_split(ts)
    actors = actor_bytes_table(R)

    def host_once():
        state = LWWMap()
        t0 = time.perf_counter()
        for k, t, a, v in zip(
            key[:n_host].tolist(), ts[:n_host].tolist(),
            actor[:n_host].tolist(), value[:n_host].tolist(),
        ):
            state.apply(LWWOp(k, t, actors[a], v))
        return time.perf_counter() - t0, state

    t_host, host_times, state = host_median(host_once)
    if host_only:
        return _host_only_record(
            "lwwmap_1Mx10k", n_host,
            dict(N=N, K=K_keys, R=R, n_host=n_host), t_host, host_times)

    args = [jax.device_put(x) for x in (key, hi, lo, actor, value)]
    # value domain is 0..99 rank-interned, so the (actor, value) cascades
    # pack into one (R * V = 1M ≪ 2^31)
    n_values = int(value.max()) + 1

    def make_chained_impl(impl, tile_cap, limbs=None):
        def make_chained(n):
            @jax.jit
            def run(key, hi, lo, actor, value):
                win0 = (
                    jnp.full(K_keys, -1, jnp.int32),
                    jnp.full(K_keys, -1, jnp.int32),
                    jnp.full(K_keys, -1, jnp.int32),
                    jnp.full(K_keys, -1, jnp.int32),
                    jnp.zeros(K_keys, bool),
                )

                def body(carry, _):
                    # rotate the batch by a carry-derived offset: the fold
                    # is order-independent so the result is identical, but
                    # the inputs are loop-varying as far as XLA can tell,
                    # so the scatter passes cannot be hoisted out of the
                    # loop (measured un-anchored: marginal shrinks as N
                    # grows — the chain was timing only the compete)
                    off = jnp.abs(carry[0][0]) % jnp.int32(len(key))
                    rolled = [
                        jnp.roll(x, off)
                        for x in (key, hi, lo, actor, value)
                    ]
                    return (
                        K.lww_fold_into(
                            carry, *rolled,
                            num_keys=K_keys, num_values=n_values,
                            impl=impl, tile_cap=tile_cap, limbs=limbs,
                        ),
                        (),
                    )

                carry, _ = jax.lax.scan(body, win0, None, length=n)
                return carry
            return lambda: run(*args)
        return make_chained

    # NOTE: each chained fold competes N new rows + K_keys carried winners,
    # so device_rate = N / t_dev UNDERSTATES per-row throughput (by up to
    # ~2x when K_keys ≈ N) — conservative by construction.
    t_dev, timing = timeit_marginal(
        make_chained_impl("xla", 0), iters, chain=20 * cmul
    )
    lww_variant = "xla_cascades"
    if jax.default_backend() == "tpu":
        # the Pallas winner fold (ops/pallas_lww.py): time it as a second
        # variant and take the better, gated on exact equality with the
        # XLA fold on the full batch (parity is also pinned in tests)
        from crdt_enc_tpu.ops.pallas_lww import (
            lww_fold_pallas, lww_limbs, lww_tile_cap,
        )

        cap = lww_tile_cap(key, K_keys)
        limbs = lww_limbs(hi, lo, actor, n_values)
        ref_tbl = K.lww_fold(*args, num_keys=K_keys, num_values=n_values)
        pal_tbl = lww_fold_pallas(
            *args, num_keys=K_keys, num_values=n_values, tile_cap=cap,
            limbs=limbs,
        )
        pallas_ok = all(
            bool(jnp.array_equal(a, b)) for a, b in zip(ref_tbl, pal_tbl)
        )
        if pallas_ok:
            t_pal, timing_pal = timeit_marginal(
                make_chained_impl("pallas", cap, limbs), iters,
                chain=20 * cmul,
            )
            log(f"  lww pallas marginal {t_pal * 1e3:.2f}ms vs xla "
                f"{t_dev * 1e3:.2f}ms")
            if t_pal < t_dev:
                t_dev, timing, lww_variant = t_pal, timing_pal, "pallas_mxu"
        else:
            log("WARNING: pallas LWW fold diverged on the full batch; "
                "excluded from timing")

    # The timed path is lww_fold_into: check IT (incremental, two halves)
    # against the whole-batch fold on the host subsample, then the whole
    # fold against the host reference
    h2 = n_host // 2
    inc = K.lww_fold_into(
        K.lww_fold(key[:h2], hi[:h2], lo[:h2], actor[:h2], value[:h2],
                   num_keys=K_keys, num_values=n_values),
        key[h2:n_host], hi[h2:n_host], lo[h2:n_host], actor[h2:n_host],
        value[h2:n_host], num_keys=K_keys, num_values=n_values,
    )
    whole = K.lww_fold(
        key[:n_host], hi[:n_host], lo[:n_host], actor[:n_host], value[:n_host],
        num_keys=K_keys,
    )
    inc_equal = all(
        np.array_equal(np.asarray(a), np.asarray(b)) for a, b in zip(whole, inc)
    )
    m_hi, m_lo, m_actor, m_value, present = whole
    m_hi, m_lo = np.asarray(m_hi), np.asarray(m_lo)
    m_actor, m_value = np.asarray(m_actor), np.asarray(m_value)
    idx = np.flatnonzero(np.asarray(present))
    dev_map = LWWMap()
    dev_map.entries = {
        int(k): [
            (int(m_hi[k]) << 31) | int(m_lo[k]),
            actors[int(m_actor[k])],
            int(m_value[k]),
            False,
        ]
        for k in idx
    }
    equal = (dev_map == state) and inc_equal
    return dict(
        config="lwwmap_1Mx10k", metric="writes_folded_per_sec", N=N,
        _pin_shape=dict(N=N, K=K_keys, R=R, n_host=n_host),
        K=K_keys, R=R,
        host_rate=n_host / t_host, device_rate=N / t_dev, byte_equal=bool(equal),
        timing=timing, variant=lww_variant,
        bytes_model=20 * N + 2 * 20 * K_keys,
        **host_stats(host_times),
    )


# ----------------------------------------------------------------- config 5


def _build_encrypted_files(N, R, E, ops_per_file, key, n_headers):
    """Columns → per-(actor)-ordered op files, sealed with the native AEAD,
    plus a few header-CRDT (Keys-style MVReg) blobs mixed in."""
    import bench as north

    from crdt_enc_tpu.backends.xchacha import encrypt_blob
    from crdt_enc_tpu.models import MVReg
    from crdt_enc_tpu.utils import codec

    kind, member, actor, counter = north.gen_columns(N, R, E, seed=5)
    actors = actor_bytes_table(R)
    live = actor < R
    order = np.argsort(actor[live], kind="stable")
    k_l = kind[live][order]
    m_l = member[live][order]
    a_l = actor[live][order]
    c_l = counter[live][order]

    payloads, plain_payloads = [], []
    i, n = 0, len(k_l)
    while i < n:
        j = min(i + ops_per_file, n)
        # keep a file within one actor (files are per (actor, version))
        j = i + int(np.searchsorted(a_l[i:j], a_l[i], side="right"))
        ops = []
        for t in range(i, j):
            ab = actors[int(a_l[t])]
            if k_l[t] == 0:
                ops.append([0, int(m_l[t]), [ab, int(c_l[t])]])
            else:
                ops.append([1, int(m_l[t]), {ab: int(c_l[t])}])
        raw = codec.pack(ops)
        plain_payloads.append(raw)
        payloads.append(encrypt_blob(key, raw))
        i = j

    headers = []
    for h in range(n_headers):
        reg = MVReg()
        reg.apply(reg.write_ctx(actors[h % R], [b"hdr", h]))
        headers.append(encrypt_blob(key, codec.pack(reg.to_obj())))
    return payloads, plain_payloads, headers, actors


def bench_streaming(N, R, E, ops_per_file, n_host_files, iters,
                    host_only: bool = False) -> dict:
    """Config 5: mixed header-CRDT + OR-Set, 100k replicas, streaming
    compaction with the XChaCha20-Poly1305 decrypt front end."""
    import secrets

    from crdt_enc_tpu.backends.xchacha import decrypt_blob, decrypt_blobs
    from crdt_enc_tpu.models import MVReg, ORSet
    from crdt_enc_tpu.models.orset import AddOp, RmOp
    from crdt_enc_tpu.models.vclock import Dot, VClock
    from crdt_enc_tpu.utils import codec

    key = secrets.token_bytes(32)
    payloads, plain, headers, actors = _build_encrypted_files(
        N, R, E, ops_per_file, key, n_headers=max(1, len(str(N)))
    )
    n_files = len(payloads)
    n_ops = sum(len(codec.unpack(p)) for p in plain[:n_host_files])
    log(f"  streaming: {n_files} files, {len(headers)} headers")

    # ---- single-core host baseline: sequential decrypt → decode → apply,
    # median-of-HOST_RUNS passes with raw samples recorded (the pinned
    # protocol — single-pass timing showed 3x run-to-run variance)
    def host_once():
        state = ORSet()
        t0 = time.perf_counter()
        for blob in payloads[:n_host_files]:
            raw = decrypt_blob(key, blob)
            for o in codec.unpack(raw):
                if o[0] == 0:
                    state.apply(AddOp(o[1], Dot.from_obj(o[2])))
                else:
                    state.apply(RmOp(o[1], VClock.from_obj(o[2])))
        for h in headers:
            MVReg.from_obj(codec.unpack(decrypt_blob(key, h)))
        return time.perf_counter() - t0, state

    t_host, host_times, state = host_median(host_once)
    host_rate = n_ops / t_host
    if host_only:
        return _host_only_record(
            "mixed_streaming_100k", n_ops,
            dict(R=R, E=E, ops_per_file=ops_per_file,
                 n_host_files=n_host_files), t_host, host_times)

    # ---- streaming pipeline: chunked threaded batch decrypt overlapping
    # the native columnar decode (fold_payload_stream), then one sparse
    # fold at this replica scale.  This is the same machinery the product
    # ingest runs: Core's bulk path feeds open_payload_stream under a
    # decrypt lookahead (core.py _read_remote_ops_bulk), and the pipelined
    # session's BUFFER mode finishes through the identical
    # _fold_orset_columns tail; the full product path on a real remote is
    # measured separately in benchmarks/compaction_e2e.py.  Headers
    # decoded host-side, they are tiny.
    from crdt_enc_tpu.backends.xchacha import decrypt_blobs_chunked
    from crdt_enc_tpu.parallel import TpuAccelerator

    accel = TpuAccelerator()
    actors_sorted = sorted(actors)

    def pipeline():
        folded = ORSet()
        chunks = decrypt_blobs_chunked(key, payloads, n_chunks=8)
        for h in decrypt_blobs(key, headers):
            MVReg.from_obj(codec.unpack(h))
        ok = accel.fold_payload_stream(folded, chunks, actors_hint=actors_sorted)
        assert ok, "accelerator declined the bulk payload batch"
        return folded

    total_ops = sum(len(codec.unpack(p)) for p in plain)
    pipeline()  # warmup + compile
    t_dev = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        folded = pipeline()
        t_dev = min(t_dev, time.perf_counter() - t0)
    dev_rate = total_ops / t_dev

    # ---- byte equality: same product path over the host subsample files
    sub = ORSet()
    ok = accel.fold_payloads(
        sub, decrypt_blobs(key, payloads[:n_host_files]), actors_hint=actors_sorted
    )
    equal = bool(ok) and codec.pack(sub.to_obj()) == codec.pack(state.to_obj())
    return dict(
        config="mixed_streaming_100k", metric="ops_streamed_per_sec",
        _pin_shape=dict(R=R, E=E, ops_per_file=ops_per_file,
                        n_host_files=n_host_files),
        N=total_ops, R=R, E=E, files=n_files,
        host_rate=host_rate, device_rate=dev_rate, byte_equal=bool(equal),
        **host_stats(host_times),
        # end-to-end host pipeline (AEAD + decode dominate): the HBM
        # roofline is not the binding resource, so no pct is reported
        timing="end_to_end", bytes_model=None,
    )


# --------------------------------------------------------------------- main


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--config", type=int, default=0, help="run one config (1-5)")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument(
        "--cpu", action="store_true",
        help="force the CPU backend (the env's sitecustomize registers the "
        "TPU plugin eagerly, so JAX_PLATFORMS=cpu alone is not enough)",
    )
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")
    scale = float(os.environ.get("SUITE_SCALE", 0.02 if args.smoke else 1.0))

    def S(n, lo=64):
        return max(lo, int(n * scale))

    # smaller configs fold faster: lengthen the timing chain so the
    # marginal signal still clears the dispatch-jitter noise floor
    cmul = max(1, min(100, round(1.0 / max(scale, 0.01))))

    runners = {
        1: lambda: bench_gcounter(S(1_000), 4, args.iters, cmul),
        2: lambda: bench_pncounter(S(100_000), min(1_000, S(1_000)), args.iters, cmul),
        3: lambda: bench_orset(
            S(1_000_000), min(10_000, S(10_000)), min(4096, S(4096)),
            n_host=S(100_000, lo=2_000), iters=args.iters, cmul=cmul,
        ),
        4: lambda: bench_lwwmap(
            S(1_000_000), min(1_000_000, S(1_000_000)), min(10_000, S(10_000)),
            n_host=S(50_000, lo=2_000), iters=args.iters, cmul=cmul,
        ),
        5: lambda: bench_streaming(
            S(200_000), min(100_000, S(100_000)), min(1024, S(1024)),
            ops_per_file=48, n_host_files=S(300, lo=20), iters=args.iters,
        ),
    }
    from bench import roofline_pct

    on_tpu = dev.platform == "tpu"
    wanted = [args.config] if args.config else sorted(runners)
    results, ratios = [], []
    for c in wanted:
        log(f"config {c}…")
        r = runners[c]()
        # roofline check (round-3 item 6): bytes any implementation must
        # touch ÷ measured marginal; >100% of HBM peak is impossible —
        # the chain was hoisted — so the number is flagged and its config
        # excluded from the geomean rather than published as a speedup
        bm = r.get("bytes_model")
        pct = (
            roofline_pct(bm, r["N"] / r["device_rate"], on_tpu)
            if bm else None
        )
        r["pct_hbm_peak"] = pct
        r["super_roofline"] = bool(pct is not None and pct > 100.0)
        from bench import pinned_ratio_fields

        r.update(pinned_ratio_fields(
            r["config"], r.pop("_pin_shape", None) or {},
            r["device_rate"], r["device_rate"] / r["host_rate"],
        ))
        if r["super_roofline"]:
            r.pop("_ratio_raw", None)  # excluded — and never published
            log(
                f"WARNING: config {c} marginal implies {pct:.0f}% of HBM "
                "peak — impossible (hoisted chain); excluded from geomean"
            )
        else:
            # the geomean of record uses the pinned denominator when
            # available (VERDICT r4: same-run host rates swing 1.5×),
            # at full precision (not the 2-decimal display rounding)
            ratios.append(r.pop("_ratio_raw"))
        r["host_rate"] = round(r["host_rate"], 1)
        r["device_rate"] = round(r["device_rate"], 1)
        results.append(r)
        print(json.dumps(r), flush=True)
    ok = all(r["byte_equal"] for r in results)
    summary = {
        "suite": "baseline_configs", "device": str(dev.device_kind),
        "configs_run": wanted, "all_byte_equal": ok,
        "geomean_speedup": round(
            float(np.exp(np.mean(np.log(ratios)))), 2
        ) if ratios else None,
    }
    print(json.dumps(summary))
    # real-TPU runs persist to the committed evidence file (same policy
    # as bench.py's BENCH_LOCAL.jsonl): a capture-time tunnel outage
    # must not erase in-round suite results
    if dev.platform == "tpu":
        import datetime

        rec = {
            "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
                timespec="seconds"),
            **summary,
            "results": results,
        }
        try:
            path = Path(__file__).resolve().parent.parent / "SUITE_LOCAL.jsonl"
            with open(path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except (OSError, TypeError, ValueError) as e:
            log(f"WARNING: could not append SUITE_LOCAL.jsonl: {e!r}")


if __name__ == "__main__":
    main()

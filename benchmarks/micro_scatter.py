"""Micro-benchmarks characterizing the north-star fold's component costs
on the real chip, to size the Pallas fold kernel (round-3 item 1).

Measures, each as a chained-scan marginal (tunnel latency cancelled):
  1. fused i16 scatter alone (the suspected serialization wall)
  2. elementwise plane pass (read 2 planes, write 2 planes)
  3. jax.lax.sort of the op batch by segment key
  4. one-hot matmul segment-max prototype (scatter -> MXU reformulation)
"""
from __future__ import annotations

import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from bench import gen_columns, force_completion

try:  # persistent compile cache: repeat profile runs skip the 30-60s jits
    import crdt_enc_tpu

    crdt_enc_tpu.enable_compilation_cache()
except Exception:
    pass

N = int(os.environ.get("MB_OPS", 1_000_000))
R = int(os.environ.get("MB_REPLICAS", 10_000))
E = int(os.environ.get("MB_MEMBERS", 4096))
CHAIN = int(os.environ.get("MB_CHAIN", 20))
ITERS = 3


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def round_robin(variants, rounds_env="MB_FUSED_ROUNDS", rounds_default=6):
    """The interleaved A/B protocol (round 5): single-position marginal
    measurements swing ±2-3ms with device/tunnel weather, so compile
    every variant FIRST, then rotate timing passes across variants and
    keep per-variant minima — only interleaved comparisons count.
    ``variants`` is [(name, mk)] where mk(n) builds the n-fold chain."""
    rounds = int(os.environ.get(rounds_env, rounds_default))
    fns = {}
    for name, mk in variants:
        fns[name] = (mk(1), mk(1 + CHAIN))
        for f in fns[name]:
            jax.block_until_ready(f())  # compile now
        log(f"compiled {name}")

    def time_once(fn):
        ts = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            out = fn()
            jax.block_until_ready(out)
            force_completion(out)
            ts.append(time.perf_counter() - t0)
        return min(ts)

    best = {name: float("inf") for name, _ in variants}
    for rd in range(rounds):
        for name, _ in variants:
            f1, fk = fns[name]
            t = (time_once(fk) - time_once(f1)) / CHAIN
            best[name] = min(best[name], t)
            log(f"  round {rd} {name}: {t*1e3:.2f} ms")
    return best


def marginal(make_chain):
    def timed(fn):
        out = fn()
        jax.block_until_ready(out)
        ts = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            out = fn()
            jax.block_until_ready(out)
            force_completion(out)
            ts.append(time.perf_counter() - t0)
        return min(ts)

    t1 = timed(make_chain(1))
    tk = timed(make_chain(1 + CHAIN))
    return (tk - t1) / CHAIN


def main():
    which = set((os.environ.get("MB_WHICH") or
                 "scatter,elem,sort,onehot,i8,f32").split(","))
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); N={N} R={R} E={E} CHAIN={CHAIN}")
    kind, member, actor, counter = gen_columns(N, R, E)
    pad = actor >= R
    actor_ix = np.minimum(actor, R - 1)
    seg = (member.astype(np.int64) * R + actor_ix).astype(np.int32)
    is_rm = (kind == 1) & ~pad
    seg2 = np.where(is_rm, seg + E * R, seg).astype(np.int32)
    vals = np.where(~pad, counter, 0).astype(np.int16)

    seg2_d = jax.device_put(seg2, dev)
    vals_d = jax.device_put(vals, dev)
    c0 = jax.device_put(np.zeros(R, np.int32), dev)
    a0 = jax.device_put(np.zeros((E, R), np.int32), dev)
    r0 = jax.device_put(np.zeros((E, R), np.int32), dev)

    # 1. fused i16 scatter alone, carry-anchored (offset added to values so
    # the scatter depends on the carry; values stay positive)
    def mk_scatter(n):
        @jax.jit
        def run():
            def body(carry, _):
                z = jnp.zeros((2 * E * R,), jnp.int16)
                both = z.at[seg2_d].max(vals_d + carry.astype(jnp.int16), mode="drop")
                return both.max().astype(jnp.int32) % 2, ()
            c, _ = jax.lax.scan(body, jnp.int32(0), None, length=n)
            return c
        return run

    if "scatter" in which:
        t = marginal(mk_scatter)
        log(f"scatter i16 alone: {t*1e3:.2f} ms  ({N/t/1e6:.0f}M rows/s)")

    # 2. elementwise plane pass: read add0/rm0 + new planes, write both
    def mk_elem(n):
        @jax.jit
        def run():
            def body(carry, _):
                a, r = carry
                an = jnp.maximum(a0, a + 1)
                rn = jnp.maximum(r0, r + 1)
                an = jnp.where(an > rn, an, 0)
                return (an, rn), ()
            carry, _ = jax.lax.scan(body, (a0, r0), None, length=n)
            return carry
        return run

    if "elem" in which:
        t = marginal(mk_elem)
        log(f"elementwise 2-plane pass: {t*1e3:.2f} ms")

    # 3. sort 1M rows by (key, counter)
    key_d = jax.device_put(seg2, dev)
    cnt_d = jax.device_put(counter, dev)

    def mk_sort(n):
        @jax.jit
        def run():
            def body(carry, _):
                k, c = jax.lax.sort((key_d + carry, cnt_d), num_keys=2)
                return k[0] % 2, ()
            c, _ = jax.lax.scan(body, jnp.int32(0), None, length=n)
            return c
        return run

    if "sort" in which:
        t = marginal(mk_sort)
        log(f"sort 1M x (key,counter): {t*1e3:.2f} ms")

    # 4. one-hot matmul prototype: per member-tile segment-max as
    #    A^T @ B over padded per-tile row chunks.  Uses sorted+deduped rows
    #    (dedup zeroes non-run-max), f32 MXU.  Prototype only measures the
    #    matmul+onehot cost on pre-binned data (binning cost = sort above).
    TILE_E = 8
    T = E // TILE_E
    CMAX = int(os.environ.get("MB_CMAX", 4096))  # rows per tile, padded
    # host-side binning for the prototype
    order = np.argsort(seg, kind="stable")
    smem, sact, scnt = member[order], actor_ix[order], counter[order].astype(np.int32)
    tile = smem // TILE_E
    rows_m = np.zeros((T, CMAX), np.int32)
    rows_a = np.zeros((T, CMAX), np.int32)
    rows_v = np.zeros((T, CMAX), np.float32)
    for t_ix in range(T):
        lo, hi = np.searchsorted(tile, [t_ix, t_ix + 1])
        n_t = min(hi - lo, CMAX)
        rows_m[t_ix, :n_t] = smem[lo:lo + n_t] % TILE_E
        rows_a[t_ix, :n_t] = sact[lo:lo + n_t]
        rows_v[t_ix, :n_t] = scnt[lo:lo + n_t]
    H = (R + 127) // 128
    rm_d = jax.device_put(rows_m, dev)
    ra_d = jax.device_put(rows_a, dev)
    rv_d = jax.device_put(rows_v, dev)

    @jax.jit
    def onehot_tile(m, a, v, bump):
        # A: (C, TILE_E*H) val * onehot(m*H + a_hi); B: (C, 128) onehot(a_lo)
        a_hi, a_lo = a // 128, a % 128
        mh = m * H + a_hi
        A = (mh[:, None] == jnp.arange(TILE_E * H)[None, :]) * (v + bump)[:, None]
        B = (a_lo[:, None] == jnp.arange(128)[None, :]).astype(jnp.float32)
        acc = A.T @ B  # (TILE_E*H, 128)
        return acc.reshape(TILE_E, H * 128)[:, :R]

    def mk_onehot(n):
        @jax.jit
        def run():
            def body(carry, _):
                out = jax.lax.map(
                    lambda t: onehot_tile(rm_d[t], ra_d[t], rv_d[t], carry),
                    jnp.arange(T), batch_size=64,
                )
                return out.max() % 2, ()
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=n)
            return c
        return run

    if "onehot" in which:
        t = marginal(mk_onehot)
        log(f"one-hot matmul f32 (T={T}, CMAX={CMAX}): {t*1e3:.2f} ms")

    # 5. int8 matmul probe: does lax.dot_general int8xint8->int32 compile+run fast?
    ai8 = jax.device_put(np.random.randint(0, 127, (4096, 4096), np.int8), dev)
    bi8 = jax.device_put(np.random.randint(0, 127, (4096, 4096), np.int8), dev)

    def mk_i8(n):
        @jax.jit
        def run():
            def body(carry, _):
                o = jax.lax.dot_general(
                    ai8, bi8, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32,
                ) + carry
                return o[0, 0], ()
            c, _ = jax.lax.scan(body, jnp.int32(0), None, length=n)
            return c
        return run

    if "i8" in which:
        try:
            t = marginal(mk_i8)
            gf = 2 * 4096**3 / t / 1e12
            log(f"int8 4096^3 matmul: {t*1e3:.2f} ms ({gf:.0f} Tops)")
        except Exception as e:
            log(f"int8 matmul failed: {e}")

    # 6. f32 4096^3 matmul for reference
    af = jax.device_put(np.random.rand(4096, 4096).astype(np.float32), dev)

    def mk_f32(n):
        @jax.jit
        def run():
            def body(carry, _):
                o = af @ (af + carry)
                return o[0, 0], ()
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=n)
            return c
        return run

    if "f32" in which:
        t = marginal(mk_f32)
        log(f"f32 4096^3 matmul: {t*1e3:.2f} ms ({2*4096**3/t/1e12:.0f} TFLOPs)")




def pallas_sections(which):
    """Round-3 additions: time the Pallas fold's XLA prologue (sort +
    dedup + edges) separately from the full fold, to locate the wall."""
    import jax
    import jax.numpy as jnp

    from bench import gen_columns
    from crdt_enc_tpu.ops.pallas_fold import (
        TILE_E, fold_cap, orset_fold_pallas,
    )

    dev = jax.devices()[0]
    kind, member, actor, counter = gen_columns(N, R, E)
    c0 = jax.device_put(np.zeros(R, np.int32), dev)
    a0 = jax.device_put(np.zeros((E, R), np.int32), dev)
    r0 = jax.device_put(np.zeros((E, R), np.int32), dev)
    rows = [jax.device_put(x, dev) for x in (kind, member, actor, counter)]
    tile_cap = fold_cap(member, E)

    if "prologue" in which:
        T = -(-E // TILE_E)

        def mk(n):
            @jax.jit
            def run():
                def body(carry, _):
                    shift = carry % jnp.int32(N)
                    k, m, a, c = (jnp.roll(x, shift) for x in rows)
                    pad = a >= R
                    a_ix = jnp.minimum(a, R - 1)
                    is_add = (k == 0) & ~pad
                    is_rm = (k == 1) & ~pad
                    tile = m // TILE_E
                    key = jnp.where(
                        is_add | is_rm,
                        (tile * 2 + is_rm) * (TILE_E * R)
                        + (m - tile * TILE_E) * R + a_ix,
                        T * 2 * TILE_E * R,
                    )
                    # cell-level replay gate lives in the kernel tail now
                    gv = jnp.where(is_add | is_rm, c, 0)
                    sk, sv = jax.lax.sort((key, gv), num_keys=2)
                    nxt = jnp.concatenate([sk[1:], jnp.full((1,), -1, sk.dtype)])
                    sv = jnp.where((sk != nxt), sv, 0)
                    bounds = jnp.arange(2 * T + 1, dtype=jnp.int32) * (TILE_E * R)
                    edges = jnp.searchsorted(sk, bounds).astype(jnp.int32)
                    return edges[0] + sv[0], ()
                out, _ = jax.lax.scan(body, jnp.int32(0), None, length=n)
                return out
            return run

        t = marginal(mk)
        log(f"pallas prologue (sort+dedup+edges): {t*1e3:.2f} ms")

    if "pallasfold" in which:
        def mk(n):
            @jax.jit
            def run():
                def body(carry, _):
                    shift = (carry[0][0] + carry[1][0, 0]) % jnp.int32(N)
                    k, m, a, c = (jnp.roll(x, shift) for x in rows)
                    out = orset_fold_pallas(
                        c0, a0, r0, k, m, a, c,
                        num_members=E, num_replicas=R, tile_cap=tile_cap,
                    )
                    return out, ()
                carry, _ = jax.lax.scan(
                    body, (c0, a0, r0), None, length=n
                )
                return carry
            return run

        t = marginal(mk)
        log(f"pallas full fold: {t*1e3:.2f} ms  ({N/t/1e6:.0f}M ops/s)")


def ablk_sections(which):
    """Round-4 phase profile of the ablk Pallas fold: where do 7.5ms go?

    Sections:
      sort1      — the 2-operand bitonic sort comparing ONLY the key
                   (num_keys=1) vs the production num_keys=2 sort
      ablkpro    — the full XLA prologue of the ablk path (key calc +
                   sort + dedup + searchsorted edges + padding)
      ablkscan   — scatter-phase marginals across kernel-body modes
                   (hi_mode x win_mode) and sub_rows, isolating the
                   per-chunk branch overhead and chunk-size sweet spot
    """
    import jax
    import jax.numpy as jnp

    from bench import gen_columns
    from crdt_enc_tpu.ops.pallas_fold import (
        LANE, TILE_E, fold_cap, orset_scatter_pallas,
    )

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); N={N} R={R} E={E}")
    kind, member, actor, counter = gen_columns(N, R, E)
    rows = [jax.device_put(x, dev) for x in (kind, member, actor, counter)]
    tile_cap = fold_cap(member, E)
    log(f"tile_cap={tile_cap}, counter.max()={counter.max()}")

    key_np = (member.astype(np.int64) * R + np.minimum(actor, R - 1)) % (2**31 - 1)
    key_d = jax.device_put(key_np.astype(np.int32), dev)
    cnt_d = jax.device_put(counter, dev)

    if "sort1" in which:
        cnt16_d = jax.device_put(counter.astype(np.int16), dev)
        for nk, val, tag in (
            (1, cnt_d, "i32 val"),
            (2, cnt_d, "i32 val"),
            (2, cnt16_d, "i16 val"),
        ):
            def mk(n, nk=nk, val=val):
                @jax.jit
                def run():
                    def body(carry, _):
                        k, c = jax.lax.sort((key_d + carry, val), num_keys=nk)
                        return k[0] % 2, ()
                    c, _ = jax.lax.scan(body, jnp.int32(0), None, length=n)
                    return c
                return run

            t = marginal(mk)
            log(f"sort 1M rows, num_keys={nk}, {tag}: {t*1e3:.2f} ms")

    if "ablkpro" in which:
        # the exact prologue orset_scatter_pallas runs, minus pallas_call
        from crdt_enc_tpu.ops.pallas_fold import ablk_key_space_fits

        assert ablk_key_space_fits(E, R)
        Ep = -(-E // TILE_E) * TILE_E
        T = Ep // TILE_E
        H = -(-R // LANE)
        H_BLK = 16 if H > 8 else 8
        Hp = -(-H // H_BLK) * H_BLK
        A_BLK = Hp // H_BLK
        SEG = TILE_E * H_BLK * LANE
        n_segs = 2 * T * A_BLK

        def mk(n):
            @jax.jit
            def run():
                def body(carry, _):
                    k, m, a, c = rows
                    c = c + carry  # carry-anchor
                    pad = a >= R
                    a_ix = jnp.minimum(a, R - 1)
                    is_add = (k == 0) & ~pad
                    is_rm = (k == 1) & ~pad
                    tile = m // TILE_E
                    m_local = m - tile * TILE_E
                    plane = is_rm.astype(jnp.int32)
                    a_hi = a_ix // LANE
                    a_lo = a_ix - a_hi * LANE
                    blk = a_hi // H_BLK
                    a_hil = a_hi - blk * H_BLK
                    seg_id = (tile * 2 + plane) * A_BLK + blk
                    within = (m_local * H_BLK + a_hil) * LANE + a_lo
                    sentinel = n_segs * SEG
                    key = jnp.where(
                        is_add | is_rm, seg_id * SEG + within, sentinel
                    )
                    gval = jnp.where(is_add | is_rm, c, 0)
                    skey, sval = jax.lax.sort((key, gval), num_keys=2)
                    nxt = jnp.concatenate(
                        [skey[1:], jnp.full((1,), -1, skey.dtype)]
                    )
                    sval = jnp.where((skey != nxt) & (skey < sentinel), sval, 0)
                    bounds = jnp.arange(n_segs + 1, dtype=jnp.int32) * SEG
                    edges = jnp.searchsorted(skey, bounds).astype(jnp.int32)
                    return edges[0] + sval[0], ()
                out, _ = jax.lax.scan(body, jnp.int32(0), None, length=n)
                return out
            return run

        t = marginal(mk)
        log(f"ablk prologue (keys+sort+dedup+edges): {t*1e3:.2f} ms")

    if "ablkscan" in which:
        default_modes = [
            ("cond", "cond", 256, "bf16"),    # round-3 default
            # production default is ("cond", "select") — win_mode
            # "select" won the round-4 A/B and the wrappers default to it
            ("fused", "cond", 256, "bf16"),   # no hi-limb branch
            ("cond", "select", 256, "bf16"),  # no window branch
            ("fused", "select", 256, "bf16"), # fully branchless body
            ("fused", "select", 128, "bf16"),
            ("fused", "select", 512, "bf16"),
        ]
        round2_modes = [
            # round 2 of the profile: SUBK sweep under the round-1
            # winner (cond hi-limb, branchless window loads).  int8 was
            # tried and REJECTED: Mosaic cannot legalize the int8 vector
            # multiply in the one-hot build (arith.muli on vector<...xi8>,
            # 2026-07-31), so the MXU dtype stays bf16.
            ("cond", "select", 128, "bf16"),
            ("cond", "select", 512, "bf16"),
        ]
        round3_modes = [
            # round 3: accumulator layout under the winning config —
            # blocked = one contiguous 128-row add per chunk + an XLA
            # transpose, member = 8 strided slice-adds, free reshape
            ("cond", "select", 256, "bf16", "blocked"),
            ("cond", "select", 256, "bf16", "member"),
        ]
        round4_modes = [
            # round 4: key-only sort + in-kernel segmented run-max
            # (dedup_mode="kernel") vs the 2-key sort + XLA dedup, both
            # under the round-3 winner (blocked accumulator).  Repeated
            # A/B/A/B in ONE process: single-shot runs swung 4.5-6.1ms
            # on the same config, so only interleaved deltas count.
            ("cond", "select", 256, "bf16", "blocked", "kernel"),
            ("cond", "select", 256, "bf16", "blocked", "sorted"),
            ("cond", "select", 256, "bf16", "blocked", "kernel"),
            ("cond", "select", 256, "bf16", "blocked", "sorted"),
        ]
        round5_modes = [
            # round 5: dedup A/B under the PRODUCTION accumulator
            # (member-major) — round 4's A/B ran under blocked.
            # Interleaved A/B/A/B; only the deltas count.
            ("cond", "select", 256, "bf16", "member", "kernel"),
            ("cond", "select", 256, "bf16", "member", "sorted"),
            ("cond", "select", 256, "bf16", "member", "kernel"),
            ("cond", "select", 256, "bf16", "member", "sorted"),
        ]
        mb_round = os.environ.get("MB_ABLK_ROUND")
        mode_list = (
            round5_modes if mb_round == "5"
            else round4_modes if mb_round == "4"
            else round3_modes if mb_round == "3"
            else round2_modes if mb_round == "2"
            else default_modes
        )
        for hi_mode, win_mode, subk, dt, *rest in mode_list:
            acc = rest[0] if rest else "member"
            dd = rest[1] if len(rest) > 1 else "sorted"

            def mk(n, hi=hi_mode, win=win_mode, sr=subk, dt=dt, acc=acc,
                   dd=dd):
                @jax.jit
                def run():
                    def body(carry, _):
                        k, m, a, c = rows
                        out = orset_scatter_pallas(
                            k, m, a, c + carry, num_members=E,
                            num_replicas=R, tile_cap=tile_cap,
                            sub_rows=sr, hi_mode=hi, win_mode=win,
                            dot_impl=dt, acc_mode=acc, dedup_mode=dd,
                        )
                        # keep the anchor to {0,1}: counters must stay in
                        # the production range or the hi-limb branch
                        # frequency (and exactness) would drift
                        return out[0][0, 0] % 2, ()
                    o, _ = jax.lax.scan(body, jnp.int32(0), None, length=n)
                    return o
                return run

            try:
                t = marginal(mk)
                log(
                    f"ablk scatter hi={hi_mode} win={win_mode} "
                    f"SUBK={subk} dot={dt} acc={acc} dedup={dd}: "
                    f"{t*1e3:.2f} ms"
                )
            except Exception as e:
                log(
                    f"ablk scatter hi={hi_mode} win={win_mode} "
                    f"SUBK={subk} dot={dt} acc={acc} dedup={dd}: FAILED "
                    f"{type(e).__name__}: {e}"
                )


def fused_sections(which):
    """Round-5: the fused-tail fold (padded-plane carry) vs the unfused
    full fold, plus the hi_mode=skip/limb_bits=8 ablation."""
    import jax
    import jax.numpy as jnp

    from bench import gen_columns
    from crdt_enc_tpu.ops.pallas_fold import (
        fold_cap, orset_fold_pallas, orset_fold_pallas_fused,
        orset_pad_state,
    )

    dev = jax.devices()[0]
    kind, member, actor, counter = gen_columns(N, R, E)
    log(f"device: {dev.platform} ({dev.device_kind}); N={N} R={R} E={E} "
        f"counter.max()={counter.max()}")
    c0 = jax.device_put(np.zeros(R, np.int32), dev)
    a0 = jax.device_put(np.zeros((E, R), np.int32), dev)
    r0 = jax.device_put(np.zeros((E, R), np.int32), dev)
    rows = [jax.device_put(x, dev) for x in (kind, member, actor, counter)]
    tile_cap = fold_cap(member, E)
    skip_ok = counter.max() < 256

    def mk_fused(hi, lb, ret, h_blk=None, subk=None):
        from crdt_enc_tpu.ops.pallas_fold import SUB_ABLK, orset_retire
        sr = subk or SUB_ABLK

        def mk(n):
            @jax.jit
            def run():
                cp, ap, rp = orset_pad_state(
                    c0, a0, r0, num_members=E, num_replicas=R, h_blk=h_blk)

                def body(carry, _):
                    # fixed initial planes + carry-derived roll: the
                    # same marginal protocol as the pallasfold section
                    shift = (carry[0][0] + carry[1][0, 0]) % jnp.int32(N)
                    k, m, a, c = (jnp.roll(x, shift) for x in rows)
                    out = orset_fold_pallas_fused(
                        cp, ap, rp, k, m, a, c,
                        num_members=E, num_replicas=R, tile_cap=tile_cap,
                        hi_mode=hi, limb_bits=lb, retire_rm=ret,
                        h_blk=h_blk, sub_rows=sr,
                    )
                    return out, ()
                carry, _ = jax.lax.scan(body, (cp, ap, rp), None, length=n)
                if not ret:  # deferred chain: one finalize (cancels in
                    # the marginal — present in both chain lengths)
                    carry = (carry[0], carry[1],
                             orset_retire(carry[0], carry[2]))
                return carry
            return run
        return mk

    def mk_unfused(n):
        @jax.jit
        def run():
            def body(carry, _):
                shift = (carry[0][0] + carry[1][0, 0]) % jnp.int32(N)
                k, m, a, c = (jnp.roll(x, shift) for x in rows)
                out = orset_fold_pallas(
                    c0, a0, r0, k, m, a, c,
                    num_members=E, num_replicas=R, tile_cap=tile_cap,
                )
                return out, ()
            carry, _ = jax.lax.scan(body, (c0, a0, r0), None, length=n)
            return carry
        return run

    variants = [("unfused", mk_unfused),
                ("fused cond/7", mk_fused("cond", 7, True))]
    if skip_ok:
        variants += [
            ("fused skip/8 eager", mk_fused("skip", 8, True)),
            ("fused skip/8 defer", mk_fused("skip", 8, False)),
            ("fused skip/8 defer hblk32", mk_fused("skip", 8, False, 32)),
            ("fused skip/8 defer hblk80", mk_fused("skip", 8, False, 80)),
            ("fused skip/8 defer hblk32 subk512",
             mk_fused("skip", 8, False, 32, 512)),
        ]
    if os.environ.get("MB_FUSED_HBLK2") == "1" and skip_ok:
        # round-5 second sweep: larger actor blocks cut n_segs further
        # (48 → A_BLK=2, 64 → A_BLK=2 at R=10k) at the cost of taller
        # one-hots (384/512 rows — VPU/MXU still far from the wall)
        variants = [
            ("fused skip/8 defer hblk32", mk_fused("skip", 8, False, 32)),
            ("fused skip/8 defer hblk48", mk_fused("skip", 8, False, 48)),
            ("fused skip/8 defer hblk64", mk_fused("skip", 8, False, 64)),
        ]

    best = round_robin(variants)
    for name, _ in variants:
        t = best[name]
        log(f"BEST {name}: {t*1e3:.2f} ms ({N/t/1e6:.0f}M ops/s)")


def lww_sections(which):
    """Round-4 LWW kernel A/B: window-load cond vs select on the
    config-4 shape (1M rows, 1M keys)."""
    import jax
    import jax.numpy as jnp

    from crdt_enc_tpu.ops.lww import ts_split
    from crdt_enc_tpu.ops.pallas_lww import lww_fold_pallas, lww_tile_cap

    dev = jax.devices()[0]
    NK = int(os.environ.get("MB_LWW_KEYS", 1_000_000))
    RA, V = 10_000, 1 << 15
    rng = np.random.default_rng(5)
    key = rng.integers(0, NK, N, dtype=np.int32)
    hi, lo = ts_split(rng.integers(0, 10 ** 12, N))
    actor = rng.integers(0, RA, N, dtype=np.int32)
    value = rng.integers(0, V, N, dtype=np.int32)
    cap = lww_tile_cap(key, NK)
    log(f"device: {dev.platform}; LWW N={N} K={NK} tile_cap={cap}")
    cols = [jax.device_put(x, dev) for x in (key, hi, lo, actor, value)]

    from crdt_enc_tpu.ops.pallas_lww import lww_limbs

    lb = lww_limbs(hi, lo, actor, V)
    log(f"static limbs: {lb}")

    def mk_fold(wm, limbs):
        def mk(n):
            @jax.jit
            def run():
                def body(carry, _):
                    k, h, l, a, v = cols
                    out = lww_fold_pallas(
                        k, h, l, a, v + (carry % 2), num_keys=NK,
                        num_values=V, tile_cap=cap, win_mode=wm,
                        limbs=limbs,
                    )
                    return out[3][0], ()
                o, _ = jax.lax.scan(body, jnp.int32(0), None, length=n)
                return o
            return run
        return mk

    # the 4-operand sort alone (the kernel's XLA prologue wall candidate)
    def mk_sort(n):
        @jax.jit
        def run():
            def body(carry, _):
                k, h, l, a, v = cols
                av = a * V + (v + carry % 2)
                sk, sh, sl, sav = jax.lax.sort((k, h, l, av), num_keys=4)
                return sav[0] % 2, ()
            o, _ = jax.lax.scan(body, jnp.int32(0), None, length=n)
            return o
        return run

    variants = [
        ("sort4 only", mk_sort),
        ("lww cond dyn-limb", mk_fold("cond", None)),
        ("lww select dyn-limb", mk_fold("select", None)),
        ("lww cond static-limb", mk_fold("cond", lb)),
        ("lww select static-limb", mk_fold("select", lb)),
    ]
    best = round_robin(variants, rounds_default=4)
    for name, _ in variants:
        t = best[name]
        log(f"BEST {name}: {t*1e3:.2f} ms  ({N/t/1e6:.0f}M rows/s)")


if __name__ == "__main__":
    which = set((os.environ.get("MB_WHICH") or "").split(","))
    if which & {"fused"}:
        fused_sections(which)
    elif which & {"lwwscan"}:
        lww_sections(which)
    elif which & {"sort1", "ablkpro", "ablkscan"}:
        ablk_sections(which)
    elif which & {"prologue", "pallasfold"}:
        pallas_sections(which)
    else:
        main()

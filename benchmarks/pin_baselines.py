"""Measure and commit the canonical pinned host baselines.

VERDICT r4 weak items 1/6: same-run host rates swing 1.5× with machine
weather, so published speedups need ONE committed idle-box denominator
per config.  This tool runs ONLY the host loops of the five suite
configs (exact same generators and subsamples — the ``host_only`` mode
of each ``bench_*``) under the median-of-N protocol and writes
``benchmarks/pinned_baselines.json`` with raw samples.

Run it on an otherwise-idle box:

    python benchmarks/pin_baselines.py [--runs 5]

Re-pin deliberately (a better box, a protocol change) — never as part
of a bench run; the whole point is that the denominator does not move
with the weather.  bench.py / suite.py pick the pin up automatically
when the workload shape matches (``bench.load_pinned``).

Spread gate (VERDICT item 4): a pin measured on a noisy box is a noisy
denominator forever, so a config whose ``host_spread_pct`` exceeds
:data:`SPREAD_LIMIT_PCT` is REFUSED (exit 1, nothing written for that
config).  ``--force`` overrides with a printed warning — for when the
spread is the box's honest steady state and you accept it knowingly.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: maximum tolerated host sample spread for a committed pin, in percent
#: — above this the box was not idle enough to be a denominator.
SPREAD_LIMIT_PCT = 30.0


def spread_gate(config_name: str, rec: dict, force: bool = False) -> bool:
    """Whether ``rec`` (one measured pin record) may be written.
    Refuses — with the reason printed — when ``host_spread_pct``
    exceeds :data:`SPREAD_LIMIT_PCT`; ``force`` overrides with a
    printed warning instead (the operator owns the judgment call)."""
    spread = rec.get("host_spread_pct")
    if spread is None or float(spread) <= SPREAD_LIMIT_PCT:
        return True
    if force:
        print(
            f"WARNING: pinning {config_name} with host_spread_pct "
            f"{float(spread):.1f} > {SPREAD_LIMIT_PCT:.0f} (--force): "
            "this denominator carries the noise of a busy box",
            file=sys.stderr,
        )
        return True
    print(
        f"REFUSING to pin {config_name}: host_spread_pct "
        f"{float(spread):.1f} > {SPREAD_LIMIT_PCT:.0f} — rerun on an "
        "idle box, or pass --force to accept the noisy denominator",
        file=sys.stderr,
    )
    return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--runs", type=int, default=0,
                    help="host runs per config (default BENCH_HOST_RUNS)")
    ap.add_argument("--config", type=int, default=0,
                    help="re-pin one config (1-6) only")
    ap.add_argument("--force", action="store_true",
                    help="write pins even past the spread gate (warns)")
    args = ap.parse_args()
    if args.runs:
        os.environ["BENCH_HOST_RUNS"] = str(args.runs)

    # host loops only — keep the TPU tunnel entirely out of this
    import jax

    jax.config.update("jax_platforms", "cpu")

    from bench import PINNED_PATH, e2e_daemon_host
    from benchmarks.suite import (
        bench_gcounter, bench_lwwmap, bench_orset, bench_pncounter,
        bench_streaming,
    )

    runners = {
        1: lambda: bench_gcounter(1_000, 4, 0, host_only=True),
        2: lambda: bench_pncounter(100_000, 1_000, 0, host_only=True),
        3: lambda: bench_orset(1_000_000, 10_000, 4096, n_host=100_000,
                               iters=0, host_only=True),
        4: lambda: bench_lwwmap(1_000_000, 1_000_000, 10_000,
                                n_host=50_000, iters=0, host_only=True),
        5: lambda: bench_streaming(200_000, 100_000, 1024, ops_per_file=48,
                                   n_host_files=300, iters=0,
                                   host_only=True),
        # the daemon family (ISSUE 12): sequential solo compacts over
        # the default --e2e-daemon fleet head shape — the denominator
        # the daemon's aggregate ops/s is ratioed against, so the
        # `trend --fail-on-regression` ratchet covers daemon
        # throughput/freshness from day one
        6: lambda: e2e_daemon_host(),
    }

    try:
        with open(PINNED_PATH) as f:
            pins = json.load(f)
    except (OSError, ValueError):
        pins = {}

    wanted = [args.config] if args.config else sorted(runners)
    ts = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds")
    refused = []
    for c in wanted:
        print(f"pinning config {c}…", file=sys.stderr, flush=True)
        r = runners[c]()
        rec = {
            "host_rate": round(r["host_rate"], 1),
            "n_ops": r["n_ops"],
            "shape": r["shape"],
            "median_s": round(r["median_s"], 4),
            "host_samples_s": r["host_samples_s"],
            "host_spread_pct": r["host_spread_pct"],
            "ts": ts,
        }
        if not spread_gate(r["config"], rec, force=args.force):
            refused.append(r["config"])
            continue
        pins[r["config"]] = rec
        print(json.dumps({r["config"]: rec}), flush=True)

    with open(PINNED_PATH, "w") as f:
        json.dump(pins, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {PINNED_PATH}", file=sys.stderr)
    if refused:
        raise SystemExit(1)


if __name__ == "__main__":
    main()

"""Worker process for tests/test_distributed_2proc.py.

Runs as ``python _dist_worker.py <rank> <port>``: joins a REAL 2-process
``jax.distributed`` cluster over a localhost coordinator (CPU backend,
2 virtual devices per process → a (dp=2 hosts, mp=2 chips) mesh), folds
a deterministically generated ORSet batch whose rows are split between
the processes, and checks the sharded result against the single-device
fold of the full batch.  Prints ``DIST_OK`` on success.

This is the first real execution of the ``process_count() > 1`` branches
of parallel/distributed.py (multihost batch assembly via
``make_array_from_process_local_data``, ragged-row allgather) — the
in-suite tests fake process boundaries inside one process.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    rank = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PJRT_LIBRARY_PATH", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from crdt_enc_tpu import ops as K
    from crdt_enc_tpu.parallel import distributed
    from crdt_enc_tpu.parallel import mesh as pmesh

    ok = distributed.initialize(f"localhost:{port}", 2, rank)
    assert ok, "distributed.initialize declined an explicit configuration"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()

    mesh = distributed.make_multihost_mesh()
    assert dict(mesh.shape) == {"dp": 2, "mp": 2}, mesh.shape

    # deterministic global batch, identical in both processes; an odd row
    # count split unevenly exercises the ragged-row allgather padding
    E, R, N = 16, 8, 101
    rng = np.random.default_rng(7)
    kind = (rng.random(N) < 0.25).astype(np.int8)
    member = rng.integers(0, E, N, dtype=np.int32)
    actor = rng.integers(0, R, N, dtype=np.int32)
    counter = np.zeros(N, np.int32)
    seen = np.zeros(R, np.int32)
    for i in range(N):  # coherent per-actor dots
        a = actor[i]
        if kind[i] == 0:
            seen[a] += 1
            counter[i] = seen[a]
        else:
            if seen[a] == 0:
                actor[i] = R  # padding row
            counter[i] = seen[a]

    cut = 55  # uneven halves
    lo, hi = (0, cut) if rank == 0 else (cut, N)
    batch = distributed.global_op_batch(
        mesh, kind[lo:hi], member[lo:hi], actor[lo:hi], counter[lo:hi],
        num_replicas=R,
    )
    n_global = batch[0].shape[0]
    assert n_global >= N, (n_global, N)  # padded to 2x max(half)

    c0 = np.zeros(R, np.int32)
    a0 = np.zeros((E, R), np.int32)
    r0 = np.zeros((E, R), np.int32)
    clock0, add0, rm0 = distributed.replicate(mesh, c0, a0, r0)
    clock, add, rm = pmesh.orset_fold_sharded(
        mesh, clock0, add0, rm0, *batch
    )

    # reference: single-device fold of the full batch (itself pinned
    # byte-identical to the host per-op loop by tests/test_ops_kernels.py)
    ref = K.orset_fold(
        c0, a0, r0, kind, member, actor, counter,
        num_members=E, num_replicas=R,
    )
    for got, want, name in zip((clock, add, rm), ref, ("clock", "add", "rm")):
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want), err_msg=name
        )

    print(f"DIST_OK rank={rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

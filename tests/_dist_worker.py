"""Worker process for tests/test_distributed_2proc.py.

Runs as ``python _dist_worker.py <rank> <port> [mode] [shared_dir]``:
joins a REAL 2-process ``jax.distributed`` cluster over a localhost
coordinator (CPU backend, 2 virtual devices per process → a (dp=2
hosts, mp=2 chips) mesh).  Modes:

- ``fold`` (default): folds a deterministically generated ORSet batch
  whose rows are split between the processes and checks the sharded
  result against the single-device fold of the full batch.
- ``lifecycle`` (round 5, VERDICT r4 item 6): the FULL ``Core`` product
  lifecycle under the multihost mesh — each rank writes through its own
  replica to a SHARED fs remote, both ranks then open fresh observer
  replicas whose accelerator carries the 2-process mesh (every ingest
  fold runs the sharded SPMD kernels in lockstep), verify cross-rank
  and host-replica byte equality, and run ``Core.compact`` on BOTH
  ranks concurrently against the shared remote — the first
  ``Core.compact`` ever executed with ``jax.process_count() > 1``,
  exercising the store-new-before-delete-old discipline under a real
  concurrent multihost GC race.

Prints ``DIST_OK`` on success.

This is the real execution of the ``process_count() > 1`` branches of
parallel/distributed.py (multihost batch assembly via
``make_array_from_process_local_data``, ragged-row allgather) — the
in-suite tests fake process boundaries inside one process.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    rank = int(sys.argv[1])
    port = sys.argv[2]
    mode = sys.argv[3] if len(sys.argv) > 3 else "fold"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PJRT_LIBRARY_PATH", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from crdt_enc_tpu import ops as K
    from crdt_enc_tpu.parallel import distributed
    from crdt_enc_tpu.parallel import mesh as pmesh

    ok = distributed.initialize(f"localhost:{port}", 2, rank)
    assert ok, "distributed.initialize declined an explicit configuration"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()

    mesh = distributed.make_multihost_mesh()
    assert dict(mesh.shape) == {"dp": 2, "mp": 2}, mesh.shape

    if mode == "lifecycle":
        return lifecycle(rank, mesh, sys.argv[4])

    # deterministic global batch, identical in both processes; an odd row
    # count split unevenly exercises the ragged-row allgather padding
    E, R, N = 16, 8, 101
    rng = np.random.default_rng(7)
    kind = (rng.random(N) < 0.25).astype(np.int8)
    member = rng.integers(0, E, N, dtype=np.int32)
    actor = rng.integers(0, R, N, dtype=np.int32)
    counter = np.zeros(N, np.int32)
    seen = np.zeros(R, np.int32)
    for i in range(N):  # coherent per-actor dots
        a = actor[i]
        if kind[i] == 0:
            seen[a] += 1
            counter[i] = seen[a]
        else:
            if seen[a] == 0:
                actor[i] = R  # padding row
            counter[i] = seen[a]

    cut = 55  # uneven halves
    lo, hi = (0, cut) if rank == 0 else (cut, N)
    batch = distributed.global_op_batch(
        mesh, kind[lo:hi], member[lo:hi], actor[lo:hi], counter[lo:hi],
        num_replicas=R,
    )
    n_global = batch[0].shape[0]
    assert n_global >= N, (n_global, N)  # padded to 2x max(half)

    c0 = np.zeros(R, np.int32)
    a0 = np.zeros((E, R), np.int32)
    r0 = np.zeros((E, R), np.int32)
    clock0, add0, rm0 = distributed.replicate(mesh, c0, a0, r0)
    clock, add, rm = pmesh.orset_fold_sharded(
        mesh, clock0, add0, rm0, *batch
    )

    # reference: single-device fold of the full batch (itself pinned
    # byte-identical to the host per-op loop by tests/test_ops_kernels.py)
    ref = K.orset_fold(
        c0, a0, r0, kind, member, actor, counter,
        num_members=E, num_replicas=R,
    )
    for got, want, name in zip((clock, add, rm), ref, ("clock", "add", "rm")):
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want), err_msg=name
        )

    print(f"DIST_OK rank={rank}", flush=True)
    return 0


def lifecycle(rank: int, mesh, shared: str) -> int:
    """Full Core lifecycle across 2 real processes on one shared remote.

    Phases (cross-process barriers via ``sync_global_devices``):
      1. each rank writes through its own replica (host accelerator —
         writer folds are per-op-sized and rank-local);
      2. each rank opens a FRESH observer replica with a mesh-carrying
         ``TpuAccelerator`` and ingests the whole remote — the fold runs
         ``_fold_orset_sharded`` over the 2-process mesh, so both ranks
         execute the collectives in lockstep on identical batches;
      3. byte equality: across ranks (via the shared dir) AND against a
         pure-host replica folding the same remote per-op;
      4. BOTH ranks compact concurrently (first multihost Core.compact;
         concurrent sealed-state publish + NotFound-tolerant GC on the
         same remote);
      5. a fresh host replica reads the compacted remote and must land
         byte-identical.  Ref scale-out contract: SURVEY §2.3.
    """
    import asyncio
    from pathlib import Path

    import jax
    from jax.experimental import multihost_utils

    from crdt_enc_tpu.backends import (
        FsStorage, PassphraseKeyCryptor, XChaChaCryptor,
    )
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.core.adapters import HostAccelerator
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.utils import codec
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    root = Path(shared)

    def barrier(name: str):
        print(f"rank{rank} @barrier {name}", file=sys.stderr, flush=True)
        multihost_utils.sync_global_devices(name)
        print(f"rank{rank} past {name}", file=sys.stderr, flush=True)

    async def open_replica(local: str, create: bool, accel):
        return await Core.open(OpenOptions(
            storage=FsStorage(str(root / local), str(root / "remote")),
            cryptor=XChaChaCryptor(),
            key_cryptor=PassphraseKeyCryptor("pw"),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=create,
            accelerator=accel,
        ))

    def canon(core) -> bytes:
        return core.with_state(lambda s: codec.pack(s.to_obj()))

    async def run():
        # phase 1: rank 0 creates the remote, rank 1 joins after
        # create=True initializes the LOCAL replica metadata — every
        # fresh local dir needs it; rank 0 goes first so the remote and
        # its initial sealing key exist before rank 1 joins and merges
        if rank == 1:
            barrier("created")
        w = await open_replica(f"w{rank}", True, HostAccelerator())
        if rank == 0:
            barrier("created")
        else:
            await w.read_remote()
        for i in range(30):
            item = f"r{rank}-item{i}".encode()
            await w.update(lambda s, item=item: s.add_ctx(w.actor_id, item))
        # remove a few own items (observed-remove with real context)
        for i in (3, 7):
            item = f"r{rank}-item{i}".encode()
            op = w.with_state(lambda s, item=item: s.rm_ctx(item))
            await w.update(lambda s, op=op: op)
        barrier("written")

        # phase 2: fresh observer under the multihost mesh — every
        # ingest fold is a lockstep SPMD program across both processes
        obs = await open_replica(
            f"obs{rank}", True, TpuAccelerator(mesh=mesh))
        await obs.read_remote()
        assert jax.process_count() == 2
        obs_bytes = canon(obs)
        n_members = obs.with_state(lambda s: len(list(s.members())))
        assert n_members == 2 * (30 - 2), n_members
        (root / f"state-obs{rank}").write_bytes(obs_bytes)
        barrier("observed")
        other = (root / f"state-obs{1 - rank}").read_bytes()
        assert other == obs_bytes, "mesh observers diverged across ranks"

        # phase 3: pure-host replica over the same remote (per-op fold)
        hostver = await open_replica(f"host{rank}", True, HostAccelerator())
        await hostver.read_remote()
        assert canon(hostver) == obs_bytes, "host replica != mesh fold"

        # phase 4: concurrent multihost compaction on the shared remote
        await obs.compact()
        barrier("compacted")

        # phase 5: fresh host replica sees only compacted state(s)
        ver = await open_replica(f"ver{rank}", True, HostAccelerator())
        await ver.read_remote()
        assert canon(ver) == obs_bytes, "post-compact state diverged"
        return ver.info()

    asyncio.run(run())
    print(f"DIST_OK rank={rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

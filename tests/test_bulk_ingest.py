"""The bulk ingestion front end (batched decrypt → native columnar decode →
jit fold) must be observationally identical to the per-file asyncio path."""

import asyncio
import secrets
import uuid

import numpy as np
import pytest

import crdt_enc_tpu.core.core as core_mod
from crdt_enc_tpu.backends import (
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.backends.xchacha import (
    XChaChaCryptor,
    decrypt_blobs,
    decrypt_blob,
    encrypt_blob,
    AeadError,
)
from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
from crdt_enc_tpu.core.adapters import (
    HostAccelerator,
    gcounter_adapter,
    mvreg_adapter,
    pncounter_adapter,
)
from crdt_enc_tpu.models import ORSet, canonical_bytes
from crdt_enc_tpu.parallel.accel import TpuAccelerator
from crdt_enc_tpu.utils import codec
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, adapter, accel=None, cryptor=None):
    return OpenOptions(
        storage=storage,
        cryptor=cryptor or XChaChaCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter,
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
        accelerator=accel or HostAccelerator(),
    )


async def _write_history(core, n_files=40):
    """Many small op files: adds and removes across members."""
    for i in range(n_files):
        if i % 5 == 4:
            op = core.with_state(lambda s: s.rm_ctx(i % 7))
            if op.ctx.is_empty():
                continue
            await core.apply_ops([op])
        else:
            await core.apply_ops(
                [core.with_state(lambda s: s.add_ctx(core.actor_id, i % 7))]
            )


@pytest.mark.parametrize("reader_accel", ["host", "tpu"])
def test_bulk_ingest_matches_per_file(reader_accel, monkeypatch):
    async def go():
        remote = MemoryRemote()
        writer = await Core.open(
            make_opts(MemoryStorage(remote), orset_adapter())
        )
        await _write_history(writer)

        accel = TpuAccelerator(min_device_batch=1) if reader_accel == "tpu" else HostAccelerator()
        bulk_reader = await Core.open(
            make_opts(MemoryStorage(remote), orset_adapter(), accel=accel)
        )
        assert core_mod.BULK_MIN_FILES <= 16  # history must trip the bulk path
        await bulk_reader.read_remote()

        # per-file reference reader: bulk path disabled
        monkeypatch.setattr(core_mod, "BULK_MIN_FILES", 10**9)
        ref_reader = await Core.open(
            make_opts(MemoryStorage(remote), orset_adapter())
        )
        await ref_reader.read_remote()

        assert bulk_reader.with_state(canonical_bytes) == ref_reader.with_state(canonical_bytes)
        assert (
            bulk_reader.info().next_op_versions.to_obj()
            == ref_reader.info().next_op_versions.to_obj()
        )

    run(go())


def test_bulk_ingest_non_columnar_adapter_falls_back(monkeypatch):
    """A CRDT the accelerator can't columnar-decode still ingests correctly
    through the bulk path's Python fallback."""

    async def go():
        remote = MemoryRemote()
        writer = await Core.open(
            make_opts(MemoryStorage(remote), mvreg_adapter())
        )
        for i in range(20):
            await writer.update(
                lambda s: s.write_ctx(writer.actor_id, i)
            )
        reader = await Core.open(
            make_opts(
                MemoryStorage(remote),
                mvreg_adapter(),
                accel=TpuAccelerator(min_device_batch=1),
            )
        )
        await reader.read_remote()
        assert reader.with_state(lambda s: s.read().values) == [19]

    run(go())


@pytest.mark.parametrize("kind", ["gcounter", "pncounter"])
def test_bulk_ingest_counters_match_per_file(kind, monkeypatch):
    """The native counter bulk path must equal the per-file reference."""

    async def go():
        adapter = gcounter_adapter if kind == "gcounter" else pncounter_adapter
        remote = MemoryRemote()
        writer = await Core.open(make_opts(MemoryStorage(remote), adapter()))
        for i in range(30):
            if kind == "pncounter" and i % 3 == 2:
                await writer.apply_ops(
                    [writer.with_state(lambda s: s.dec(writer.actor_id, i % 4 + 1))]
                )
            else:
                await writer.apply_ops(
                    [writer.with_state(lambda s: s.inc(writer.actor_id, i % 5 + 1))]
                )

        bulk = await Core.open(
            make_opts(
                MemoryStorage(remote),
                adapter(),
                accel=TpuAccelerator(min_device_batch=1),
            )
        )
        await bulk.read_remote()

        monkeypatch.setattr(core_mod, "BULK_MIN_FILES", 10**9)
        ref = await Core.open(make_opts(MemoryStorage(remote), adapter()))
        await ref.read_remote()

        assert bulk.with_state(lambda s: s.read()) == ref.with_state(
            lambda s: s.read()
        )
        assert bulk.with_state(canonical_bytes) == ref.with_state(canonical_bytes)

    run(go())


def test_decode_orset_payload_batch_matches_python():
    from crdt_enc_tpu import ops as K
    from crdt_enc_tpu.ops.native_decode import decode_orset_payload_batch

    actors = sorted(uuid.UUID(int=i + 1).bytes for i in range(5))
    state = ORSet()
    payloads = []
    all_ops = []
    for f in range(30):
        ops = []
        for i in range(7):
            a = actors[(f + i) % 5]
            if (f + i) % 6 == 5:
                op = state.rm_ctx((f * 7 + i) % 11)
                if op.ctx.is_empty():
                    continue
            else:
                op = state.add_ctx(a, (f * 7 + i) % 11)
            state.apply(op)
            ops.append(op)
        payloads.append(codec.pack([op.to_obj() for op in ops]))
        all_ops.extend(ops)

    decoded = decode_orset_payload_batch(payloads, actors)
    assert decoded is not None
    kind, member_idx, actor_idx, counter, members = decoded

    ref = K.orset_ops_to_columns(all_ops)
    assert len(kind) == len(ref.kind)
    np.testing.assert_array_equal(kind, ref.kind)
    np.testing.assert_array_equal(counter, ref.counter)
    # member/actor indices use different intern orders; compare resolved
    for i in range(len(kind)):
        assert members[member_idx[i]] == ref.members.items[ref.member[i]]
        assert actors[actor_idx[i]] == ref.replicas.items[ref.actor[i]]


def test_fold_payloads_bails_on_member_value_collision():
    """Distinct canonical encodings that collide as Python values (1 == True)
    would collapse the member vocab and scatter rows out of range; the
    accelerator must decline so the per-op host path (whose dict semantics
    define the contract) handles the batch."""
    from crdt_enc_tpu.models.vclock import Dot

    actor = uuid.UUID(int=1).bytes
    ops = [
        [0, 1, Dot(actor, 1).to_obj()],
        [0, True, Dot(actor, 2).to_obj()],
        [0, b"x", Dot(actor, 3).to_obj()],
    ]
    payload = codec.pack(ops)
    accel = TpuAccelerator(min_device_batch=1)
    state = ORSet()
    assert accel.fold_payloads(state, [payload], actors_hint=[actor]) is False
    assert canonical_bytes(state) == canonical_bytes(ORSet())  # untouched


def test_decode_unknown_actor_returns_none():
    from crdt_enc_tpu.ops.native_decode import decode_orset_payload_batch

    known = [uuid.UUID(int=1).bytes]
    stranger = uuid.UUID(int=99).bytes
    state = ORSet()
    op = state.add_ctx(stranger, "m")
    payload = codec.pack([op.to_obj()])
    assert decode_orset_payload_batch([payload], known) is None


def test_decrypt_blobs_matches_sequential_and_detects_tamper():
    key = secrets.token_bytes(32)
    blobs = [encrypt_blob(key, f"payload-{i}".encode() * (i % 9 + 1)) for i in range(64)]
    assert decrypt_blobs(key, blobs) == [decrypt_blob(key, b) for b in blobs]
    bad = bytearray(blobs[7])
    bad[-1] ^= 1
    with pytest.raises(AeadError):
        decrypt_blobs(key, blobs[:7] + [bytes(bad)] + blobs[8:])


def test_fold_payload_stream_matches_batch_and_host():
    """The chunked streaming front end (decrypt lookahead → per-chunk span
    decode → one combined fold) must equal both the one-shot bulk path and
    the per-op host fold, at every chunking."""
    from crdt_enc_tpu.backends.xchacha import decrypt_blobs_chunked
    from crdt_enc_tpu.models.orset import op_from_obj

    key = secrets.token_bytes(32)
    actors = sorted(uuid.UUID(int=i + 1).bytes for i in range(5))
    state = ORSet()
    payloads, all_ops = [], []
    for f in range(30):
        ops = []
        for i in range(7):
            a = actors[(f + i) % 5]
            if (f + i) % 6 == 5:
                op = state.rm_ctx((f * 7 + i) % 11)
                if op.ctx.is_empty():
                    continue
            else:
                op = state.add_ctx(a, (f * 7 + i) % 11)
            state.apply(op)
            ops.append(op)
        payloads.append(encrypt_blob(key, codec.pack([op.to_obj() for op in ops])))
        all_ops.extend(ops)

    host = ORSet()
    for op in all_ops:
        host.apply(op)

    accel = TpuAccelerator(min_device_batch=1)
    batch = ORSet()
    assert accel.fold_payloads(batch, decrypt_blobs(key, payloads), actors_hint=actors)
    assert canonical_bytes(batch) == canonical_bytes(host)

    for kwargs in ({"n_chunks": 4}, {"n_chunks": 64}, {"chunk_blobs": 1}):
        streamed = ORSet()
        chunks = decrypt_blobs_chunked(key, payloads, **kwargs)
        assert accel.fold_payload_stream(streamed, chunks, actors_hint=actors)
        assert canonical_bytes(streamed) == canonical_bytes(host), kwargs

    # empty stream is a no-op success
    untouched = ORSet()
    assert accel.fold_payload_stream(untouched, iter([]), actors_hint=actors)
    assert canonical_bytes(untouched) == canonical_bytes(ORSet())


def test_fold_payload_stream_declines_unknown_actor_mid_stream():
    """A chunk the native decoder can't handle declines the whole stream,
    leaving the state untouched for the caller's per-op replay."""
    from crdt_enc_tpu.backends.xchacha import decrypt_blobs_chunked

    key = secrets.token_bytes(32)
    known = uuid.UUID(int=1).bytes
    stranger = uuid.UUID(int=99).bytes
    s = ORSet()
    ok_op = s.add_ctx(known, "m")
    s.apply(ok_op)
    bad_op = s.add_ctx(stranger, "n")
    payloads = [
        encrypt_blob(key, codec.pack([ok_op.to_obj()])),
        encrypt_blob(key, codec.pack([bad_op.to_obj()])),
    ]
    accel = TpuAccelerator(min_device_batch=1)
    state = ORSet()
    chunks = decrypt_blobs_chunked(key, payloads, chunk_blobs=1)
    assert accel.fold_payload_stream(state, chunks, actors_hint=[known]) is False
    assert canonical_bytes(state) == canonical_bytes(ORSet())


def test_bulk_gap_leaves_cursors_consistent(monkeypatch):
    """An op file arriving beyond the expected version (a GC'd hole with
    stranded files) must raise OpOrderError WITHOUT advancing cursors past
    ops that never folded — after the remote is repaired, a re-read must
    recover everything.  Regression: the bulk path used to advance cursors
    during validation and fold only afterwards, so a mid-batch gap
    stranded the validated prefix behind advanced cursors forever."""
    from crdt_enc_tpu.core.core import OpOrderError

    class GappedStorage(MemoryStorage):
        gap_on = True

        async def load_ops(self, afv):
            out = await super().load_ops(afv)
            if not self.gap_on:
                return out
            # forge a hole: drop one mid-batch file, keep the rest stranded
            return [f for i, f in enumerate(out) if i != 20]

    async def go():
        remote = MemoryRemote()
        writer = await Core.open(make_opts(MemoryStorage(remote), orset_adapter()))
        await _write_history(writer, n_files=40)

        st = GappedStorage(remote)
        reader = await Core.open(make_opts(st, orset_adapter()))
        with pytest.raises(OpOrderError):
            await reader.read_remote()

        st.gap_on = False  # the missing file "syncs in"
        await reader.read_remote()

        ref = await Core.open(make_opts(MemoryStorage(remote), orset_adapter()))
        await ref.read_remote()
        assert reader.with_state(canonical_bytes) == ref.with_state(canonical_bytes)
        assert (
            reader.info().next_op_versions.to_obj()
            == ref.info().next_op_versions.to_obj()
        )

    run(go())


def test_bulk_stream_path_matches_per_file(monkeypatch):
    """The chunked-decrypt streaming bulk ingest (single sealing key +
    open_payload_stream, multiple lookahead chunks) must equal the
    per-file reference reader."""
    import crdt_enc_tpu.core.core as core_mod_

    class NoSessionTpu(TpuAccelerator):
        """Force the legacy bulk path (no fold session) while keeping the
        payload-stream front end."""

        def open_fold_session(self, state, actors_hint=()):
            return None

    async def go():
        remote = MemoryRemote()
        writer = await Core.open(make_opts(MemoryStorage(remote), orset_adapter()))
        await _write_history(writer, n_files=40)

        monkeypatch.setattr(core_mod_, "BULK_STREAM_CHUNK", 7)  # many chunks
        reader = await Core.open(
            make_opts(
                MemoryStorage(remote), orset_adapter(),
                accel=NoSessionTpu(min_device_batch=1),
            )
        )
        await reader.read_remote()

        monkeypatch.setattr(core_mod_, "BULK_MIN_FILES", 10**9)
        ref = await Core.open(make_opts(MemoryStorage(remote), orset_adapter()))
        await ref.read_remote()

        assert reader.with_state(canonical_bytes) == ref.with_state(canonical_bytes)
        assert (
            reader.info().next_op_versions.to_obj()
            == ref.info().next_op_versions.to_obj()
        )

    run(go())


# ---- ISSUE 13 acceptance: streaming ≡ sequential scalar, adapters × backends


@pytest.mark.parametrize("backend", ["memory", "fs"])
@pytest.mark.parametrize("kind", ["orset", "gcounter", "pncounter"])
def test_streaming_ingest_matches_scalar_adapters_backends(
    kind, backend, tmp_path, monkeypatch
):
    """The striped streaming front end (pipelined fold sessions, unified
    work queue, bytes-keyed remap, split sparse fold) must produce
    byte-identical state AND cursors to the sequential per-file scalar
    path, for ≥3 adapters on BOTH storage backends."""
    from crdt_enc_tpu.backends import FsStorage

    adapters = {
        "orset": orset_adapter,
        "gcounter": gcounter_adapter,
        "pncounter": pncounter_adapter,
    }
    mk_adapter = adapters[kind]

    if backend == "memory":
        remote = MemoryRemote()

        def make(name):
            return MemoryStorage(remote)
    else:
        remote_dir = tmp_path / "remote"

        def make(name):
            return FsStorage(str(tmp_path / f"local-{name}"), str(remote_dir))

    def build(core, i):
        if kind == "orset":
            if i % 5 == 4:
                op = core.with_state(lambda s: s.rm_ctx(i % 7))
                if op.ctx.is_empty():
                    return None
                return op
            return core.with_state(
                lambda s: s.add_ctx(core.actor_id, i % 7)
            )
        if kind == "pncounter" and i % 3 == 2:
            return core.with_state(lambda s: s.dec(core.actor_id))
        return core.with_state(lambda s: s.inc(core.actor_id, 1 + i % 3))

    async def go():
        writer = await Core.open(make_opts(make("w"), mk_adapter()))
        for i in range(core_mod.BULK_MIN_FILES + 20):
            op = build(writer, i)
            if op is not None:
                await writer.apply_ops([op])

        streaming = await Core.open(make_opts(
            make("s"), mk_adapter(),
            accel=TpuAccelerator(min_device_batch=1),
        ))
        await streaming.read_remote()

        monkeypatch.setattr(core_mod, "BULK_MIN_FILES", 10**9)
        scalar = await Core.open(make_opts(make("r"), mk_adapter()))
        await scalar.read_remote()

        assert streaming.with_state(canonical_bytes) == scalar.with_state(
            canonical_bytes
        )
        assert (
            streaming.info().next_op_versions.to_obj()
            == scalar.info().next_op_versions.to_obj()
        )

    run(go())

"""Multi-tenant fold service (ISSUE 7): byte-identity, bucketing, probes.

The serving contract under test: batching N tenants into shared device
dispatches must be an *invisible* optimization — every tenant's folded
state and sealed snapshot is byte-identical to what its own solo
``Core.compact()`` would have produced (the degenerate 1-tenant case is
the refactor's safety net), the compiled-shape set is bounded by size
classes (shuffled tenant mixes of one class set cannot recompile), and
the batch never pays the PR-6 per-tenant replication probe N times per
dispatch.
"""

import asyncio
import copy
import random

import numpy as np
import pytest

from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import (
    Core,
    OpenOptions,
    gcounter_adapter,
    gset_adapter,
    orset_adapter,
)
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.obs import runtime as obs_runtime
from crdt_enc_tpu.parallel import TpuAccelerator
from crdt_enc_tpu.serve import (
    FoldService,
    PlaneWarmTier,
    ServeConfig,
    TenantShape,
    plan_buckets,
)
from crdt_enc_tpu.utils import trace
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, adapter=None, create=True, **kw):
    kw.setdefault("accelerator", TpuAccelerator(min_device_batch=1))
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter if adapter is not None else orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
        **kw,
    )


@pytest.fixture(params=["memory", "fs"])
def remote_duo(request, tmp_path):
    """Two byte-identical but independent remotes: ``writer()`` is the
    storage the fixture's writer populates, ``split()`` freezes the
    remote into two copies and hands out ``(solo, served, cold)``
    storages — solo on copy A, served + cold on copy B."""
    if request.param == "memory":
        remote_a = MemoryRemote()

        class Duo:
            def writer(self):
                return MemoryStorage(remote_a)

            def split(self):
                remote_b = copy.deepcopy(remote_a)
                return (
                    MemoryStorage(remote_a),
                    MemoryStorage(remote_b),
                    MemoryStorage(remote_b),
                )

        return Duo()

    class Duo:
        def writer(self):
            return FsStorage(str(tmp_path / "local-w"), str(tmp_path / "rA"))

        def split(self):
            import shutil

            shutil.copytree(str(tmp_path / "rA"), str(tmp_path / "rB"))
            return (
                FsStorage(str(tmp_path / "local-s"), str(tmp_path / "rA")),
                FsStorage(str(tmp_path / "local-v"), str(tmp_path / "rB")),
                FsStorage(str(tmp_path / "local-c"), str(tmp_path / "rB")),
            )

    return Duo()


async def write_orset(storage, n_ops, tag, rm_every=7):
    """Populate a tenant remote with adds + causal removes."""
    core = await Core.open(make_opts(storage))
    for i in range(n_ops):
        m = b"%s-%d" % (tag, i % 31)
        await core.apply_ops(
            [core.with_state(lambda s, m=m: s.add_ctx(core.actor_id, m))]
        )
        if rm_every and i % rm_every == rm_every - 1:
            victim = b"%s-%d" % (tag, (i * 3) % 31)

            def rm(s, victim=victim):
                return s.rm_ctx(victim) if victim in s.entries else None

            op = core.with_state(rm)
            if op is not None:
                await core.apply_ops([op])
    return core


async def write_gcounter(storage, n_ops):
    core = await Core.open(make_opts(storage, gcounter_adapter()))
    for _ in range(n_ops):
        await core.apply_ops(
            [core.with_state(lambda s: s.inc(core.actor_id))]
        )
    return core


# ------------------------------------------------------- bucket planning


def test_plan_buckets_quantizes_and_groups():
    shapes = [
        TenantShape(0, "orset", 100, 20, 5),
        TenantShape(1, "orset", 90, 17, 7),  # same classes as tenant 0
        TenantShape(2, "orset", 1000, 20, 5),  # different row class
        TenantShape(3, "gcounter", 100, 0, 5),  # different kind
        TenantShape(4, "orset", 0, 0, 0),  # empty: not planned at all
    ]
    buckets, solo = plan_buckets(shapes)
    assert solo == []
    keyed = {
        (b.kind, b.rows, b.members, b.replicas): b.tenants for b in buckets
    }
    assert keyed[("orset", 128, 32, 8)] == [0, 1]
    assert keyed[("orset", 1024, 32, 8)] == [2]
    assert keyed[("gcounter", 128, 0, 8)] == [3]
    assert all(4 not in b.tenants for b in buckets)
    # slots quantize with floor 1: two tenants need exactly 2 lanes
    assert {b.slots for b in buckets} == {2, 1}


def test_plan_buckets_shuffle_invariant_shapes():
    """Shuffled mixes of one size-class set plan the same compiled-shape
    set — the pure half of the bounded-jax_compiles acceptance."""
    rng = random.Random(3)
    base = [
        TenantShape(i, "orset", 50 + (i % 3), 10, 4) for i in range(20)
    ] + [TenantShape(100 + i, "orset", 700, 40, 12) for i in range(5)]
    shapes_a = list(base)
    shapes_b = list(base)
    rng.shuffle(shapes_b)
    shape_set = lambda bs: sorted(
        (b.kind, b.rows, b.members, b.replicas, b.slots) for b in bs
    )
    a, _ = plan_buckets(shapes_a)
    b, _ = plan_buckets(shapes_b)
    assert shape_set(a) == shape_set(b)


def test_plan_buckets_spills_and_splits():
    shapes = [
        TenantShape(0, "orset", 10_000, 10, 4),  # rows past cap → solo
        TenantShape(1, "orset", 100, 3000, 600),  # cells past cap → solo
        TenantShape(2, "orset", 100, 10, 4),
        TenantShape(3, "orset", 100, 10, 4),
        TenantShape(4, "orset", 100, 10, 4),
    ]
    buckets, solo = plan_buckets(
        shapes, rows_cap=1024, cells_cap=1 << 20, tenants_cap=2
    )
    assert solo == [0, 1]
    # the 3-tenant group splits at tenants_cap=2 into 2+1, same class
    assert [b.tenants for b in buckets] == [[2, 3], [4]]
    assert [(b.rows, b.members, b.replicas) for b in buckets] == [
        (128, 16, 8), (128, 16, 8),
    ]
    with pytest.raises(ValueError):
        plan_buckets(shapes, rows_cap=0)


# ------------------------------------------------- differential: 1 tenant


def test_single_tenant_service_equals_solo_compact(remote_duo):
    """Satellite 1: the degenerate 1-tenant FoldService dispatch is
    byte-identical to the existing solo ``Core.compact`` path — state,
    sealed snapshot (as read by a cold replica), and op GC — across the
    memory and fs backends.  Solo and served run over byte-identical
    copies of one remote, so the comparison is apples to apples."""

    async def scenario():
        await write_orset(remote_duo.writer(), 60, b"solo")
        solo_s, served_s, cold_s = remote_duo.split()
        solo = await Core.open(make_opts(solo_s))
        served = await Core.open(make_opts(served_s))
        await solo.compact()
        service = FoldService([served])
        (res,) = await service.run_cycle()
        assert res.error is None and res.path == "batched" and res.sealed
        assert solo.with_state(canonical_bytes) == served.with_state(
            canonical_bytes
        )
        # the service-sealed snapshot reads back into the same state on
        # a cold replica, and the covered op files are GC'd
        cold = await Core.open(make_opts(cold_s))
        await cold.read_remote()
        assert cold.with_state(canonical_bytes) == solo.with_state(
            canonical_bytes
        )
        stats = await served.storage.stat_ops(
            [(a, 1) for a in await served.storage.list_op_actors()]
        )
        assert stats == []  # every covered op file removed

    run(scenario())


def test_multitenant_mixed_fleet_differential():
    """Mixed fleet: ragged ORSets, a G-Counter, a solo-type (G-Set) and
    an empty tenant — every tenant's serviced state is byte-identical
    to its solo compact, whatever path it took."""

    async def scenario():
        remotes, adapters, n_ops = [], [], [0, 23, 57, 110, 40, 40]
        for t, n in enumerate(n_ops):
            remote = MemoryRemote()
            remotes.append(remote)
            if t == 4:
                adapters.append(gcounter_adapter)
                await write_gcounter(MemoryStorage(remote), n)
            elif t == 5:
                adapters.append(gset_adapter)
                core = await Core.open(
                    make_opts(MemoryStorage(remote), gset_adapter())
                )
                for i in range(n):
                    await core.apply_ops([b"m%d" % (i % 13)])
            else:
                adapters.append(orset_adapter)
                if n:
                    await write_orset(MemoryStorage(remote), n, b"t%d" % t)

        twins = [copy.deepcopy(r) for r in remotes]
        solo_cores = []
        for ad, r in zip(adapters, twins):
            c = await Core.open(make_opts(MemoryStorage(r), ad()))
            await c.compact()
            solo_cores.append(c)

        served = [
            await Core.open(make_opts(MemoryStorage(r), ad()))
            for ad, r in zip(adapters, remotes)
        ]
        results = await FoldService(served).run_cycle()
        paths = [r.path for r in results]
        assert paths[0] == "empty"
        assert paths[1] == paths[2] == paths[3] == "batched"
        assert paths[4] == "batched"  # gcounter rides its own bucket
        assert paths[5] == "solo"  # gset: accel bulk path, not batched
        for i, (a, b) in enumerate(zip(solo_cores, served)):
            assert a.with_state(canonical_bytes) == b.with_state(
                canonical_bytes
            ), f"tenant {i} diverged ({paths[i]})"
        assert all(r.sealed for r in results)

    run(scenario())


# --------------------------------------------------- ragged edge cases


def test_empty_tenant_seal_parity_and_opt_out():
    async def scenario():
        remote = MemoryRemote()
        served = await Core.open(make_opts(MemoryStorage(remote)))
        (res,) = await FoldService([served]).run_cycle()
        assert res.path == "empty" and res.sealed
        assert len(remote.states) == 1  # solo-compact parity: seals

        remote2 = MemoryRemote()
        served2 = await Core.open(make_opts(MemoryStorage(remote2)))
        (res2,) = await FoldService(
            [served2], ServeConfig(seal_empty=False)
        ).run_cycle()
        assert res2.path == "empty" and not res2.sealed
        assert len(remote2.states) == 0  # quiet tenant costs nothing

    run(scenario())


def test_oversize_tenant_spills_to_solo_path():
    """A tenant past the bucket row cap leaves the mega-fold (solo
    accelerator path) and still lands byte-identical."""

    async def scenario():
        remotes = [MemoryRemote(), MemoryRemote()]
        await write_orset(MemoryStorage(remotes[0]), 120, b"big")
        await write_orset(MemoryStorage(remotes[1]), 30, b"small")
        twins = [copy.deepcopy(r) for r in remotes]
        solo_cores = []
        for r in twins:
            c = await Core.open(make_opts(MemoryStorage(r)))
            await c.compact()
            solo_cores.append(c)
        served = [
            await Core.open(make_opts(MemoryStorage(r))) for r in remotes
        ]
        trace.reset()
        results = await FoldService(
            served, ServeConfig(rows_cap=64)
        ).run_cycle()
        assert results[0].path == "solo"
        assert results[1].path == "batched"
        assert trace.snapshot()["counters"]["serve_solo_spills"] == 1
        for a, b in zip(solo_cores, served):
            assert a.with_state(canonical_bytes) == b.with_state(
                canonical_bytes
            )

    run(scenario())


def test_zero_row_op_files_still_advance_cursors():
    """Validated op files that decode to ZERO columnar rows (an
    empty-ctx remove) must still advance cursors and GC exactly as the
    solo path — or the sealed snapshot carries a stale cursor and the
    files are re-read every cycle forever."""

    async def scenario():
        from crdt_enc_tpu.models.orset import RmOp
        from crdt_enc_tpu.models.vclock import VClock

        remote = MemoryRemote()
        w = await Core.open(make_opts(MemoryStorage(remote)))
        await w.apply_ops([RmOp(b"ghost", VClock())])  # 0-row op file
        twin = copy.deepcopy(remote)
        solo = await Core.open(make_opts(MemoryStorage(twin)))
        await solo.compact()
        served = await Core.open(make_opts(MemoryStorage(remote)))
        service = FoldService([served])
        (res,) = await service.run_cycle()
        assert res.error is None and res.sealed and res.path == "batched"
        assert (
            served._data.next_op_versions.counters
            == solo._data.next_op_versions.counters
        )
        assert await served.storage.list_op_actors() == []  # GC'd
        assert solo.with_state(canonical_bytes) == served.with_state(
            canonical_bytes
        )
        (res2,) = await service.run_cycle()  # nothing left to re-read
        assert res2.path == "empty"

    run(scenario())


def test_all_tenants_land_in_one_bucket():
    async def scenario():
        remotes = [MemoryRemote() for _ in range(5)]
        for t, r in enumerate(remotes):
            await write_orset(MemoryStorage(r), 40, b"same%d" % t)
        served = [
            await Core.open(make_opts(MemoryStorage(r))) for r in remotes
        ]
        trace.reset()
        results = await FoldService(served).run_cycle()
        snap = trace.snapshot()
        assert snap["gauges"]["serve_buckets"] == 1
        assert all(r.path == "batched" for r in results)
        assert snap["counters"]["serve_rows_folded"] == sum(
            r.rows for r in results
        )

    run(scenario())


def test_bounded_compiles_across_shuffled_tenant_mixes():
    """Acceptance: ``jax_compiles`` is constant after warmup across two
    different shuffled tenant mixes of the same size classes — bucket
    quantization as a machine-checked property, not a hope."""

    async def build_fleet(sizes, tag):
        served = []
        for t, n in enumerate(sizes):
            remote = MemoryRemote()
            await write_orset(
                MemoryStorage(remote), n, b"%s%d" % (tag, t), rm_every=5
            )
            served.append(await Core.open(make_opts(MemoryStorage(remote))))
        return served

    async def scenario():
        obs_runtime.track_recompiles()
        sizes = [20, 25, 30, 90, 100, 40]
        fleet_a = await build_fleet(sizes, b"a")
        await FoldService(fleet_a).run_cycle()  # warmup compiles
        baseline = obs_runtime.recompile_count()
        shuffled = list(sizes)
        random.Random(11).shuffle(shuffled)
        fleet_b = await build_fleet(shuffled, b"b")
        await FoldService(fleet_b).run_cycle()
        assert obs_runtime.recompile_count() == baseline, (
            "a shuffled tenant mix of the same size classes recompiled "
            "the mega-fold"
        )

    run(scenario())


# ----------------------------------------------------- replication probes


class _ProbeCountingStorage(MemoryStorage):
    def __init__(self, remote):
        super().__init__(remote)
        self.stat_calls = 0
        self.list_calls = 0

    def reset_counts(self):
        self.stat_calls = 0
        self.list_calls = 0

    async def stat_ops(self, actor_first_versions):
        self.stat_calls += 1
        return await super().stat_ops(actor_first_versions)

    async def list_op_actors(self):
        self.list_calls += 1
        return await super().list_op_actors()


def test_service_cycle_pays_zero_replication_probes():
    """Satellite 3: the batch seal samples replication once per tenant
    per cycle REUSING the ingest's own listing (``_backlog=[]``, the
    read_remote contract) — per tenant the cycle pays exactly ONE
    ``list_op_actors`` (its own ingest) and ZERO ``stat_ops``, where a
    solo compact pays a second listing for its post-GC status probe.
    Every tenant still publishes a sample."""

    async def scenario():
        n = 4
        storages = []
        served = []
        for t in range(n):
            remote = MemoryRemote()
            await write_orset(MemoryStorage(remote), 25, b"p%d" % t)
            st = _ProbeCountingStorage(remote)
            storages.append(st)
            served.append(await Core.open(make_opts(st)))
        for st in storages:
            st.reset_counts()  # open() legitimately probes once
        trace.reset()
        results = await FoldService(served).run_cycle()
        assert all(r.sealed for r in results)
        assert [st.stat_calls for st in storages] == [0] * n
        assert [st.list_calls for st in storages] == [1] * n
        assert trace.snapshot()["counters"]["repl_samples"] == n
        # ...and the sampled status is the post-compaction fixed point
        for c in served:
            assert c.last_replication_status["backlog"]["files"] == 0

        # the solo path on the same remotes pays a SECOND listing per
        # tenant for its status sample — the probe cost the service
        # amortizes away (regression anchor: if the solo path stops
        # probing, rethink this test, not the service)
        for st in storages:
            st.reset_counts()
        for c in served:
            await c.compact()
        assert [st.list_calls for st in storages] == [2] * n

    run(scenario())


# ------------------------------------------------------------- warm tier


def test_warm_tier_unit_lru_budget_and_invalidation():
    class S:  # minimal state stand-in with a mutation epoch
        _mut = 0

    tier = PlaneWarmTier(byte_budget=100)
    states = [S(), S(), S()]
    planes = lambda n: (np.zeros(n, np.int32),)  # n*4 bytes
    trace.reset()
    tier.store(states[0], None, None, planes(10))  # 40 bytes
    tier.store(states[1], None, None, planes(10))  # 80 bytes
    assert tier.lookup(states[0]) is not None  # refreshes LRU: 1 is oldest
    tier.store(states[2], None, None, planes(10))  # 120 → evict state 1
    assert len(tier) == 2 and tier.bytes_held == 80
    assert tier.lookup(states[1]) is None
    snap = trace.snapshot()["counters"]
    assert snap["serve_warm_evictions"] == 1
    # mutation-epoch invalidation
    assert tier.lookup(states[2]) is not None
    states[2]._mut = 99
    assert tier.lookup(states[2]) is None
    assert len(tier) == 1
    with pytest.raises(ValueError):
        PlaneWarmTier(byte_budget=0)


def test_warm_tier_reuse_across_cycles_byte_identical():
    """Cycle 2 on un-mutated tenants hits the warm tier (no state
    re-scan) and still folds byte-identically vs a cold reader; a local
    apply between cycles invalidates that tenant's entry."""

    async def scenario():
        remotes = [MemoryRemote() for _ in range(3)]
        for t, r in enumerate(remotes):
            await write_orset(MemoryStorage(r), 35, b"w%d" % t)
        served = [
            await Core.open(make_opts(MemoryStorage(r))) for r in remotes
        ]
        service = FoldService(served)
        await service.run_cycle()
        assert len(service.warm) == 3
        for t, r in enumerate(remotes):  # second round of remote writes
            await write_orset(MemoryStorage(r), 12, b"x%d" % t, rm_every=0)
        # tenant 0 also applies locally → its warm entry must invalidate
        await served[0].apply_ops(
            [served[0].with_state(
                lambda s: s.add_ctx(served[0].actor_id, b"local")
            )]
        )
        trace.reset()
        results = await service.run_cycle()
        snap = trace.snapshot()["counters"]
        assert snap["serve_warm_hits"] == 2
        assert snap["serve_warm_misses"] == 1
        assert all(r.path == "batched" for r in results)
        for c, r in zip(served, remotes):
            cold = await Core.open(make_opts(MemoryStorage(r)))
            await cold.read_remote()
            assert c.with_state(canonical_bytes) == cold.with_state(
                canonical_bytes
            )

    run(scenario())


# --------------------------------------------- planes-packed checkpoints


def test_pack_checkpoint_planes_roundtrip_equals_sparse_pack():
    """The service's vectorized checkpoint payload (packed from dense
    planes) unpacks to the same state as the sparse dict-walk pack —
    including bucket-padded planes, deferred-only members, and an
    empty state."""
    import random

    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.models.orset import AddOp, RmOp
    from crdt_enc_tpu.models.vclock import Dot, VClock
    from crdt_enc_tpu.ops.columnar import (
        Vocab,
        orset_pack_checkpoint,
        orset_pack_checkpoint_planes,
        orset_state_to_planes,
        orset_unpack_checkpoint,
    )
    from crdt_enc_tpu.utils import codec

    rng = random.Random(13)
    actors = [bytes([i]) * 16 for i in range(9)]
    s = ORSet()
    for _ in range(800):
        a = rng.choice(actors)
        m = rng.choice([b"x", 5, "s", (2, "t"), rng.randrange(25)])
        s.apply(AddOp(m, s.clock.inc(a)))
        if rng.random() < 0.3 and s.entries:
            m2 = rng.choice(list(s.entries))
            s.apply(RmOp(m2, VClock(dict(s.entries[m2]))))
    s.apply(RmOp(b"ahead", VClock({b"z" * 16: 7})))  # deferred-only member
    members, replicas = Vocab(), Vocab()
    clock, add, rm = orset_state_to_planes(s, members, replicas)
    # bucket-pad the planes as the service would
    add_p = np.pad(add, ((0, 5), (0, 3)))
    rm_p = np.pad(rm, ((0, 5), (0, 3)))
    clock_p = np.pad(clock, (0, 3))
    via_planes = orset_unpack_checkpoint(codec.unpack(codec.pack(
        orset_pack_checkpoint_planes(clock_p, add_p, rm_p, members, replicas)
    )))
    via_sparse = orset_unpack_checkpoint(codec.unpack(codec.pack(
        orset_pack_checkpoint(s)
    )))
    assert codec.pack(via_planes.to_obj()) == codec.pack(s.to_obj())
    assert codec.pack(via_planes.to_obj()) == codec.pack(via_sparse.to_obj())
    empty = orset_unpack_checkpoint(codec.unpack(codec.pack(
        orset_pack_checkpoint_planes(
            np.zeros(4, np.int32), np.zeros((4, 4), np.int32),
            np.zeros((4, 4), np.int32), Vocab(), Vocab(),
        )
    )))
    assert codec.pack(empty.to_obj()) == codec.pack(ORSet().to_obj())


def test_service_sealed_checkpoint_warm_opens():
    """A tenant closed after a service cycle warm-opens from the
    service-sealed (planes-packed) checkpoint, byte-identical."""

    async def scenario():
        remote = MemoryRemote()
        await write_orset(MemoryStorage(remote), 45, b"ck")
        storage = MemoryStorage(remote)
        served = await Core.open(make_opts(storage))
        (res,) = await FoldService([served]).run_cycle()
        assert res.path == "batched" and res.sealed
        reopened = await Core.open(make_opts(storage, create=False))
        assert reopened.opened_from_checkpoint, (
            reopened.checkpoint_fallback_reason
        )
        assert reopened.with_state(canonical_bytes) == served.with_state(
            canonical_bytes
        )

    run(scenario())


# -------------------------------------------------------- CI trend gate


def test_multitenant_metric_rides_the_trend_gate():
    """Satellite 5: the committed multitenant BENCH_LOCAL record is a
    first-class config for ``obs_report trend`` and its
    ``--fail-on-regression`` CI gate — same machinery, new metric."""
    import pathlib

    from crdt_enc_tpu.obs import fleet, sink

    bench_local = pathlib.Path(__file__).parent.parent / "BENCH_LOCAL.jsonl"
    records = sink.read_records(str(bench_local))
    trend = fleet.bench_trend(
        records, metric="orset_multitenant_agg_ops_per_sec"
    )
    assert trend, "committed BENCH_LOCAL carries no multitenant record"
    cfg = trend[0]
    assert cfg["shape"]["tenants"] >= 256
    assert cfg["latest"] > 0
    # the gate math applies to it exactly like every other config: a
    # synthetic regressed run after the committed one must trip
    regressed = dict(records[-1], metric=cfg["metric"], value=cfg["best"] / 2,
                     backend=cfg["backend"], shape=cfg["shape"])
    t2 = fleet.bench_trend(
        [r for r in records] + [regressed],
        metric="orset_multitenant_agg_ops_per_sec",
    )
    assert fleet.trend_regressions(t2, 10)


# --------------------------------------------------- lifecycle guards


def test_close_is_idempotent_and_cycle_after_close_refuses():
    """Satellite 2 (ISSUE 12): a second ``close()`` is a logged no-op
    (never a hang), and ``run_cycle`` on a closed service is a loud
    error instead of silently cycling released resources."""

    async def scenario():
        core = await Core.open(make_opts(MemoryStorage(MemoryRemote())))
        service = FoldService([core], live_port=0)
        port = service.live.port
        await service.run_cycle()
        service.close()
        assert service.closed
        service.close()  # idempotent — must return, not hang
        with pytest.raises(RuntimeError, match="closed"):
            await service.run_cycle()
        # the live listener really stopped
        import socket

        with socket.socket() as s:
            assert s.connect_ex(("127.0.0.1", port)) != 0

    run(scenario())


def test_run_cycle_is_not_reentrant():
    """An overlapping ``run_cycle`` raises immediately: the fold phase
    assumes exclusive ownership of the cycle's tenants, so interleaving
    two cycles would interleave two fleets' folds."""

    class StallingStorage(MemoryStorage):
        def __init__(self, remote, gate):
            super().__init__(remote)
            self._gate = gate

        async def list_op_actors(self):
            await self._gate.wait()
            return await super().list_op_actors()

    async def scenario():
        gate = asyncio.Event()
        gate.set()  # open() samples replication through the listing
        remote = MemoryRemote()
        await write_orset(MemoryStorage(remote), 10, b"re")
        core = await Core.open(make_opts(StallingStorage(remote, gate)))
        service = FoldService([core])
        gate.clear()
        first = asyncio.ensure_future(service.run_cycle())
        await asyncio.sleep(0)  # first cycle enters its ingest stall
        with pytest.raises(RuntimeError, match="not reentrant"):
            await service.run_cycle()
        gate.set()
        results = await first
        assert results[0].error is None
        # the guard resets: a sequential second cycle is fine
        (res2,) = await service.run_cycle()
        assert res2.error is None

    run(scenario())


def test_run_cycle_subset_override():
    """``run_cycle(tenants=...)`` cycles exactly the given subset (the
    daemon's staleness scheduler) without touching the rest."""

    async def scenario():
        remotes = [MemoryRemote() for _ in range(3)]
        for t, r in enumerate(remotes):
            await write_orset(MemoryStorage(r), 20, b"s%d" % t)
        served = [
            await Core.open(make_opts(MemoryStorage(r))) for r in remotes
        ]
        service = FoldService(served)
        results = await service.run_cycle(served[:2])
        assert len(results) == 2
        assert all(r.sealed for r in results)
        # tenant 2 untouched: its remote still has its op backlog
        assert await served[2].storage.list_op_actors() != []

    run(scenario())


# ------------------------------------------------------- fault isolation


def test_tenant_failure_is_isolated():
    class BrokenStorage(MemoryStorage):
        async def list_op_actors(self):
            raise OSError("remote unreachable")

    async def scenario():
        ok_remote = MemoryRemote()
        await write_orset(MemoryStorage(ok_remote), 20, b"ok")
        broken = await Core.open(make_opts(MemoryStorage(MemoryRemote())))
        broken.storage.__class__ = BrokenStorage  # break AFTER open
        healthy = await Core.open(make_opts(MemoryStorage(ok_remote)))
        results = await FoldService([broken, healthy]).run_cycle()
        assert results[0].path == "error"
        assert "remote unreachable" in results[0].error
        assert not results[0].sealed
        assert results[1].path == "batched" and results[1].sealed

    run(scenario())

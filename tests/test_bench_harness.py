"""The bench harness itself is round-4 infrastructure worth pinning:
one JSON line on success, a diagnostic JSON + exit 3 when the TPU
backend is unavailable (the round-3 failure mode was a hang with no
artifact at all).  Runs bench.py as a real subprocess on tiny CPU
shapes."""

from __future__ import annotations

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_REPO, "bench.py")


def _env(**extra):
    env = os.environ.copy()
    # never touch a possibly-wedged TPU tunnel from tests
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PJRT_LIBRARY_PATH", None)
    env.update(extra)
    return env


def test_smoke_emits_one_json_line():
    r = subprocess.run(
        [sys.executable, _BENCH, "--smoke"],
        env=_env(
            JAX_PLATFORMS="cpu",
            BENCH_OPS="4000", BENCH_REPLICAS="64", BENCH_MEMBERS="32",
            BENCH_HOST_OPS="2000", BENCH_CHAIN="50", BENCH_ITERS="1",
        ),
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "orset_compaction_fold_ops_per_sec"
    assert rec["value"] > 0
    assert rec["unit"] == "ops/s"
    assert rec["backend"] == "cpu"
    assert rec["full_batch_equal"] is True
    assert rec["method"] in ("marginal_chain", "single_dispatch_upper_bound")


def test_multitenant_smoke_emits_one_json_line():
    """The ISSUE-7 bench end-to-end on a tiny CPU fleet: one JSON line,
    byte-identity asserted inside the run (a divergence exits 1)."""
    r = subprocess.run(
        [sys.executable, _BENCH, "--e2e-multitenant", "--smoke",
         "--tenants", "4"],
        env=_env(
            JAX_PLATFORMS="cpu", BENCH_LOCAL_DISABLE="1",
            BENCH_MT_OPS="48", BENCH_MT_OPF="12", BENCH_MT_MEMBERS="16",
        ),
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "orset_multitenant_agg_ops_per_sec"
    assert rec["value"] > 0
    assert rec["byte_identical"] is True
    assert rec["unit"] == "ops/s" and rec["vs_baseline"] > 0
    assert rec["fold_paths"].get("batched") == 4
    assert rec["warm_cycle"]["warm_hits"] == 4


def test_strong_read_smoke_emits_one_json_line():
    """The ISSUE-15 bench end-to-end on a tiny fleet: one JSON line,
    the final strong read oracle-compared inside the run (divergence
    exits 1)."""
    r = subprocess.run(
        [sys.executable, _BENCH, "--e2e-strong-read", "--smoke"],
        env=_env(JAX_PLATFORMS="cpu", BENCH_LOCAL_DISABLE="1"),
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "strong_read_e2e_reads_per_sec"
    assert rec["value"] > 0 and rec["unit"] == "reads/s"
    assert rec["byte_identical"] is True
    assert rec["reads_strong"] > 0
    assert rec["final_covered_versions"] == rec["total_ops"]
    assert "p99_ms" in rec["strong_ms"] and "p99_ms" in rec["eventual_ms"]
    assert rec["watermark_lag_versions"]["max"] >= 0


def test_delta_smoke_emits_one_json_line():
    """The ISSUE-10 bench end-to-end on a tiny CPU remote: one JSON
    line, byte-identity + chains-applied asserted inside the run (a
    divergence or an unused chain exits 1)."""
    r = subprocess.run(
        [sys.executable, _BENCH, "--e2e-delta", "--smoke"],
        env=_env(
            JAX_PLATFORMS="cpu", BENCH_LOCAL_DISABLE="1",
            BENCH_DELTA_OPS="3000", BENCH_DELTA_REPLICAS="40",
            BENCH_DELTA_MEMBERS="48", BENCH_DELTA_ROUNDS="2",
        ),
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "orset_e2e_delta_bytes_reduction"
    assert rec["unit"] == "x"
    assert rec["byte_identical"] is True
    assert rec["deltas_applied"] == 2
    # the whole point: the incremental consumer reads far fewer bytes
    assert rec["value"] >= 5
    assert rec["bytes_read_delta_path"] < rec["bytes_read_snapshot_path"]


def test_unavailable_backend_emits_diagnostic_and_exit_3():
    # non-smoke + no TPU: the subprocess probe sees a CPU-only backend,
    # retries are configured to a single fast attempt, and the bench
    # must emit ONE diagnostic JSON line and exit 3 — never hang.
    # JAX_PLATFORMS must be emptied explicitly: the test conftest pins
    # it to "cpu" in THIS process, which would otherwise flow into the
    # child and legitimately select the no-probe CPU path.
    r = subprocess.run(
        [sys.executable, _BENCH],
        env=_env(
            JAX_PLATFORMS="",
            BENCH_INIT_TIMEOUT="60", BENCH_INIT_ATTEMPTS="1",
            BENCH_INIT_BACKOFF="1",
            # a host with a directly reachable TPU would pass the probe
            # and run the real benchmark: pin tiny shapes so that case
            # stays bounded, and never touch the committed evidence file
            BENCH_OPS="4000", BENCH_REPLICAS="64", BENCH_MEMBERS="32",
            BENCH_HOST_OPS="2000", BENCH_CHAIN="50", BENCH_ITERS="1",
            BENCH_LOCAL_DISABLE="1",
        ),
        capture_output=True, text=True, timeout=300,
    )
    if r.returncode == 0:
        import pytest

        pytest.skip("a real TPU is reachable from this host — the "
                    "unavailable-backend path cannot be exercised here")
    assert r.returncode == 3, (r.returncode, r.stderr[-2000:])
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["value"] is None
    assert rec["error"] == "tpu_backend_unavailable"
    assert rec["stage"] == "subprocess_probe"
    assert rec["attempts"]

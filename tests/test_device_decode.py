"""The CRDT_DEVICE_DECODE experiment (ops/device_decode.py): the device
gather kernel, its host control arm, and the production native decoder
must produce identical columns on qualifying corpora; anything outside
the fixed-stride add-only subset must be refused (None), never
mis-decoded; and the session gate must keep end-to-end states
byte-identical with the flag on."""

import secrets

import numpy as np
import pytest

from crdt_enc_tpu.utils import codec, trace


def _adds_corpus(n_payloads=40, opf=9, R=17, seed=3):
    rng = np.random.default_rng(seed)
    actors = sorted(secrets.token_bytes(16) for _ in range(R))
    payloads = []
    for _ in range(n_payloads):
        ops = [
            [0, int(rng.integers(0, 128)),
             [actors[int(rng.integers(0, R))], int(rng.integers(1, 128))]]
            for _ in range(opf)
        ]
        payloads.append(codec.pack(ops))
    lens = np.array([len(p) for p in payloads], np.uint64)
    offs = np.zeros(len(payloads) + 1, np.uint64)
    np.cumsum(lens, out=offs[1:])
    buf = np.frombuffer(b"".join(payloads), np.uint8)
    return payloads, (buf, offs), actors


def _resolved_rows(decoded):
    kind, m_idx, a_idx, ctr, members = (
        decoded[0], decoded[1], decoded[2], decoded[3], decoded[4],
    )
    ms = [members[int(i)] for i in np.asarray(m_idx).tolist()]
    return (
        np.asarray(kind).tolist(), ms, np.asarray(a_idx).tolist(),
        np.asarray(ctr).tolist(),
    )


def test_device_host_native_identical_columns():
    from crdt_enc_tpu.ops.device_decode import (
        decode_adds_device, decode_adds_host,
    )
    from crdt_enc_tpu.ops.native_decode import decode_orset_payload_batch

    payloads, packed, actors = _adds_corpus()
    dd = decode_adds_device(packed, actors)
    hh = decode_adds_host(packed, actors)
    nn = decode_orset_payload_batch(list(payloads), actors)
    assert dd is not None and hh is not None and nn is not None
    assert _resolved_rows(dd) == _resolved_rows(hh) == _resolved_rows(nn)
    # member_bytes are the canonical single-byte fixint spans
    assert dd[5] == [codec.pack(m) for m in dd[4]]


def test_device_decode_h2d_accounted_exactly():
    """OBS001 substance: the kernel's uploads (cleartext buffer + the
    int32 gather base column) are counted at issue, exactly."""
    from crdt_enc_tpu.ops.device_decode import decode_adds_device

    payloads, packed, actors = _adds_corpus(n_payloads=10)
    n_ops = 10 * 9
    trace.reset()
    assert decode_adds_device(packed, actors) is not None
    snap = trace.snapshot()
    expect = packed[0].nbytes + n_ops * 8  # buf + base (int64 host-side)
    assert snap["counters"].get("h2d_bytes", 0) == expect
    trace.reset()


@pytest.mark.parametrize("poison", ["rm", "wide_counter", "wide_member",
                                    "truncated", "bad_header"])
def test_non_qualifying_corpora_refused(poison):
    from crdt_enc_tpu.ops.device_decode import (
        decode_adds_device, decode_adds_host,
    )

    payloads, _, actors = _adds_corpus(n_payloads=6)
    a0 = actors[0]
    if poison == "rm":
        bad = codec.pack([[1, 3, {a0: 2}]])
    elif poison == "wide_counter":
        bad = codec.pack([[0, 3, [a0, 1000]]])
    elif poison == "wide_member":
        bad = codec.pack([[0, 70000, [a0, 2]]])
    elif poison == "truncated":
        bad = codec.pack([[0, 3, [a0, 2]]])[:-4]
    else:
        bad = b"\xc4\x03abc"
    payloads = payloads + [bad]
    lens = np.array([len(p) for p in payloads], np.uint64)
    offs = np.zeros(len(payloads) + 1, np.uint64)
    np.cumsum(lens, out=offs[1:])
    packed = (np.frombuffer(b"".join(payloads), np.uint8), offs)
    assert decode_adds_device(packed, actors) is None
    assert decode_adds_host(packed, actors) is None


def test_unknown_actor_refused():
    from crdt_enc_tpu.ops.device_decode import decode_adds_host

    payloads, packed, actors = _adds_corpus(n_payloads=4)
    # drop the table entry for an actor the corpus definitely uses
    from crdt_enc_tpu.ops.device_decode import decode_adds_device

    used = decode_adds_device(packed, actors)
    assert used is not None
    drop = actors[int(np.asarray(used[2])[0])]
    table = [a for a in actors if a != drop]
    assert decode_adds_host(packed, table) is None
    assert decode_adds_device(packed, table) is None


def test_session_gate_byte_identical_end_to_end(monkeypatch):
    """CRDT_DEVICE_DECODE=1 through the real streaming front door: an
    all-adds encrypted corpus folds byte-identically with the device
    path on vs off (and a mixed corpus silently falls back)."""
    from crdt_enc_tpu import native

    try:
        native.load()
    except RuntimeError as e:
        pytest.skip(f"native crypto library unavailable: {e}")
    from crdt_enc_tpu.backends.xchacha import encrypt_blob
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator

    key = secrets.token_bytes(32)
    payloads, _, actors = _adds_corpus(n_payloads=24, opf=7, seed=8)
    blobs = [encrypt_blob(key, p) for p in payloads]
    accel = TpuAccelerator()

    def fold(env: bool):
        if env:
            monkeypatch.setenv("CRDT_DEVICE_DECODE", "1")
        else:
            monkeypatch.delenv("CRDT_DEVICE_DECODE", raising=False)
        state = ORSet()
        assert accel.fold_encrypted_stream(
            state, key, blobs, actors_hint=list(actors), n_chunks=3
        )
        return codec.pack(state.to_obj())

    off = fold(False)
    trace.reset()
    on = fold(True)
    assert on == off
    # the device path genuinely ran: its uploads were accounted
    assert trace.snapshot()["counters"].get("h2d_bytes", 0) > 0
    trace.reset()

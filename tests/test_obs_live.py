"""Live telemetry plane (ISSUE 11): scrapeable /metrics, /healthz,
/snapshot; FoldService live_port integration; hot-path neutrality.

The acceptance contract: a FoldService started with ``live_port`` runs
a real cycle and a scraper sees (a) ``/metrics`` parsing as Prometheus
text with the ``serve_*`` families present and (b) ``/healthz``
reporting the EXACT watermark ``Core.replication_status()`` computes —
and turning the whole plane on adds no work to the compaction hot path
(byte-identical states, identical storage-probe counts)."""

import asyncio
import copy
import json
import re
import urllib.error
import urllib.request

import pytest

from crdt_enc_tpu.backends import (
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import Core, OpenOptions, gcounter_adapter
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.obs import live, record
from crdt_enc_tpu.serve import FoldService
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, **kw):
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=gcounter_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
        **kw,
    )


@pytest.fixture(autouse=True)
def _clean_live_state(monkeypatch):
    """Every test starts with no default server, no CRDT_OBS_HTTP, and
    a clean registry; the default server never leaks across tests."""
    monkeypatch.delenv(live.ENV_VAR, raising=False)
    live._reset()
    record.reset()
    yield
    live._reset()
    record.reset()


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


# one Prometheus text-format sample line: name{labels} value [ts]
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^ ]+( \d+)?$"
)


def _assert_prom_parses(body):
    """Every non-comment line is a well-formed sample; families carry
    # HELP + # TYPE.  Returns the set of family names."""
    families = set()
    for ln in body.splitlines():
        if not ln:
            continue
        if ln.startswith("# "):
            parts = ln.split(" ")
            assert parts[1] in ("HELP", "TYPE")
            families.add(parts[2])
            continue
        assert _SAMPLE_RE.match(ln), f"unparseable sample line: {ln!r}"
    return families


# ---- the server itself ----------------------------------------------------


def test_endpoints_and_graceful_shutdown():
    record.add("ops_folded", 7)
    record.gauge("device_bytes_in_use", 123)
    srv = live.LiveTelemetryServer(port=0)
    port = srv.start()
    assert port > 0
    assert srv.start() == port  # idempotent

    code, ctype, body = _get(port, "/metrics")
    assert code == 200 and ctype.startswith("text/plain")
    fams = _assert_prom_parses(body)
    assert "crdt_ops_folded_total" in fams
    assert "crdt_ops_folded_total 7" in body
    assert "crdt_device_bytes_in_use 123" in body

    code, ctype, body = _get(port, "/snapshot")
    assert code == 200 and ctype == "application/json"
    snap = json.loads(body)
    assert snap["schema"] == 2
    assert snap["counters"]["ops_folded"] == 7

    code, _, body = _get(port, "/healthz")
    health = json.loads(body)
    assert health["schema"] == 2
    assert health["label"] == "healthz"
    assert health["remotes"] == {} and health["cycles"] == {}

    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/nope")
    assert ei.value.code == 404

    # requests were themselves counted (off the hot path, but counted)
    assert record.snapshot()["counters"]["live_requests"] >= 4

    srv.stop()
    assert not srv.running
    with pytest.raises(urllib.error.URLError):
        _get(port, "/metrics")
    srv.stop()  # idempotent


def test_handler_bounds_idle_keepalive_connections():
    """HTTP/1.1 keep-alive must carry an idle timeout, or every silent
    connection pins one server thread forever in the always-on
    daemon."""
    assert live._Handler.protocol_version == "HTTP/1.1"
    assert 0 < live._Handler.timeout <= 60


def test_publish_health_rendering_and_bounds():
    srv = live.LiveTelemetryServer(port=0)
    port = srv.start()
    try:
        status = {
            "actor": "aa" * 16,
            "remote_id": "99" * 32,
            "local_clock": {"aa" * 16: 3},
            "union_clock": {"aa" * 16: 3},
            "watermark": {"aa" * 16: 3},
            "matrix": {"bb" * 16: {"aa" * 16: 3}},
            "backlog": {"files": 1, "bytes": 50, "per_actor": {}},
            "divergence": {"actors_behind": 0, "version_lag": 0,
                           "watermark_lag": 0, "known_replicas": 2},
            "checkpoint": {"enabled": False, "sealed": False,
                           "staleness_versions": 0},
        }
        srv.publish_health(status, ts=111.0)
        srv.publish_cycle("fold_service", {"tenants": 4, "sealed": 4})
        _, _, body = _get(port, "/healthz")
        health = json.loads(body)
        dev = health["remotes"]["99" * 32]["devices"]["aa" * 16]
        assert dev["watermark"] == {"aa" * 16: 3}
        assert dev["backlog"] == {"files": 1, "bytes": 50, "per_actor": {}}
        assert dev["ts"] == 111.0
        # bounded payload: the cursor matrix stays OUT of /healthz
        assert "matrix" not in dev
        assert health["cycles"]["fold_service"]["tenants"] == 4
        # last write per (remote, actor) wins
        status2 = dict(status, watermark={"aa" * 16: 5})
        srv.publish_health(status2)
        _, _, body = _get(port, "/healthz")
        dev = json.loads(body)["remotes"]["99" * 32]["devices"]["aa" * 16]
        assert dev["watermark"] == {"aa" * 16: 5}
    finally:
        srv.stop()


def test_env_opt_in_and_publish(monkeypatch):
    """CRDT_OBS_HTTP starts the default server lazily at the first
    publication — the Core._sample_replication hook's path — and a
    malformed value disables rather than raises."""
    monkeypatch.setenv(live.ENV_VAR, "0")
    status = {"actor": "aa", "remote_id": "99", "watermark": {},
              "backlog": {}, "divergence": {"watermark_lag": 0},
              "checkpoint": {}, "local_clock": {}}
    live.publish(status)
    srv = live.default_server()
    assert srv is not None and srv.running and srv.port > 0
    _, _, body = _get(srv.port, "/healthz")
    assert "99" in json.loads(body)["remotes"]

    # shutdown() is FINAL: the next sample must NOT silently rebind the
    # port the embedder just closed (env stays latched)
    live.shutdown()
    live.publish(status)
    assert live.default_server() is None

    live._reset()
    monkeypatch.setenv(live.ENV_VAR, "not-a-port")
    live.publish(status)  # must not raise
    assert live.default_server() is None


def test_client_disconnect_mid_response_is_quiet(capfd):
    """A scraper dropping the connection mid-response must not dump a
    traceback to stderr per scrape (socketserver's handle_error), and
    the server keeps serving."""
    import socket

    # a deliberately large body so the write outlives the client
    for i in range(20000):
        record.add(f"c{i:05d}", i)
    srv = live.LiveTelemetryServer(port=0)
    port = srv.start()
    try:
        for _ in range(3):
            s = socket.create_connection(("127.0.0.1", port))
            # RST on close so the in-flight write fails hard
            s.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                b"\x01\x00\x00\x00\x00\x00\x00\x00",
            )
            s.sendall(b"GET /snapshot HTTP/1.1\r\nHost: x\r\n\r\n")
            s.recv(1)  # response started
            s.close()  # drop it mid-body
        # the server is still healthy for the next scraper
        code, _, _ = _get(port, "/healthz")
        assert code == 200
    finally:
        srv.stop()
    err = capfd.readouterr().err
    assert "Exception occurred" not in err
    assert "Traceback" not in err


def test_core_sampling_publishes_into_default_server():
    """A real Core's replication sample lands in /healthz with the
    exact watermark replication_status() computes."""
    srv = live.configure(0)
    try:
        async def drive():
            core = await Core.open(make_opts(MemoryStorage(MemoryRemote())))
            for _ in range(3):
                await core.apply_ops(
                    [core.with_state(lambda s: s.inc(core.actor_id))]
                )
            await core.compact()
            return core, await core.replication_status()

        core, status = run(drive())
        _, _, body = _get(srv.port, "/healthz")
        health = json.loads(body)
        dev = health["remotes"][status["remote_id"]]["devices"][
            status["actor"]
        ]
        assert dev["watermark"] == status["watermark"]
        assert dev["watermark"] == {core.actor_id.hex(): 3}
        # the freshness-SLO gauges rode along with the sample
        gauges = record.snapshot()["gauges"]
        assert gauges["repl_slo_freshness_ok"] == 1.0
        assert gauges["repl_slo_freshness_target"] == 64.0
    finally:
        live.shutdown()


# ---- FoldService integration (the acceptance scrape) ----------------------


def _seed_remote(n_ops=5):
    """One remote with a writer's sealed op files pending for a second
    (consumer) replica to fold."""
    remote = MemoryRemote()

    async def write():
        w = await Core.open(make_opts(MemoryStorage(remote)))
        for _ in range(n_ops):
            await w.apply_ops([w.with_state(lambda s: s.inc(w.actor_id))])
        return w.actor_id

    writer_actor = run(write())
    return remote, writer_actor


def test_foldservice_live_scrape_end_to_end():
    remote, writer_actor = _seed_remote()
    tenant = run(Core.open(make_opts(MemoryStorage(remote))))
    service = FoldService([tenant], live_port=0)
    try:
        assert service.live is not None and service.live.running
        results = run(service.run_cycle())
        assert results[0].error is None and results[0].sealed

        port = service.live.port
        code, ctype, body = _get(port, "/metrics")
        assert code == 200 and "version=0.0.4" in ctype
        fams = _assert_prom_parses(body)
        assert "crdt_serve_cycles_total" in fams
        assert "crdt_serve_tenants_total" in fams
        assert "crdt_serve_slo_seal_burn" in fams
        assert 'crdt_span_count_total{span="serve.cycle"} 1' in body

        expected = run(tenant.replication_status())
        _, _, body = _get(port, "/healthz")
        health = json.loads(body)
        dev = health["remotes"][expected["remote_id"]]["devices"][
            expected["actor"]
        ]
        # the exact watermark replication_status() computes — folded
        # writer history + the tenant's own published cursor
        assert dev["watermark"] == expected["watermark"]
        assert dev["watermark"][writer_actor.hex()] == 5
        cyc = health["cycles"]["fold_service"]
        assert cyc["tenants"] == 1 and cyc["sealed"] == 1
        assert cyc["errors"] == 0
        assert cyc["slo"]["sealed"] == 1
        assert service.last_cycle_summary == cyc
    finally:
        service.close()
    assert not service.live.running


def test_cycle_publishes_only_freshly_sealed_tenants():
    """A tenant that sealed nothing this cycle has NOT refreshed its
    replication sample — republishing its old status would stamp stale
    watermark data with a fresh /healthz timestamp, hiding exactly the
    wedged-replica staleness the endpoint exists to expose."""
    from crdt_enc_tpu.serve import ServeConfig

    remote, _ = _seed_remote()
    busy = run(Core.open(make_opts(MemoryStorage(remote))))
    quiet = run(Core.open(make_opts(MemoryStorage(MemoryRemote()))))
    run(quiet.compact())  # quiet tenant is fully folded and sealed
    service = FoldService(
        [busy, quiet], ServeConfig(seal_empty=False), live_port=0,
    )
    try:
        results = run(service.run_cycle())
        assert results[0].sealed and not results[1].sealed
        _, _, body = _get(service.live.port, "/healthz")
        health = json.loads(body)
        actors = {
            a for r in health["remotes"].values() for a in r["devices"]
        }
        assert busy.actor_id.hex() in actors
        assert quiet.actor_id.hex() not in actors
    finally:
        service.close()


class _ProbeCountingStorage(MemoryStorage):
    """Counts the replication-probe storage calls the hot path pays."""

    def __init__(self, remote):
        super().__init__(remote)
        self.probe_calls = 0

    async def stat_ops(self, wanted):
        self.probe_calls += 1
        return await super().stat_ops(wanted)

    async def list_op_actors(self):
        self.probe_calls += 1
        return await super().list_op_actors()


def test_live_and_slo_enabled_add_no_hot_path_work():
    """The enabled-vs-disabled differential: byte-identical compacted
    state and an IDENTICAL storage-probe count whether the live server
    + SLO sampling are on or off — the telemetry plane observes the hot
    path, it never joins it."""
    remote, _ = _seed_remote()

    def compact_once(storage):
        async def drive():
            core = await Core.open(make_opts(storage))
            await core.compact()
            return core.with_state(canonical_bytes)

        return run(drive())

    s_off = _ProbeCountingStorage(copy.deepcopy(remote))
    bytes_off = compact_once(s_off)
    record.reset()

    live.configure(0)
    try:
        s_on = _ProbeCountingStorage(copy.deepcopy(remote))
        bytes_on = compact_once(s_on)
        # the scrape surface served nothing during the compact, yet the
        # health map was fed — all off the compaction path
        snap = record.snapshot()
        assert snap["counters"].get("live_requests", 0) == 0
        assert live.default_server().health()["remotes"]
    finally:
        live.shutdown()

    assert bytes_on == bytes_off
    assert s_on.probe_calls == s_off.probe_calls

"""The native canonical msgpack packer (statebuild.cpp ``canon_pack``)
must emit byte-identical output to the Python canonical path
(``msgpack.packb(_canon(obj))``) on everything it accepts, and decline
(return None) anything it cannot — ``codec.pack`` falls back silently,
so a silent divergence here would corrupt every persisted state.
"""

from __future__ import annotations

import msgpack
import pytest
from _hyp import given, settings, st  # hypothesis, or skip-stubs

from crdt_enc_tpu.utils import codec


def _native():
    from crdt_enc_tpu import native

    try:
        return native.load_state()
    except Exception:
        pytest.skip("native state library unavailable")


def _python_pack(obj) -> bytes:
    return msgpack.packb(codec._canon(obj), use_bin_type=True)


EDGES = [
    None, True, False,
    0, 1, 127, 128, 255, 256, 65535, 65536, 2 ** 32 - 1, 2 ** 32,
    2 ** 63 - 1, 2 ** 63, 2 ** 64 - 1,
    -1, -31, -32, -33, -128, -129, -32768, -32769, -2 ** 31, -2 ** 31 - 1,
    -2 ** 63,
    1.5, -0.0,
    b"", b"x" * 255, b"y" * 256, b"z" * 70000,
    "", "a" * 31, "b" * 32, "c" * 255, "d" * 256, "é" * 100,
    [], [1, 2, 3], tuple(range(20)),
    {}, {b"b": 1, b"a": 2}, {1: "x", "1": "y", b"1": b"z"},
    {b"c": {b"k": [1, b"v", None]}, b"e": {5: {b"a": 2 ** 40}}, b"d": {}},
    [{"k": (1, 2)}, {2: [3, {4: 5}]}],
    list(range(70000)),           # array32 header
    {i: i * 2 for i in range(70000)},  # map32 header + big sort
]


def test_edge_cases_byte_identical():
    lib = _native()
    for case in EDGES:
        assert lib.canon_pack(case) == _python_pack(case), repr(case)[:80]


def test_unsupported_types_decline():
    import numpy as np

    lib = _native()
    for case in ({1, 2}, object(), np.int32(5), 2 ** 64, -2 ** 63 - 1):
        assert lib.canon_pack(case) is None
    # the fallback still packs what msgpack can take
    assert codec.pack(5) == _python_pack(5)
    # ...and raises identically on what it can't (set → the Python
    # packer's TypeError, not silence)
    with pytest.raises(TypeError):
        codec.pack({1, 2})


_scalar = st.one_of(
    st.none(),
    st.booleans(),
    st.integers(min_value=-(2 ** 63), max_value=2 ** 64 - 1),
    st.binary(max_size=40),
    st.text(max_size=20),
    st.floats(allow_nan=False),
)
_key = st.one_of(
    st.integers(min_value=0, max_value=2 ** 20),
    st.binary(min_size=1, max_size=16),
    st.text(min_size=1, max_size=8),
    # composite map keys are real in this codebase ((replica, counter)
    # dots stay hashable through codec.unpack's use_list=False)
    st.tuples(
        st.integers(min_value=0, max_value=255), st.binary(max_size=8)
    ),
)
_value = st.recursive(
    _scalar,
    lambda children: st.one_of(
        st.lists(children, max_size=5),
        st.dictionaries(_key, children, max_size=5),
    ),
    max_leaves=30,
)


@settings(max_examples=150, deadline=None)
@given(obj=_value)
def test_hypothesis_byte_identical(obj):
    lib = _native()
    assert lib.canon_pack(obj) == _python_pack(obj)


def test_codec_pack_routes_native():
    # pack() itself (with the lazy native hook) agrees with the pure
    # Python expression on a state-shaped object
    obj = {b"c": {b"a%d" % i: i for i in range(100)},
           b"e": {i: {b"x": i} for i in range(50)}, b"d": {}}
    assert codec.pack(obj) == _python_pack(obj)

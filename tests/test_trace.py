"""Per-phase tracing: the observability layer the reference lacks
(SURVEY.md §5 requires phase timers for list/load/decrypt/decode/fold/write
and ops-merged counters in the rebuild)."""

import asyncio

from crdt_enc_tpu.backends import IdentityCryptor, MemoryRemote, MemoryStorage, PlainKeyCryptor
from crdt_enc_tpu.core import Core, OpenOptions, gcounter_adapter
from crdt_enc_tpu.utils import trace
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def make_opts(remote):
    return OpenOptions(
        storage=MemoryStorage(remote),
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=gcounter_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
    )


def test_span_and_counter_accumulate():
    trace.reset()
    with trace.span("phase.x"):
        pass
    with trace.span("phase.x"):
        pass
    trace.add("items", 3)
    trace.add("items", 4)
    snap = trace.snapshot()
    assert snap["spans"]["phase.x"]["count"] == 2
    assert snap["spans"]["phase.x"]["seconds"] >= 0
    assert snap["counters"]["items"] == 7
    assert "phase.x" in trace.report()
    trace.reset()
    assert trace.snapshot() == {"spans": {}, "counters": {}, "gauges": {}}


def test_span_records_on_exception():
    trace.reset()
    try:
        with trace.span("phase.err"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert trace.snapshot()["spans"]["phase.err"]["count"] == 1


def test_lifecycle_emits_phase_spans():
    trace.reset()

    async def go():
        remote = MemoryRemote()
        w = await Core.open(make_opts(remote))
        for _ in range(3):
            await w.apply_ops([w.with_state(lambda s: s.inc(w.actor_id))])
        r = await Core.open(make_opts(remote))
        await r.read_remote()
        await r.compact()

    asyncio.run(go())
    snap = trace.snapshot()
    for name in ("ops.list", "ops.load", "ops.decrypt_decode", "ops.fold",
                 "compact.seal", "compact.write", "compact.gc"):
        assert name in snap["spans"], name
    assert snap["counters"]["ops_folded"] == 3
    assert snap["counters"]["op_files_loaded"] >= 3
    tp = trace.throughput("ops.fold", "ops_folded")
    assert tp is None or tp > 0
    trace.reset()

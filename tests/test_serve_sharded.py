"""Pod-scale serving (ISSUE 14): tenant-batch as a mesh axis.

The contract under test: sharding the multi-tenant mega-fold across the
device mesh — tenant lanes over ``dp``, member planes over ``mp``
(``parallel.mesh.orset_fold_tenants_sharded`` and its G-Counter twin) —
must be an *invisible* layout change.  Byte-identity per tenant to both
the single-chip FoldService cycle and the solo ``Core.compact()`` path,
the bucket planner's dp/mp quantization keeping the compiled-shape set
constant under tenant churn, oversize tenants riding the existing solo
``orset_fold_sharded`` SPMD path, and the control plane (FleetDaemon)
running mesh-backed inside PR-9 all-fault schedules — all on the
virtual 8-device CPU mesh the conftest forces.
"""

import asyncio
import copy
import random

import numpy as np
import pytest

from crdt_enc_tpu import ops as K
from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import (
    Core,
    OpenOptions,
    gcounter_adapter,
    orset_adapter,
)
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.obs import runtime as obs_runtime
from crdt_enc_tpu.parallel import TpuAccelerator
from crdt_enc_tpu.parallel import mesh as pmesh
from crdt_enc_tpu.serve import (
    FoldService,
    PlaneWarmTier,
    ServeConfig,
    TenantShape,
    plan_buckets,
)
from crdt_enc_tpu.utils import trace
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, adapter=None, create=True, **kw):
    kw.setdefault("accelerator", TpuAccelerator(min_device_batch=1))
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter if adapter is not None else orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
        **kw,
    )


async def write_orset(storage, n_ops, tag, rm_every=7):
    core = await Core.open(make_opts(storage))
    for i in range(n_ops):
        m = b"%s-%d" % (tag, i % 31)
        await core.apply_ops(
            [core.with_state(lambda s, m=m: s.add_ctx(core.actor_id, m))]
        )
        if rm_every and i % rm_every == rm_every - 1:
            victim = b"%s-%d" % (tag, (i * 3) % 31)

            def rm(s, victim=victim):
                return s.rm_ctx(victim) if victim in s.entries else None

            op = core.with_state(rm)
            if op is not None:
                await core.apply_ops([op])
    return core


async def write_gcounter(storage, n_ops):
    core = await Core.open(make_opts(storage, gcounter_adapter()))
    for _ in range(n_ops):
        await core.apply_ops(
            [core.with_state(lambda s: s.inc(core.actor_id))]
        )
    return core


# ------------------------------------------------- kernel differentials


@pytest.mark.parametrize("dp,mp", [(8, 1), (4, 2), (2, 4)])
def test_tenant_fold_sharded_kernel_differential(dp, mp):
    """The sharded tenant mega-fold is byte-identical to the vmapped
    single-device kernel on random ragged stacks — including sentinel
    padding rows, all-sentinel dummy tenant lanes over zero planes, and
    pre-populated (normalized and not) state planes — across tenant/dp
    and member/mp splits."""
    rng = np.random.default_rng(dp * 10 + mp)
    mesh = pmesh.make_mesh((dp, mp))
    T, N, R = 16, 48, 4
    E = max(8, mp * 4)
    clock0 = rng.integers(0, 5, (T, R)).astype(np.int32)
    add0 = np.where(
        rng.random((T, E, R)) < 0.3, rng.integers(1, 9, (T, E, R)), 0
    ).astype(np.int32)
    rm0 = np.where(
        rng.random((T, E, R)) < 0.2, rng.integers(1, 9, (T, E, R)), 0
    ).astype(np.int32)
    kind = rng.integers(0, 2, (T, N)).astype(np.int8)
    member = rng.integers(0, E, (T, N)).astype(np.int32)
    actor = rng.integers(0, R + 1, (T, N)).astype(np.int32)  # R = pad
    counter = rng.integers(1, 12, (T, N)).astype(np.int32)
    for t in (T - 1, T - 2):  # dummy lanes
        actor[t, :] = R
        clock0[t] = 0
        add0[t] = 0
        rm0[t] = 0
    ref = K.orset_fold_tenants(
        clock0, add0, rm0, kind, member, actor, counter,
        num_members=E, num_replicas=R,
    )
    orset_step, gcounter_step = pmesh.tenant_fold_steps(mesh)
    got = orset_step(clock0, add0, rm0, kind, member, actor, counter)
    for a, b, name in zip(ref, got, ("clock", "add", "rm")):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name

    gc_clock = rng.integers(0, 5, (T, R)).astype(np.int32)
    ga = rng.integers(0, R + 1, (T, N)).astype(np.int32)
    gc = rng.integers(1, 99, (T, N)).astype(np.int32)
    gref = K.gcounter_fold_tenants(gc_clock, ga, gc, num_replicas=R)
    ggot = gcounter_step(gc_clock, ga, gc)
    assert np.array_equal(np.asarray(gref), np.asarray(ggot))


def test_tenant_fold_sharded_rejects_undivisible():
    mesh = pmesh.make_mesh((8, 1))
    z = np.zeros((6, 8, 4), np.int32)  # 6 tenants % dp=8
    with pytest.raises(ValueError, match="pad first"):
        pmesh.orset_fold_tenants_sharded(
            mesh, np.zeros((6, 4), np.int32), z, z,
            np.zeros((6, 8), np.int8), np.zeros((6, 8), np.int32),
            np.zeros((6, 8), np.int32), np.zeros((6, 8), np.int32),
        )
    with pytest.raises(ValueError, match="pad first"):
        pmesh.gcounter_fold_tenants_sharded(
            mesh, np.zeros((6, 4), np.int32),
            np.zeros((6, 8), np.int32), np.zeros((6, 8), np.int32),
        )


def test_tenant_step_cache_is_bounded_lru():
    pmesh._TENANT_STEP_CACHE.clear()
    mesh = pmesh.make_mesh((8, 1))
    steps = pmesh.tenant_fold_steps(mesh)
    assert pmesh.tenant_fold_steps(mesh) is steps  # cached per mesh
    assert len(pmesh._TENANT_STEP_CACHE) == 1


# ------------------------------------------------------ planner (dp/mp)


def test_plan_buckets_dp_quantizes_slots():
    """Slot classes become dp-multiples: {dp, 2·dp, 4·dp, …} — bounded
    AND always divisible by the tenant mesh axis."""
    shapes = [TenantShape(i, "orset", 40, 10, 4) for i in range(3)]
    buckets, solo = plan_buckets(shapes, dp=8)
    assert solo == []
    assert [b.slots for b in buckets] == [8]  # 3 tenants → 8 lanes
    shapes = [TenantShape(i, "orset", 40, 10, 4) for i in range(9)]
    (bucket,), _ = plan_buckets(shapes, dp=8)
    assert bucket.slots == 16  # 9 tenants → 2·dp
    # dp=1 is exactly the historical plan
    (bucket,), _ = plan_buckets(shapes, dp=1)
    assert bucket.slots == 16  # pow2 floor 1
    with pytest.raises(ValueError):
        plan_buckets(shapes, dp=0)


def test_plan_buckets_mp_lifts_member_classes():
    shapes = [TenantShape(0, "orset", 40, 3, 4)]  # E class 8
    (bucket,), _ = plan_buckets(shapes, mp=16)
    assert bucket.members == 16  # lifted to divide mp
    (bucket,), _ = plan_buckets(shapes, mp=2)
    assert bucket.members == 8  # pow2 already divides
    # a non-power-of-two mp must terminate and still divide (the
    # doubling lift looped forever here — review regression)
    (bucket,), _ = plan_buckets(shapes, mp=3)
    assert bucket.members % 3 == 0 and bucket.members >= 8


def test_parse_mesh_spec_validation():
    """The ONE --mesh parser (bench + daemon CLI): malformed specs,
    unknown axes, and degenerate size-1 meshes are ValueErrors — a
    sharding flag must never silently run the unsharded path."""
    assert pmesh.parse_mesh_spec("dp=8") == (8, 1)
    assert pmesh.parse_mesh_spec("dp=4,mp=2") == (4, 2)
    assert pmesh.parse_mesh_spec("mp=2") == (1, 2)
    for bad in ("dp=1", "dp=0", "dp=0,mp=5", "dp=eight", "dq=8", ""):
        with pytest.raises(ValueError):
            pmesh.parse_mesh_spec(bad)


def test_plan_buckets_mesh_churn_shape_invariance():
    """Join/evict churn across same-class tenants never changes the
    compiled-shape set on a mesh: any count in (0, dp] shares one slot
    class, and shuffles of one class mix plan identical shapes."""
    rng = random.Random(7)
    base = [TenantShape(i, "orset", 50 + (i % 3), 10, 4) for i in range(20)]
    shuffled = list(base)
    rng.shuffle(shuffled)
    shape_set = lambda bs: sorted(
        (b.kind, b.rows, b.members, b.replicas, b.slots) for b in bs
    )
    a, _ = plan_buckets(base, dp=8, mp=2)
    b, _ = plan_buckets(shuffled, dp=8, mp=2)
    assert shape_set(a) == shape_set(b)
    # shrinking the fleet within one dp-quantum keeps the class
    c, _ = plan_buckets(base[:17], dp=8, mp=2)
    assert {x.slots for x in c} <= {x.slots for x in a}


# ------------------------------------- service differential (mesh arm)


@pytest.fixture(params=["memory", "fs"])
def fleet_backend(request, tmp_path):
    """Per-tenant storage factories over either backend; ``split(t)``
    freezes tenant ``t``'s remote into an independent twin."""
    if request.param == "memory":

        class B:
            def __init__(self):
                self.remotes = {}

            def storage(self, t):
                r = self.remotes.setdefault(t, MemoryRemote())
                return MemoryStorage(r)

            def twin_storage(self, t):
                return MemoryStorage(copy.deepcopy(self.remotes[t]))

        return B()

    class B:
        def __init__(self):
            self.n = {}

        def storage(self, t):
            i = self.n.get(t, 0)
            self.n[t] = i + 1
            return FsStorage(
                str(tmp_path / f"local-{t}-{i}"), str(tmp_path / f"r{t}")
            )

        def twin_storage(self, t):
            import shutil

            i = self.n.get(t, 0)
            self.n[t] = i + 1
            dst = tmp_path / f"r{t}-twin{i}"
            shutil.copytree(str(tmp_path / f"r{t}"), str(dst))
            return FsStorage(str(tmp_path / f"local-t{t}-{i}"), str(dst))

    return B()


def test_sharded_mixed_fleet_differential(fleet_backend):
    """The acceptance differential: a mixed ragged fleet — ragged
    ORSets, a G-Counter, an oversize spill, an empty tenant — cycled by
    a mesh-backed FoldService is byte-identical per tenant to BOTH the
    single-chip service and solo ``Core.compact()``, across memory and
    fs backends, and the sealed snapshots read back cold."""

    async def scenario():
        sizes = [0, 23, 57, 110, 40, 200]  # 200 > rows_cap=128 → spill

        async def build():
            for t, n in enumerate(sizes):
                if t == 4:
                    await write_gcounter(fleet_backend.storage(t), sizes[4])
                elif n:
                    await write_orset(
                        fleet_backend.storage(t), n, b"t%d" % t
                    )
                else:  # empty tenant: bootstrap the remote (meta only)
                    await Core.open(make_opts(fleet_backend.storage(t)))

        await build()

        def ad(t):
            return gcounter_adapter() if t == 4 else orset_adapter()

        solo = [
            await Core.open(make_opts(fleet_backend.twin_storage(t), ad(t)))
            for t in range(len(sizes))
        ]
        for c in solo:
            await c.compact()

        chip = [
            await Core.open(make_opts(fleet_backend.twin_storage(t), ad(t)))
            for t in range(len(sizes))
        ]
        chip_res = await FoldService(
            chip, ServeConfig(rows_cap=128)
        ).run_cycle()

        mesh = pmesh.make_mesh((4, 2))
        served = [
            await Core.open(make_opts(fleet_backend.storage(t), ad(t)))
            for t in range(len(sizes))
        ]
        trace.reset()
        results = await FoldService(
            served, ServeConfig(rows_cap=128), mesh=mesh
        ).run_cycle()
        snap = trace.snapshot()["counters"]
        paths = [r.path for r in results]
        assert paths[0] == "empty"
        assert paths[1] == paths[2] == paths[3] == paths[4] == "batched"
        assert paths[5] == "solo"  # oversize: the SPMD solo spill
        assert [r.path for r in chip_res] == paths
        assert snap.get("serve_sharded_folds", 0) >= 2  # orset + gcounter
        assert snap.get("serve_sharded_tenants", 0) == 4
        for t, (a, b, c) in enumerate(zip(solo, chip, served)):
            sb = a.with_state(canonical_bytes)
            assert sb == c.with_state(canonical_bytes), (
                f"tenant {t} sharded diverged ({paths[t]})"
            )
            assert sb == b.with_state(canonical_bytes), (
                f"tenant {t} single-chip diverged"
            )
        assert all(r.sealed for r in results)
        # cold readback of the mesh-sealed snapshots
        for t in range(len(sizes)):
            cold = await Core.open(
                make_opts(fleet_backend.twin_storage(t), ad(t))
            )
            await cold.read_remote()
            assert cold.with_state(canonical_bytes) == served[
                t
            ].with_state(canonical_bytes), f"tenant {t} cold readback"

    run(scenario())


def test_sharded_bounded_compiles_across_shuffled_mixes():
    """Zero steady-state XLA recompiles across tenant churn on the
    mesh: two shuffled fleets of one size-class set fold through the
    same compiled sharded programs (the acceptance gate's compile
    half)."""

    async def build_fleet(sizes, tag):
        served = []
        for t, n in enumerate(sizes):
            remote = MemoryRemote()
            await write_orset(
                MemoryStorage(remote), n, b"%s%d" % (tag, t), rm_every=5
            )
            served.append(await Core.open(make_opts(MemoryStorage(remote))))
        return served

    async def scenario():
        obs_runtime.track_recompiles()
        mesh = pmesh.make_mesh((8, 1))
        sizes = [20, 25, 30, 90, 100, 40]
        fleet_a = await build_fleet(sizes, b"a")
        await FoldService(fleet_a, mesh=mesh).run_cycle()  # warmup
        baseline = obs_runtime.recompile_count()
        shuffled = list(sizes)
        random.Random(11).shuffle(shuffled)
        fleet_b = await build_fleet(shuffled, b"b")
        await FoldService(fleet_b, mesh=mesh).run_cycle()
        assert obs_runtime.recompile_count() == baseline, (
            "a shuffled tenant mix of the same size classes recompiled "
            "the SHARDED mega-fold"
        )
        # ...and fleet-size churn within one dp quantum stays compiled
        fleet_c = await build_fleet(sizes[:5], b"c")
        await FoldService(fleet_c, mesh=mesh).run_cycle()
        assert obs_runtime.recompile_count() == baseline, (
            "tenant join/evict churn within a dp slot class recompiled"
        )

    run(scenario())


def test_warm_tier_mesh_identity_and_cross_cycle_reuse():
    """The warm tier is keyed by mesh identity (device-sharded slices
    are only addressable under their mesh), and cross-cycle warm reuse
    on the mesh stays byte-identical vs a cold reader."""
    tier = PlaneWarmTier(mesh_key=None)
    assert tier.compatible_with(None)
    mesh = pmesh.make_mesh((8, 1))
    tier_m = PlaneWarmTier(mesh_key=mesh)
    assert tier_m.compatible_with(mesh)
    assert not tier_m.compatible_with(None)
    assert not tier.compatible_with(mesh)

    async def scenario():
        remotes = [MemoryRemote() for _ in range(3)]
        for t, r in enumerate(remotes):
            await write_orset(MemoryStorage(r), 35, b"w%d" % t)
        served = [
            await Core.open(make_opts(MemoryStorage(r))) for r in remotes
        ]
        service = FoldService(served, mesh=mesh)
        assert service.warm.compatible_with(mesh)
        await service.run_cycle()
        assert len(service.warm) == 3
        for t, r in enumerate(remotes):
            await write_orset(MemoryStorage(r), 12, b"x%d" % t, rm_every=0)
        trace.reset()
        results = await service.run_cycle()
        snap = trace.snapshot()["counters"]
        assert snap["serve_warm_hits"] == 3
        assert all(r.path == "batched" for r in results)
        for c, r in zip(served, remotes):
            cold = await Core.open(make_opts(MemoryStorage(r)))
            await cold.read_remote()
            assert c.with_state(canonical_bytes) == cold.with_state(
                canonical_bytes
            )

    run(scenario())


# --------------------------------------- control plane on the mesh


def test_daemon_mesh_cycles_and_drain_inside_allfault_sim():
    """FleetDaemon ``run_cycle`` + graceful drain with a MESH-backed
    service, inside a PR-9 all-fault schedule: the daemon/ddrain
    vocabulary runs against torn reads, partial listings, delayed
    visibility and crashes, and all five quiescence invariants hold —
    the sharded fold path under the same hostility every other path
    faces."""
    from crdt_enc_tpu.sim import FaultConfig, SimRunner, generate

    mesh = pmesh.make_mesh((8, 1))
    schedule = generate(3, 4, 80, FaultConfig.all_faults(), daemon=True)
    assert any(s.kind == "daemon" for s in schedule.steps)
    assert any(s.kind == "ddrain" for s in schedule.steps)
    result = SimRunner(schedule, mesh=mesh).run()
    assert result.ok, result.violation
    assert result.daemon_cycles > 0


def test_sim_service_pool_reused_across_steps():
    """The sim fast path: one FoldService instance serves every
    ``service`` step of a schedule (construction was per-step
    overhead), and the run still satisfies every invariant."""
    from crdt_enc_tpu.sim import FaultConfig, SimRunner, generate

    schedule = generate(1, 4, 60, FaultConfig.none())
    if not any(s.kind == "service" for s in schedule.steps):
        schedule = generate(5, 4, 120, FaultConfig.none())
    assert any(s.kind == "service" for s in schedule.steps)
    runner = SimRunner(schedule)
    result = runner.run()
    assert result.ok, result.violation
    assert result.service_cycles > 0
    assert runner._service_pool is not None  # built once, reused

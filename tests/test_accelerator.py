"""Host-equivalence tests for every TpuAccelerator fast path.

Each path (ORSet fold is covered by tests/test_parallel.py; here: LWW-map,
G-Counter, PN-Counter folds and the ≥3-state ORSet merge) must produce a
state canonically byte-identical to the sequential host loop it replaces
(HostAccelerator — reference HOT LOOPS #1/#2, crdt-enc/src/lib.rs:458-466,
533-539)."""

import copy
import uuid

import numpy as np
import pytest

from crdt_enc_tpu.core.adapters import HostAccelerator
from crdt_enc_tpu.models import GCounter, LWWMap, ORSet, PNCounter, canonical_bytes
from crdt_enc_tpu.parallel.accel import TpuAccelerator

ACTORS = [uuid.UUID(int=i + 1).bytes for i in range(7)]


def accel():
    # min_device_batch=1 forces the device path even for small test batches
    return TpuAccelerator(min_device_batch=1)


def both_fold(state, ops):
    h = HostAccelerator().fold_ops(copy.deepcopy(state), list(ops))
    t = accel().fold_ops(copy.deepcopy(state), list(ops))
    assert canonical_bytes(t) == canonical_bytes(h)
    return h, t


def test_gcounter_fold_matches_host():
    rng = np.random.default_rng(0)
    state = GCounter()
    ops = []
    for i in range(500):
        a = ACTORS[int(rng.integers(len(ACTORS)))]
        ops.append(state.inc(a, int(rng.integers(1, 5))))
        state.apply(ops[-1])
    h, _ = both_fold(GCounter(), ops)
    assert h.read() == state.read()


def test_pncounter_fold_matches_host():
    rng = np.random.default_rng(1)
    state = PNCounter()
    ops = []
    for i in range(500):
        a = ACTORS[int(rng.integers(len(ACTORS)))]
        op = (state.dec if rng.random() < 0.4 else state.inc)(a)
        state.apply(op)
        ops.append(op)
    h, _ = both_fold(PNCounter(), ops)
    assert h.read() == state.read()


def test_lww_fold_matches_host():
    rng = np.random.default_rng(2)
    state = LWWMap()
    ops = []
    for i in range(400):
        a = ACTORS[int(rng.integers(len(ACTORS)))]
        k = f"k{int(rng.integers(40))}"
        # coarse timestamps force plenty of (ts, actor, value) tie-breaks
        ts = int(rng.integers(0, 8)) * (1 << 33) + int(rng.integers(0, 4))
        if rng.random() < 0.25:
            op = state.delete(k, ts, a)
        else:
            op = state.put(k, ts, a, int(rng.integers(100)))
        state.apply(op)
        ops.append(op)
    both_fold(LWWMap(), ops)


def test_lww_fold_duplicate_write_tombstone_tie():
    # exact duplicate (ts, actor, value) where one is a delete: delete wins
    a = ACTORS[0]
    ops = [
        LWWMap().put("k", 5, a, 1),
        LWWMap().delete("k", 5, a),
    ]
    # host semantics: tombstone wins the full tie (models/lwwmap.py _wins)
    h, t = both_fold(LWWMap(), ops)
    assert h.get("k") is None


def test_merge_many_orsets_matches_host():
    rng = np.random.default_rng(3)
    # build 5 divergent replicas from a shared ancestor
    base = ORSet()
    for i in range(10):
        op = base.add_ctx(ACTORS[0], i)
        base.apply(op)
    replicas = []
    for r in range(5):
        s = copy.deepcopy(base)
        for i in range(30):
            if rng.random() < 0.3:
                op = s.rm_ctx(int(rng.integers(15)))
                if op.ctx.is_empty():
                    continue
            else:
                op = s.add_ctx(ACTORS[r + 1], int(rng.integers(15)))
            s.apply(op)
        replicas.append(s)
    h = HostAccelerator().merge_states(
        copy.deepcopy(replicas[0]), [copy.deepcopy(s) for s in replicas[1:]]
    )
    t = accel().merge_states(
        copy.deepcopy(replicas[0]), [copy.deepcopy(s) for s in replicas[1:]]
    )
    assert canonical_bytes(t) == canonical_bytes(h)


# ---- sparse (sorted-COO) ORSet fold path ---------------------------------


def sparse_accel():
    """Force the sparse fold for any vocab: thresholds dropped to zero."""
    a = TpuAccelerator(min_device_batch=1)
    a.SPARSE_MIN_CELLS = 0
    a.SPARSE_CELLS_PER_ROW = 0
    return a


def _orset_script(n_ops=400, n_members=30, seed=5, actors=ACTORS):
    """A host-applied op history with interleaved adds/removes."""
    rng = np.random.default_rng(seed)
    state = ORSet()
    ops = []
    for i in range(n_ops):
        a = actors[int(rng.integers(len(actors)))]
        m = int(rng.integers(n_members))
        if rng.random() < 0.25:
            op = state.rm_ctx(m)
            if op.ctx.is_empty():
                continue
        else:
            op = state.add_ctx(a, m)
        state.apply(op)
        ops.append(op)
    return state, ops


def test_sparse_orset_fold_matches_host_and_dense():
    final, ops = _orset_script()
    h = HostAccelerator().fold_ops(ORSet(), list(ops))
    dense = accel().fold_ops(ORSet(), list(ops))
    sparse = sparse_accel().fold_ops(ORSet(), list(ops))
    assert canonical_bytes(sparse) == canonical_bytes(h)
    assert canonical_bytes(sparse) == canonical_bytes(dense)
    assert canonical_bytes(sparse) == canonical_bytes(final)


def test_sparse_orset_fold_into_existing_state():
    # fold the second half of a history into the state built from the first
    final, ops = _orset_script(seed=8)
    half = len(ops) // 2
    base_h = HostAccelerator().fold_ops(ORSet(), list(ops[:half]))
    base_s = copy.deepcopy(base_h)
    h = HostAccelerator().fold_ops(base_h, list(ops[half:]))
    s = sparse_accel().fold_ops(base_s, list(ops[half:]))
    assert canonical_bytes(s) == canonical_bytes(h)
    assert canonical_bytes(s) == canonical_bytes(final)


def test_sparse_orset_fold_clock_retires_foreign_deferred():
    # a remove-ahead horizon parks in deferred; a later add batch advances
    # the clock past it — the sparse path must retire it exactly like the
    # host does, even though the batch never names that member
    s_host = ORSet()
    s_host.apply(ORSet().add_ctx(ACTORS[0], 1))  # dot (a0, 1) for member 1
    from crdt_enc_tpu.models.orset import RmOp
    from crdt_enc_tpu.models.vclock import VClock

    rm_ahead = RmOp(2, VClock({ACTORS[1]: 3}))  # horizon beyond a1's clock
    s_host.apply(rm_ahead)
    s_sparse = copy.deepcopy(s_host)
    assert 2 in s_host.deferred

    # hand-build dots 1..3 for member 9 so a1's clock reaches the horizon
    from crdt_enc_tpu.models.orset import AddOp
    from crdt_enc_tpu.models.vclock import Dot

    late_adds = [AddOp(9, Dot(ACTORS[1], c)) for c in (1, 2, 3)]
    h = HostAccelerator().fold_ops(s_host, list(late_adds))
    s = sparse_accel().fold_ops(s_sparse, list(late_adds))
    assert canonical_bytes(s) == canonical_bytes(h)
    assert 2 not in s.deferred  # horizon retired by the advanced clock


def test_streamed_dense_fold_matches_unstreamed():
    """Batches above STREAM_CHUNK_ROWS fold blockwise with donated plane
    buffers; forcing a tiny chunk bound must not change a single byte."""
    final, ops = _orset_script(n_ops=300, seed=13)
    a = accel()
    a.STREAM_CHUNK_ROWS = 32  # force many chunks
    streamed = a.fold_ops(ORSet(), list(ops))
    plain = accel().fold_ops(ORSet(), list(ops))
    host = HostAccelerator().fold_ops(ORSet(), list(ops))
    assert canonical_bytes(streamed) == canonical_bytes(plain)
    assert canonical_bytes(streamed) == canonical_bytes(host)
    assert canonical_bytes(streamed) == canonical_bytes(final)


def test_sparse_fold_property_random_histories():
    """Hypothesis sweep: sparse ≡ host from arbitrary base states and op
    tails (the fixed-seed tests above pin a handful of histories; this
    pins the space)."""
    from _hyp import given, settings, st  # hypothesis, or skip-stubs

    script = st.lists(
        st.tuples(
            st.integers(0, len(ACTORS) - 1),
            st.sampled_from(["add", "rm"]),
            st.integers(0, 9),
        ),
        max_size=25,
    )

    def run_script(s, state=None):
        state = state if state is not None else ORSet()
        ops = []
        for actor_i, kind, member in s:
            if kind == "add":
                op = state.add_ctx(ACTORS[actor_i], member)
            else:
                op = state.rm_ctx(member)
                if op.ctx.is_empty():
                    continue
            state.apply(op)
            ops.append(op)
        return state, ops

    @settings(max_examples=60, deadline=None)
    @given(script, script)
    def inner(script_a, script_b):
        base, _ = run_script(script_a)
        base_host = ORSet.from_obj(base.to_obj())
        base_sparse = ORSet.from_obj(base.to_obj())
        host2, ops = run_script(script_b, base_host)
        if not ops:
            return
        s = sparse_accel().fold_ops(base_sparse, list(ops))
        assert canonical_bytes(s) == canonical_bytes(host2)

    inner()


def test_sparse_device_coo_route_matches_host():
    """sparse_device=True routes sparse-regime folds through the device
    COO kernel (orset_fold_coo) — byte-equal to both the host loop and
    the default host-sort route."""
    import numpy as np

    from crdt_enc_tpu.models import ORSet, canonical_bytes
    from crdt_enc_tpu.parallel import TpuAccelerator

    rng = np.random.default_rng(31)
    actors = [bytes([i + 1]) * 16 for i in range(6)]
    host = ORSet()
    ops = []
    for i in range(400):
        a = actors[int(rng.integers(len(actors)))]
        m = int(rng.integers(500))
        if i % 6 == 5 and host.contains(m):
            op = host.rm_ctx(m)
        else:
            op = host.add_ctx(a, m)
        host.apply(op)
        ops.append(op)

    def run(accel):
        s = ORSet()
        # force the sparse regime at this small test shape
        accel.SPARSE_MIN_CELLS = 1
        accel.SPARSE_CELLS_PER_ROW = 0
        accel.min_device_batch = 1
        return accel.fold_ops(s, list(ops))

    via_host_sort = run(TpuAccelerator())
    via_device_coo = run(TpuAccelerator(sparse_device=True))
    assert canonical_bytes(via_host_sort) == canonical_bytes(host)
    assert canonical_bytes(via_device_coo) == canonical_bytes(host)


def test_mvreg_batched_dominance_merge_matches_host():
    """The accelerator's batched MVReg merge (mvreg_dominance_keep) must
    equal sequential host merges on dominated + concurrent + duplicate
    register snapshots."""
    from crdt_enc_tpu.models import MVReg, canonical_bytes
    from crdt_enc_tpu.parallel import TpuAccelerator

    actors = [bytes([i + 1]) * 16 for i in range(4)]
    base = MVReg()
    base.apply(base.write_ctx(actors[0], b"v0"))

    snaps = []
    for i, a in enumerate(actors):
        s = MVReg.from_obj(base.to_obj())
        s.apply(s.write_ctx(a, b"w%d" % i))  # concurrent successors of v0
        snaps.append(s)
    snaps.append(MVReg.from_obj(base.to_obj()))  # dominated snapshot
    snaps.append(MVReg.from_obj(snaps[0].to_obj()))  # exact duplicate

    host = MVReg.from_obj(base.to_obj())
    for s in snaps:
        host.merge(MVReg.from_obj(s.to_obj()))

    accel = TpuAccelerator(min_device_batch=1)
    batched = MVReg.from_obj(base.to_obj())
    accel.merge_states(batched, [MVReg.from_obj(s.to_obj()) for s in snaps])
    assert canonical_bytes(batched) == canonical_bytes(host)
    assert sorted(bytes(v) for v in batched.read().values) == [
        b"w0", b"w1", b"w2", b"w3",
    ]

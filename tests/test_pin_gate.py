"""pin_baselines spread gate (ISSUE 11 satellite / VERDICT item 4): a
pin measured with >30% host sample spread is refused unless forced."""

from benchmarks.pin_baselines import SPREAD_LIMIT_PCT, spread_gate


def test_spread_limit_is_thirty():
    assert SPREAD_LIMIT_PCT == 30.0


def test_within_limit_passes_silently(capsys):
    assert spread_gate("cfg3", {"host_spread_pct": 12.4}) is True
    assert spread_gate("cfg3", {"host_spread_pct": 30.0}) is True
    # legacy records without the field are not retroactively refused
    assert spread_gate("cfg3", {}) is True
    assert capsys.readouterr().err == ""


def test_over_limit_refused_with_reason(capsys):
    assert spread_gate("cfg5", {"host_spread_pct": 31.0}) is False
    err = capsys.readouterr().err
    assert "REFUSING to pin cfg5" in err
    assert "31.0 > 30" in err
    assert "--force" in err


def test_force_overrides_with_warning(capsys):
    assert spread_gate("cfg5", {"host_spread_pct": 55.5}, force=True) \
        is True
    err = capsys.readouterr().err
    assert "WARNING" in err and "55.5" in err

"""Columnar CrdtMap<orset> bulk fold ≡ per-op host fold.

The referee is the host model: random causally consistent op histories
(the same generator the map law tests use) sealed into payloads, decoded
natively, folded columnar — canonical bytes must match the per-op apply,
batch-into-empty and batch-into-populated-state alike."""

import asyncio
import random
import uuid

from crdt_enc_tpu.backends import (
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import Core, OpenOptions, map_adapter
from crdt_enc_tpu.models import CrdtMap, canonical_bytes
from crdt_enc_tpu.models.orset import AddOp
from crdt_enc_tpu.parallel.accel import TpuAccelerator
from crdt_enc_tpu.utils import codec
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

from tests.test_crdtmap import orset_child_history

ACTORS = [uuid.UUID(int=i + 1).bytes for i in range(4)]


def _payloads_from_streams(m, streams, per_file=3):
    """Seal per-actor op streams into op-file payloads, one actor's files
    after another (per-actor order is the only ordering the fold's
    contract requires; it is order-free across actors)."""
    files = []
    for s in streams:
        for i in range(0, len(s), per_file):
            files.append([m.op_to_obj(op) for op in s[i : i + per_file]])
    return [codec.pack(f) for f in files]


import pytest


@pytest.mark.parametrize("impl", ["host", "device"])
def test_columnar_fold_matches_host_fuzz(impl):
    rng = random.Random(7)
    proto = CrdtMap(child=b"orset")
    for trial in range(400 if impl == "host" else 150):
        n = rng.randrange(0, 30)
        script = [
            (rng.randrange(4),
             rng.choice(["add", "rm_member", "rm_key", "write"]),
             rng.randrange(3), rng.randrange(3))
            for _ in range(n)
        ]
        oracle, streams = orset_child_history(script)
        payloads = _payloads_from_streams(proto, streams)
        accel = TpuAccelerator(min_device_batch=1, map_fold_impl=impl)
        folded = CrdtMap(child=b"orset")
        ok = accel.fold_payloads(folded, payloads, actors_hint=ACTORS)
        assert ok, f"trial {trial}: accelerator declined"
        assert canonical_bytes(folded) == canonical_bytes(oracle), (
            f"trial {trial} diverged: {script}"
        )


@pytest.mark.parametrize("impl", ["host", "device"])
def test_columnar_fold_into_populated_state(impl):
    """Fold the second half of a history into the state built per-op from
    the first half — cursor-style incremental ingest."""
    rng = random.Random(11)
    proto = CrdtMap(child=b"orset")
    for trial in range(200 if impl == "host" else 100):
        n = rng.randrange(4, 30)
        script = [
            (rng.randrange(4),
             rng.choice(["add", "rm_member", "rm_key", "write"]),
             rng.randrange(3), rng.randrange(3))
            for _ in range(n)
        ]
        oracle, streams = orset_child_history(script)
        # split each actor stream: first half applied per-op, second bulk
        base = CrdtMap(child=b"orset")
        tails = []
        for s in streams:
            half = len(s) // 2
            for op in s[:half]:
                base.apply(op)
            tails.append(s[half:])
        payloads = _payloads_from_streams(proto, tails)
        accel = TpuAccelerator(min_device_batch=1, map_fold_impl=impl)
        ok = accel.fold_payloads(base, payloads, actors_hint=ACTORS)
        assert ok, f"trial {trial}: declined"
        assert canonical_bytes(base) == canonical_bytes(oracle), (
            f"trial {trial} diverged: {script}"
        )


def test_columnar_declines_foreign_dot():
    """A child add whose dot differs from the map dot breaks the
    shared-dot discipline the fold relies on — must decline, per-op path
    handles it."""
    from crdt_enc_tpu.models.vclock import Dot

    m = CrdtMap(child=b"orset")
    up = m.update_ctx(ACTORS[0], "k", lambda c, d: AddOp(1, Dot(ACTORS[1], 1)))
    payload = codec.pack([m.op_to_obj(up)])
    accel = TpuAccelerator(min_device_batch=1)
    state = CrdtMap(child=b"orset")
    assert accel.fold_payloads(state, [payload], actors_hint=ACTORS) is False
    assert canonical_bytes(state) == canonical_bytes(CrdtMap(child=b"orset"))


def test_map_bulk_ingest_through_core():
    """End to end: a map replica's history ingests through the bulk path
    and matches a per-op reference reader."""
    import crdt_enc_tpu.core.core as core_mod

    async def go():
        def opts(remote, accel=None):
            kw = {"accelerator": accel} if accel is not None else {}
            return OpenOptions(
                storage=MemoryStorage(remote),
                cryptor=IdentityCryptor(),
                key_cryptor=PlainKeyCryptor(),
                adapter=map_adapter(b"orset"),
                supported_data_versions=(DEFAULT_DATA_VERSION_1,),
                current_data_version=DEFAULT_DATA_VERSION_1,
                create=True,
                **kw,
            )

        remote = MemoryRemote()
        w = await Core.open(opts(remote))
        for i in range(30):
            key = f"k{i % 5}"
            if i % 11 == 10:
                op = w.with_state(lambda s, key=key: s.rm_ctx(key))
                if not op.ctx.is_empty():
                    await w.apply_ops([op])
            else:
                await w.update(
                    lambda s, key=key, i=i: s.update_ctx(
                        w.actor_id, key, lambda c, d: AddOp(i % 7, d)
                    )
                )
        r = await Core.open(opts(remote, TpuAccelerator(min_device_batch=1)))
        await r.read_remote()
        ref = await Core.open(opts(remote))
        await ref.read_remote()
        assert r.with_state(canonical_bytes) == ref.with_state(canonical_bytes)
        # and the compaction snapshot round-trips
        await r.compact()
        f = await Core.open(opts(remote))
        await f.read_remote()
        assert f.with_state(canonical_bytes) == r.with_state(canonical_bytes)

    asyncio.run(go())


@pytest.mark.parametrize("impl", ["host", "device"])
def test_map_fold_session_chunked(impl):
    """MapFoldSession (round 3): chunked decode+intern, one fold at
    finish — must equal the per-op oracle and the whole-batch path."""
    from crdt_enc_tpu.parallel.session import open_fold_session

    rng = random.Random(23)
    proto = CrdtMap(child=b"orset")
    for trial in range(60):
        n = rng.randrange(4, 40)
        script = [
            (rng.randrange(4),
             rng.choice(["add", "rm_member", "rm_key", "write"]),
             rng.randrange(3), rng.randrange(3))
            for _ in range(n)
        ]
        oracle, streams = orset_child_history(script)
        payloads = _payloads_from_streams(proto, streams)
        accel = TpuAccelerator(min_device_batch=1, map_fold_impl=impl)
        state = CrdtMap(child=b"orset")
        session = open_fold_session(accel, state, actors_hint=ACTORS)
        assert session is not None
        # feed in uneven chunks
        i = 0
        while i < len(payloads):
            step = 1 + (i % 3)
            session.feed(payloads[i : i + step])
            i += step
        session.finish()
        assert canonical_bytes(state) == canonical_bytes(oracle), (
            f"trial {trial} diverged: {script}"
        )


def test_map_fold_session_into_populated_state():
    from crdt_enc_tpu.parallel.session import open_fold_session

    rng = random.Random(29)
    proto = CrdtMap(child=b"orset")
    for trial in range(40):
        n = rng.randrange(6, 36)
        script = [
            (rng.randrange(4),
             rng.choice(["add", "rm_member", "rm_key", "write"]),
             rng.randrange(3), rng.randrange(3))
            for _ in range(n)
        ]
        oracle, streams = orset_child_history(script)
        base = CrdtMap(child=b"orset")
        tails = []
        for s in streams:
            half = len(s) // 2
            for op in s[:half]:
                base.apply(op)
            tails.append(s[half:])
        payloads = _payloads_from_streams(proto, tails)
        accel = TpuAccelerator(min_device_batch=1)
        session = open_fold_session(accel, base, actors_hint=ACTORS)
        for p in payloads:
            session.feed([p])
        session.finish()
        assert canonical_bytes(base) == canonical_bytes(oracle), (
            f"trial {trial} diverged: {script}"
        )


def test_map_fold_session_actor_joins_mid_flight():
    """An actor absent at session open applies an op while chunks are in
    flight: finish must honor it (review finding, round 3 — the actor
    table is a prefix, new actors intern after it)."""
    from crdt_enc_tpu.models.orset import AddOp
    from crdt_enc_tpu.parallel.session import open_fold_session

    proto = CrdtMap(child=b"orset")
    late_actor = uuid.UUID(int=99).bytes
    script = [(0, "add", 0, 0), (1, "add", 1, 1), (2, "add", 2, 2)]
    oracle, streams = orset_child_history(script)
    payloads = _payloads_from_streams(proto, streams)
    accel = TpuAccelerator(min_device_batch=1)
    state = CrdtMap(child=b"orset")
    session = open_fold_session(accel, state, actors_hint=ACTORS)
    session.feed(payloads[:1])
    # mid-flight apply from a brand-new actor
    up = state.update_ctx(late_actor, "late", lambda c, d: AddOp(7, d))
    state.apply(up)
    oracle.apply(up)
    session.feed(payloads[1:])
    session.finish()
    assert canonical_bytes(state) == canonical_bytes(oracle)

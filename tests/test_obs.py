"""The observability subsystem (crdt_enc_tpu/obs/, ISSUE 2).

Pinned here:

* **histogram quantiles**: log-scale aggregates report p50/p95/p99 within
  the documented quarter-octave bucket error;
* **event ring buffer**: bounded capacity, drop counting, and
  ``reset()`` restoring the events-off default (no state leaks between
  tests);
* **thread safety**: concurrent spans/counters lose no updates;
* **disabled-path overhead**: spans stay cheap with events off;
* **timeline export**: Chrome-trace JSON schema (lanes, chunk args,
  counter tracks) and the chunk-overlap proof on a recorded streaming
  run, via the obs_report CLI — the ISSUE 2 acceptance;
* **recompile counter**: constant across a varying-batch fold loop
  (the ADVICE-r5 unbounded-recompile bug class, mechanized);
* **sink**: JSONL round-trip, Prometheus exposition, Core.compact
  wiring;
The span-name registry lint lives in the static-analysis engine now
(rule SPN001, gated by tests/test_static_analysis.py).
"""

from __future__ import annotations

import json
import secrets
import threading
import time

import numpy as np
import pytest

from crdt_enc_tpu.obs import record, runtime, sink, timeline
from crdt_enc_tpu.utils import codec, trace


@pytest.fixture(autouse=True)
def _clean_registry():
    trace.reset()
    yield
    trace.reset()


def test_trace_shim_is_the_registry():
    # the utils.trace compat shim and obs.record must be ONE module, or
    # flags set through the old name would fork
    assert trace is record


# ---------------------------------------------------------------- histogram


def test_histogram_quantiles_within_bucket_error():
    durations = [0.001] * 50 + [0.010] * 45 + [0.100] * 5
    for d in durations:
        record.observe("phase.x", d)
    s = trace.snapshot()["spans"]["phase.x"]
    assert s["count"] == 100
    # quarter-octave buckets: estimates within ~±19% of the true value
    assert 0.8 <= s["p50_ms"] <= 1.25
    assert 8.0 <= s["p95_ms"] <= 12.5
    assert 80.0 <= s["p99_ms"] <= 125.0
    assert s["max_ms"] >= 99.0
    rep = trace.report()
    assert "p95" in rep and "phase.x" in rep


def test_observe_feeds_throughput_and_report():
    record.observe("phase.y", 0.5)
    trace.add("items", 100)
    assert 150 < trace.throughput("phase.y", "items") < 250


# ------------------------------------------------------------- event buffer


def test_event_ring_buffer_bounds_and_drop_counter():
    trace.enable_events()
    trace.set_events_capacity(4)
    for i in range(10):
        with trace.span("phase.x", meta=i):
            pass
    evs = trace.events()
    assert len(evs) == 4
    # newest survive, oldest dropped
    assert [e["meta"] for e in evs] == [6, 7, 8, 9]
    assert trace.snapshot()["counters"]["events_dropped"] == 6
    # aggregates are NOT affected by event drops
    assert trace.snapshot()["spans"]["phase.x"]["count"] == 10
    # a capacity SHRINK counts its discards too — the drop counter is the
    # timeline-completeness signal, whatever caused the loss
    trace.set_events_capacity(1)
    assert len(trace.events()) == 1
    assert trace.snapshot()["counters"]["events_dropped"] == 9


def test_reset_restores_events_defaults():
    trace.enable_events()
    trace.set_events_capacity(8)
    with trace.span("phase.x"):
        pass
    assert trace.events()
    trace.reset()
    # flag AND capacity restored: a seam test cannot leak event
    # recording (or a tiny ring) into later tests
    assert trace.events_capacity() == record.DEFAULT_EVENT_CAPACITY
    with trace.span("phase.x"):
        pass
    assert trace.events() == []


def test_events_carry_thread_identity():
    trace.enable_events()
    with trace.span("phase.x"):
        pass
    t = threading.Thread(
        target=lambda: record.observe("phase.x", 0.001), name="obs-worker"
    )
    t.start()
    t.join()
    threads = {e["thread"] for e in trace.events()}
    assert "obs-worker" in threads and len(threads) == 2
    assert all(isinstance(e["tid"], int) for e in trace.events())


# ------------------------------------------------------------ thread safety


def test_multithreaded_spans_and_counters_lose_no_updates():
    N_THREADS, N_ITERS = 8, 400
    trace.enable_events()
    trace.set_events_capacity(N_THREADS * N_ITERS // 2)  # force drops too
    barrier = threading.Barrier(N_THREADS)

    def work(k):
        barrier.wait()
        for _ in range(N_ITERS):
            with trace.span("stress.span"):
                pass
            trace.add("stress_counter", 1)
            trace.gauge("stress_gauge", k)

    threads = [threading.Thread(target=work, args=(k,)) for k in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = trace.snapshot()
    total = N_THREADS * N_ITERS
    assert snap["spans"]["stress.span"]["count"] == total
    assert snap["counters"]["stress_counter"] == total
    assert snap["gauges"]["stress_gauge"] in range(N_THREADS)
    # histogram buckets account for every occurrence
    hist_total = sum(
        record._spans["stress.span"][3].values()  # noqa: SLF001 — white-box
    )
    assert hist_total == total
    # ring buffer stayed bounded and drops were counted exactly
    kept = len(trace.events())
    dropped = snap["counters"]["events_dropped"]
    assert kept == trace.events_capacity()
    # span + counter + gauge events each fired `total` times
    assert kept + dropped == 3 * total


def test_disabled_path_overhead_and_no_events():
    N = 20_000
    t0 = time.perf_counter()
    for _ in range(N):
        with trace.span("phase.x"):
            pass
    per_span = (time.perf_counter() - t0) / N
    assert trace.events() == []
    assert trace.snapshot()["spans"]["phase.x"]["count"] == N
    # generous bound (~30x measured) so machine weather can't flake it;
    # catches accidental O(events) or allocation regressions on the
    # disabled path
    assert per_span < 200e-6, f"span overhead {per_span * 1e6:.1f}µs"


# ----------------------------------------------------------------- timeline


def _synthetic_pipeline_events():
    """A recorded 4-chunk run of the real ingest pipeline with stage
    durations pinned by sleeps — deterministic overlap on any box."""
    from crdt_enc_tpu import ops as K

    trace.enable_events()

    def ingest(span, k):
        time.sleep(0.02)
        return span

    def reduce(item, k):
        time.sleep(0.05)

    K.run_ingest_pipeline(list(range(4)), ingest, reduce, depth=2)
    trace.add("h2d_bytes", 4096)
    return trace.events()


def test_chrome_trace_schema_golden():
    events = _synthetic_pipeline_events()
    obj = timeline.to_chrome_trace(events)
    # round-trips as JSON (Perfetto/chrome://tracing load this directly)
    obj = json.loads(json.dumps(obj))
    assert obj["displayTimeUnit"] == "ms"
    evs = obj["traceEvents"]
    phases = {e["ph"] for e in evs}
    assert phases == {"M", "X", "C"}
    # one thread_name metadata event per lane; producer + consumer lanes
    lanes = [e for e in evs if e["ph"] == "M"]
    assert {e["name"] for e in lanes} == {"thread_name"}
    lane_names = {e["args"]["name"] for e in lanes}
    # producer workers are numbered lanes (crdt-ingest-producer-<i>);
    # a single-producer run exports exactly producer + consumer
    assert any(n.startswith("crdt-ingest-producer") for n in lane_names)
    assert len(lanes) == 2
    # timestamps rebase to 0 at the earliest event (the run's
    # stream_producers gauge fires first, ahead of any X span); X events
    # carry positive durations and the chunk index in args
    xs = [e for e in evs if e["ph"] == "X"]
    assert min(e["ts"] for e in evs if e["ph"] in ("X", "C")) == 0.0
    assert min(e["ts"] for e in xs) >= 0.0
    assert all(e["dur"] > 0 for e in xs)
    ingests = [e for e in xs if e["name"] == "stream.ingest"]
    assert sorted(e["args"]["chunk"] for e in ingests) == [0, 1, 2, 3]
    # ingest and reduce run on DIFFERENT lanes
    tid_by_stage = {
        name: {e["tid"] for e in xs if e["name"] == name}
        for name in ("stream.ingest", "stream.reduce")
    }
    assert tid_by_stage["stream.ingest"].isdisjoint(tid_by_stage["stream.reduce"])
    # counter track present
    cs = [e for e in evs if e["ph"] == "C"]
    assert any(e["name"] == "h2d_bytes" and e["args"]["value"] == 4096
               for e in cs)
    # and the overlap is provable from the exported JSON alone
    assert timeline.chunk_overlaps(obj, "stream.ingest", "stream.reduce")


def _native_crypto_or_skip():
    from crdt_enc_tpu import native

    try:
        native.load()
    except RuntimeError as e:
        pytest.skip(f"native crypto library unavailable: {e}")


def test_export_trace_cli_proves_overlap_on_streaming_run(
    tmp_path, capsys, monkeypatch
):
    """ISSUE 2 acceptance: obs_report export-trace on a recorded
    streaming run (the --e2e-streaming smoke shape: encrypted blobs →
    fold_encrypted_stream) emits valid Chrome-trace JSON whose events
    prove chunk k+1's ingest overlaps chunk k's fold/reduce."""
    _native_crypto_or_skip()
    import time as _time

    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.parallel import session as psession
    from crdt_enc_tpu.tools import obs_report
    from tests.test_streaming_pipeline import _encrypted_orset_workload

    key, blobs, actors, host = _encrypted_orset_workload(
        n_files=60, ops_per_file=8
    )
    accel = TpuAccelerator()
    streamed = ORSet()
    trace.enable_events()
    # two producers force the threaded pipeline (on a 1-core box the
    # auto-tuned single producer runs INLINE — no lookahead to prove),
    # and a slowed consumer widens the overlap window so the proof is
    # deterministic on one core: a PIPELINED run shows chunk k+1's
    # ingest starting inside the slow reduce k; a serial run would not,
    # however slow the reduce — same discipline as the seam tests'
    # injected delays
    real_reduce = psession.OrsetFoldSession.reduce_chunk

    def slow_reduce(self, decoded):
        _time.sleep(0.005)
        return real_reduce(self, decoded)

    monkeypatch.setattr(
        psession.OrsetFoldSession, "reduce_chunk", slow_reduce
    )
    ok = accel.fold_encrypted_stream(
        streamed, key, blobs, actors_hint=sorted(actors), n_chunks=6,
        n_producers=2,
    )
    assert ok
    assert codec.pack(streamed.to_obj()) == codec.pack(host.to_obj())
    # record the run through the sink (events attach automatically)
    run_path = tmp_path / "run.jsonl"
    rec = sink.MetricsSink(str(run_path)).write("e2e-streaming-smoke")
    assert rec["events"]
    out_path = tmp_path / "trace.json"
    rc = obs_report.main([
        "export-trace", str(run_path), "-o", str(out_path),
        "--check-overlap", "stream.ingest:stream.reduce",
    ])
    assert rc == 0, capsys.readouterr()
    with open(out_path) as f:
        obj = json.load(f)
    assert obj["traceEvents"]
    ks = timeline.chunk_overlaps(obj, "stream.ingest", "stream.reduce")
    assert ks, "recorded streaming run shows no ingest/fold overlap"
    out = capsys.readouterr().out
    assert "overlap proof" in out


# ------------------------------------------------------------ JAX runtime


def test_recompile_counter_constant_across_varying_batches():
    """ISSUE 2 acceptance: the jax_compiles counter stays CONSTANT
    across a fold loop whose raw batch sizes vary inside one padding
    bucket — the regression test for the ADVICE-r5 recompile bug class
    (every growth step recompiling the donated fold)."""
    import jax

    from crdt_enc_tpu import ops as K
    from crdt_enc_tpu.parallel.accel import _bucket

    runtime.track_recompiles()
    R, E = 4, 8
    rng = np.random.default_rng(5)

    def fold(n_rows):
        bucket = _bucket(n_rows, floor=64)
        kind = np.zeros(bucket, np.int8)
        member = np.zeros(bucket, np.int32)
        actor = np.full(bucket, R, np.int32)  # sentinel-pad the tail
        counter = np.zeros(bucket, np.int32)
        kind[:n_rows] = rng.integers(0, 2, n_rows)
        member[:n_rows] = rng.integers(0, E, n_rows)
        actor[:n_rows] = rng.integers(0, R, n_rows)
        counter[:n_rows] = rng.integers(1, 100, n_rows)
        out = K.orset_fold(
            np.zeros(R, np.int32), np.zeros((E, R), np.int32),
            np.zeros((E, R), np.int32), kind, member, actor, counter,
            num_members=E, num_replicas=R,
        )
        jax.block_until_ready(out)

    fold(40)  # warmup: compiles once for the 64-row bucket
    baseline = runtime.recompile_count()
    for n in (33, 47, 56, 64, 41):
        fold(n)
    assert runtime.recompile_count() == baseline, (
        "varying raw batch sizes inside one padding bucket recompiled "
        "the fold"
    )
    # ...and a bucket CHANGE is visible as exactly what it is
    fold(100)
    assert runtime.recompile_count() > baseline


def test_jax_compile_span_records_durations():
    import jax
    import jax.numpy as jnp

    runtime.track_recompiles()

    @jax.jit
    def f(x):
        return x * 2 + 1

    jax.block_until_ready(f(jnp.arange(7)))
    snap = trace.snapshot()
    assert snap["counters"].get("jax_compiles", 0) >= 1
    assert snap["spans"]["jax.compile"]["seconds"] > 0


def test_sample_device_memory_cpu_degrades_to_noop():
    # CPU backend has no allocator stats: returns None, records nothing,
    # and caches the capability probe
    assert runtime.sample_device_memory() is None
    assert "device_bytes_in_use" not in trace.snapshot()["gauges"]


# ------------------------------------------------------------------- sink


def test_sink_jsonl_roundtrip_and_prometheus(tmp_path):
    with trace.span("stream.fold"):
        pass
    trace.add("ops_folded", 7)
    trace.gauge("device_bytes_in_use", 123)
    path = tmp_path / "metrics.jsonl"
    s = sink.MetricsSink(str(path))
    s.write("first")
    s.write("second", meta={"note": "hi"})
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [r["label"] for r in lines] == ["first", "second"]
    rec = lines[-1]
    assert rec["counters"]["ops_folded"] == 7
    assert rec["spans"]["stream.fold"]["count"] == 1
    assert rec["meta"] == {"note": "hi"}
    assert "events" not in rec  # events off → no timeline payload
    prom = sink.to_prometheus(rec)
    assert "crdt_ops_folded_total 7" in prom
    assert 'crdt_span_count_total{span="stream.fold"} 1' in prom
    assert "crdt_device_bytes_in_use 123" in prom
    assert 'quantile="0.95"' in prom
    # registry-derived exposition metadata (ISSUE 6 satellite)
    assert "# TYPE crdt_ops_folded_total counter" in prom
    assert "# TYPE crdt_device_bytes_in_use gauge" in prom
    assert "# HELP crdt_ops_folded_total" in prom
    # sink records are schema-stamped so fleet/trend can reject
    # mixed-version inputs loudly
    assert rec["schema"] == sink.SCHEMA_VERSION


def test_sink_drains_events_per_write(tmp_path):
    trace.enable_events()
    with trace.span("phase.x", meta=0):
        pass
    s = sink.MetricsSink(str(tmp_path / "m.jsonl"))
    first = s.write("first")
    assert [e["name"] for e in first["events"]] == ["phase.x"]
    # drained: a second write without new activity carries no timeline,
    # and the live log is empty
    assert "events" not in s.write("second")
    assert trace.events() == []
    # disabling recording (without reset) also stops attachment, even if
    # stale events remained
    with trace.span("phase.x", meta=1):
        pass
    trace.enable_events(False)
    assert "events" not in s.write("third")


def test_chunk_overlaps_ignores_earlier_runs():
    """An event log holding TWO pipeline runs (e.g. warmup then
    measured) must not pair run-1 reduces with run-2 ingests — a fully
    serialized second run yields NO overlap proof."""
    def x(name, chunk, ts, dur):
        return {"ph": "X", "name": name, "ts": ts, "dur": dur,
                "args": {"chunk": chunk}, "pid": 1, "tid": 0}

    # reduce k spans [100k+40, 100k+110): ingest k+1 (starts 100k+100)
    # opens inside it — every interior chunk overlaps
    run1 = [x("stream.ingest", k, 100 * k, 50) for k in range(4)] + [
        x("stream.reduce", k, 100 * k + 40, 70) for k in range(4)
    ]
    # second run, strictly serialized: ingest k+1 starts after reduce k
    base = 10_000
    run2 = []
    for k in range(3):
        run2.append(x("stream.ingest", k, base + 200 * k, 50))
        run2.append(x("stream.reduce", k, base + 200 * k + 60, 50))
    serial = {"traceEvents": run1 + run2, "displayTimeUnit": "ms"}
    assert timeline.chunk_overlaps(serial) == []
    # run 1 alone DID overlap — the split keeps real proofs working
    assert timeline.chunk_overlaps(
        {"traceEvents": run1, "displayTimeUnit": "ms"}
    )


def test_sample_device_memory_explicit_device_bypasses_cache():
    class FakeDev:
        def memory_stats(self):
            return {"bytes_in_use": 5, "peak_bytes_in_use": 9}

    # the default-device probe on CPU latched unsupported...
    assert runtime.sample_device_memory() is None
    assert runtime._mem_supported is False  # noqa: SLF001 — white-box
    # ...but an explicitly passed stats-capable device still samples
    stats = runtime.sample_device_memory(FakeDev())
    assert stats == {"bytes_in_use": 5, "peak_bytes_in_use": 9}
    g = trace.snapshot()["gauges"]
    assert g["device_bytes_in_use"] == 5 and g["device_peak_bytes"] == 9
    # and the default-device cache was not flipped by the explicit probe
    assert runtime._mem_supported is False  # noqa: SLF001


def test_sink_default_from_env_and_configure(tmp_path, monkeypatch):
    env_path = tmp_path / "env.jsonl"
    monkeypatch.setenv(sink.ENV_VAR, str(env_path))
    monkeypatch.setattr(sink, "_configured", False)
    assert sink.maybe_write("via-env") is not None
    assert json.loads(env_path.read_text())["label"] == "via-env"
    # explicit configure overrides the env var
    conf_path = tmp_path / "conf.jsonl"
    sink.configure(str(conf_path))
    try:
        sink.maybe_write("via-configure")
        assert json.loads(conf_path.read_text())["label"] == "via-configure"
        assert len(env_path.read_text().splitlines()) == 1
    finally:
        monkeypatch.setattr(sink, "_configured", False)


def test_compact_appends_sink_snapshot(tmp_path, monkeypatch):
    """Core.compact is wired into the run-scoped sink: one labelled
    snapshot per compaction, with the compact.* spans populated."""
    import asyncio

    from tests.test_trace import make_opts
    from crdt_enc_tpu.backends import MemoryRemote
    from crdt_enc_tpu.core import Core

    path = tmp_path / "compact.jsonl"
    sink.configure(str(path))
    try:
        async def go():
            remote = MemoryRemote()
            w = await Core.open(make_opts(remote))
            for _ in range(3):
                await w.apply_ops([w.with_state(lambda s: s.inc(w.actor_id))])
            await w.compact()

        asyncio.run(go())
    finally:
        monkeypatch.setattr(sink, "_configured", False)
    rec = json.loads(path.read_text().splitlines()[-1])
    assert rec["label"] == "compact"
    for name in ("compact.ingest", "compact.seal", "compact.write",
                 "compact.gc"):
        assert name in rec["spans"], name
    assert rec["meta"]["gc_op_actors"] >= 1


# -------------------------------------------------------------- CLI + lint


def _write_run(tmp_path, label, seconds):
    record.observe("stream.fold", seconds)
    trace.add("ops_folded", 10)
    path = tmp_path / f"{label}.jsonl"
    sink.MetricsSink(str(path)).write(label)
    trace.reset()
    return path


def test_obs_report_report_and_diff(tmp_path, capsys):
    from crdt_enc_tpu.tools import obs_report

    a = _write_run(tmp_path, "old", 0.010)
    b = _write_run(tmp_path, "new", 0.030)
    assert obs_report.main(["report", str(a)]) == 0
    out = capsys.readouterr().out
    assert "stream.fold" in out and "p95" in out
    assert obs_report.main(["diff", str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "stream.fold" in out and "+" in out
    # prometheus subcommand
    assert obs_report.main(["prom", str(b)]) == 0
    assert "crdt_span_seconds_total" in capsys.readouterr().out


def test_obs_report_export_trace_requires_events(tmp_path, capsys):
    from crdt_enc_tpu.tools import obs_report

    a = _write_run(tmp_path, "noevents", 0.010)
    rc = obs_report.main(
        ["export-trace", str(a), "-o", str(tmp_path / "t.json")]
    )
    assert rc == 2
    assert "no event log" in capsys.readouterr().err


# The span-name registry and thread-discipline lints moved into the
# static-analysis engine (rules SPN001/THR001); the tier-1 gate is now
# tests/test_static_analysis.py::test_live_repo_analysis_clean_within_budget
# (plus the shim exit-code tests there).


# ---- prometheus text-format escaping (ISSUE 11 satellite) -----------------


def test_prometheus_label_value_escaping_roundtrip():
    """Label values escape backslash, double-quote and newline per the
    text-format spec; a spec-compliant unescape recovers the original
    span name exactly."""
    weird = 'sp"an\\x\nend'
    snap = {
        "spans": {weird: {"count": 1, "seconds": 0.5}},
        "counters": {},
        "gauges": {},
    }
    prom = sink.to_prometheus(snap)
    line = next(
        ln for ln in prom.splitlines()
        if ln.startswith("crdt_span_count_total{")
    )
    # the rendered line is ONE physical line (the newline was escaped)
    assert "\n" not in line
    rendered = line[len('crdt_span_count_total{span="'):line.rindex('"')]
    assert rendered == 'sp\\"an\\\\x\\nend'
    unescaped = (
        rendered.replace("\\\\", "\x00").replace('\\"', '"')
        .replace("\\n", "\n").replace("\x00", "\\")
    )
    assert unescaped == weird


def test_prometheus_help_escaping(monkeypatch):
    """HELP text escapes backslash and newline (only those two, per the
    spec) — both for registry-derived and fallback help strings."""
    monkeypatch.setattr(
        sink, "registry_help", lambda: {"ops_folded": "a\\b\nc"}
    )
    snap = {"spans": {}, "counters": {"ops_folded": 1}, "gauges": {}}
    prom = sink.to_prometheus(snap)
    assert "# HELP crdt_ops_folded_total a\\\\b\\nc" in prom
    # fallback help for an unregistered name is escaped the same way
    snap = {"spans": {}, "counters": {}, "gauges": {"we\\ird": 1}}
    prom = sink.to_prometheus(snap)
    help_line = next(
        ln for ln in prom.splitlines() if ln.startswith("# HELP")
    )
    assert "we\\\\ird" in help_line


def test_prometheus_registry_help_single_escape():
    """The registry parse keeps raw text; escaping happens once at
    render time (a doc description containing a backslash must not
    double-escape)."""
    sink._help_cache = None
    try:
        help_ = sink.registry_help()
        # live-repo registry descriptions never pre-escape
        assert all("\\\\" not in v for v in help_.values())
    finally:
        sink._help_cache = None


def test_sink_rotation_concurrent_writers(tmp_path, monkeypatch):
    """N threads writing through CRDT_OBS_SINK_MAX_MB rotation: the
    size bound holds, every record lands in EXACTLY one generation
    (the limit allows at most one rotation for this workload — nothing
    is lost, nothing duplicated), and every surviving record parses
    under check_schema."""
    import threading

    path = tmp_path / "rot.jsonl"
    s = sink.MetricsSink(str(path))
    probe = len(json.dumps(s.write("probe-00"))) + 1
    n_threads, per_thread = 8, 6
    total = n_threads * per_thread + 1  # +1 for the probe record
    limit = probe * total  # > half the volume → at most ONE rotation
    monkeypatch.setenv("CRDT_OBS_SINK_MAX_MB", str(limit / 1e6))

    barrier = threading.Barrier(n_threads)

    def writer(i):
        barrier.wait()
        for k in range(per_thread):
            s.write(f"w-{i:03d}-{k:02d}")

    threads = [
        threading.Thread(target=writer, args=(i,))
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    labels = []
    for p in (path, tmp_path / "rot.jsonl.1"):
        if not p.exists():
            continue
        assert p.stat().st_size <= limit  # the bound held per generation
        records = sink.read_records(str(p))
        sink.check_schema(records, source=str(p))
        labels.extend(r["label"] for r in records)
    assert len(labels) == total  # nothing lost
    assert len(set(labels)) == total  # nothing written twice

"""Passphrase key-cryptor backend: real protection of the Keys CRDT blob.

The reference's key backend leaves its protect/unprotect as identity TODOs
(crdt-enc-gpgme/src/lib.rs:95-98, 118-121); this backend seals the blob for
real, so these tests cover what the reference never could: wrong-passphrase
rejection and the sealed blob actually being opaque.
"""

import asyncio

import pytest

from crdt_enc_tpu.backends import (
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PassphraseKeyCryptor,
    WrongPassphrase,
)
from crdt_enc_tpu.backends.passphrase_keys import unwrap_blob, wrap_blob
from crdt_enc_tpu.core import Core, OpenOptions, gcounter_adapter
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

# cheap KDF for tests: 2**4 iterations instead of 2**14
FAST = dict(kdf_log2_n=4, kdf_r=8, kdf_p=1)


def run(coro):
    return asyncio.run(coro)


def make_opts(remote, passphrase=b"hunter2", create=True):
    return OpenOptions(
        storage=MemoryStorage(remote),
        cryptor=IdentityCryptor(),
        key_cryptor=PassphraseKeyCryptor(passphrase, **FAST),
        adapter=gcounter_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
    )


def test_wrap_roundtrip():
    blob = wrap_blob(b"pw", b"payload", log2_n=4)
    assert unwrap_blob(b"pw", blob) == b"payload"
    # fresh salt per wrap → distinct ciphertexts for identical input
    assert wrap_blob(b"pw", b"payload", log2_n=4) != blob


def test_wrap_rejects_wrong_passphrase():
    blob = wrap_blob(b"pw", b"payload", log2_n=4)
    with pytest.raises(WrongPassphrase):
        unwrap_blob(b"other", blob)


def test_wrap_rejects_garbage_and_hostile_kdf_params():
    with pytest.raises(WrongPassphrase):
        unwrap_blob(b"pw", b"not msgpack at all")
    # a hostile blob demanding an out-of-bounds work factor must be rejected
    # before any scrypt memory is committed
    from crdt_enc_tpu.utils import codec

    hostile = codec.pack([b"\0" * 16, 30, 8, 1, b"x" * 40])
    with pytest.raises(WrongPassphrase):
        unwrap_blob(b"pw", hostile)


def test_max_bounds_kdf_params_are_computable():
    """Every parameter set _params_in_bounds accepts must actually run
    (stay under OpenSSL's 2**31-1 maxmem cap)."""
    from crdt_enc_tpu.backends.passphrase_keys import MAX_LOG2_N, MAX_P, MAX_R

    blob = wrap_blob(b"pw", b"payload", log2_n=MAX_LOG2_N, r=MAX_R, p=MAX_P)
    assert unwrap_blob(b"pw", blob) == b"payload"


def test_integer_salt_rejected_without_allocation():
    """bytes(big_int) would zero-allocate gigabytes pre-auth; the decoder
    must type-check instead of coercing."""
    from crdt_enc_tpu.utils import codec

    hostile = codec.pack([2**33, 4, 8, 1, b"x" * 40])
    with pytest.raises(WrongPassphrase):
        unwrap_blob(b"pw", hostile)


def test_wrap_does_not_leak_plaintext():
    secret = b"super-secret-key-material-0123456789"
    blob = wrap_blob(b"pw", secret, log2_n=4)
    assert secret not in blob


def test_two_replica_convergence_shared_passphrase():
    async def go():
        remote = MemoryRemote()
        c1 = await Core.open(make_opts(remote))
        # the second replica adopts the sealed key set via the passphrase
        c2 = await Core.open(make_opts(remote))
        k1 = c1._data.keys.latest_key()
        k2 = c2._data.keys.latest_key()
        assert k1 is not None and k2 is not None
        assert k1.id == k2.id and k1.material == k2.material
        await c1.apply_ops([c1.with_state(lambda s: s.inc(c1.actor_id, 5))])
        await c2.read_remote()
        assert c2.with_state(lambda s: s.read()) == 5

    run(go())


def test_wrong_passphrase_replica_cannot_join():
    async def go():
        remote = MemoryRemote()
        await Core.open(make_opts(remote))
        with pytest.raises(WrongPassphrase):
            await Core.open(make_opts(remote, passphrase=b"wrong"))

    run(go())


def test_keys_blob_sealed_in_remote_meta():
    """The stored remote metadata must not contain raw key material."""

    async def go():
        remote = MemoryRemote()
        c1 = await Core.open(make_opts(remote))
        key = c1._data.keys.latest_key()
        assert key is not None
        for raw in remote.metas.values():
            assert key.material.content not in bytes(raw)

    run(go())

"""End-to-end replica lifecycle: the multi-replica convergence tests the
reference's architecture enables but never shipped (SURVEY.md §4).

N cores with distinct local storage share one remote (memory dict or
tmpdir); convergence flows purely through stored files — no other channel
exists, exactly like replicas under a file-sync tool.
"""

import asyncio
import uuid

import pytest

from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import (
    Core,
    CoreError,
    OpenOptions,
    gcounter_adapter,
    orset_adapter,
)
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, adapter, create=True):
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter,
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
    )


@pytest.fixture(params=["memory", "fs"])
def storage_factory(request, tmp_path):
    """Returns a () -> Storage factory where all instances share a remote."""
    if request.param == "memory":
        remote = MemoryRemote()
        return lambda: MemoryStorage(remote)
    remote_dir = tmp_path / "remote"
    counter = iter(range(1000))
    return lambda: FsStorage(str(tmp_path / f"local{next(counter)}"), str(remote_dir))


def test_open_requires_create(storage_factory):
    async def go():
        with pytest.raises(CoreError):
            await Core.open(make_opts(storage_factory(), gcounter_adapter(), create=False))

    run(go())


def test_open_persists_identity(storage_factory):
    async def go():
        storage = storage_factory()
        c1 = await Core.open(make_opts(storage, gcounter_adapter()))
        actor = c1.actor_id
        # reopening the same local storage must restore the same actor
        c2 = await Core.open(make_opts(storage, gcounter_adapter(), create=False))
        assert c2.actor_id == actor

    run(go())


def test_key_bootstrap_and_share(storage_factory):
    async def go():
        c1 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        assert c1.info().has_latest_key
        # a second replica joining the same remote adopts the existing key
        c2 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        k1 = c1._data.keys.latest_key()
        k2 = c2._data.keys.latest_key()
        assert k1 is not None and k2 is not None
        assert k1.id == k2.id and k1.material == k2.material

    run(go())


def test_two_replica_convergence(storage_factory):
    async def go():
        c1 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        c2 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        await c1.apply_ops([c1.with_state(lambda s: s.inc(c1.actor_id, 5))])
        await c2.apply_ops([c2.with_state(lambda s: s.inc(c2.actor_id, 7))])
        await c1.read_remote()
        await c2.read_remote()
        assert c1.with_state(lambda s: s.read()) == 12
        assert c2.with_state(lambda s: s.read()) == 12
        assert c1.with_state(canonical_bytes) == c2.with_state(canonical_bytes)

    run(go())


def test_orset_convergence_and_remove(storage_factory):
    async def go():
        c1 = await Core.open(make_opts(storage_factory(), orset_adapter()))
        c2 = await Core.open(make_opts(storage_factory(), orset_adapter()))
        await c1.apply_ops([c1.with_state(lambda s: s.add_ctx(c1.actor_id, b"x"))])
        await c2.read_remote()
        assert c2.with_state(lambda s: s.contains(b"x"))
        await c2.apply_ops([c2.with_state(lambda s: s.rm_ctx(b"x"))])
        await c1.read_remote()
        assert not c1.with_state(lambda s: s.contains(b"x"))
        assert c1.with_state(canonical_bytes) == c2.with_state(canonical_bytes)

    run(go())


def test_compact_roundtrip(storage_factory):
    """The reference's own compacted states couldn't be read back
    (SURVEY.md §3.4 defect 1).  Ours must: compact, then a fresh replica
    joins from the snapshot alone."""

    async def go():
        c1 = await Core.open(make_opts(storage_factory(), orset_adapter()))
        for m in (b"a", b"b", b"c"):
            await c1.apply_ops([c1.with_state(lambda s, m=m: s.add_ctx(c1.actor_id, m))])
        await c1.apply_ops([c1.with_state(lambda s: s.rm_ctx(b"b"))])
        await c1.compact()

        # defect-2 fix: ALL covered op files must be gone, not just the last
        storage = storage_factory()
        assert await storage.list_op_actors() == []
        assert len(await storage.list_state_names()) == 1

        c3 = await Core.open(make_opts(storage_factory(), orset_adapter()))
        await c3.read_remote()
        assert c3.with_state(lambda s: s.members()) == [b"a", b"c"]
        assert c3.with_state(canonical_bytes) == c1.with_state(canonical_bytes)

    run(go())


def test_compact_then_new_ops_resume(storage_factory):
    async def go():
        c1 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        await c1.apply_ops([c1.with_state(lambda s: s.inc(c1.actor_id, 3))])
        await c1.compact()
        # ops continue after compaction; cursors must resume past the snapshot
        await c1.apply_ops([c1.with_state(lambda s: s.inc(c1.actor_id, 4))])
        c2 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        await c2.read_remote()
        assert c2.with_state(lambda s: s.read()) == 7
        # second compaction folds snapshot + tail into one fresh snapshot
        await c2.compact()
        c3 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        await c3.read_remote()
        assert c3.with_state(lambda s: s.read()) == 7

    run(go())


def test_duplicate_read_is_idempotent(storage_factory):
    async def go():
        c1 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        await c1.apply_ops([c1.with_state(lambda s: s.inc(c1.actor_id, 2))])
        c2 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        await c2.read_remote()
        await c2.read_remote()  # replay: version-skew skip must absorb it
        assert c2.with_state(lambda s: s.read()) == 2

    run(go())


def test_meta_files_garbage_collected(storage_factory):
    async def go():
        storage = storage_factory()
        await Core.open(make_opts(storage, gcounter_adapter()))
        await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        # store-then-delete keeps the meta family compact: after both opens
        # settle, each replica folded to few (≤2 with concurrent writers) files
        names = await storage.list_remote_meta_names()
        assert 1 <= len(names) <= 2

    run(go())


def test_concurrent_writers_serialized(storage_factory):
    async def go():
        c1 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))

        async def writer(amount):
            # update() derives the dot under the writer lock — concurrent
            # with_state+apply_ops would race on dot derivation
            await c1.update(lambda s: s.inc(c1.actor_id, amount))

        await asyncio.gather(*(writer(i + 1) for i in range(5)))
        c2 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        await c2.read_remote()
        assert c2.with_state(lambda s: s.read()) == 15

    run(go())


def test_key_rotation_old_data_stays_readable(storage_factory):
    """rotate_key: new writes seal with the new key, old blobs stay
    readable via their recorded key id, and the rotation converges to
    replicas that join later (the LUKS property, README.md:19-25)."""

    async def go():
        c1 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        await c1.update(lambda s: s.inc(c1.actor_id, 3))
        old = c1._data.keys.latest_key()

        new = await c1.rotate_key()
        assert new.id != old.id
        assert c1._data.keys.latest_key().id == new.id
        # the superseded key remains resolvable for old blobs
        assert c1._data.keys.get_key(old.id) is not None

        await c1.update(lambda s: s.inc(c1.actor_id, 4))  # sealed w/ new key

        # a replica joining after the rotation reads both generations
        c2 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        assert c2._data.keys.latest_key().id == new.id
        await c2.read_remote()
        assert c2.with_state(lambda s: s.read()) == 7

        # compaction re-seals everything under the latest key
        await c2.compact()
        c3 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        await c3.read_remote()
        assert c3.with_state(lambda s: s.read()) == 7

    run(go())


def test_rotation_race_min_id_tie_break(storage_factory):
    """Two replicas rotate concurrently: both keys land in the CRDT and
    every replica deterministically agrees on the same latest
    (min-id tie-break, reference key_cryptor.rs:59-70)."""

    async def go():
        c1 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        c2 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        # both rotate without seeing each other's rotation
        k1 = await c1.rotate_key()
        k2 = await c2.rotate_key()
        await c1.read_remote()
        await c2.read_remote()
        expect = min(k1.id, k2.id)
        assert c1._data.keys.latest_key().id == expect
        assert c2._data.keys.latest_key().id == expect
        # writes from both sides remain mutually readable
        await c1.update(lambda s: s.inc(c1.actor_id, 1))
        await c2.update(lambda s: s.inc(c2.actor_id, 2))
        await c1.read_remote()
        await c2.read_remote()
        assert c1.with_state(lambda s: s.read()) == 3
        assert c2.with_state(lambda s: s.read()) == 3

    run(go())


def test_rotation_vs_meta_ingestion_race_keeps_all_keys(storage_factory):
    """Regression: rotate_key's snapshot→register-write cycle suspends in
    the key cryptor's protect step (scrypt takes ~50ms); a remote Keys
    value merged during that window must NOT be causally superseded by
    the stale snapshot — that would permanently drop its key material and
    orphan every blob it sealed.  The _keys_lock serializes the two."""
    import asyncio as aio

    from crdt_enc_tpu.backends.plain_keys import PlainKeyCryptor

    class SlowKeyCryptor(PlainKeyCryptor):
        async def _protect(self, raw):
            await aio.sleep(0.05)  # model the scrypt window
            return raw

    async def go():
        c1 = await Core.open(make_opts(storage_factory(), gcounter_adapter()))
        # B opens BEFORE A's rotation, so B's key snapshot can't contain kA
        opts_b = make_opts(storage_factory(), gcounter_adapter())
        opts_b.key_cryptor = SlowKeyCryptor()
        c2 = await Core.open(opts_b)

        await c1.update(lambda s: s.inc(c1.actor_id, 1))
        kA = await c1.rotate_key()
        await c1.update(lambda s: s.inc(c1.actor_id, 2))  # sealed with kA

        # the race: B rotates (slow protect) while ingesting A's metadata
        await aio.gather(c2.rotate_key(), c2.read_remote())
        await c2.read_remote()
        assert c2._data.keys.get_key(kA.id) is not None, "kA material lost"
        assert c2.with_state(lambda s: s.read()) == 3  # kA blobs readable

        # and A still converges with B's rotation in the mix
        await c1.read_remote()
        assert c1._data.keys.get_key(kA.id) is not None

    run(go())


def test_native_op_scan_matches_python(tmp_path):
    """The C++ bulk op reader must return exactly what the per-file
    Python scan returns, including partial (first > 1) scans."""
    from crdt_enc_tpu.backends.fs import FsStorage

    async def go():
        s = FsStorage(str(tmp_path / "l"), str(tmp_path / "remote"))
        actor = b"\x01" * 16
        blobs = [bytes([i]) * (i * 37 + 1) for i in range(12)]
        for v, b in enumerate(blobs, start=1):
            await s.store_ops(actor, v, b)
        for first in (1, 5, 13):
            files, resume = s._scan_native(actor, first)
            assert resume is None  # run completed natively
            expect = [
                (actor, v, blobs[v - 1])
                for v in range(first, len(blobs) + 1)
            ]
            assert files == expect
            loaded = await s.load_ops([(actor, first)])
            assert loaded == expect

    run(go())


def test_native_op_scan_byte_cap_rounds(tmp_path):
    """A tiny byte cap forces many native read rounds; the result must be
    identical to one unbounded round (progress guaranteed even when a
    single file exceeds the cap)."""
    from crdt_enc_tpu.backends.fs import FsStorage

    async def go():
        s = FsStorage(str(tmp_path / "l"), str(tmp_path / "remote"))
        actor = b"\x02" * 16
        blobs = [bytes([i]) * (200 + i) for i in range(9)]
        for v, b in enumerate(blobs, start=1):
            await s.store_ops(actor, v, b)
        s.NATIVE_SCAN_BYTES = 64  # smaller than every single file
        files, resume = s._scan_native(actor, 1)
        assert resume is None
        assert files == [(actor, v, blobs[v - 1]) for v in range(1, 10)]

    run(go())


def test_native_scan_race_keeps_prefix_and_reprobes(tmp_path, monkeypatch):
    """A failed native bulk read must not discard already-read rounds; the
    per-file scan re-probes the failed round, so a vanished file ends the
    dense run cleanly while other files still load (advisor finding)."""
    from crdt_enc_tpu.backends.fs import FsStorage

    async def go():
        s = FsStorage(str(tmp_path / "l"), str(tmp_path / "remote"))
        actor = b"\x03" * 16
        blobs = [bytes([i]) * 50 for i in range(8)]
        for v, b in enumerate(blobs, start=1):
            await s.store_ops(actor, v, b)
        s.NATIVE_SCAN_BATCH = 3  # several native rounds

        from crdt_enc_tpu import native

        lib = native.load()
        real_read = lib.read_op_files
        fail_from = 4  # fail every round starting at version >= 4

        def racy_read(d, first, n, offsets, sizes, buf):
            if first >= fail_from:
                return -1
            return real_read(d, first, n, offsets, sizes, buf)

        monkeypatch.setattr(lib, "read_op_files", racy_read)
        files, resume = s._scan_native(actor, 1)
        # round 1 (v1-3) succeeded natively; the failed round is handed off
        assert files == [(actor, v, blobs[v - 1]) for v in (1, 2, 3)]
        assert resume == 4
        # load_ops transparently finishes per-file: full result, no loss
        loaded = await s.load_ops([(actor, 1)])
        assert loaded == [
            (actor, v, blobs[v - 1]) for v in range(1, len(blobs) + 1)
        ]

    run(go())


def test_unreadable_op_file_raises_loudly(tmp_path, monkeypatch):
    """A present-but-unreadable op file is a real defect, not a race: the
    scan must raise, not silently truncate the log (reviewer finding).
    Unreadability is simulated by monkeypatching (chmod 0 would not bind
    when tests run as root): the native bulk round fails, and the per-file
    re-probe hits the open error — the exact production sequence."""
    import os as _os

    import pytest

    import crdt_enc_tpu.backends.fs as fsmod
    from crdt_enc_tpu import native
    from crdt_enc_tpu.backends.fs import FsStorage

    async def go():
        s = FsStorage(str(tmp_path / "l"), str(tmp_path / "remote"))
        actor = b"\x05" * 16
        for v in range(1, 6):
            await s.store_ops(actor, v, bytes([v]) * 40)

        lib = native.load()
        real_read = lib.read_op_files

        def failing_read(d, first, n, offsets, sizes, buf):
            if first <= 3 < first + n:
                return -1  # the unreadable file fails the whole bulk round
            return real_read(d, first, n, offsets, sizes, buf)

        real_rf = fsmod._read_file

        def failing_rf(path):
            if path.endswith(_os.sep + "3"):
                raise PermissionError(path)
            return real_rf(path)

        monkeypatch.setattr(lib, "read_op_files", failing_read)
        monkeypatch.setattr(fsmod, "_read_file", failing_rf)
        with pytest.raises(PermissionError):
            await s.load_ops([(actor, 1)])

    run(go())

"""Property tests: CRDT laws under random op histories.

Strategy: generate a causally consistent global op history (ops created
against an oracle state, so remove-contexts observe real dots), then assert

* convergence: any per-actor-order-preserving delivery reaches identical
  canonical bytes,
* merge laws: commutativity, associativity, idempotence of CvRDT merge,
* CmRDT/CvRDT agreement: folding ops equals merging per-replica states.

Per-actor ordering is the framework's delivery contract (op files are applied
in version order per actor, cf. SURVEY.md §3.3); cross-actor interleaving is
adversarial (chosen by hypothesis).
"""

import uuid

from _hyp import given, settings, st  # hypothesis, or skip-stubs

from crdt_enc_tpu.models import (
    GCounter,
    LWWMap,
    MVReg,
    ORSet,
    PNCounter,
    canonical_bytes,
)

ACTORS = [uuid.UUID(int=i + 1).bytes for i in range(4)]
MEMBERS = [b"a", b"b", b"c"]


def interleave(streams, rng: "st.DataObject"):
    """Draw one per-stream-order-preserving interleaving."""
    streams = [list(s) for s in streams if s]
    out = []
    while streams:
        i = rng.draw(st.integers(0, len(streams) - 1))
        out.append(streams[i].pop(0))
        if not streams[i]:
            streams.pop(i)
    return out


# ---- ORSet ---------------------------------------------------------------

orset_script = st.lists(
    st.tuples(
        st.integers(0, len(ACTORS) - 1),
        st.sampled_from(["add", "rm"]),
        st.integers(0, len(MEMBERS) - 1),
    ),
    max_size=24,
)


def orset_history(script):
    """Run the script against an oracle; return (oracle, per-actor op streams)."""
    oracle = ORSet()
    streams = {a: [] for a in ACTORS}
    for actor_i, kind, member_i in script:
        actor, member = ACTORS[actor_i], MEMBERS[member_i]
        if kind == "add":
            op = oracle.add_ctx(actor, member)
        else:
            op = oracle.rm_ctx(member)
            if op.ctx.is_empty():
                continue  # removing nothing is a no-op, not an op file
        oracle.apply(op)
        streams[actor].append(op)
    return oracle, [s for s in streams.values() if s]


@settings(max_examples=150, deadline=None)
@given(orset_script, st.data())
def test_orset_convergence_under_interleaving(script, data):
    oracle, streams = orset_history(script)
    replica = ORSet()
    for op in interleave(streams, data):
        replica.apply(op)
    assert canonical_bytes(replica) == canonical_bytes(oracle)


@settings(max_examples=150, deadline=None)
@given(orset_script, orset_script, st.data())
def test_orset_merge_laws(script_a, script_b, data):
    # two divergent histories from a (possibly empty) shared prefix
    _, streams_a = orset_history(script_a)
    _, streams_b = orset_history(script_b)
    sa, sb = ORSet(), ORSet()
    for op in interleave(streams_a, data):
        sa.apply(op)
    for op in interleave(streams_b, data):
        sb.apply(op)

    ab = ORSet.from_obj(sa.to_obj())
    ab.merge(sb)
    ba = ORSet.from_obj(sb.to_obj())
    ba.merge(sa)
    assert canonical_bytes(ab) == canonical_bytes(ba)  # commutative

    again = ORSet.from_obj(ab.to_obj())
    again.merge(sb)
    again.merge(sa)
    assert canonical_bytes(again) == canonical_bytes(ab)  # idempotent

    # associativity with a third state
    sc = ORSet()
    sc.apply(sc.add_ctx(ACTORS[0], MEMBERS[0]))
    left = ORSet.from_obj(sa.to_obj())
    left.merge(sb)
    left.merge(sc)
    right_inner = ORSet.from_obj(sb.to_obj())
    right_inner.merge(sc)
    right = ORSet.from_obj(sa.to_obj())
    right.merge(right_inner)
    assert canonical_bytes(left) == canonical_bytes(right)


@settings(max_examples=100, deadline=None)
@given(orset_script, st.data())
def test_orset_fold_equals_merge(script, data):
    oracle, streams = orset_history(script)
    # each actor's ops applied on its own replica (per-actor causal order),
    # then states merged in a random order
    replicas = []
    for stream in streams:
        r = ORSet()
        for op in stream:
            r.apply(op)
        replicas.append(r)
    merged = ORSet()
    order = interleave([[i] for i in range(len(replicas))], data)
    for i in order:
        merged.merge(replicas[i])
    assert sorted(map(repr, merged.members())) == sorted(map(repr, oracle.members()))


# ---- counters ------------------------------------------------------------


@settings(max_examples=100, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(0, len(ACTORS) - 1),
            st.sampled_from(["inc", "dec"]),
            st.integers(1, 5),
        ),
        max_size=30,
    ),
    st.data(),
)
def test_pncounter_convergence(script, data):
    oracle = PNCounter()
    streams = {a: [] for a in ACTORS}
    total = 0
    for actor_i, kind, steps in script:
        actor = ACTORS[actor_i]
        op = oracle.inc(actor, steps) if kind == "inc" else oracle.dec(actor, steps)
        total += steps if kind == "inc" else -steps
        oracle.apply(op)
        streams[actor].append(op)
    replica = PNCounter()
    for op in interleave(list(streams.values()), data):
        replica.apply(op)
    assert replica.read() == oracle.read() == total
    assert canonical_bytes(replica) == canonical_bytes(oracle)
    merged = PNCounter.from_obj(replica.to_obj())
    merged.merge(oracle)
    assert canonical_bytes(merged) == canonical_bytes(oracle)


@settings(max_examples=100, deadline=None)
@given(st.lists(st.tuples(st.integers(0, 3), st.integers(1, 4)), max_size=20))
def test_gcounter_merge_commutes(script):
    a, b = GCounter(), GCounter()
    for actor_i, steps in script:
        target = a if actor_i % 2 == 0 else b
        target.apply(target.inc(ACTORS[actor_i], steps))
    ab = GCounter.from_obj(a.to_obj())
    ab.merge(b)
    ba = GCounter.from_obj(b.to_obj())
    ba.merge(a)
    assert canonical_bytes(ab) == canonical_bytes(ba)
    assert ab.read() == ba.read()


# ---- MVReg ---------------------------------------------------------------


@settings(max_examples=100, deadline=None)
@given(
    st.lists(
        st.one_of(
            st.tuples(st.just("write"), st.integers(0, 3), st.integers(0, 100)),
            st.tuples(st.just("sync"), st.integers(0, 3), st.integers(0, 3)),
        ),
        max_size=20,
    ),
    st.data(),
)
def test_mvreg_convergence(script, data):
    regs = [MVReg() for _ in ACTORS]
    for ev in script:
        if ev[0] == "write":
            _, i, val = ev
            regs[i].apply(regs[i].write_ctx(ACTORS[i], val))
        else:
            _, i, j = ev
            regs[i].merge(regs[j])
    # merge everything into one in two different orders
    order = data.draw(st.permutations(range(len(regs))))
    m1, m2 = MVReg(), MVReg()
    for i in order:
        m1.merge(regs[i])
    for i in reversed(order):
        m2.merge(regs[i])
    assert canonical_bytes(m1) == canonical_bytes(m2)
    m1.merge(m2)
    assert canonical_bytes(m1) == canonical_bytes(m2)  # idempotent


# ---- LWWMap --------------------------------------------------------------


@settings(max_examples=100, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(0, 3),  # actor
            st.integers(0, 2),  # key
            st.integers(0, 20),  # ts
            st.integers(0, 5),  # value
            st.booleans(),  # tombstone
        ),
        max_size=25,
    ),
    st.data(),
)
def test_lwwmap_convergence(script, data):
    ops = []
    for actor_i, key_i, ts, val, tomb in script:
        m = LWWMap()
        op = (
            m.delete(key_i, ts, ACTORS[actor_i])
            if tomb
            else m.put(key_i, ts, ACTORS[actor_i], val)
        )
        ops.append(op)
    order = data.draw(st.permutations(range(len(ops))))
    m1, m2 = LWWMap(), LWWMap()
    for i in order:
        m1.apply(ops[i])
    for op in ops:
        m2.apply(op)
    assert canonical_bytes(m1) == canonical_bytes(m2)

"""LockBox mechanism tests (utils/lockbox.py): the reference's
compile-time no-await guarantee (crdt-enc/src/utils/mod.rs:165-195) as a
runtime one — coroutine rejection, borrow revocation, escape detection —
and its enforcement at the core's with_state/update entry points."""

from __future__ import annotations

import asyncio

import pytest

from crdt_enc_tpu.utils.lockbox import (
    LockBox,
    LockBoxViolation,
    assert_outside_section,
    in_section,
)


class Box:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
        return self.n


def test_sync_section_works():
    lb = LockBox(Box())
    assert lb.with_(lambda b: b.bump()) == 1
    assert lb.with_(lambda b: b.n) == 1


def test_rejects_coroutine_function():
    lb = LockBox(Box())

    async def bad(b):
        return b.n

    with pytest.raises(TypeError, match="synchronous"):
        lb.with_(bad)


def test_rejects_returned_awaitable():
    lb = LockBox(Box())
    made = []

    def sneaky(b):
        async def inner():
            return b.n

        coro = inner()
        made.append(coro)
        return coro

    with pytest.raises(TypeError, match="suspendable"):
        lb.with_(sneaky)
    # the rejected coroutine was never awaited by design — close it so
    # the interpreter doesn't warn at GC time
    made[0].close()


def test_rejects_returned_generator():
    lb = LockBox(Box())

    def sneaky(b):
        def gen():
            yield b.n

        return gen()

    with pytest.raises(TypeError, match="suspendable"):
        lb.with_(sneaky)


def test_escaped_borrow_raises_on_use():
    lb = LockBox(Box())
    leaked = []
    lb.with_(lambda b: leaked.append(b))
    with pytest.raises(LockBoxViolation):
        leaked[0].bump()
    with pytest.raises(LockBoxViolation):
        _ = leaked[0].n
    with pytest.raises(LockBoxViolation):
        leaked[0].n = 5


def test_borrow_mutations_hit_real_value():
    box = Box()
    lb = LockBox(box)
    lb.with_(lambda b: setattr(b, "n", 41))
    assert box.n == 41
    lb.with_(lambda b: b.bump())
    assert box.n == 42


def test_section_depth_and_guard():
    lb = LockBox(Box())
    assert not in_section()
    seen = []
    lb.with_(lambda b: seen.append(in_section()))
    assert seen == [True]
    assert not in_section()
    assert_outside_section("test await")  # no raise outside

    def inner(_b):
        with pytest.raises(LockBoxViolation):
            assert_outside_section("awaiting storage")

    lb.with_(inner)


def test_core_with_state_enforces(tmp_path):
    from crdt_enc_tpu.backends.identity_crypto import IdentityCryptor
    from crdt_enc_tpu.backends.memory import MemoryStorage
    from crdt_enc_tpu.backends.plain_keys import PlainKeyCryptor
    from crdt_enc_tpu.core.adapters import orset_adapter
    from crdt_enc_tpu.core.core import Core, OpenOptions
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    async def run():
        core = await Core.open(OpenOptions(
            storage=MemoryStorage(),
            cryptor=IdentityCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
        ))
        await core.update(lambda s: s.add_ctx(core.actor_id, b"x"))
        assert core.with_state(lambda s: s.members()) == [b"x"]

        async def bad(s):
            return s.members()

        with pytest.raises(TypeError):
            core.with_state(bad)
        with pytest.raises(TypeError):
            await core.update(bad)

        # the borrow must not survive the section
        leak = []
        core.with_state(lambda s: leak.append(s))
        with pytest.raises(LockBoxViolation):
            leak[0].members()

    asyncio.run(run())


def test_borrow_forwards_protocol_dunders():
    class Seq:
        def __init__(self):
            self.items = [1, 2, 3]

        def __len__(self):
            return len(self.items)

        def __iter__(self):
            return iter(self.items)

        def __contains__(self, x):
            return x in self.items

        def __getitem__(self, i):
            return self.items[i]

        def __eq__(self, other):
            return isinstance(other, Seq) and self.items == other.items

    lb = LockBox(Seq())
    other = Seq()
    out = lb.with_(
        lambda s: (len(s), list(s), 2 in s, s[1], s == other, bool(s))
    )
    assert out == (3, [1, 2, 3], True, 2, True, True)

"""The streaming Pallas S-way merge must equal the XLA tree reduction
(and both equal the host CvRDT merge).  On CPU the kernel runs in
interpreter mode — semantics only; the bandwidth win is a TPU property."""

import numpy as np
import pytest
from _hyp import given, settings, st  # hypothesis, or skip-stubs

from crdt_enc_tpu import ops as K
from crdt_enc_tpu.models import ORSet, canonical_bytes
from crdt_enc_tpu.ops.pallas_merge import orset_merge_many_pallas

from test_ops_kernels import fixed_vocabs, orset_script, run_script


def stacked_planes(states):
    members, replicas = fixed_vocabs()
    planes = [K.orset_state_to_planes(s, members, replicas) for s in states]
    return (
        np.stack([p[0] for p in planes]),
        np.stack([p[1] for p in planes]),
        np.stack([p[2] for p in planes]),
        members,
        replicas,
    )


@settings(max_examples=25, deadline=None)
@given(st.lists(orset_script, min_size=1, max_size=6))
def test_pallas_merge_matches_tree_and_host(scripts):
    states = [run_script(s)[0] for s in scripts]
    host = ORSet()
    for s in states:
        host.merge(s)

    clocks, adds, rms, members, replicas = stacked_planes(states)
    ct, at_, rt = K.orset_merge_many(clocks, adds, rms, impl="tree")
    cp, ap, rp = orset_merge_many_pallas(clocks, adds, rms, interpret=True)

    np.testing.assert_array_equal(np.asarray(ct), np.asarray(cp))
    np.testing.assert_array_equal(np.asarray(at_), np.asarray(ap))
    np.testing.assert_array_equal(np.asarray(rt), np.asarray(rp))

    device = K.orset_planes_to_state(
        np.asarray(cp), np.asarray(ap), np.asarray(rp), members, replicas
    )
    assert canonical_bytes(device) == canonical_bytes(host)


def test_pallas_merge_unaligned_shapes():
    """E and R far from the (8, 128) tile: padding must be invisible."""
    rng = np.random.default_rng(9)
    S, E, R = 5, 13, 37
    clocks = rng.integers(0, 50, (S, R)).astype(np.int32)
    adds = np.zeros((S, E, R), np.int32)
    rms = np.zeros((S, E, R), np.int32)
    for s in range(S):
        # dots below the clock (live adds), horizons below the clock
        mask = rng.random((E, R)) < 0.3
        adds[s] = np.where(mask, rng.integers(1, 50, (E, R)), 0)
        adds[s] = np.minimum(adds[s], clocks[s][None, :])
        rmask = rng.random((E, R)) < 0.1
        rms[s] = np.where(rmask & ~mask, rng.integers(1, 50, (E, R)), 0)
        rms[s] = np.minimum(rms[s], clocks[s][None, :] + 5)
        # normalize as the fold would
        adds[s] = np.where(adds[s] > rms[s], adds[s], 0)
        rms[s] = np.where(rms[s] > clocks[s][None, :], rms[s], 0)

    ct, at_, rt = K.orset_merge_many(clocks, adds, rms, impl="tree")
    cp, ap, rp = orset_merge_many_pallas(clocks, adds, rms, interpret=True)
    np.testing.assert_array_equal(np.asarray(ct), np.asarray(cp))
    np.testing.assert_array_equal(np.asarray(at_), np.asarray(ap))
    np.testing.assert_array_equal(np.asarray(rt), np.asarray(rp))


def test_pallas_merge_single_state_is_identity():
    clocks = np.array([[3, 0, 1]], np.int32)
    adds = np.array([[[3, 0, 0], [0, 0, 1]]], np.int32)
    rms = np.zeros((1, 2, 3), np.int32)
    c, a, r = orset_merge_many_pallas(clocks, adds, rms, interpret=True)
    np.testing.assert_array_equal(np.asarray(c), clocks[0])
    np.testing.assert_array_equal(np.asarray(a), adds[0])
    np.testing.assert_array_equal(np.asarray(r), rms[0])

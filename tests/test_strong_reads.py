"""Strong-read tier (ISSUE 15, docs/strong_reads.md).

The guarantee is byte-exact, not shape-checked: every strong read here
is compared against a pure-Python oracle fold of exactly the cut it
names (the sim/linearize.py checker reused as a unit oracle), across
memory AND fs backends and through the FoldService per-tenant endpoint.
The membership policy, the refusal taxonomy, the freshness-wait
protocol (core + daemon), the wall-clock-aware daemon pacing, the
watermark-age surfacing, and the PR-6 "membership growth legitimately
collapses the watermark" caveat each get a dedicated regression.
"""

import asyncio
import json
import pathlib
import time

import pytest

from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import Core, OpenOptions, gcounter_adapter, orset_adapter
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.models.orset import ORSet
from crdt_enc_tpu.models.vclock import VClock
from crdt_enc_tpu.read import MembershipPolicy, StalenessError
from crdt_enc_tpu.sim.linearize import check_strong_read, oracle_fold
from crdt_enc_tpu.utils import trace

REPO = pathlib.Path(__file__).parent.parent


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, adapter=None, **kw):
    kw.setdefault("create", True)
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter if adapter is not None else orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        **kw,
    )


async def _write(core, member, oplog=None):
    """One add through the production writer path, its plaintext
    recorded for the oracle."""
    ops = await core.update(lambda s: s.add_ctx(core.actor_id, member))
    if oplog is not None:
        oplog[(core.actor_id, core._local_meta.last_op_version)] = [
            op.to_obj() for op in ops
        ]
    return ops


# ---- membership policy ----------------------------------------------------

A = b"\xaa" * 16
B = b"\xbb" * 16
C = b"\xcc" * 16


def test_policy_expected_pins_the_denominator():
    pol = MembershipPolicy(expected=[B])
    # B published nothing: denominator is {A(self), B}
    assert pol.denominator(A, {}, VClock({A: 3, C: 5})) == {A, B}
    assert pol.observe(A, {}, VClock({A: 3, C: 5})) == {A, B}
    # C produced ops but is NOT expected: it never joins the min
    assert C not in pol.denominator(A, {C: VClock({C: 5})}, VClock({C: 5}))


def test_policy_silence_quarantine_and_revival():
    pol = MembershipPolicy(silent_after=2)
    union = VClock({A: 1, B: 1})
    row = {B: VClock({B: 1})}
    # B's cursor never advances: after the first sighting, two more
    # silent observations put it past silent_after -> quarantined
    for _ in range(4):
        eff = pol.observe(A, row, union)
    assert B not in eff and pol.excluded == frozenset({B})
    assert pol.summary()["excluded"] == [B.hex()]
    # B's published cursor advances -> re-admitted
    eff = pol.observe(A, {B: VClock({B: 2})}, union)
    assert B in eff and pol.excluded == frozenset()
    # self is never excluded, however silent
    assert A in eff


def test_policy_off_by_default_matches_pr6_denominator():
    pol = MembershipPolicy()
    row = {B: VClock({B: 1})}
    union = VClock({A: 1, B: 1, C: 2})
    assert pol.observe(A, row, union) == {A, B, C}
    assert pol.summary() == {
        "expected": None, "silent_after": 0, "excluded": [],
    }


# ---- the stable prefix: exactness, taxonomy, waits ------------------------


def test_strong_read_exact_oracle_fold_memory():
    async def scenario():
        remote = MemoryRemote()
        a = await Core.open(make_opts(MemoryStorage(remote)))
        b = await Core.open(make_opts(MemoryStorage(remote)))
        oplog: dict = {}
        await _write(a, b"x", oplog)
        await _write(b, b"y", oplog)
        await a.compact()  # publishes a's cursor (covers b's op)
        res = await b.read(linearizable=True)
        assert res.consistency == "strong"
        defect = check_strong_read(oplog, res, None)
        assert defect is None, defect
        # monotone on a second read
        res2 = await b.read(linearizable=True)
        assert check_strong_read(oplog, res2, res.cursor) is None
        # eventual tier never refuses and reports its tier honestly
        ev = await b.read()
        assert ev.consistency == "eventual" and ev.view is None
        # point lookups answer from the stable prefix
        assert await b.contains(b"x", linearizable=True)
        assert await b.contains(b"y", linearizable=True)
        assert not await b.contains(b"zzz", linearizable=True)

    run(scenario())


def test_strong_read_exact_oracle_fold_fs(tmp_path):
    async def scenario():
        remote = str(tmp_path / "remote")
        a = await Core.open(
            make_opts(FsStorage(str(tmp_path / "a"), remote))
        )
        b = await Core.open(
            make_opts(FsStorage(str(tmp_path / "b"), remote))
        )
        oplog: dict = {}
        for m in (b"x", b"y", b"z"):
            await _write(a, m, oplog)
        await _write(b, b"w", oplog)
        await a.compact()
        res = await b.read(linearizable=True)
        defect = check_strong_read(oplog, res, None)
        assert defect is None, defect
        assert sorted(b._strong().state.members()) == [
            b"w", b"x", b"y", b"z",
        ]

    run(scenario())


def test_refusal_taxonomy_uncovered_target_and_lag():
    async def scenario():
        remote = MemoryRemote()
        a = await Core.open(make_opts(MemoryStorage(remote)))
        b = await Core.open(make_opts(MemoryStorage(remote)))
        await _write(a, b"x")
        await _write(b, b"y")  # unpublished: holds the watermark back
        await b.read_remote()
        # b's own write cannot be covered until a folds+publishes it
        with pytest.raises(StalenessError) as ei:
            await b.read(
                linearizable=True,
                min_cursor=VClock({b.actor_id: 1}),
            )
        assert ei.value.reason == "uncovered_target"
        with pytest.raises(StalenessError) as ei:
            await b.read(linearizable=True, max_lag=0)
        assert ei.value.reason == "lag_exceeded"
        # the message/status name WHO holds the watermark back
        assert ei.value.status["holdouts"]
        trace.reset()
        try:
            await b.read(linearizable=True, max_lag=0)
        except StalenessError:
            pass
        snap = trace.snapshot()
        assert snap["counters"]["read_strong_refusals"] == 1
        assert snap["counters"]["read_strong_total"] == 1

    run(scenario())


def test_await_stable_read_your_writes_and_timeout():
    async def scenario():
        remote = MemoryRemote()
        a = await Core.open(make_opts(MemoryStorage(remote)))
        b = await Core.open(make_opts(MemoryStorage(remote)))
        oplog: dict = {}
        # a must be VISIBLE (an op producer) to join the denominator —
        # otherwise b is alone and its own write is trivially stable
        await _write(a, b"theirs", oplog)
        await _write(b, b"mine", oplog)
        await b.read_remote()
        target = VClock({b.actor_id: 1})
        # deterministic-timeout seam: counted clock, no peer progress
        ticks = [0.0]

        def clock():
            ticks[0] += 1.0
            return ticks[0]

        with pytest.raises(StalenessError) as ei:
            await b.await_stable(target, timeout_s=3, clock=clock,
                                 poll_interval_s=0.0)
        assert ei.value.reason == "timeout"
        # peer folds + publishes -> the wait resolves and RYW holds
        await a.compact()
        view = await b.await_stable(target, timeout_s=5,
                                    poll_interval_s=0.0)
        assert view.covers(target)
        res = await b.read(linearizable=True, min_cursor=target)
        assert check_strong_read(oplog, res, None, ryw_target=target) \
            is None

    run(scenario())


def test_gc_gap_wedges_then_recovers_via_stable_snapshot():
    """Op files GC'd into a snapshot whose cursor exceeds the watermark
    leave the prefix honestly wedged (``gc_gap``); the moment the
    watermark covers the snapshot, the frontier jumps — monotone
    throughout."""

    async def scenario():
        remote = MemoryRemote()
        a = await Core.open(make_opts(MemoryStorage(remote)))
        b = await Core.open(make_opts(MemoryStorage(remote)))
        reader = await Core.open(make_opts(MemoryStorage(remote)))
        oplog: dict = {}
        await _write(a, b"x", oplog)
        r0 = await reader.read(linearizable=True)
        assert r0.cursor.get(a.actor_id) == 1  # a-only remote: stable
        await _write(b, b"y", oplog)  # b joins: watermark now needs b
        await _write(a, b"z", oplog)
        # a compacts: folds everything, GCs ALL op files; its snapshot
        # cursor covers b's op, which b never published -> unstable
        await a.compact()
        r1 = await reader.read(linearizable=True)
        # monotone: the frontier never regressed despite the collapse
        assert r1.cursor.get(a.actor_id) >= r0.cursor.get(a.actor_id)
        # b's op file was GC'd into a's snapshot, whose cursor exceeds
        # the watermark (b never published): honestly wedged, not lost
        assert r1.view.wedged.get(b.actor_id.hex()) == "gc_gap"
        assert r1.view.lag > 0
        # b publishes -> snapshot becomes stable -> frontier jumps
        await b.compact()
        r2 = await reader.read(linearizable=True)
        assert r2.view.wedged == {}
        defect = check_strong_read(oplog, r2, r1.cursor)
        assert defect is None, defect
        assert sorted(reader._strong().state.members()) == [
            b"x", b"y", b"z",
        ]

    run(scenario())


def test_prefix_survives_warm_reopen_and_rebuilds_cold(tmp_path):
    async def scenario():
        remote = str(tmp_path / "remote")
        local = str(tmp_path / "dev")
        a = await Core.open(make_opts(FsStorage(local, remote)))
        oplog: dict = {}
        for m in (b"p", b"q"):
            await _write(a, m, oplog)
        res = await a.read(linearizable=True)
        await a.compact()  # reseals the checkpoint WITH the b"sp" slot
        frontier = a._strong().cursor.copy()
        # warm reopen: the prefix is restored, no remote read needed
        warm = await Core.open(
            make_opts(FsStorage(local, remote), create=False)
        )
        assert warm.opened_from_checkpoint
        assert warm._stable is not None
        assert warm._stable.cursor == frontier
        res_w = await warm.read(linearizable=True)
        assert check_strong_read(oplog, res_w, res.cursor) is None
        # cold reopen: a fresh session rebuilds from storage and
        # reaches the same bytes
        cold = await Core.open(
            make_opts(FsStorage(local, remote), create=False,
                      checkpoint=False)
        )
        assert cold._stable is None
        res_c = await cold.read(linearizable=True)
        assert canonical_bytes(ORSet.from_obj(res_c.obj)) == \
            canonical_bytes(ORSet.from_obj(res_w.obj))

    run(scenario())


def test_value_lookup_on_counter_and_type_refusal():
    async def scenario():
        remote = MemoryRemote()
        g = await Core.open(
            make_opts(MemoryStorage(remote), adapter=gcounter_adapter())
        )
        await g.update(lambda s: s.inc(g.actor_id))
        await g.update(lambda s: s.inc(g.actor_id))
        assert await g.value() == 2
        assert await g.value(linearizable=True) == 2
        with pytest.raises(TypeError):
            await g.contains(b"x")

    run(scenario())


# ---- membership collapse-then-recover (the PR-6 caveat, end to end) -------


def test_watermark_collapse_then_recover_with_stale_checkpoint(tmp_path):
    """ISSUE-15 satellite: membership growth legitimately collapses the
    watermark (a newly heard-from replica drags the min down) and a
    stale-checkpoint reopen replays through the collapse — pinned end
    to end: the watermark view collapses, the EXPOSED frontier never
    regresses, and recovery converges byte-exactly."""

    async def scenario():
        remote = str(tmp_path / "remote")
        rdr_local = str(tmp_path / "reader")
        oplog: dict = {}
        a = await Core.open(
            make_opts(FsStorage(str(tmp_path / "a"), remote))
        )
        reader = await Core.open(make_opts(FsStorage(rdr_local, remote)))
        # phase 1: single producer -> everything it wrote is stable
        for m in (b"one", b"two"):
            await _write(a, m, oplog)
        r1 = await reader.read(linearizable=True)
        assert r1.cursor.get(a.actor_id) == 2
        await reader.save_checkpoint()  # the soon-to-be-stale resume point
        # phase 2: membership growth — B writes, publishes nothing
        b = await Core.open(
            make_opts(FsStorage(str(tmp_path / "b"), remote))
        )
        await _write(b, b"three", oplog)
        await _write(a, b"four", oplog)
        r2 = await reader.read(linearizable=True)
        # the watermark for a's entries collapsed (B's row is unknown)…
        assert r2.view.watermark.get(a.actor_id, 0) < 4
        # …but the exposed frontier is monotone
        assert check_strong_read(oplog, r2, r1.cursor) is None
        # phase 3: recovery — both publish cursors (the reader observes
        # each publication before the next compact GCs the snapshot
        # carrying it: cursor knowledge lives in snapshots)
        await a.compact()
        await reader.read_remote()
        await b.compact()
        r3 = await reader.read(linearizable=True)
        assert check_strong_read(oplog, r3, r2.cursor) is None
        assert r3.cursor.get(a.actor_id) == 3  # 2 writes + compact? no:
        # a wrote one/two/four = 3 op files; all stable now
        assert sorted(reader._strong().state.members()) == [
            b"four", b"one", b"three", b"two",
        ]
        # stale-checkpoint reopen: the phase-1 checkpoint replays into
        # the phase-3 world — warm open restores the OLD frontier, the
        # next strong read advances it monotonically to full coverage
        stale = await Core.open(
            make_opts(FsStorage(rdr_local, remote), create=False)
        )
        restored = (
            stale._stable.cursor.copy() if stale._stable is not None
            else VClock()
        )
        rs0 = await stale.read(linearizable=True)
        assert check_strong_read(oplog, rs0, restored) is None
        # the snapshot that carried a's cursor row was GC'd by b's
        # compact, so the stale reader honestly wedges below full
        # coverage until a publishes again — then it converges to the
        # same bytes as the always-online reader
        await a.compact()
        rs = await stale.read(linearizable=True)
        assert check_strong_read(oplog, rs, rs0.cursor) is None
        assert canonical_bytes(ORSet.from_obj(rs.obj)) == \
            canonical_bytes(ORSet.from_obj(r3.obj))

    run(scenario())


# ---- serving layer --------------------------------------------------------


def test_fold_service_strong_read_matches_core():
    from crdt_enc_tpu.serve import FoldService, ServeConfig

    async def scenario():
        remote = MemoryRemote()
        tenant = await Core.open(make_opts(MemoryStorage(remote)))
        writer = await Core.open(make_opts(MemoryStorage(remote)))
        oplog: dict = {}
        await _write(writer, b"served", oplog)
        service = FoldService([tenant], ServeConfig(seal_empty=True))
        await service.run_cycle()
        trace.reset()
        res = await service.read_strong(tenant, refresh=False)
        assert trace.snapshot()["counters"]["serve_strong_reads"] == 1
        defect = check_strong_read(oplog, res, None)
        assert defect is None, defect
        # the endpoint refuses exactly like the core
        with pytest.raises(StalenessError):
            await service.read_strong(
                tenant, min_cursor=VClock({b"\x01" * 16: 9})
            )
        service.close()
        with pytest.raises(RuntimeError):
            await service.read_strong(tenant)

    run(scenario())


# ---- daemon: freshness waits, laggard priority, wall-clock pacing ---------


def _daemon(tenants, clock=None, **cfg_kw):
    from crdt_enc_tpu.serve import DaemonConfig, FleetDaemon, ServeConfig

    cfg = DaemonConfig(serve=ServeConfig(seal_empty=True), **cfg_kw)
    return FleetDaemon(tenants, cfg, clock=clock)


def test_daemon_waiter_jumps_the_queue_and_resolves():
    async def scenario():
        remote = MemoryRemote()
        tenant = await Core.open(make_opts(MemoryStorage(remote)))
        writer = await Core.open(make_opts(MemoryStorage(remote)))
        await _write(writer, b"w")
        # nothing is "due" by pressure: huge idle cadence, big backlog
        # threshold — only the waiter can make t0 due
        d = _daemon([tenant], min_backlog_files=99, max_idle_cycles=99)
        await d.run_cycle()  # baseline: statuses + last_sealed
        r = await d.run_cycle()
        assert r["selected"] == []  # pinned: nothing due without a waiter
        target = VClock({writer.actor_id: 1})

        async def driver():
            for _ in range(3):
                await d.run_cycle()
                await asyncio.sleep(0)

        view, _ = await asyncio.gather(
            d.await_stable("t0", target, timeout_s=10), driver()
        )
        assert view.covers(target)
        assert d.health()["waiters"] == 0
        # the waiter made the tenant due (it was selected for a cycle)
        assert any(
            "t0" in rep.get("selected", [])
            for rep in [d.last_cycle_report]
        ) or view.covers(target)
        with pytest.raises(KeyError):
            await d.await_stable("nope", target)
        await d.drain()

    run(scenario())


def test_eventual_read_rejects_strong_only_constraints():
    async def scenario():
        core = await Core.open(make_opts(MemoryStorage(MemoryRemote())))
        with pytest.raises(ValueError):
            await core.read(max_lag=0)
        with pytest.raises(ValueError):
            await core.read(min_cursor=VClock({A: 1}))

    run(scenario())


def test_daemon_evict_and_discard_fail_pending_waiters():
    async def scenario():
        remote = MemoryRemote()
        t0 = await Core.open(make_opts(MemoryStorage(remote)))
        t1 = await Core.open(make_opts(MemoryStorage(MemoryRemote())))
        d = _daemon([t0, t1])
        w0 = asyncio.create_task(
            d.await_stable("t0", VClock({b"\x01" * 16: 1}), timeout_s=60)
        )
        w1 = asyncio.create_task(
            d.await_stable("t1", VClock({b"\x01" * 16: 1}), timeout_s=60)
        )
        await asyncio.sleep(0)
        await d.evict("t0")
        with pytest.raises(StalenessError) as ei:
            await w0
        assert "evicted" in str(ei.value)
        await d.discard("t1")
        with pytest.raises(StalenessError) as ei:
            await w1
        assert "discarded" in str(ei.value)
        assert d.health()["waiters"] == 0
        await d.drain()

    run(scenario())


def test_daemon_waiter_tier_beats_arbitrarily_large_laggards():
    """A flat score boost can be crowded out by a big enough laggard;
    the waiter must be a separate sort TIER — pinned with batch=1 and
    a never-sampled (score=inf) competitor."""
    from crdt_enc_tpu.serve.daemon import TenantEntry

    async def scenario():
        remote = MemoryRemote()
        waiting = await Core.open(make_opts(MemoryStorage(remote)))
        laggard = await Core.open(make_opts(MemoryStorage(MemoryRemote())))
        d = _daemon([waiting, laggard], batch=1)
        # laggard never sampled -> _score second element is inf
        d.entry("t1").core.last_replication_status = None
        fut = asyncio.get_running_loop().create_future()
        d._waiters["t0"] = [(VClock(), fut)]
        target = d._slo_target()
        assert d._score(d.entry("t0"), target) > d._score(
            d.entry("t1"), target
        )
        report = await d.run_cycle()
        assert report["selected"][0] == "t0"
        await d.drain()

    run(scenario())


def test_daemon_drain_fails_pending_waiters_loudly():
    async def scenario():
        remote = MemoryRemote()
        tenant = await Core.open(make_opts(MemoryStorage(remote)))
        d = _daemon([tenant])
        task = asyncio.create_task(
            d.await_stable(
                "t0", VClock({b"\x01" * 16: 1}), timeout_s=60
            )
        )
        await asyncio.sleep(0)
        await d.drain()
        with pytest.raises(StalenessError) as ei:
            await task
        assert ei.value.reason == "timeout"

    run(scenario())


def test_daemon_wall_clock_interval_follows_slo_burn():
    async def scenario():
        t = [0.0]

        def clock():
            return t[0]

        d = _daemon([], clock=clock, interval_auto=True,
                    interval_min_s=0.1, interval_max_s=10.0,
                    burn_window_s=30.0)
        # no samples: no burn -> the relaxed end
        assert d.next_interval() == pytest.approx(10.0)
        # a fully-burning window -> the aggressive end
        d._burn_window[:] = [(1.0, 5, 0)]
        t[0] = 2.0
        assert d.next_interval() == pytest.approx(0.1)
        # samples age out of the window (the deterministic clock seam)
        t[0] = 40.0
        d._note_burn(64.0)
        assert d._burn_window == [(40.0, 0, 0)]
        assert d.next_interval() == pytest.approx(10.0)
        # fixed pacing unless opted in
        d.config.interval_auto = False
        assert d.next_interval() == d.config.interval_s
        await d.drain()

    run(scenario())


def test_daemon_health_uses_clock_seam():
    async def scenario():
        t = [100.0]
        d = _daemon([], clock=lambda: t[0])
        t[0] = 107.5
        assert d.health()["uptime_s"] == pytest.approx(7.5)
        await d.drain()

    run(scenario())


# ---- observability: watermark age + membership surfacing ------------------


def test_live_healthz_watermark_age():
    from crdt_enc_tpu.obs.live import LiveTelemetryServer

    srv = LiveTelemetryServer()
    now = time.time()
    status = {
        "actor": "aa", "remote_id": "99",
        "watermark": {"aa": 1}, "local_clock": {}, "backlog": {},
        "divergence": {"watermark_lag": 5},
        "checkpoint": {},
    }
    srv.publish_health(status, ts=now - 50)
    srv.publish_health(status, ts=now - 10)  # wm unchanged: age grows
    h = srv.health()
    dev = h["remotes"]["99"]["devices"]["aa"]
    assert dev["watermark_age_s"] == pytest.approx(50, abs=5)
    assert h["remotes"]["99"]["watermark_age_s"] == dev["watermark_age_s"]
    # the watermark moves: age resets to ~0
    srv.publish_health(dict(status, watermark={"aa": 2}), ts=now)
    dev = srv.health()["remotes"]["99"]["devices"]["aa"]
    assert dev["watermark_age_s"] == pytest.approx(0, abs=5)


def test_live_healthz_membership_key_rides_along():
    from crdt_enc_tpu.obs.live import LiveTelemetryServer

    srv = LiveTelemetryServer()
    srv.publish_health({
        "actor": "aa", "remote_id": "99", "watermark": {},
        "local_clock": {}, "backlog": {},
        "divergence": {"watermark_lag": 0}, "checkpoint": {},
        "membership": {"expected": None, "silent_after": 3,
                       "excluded": ["bb"]},
    })
    dev = srv.health()["remotes"]["99"]["devices"]["aa"]
    assert dev["membership"]["excluded"] == ["bb"]


def test_fleet_watermark_age_from_sink_timestamps(tmp_path):
    from crdt_enc_tpu.obs import fleet

    rep = {
        "actor": "aa", "remote_id": "99",
        "local_clock": {"aa": 1}, "union_clock": {"aa": 1},
        "watermark": {"aa": 1}, "matrix": {},
        "backlog": {"files": 0, "bytes": 0, "per_actor": {}},
        "divergence": {"actors_behind": 0, "version_lag": 0,
                       "watermark_lag": 0, "known_replicas": 1},
        "checkpoint": {"enabled": False, "sealed": False,
                       "staleness_versions": 0},
        "membership": {"expected": None, "silent_after": 2,
                       "excluded": ["bb", "cc"]},
    }
    path = tmp_path / "dev.jsonl"
    recs = [
        {"schema": 2, "label": "compact", "ts": 100.0,
         "replication": rep},
        {"schema": 2, "label": "compact", "ts": 200.0,
         "replication": rep},  # watermark unchanged for 100s
        {"schema": 2, "label": "compact", "ts": 260.0,
         "replication": rep},  # …and 160s by the newest sample
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    (s,) = fleet.device_summaries([str(path)])
    assert s["watermark_age_s"] == pytest.approx(160.0)
    report = fleet.fleet_report([s])
    dev = report["remotes"][0]["devices"][0]
    assert dev["watermark_age_s"] == pytest.approx(160.0)
    assert dev["membership_excluded"] == 2
    rendered = fleet.format_fleet(report)
    assert "wm_age=160s" in rendered and "excl=2" in rendered


def test_fleet_golden_includes_wm_age():
    golden = (REPO / "tests" / "data" / "obs_fleet_golden.txt").read_text()
    assert "wm_age=" in golden


# ---- simulator vocabulary + checker ---------------------------------------


def test_sim_strong_read_schedule_all_faults_clean():
    from crdt_enc_tpu.sim import FaultConfig, generate, run_schedule

    schedule = generate(
        1, 3, 70, FaultConfig.all_faults(), strong_reads=True
    )
    assert any(
        s.kind in ("read_strong", "await_stable") for s in schedule.steps
    )
    result = run_schedule(schedule)
    assert result.ok, result.violation
    assert result.strong_reads > 0


def test_sim_strong_schedule_roundtrip_and_flag_off_vocab():
    from crdt_enc_tpu.sim import FaultConfig, Schedule, generate

    sched = generate(
        5, 3, 40, FaultConfig.none(), strong_reads=True
    )
    again = Schedule.from_obj(sched.to_obj())
    assert again.strong_reads is True
    assert [s.to_obj() for s in again.steps] == [
        s.to_obj() for s in sched.steps
    ]
    # flag off: the vocabulary (and the RNG stream) is untouched
    plain = generate(5, 3, 40, FaultConfig.none())
    assert not any(
        s.kind in ("read_strong", "await_stable") for s in plain.steps
    )
    assert plain.to_obj()["strong"] is False


def test_linearize_checker_detects_each_defect_class():
    oplog = {
        (A, 1): [[0, b"x", [A, 1]]],
        (A, 2): [[0, b"y", [A, 2]]],
    }
    good, missing = oracle_fold(oplog, VClock({A: 2}))
    assert not missing

    from crdt_enc_tpu.read.stable import ReadResult

    ok = check_strong_read(
        oplog, ReadResult(good.to_obj(), "strong", VClock({A: 2})), None
    )
    assert ok is None
    bad_state = ORSet()
    bad_state.apply([0, b"x", [A, 1]])
    d = check_strong_read(
        oplog, ReadResult(bad_state.to_obj(), "strong", VClock({A: 2})),
        None,
    )
    assert d is not None and "diverges" in d
    d = check_strong_read(
        oplog, ReadResult(good.to_obj(), "strong", VClock({A: 3})), None
    )
    assert d is not None and "durable" in d
    d = check_strong_read(
        oplog, ReadResult(good.to_obj(), "strong", VClock({A: 2})),
        VClock({A: 3}),
    )
    assert d is not None and "regressed" in d
    d = check_strong_read(
        oplog, ReadResult(good.to_obj(), "strong", VClock({A: 2})),
        None, ryw_target=VClock({A: 3}),
    )
    assert d is not None and "await_stable" in d


@pytest.mark.slow
def test_sim_strong_reads_fleet_acceptance():
    """ISSUE-15 acceptance: an 8-replica all-fault schedule set with
    the full vocabulary (daemon in the loop) and the linearizability
    checker on every strong read."""
    from crdt_enc_tpu.sim import FaultConfig, generate, run_schedule

    total = 0
    for seed in range(2):
        schedule = generate(
            seed, 8, 500, FaultConfig.all_faults(),
            daemon=True, strong_reads=True,
        )
        result = run_schedule(schedule)
        assert result.ok, f"seed {seed}: {result.violation}"
        total += result.strong_reads
    assert total > 20


# ---- bench record + trend pickup ------------------------------------------


def test_strong_read_bench_record_committed_and_trended():
    from crdt_enc_tpu.obs import fleet, sink

    records = sink.read_records(str(REPO / "BENCH_LOCAL.jsonl"))
    mine = [
        r for r in records
        if r.get("metric") == "strong_read_e2e_reads_per_sec"
    ]
    assert mine, "the --e2e-strong-read record must be committed"
    rec = mine[-1]
    assert rec["byte_identical"] is True
    assert rec["value"] > 0
    assert rec["watermark_lag_versions"]["p99"] >= 0
    assert rec["strong_ms"]["p99_ms"] > 0
    trend = fleet.bench_trend(records, metric="strong_read_e2e_reads_per_sec")
    assert len(trend) >= 1 and trend[0]["latest"] == rec["value"]
    # the CI ratchet must pass on the committed history
    assert fleet.trend_regressions(trend, 45.0) == []

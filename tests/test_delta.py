"""Delta-state replication + composed adapters (ISSUE 10).

The acceptance gate is differential: a consumer that folds
``full-at-base + delta chain`` must end byte-identical to one that
re-reads every full snapshot — across adapters (including the composed
resettable counter), across storage backends, and under every doubt
path (gap, GC'd link, torn file, wrong adapter, no base), where the
fallback to the snapshot path must be automatic and traced.
"""

import asyncio
import random

import pytest

from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import (
    Core,
    OpenOptions,
    gcounter_adapter,
    gset_adapter,
    orset_adapter,
    pncounter_adapter,
)
from crdt_enc_tpu.delta import (
    MAX_CHAIN,
    ResettableCounter,
    UndoError,
    codec_for,
    rcounter_adapter,
)
from crdt_enc_tpu.delta import wire as delta_wire
from crdt_enc_tpu.models import ORSet, canonical_bytes
from crdt_enc_tpu.utils import codec, trace
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, adapter, create=True, **kw):
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter,
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
        **kw,
    )


@pytest.fixture(params=["memory", "fs"])
def storage_factory(request, tmp_path):
    if request.param == "memory":
        remote = MemoryRemote()
        instances: dict = {}

        def make(name="a"):
            return instances.setdefault(name, MemoryStorage(remote))

        make.remote = remote
        return make
    remote_dir = tmp_path / "remote"

    def make(name="a"):
        return FsStorage(str(tmp_path / f"local-{name}"), str(remote_dir))

    make.remote = None
    return make


async def apply_each(core, builders):
    """One op file per builder — dots mint against the live state, the
    way real writers interleave build/apply."""
    for build in builders:
        await core.update(build)


def counters():
    return trace.snapshot()["counters"]


# ---- codec unit level ------------------------------------------------------


def _rand_orset_history(seed, n_actors=4, n_members=10, n_ops=120):
    """Three causally related Orswot states: base B, its extension N
    (same replica after more folding), and a consumer X that merged B
    and then independently folded more third-party ops — the exact
    precondition shape the codec contract names."""
    rng = random.Random(seed)
    actors = [bytes([i]) * 16 for i in range(n_actors)]
    members = [b"m%d" % i for i in range(n_members)]

    producer = ORSet()
    third = ORSet()  # a peer whose ops only X sees

    def mutate(s, owner):
        m = rng.choice(members)
        if rng.random() < 0.65 or not s.contains(m):
            s.apply(s.add_ctx(owner, m))
        else:
            s.apply(s.rm_ctx(m))

    for _ in range(n_ops):
        mutate(producer, actors[0])
    base = ORSet.from_obj(producer.to_obj())

    X = ORSet.from_obj(producer.to_obj())  # X merged the base exactly
    for _ in range(n_ops // 2):
        mutate(third, actors[1])
    X.merge(third)
    for _ in range(n_ops // 3):
        mutate(X, actors[2])

    # the producer keeps going: more own ops AND it folds some of the
    # third party too (so the window kills dots X independently holds)
    for _ in range(n_ops):
        mutate(producer, actors[0])
    half = ORSet.from_obj(third.to_obj())
    producer.merge(half)
    for _ in range(n_ops // 4):
        mutate(producer, actors[3])
    new = ORSet.from_obj(producer.to_obj())
    return base, new, X


@pytest.mark.parametrize("seed", range(8))
def test_orset_delta_apply_equals_full_merge(seed):
    from crdt_enc_tpu.delta.codec import orset_delta_apply, orset_delta_diff

    base, new, consumer = _rand_orset_history(seed)
    dobj = orset_delta_diff(base, new)
    # the delta must survive the wire (msgpack round-trip)
    dobj = codec.unpack(codec.pack(dobj))

    via_delta = ORSet.from_obj(consumer.to_obj())
    orset_delta_apply(via_delta, dobj)
    via_merge = ORSet.from_obj(consumer.to_obj())
    via_merge.merge(new)
    assert canonical_bytes(via_delta) == canonical_bytes(via_merge)

    # and on the base itself (the sealer's self-verify shape)
    refold = ORSet.from_obj(base.to_obj())
    orset_delta_apply(refold, dobj)
    assert canonical_bytes(refold) == canonical_bytes(new)


def test_orset_delta_remove_only_window():
    """Removes never advance the Orswot clock, so a remove-only delta
    has an empty window — the apply's cheap path — and must still kill
    exactly the removed dots."""
    from crdt_enc_tpu.delta.codec import orset_delta_apply, orset_delta_diff

    a = bytes([7]) * 16
    s = ORSet()
    for m in (b"x", b"y", b"z"):
        s.apply(s.add_ctx(a, m))
    base = ORSet.from_obj(s.to_obj())
    s.apply(s.rm_ctx(b"y"))
    new = ORSet.from_obj(s.to_obj())
    dobj = orset_delta_diff(base, new)
    assert not dobj[b"e"]  # no adds: pure removal
    consumer = ORSet.from_obj(base.to_obj())
    orset_delta_apply(consumer, dobj)
    assert canonical_bytes(consumer) == canonical_bytes(new)


def test_counter_and_gset_codecs_are_sub_lattices():
    from crdt_enc_tpu.models import GCounter, GSet, PNCounter

    for make, mutate in (
        (GCounter, lambda s, a, i: s.apply(s.inc(a, i + 1))),
        (PNCounter, lambda s, a, i: s.apply(
            s.inc(a, i + 1) if i % 3 else s.dec(a, i + 1))),
        (GSet, lambda s, a, i: s.apply(b"m%d" % i)),
    ):
        name = {GCounter: b"gcounter", PNCounter: b"pncounter",
                GSet: b"gset"}[make]
        cdc = codec_for(name)
        a, b = bytes([1]) * 16, bytes([2]) * 16
        s = make()
        for i in range(6):
            mutate(s, a, i)
        base = make.from_obj(codec.unpack(codec.pack(s.to_obj())))
        for i in range(6, 12):
            mutate(s, a, i)
        new = make.from_obj(codec.unpack(codec.pack(s.to_obj())))
        dobj = codec.unpack(codec.pack(cdc.diff(base, new)))
        # consumer ahead of the base on another actor
        consumer = make.from_obj(codec.unpack(codec.pack(base.to_obj())))
        mutate(consumer, b, 20)
        via_merge = make.from_obj(codec.unpack(codec.pack(consumer.to_obj())))
        via_merge.merge(new)
        cdc.apply(consumer, dobj)
        assert canonical_bytes(consumer) == canonical_bytes(via_merge)


def test_delta_wire_rejects_malformed():
    rec = delta_wire.DeltaRecord(
        base_name="b", new_name="n",
        base_cursor=__import__(
            "crdt_enc_tpu.models.vclock", fromlist=["VClock"]).VClock(),
        new_cursor=__import__(
            "crdt_enc_tpu.models.vclock", fromlist=["VClock"]).VClock(),
        sealer=b"\x01" * 16, adapter=b"orset", watermark={}, delta_obj={},
    )
    good = delta_wire.build_delta_obj(rec)
    assert delta_wire.parse_delta_obj(
        codec.unpack(codec.pack(good))
    ).new_name == "n"
    for breakage in (
        lambda o: o.pop(b"wm"),            # missing base watermark
        lambda o: o.pop(b"new"),
        lambda o: o.pop(b"d"),
        lambda o: o.__setitem__(b"s", b"short"),
        lambda o: o.__setitem__(b"v", 99),
    ):
        bad = dict(good)
        breakage(bad)
        with pytest.raises(ValueError):
            delta_wire.parse_delta_obj(bad)


# ---- core differential: delta path ≡ snapshot path -------------------------

ADAPTER_CASES = {
    "orset": (
        orset_adapter,
        lambda actor, r: [
            (lambda s, m=b"m%d-%d" % (r, i): s.add_ctx(actor, m))
            for i in range(6)
        ] + [(lambda s, m=b"m%d-0" % max(0, r - 1):
              s.rm_ctx(m) if s.contains(m) else None)],
    ),
    "rcounter": (
        rcounter_adapter,
        lambda actor, r: [
            (lambda s: ResettableCounter.inc(s, actor, r + 1))
            for _ in range(5)
        ] + ([lambda s: ResettableCounter.reset(s)] if r == 2 else []),
    ),
    "gcounter": (
        gcounter_adapter,
        lambda actor, r: [(lambda s: s.inc(actor, r + 1))] * 4,
    ),
    "pncounter": (
        pncounter_adapter,
        lambda actor, r: [
            (lambda s: s.inc(actor, r + 2)), (lambda s: s.dec(actor, 1))
        ] * 2,
    ),
    "gset": (
        gset_adapter,
        lambda actor, r: [
            (lambda s, m=b"g%d-%d" % (r, i): s.insert_ctx(m))
            for i in range(5)
        ],
    ),
}


@pytest.mark.parametrize("which", sorted(ADAPTER_CASES))
def test_differential_delta_vs_snapshot_path(storage_factory, which):
    """≥3 adapters × memory+fs: after R producer compactions, a chained
    delta consumer and a full-snapshot consumer are byte-identical —
    and the delta consumer really did use the chain."""
    make_adapter, round_ops = ADAPTER_CASES[which]

    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), make_adapter())
        )
        c_delta = await Core.open(
            make_opts(storage_factory("cd"), make_adapter())
        )
        c_snap = await Core.open(
            make_opts(storage_factory("cs"), make_adapter(), delta=False)
        )
        # a fleet of seed writers widens the state (multi-actor clocks)
        # so a one-writer round's delta beats the full snapshot even for
        # counter types, whose whole state is one small clock
        for w in range(6):
            writer = await Core.open(
                make_opts(storage_factory(f"w{w}"), make_adapter())
            )
            await apply_each(writer, round_ops(writer.actor_id, 0))
        # round 0 builds a base big enough that deltas beat full states
        await apply_each(
            producer,
            [b for r in range(3) for b in round_ops(producer.actor_id, r)],
        )
        await producer.compact()
        await c_delta.read_remote()
        await c_snap.read_remote()
        applied_total = 0
        for r in range(3, 7):
            await apply_each(producer, round_ops(producer.actor_id, r))
            await producer.compact()
            trace.reset()
            await c_delta.read_remote()
            applied_total += counters().get("delta_applied", 0)
            await c_snap.read_remote()
            assert (
                c_delta.with_state(canonical_bytes)
                == c_snap.with_state(canonical_bytes)
                == producer.with_state(canonical_bytes)
            ), f"{which}: delta path diverged at round {r}"
            assert (
                c_delta.info().next_op_versions
                == c_snap.info().next_op_versions
            )
        assert applied_total > 0, f"{which}: chain never applied"

    run(go())


def test_delta_files_smaller_than_snapshots(storage_factory):
    """The point of the subsystem: on an incremental workload the delta
    payloads are a small fraction of the snapshot they replace."""

    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        for i in range(150):
            m = b"member-%04d" % i
            await producer.update(lambda s, m=m: s.add_ctx(producer.actor_id, m))
        await producer.compact()
        trace.reset()
        await producer.update(
            lambda s: s.add_ctx(producer.actor_id, b"tail-1")
        )
        await producer.compact()
        c = counters()
        assert c.get("delta_files_sealed") == 1
        snap_bytes = None
        names = await producer.storage.list_state_names()
        loaded = await producer.storage.load_states(names)
        snap_bytes = max(len(raw) for _, raw in loaded)
        assert c["delta_bytes_sealed"] * 5 <= snap_bytes

    run(go())


# ---- fallbacks: every doubt path reads the full snapshot -------------------


def test_fallback_on_gc_mid_chain(storage_factory):
    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        consumer = await Core.open(
            make_opts(storage_factory("c"), orset_adapter())
        )
        for i in range(80):
            await producer.update(
                lambda s, m=b"m%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()
        await consumer.read_remote()
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"t1"))
        await producer.compact()
        # the hostile move: the whole delta log vanishes mid-chain
        await producer.storage.remove_deltas([(producer.actor_id, 1 << 62)])
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"t2"))
        await producer.compact()
        await producer.storage.remove_deltas([(producer.actor_id, 1 << 62)])
        trace.reset()
        await consumer.read_remote()
        c = counters()
        assert not c.get("delta_applied")
        assert consumer.with_state(canonical_bytes) == producer.with_state(
            canonical_bytes
        )
        # next round the consumer re-anchors at the full snapshot it
        # just read and rejoins the chain
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"t3"))
        await producer.compact()
        trace.reset()
        await consumer.read_remote()
        assert counters().get("delta_applied") == 1
        assert consumer.with_state(canonical_bytes) == producer.with_state(
            canonical_bytes
        )

    run(go())


def test_fallback_on_torn_delta_and_base_doubt(storage_factory):
    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        late = await Core.open(
            make_opts(storage_factory("l"), orset_adapter())
        )
        for i in range(60):
            await producer.update(
                lambda s, m=b"m%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"x"))
        await producer.compact()
        # a consumer that never saw the base: base-name doubt → full read
        trace.reset()
        await late.read_remote()
        c = counters()
        assert c.get("delta_fallbacks", 0) >= 1
        assert late.last_delta_fallback_reason == "base_missing"
        assert not c.get("delta_applied")
        assert late.with_state(canonical_bytes) == producer.with_state(
            canonical_bytes
        )

        # torn delta file: unreadable → traced fallback, snapshot wins
        consumer = await Core.open(
            make_opts(storage_factory("c2"), orset_adapter())
        )
        await late.read_remote()
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"y"))
        await producer.compact()
        files = await producer.storage.load_deltas([(producer.actor_id, 1)])
        actor, version, raw = files[-1]
        await producer.storage.remove_deltas([(actor, version)])
        await producer.storage.store_delta(actor, version, raw[: len(raw) // 2])
        trace.reset()
        await consumer.read_remote()
        c = counters()
        assert c.get("delta_fallbacks", 0) >= 1
        assert consumer.with_state(canonical_bytes) == producer.with_state(
            canonical_bytes
        )

    run(go())


def test_fallback_on_adapter_mismatch(storage_factory):
    """A delta sealed by an orset fleet read by an rcounter-configured
    replica: fingerprint doubt (adapter name), full snapshot path."""

    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        reader = await Core.open(
            make_opts(storage_factory("r"), rcounter_adapter())
        )
        for i in range(60):
            await producer.update(
                lambda s, m=b"m%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()
        await reader.read_remote()
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"z"))
        await producer.compact()
        trace.reset()
        await reader.read_remote()
        assert reader.last_delta_fallback_reason == "adapter"
        assert not counters().get("delta_applied")
        assert reader.with_state(canonical_bytes) == producer.with_state(
            canonical_bytes
        )

    run(go())


def test_delta_disabled_seals_and_reads_nothing(storage_factory):
    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter(), delta=False)
        )
        for i in range(40):
            await producer.update(
                lambda s, m=b"m%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"t"))
        await producer.compact()
        assert not await producer.storage.list_delta_actors()

    run(go())


# ---- GC discipline ---------------------------------------------------------


def test_compact_gcs_consumed_foreign_deltas(storage_factory):
    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        compactor = await Core.open(
            make_opts(storage_factory("c"), orset_adapter())
        )
        for i in range(60):
            await producer.update(
                lambda s, m=b"m%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()
        await compactor.read_remote()
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"t"))
        await producer.compact()
        assert await producer.storage.list_delta_actors() == [
            producer.actor_id
        ]
        # the second compactor consumes the chain, then its compaction
        # removes the consumed prefix (covered by its new snapshot)
        await compactor.compact()
        files = await compactor.storage.load_deltas([(producer.actor_id, 1)])
        assert files == []

    run(go())


def test_own_log_bounded_at_max_chain(storage_factory):
    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        for i in range(80):
            await producer.update(
                lambda s, m=b"base%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()
        for r in range(MAX_CHAIN + 4):
            await producer.update(
                lambda s, m=b"r%d" % r: s.add_ctx(producer.actor_id, m)
            )
            await producer.compact()
        files = await producer.storage.load_deltas([(producer.actor_id, 1)])
        versions = [v for _, v, _ in files]
        assert len(versions) == MAX_CHAIN
        assert max(versions) - min(versions) == MAX_CHAIN - 1

    run(go())


def test_deltaless_compact_wipes_own_stale_chain(storage_factory):
    """A cold reopen (no delta base) compacts without a delta; its old
    chain cannot extend to the new snapshot and is removed rather than
    left for every consumer to scan and fall back on."""

    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        for i in range(60):
            await producer.update(
                lambda s, m=b"m%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"t"))
        await producer.compact()
        assert await producer.storage.load_deltas([(producer.actor_id, 1)])
        # cold restart: checkpoint disabled ⇒ no delta base survives
        reopened = await Core.open(
            make_opts(
                storage_factory("p"), orset_adapter(), create=False,
                checkpoint=False,
            )
        )
        await reopened.read_remote()
        await reopened.update(
            lambda s: s.add_ctx(reopened.actor_id, b"after")
        )
        await reopened.compact()
        assert not await reopened.storage.load_deltas(
            [(reopened.actor_id, 1)]
        )

    run(go())


def test_warm_reopen_extends_chain(storage_factory):
    """Checkpoint continuity (b"snap"): a warm-reopened compactor keeps
    sealing deltas against its pre-crash snapshot — the chain never
    breaks, and a steady consumer applies straight through."""

    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        consumer = await Core.open(
            make_opts(storage_factory("c"), orset_adapter())
        )
        for i in range(60):
            await producer.update(
                lambda s, m=b"m%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()
        await consumer.read_remote()
        reopened = await Core.open(
            make_opts(storage_factory("p"), orset_adapter(), create=False)
        )
        assert reopened.opened_from_checkpoint
        await reopened.update(
            lambda s: s.add_ctx(reopened.actor_id, b"post-reopen")
        )
        await reopened.compact()
        trace.reset()
        await consumer.read_remote()
        assert counters().get("delta_applied") == 1
        assert consumer.with_state(canonical_bytes) == reopened.with_state(
            canonical_bytes
        )

    run(go())


def test_stale_checkpoint_reanchors_chain_without_fsck_errors(storage_factory):
    """A reopen from a one-generation-stale checkpoint (the simulator's
    ``stale_checkpoint`` fault) re-anchors the delta chain at an EARLIER
    own snapshot.  The resulting link skips its predecessor's target —
    which must stay fsck-clean (warn at most), apply on consumers that
    hold the old anchor, and converge byte-identically."""

    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        consumer = await Core.open(
            make_opts(storage_factory("c"), orset_adapter())
        )
        for i in range(70):
            await producer.update(
                lambda s, m=b"m%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()  # S1
        await consumer.read_remote()
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"a"))
        await producer.compact()  # S2 + D1(S1→S2); checkpoint gen A
        stale_ckpt = await producer.storage.load_local_checkpoint()
        await consumer.read_remote()
        await producer.update(lambda s: s.add_ctx(producer.actor_id, b"b"))
        await producer.compact()  # S3 + D2(S2→S3); checkpoint gen B
        # the fault: the resume point lags one generation
        await producer.storage.store_local_checkpoint(stale_ckpt)
        reopened = await Core.open(
            make_opts(storage_factory("p"), orset_adapter(), create=False)
        )
        assert reopened.opened_from_checkpoint
        await reopened.read_remote()  # applies D2 from the old anchor
        await reopened.update(
            lambda s: s.add_ctx(reopened.actor_id, b"c")
        )
        await reopened.compact()  # S4 + D3(base = S2, not S3!)
        report = await _fsck(storage_factory("fsck"))
        assert report.ok, [str(i) for i in report.issues]
        trace.reset()
        await consumer.read_remote()
        assert counters().get("delta_applied", 0) >= 1
        assert consumer.with_state(canonical_bytes) == reopened.with_state(
            canonical_bytes
        )

    run(go())


# ---- composed resettable counter (semidirect product) ----------------------


def test_rcounter_inc_value_reset_undo():
    s = ORSet()
    a = bytes([3]) * 16
    op1 = ResettableCounter.inc(s, a, 5)
    s.apply(op1)
    op2 = ResettableCounter.inc(s, a, 2)
    s.apply(op2)
    assert ResettableCounter.value(s) == 7
    assert len(ResettableCounter.tokens(s)) == 2
    # exact inverse of one observed increment
    s.apply(ResettableCounter.undo(s, op1))
    assert ResettableCounter.value(s) == 2
    # undo twice: nothing left to invert
    with pytest.raises(UndoError):
        ResettableCounter.undo(s, op1)
    # resets admit no inverse (arXiv:2006.10494)
    rm_ops = ResettableCounter.reset(s)
    for op in rm_ops:
        with pytest.raises(UndoError):
            ResettableCounter.undo(s, op)
        s.apply(op)
    assert ResettableCounter.value(s) == 0


def test_rcounter_concurrent_inc_survives_reset(storage_factory):
    """The semidirect action law: a reset cancels what it observed; a
    concurrent unobserved increment survives."""

    async def go():
        a = await Core.open(
            make_opts(storage_factory("a"), rcounter_adapter())
        )
        b = await Core.open(
            make_opts(storage_factory("b"), rcounter_adapter())
        )
        await a.update(lambda s: ResettableCounter.inc(s, a.actor_id, 10))
        await b.read_remote()
        # concurrent: a increments again, b resets what it has seen (10)
        await a.update(lambda s: ResettableCounter.inc(s, a.actor_id, 4))
        await b.update(lambda s: ResettableCounter.reset(s))
        await a.read_remote()
        await b.read_remote()
        await a.read_remote()
        va = a.with_state(ResettableCounter.value)
        vb = b.with_state(ResettableCounter.value)
        assert va == vb == 4  # the unobserved +4 survived the reset

    run(go())


def test_rcounter_rides_device_kernels_and_delta_chain(storage_factory):
    """No new kernels: the composed counter folds through the OR-Set
    accelerator (TpuAccelerator on the CPU backend here) and replicates
    through the same delta chains, byte-identical to the host path."""
    from crdt_enc_tpu.parallel import TpuAccelerator

    async def go():
        producer = await Core.open(
            make_opts(
                storage_factory("p"), rcounter_adapter(),
                accelerator=TpuAccelerator(min_device_batch=1),
            )
        )
        host = await Core.open(
            make_opts(storage_factory("h"), rcounter_adapter())
        )
        for i in range(40):
            await producer.update(
                lambda s: ResettableCounter.inc(s, producer.actor_id, 1)
            )
        await producer.compact()
        await host.read_remote()
        await producer.update(
            lambda s: ResettableCounter.inc(s, producer.actor_id, 2)
        )
        await producer.compact()
        trace.reset()
        await host.read_remote()
        assert counters().get("delta_applied") == 1
        assert host.with_state(canonical_bytes) == producer.with_state(
            canonical_bytes
        )
        assert host.with_state(ResettableCounter.value) == 42

    run(go())


# ---- fsck: delta family validation -----------------------------------------


async def _fsck(storage):
    from crdt_enc_tpu.tools.fsck import fsck_remote

    return await fsck_remote(
        storage, IdentityCryptor(), PlainKeyCryptor(), deep=True
    )


def test_fsck_accepts_healthy_delta_chain(storage_factory):
    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        for i in range(60):
            await producer.update(
                lambda s, m=b"m%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()
        for r in range(3):
            await producer.update(
                lambda s, m=b"t%d" % r: s.add_ctx(producer.actor_id, m)
            )
            await producer.compact()
        report = await _fsck(storage_factory("fsck"))
        assert report.ok, [str(i) for i in report.issues]
        assert report.delta_files == 3

    run(go())


def test_fsck_flags_orphan_gap_and_divergence(storage_factory):
    """The three ISSUE-named defect classes each produce an error row
    (CLI exit 1): a misfiled orphan delta, an interior chain gap, and
    delta-vs-refold byte divergence."""

    async def go():
        producer = await Core.open(
            make_opts(storage_factory("p"), orset_adapter())
        )
        for i in range(60):
            await producer.update(
                lambda s, m=b"m%d" % i: s.add_ctx(producer.actor_id, m)
            )
        await producer.compact()
        storage = producer.storage
        base_name = base_blob = None
        for r in range(3):
            await producer.update(
                lambda s, m=b"t%d" % r: s.add_ctx(producer.actor_id, m)
            )
            await producer.compact()
            if r == 1:
                # keep the last delta's BASE snapshot bytes: re-storing
                # them later (content addressing restores the exact
                # name) recreates the both-endpoints-listed window the
                # refold check needs
                (base_name, base_blob), = await storage.load_states(
                    await storage.list_state_names()
                )

        # interior gap: damage (GC only removes prefixes)
        files = await storage.load_deltas([(producer.actor_id, 1)])
        assert len(files) == 3
        _, v_mid, _ = files[1]
        if hasattr(storage, "_deltas_dir"):
            import os

            os.remove(
                os.path.join(storage._deltas_dir(producer.actor_id),
                             str(v_mid))
            )
        else:
            del storage.remote.deltas[producer.actor_id][v_mid]
        report = await _fsck(storage_factory("f1"))
        assert not report.ok
        assert any(
            "broken chain: gap" in str(i) for i in report.issues
        ), [str(i) for i in report.issues]

        # misfiled orphan: a delta filed under a foreign sealer's log
        _, v_last, raw_last = files[-1]
        stranger = bytes([9]) * 16
        await storage.store_delta(stranger, 1, raw_last)
        report = await _fsck(storage_factory("f2"))
        assert any("orphan delta" in str(i) for i in report.issues), [
            str(i) for i in report.issues
        ]
        await storage.remove_deltas([(stranger, 1 << 62)])

        # delta-vs-refold divergence: tamper the NEWEST delta's body
        # (its base is the snapshot captured above, its target is the
        # current snapshot), re-store the GC'd base, and the refold
        # check must catch base+delta != target
        from crdt_enc_tpu.core.core import open_sealed_blob

        actor, version, raw = files[-1]
        obj = await open_sealed_blob(
            producer._data.keys, producer.cryptor, raw
        )
        rec = delta_wire.parse_delta_obj(obj)
        assert rec.base_name == base_name
        rec.delta_obj[b"e"] = {}  # drop every add: body no longer refolds
        tampered = await producer._seal(delta_wire.build_delta_obj(rec))
        await storage.remove_deltas([(actor, version)])
        await storage.store_delta(actor, version, tampered)
        assert await storage.store_state(base_blob) == base_name
        report = await _fsck(storage_factory("f3"))
        assert any(
            "divergence" in str(i) and i.severity == "error"
            for i in report.issues
        ), [str(i) for i in report.issues]

    run(go())


# ---- CI trend gate ---------------------------------------------------------


def test_delta_metric_rides_the_trend_gate():
    """The committed e2e-delta BENCH_LOCAL record is a first-class
    config for ``obs_report trend`` and its ``--fail-on-regression``
    CI gate — same machinery, new metric, ≥5× acceptance pinned."""
    import pathlib

    from crdt_enc_tpu.obs import fleet, sink

    bench_local = pathlib.Path(__file__).parent.parent / "BENCH_LOCAL.jsonl"
    records = sink.read_records(str(bench_local))
    trend = fleet.bench_trend(
        records, metric="orset_e2e_delta_bytes_reduction"
    )
    assert trend, "committed BENCH_LOCAL carries no e2e-delta record"
    cfg = trend[0]
    assert cfg["latest"] >= 5  # the ISSUE-10 acceptance floor
    assert cfg["shape"]["tail_pct"] <= 1.0
    regressed = dict(
        records[-1], metric=cfg["metric"], value=cfg["best"] / 2,
        backend=cfg["backend"], shape=cfg["shape"],
    )
    t2 = fleet.bench_trend(
        list(records) + [regressed],
        metric="orset_e2e_delta_bytes_reduction",
    )
    assert fleet.trend_regressions(t2, 10)


# ---- simulator vocabulary --------------------------------------------------


def test_sim_delta_schedule_all_faults_tier1():
    from crdt_enc_tpu.sim import FaultConfig, generate, run_schedule

    sched = generate(
        11, 4, 70, FaultConfig.all_faults(), members=10, deltas=True
    )
    assert sched.deltas
    kinds = {s.kind for s in sched.steps}
    assert kinds & {"dseal", "dread", "dgc"}, kinds
    result = run_schedule(sched)
    assert result.ok, result.violation


def test_sim_delta_fixture_fallback_to_snapshot():
    """The committed fixture: seal-delta / read-delta-chain / GC-mid-
    chain, driving the fallback-to-snapshot path to convergence."""
    import json
    import os

    path = os.path.join(
        os.path.dirname(__file__), "data", "sim",
        "delta_gc_fallback_snapshot.json",
    )
    from crdt_enc_tpu.sim import Schedule, run_schedule

    with open(path) as f:
        sched = Schedule.from_obj(json.load(f))
    assert sched.deltas
    result = run_schedule(sched)
    assert result.ok, result.violation


def test_sim_8_replica_all_fault_delta_schedule_deterministic():
    """ISSUE-10 acceptance: an 8-replica all-fault schedule with the
    delta-sync vocabulary passes every quiescence invariant AND
    replays to the same fingerprint bit-for-bit."""
    from crdt_enc_tpu.sim import FaultConfig, generate, run_schedule

    def one():
        return run_schedule(
            generate(31, 8, 120, FaultConfig.all_faults(), members=12,
                     deltas=True)
        )

    r1, r2 = one(), one()
    assert r1.ok, r1.violation
    assert r1.fingerprint == r2.fingerprint
    assert sum(r1.fault_stats.values()) > 0


def test_foldservice_seals_per_tenant_deltas(storage_factory):
    """The serving layer rides the same seal tail: a FoldService cycle
    seals each tenant's delta in the same dispatch, chains verify
    byte-identical to a solo compact, and steady consumers apply them."""
    from crdt_enc_tpu.serve import FoldService, ServeConfig

    async def go():
        t1 = await Core.open(make_opts(storage_factory("t1"), orset_adapter()))
        consumer = await Core.open(
            make_opts(storage_factory("c"), orset_adapter())
        )
        for i in range(70):
            await t1.update(
                lambda s, m=b"m%d" % i: s.add_ctx(t1.actor_id, m)
            )
        service = FoldService([t1], ServeConfig())
        (res1,) = await service.run_cycle()
        assert res1.error is None
        await consumer.read_remote()
        await t1.update(lambda s: s.add_ctx(t1.actor_id, b"tail"))
        trace.reset()
        (res2,) = await service.run_cycle()
        assert res2.error is None
        assert counters().get("delta_files_sealed") == 1
        trace.reset()
        await consumer.read_remote()
        assert counters().get("delta_applied") == 1
        assert consumer.with_state(canonical_bytes) == t1.with_state(
            canonical_bytes
        )

    run(go())


def test_schedule_deltas_roundtrip_and_default_off():
    from crdt_enc_tpu.sim import FaultConfig, Schedule, generate

    old = generate(5, 3, 40, FaultConfig.none())
    assert not old.deltas
    assert "deltas" in old.to_obj()
    # pre-delta fixture objects (no "deltas" key) default off
    obj = old.to_obj()
    del obj["deltas"]
    assert not Schedule.from_obj(obj).deltas
    new = generate(5, 3, 40, FaultConfig.none(), deltas=True)
    assert Schedule.from_obj(new.to_obj()).deltas
    # the pre-delta RNG stream is untouched: same seed, same steps
    assert [s.to_obj() for s in old.steps] == [
        s.to_obj() for s in generate(5, 3, 40, FaultConfig.none()).steps
    ]

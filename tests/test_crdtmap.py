"""Causal reset-remove map (models/crdtmap.py): observed-remove
semantics with nested CRDT children.

Ground truth is the CmRDT fold of an oracle-derived causally consistent
history; convergence under adversarial interleavings, merge laws, and
CmRDT/CvRDT agreement are all pinned against it — the same proof
obligations every other model here carries, which matters doubly for the
map because its merge implements the subtle cross-side reset rule
(a remover's child forgot the removed dots, so the child-level clock
filter alone cannot kill them on the other side)."""

import asyncio
import copy
import uuid

import pytest
from _hyp import given, settings, st  # hypothesis, or skip-stubs

from crdt_enc_tpu.backends import (
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import Core, OpenOptions, map_adapter
from crdt_enc_tpu.models import CrdtMap, canonical_bytes
from crdt_enc_tpu.utils import codec
from crdt_enc_tpu.models.orset import AddOp
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

ACTORS = [uuid.UUID(int=i + 1).bytes for i in range(4)]
KEYS = ["k0", "k1", "k2"]
MEMBERS = [10, 11, 12]


def interleave(streams, rng):
    streams = [list(s) for s in streams if s]
    out = []
    while streams:
        i = rng.draw(st.integers(0, len(streams) - 1))
        out.append(streams[i].pop(0))
        if not streams[i]:
            streams.pop(i)
    return out


# ---- history generation ----------------------------------------------------

map_script = st.lists(
    st.tuples(
        st.integers(0, len(ACTORS) - 1),
        st.sampled_from(["add", "rm_member", "rm_key", "write"]),
        st.integers(0, len(KEYS) - 1),
        st.integers(0, len(MEMBERS) - 1),
    ),
    max_size=24,
)


def orset_child_history(script):
    """Map<orset> oracle + per-actor streams (rm_member exercises child
    ops under the shared dot; rm_key the observed-remove)."""
    oracle = CrdtMap(child=b"orset")
    streams = {a: [] for a in ACTORS}
    for actor_i, kind, key_i, member_i in script:
        actor, key, member = ACTORS[actor_i], KEYS[key_i], MEMBERS[member_i]
        if kind == "rm_key":
            op = oracle.rm_ctx(key)
            if op.ctx.is_empty():
                continue
        elif kind == "add":
            op = oracle.update_ctx(
                actor, key,
                lambda child, dot: AddOp(member, dot),
            )
        elif kind == "rm_member":
            child = oracle.get(key)
            if child is None or not child.contains(member):
                continue
            op = oracle.update_ctx(
                actor, key,
                lambda child, dot: child.rm_ctx(member),
            )
        else:  # write → treat as add of a different member
            op = oracle.update_ctx(
                actor, key,
                lambda child, dot: AddOp(member + 100, dot),
            )
        oracle.apply(op)
        streams[actor].append(op)
    return oracle, [s for s in streams.values() if s]


HISTORIES = {"orset": orset_child_history}


# ---- laws ------------------------------------------------------------------


@pytest.mark.parametrize("child", ["orset"])
@settings(max_examples=120, deadline=None)
@given(script=map_script, data=st.data())
def test_map_convergence_under_interleaving(child, script, data):
    oracle, streams = HISTORIES[child](script)
    replica = CrdtMap(child=child.encode())
    for op in interleave(streams, data):
        replica.apply(op)
    assert canonical_bytes(replica) == canonical_bytes(oracle)
    # wire round-trip
    assert canonical_bytes(
        CrdtMap.from_obj(replica.to_obj())
    ) == canonical_bytes(oracle)


@pytest.mark.parametrize("child", ["orset"])
@settings(max_examples=120, deadline=None)
@given(script=map_script, data=st.data())
def test_map_cm_cv_agreement_and_merge_laws(child, script, data):
    oracle, streams = HISTORIES[child](script)
    replicas = []
    for s in streams:
        r = CrdtMap(child=child.encode())
        for op in s:
            r.apply(op)
        replicas.append(r)
    if not replicas:
        return
    # merging per-actor replicas in any order equals the oracle fold
    order = interleave([[i] for i in range(len(replicas))], data)
    merged = CrdtMap(child=child.encode())
    for i in order:
        merged.merge(replicas[i])
    assert canonical_bytes(merged) == canonical_bytes(oracle)
    # commutativity + idempotence
    a, b = copy.deepcopy(replicas[0]), copy.deepcopy(replicas[-1])
    ab, ba = copy.deepcopy(a), copy.deepcopy(b)
    ab.merge(b)
    ba.merge(a)
    assert canonical_bytes(ab) == canonical_bytes(ba)
    ab2 = copy.deepcopy(ab)
    ab2.merge(b)
    assert canonical_bytes(ab2) == canonical_bytes(ab)


# ---- targeted semantics ----------------------------------------------------


def test_observed_remove_spares_concurrent_update():
    """rm(key) on A must not delete B's concurrent update to that key."""
    a = CrdtMap(child=b"orset")
    b = CrdtMap(child=b"orset")
    up = a.update_ctx(ACTORS[0], "k", lambda c, d: AddOp(1, d))
    a.apply(up)
    b.apply(up)
    # concurrent: A removes k; B adds member 2 under k
    rm = a.rm_ctx("k")
    upb = b.update_ctx(ACTORS[1], "k", lambda c, d: AddOp(2, d))
    a.apply(rm)
    b.apply(upb)
    a.merge(b)
    b.apply(rm)
    assert canonical_bytes(a) == canonical_bytes(b)
    assert a.contains("k")
    assert a.get("k").contains(2)  # concurrent add survives
    assert not a.get("k").contains(1)  # observed state removed


def test_remove_observed_via_merge_kills_other_sides_copy():
    """The cross-side reset rule: B's copy of observed-removed child
    state dies in the merge even though the remover's child forgot it."""
    a = CrdtMap(child=b"orset")
    b = CrdtMap(child=b"orset")
    up1 = a.update_ctx(ACTORS[0], "k", lambda c, d: AddOp(1, d))
    a.apply(up1)
    b.apply(up1)
    rm = a.rm_ctx("k")
    a.apply(rm)  # A: key gone entirely
    assert not a.contains("k")
    a.merge(b)  # B still has the old copy — must NOT resurrect
    assert not a.contains("k")
    # and the reverse merge converges identically
    b.merge(a)
    assert canonical_bytes(b) == canonical_bytes(a)


def test_deferred_remove_beyond_local_clock():
    """A remove whose context cites dots this replica has not seen yet
    suppresses those dots when they arrive (same contract as the ORSet's
    deferred horizons)."""
    a = CrdtMap(child=b"orset")
    b = CrdtMap(child=b"orset")
    up1 = a.update_ctx(ACTORS[0], "k", lambda c, d: AddOp(1, d))
    a.apply(up1)
    rm = a.rm_ctx("k")  # observed {actor0: 1}
    # b receives the remove BEFORE the update it observed
    b.apply(rm)
    assert not b.contains("k")
    b.apply(up1)  # arrives late: born dead
    assert not b.contains("k")
    a.apply(rm)
    assert canonical_bytes(b) == canonical_bytes(a)


# ---- Core lifecycle --------------------------------------------------------


def test_core_lifecycle_map():
    async def go():
        def opts(remote):
            return OpenOptions(
                storage=MemoryStorage(remote),
                cryptor=IdentityCryptor(),
                key_cryptor=PlainKeyCryptor(),
                adapter=map_adapter(b"orset"),
                supported_data_versions=(DEFAULT_DATA_VERSION_1,),
                current_data_version=DEFAULT_DATA_VERSION_1,
                create=True,
            )

        remote = MemoryRemote()
        w = await Core.open(opts(remote))
        await w.update(
            lambda s: s.update_ctx(w.actor_id, "fruits", lambda c, d: AddOp("apple", d))
        )
        await w.update(
            lambda s: s.update_ctx(w.actor_id, "fruits", lambda c, d: AddOp("pear", d))
        )
        await w.update(
            lambda s: s.update_ctx(w.actor_id, "nums", lambda c, d: AddOp(1, d))
        )
        await w.update(lambda s: s.rm_ctx("nums"))
        await w.compact()
        r = await Core.open(opts(remote))
        await r.read_remote()
        assert r.with_state(lambda s: s.keys()) == ["fruits"]
        assert r.with_state(lambda s: sorted(s.get("fruits").members()))
        assert r.with_state(canonical_bytes) == w.with_state(canonical_bytes)

    asyncio.run(go())


def test_true_concurrency_convergence():
    """Ops derived from DIVERGENT replica states (not a single oracle),
    gossiped with per-actor FIFO but no causal ordering — the delivery
    model the file-sync transport actually provides.  All replicas must
    converge at full delivery, and the columnar bulk fold must agree.
    This class of history caught two real design flaws the oracle-based
    tests cannot see (suppression losing child sub-ops; child horizons
    stranded across key incarnations)."""
    import random

    from crdt_enc_tpu.parallel.accel import TpuAccelerator

    accel = TpuAccelerator(min_device_batch=1)
    proto = CrdtMap(child=b"orset")
    rng = random.Random(5)
    for trial in range(150):
        n_rep = 3
        reps = [CrdtMap(child=b"orset") for _ in range(n_rep)]
        logs = {a: [] for a in ACTORS[:n_rep]}
        delivered = [
            dict((a, 0) for a in ACTORS[:n_rep]) for _ in range(n_rep)
        ]
        for _ in range(rng.randrange(4, 22)):
            i = rng.randrange(n_rep)
            actor = ACTORS[i]
            s = reps[i]
            kind = rng.choice(
                ["add", "rm_member", "rm_key", "deliver", "deliver"]
            )
            if kind == "deliver":
                src = ACTORS[rng.randrange(n_rep)]
                pos = delivered[i][src]
                if pos < len(logs[src]):
                    reps[i].apply(logs[src][pos])
                    delivered[i][src] = pos + 1
                continue
            key = rng.choice(KEYS)
            if kind == "add":
                op = s.update_ctx(
                    actor, key,
                    lambda c, d: AddOp(rng.choice(MEMBERS), d),
                )
            elif kind == "rm_member":
                child = s.get(key)
                ms = (
                    sorted(child.entries, key=codec.pack) if child else []
                )
                if not ms:
                    continue
                op = s.update_ctx(
                    actor, key,
                    lambda c, d, m=rng.choice(ms): c.rm_ctx(m),
                )
            else:
                op = s.rm_ctx(key)
                if op.ctx.is_empty():
                    continue
            s.apply(op)
            logs[actor].append(op)
            delivered[i][actor] = len(logs[actor])
        finals = []
        for i in range(n_rep):
            pending = {a: delivered[i][a] for a in logs}
            while any(pending[a] < len(logs[a]) for a in logs):
                a = rng.choice(
                    [a for a in logs if pending[a] < len(logs[a])]
                )
                reps[i].apply(logs[a][pending[a]])
                pending[a] += 1
            finals.append(canonical_bytes(reps[i]))
        assert len(set(finals)) == 1, (trial, "replicas diverged")
        payloads = [
            codec.pack([proto.op_to_obj(op)])
            for a in logs
            for op in logs[a]
        ]
        bulk = CrdtMap(child=b"orset")
        ok = accel.fold_payloads(bulk, payloads, actors_hint=ACTORS[:n_rep])
        assert ok and canonical_bytes(bulk) == finals[0], (trial, "bulk")


def test_mvreg_child_impossibility_pinned():
    """The pinned counterexample for why CHILD_TYPES excludes MVReg
    (round-3 item 7: impossibility argument as a fixture, not prose).

    Under this framework's transport a replica ingests both OP streams
    (per-actor FIFO) and STATE snapshots (compaction files written at
    arbitrary points).  A causal-map key-remove resets the child MVReg
    (``reset_remove``), and snapshot merge uses clock dominance.  Those
    two operations do not commute: merging a snapshot taken BEFORE a
    remove into a state that already applied the remove resurrects the
    removed dots (the stale pair's clock strictly dominates the reset
    pair's), while the opposite order keeps the reset.  Same multiset of
    operations, different final bytes — non-confluent, so no delivery
    order the core can enforce (short of full causal broadcast, which
    the file-sync transport cannot provide) makes an MVReg child
    converge.  The ORSet child has no such collapse: its unit of state
    is a per-(member, actor) dot maximum, which only grows under merge,
    and removes are horizon maxima, not clock shrinkage.
    """
    import uuid

    from crdt_enc_tpu.models import MVReg, canonical_bytes
    from crdt_enc_tpu.models.vclock import VClock

    A, B = uuid.UUID(int=1).bytes, uuid.UUID(int=2).bytes

    def fresh():
        # the child register as the map held it before the key-remove:
        # one surviving write v2 whose causal basis includes A's dot
        # (B wrote v2 after reading A's v1)
        reg = MVReg()
        reg.vals = [(VClock({A: 1, B: 1}), "v2")]
        return reg

    # the stale snapshot: a remote state file sealed BEFORE the remove
    stale = fresh()

    # replica X: key-remove fires (resetting ctx {A:1}), THEN the stale
    # snapshot arrives and merges
    x = fresh()
    x.reset_remove(VClock({A: 1}))
    assert x.vals == [(VClock({B: 1}), "v2")]  # reset applied
    x.merge(stale)

    # replica Y: the stale snapshot merges first (no-op — identical),
    # THEN the same remove fires
    y = fresh()
    y.merge(stale)
    y.reset_remove(VClock({A: 1}))

    # Same operations, both orders legal under per-actor-FIFO + snapshot
    # delivery — and they disagree: X resurrected the removed dot A:1.
    assert canonical_bytes(x) != canonical_bytes(y), (
        "if these ever converge, the MVReg-child exclusion in "
        "models/crdtmap.py CHILD_TYPES should be revisited"
    )
    assert x.vals[0][0].get(A) == 1  # the dead dot is back at X
    assert y.vals[0][0].get(A) == 0  # and gone at Y

"""Unit semantics for the host-reference CRDT engine."""

import uuid

from crdt_enc_tpu.models import (
    Dot,
    EmptyCrdt,
    GCounter,
    LWWMap,
    MVReg,
    ORSet,
    PNCounter,
    RmOp,
    VClock,
    canonical_bytes,
)

A = uuid.UUID(int=1).bytes
B = uuid.UUID(int=2).bytes
C = uuid.UUID(int=3).bytes


def test_vclock_basics():
    v = VClock()
    d = v.inc(A)
    assert d == Dot(A, 1)
    v.apply(d)
    assert v.get(A) == 1 and v.contains(d)
    v.apply(Dot(A, 1))  # idempotent
    assert v.get(A) == 1
    w = VClock({B: 3})
    v.merge(w)
    assert v.get(B) == 3
    assert v.concurrent(VClock({C: 1}))
    assert v.descends(VClock({A: 1}))
    assert VClock({A: 2}).dominates(VClock({A: 1}))


def test_gcounter():
    g = GCounter()
    g.apply(g.inc(A))
    g.apply(g.inc(A))
    g.apply(g.inc(B, steps=5))
    assert g.read() == 7
    h = GCounter.from_obj(g.to_obj())
    assert h == g
    g2 = GCounter()
    g2.apply(Dot(A, 1))
    g.merge(g2)  # older dot is a no-op
    assert g.read() == 7


def test_pncounter():
    p = PNCounter()
    p.apply(p.inc(A, 10))
    p.apply(p.dec(B, 4))
    assert p.read() == 6
    assert PNCounter.from_obj(p.to_obj()) == p


def test_orset_add_remove_readd():
    s = ORSet()
    s.apply(s.add_ctx(A, b"x"))
    assert s.contains(b"x")
    s.apply(s.rm_ctx(b"x"))
    assert not s.contains(b"x")
    s.apply(s.add_ctx(A, b"x"))
    assert s.contains(b"x")
    assert s.members() == [b"x"]


def test_orset_remove_only_observed():
    # A remove only kills the dots it saw: a concurrent re-add survives.
    s1, s2 = ORSet(), ORSet()
    add1 = s1.add_ctx(A, b"x")
    s1.apply(add1)
    s2.apply(add1)  # replicate
    s2.clock.merge(VClock({A: 1}))
    rm = s2.rm_ctx(b"x")  # observes only dot (A,1)
    s2.apply(rm)
    add2 = s1.add_ctx(A, b"x")  # concurrent re-add, dot (A,2)
    s1.apply(add2)
    s1.merge(s2)
    assert s1.contains(b"x")  # add-wins for the unobserved dot
    s2.apply(add2)
    assert s2.contains(b"x")
    assert canonical_bytes(s1) == canonical_bytes(s2)


def test_orset_deferred_remove():
    # Remove arrives before the adds it observed: must still win.
    s = ORSet()
    rm = RmOp(b"x", VClock({A: 2}))
    s.apply(rm)
    assert s.deferred  # recorded as pending
    s.apply(ORSet().add_ctx(A, b"x"))  # dot (A,1) ≤ horizon: born dead
    assert not s.contains(b"x")
    a2 = ORSet()
    a2.clock = VClock({A: 1})
    s.apply(a2.add_ctx(A, b"x"))  # dot (A,2) = horizon: still dead
    assert not s.contains(b"x")
    assert not s.deferred  # horizon reached → pruned
    a3 = ORSet()
    a3.clock = VClock({A: 2})
    s.apply(a3.add_ctx(A, b"x"))  # dot (A,3) > horizon: survives
    assert s.contains(b"x")


def test_orset_clock_filter_no_resurrection():
    # After a state saw and removed a dot, merging an old state holding that
    # dot must not resurrect it — the clock alone is the tombstone.
    s1 = ORSet()
    add = s1.add_ctx(A, b"x")
    s1.apply(add)
    old = ORSet()
    old.apply(add)  # an old replica still holding the dot
    s1.apply(s1.rm_ctx(b"x"))
    assert not s1.deferred  # remove fully applied, no tombstone kept
    s1.merge(old)
    assert not s1.contains(b"x")
    # and the other direction
    old.merge(s1)
    assert not old.contains(b"x")


def test_mvreg_concurrent_then_supersede():
    r1, r2 = MVReg(), MVReg()
    r1.apply(r1.write_ctx(A, b"va"))
    r2.apply(r2.write_ctx(B, b"vb"))
    r1.merge(r2)
    assert sorted(r1.read().values) == [b"va", b"vb"]  # concurrent: both live
    # a write deriving from the merged read supersedes both
    r1.apply(r1.write_ctx(A, b"vc"))
    assert r1.read().values == [b"vc"]
    r2.merge(r1)
    assert r2.read().values == [b"vc"]
    assert canonical_bytes(r1) == canonical_bytes(r2)


def test_lwwmap():
    m = LWWMap()
    m.apply(m.put(b"k", 10, A, b"v1"))
    m.apply(m.put(b"k", 5, B, b"old"))  # older ts loses
    assert m.get(b"k") == b"v1"
    m.apply(m.put(b"k", 10, B, b"tie"))  # ts tie → higher actor wins
    assert m.get(b"k") == b"tie"
    m.apply(m.delete(b"k", 11, A))
    assert m.get(b"k") is None
    assert m.keys() == []
    m2 = LWWMap()
    m2.apply(m2.put(b"k", 10, C, b"stale"))
    m2.merge(m)
    assert m2.get(b"k") is None  # tombstone wins over older put
    assert canonical_bytes(m2) == canonical_bytes(m)


def test_empty_crdt():
    e = EmptyCrdt()
    e.apply(None)
    e.merge(EmptyCrdt())
    assert EmptyCrdt.from_obj(e.to_obj()) == e


def test_canonical_bytes_roundtrip():
    s = ORSet()
    s.apply(s.add_ctx(A, b"x"))
    s.apply(s.add_ctx(B, (1, 2)))
    s.apply(s.rm_ctx(b"x"))
    blob = canonical_bytes(s)
    from crdt_enc_tpu.utils import codec

    s2 = ORSet.from_obj(codec.unpack(blob))
    assert canonical_bytes(s2) == blob

"""Warm-open fold checkpoints (ISSUE 4): safety, fidelity, fallbacks.

The local checkpoint is a CACHE, never a source of truth — every test
here pins one side of that contract: a verified checkpoint restores a
state byte-identical to a cold refold (across model adapters and both
storage backends), and ANY doubt (torn file, rotated key, wiped remote,
wrong adapter) falls back to the cold path with the reason traced.
"""

import asyncio
import random

import pytest

from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import (
    Core,
    OpenOptions,
    gcounter_adapter,
    gset_adapter,
    lwwmap_adapter,
    map_adapter,
    mvreg_adapter,
    orset_adapter,
    pncounter_adapter,
)
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.utils import codec, trace
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, adapter, create=True, **kw):
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter,
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
        **kw,
    )


@pytest.fixture(params=["memory", "fs"])
def storage_factory(request, tmp_path):
    """() -> Storage factories sharing one remote; same-name reuse gives
    the same local dir (the warm-open identity)."""
    if request.param == "memory":
        remote = MemoryRemote()
        instances: dict = {}

        def make(name="a"):
            return instances.setdefault(name, MemoryStorage(remote))

        return make
    remote_dir = tmp_path / "remote"

    def make(name="a"):
        return FsStorage(str(tmp_path / f"local-{name}"), str(remote_dir))

    return make


# ---- checkpoint codec ------------------------------------------------------


def test_columnar_checkpoint_roundtrip_randomized():
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.models.orset import AddOp, RmOp
    from crdt_enc_tpu.models.vclock import Dot, VClock
    from crdt_enc_tpu.ops.columnar import (
        orset_pack_checkpoint,
        orset_unpack_checkpoint,
    )

    rng = random.Random(7)
    actors = [bytes([i]) * 16 for i in range(12)]
    s = ORSet()
    for _ in range(1500):
        a = rng.choice(actors)
        m = rng.choice([b"b", 3, "s", (1, "t"), rng.randrange(40)])
        s.apply(AddOp(m, s.clock.inc(a)))
        if rng.random() < 0.25 and s.entries:
            m2 = rng.choice(list(s.entries))
            s.apply(RmOp(m2, VClock(dict(s.entries[m2]))))
    s.apply(RmOp(b"ahead", VClock({b"z" * 16: 9})))  # deferred horizon
    wire = codec.unpack(codec.pack(orset_pack_checkpoint(s)))
    r = orset_unpack_checkpoint(wire)
    assert codec.pack(r.to_obj()) == codec.pack(s.to_obj())


def test_columnar_checkpoint_empty_and_overflow():
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.ops.columnar import (
        orset_pack_checkpoint,
        orset_unpack_checkpoint,
    )

    empty = orset_unpack_checkpoint(
        codec.unpack(codec.pack(orset_pack_checkpoint(ORSet())))
    )
    assert codec.pack(empty.to_obj()) == codec.pack(ORSet().to_obj())
    big = ORSet()
    big.clock.counters[b"a" * 16] = 2**70  # outside int64
    assert orset_pack_checkpoint(big) is None  # generic fmt takes over


# ---- warm open == cold open, across adapters (differential) ----------------


def _ops_orset(core, i):
    return core.with_state(
        lambda s: s.add_ctx(core.actor_id, b"m%d" % (i % 7))
    )


def _ops_orset_rm(core, i):
    if i % 5 == 4:
        return core.with_state(lambda s: s.rm_ctx(b"m%d" % (i % 7)))
    return _ops_orset(core, i)


def _ops_gcounter(core, i):
    return core.with_state(lambda s: s.inc(core.actor_id, 1 + i % 3))


def _ops_pncounter(core, i):
    if i % 3 == 2:
        return core.with_state(lambda s: s.dec(core.actor_id))
    return core.with_state(lambda s: s.inc(core.actor_id))


def _ops_mvreg(core, i):
    return core.with_state(lambda s: s.write_ctx(core.actor_id, [b"v", i]))


def _ops_gset(core, i):
    return [b"g%d" % (i % 9)]  # the op IS the member


def _ops_lwwmap(core, i):
    from crdt_enc_tpu.models import LWWOp

    return LWWOp(b"k%d" % (i % 4), 1000 + i, core.actor_id, b"v%d" % i)


def _ops_map(core, i):
    from crdt_enc_tpu.models.orset import AddOp

    def build(s):
        return s.update_ctx(
            core.actor_id, "k%d" % (i % 3), lambda c, d: AddOp(i % 5, d)
        )

    return core.with_state(build)


ADAPTER_CASES = [
    ("orset", orset_adapter, _ops_orset_rm),
    ("gcounter", gcounter_adapter, _ops_gcounter),
    ("pncounter", pncounter_adapter, _ops_pncounter),
    ("mvreg", mvreg_adapter, _ops_mvreg),
    ("gset", gset_adapter, _ops_gset),
    ("lwwmap", lwwmap_adapter, _ops_lwwmap),
    ("map+orset", lambda: map_adapter(b"orset"), _ops_map),
]


@pytest.mark.parametrize(
    "name,mk_adapter,build", ADAPTER_CASES, ids=[c[0] for c in ADAPTER_CASES]
)
def test_warm_open_byte_identical_to_cold(storage_factory, name, mk_adapter, build):
    """The differential: compact → warm reopen vs a cold replica, plus a
    post-checkpoint tail only the ingest path can deliver — resulting
    states must be byte-identical for every adapter."""

    async def go():
        s_a = storage_factory("a")
        c1 = await Core.open(make_opts(s_a, mk_adapter()))
        for i in range(24):
            op = build(c1, i)
            await c1.apply_ops(op if isinstance(op, list) else [op])
        await c1.compact()
        # a tail past the checkpoint, from another replica
        w = await Core.open(make_opts(storage_factory("w"), mk_adapter()))
        for i in range(24, 30):
            op = build(w, i)
            await w.apply_ops(op if isinstance(op, list) else [op])
        # warm reopen of replica A's local dir
        warm = await Core.open(
            make_opts(storage_factory("a"), mk_adapter(), create=False)
        )
        assert warm.opened_from_checkpoint, warm.checkpoint_fallback_reason
        await warm.read_remote()
        # cold replica refolds everything
        cold = await Core.open(make_opts(storage_factory("c"), mk_adapter()))
        await cold.read_remote()
        assert warm.with_state(canonical_bytes) == cold.with_state(
            canonical_bytes
        )

    run(go())


def test_warm_open_skips_refold(storage_factory):
    """Warm open must not re-read the compacted history: the tail ingest
    touches only files past the cursor."""

    async def go():
        s_a = storage_factory("a")
        c1 = await Core.open(make_opts(s_a, orset_adapter()))
        for i in range(40):
            await c1.apply_ops([_ops_orset(c1, i)])
        await c1.compact()
        w = await Core.open(make_opts(storage_factory("w"), orset_adapter()))
        await w.apply_ops([_ops_orset(w, 99)])
        trace.reset()
        warm = await Core.open(
            make_opts(storage_factory("a"), orset_adapter(), create=False)
        )
        assert warm.opened_from_checkpoint
        await warm.read_remote()
        counters = trace.snapshot()["counters"]
        trace.reset()
        folded = counters.get("ops_folded", 0) + counters.get(
            "op_files_bulk_folded", 0
        )
        assert folded <= 1, f"warm open refolded history: {counters}"
        # and the warm state still contains the full history
        assert warm.with_state(lambda s: s.contains(b"m0"))

    run(go())


def test_checkpoint_on_read_consumer_replica(storage_factory):
    """A pure consumer (never compacts) with checkpoint_on_read reseals
    after each ingest and warm-opens from it."""

    async def go():
        w = await Core.open(make_opts(storage_factory("w"), orset_adapter()))
        for i in range(20):
            await w.apply_ops([_ops_orset(w, i)])
        s_r = storage_factory("r")
        reader = await Core.open(
            make_opts(s_r, orset_adapter(), checkpoint_on_read=True)
        )
        await reader.read_remote()
        reopened = await Core.open(
            make_opts(storage_factory("r"), orset_adapter(), create=False)
        )
        assert reopened.opened_from_checkpoint
        assert reopened.with_state(canonical_bytes) == reader.with_state(
            canonical_bytes
        )

    run(go())


# ---- fallbacks -------------------------------------------------------------


def _truncate_checkpoint(storage) -> None:
    if isinstance(storage, MemoryStorage):
        assert storage._local_checkpoint
        storage._local_checkpoint = storage._local_checkpoint[:-7]
    else:
        import os

        path = storage._local_checkpoint_path()
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[:-7])


def test_torn_checkpoint_falls_back_cold(storage_factory):
    async def go():
        s_a = storage_factory("a")
        c1 = await Core.open(make_opts(s_a, orset_adapter()))
        for i in range(25):
            await c1.apply_ops([_ops_orset_rm(c1, i)])
        await c1.compact()
        cold_bytes = c1.with_state(canonical_bytes)
        _truncate_checkpoint(storage_factory("a"))
        trace.reset()
        warm = await Core.open(
            make_opts(storage_factory("a"), orset_adapter(), create=False)
        )
        assert not warm.opened_from_checkpoint
        assert warm.checkpoint_fallback_reason == "unreadable"
        assert trace.snapshot()["counters"].get("checkpoint_fallbacks") == 1
        trace.reset()
        await warm.read_remote()
        assert warm.with_state(canonical_bytes) == cold_bytes

    run(go())


def test_key_rotation_invalidates_checkpoint(storage_factory):
    async def go():
        s_a = storage_factory("a")
        c1 = await Core.open(make_opts(s_a, orset_adapter()))
        for i in range(10):
            await c1.apply_ops([_ops_orset(c1, i)])
        await c1.compact()
        await c1.rotate_key()  # checkpoint now belongs to an old generation
        warm = await Core.open(
            make_opts(storage_factory("a"), orset_adapter(), create=False)
        )
        assert not warm.opened_from_checkpoint
        assert warm.checkpoint_fallback_reason == "key_rotation"
        await warm.read_remote()
        assert warm.with_state(canonical_bytes) == c1.with_state(
            canonical_bytes
        )

    run(go())


def test_adapter_mismatch_falls_back(storage_factory):
    async def go():
        s_a = storage_factory("a")
        c1 = await Core.open(make_opts(s_a, gcounter_adapter()))
        await c1.apply_ops([c1.with_state(lambda s: s.inc(c1.actor_id, 3))])
        await c1.compact()
        warm = await Core.open(
            make_opts(storage_factory("a"), orset_adapter(), create=False)
        )
        assert not warm.opened_from_checkpoint
        assert warm.checkpoint_fallback_reason == "adapter"

    run(go())


def test_wiped_remote_rejects_checkpoint(tmp_path):
    """A checkpoint must never install over a remote it did not come
    from: wipe the remote, re-bootstrap, reopen the old local dir."""
    import shutil

    remote = tmp_path / "remote"

    async def go():
        c1 = await Core.open(
            make_opts(
                FsStorage(str(tmp_path / "localA"), str(remote)),
                orset_adapter(),
            )
        )
        for i in range(12):
            await c1.apply_ops([_ops_orset(c1, i)])
        await c1.compact()
        shutil.rmtree(remote)
        # someone re-creates a fresh remote under the same path
        boot = await Core.open(
            make_opts(
                FsStorage(str(tmp_path / "localB"), str(remote)),
                orset_adapter(),
            )
        )
        await boot.apply_ops([_ops_orset(boot, 0)])
        warm = await Core.open(
            make_opts(
                FsStorage(str(tmp_path / "localA"), str(remote)),
                orset_adapter(),
                create=False,
            )
        )
        assert not warm.opened_from_checkpoint
        # the fresh remote bootstrapped a new key generation (and new
        # metadata) — either fingerprint check must trip
        assert warm.checkpoint_fallback_reason in (
            "key_rotation", "remote_meta", "unreadable",
        )

    run(go())


def test_checkpoint_disabled_never_writes(storage_factory):
    async def go():
        s_a = storage_factory("a")
        c1 = await Core.open(
            make_opts(s_a, orset_adapter(), checkpoint=False)
        )
        for i in range(8):
            await c1.apply_ops([_ops_orset(c1, i)])
        await c1.compact()
        assert not await c1.save_checkpoint()
        assert await s_a.load_local_checkpoint() is None

    run(go())


# ---- fsck --verify-checkpoint ---------------------------------------------


def test_fsck_verify_checkpoint_ok_and_divergent(storage_factory):
    from crdt_enc_tpu.tools.fsck import verify_checkpoint

    async def go():
        s_a = storage_factory("a")
        c1 = await Core.open(make_opts(s_a, orset_adapter()))
        for i in range(25):
            await c1.apply_ops([_ops_orset_rm(c1, i)])
        # pre-compact: refold replays op files
        await c1.save_checkpoint()
        r = await verify_checkpoint(
            s_a, storage_factory("x"), IdentityCryptor(), PlainKeyCryptor()
        )
        assert r.ok and r.op_files > 0, [str(i) for i in r.issues]
        # post-compact: refold goes through the snapshot
        await c1.compact()
        r = await verify_checkpoint(
            s_a, storage_factory("x"), IdentityCryptor(), PlainKeyCryptor()
        )
        assert r.ok and r.state_files == 1, [str(i) for i in r.issues]
        # forge a diverging checkpoint (sealed correctly, wrong state)
        from crdt_enc_tpu.models import ORSet
        from crdt_enc_tpu.models.orset import AddOp
        from crdt_enc_tpu.models.vclock import Dot

        real = c1._data.state
        bogus = ORSet()
        bogus.apply(AddOp(b"bogus", Dot(c1.actor_id, 1)))
        c1._data.state = bogus
        await c1.save_checkpoint()
        c1._data.state = real
        r = await verify_checkpoint(
            s_a, storage_factory("x"), IdentityCryptor(), PlainKeyCryptor()
        )
        assert not r.ok
        assert any(
            i.family == "checkpoint" and "diverges" in i.problem
            for i in r.issues
        )

    run(go())


def test_fsck_cli_verify_checkpoint_flag(tmp_path):
    """End-to-end CLI: a real XChaCha-sealed remote, --verify-checkpoint
    passes on an honest local dir and exits 1 on a forged one."""
    pytest.importorskip("crdt_enc_tpu.native")
    from crdt_enc_tpu.backends import XChaChaCryptor
    from crdt_enc_tpu.tools import fsck as fsck_cli

    try:
        from crdt_enc_tpu import native

        native.load()
    except Exception:
        pytest.skip("native crypto unavailable")

    remote = str(tmp_path / "remote")
    local = str(tmp_path / "localA")

    async def build():
        c1 = await Core.open(
            OpenOptions(
                storage=FsStorage(local, remote),
                cryptor=XChaChaCryptor(),
                key_cryptor=PlainKeyCryptor(),
                adapter=orset_adapter(),
                supported_data_versions=(DEFAULT_DATA_VERSION_1,),
                current_data_version=DEFAULT_DATA_VERSION_1,
                create=True,
            )
        )
        for i in range(20):
            await c1.apply_ops([_ops_orset(c1, i)])
        await c1.compact()
        return c1

    run(build())
    assert fsck_cli.main([remote, "--verify-checkpoint", local]) == 0
    # a torn checkpoint is an error row for fsck (the core would fall
    # back silently; fsck's job is to say so loudly)
    import os

    path = os.path.join(local, "checkpoint.msgpack")
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[:-5])
    assert fsck_cli.main([remote, "--verify-checkpoint", local]) == 1


# ---- checkpoint from streaming-fold rows (ISSUE 13: zero dict walk) -------


def test_pack_checkpoint_rows_semantically_equal_to_dict_walk():
    """A fresh streaming fold stashes its surviving rows; packing the
    checkpoint from them must unpack to a state canonically identical
    to the dict-walk pack, and the stash must be mut-epoch-guarded."""
    import secrets

    import numpy as np

    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.models.orset import AddOp
    from crdt_enc_tpu.models.vclock import Dot
    from crdt_enc_tpu.ops import columnar as C
    from crdt_enc_tpu.ops.columnar import Vocab

    rng = np.random.default_rng(4)
    R, E, N = 64, 200, 9000  # ≥ CKPT_STASH_MIN_ROWS surviving rows
    actors = sorted(secrets.token_bytes(16) for _ in range(R))
    members = Vocab(list(range(E)))
    replicas = Vocab(actors)
    counters = np.zeros(R, np.int64)
    kind = np.zeros(N, np.int8)
    member = rng.integers(0, E, N).astype(np.int32)
    actor = rng.integers(0, R, N).astype(np.int32)
    ctr = np.zeros(N, np.int32)
    for i in range(N):
        a = int(actor[i])
        roll = rng.random()
        if roll < 0.05:
            # future-horizon remove: survives the merged clock, so the
            # DEFERRED table (dm/da/dc) gets real coverage too
            kind[i] = 1
            ctr[i] = counters[a] + 3
        elif roll < 0.18 and counters[a]:
            kind[i] = 1
            ctr[i] = counters[a]
        else:
            counters[a] += 1
            ctr[i] = counters[a]
    state = ORSet()
    C.orset_fold_sparse_host(
        state, kind, member, actor, ctr, members, replicas
    )
    stash = getattr(state, "_ckpt_rows", None)
    assert stash is not None and stash[0] == state._mut
    from_rows = C.orset_unpack_checkpoint(
        C.orset_pack_checkpoint_rows(*stash[1])
    )
    from_dicts = C.orset_unpack_checkpoint(C.orset_pack_checkpoint(state))
    assert codec.pack(from_rows.to_obj()) == codec.pack(state.to_obj())
    assert codec.pack(from_rows.to_obj()) == codec.pack(from_dicts.to_obj())
    # a later mutation invalidates the stash via the epoch guard
    state.apply(AddOp(0, Dot(actors[0], int(counters[0]) + 1)))
    assert stash[0] != state._mut


def test_streaming_compact_checkpoints_from_rows(storage_factory, monkeypatch):
    """End-to-end: a core whose ingest ran the fresh streaming fold
    seals its warm-open checkpoint FROM THE STASHED ROWS (the dict-walk
    packer is forbidden by the spy), and the warm reopen restores a
    state byte-identical to a cold refold."""
    import crdt_enc_tpu.core.core as core_mod
    from crdt_enc_tpu.ops import columnar as C
    from crdt_enc_tpu.parallel.accel import TpuAccelerator

    monkeypatch.setattr(C, "CKPT_STASH_MIN_ROWS", 1)
    # the tiny test shape would pick the dense device fold; the rows
    # stash rides the sparse host regime (the config-5 streaming shape)
    monkeypatch.setattr(
        TpuAccelerator, "_use_sparse", lambda self, E, R, n: True
    )

    async def go():
        writer = await Core.open(
            make_opts(storage_factory("w"), orset_adapter())
        )
        for i in range(core_mod.BULK_MIN_FILES + 8):
            await writer.apply_ops(
                [writer.with_state(
                    lambda s: s.add_ctx(writer.actor_id, i % 9)
                )]
            )
        reader = await Core.open(make_opts(
            storage_factory("r"), orset_adapter(),
            accelerator=TpuAccelerator(min_device_batch=1),
        ))

        def forbidden(state):
            raise AssertionError(
                "dict-walk checkpoint pack ran despite a fresh rows stash"
            )

        monkeypatch.setattr(C, "orset_pack_checkpoint", forbidden)
        await reader.compact()
        monkeypatch.undo()

        warm = await Core.open(make_opts(
            storage_factory("r"), orset_adapter(), create=False,
        ))
        assert warm.checkpoint_fallback_reason is None
        cold = await Core.open(make_opts(
            storage_factory("cold"), orset_adapter(),
        ))
        await cold.read_remote()
        assert warm.with_state(canonical_bytes) == cold.with_state(
            canonical_bytes
        )

    run(go())

"""Multi-host layer on the virtual 8-device CPU mesh.

A real DCN cluster is not available in tests, so process boundaries are
*faked* through ``local_count``: an 8-device "pod" treated as 4 hosts × 2
chips must place hosts along ``dp`` (each host folds only its own rows;
the single cross-host collective is the ``pmax`` of folded partial planes)
and each host's chips along ``mp`` (member-sharded planes, no fold-time
collectives — ICI in production).  The globally-sharded batch assembly
runs the same downstream fold path a multi-process run takes
(``make_array_from_process_local_data`` itself degrades to a sharded
``device_put`` when process_count == 1).
"""

import uuid

import jax
import numpy as np

from crdt_enc_tpu import ops as K
from crdt_enc_tpu.models import ORSet, canonical_bytes
from crdt_enc_tpu.models.orset import AddOp, RmOp
from crdt_enc_tpu.models.vclock import Dot, VClock
from crdt_enc_tpu.parallel import distributed, mesh as pmesh

ACTORS = [uuid.UUID(int=i + 1).bytes for i in range(4)]


def test_initialize_single_process_is_noop():
    # no coordinator configured, backend already up → nothing to bootstrap
    assert distributed.initialize() is False
    # and calling it again stays safe
    assert distributed.initialize() is False


def test_multihost_mesh_places_hosts_on_dp():
    devices = jax.devices()
    assert len(devices) == 8
    mesh = distributed.make_multihost_mesh(local_count=2)  # fake 4 hosts × 2
    assert mesh.shape == {"dp": 4, "mp": 2}
    arr = mesh.devices
    # row i must be exactly host i's chips (process-ordered pairs): each
    # host is one dp shard, so its locally-decoded rows never leave it
    for host in range(4):
        row = list(arr[host, :])
        assert row == devices[2 * host : 2 * host + 2]


def test_multihost_mesh_single_host_degrades_to_all_mp():
    mesh = distributed.make_multihost_mesh()
    assert mesh.shape == {"dp": 1, "mp": 8}


def _op_columns(n, R, E, seed=0):
    rng = np.random.default_rng(seed)
    kind = (rng.random(n) < 0.2).astype(np.int8)
    member = rng.integers(0, E, n, dtype=np.int32)
    actor = rng.integers(0, R, n, dtype=np.int32)
    counter = np.zeros(n, np.int32)
    seen = np.zeros(R, np.int32)
    for i in range(n):
        a = actor[i]
        if kind[i] == 0:
            seen[a] += 1
            counter[i] = seen[a]
        else:
            if seen[a] == 0:
                actor[i] = R  # nothing to remove → pad row
            counter[i] = seen[a]
    return kind, member, actor, counter


def _host_fold(kind, member, actor, counter, R):
    state = ORSet()
    for k, m, a, c in zip(kind, member, actor, counter):
        if a >= R:
            continue
        if k == 0:
            state.apply(AddOp(int(m), Dot(ACTORS[a], int(c))))
        else:
            state.apply(RmOp(int(m), VClock({ACTORS[a]: int(c)})))
    return state


def test_global_batch_fold_on_multihost_mesh_matches_host():
    """End to end: sharded batch assembly → sharded fold over a fake
    4-host mesh → byte-identical state vs the per-op host loop."""
    R, E = 4, 8
    n = 93  # deliberately not a multiple of dp → exercises sentinel padding
    kind, member, actor, counter = _op_columns(n, R, E, seed=3)
    host = _host_fold(kind, member, actor, counter, R)

    mesh = distributed.make_multihost_mesh(local_count=2)  # dp=4, mp=2
    batch = distributed.global_op_batch(
        mesh, kind, member, actor, counter, num_replicas=R
    )
    assert len(batch[0]) % mesh.shape["dp"] == 0
    clock0, add0, rm0 = distributed.replicate(
        mesh, np.zeros(R, np.int32), np.zeros((E, R), np.int32),
        np.zeros((E, R), np.int32),
    )
    clock, add, rm = pmesh.orset_fold_sharded(mesh, clock0, add0, rm0, *batch)

    members = K.Vocab(range(E))
    replicas = K.Vocab(ACTORS)
    folded = K.orset_planes_to_state(
        np.asarray(clock), np.asarray(add), np.asarray(rm), members, replicas
    )
    assert canonical_bytes(folded) == canonical_bytes(host)


def test_global_batch_respects_explicit_rows_per_host():
    """rows_per_host (the cross-host row bucket) pads above the minimum —
    extra rows must be inert sentinels."""
    R, E = 4, 8
    kind, member, actor, counter = _op_columns(40, R, E, seed=9)
    host = _host_fold(kind, member, actor, counter, R)
    mesh = distributed.make_multihost_mesh(local_count=2)
    batch = distributed.global_op_batch(
        mesh, kind, member, actor, counter, num_replicas=R, rows_per_host=64
    )
    assert len(batch[0]) == 64 * mesh.shape["dp"]  # one bucket per dp shard
    clock0, add0, rm0 = distributed.replicate(
        mesh, np.zeros(R, np.int32), np.zeros((E, R), np.int32),
        np.zeros((E, R), np.int32),
    )
    clock, add, rm = pmesh.orset_fold_sharded(mesh, clock0, add0, rm0, *batch)
    folded = K.orset_planes_to_state(
        np.asarray(clock), np.asarray(add), np.asarray(rm),
        K.Vocab(range(E)), K.Vocab(ACTORS),
    )
    assert canonical_bytes(folded) == canonical_bytes(host)

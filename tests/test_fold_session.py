"""Chunked fold sessions (parallel/session.py) and the pipelined bulk
ingest (core._read_remote_ops_pipelined): every mode must land byte-equal
to the per-op host loop, chunk boundaries must not show, and declines /
races must degrade without losing data."""

import asyncio

import numpy as np
import pytest

from crdt_enc_tpu import ops as K
from crdt_enc_tpu.backends import (
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import (
    Core,
    OpenOptions,
    gcounter_adapter,
    orset_adapter,
    pncounter_adapter,
)
from crdt_enc_tpu.models import ORSet, PNCounter, canonical_bytes
from crdt_enc_tpu.models.orset import AddOp, RmOp
from crdt_enc_tpu.models.vclock import Dot, VClock
from crdt_enc_tpu.parallel import TpuAccelerator
from crdt_enc_tpu.parallel.session import (
    OrsetFoldSession,
    SessionDeclined,
    apply_batch_planes_host,
    open_fold_session,
)
from crdt_enc_tpu.utils import codec
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

ACTORS = [bytes([i + 1]) * 16 for i in range(5)]


def run(coro):
    return asyncio.run(coro)


# ---- session unit level ----------------------------------------------------


def _history(n_ops, n_members, seed=0, rm_every=7):
    """A well-formed multi-actor op history + the host-folded state."""
    rng = np.random.default_rng(seed)
    state = ORSet()
    ops = []
    for i in range(n_ops):
        a = ACTORS[int(rng.integers(len(ACTORS)))]
        m = int(rng.integers(n_members))
        if i % rm_every == rm_every - 1 and state.contains(m):
            op = state.rm_ctx(m)
        else:
            op = state.add_ctx(a, m)
        state.apply(op)
        ops.append(op)
    return state, ops


def _payloads(ops, per_file=10):
    """Op files exactly as the wire carries them (msgpack op arrays)."""
    out = []
    for lo in range(0, len(ops), per_file):
        out.append(codec.pack([op.to_obj() for op in ops[lo : lo + per_file]]))
    return out


def _run_session(ops, *, chunk_files, force_mode=None, state=None):
    accel = TpuAccelerator(min_device_batch=1)
    state = state if state is not None else ORSet()
    session = OrsetFoldSession(accel, state, actors_hint=ACTORS)
    if force_mode == "host_reduce":
        session._buffered_bytes = 10**9  # promote on first feed
    elif force_mode == "device_stream":
        session._buffered_bytes = 10**9
        OrsetFoldSession_promote_to_device(session)
    payloads = _payloads(ops)
    for lo in range(0, len(payloads), chunk_files):
        session.feed(payloads[lo : lo + chunk_files])
    return session.finish()


def OrsetFoldSession_promote_to_device(session):
    # force the device path regardless of plane size
    import crdt_enc_tpu.parallel.session as S

    session._orig_cells = S.HOST_PLANE_CELLS
    S.HOST_PLANE_CELLS = -1


@pytest.fixture(autouse=True)
def _restore_thresholds():
    import crdt_enc_tpu.parallel.session as S

    cells = S.HOST_PLANE_CELLS
    yield
    S.HOST_PLANE_CELLS = cells


@pytest.mark.parametrize("force_mode", [None, "host_reduce", "device_stream"])
@pytest.mark.parametrize("chunk_files", [1, 3, 50])
def test_session_modes_match_host(force_mode, chunk_files):
    host, ops = _history(400, 23, seed=3)
    folded = _run_session(ops, chunk_files=chunk_files, force_mode=force_mode)
    assert canonical_bytes(folded) == canonical_bytes(host), (
        force_mode,
        chunk_files,
    )


def test_device_stream_pallas_route_matches_host():
    """The DEVICE_STREAM fold's Pallas route (real-TPU default; interpret
    mode here) must byte-match the host fold — including the
    retire_rm=False discipline the session relies on."""
    import crdt_enc_tpu.parallel.session as S

    host, ops = _history(400, 23, seed=6)
    S.FORCE_PALLAS_STREAM = True
    try:
        folded = _run_session(ops, chunk_files=3, force_mode="device_stream")
    finally:
        S.FORCE_PALLAS_STREAM = None
    assert canonical_bytes(folded) == canonical_bytes(host)


@pytest.mark.parametrize("force_mode", ["host_reduce", "device_stream"])
def test_session_into_existing_state_matches_host(force_mode):
    """Folding a tail into a state that already holds a prefix (the
    snapshot-resume shape) — including removes whose targets live only
    in the prefix state."""
    host, ops = _history(300, 17, seed=5, rm_every=5)
    prefix = ORSet()
    for op in ops[:120]:
        prefix.apply(op)
    folded = _run_session(
        ops[120:], chunk_files=2, force_mode=force_mode,
        state=ORSet.from_obj(prefix.to_obj()),
    )
    assert canonical_bytes(folded) == canonical_bytes(host)


def test_host_and_device_combine_never_diverge():
    rng = np.random.default_rng(7)
    for _ in range(20):
        E, R = int(rng.integers(1, 12)), int(rng.integers(1, 6))
        clock0 = rng.integers(0, 9, R).astype(np.int32)
        add0 = rng.integers(0, 9, (E, R)).astype(np.int32)
        rm0 = rng.integers(0, 9, (E, R)).astype(np.int32)
        add_b = rng.integers(0, 12, (E, R)).astype(np.int32)
        rm_b = rng.integers(0, 12, (E, R)).astype(np.int32)
        h = apply_batch_planes_host(clock0, add0, rm0, add_b, rm_b)
        d = K.orset_apply_batch_planes(clock0, add0, rm0, add_b, rm_b)
        for a, b in zip(h, d):
            assert np.array_equal(a, np.asarray(b))


def test_counter_session_matches_host():
    accel = TpuAccelerator(min_device_batch=1)
    host = PNCounter()
    ops = []
    for i in range(200):
        a = ACTORS[i % 3]
        op = host.inc(a, i + 1) if i % 4 else host.dec(a, 2)
        host.apply(op)
        ops.append([op[0], op[1].to_obj()])
    payloads = [codec.pack(ops[lo : lo + 9]) for lo in range(0, len(ops), 9)]
    state = PNCounter()
    session = open_fold_session(accel, state, actors_hint=ACTORS)
    for p in payloads:
        session.feed([p])
    session.finish()
    assert canonical_bytes(state) == canonical_bytes(host)
    assert state.read() == host.read()


def test_session_decline_leaves_chunk_unconsumed():
    accel = TpuAccelerator(min_device_batch=1)
    state = ORSet()
    session = OrsetFoldSession(accel, state, actors_hint=ACTORS)
    host, ops = _history(40, 7, seed=2)
    session.feed(_payloads(ops))
    with pytest.raises(SessionDeclined):
        session.feed([b"\xc1 definitely not msgpack ops"])
    # the good chunk still lands
    folded = session.finish()
    assert canonical_bytes(folded) == canonical_bytes(host)


# ---- through the live core -------------------------------------------------


def make_opts(remote, adapter=None, accel=None):
    kw = {"accelerator": accel} if accel else {}
    return OpenOptions(
        storage=MemoryStorage(remote),
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter or orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
        **kw,
    )


def _chunked_storage(remote, files_per_chunk):
    """MemoryStorage that yields op chunks of a few files — exercises the
    pipeline's chunk boundaries without a real fs."""

    class ChunkedMemoryStorage(MemoryStorage):
        async def iter_op_chunks(self, wanted, max_bytes=1 << 30):
            files = await self.load_ops(wanted)
            for lo in range(0, len(files), files_per_chunk):
                yield files[lo : lo + files_per_chunk]

    return ChunkedMemoryStorage(remote)


@pytest.mark.parametrize("files_per_chunk", [1, 5, 64])
def test_pipelined_ingest_matches_host_core(files_per_chunk):
    async def go():
        remote = MemoryRemote()
        producer = await Core.open(make_opts(remote))
        for w in range(40):
            await producer.update(
                lambda s, w=w: s.add_ctx(producer.actor_id, w % 19)
            )
        for m in (3, 8):
            await producer.update(lambda s, m=m: s.rm_ctx(m))

        host = await Core.open(make_opts(remote))
        await host.read_remote()

        reader_opts = make_opts(remote, accel=TpuAccelerator(min_device_batch=1))
        reader_opts.storage = _chunked_storage(remote, files_per_chunk)
        reader = await Core.open(reader_opts)
        await reader.read_remote()
        assert reader.with_state(canonical_bytes) == host.with_state(
            canonical_bytes
        )
        # and the stream is re-entrant: a second read is a no-op
        await reader.read_remote()
        assert reader.with_state(canonical_bytes) == host.with_state(
            canonical_bytes
        )

    run(go())


def test_pipelined_ingest_counters(files_per_chunk=4):
    async def go():
        remote = MemoryRemote()
        producer = await Core.open(make_opts(remote, adapter=pncounter_adapter()))
        for i in range(30):
            await producer.update(
                lambda s, i=i: s.inc(producer.actor_id, i + 1)
                if i % 3
                else s.dec(producer.actor_id, 1)
            )
        host = await Core.open(make_opts(remote, adapter=pncounter_adapter()))
        await host.read_remote()
        reader_opts = make_opts(
            remote, adapter=pncounter_adapter(),
            accel=TpuAccelerator(min_device_batch=1),
        )
        reader_opts.storage = _chunked_storage(remote, files_per_chunk)
        reader = await Core.open(reader_opts)
        await reader.read_remote()
        assert reader.with_state(canonical_bytes) == host.with_state(
            canonical_bytes
        )
        assert reader.with_state(lambda s: s.read()) == host.with_state(
            lambda s: s.read()
        )

    run(go())


def test_concurrent_apply_during_pipelined_ingest_survives():
    """A local write landing BETWEEN pipeline chunks must not be clobbered
    by the session's finish (the finish re-reads the state in its sync
    section; host-reduce re-masks against the current clock)."""

    async def go():
        remote = MemoryRemote()
        producer = await Core.open(make_opts(remote))
        for w in range(30):
            await producer.update(
                lambda s, w=w: s.add_ctx(producer.actor_id, w)
            )

        reader_opts = make_opts(remote, accel=TpuAccelerator(min_device_batch=1))
        base = _chunked_storage(remote, 5)
        reader_holder = {}

        class RacingStorage(type(base)):
            async def iter_op_chunks(self, wanted, max_bytes=1 << 30):
                n = 0
                async for chunk in super().iter_op_chunks(wanted, max_bytes):
                    yield chunk
                    n += 1
                    if n == 2 and "core" in reader_holder:
                        # a local write lands mid-ingest
                        core = reader_holder["core"]
                        await core.update(
                            lambda s: s.add_ctx(core.actor_id, b"local-mid")
                        )

        racing = RacingStorage(remote)
        reader_opts.storage = racing
        reader = await Core.open(reader_opts)
        reader_holder["core"] = reader
        await reader.read_remote()
        # both the remote history AND the mid-ingest local write survive
        assert reader.with_state(lambda s: s.contains(b"local-mid"))
        for w in range(30):
            assert reader.with_state(lambda s, w=w: s.contains(w)), w

    run(go())


def test_empty_crdt_falls_back_to_legacy():
    """No columnar session exists for EmptyCrdt-style adapters — the
    pipelined path must bow out cleanly."""
    from crdt_enc_tpu.core import empty_adapter

    async def go():
        remote = MemoryRemote()
        producer = await Core.open(make_opts(remote, adapter=empty_adapter()))
        for _ in range(20):
            await producer.apply_ops([None])
        reader = await Core.open(
            make_opts(
                remote, adapter=empty_adapter(),
                accel=TpuAccelerator(min_device_batch=1),
            )
        )
        await reader.read_remote()  # must not raise

    run(go())


def test_concurrent_new_actor_before_finish():
    """An apply from an actor unknown at session init landing before
    finish() must neither crash (the state planes then carry more replica
    columns than the batch planes) nor be clobbered by the writeback."""
    host, ops = _history(200, 11, seed=8)
    accel = TpuAccelerator(min_device_batch=1)
    state = ORSet()
    session = OrsetFoldSession(accel, state, actors_hint=ACTORS)
    session._buffered_bytes = 10**9  # promote to host_reduce on first feed
    payloads = _payloads(ops)
    for lo in range(0, len(payloads), 4):
        session.feed(payloads[lo : lo + 4])
    # a brand-new actor writes directly to the state mid-session
    newcomer = b"\xaa" * 16
    late = state.add_ctx(newcomer, b"late-member")
    state.apply(late)
    host.apply(AddOp(b"late-member", late.dot))
    folded = session.finish()
    assert folded.contains(b"late-member")
    assert canonical_bytes(folded) == canonical_bytes(host)


def test_mid_stream_decline_keeps_version_order():
    """A chunk the native decoder declines (here: an op whose dot actor
    appears in no op directory or state) flips the pipeline to per-op
    folds — chunks already validated and in flight must fold IN ORDER
    first, or the version-gap check would trip on a newer chunk."""

    async def go():
        remote = MemoryRemote()
        producer = await Core.open(make_opts(remote))
        fake = b"\xbb" * 16  # a dot actor with no op dir: decoder declines
        for w in range(30):
            if w == 12:
                await producer.apply_ops([AddOp(999, Dot(fake, 1))])
            else:
                await producer.update(
                    lambda s, w=w: s.add_ctx(producer.actor_id, w)
                )
        host = await Core.open(make_opts(remote))
        await host.read_remote()
        reader_opts = make_opts(remote, accel=TpuAccelerator(min_device_batch=1))
        reader_opts.storage = _chunked_storage(remote, 3)
        reader = await Core.open(reader_opts)
        await reader.read_remote()
        assert reader.with_state(canonical_bytes) == host.with_state(
            canonical_bytes
        )
        assert reader.with_state(lambda s: s.contains(999))

    run(go())


def test_scan_error_propagates_not_hangs(tmp_path):
    """A dead actor scanner must deliver its failure to the chunk emitter,
    not leave it awaiting a sentinel that never comes."""
    import os as _os

    import crdt_enc_tpu.backends.fs as fsmod
    from crdt_enc_tpu import native
    from crdt_enc_tpu.backends.fs import FsStorage

    async def go():
        s = FsStorage(str(tmp_path / "l"), str(tmp_path / "remote"))
        actor = b"\x07" * 16
        for v in range(1, 8):
            await s.store_ops(actor, v, bytes([v]) * 30)

        lib = native.load()

        def broken_read(*a):
            return -1  # force the per-file fallback

        real_rf = fsmod._read_file

        def failing_rf(path):
            if path.endswith(_os.sep + "4"):
                raise PermissionError(path)
            return real_rf(path)

        import unittest.mock as mock

        with mock.patch.object(lib, "read_op_files", broken_read), \
                mock.patch.object(fsmod, "_read_file", failing_rf):
            with pytest.raises(PermissionError):
                chunks = []
                async for c in s.iter_op_chunks([(actor, 1)]):
                    chunks.append(c)

    # a hang would block forever; wrap in a timeout to fail loudly instead
    async def with_timeout():
        await asyncio.wait_for(go(), timeout=30)

    run(with_timeout())


@pytest.mark.parametrize("force_mode", ["host_reduce", "device_stream"])
def test_session_keeps_untouched_preexisting_members(force_mode):
    """Regression (confirmed data loss): a pre-existing member whose dot
    is OLDER than the batch's dots for the same actor, and which the
    batch never mentions, must survive the session.  The zero-seeded
    device planes' per-actor add maxima cover such dots, so combining
    them with the CvRDT merge (instead of op-apply semantics) deleted
    the member; `_history`'s small cycling member pool masked it because
    every prefix member was re-added in the tail."""
    actor = ACTORS[0]
    base = ORSet()
    base.apply(base.add_ctx(actor, "old-untouched"))
    host = ORSet.from_obj(base.to_obj())
    ops = []
    for i in range(40):  # later dots by the SAME actor, other members
        op = host.add_ctx(actor, f"new-{i}")
        host.apply(op)
        ops.append(op)
    folded = _run_session(
        ops, chunk_files=2, force_mode=force_mode,
        state=ORSet.from_obj(base.to_obj()),
    )
    assert folded.contains("old-untouched"), force_mode
    assert canonical_bytes(folded) == canonical_bytes(host), force_mode


def test_encrypted_stream_device_mode_matches_host(monkeypatch):
    """ISSUE 1 differential: the full overlapped pipeline (threaded
    decrypt + decode producer → session consumer) forced through the
    DEVICE_STREAM donated-fold mode lands byte-identical to the per-op
    host loop — streaming ≡ whole-batch on the device path too."""
    import secrets

    import crdt_enc_tpu.parallel.session as S
    from crdt_enc_tpu import native
    from crdt_enc_tpu.backends import xchacha

    try:
        native.load()
    except RuntimeError as e:
        pytest.skip(f"native crypto library unavailable: {e}")
    monkeypatch.setattr(S, "BUFFER_BYTES", 0)  # promote on first chunk
    monkeypatch.setattr(S, "HOST_PLANE_CELLS", -1)  # ... to device planes
    host, ops = _history(300, 17, seed=6)
    key = secrets.token_bytes(32)
    blobs = [xchacha.encrypt_blob(key, p) for p in _payloads(ops)]
    accel = TpuAccelerator(min_device_batch=1)
    streamed = ORSet()
    ok = accel.fold_encrypted_stream(
        streamed, key, blobs, actors_hint=ACTORS, n_chunks=5
    )
    assert ok
    assert canonical_bytes(streamed) == canonical_bytes(host)

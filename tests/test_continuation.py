"""O(tail) steady state (ISSUE 16): persistent fold continuations +
device-cut delta sealing.

The contract under test: the serve tier's steady-state cost must scale
with the TAIL (new ops since the last seal), not with resident STATE —
without moving a single sealed byte.  Three seams, each pinned
differentially against the paths they replace:

* **Device-cut deltas** — ``ops.orset_plane_diff`` (+ the rows gather
  and the host builder ``delta.codec.orset_delta_from_rows``) must
  reproduce the host dict-walk ``orset_delta_diff`` wire form
  byte-for-byte, solo and on the virtual mesh.
* **Persistent continuations** — a FoldService cycle that folds a
  tenant's new rows onto warm resident planes and seals the delta by
  device cut (dropping the retained host base) must stay byte-identical
  to solo ``Core.compact()``, cold readers, and delta-chain consumers,
  with the seal-time self-verify still on.
* **Honest no-ops** — a quiet tenant (no new rows, no local mutation)
  skips device dispatch, state H2D, and every non-listing storage
  probe; eviction or a mut-epoch bump degrade to the full re-fold with
  the reason counted, never to silence.
"""

import asyncio
import copy
import random

import numpy as np
import pytest

from crdt_enc_tpu import ops as K
from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import (
    Core,
    OpenOptions,
    gcounter_adapter,
    orset_adapter,
)
from crdt_enc_tpu.delta import ResettableCounter, rcounter_adapter
from crdt_enc_tpu.delta.codec import orset_delta_diff, orset_delta_from_rows
from crdt_enc_tpu.models import ORSet, VClock, canonical_bytes
from crdt_enc_tpu.models.orset import AddOp, Dot, RmOp
from crdt_enc_tpu.obs import runtime as obs_runtime
from crdt_enc_tpu.parallel import TpuAccelerator
from crdt_enc_tpu.parallel import mesh as pmesh
from crdt_enc_tpu.serve import FoldService, ServeConfig
from crdt_enc_tpu.utils import codec as ucodec
from crdt_enc_tpu.utils import trace
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, adapter=None, create=True, **kw):
    kw.setdefault("accelerator", TpuAccelerator(min_device_batch=1))
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter if adapter is not None else orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
        **kw,
    )


@pytest.fixture(params=["memory", "fs"])
def storage_factory(request, tmp_path):
    if request.param == "memory":
        remote = MemoryRemote()
        instances: dict = {}

        def make(name="a"):
            return instances.setdefault(name, MemoryStorage(remote))

        make.remote = remote
        return make
    remote_dir = tmp_path / "remote"

    def make(name="a"):
        return FsStorage(str(tmp_path / f"local-{name}"), str(remote_dir))

    make.remote = None
    return make


def counters():
    return trace.snapshot()["counters"]


def gauges():
    return trace.snapshot()["gauges"]


# ------------------------------------------------- kernel differentials


def _rand_orset(rng, rounds):
    s = ORSet()
    for _ in range(rounds):
        m = b"m%d" % rng.randrange(8)
        r = b"r%d" % rng.randrange(4)
        if rng.random() < 0.65:
            s.apply(AddOp(m, Dot(r, s.clock.get(r) + rng.randrange(1, 3))))
        else:
            s.apply(RmOp(m, VClock(dict(s.clock.counters))))
    return s


def _evolve(rng, s, rounds):
    n = copy.deepcopy(s)
    for _ in range(rounds):
        m = b"m%d" % rng.randrange(10)
        r = b"r%d" % rng.randrange(4)
        if rng.random() < 0.6:
            n.apply(AddOp(m, Dot(r, n.clock.get(r) + rng.randrange(1, 3))))
        else:
            n.apply(RmOp(m, VClock(dict(n.clock.counters))))
    return n


def _bucket(n, floor=8):
    b = floor
    while b < n:
        b *= 2
    return b


def _cut_on_device(base, new, *, mesh=None):
    """The full device-cut pipeline on two host states: scan a union
    vocab, plane both, diff on device, gather the rows, rebuild the
    wire object with the host builder."""
    members, replicas = K.Vocab(), K.Vocab()
    K.orset_scan_vocab(base, members, replicas)
    K.orset_scan_vocab(new, members, replicas)
    cb, ab, rb = K.orset_state_to_planes(base, members, replicas, scanned=True)
    cn, an, rn = K.orset_state_to_planes(new, members, replicas, scanned=True)
    E, R = len(members), len(replicas)
    if mesh is None:
        code, count = K.orset_plane_diff(cb, ab, rb, cn, an, rn)
    else:
        stack = lambda x: np.broadcast_to(np.asarray(x), (8,) + x.shape)
        code_s, count_s = pmesh.tenant_diff_step(mesh)(
            stack(cb), stack(ab), stack(rb), stack(cn), stack(an), stack(rn)
        )
        code, count = np.asarray(code_s)[0], int(np.asarray(count_s)[0])
    size = min(_bucket(max(int(count), 1)), E * R)
    rows = K.orset_plane_diff_rows(code, ab, an, rn, size=size)
    return orset_delta_from_rows(
        tuple(np.asarray(x) for x in rows),
        members=members.items, replicas=replicas.items, row_width=R,
        base_clock=np.asarray(cb), new_clock=np.asarray(cn),
    )


@pytest.mark.parametrize("seed", range(8))
def test_plane_diff_kernel_matches_host_dict_walk(seed):
    """Randomized causal pairs: the device cut's wire object is
    byte-identical (canonical pack) to the host ``orset_delta_diff`` —
    adds, re-add-over-remove confirmations, removals, and horizons."""
    rng = random.Random(seed)
    base = _rand_orset(rng, 60)
    new = _evolve(rng, base, 40)
    host = orset_delta_diff(base, new)
    dev = _cut_on_device(base, new)
    assert ucodec.pack(host) == ucodec.pack(dev)


def test_plane_diff_of_identical_states_is_empty():
    """diff(x, x) = 0 under the canonical plane laws — the property
    that lets ineligible bucket slots ride the diff dispatch free."""
    rng = random.Random(99)
    s = _rand_orset(rng, 50)
    members, replicas = K.Vocab(), K.Vocab()
    K.orset_scan_vocab(s, members, replicas)
    c, a, r = K.orset_state_to_planes(s, members, replicas, scanned=True)
    code, count = K.orset_plane_diff(c, a, r, c, a, r)
    assert int(count) == 0
    assert not np.asarray(code).any()


@pytest.mark.parametrize("dp,mp", [(8, 1), (4, 2), (2, 4)])
def test_plane_diff_sharded_twin_differential(dp, mp):
    """The shard_map twin returns the same per-tenant code planes and
    (mp-psummed) counts as the vmapped single-device kernel."""
    rng = np.random.default_rng(dp * 10 + mp)
    mesh = pmesh.make_mesh((dp, mp))
    T, R = 8, 4
    E = max(8, mp * 4)
    mk = lambda: np.where(
        rng.random((T, E, R)) < 0.3, rng.integers(1, 9, (T, E, R)), 0
    ).astype(np.int32)
    cb = rng.integers(0, 5, (T, R)).astype(np.int32)
    cn = cb + rng.integers(0, 3, (T, R)).astype(np.int32)
    ab, rb, an, rn = mk(), mk(), mk(), mk()
    ref_code, ref_count = K.orset_plane_diff_tenants(cb, ab, rb, cn, an, rn)
    got_code, got_count = pmesh.tenant_diff_step(mesh)(cb, ab, rb, cn, an, rn)
    assert np.array_equal(np.asarray(ref_code), np.asarray(got_code))
    assert np.array_equal(np.asarray(ref_count), np.asarray(got_count))


def test_plane_diff_sharded_rejects_undivisible():
    mesh = pmesh.make_mesh((8, 1))
    z = np.zeros((6, 8, 4), np.int32)
    c = np.zeros((6, 4), np.int32)
    with pytest.raises(ValueError, match="pad first"):
        pmesh.tenant_plane_diff_sharded(mesh, c, z, z, c, z, z)


@pytest.mark.parametrize("use_mesh", [False, True])
def test_cut_pipeline_differential_through_mesh(use_mesh):
    """The same randomized pair cut solo and through the mesh twin
    packs to the same bytes as the host diff."""
    rng = random.Random(31)
    base = _rand_orset(rng, 50)
    new = _evolve(rng, base, 30)
    mesh = pmesh.make_mesh((8, 1)) if use_mesh else None
    dev = _cut_on_device(base, new, mesh=mesh)
    assert ucodec.pack(orset_delta_diff(base, new)) == ucodec.pack(dev)


# --------------------------------------- service: device cut + no-op


async def _write_orset(core, n, tag):
    for i in range(n):
        m = b"%s-%d" % (tag, i % 13)
        await core.apply_ops(
            [core.with_state(lambda s, m=m: s.add_ctx(core.actor_id, m))]
        )
        if i % 7 == 6:
            victim = b"%s-%d" % (tag, (i * 3) % 13)

            def rm(s, victim=victim):
                return s.rm_ctx(victim) if victim in s.entries else None

            op = core.with_state(rm)
            if op is not None:
                await core.apply_ops([op])


@pytest.mark.parametrize("mesh_spec", [None, (8, 1)])
def test_device_cut_cycle_differential(storage_factory, mesh_spec):
    """The ISSUE-16 end-to-end contract, memory+fs × solo/mesh: a
    continuation cycle seals its delta by device cut (base bytes
    dropped, ``delta_base_bytes`` 0), a quiet cycle honestly no-ops,
    the next active cycle cuts again from the re-stamped planes — and
    at every step the served tenant is byte-identical to a cold reader
    and a delta-chain consumer, with the seal-time self-verify on."""
    mesh = pmesh.make_mesh(mesh_spec) if mesh_spec else None

    async def go():
        writer = await Core.open(make_opts(storage_factory("w")))
        served = await Core.open(
            make_opts(storage_factory("s"), delta=True)
        )
        service = FoldService([served], ServeConfig(), mesh=mesh)

        await _write_orset(writer, 30, b"a")
        trace.reset()
        (r1,) = await service.run_cycle()
        assert r1.sealed and r1.path == "batched"
        assert counters().get("serve_continuations") == 1

        await _write_orset(writer, 10, b"b")
        trace.reset()
        (r2,) = await service.run_cycle()
        assert r2.sealed
        assert counters().get("delta_device_cuts") == 1
        assert counters().get("delta_files_sealed") == 1
        assert not counters().get("delta_seal_divergence")
        assert gauges().get("delta_base_bytes") == 0

        # quiet cycle: the honest no-op (and no re-seal)
        trace.reset()
        (r3,) = await service.run_cycle()
        assert r3.path == "empty" and not r3.sealed
        assert counters().get("serve_noop_cycles") == 1
        assert not counters().get("delta_device_cuts")

        # the continuation survives the no-op: next active cycle cuts
        await _write_orset(writer, 7, b"c")
        trace.reset()
        (r4,) = await service.run_cycle()
        assert r4.sealed
        assert counters().get("delta_device_cuts") == 1

        cold = await Core.open(make_opts(storage_factory("cold")))
        await cold.read_remote()
        assert cold.with_state(canonical_bytes) == served.with_state(
            canonical_bytes
        )
        trace.reset()
        consumer = await Core.open(
            make_opts(storage_factory("consumer"), delta=True)
        )
        await consumer.read_remote()
        assert consumer.with_state(canonical_bytes) == served.with_state(
            canonical_bytes
        )

    run(go())


def test_device_cut_matches_host_diff_arm(storage_factory):
    """Differential against the path it replaces: an op stream served
    with the warm tier OFF (host dict-walk diff, retained base bytes)
    and ON (device cut, dropped base) must each stay byte-identical to
    the authoritative solo ``Core.compact()`` of their remote."""

    async def go():
        for arm in ("host", "cut"):
            writer = await Core.open(make_opts(storage_factory(f"w-{arm}")))
            served = await Core.open(
                make_opts(storage_factory(f"s-{arm}"), delta=True)
            )
            cfg = ServeConfig() if arm == "cut" else ServeConfig(warm=False)
            service = FoldService([served], cfg)
            trace.reset()
            for rnd in range(3):
                await _write_orset(writer, 12, b"r%d" % rnd)
                (res,) = await service.run_cycle()
                assert res.sealed
            if arm == "cut":
                assert counters().get("delta_device_cuts")
                assert gauges().get("delta_base_bytes") == 0
            else:
                assert not counters().get("delta_device_cuts")
            assert not counters().get("delta_seal_divergence")
            solo = await Core.open(make_opts(storage_factory(f"x-{arm}")))
            await solo.compact()
            assert solo.with_state(canonical_bytes) == served.with_state(
                canonical_bytes
            ), arm

    run(go())


@pytest.mark.parametrize("which", ["rcounter", "gcounter"])
def test_other_kinds_ride_the_continuation(storage_factory, which):
    """rcounter states ARE ORSets (adapter inheritance law) so they
    ride the device cut; gcounters take the continuation + no-op path
    with their own codec.  Both stay byte-identical to solo compact."""

    async def go():
        if which == "rcounter":
            adapter, delta = rcounter_adapter, True

            async def write(core, n, r):
                for i in range(n):
                    await core.apply_ops([core.with_state(
                        lambda s, i=i: ResettableCounter.inc(
                            s, core.actor_id, i + r + 1)
                    )])
        else:
            adapter, delta = gcounter_adapter, False

            async def write(core, n, r):
                for _ in range(n):
                    await core.apply_ops([core.with_state(
                        lambda s: s.inc(core.actor_id)
                    )])

        writer = await Core.open(make_opts(storage_factory("w"), adapter()))
        served = await Core.open(
            make_opts(storage_factory("s"), adapter(), delta=delta)
        )
        service = FoldService([served])
        trace.reset()
        for rnd in range(3):
            await write(writer, 10, rnd)
            (res,) = await service.run_cycle()
            assert res.sealed
        if which == "rcounter":
            assert counters().get("delta_device_cuts")
            assert not counters().get("delta_seal_divergence")
        # quiet cycle no-ops for every kind
        trace.reset()
        (rq,) = await service.run_cycle()
        assert rq.path == "empty" and not rq.sealed
        assert counters().get("serve_noop_cycles") == 1

        solo = await Core.open(make_opts(storage_factory("solo"), adapter()))
        await solo.compact()
        assert solo.with_state(canonical_bytes) == served.with_state(
            canonical_bytes
        )

    run(go())


# ----------------------------------------- fallbacks: doubt re-folds


def test_eviction_mid_continuation_falls_back_and_recovers(storage_factory):
    """A warm budget that only holds ONE tenant evicts the other each
    cycle: the evicted tenant full-re-folds next cycle (reason counted:
    ``serve_warm_evictions`` then ``serve_warm_misses``), no device cut
    for it — and every tenant still matches solo compact."""

    async def go():
        writers, served = [], []
        for t in range(2):
            writers.append(
                await Core.open(make_opts(storage_factory(f"w{t}")))
            )
            served.append(await Core.open(
                make_opts(storage_factory(f"s{t}"), delta=True)
            ))
        service = FoldService(served, ServeConfig(warm_bytes=64))
        for t in range(2):
            await _write_orset(writers[t], 20, b"t%d" % t)
        trace.reset()
        r = await service.run_cycle()
        assert all(x.sealed for x in r)
        assert counters().get("serve_warm_evictions")

        for t in range(2):
            await _write_orset(writers[t], 8, b"u%d" % t)
        trace.reset()
        r = await service.run_cycle()
        assert all(x.sealed for x in r)
        assert counters().get("serve_warm_misses")  # the evicted tenant
        # at most one tenant can be plane-resident under this budget
        assert counters().get("delta_device_cuts", 0) <= 1
        assert not counters().get("delta_seal_divergence")

        for t in range(2):
            solo = await Core.open(make_opts(storage_factory(f"solo{t}")))
            await solo.compact()
            assert solo.with_state(canonical_bytes) == served[
                t
            ].with_state(canonical_bytes)

    run(go())


@pytest.mark.parametrize("mesh_spec", [None, (8, 1)])
def test_mut_epoch_bump_mid_continuation_refolds(storage_factory, mesh_spec):
    """A local mutation on the served core between cycles bumps the mut
    epoch: the stamped warm entry's token no longer matches, the next
    cycle counts ``serve_warm_expired`` and re-folds fully — and the
    result is still byte-identical to solo compact."""
    mesh = pmesh.make_mesh(mesh_spec) if mesh_spec else None

    async def go():
        writer = await Core.open(make_opts(storage_factory("w")))
        served = await Core.open(
            make_opts(storage_factory("s"), delta=True)
        )
        service = FoldService([served], mesh=mesh)
        await _write_orset(writer, 20, b"a")
        (r1,) = await service.run_cycle()
        assert r1.sealed

        # the mid-continuation local mutation
        await served.apply_ops([served.with_state(
            lambda s: s.add_ctx(served.actor_id, b"local-op")
        )])
        await _write_orset(writer, 8, b"b")
        trace.reset()
        (r2,) = await service.run_cycle()
        assert r2.sealed
        assert counters().get("serve_warm_expired")
        assert not counters().get("delta_device_cuts")
        assert not counters().get("delta_seal_divergence")

        solo = await Core.open(make_opts(storage_factory("solo")))
        await solo.compact()
        assert solo.with_state(canonical_bytes) == served.with_state(
            canonical_bytes
        )

    run(go())


def test_dropped_base_without_cut_reanchors_snapshot_only(storage_factory):
    """After a device cut dropped the base bytes, a cycle whose cut is
    invalid (fresh service: no stamped planes) must NOT fabricate a
    delta: it counts ``delta_cut_fallbacks`` + ``delta_seal_skipped``,
    re-anchors with a snapshot-only link, and the NEXT cycle deltas
    again — consumers stay byte-identical throughout."""

    async def go():
        writer = await Core.open(make_opts(storage_factory("w")))
        served = await Core.open(
            make_opts(storage_factory("s"), delta=True)
        )
        service = FoldService([served])
        await _write_orset(writer, 20, b"a")
        await service.run_cycle()
        await _write_orset(writer, 8, b"b")
        trace.reset()
        await service.run_cycle()
        assert counters().get("delta_device_cuts") == 1
        assert gauges().get("delta_base_bytes") == 0

        # a FRESH service has no warm planes for the stamped seal — the
        # dropped base cannot be diffed on host either
        service2 = FoldService([served])
        await _write_orset(writer, 8, b"c")
        trace.reset()
        (r,) = await service2.run_cycle()
        assert r.sealed
        assert counters().get("delta_cut_fallbacks") == 1
        assert counters().get("delta_seal_skipped") == 1
        assert not counters().get("delta_files_sealed")

        # self-healing: the snapshot-only link re-retained bytes, so
        # the chain deltas again (host diff now, cut after re-stamp)
        await _write_orset(writer, 6, b"d")
        trace.reset()
        await service2.run_cycle()
        assert counters().get("delta_files_sealed") == 1

        consumer = await Core.open(
            make_opts(storage_factory("consumer"), delta=True)
        )
        await consumer.read_remote()
        assert consumer.with_state(canonical_bytes) == served.with_state(
            canonical_bytes
        )
        from crdt_enc_tpu.tools.fsck import fsck_remote

        report = await fsck_remote(
            storage_factory("fsck"), IdentityCryptor(), PlainKeyCryptor(),
            deep=True,
        )
        assert report.ok, [str(i) for i in report.issues]

    run(go())


# ------------------------------------------------ the CI idle gate


class SpyStorage(MemoryStorage):
    """Counts every storage call, split into LISTING probes (cursor
    staleness checks — allowed every cycle) and everything else (loads,
    stores, removes — forbidden for a quiet tenant's no-op cycle)."""

    LISTING = frozenset({
        "list_remote_meta_names", "list_state_names", "list_op_actors",
        "stat_ops", "list_delta_actors",
    })

    def __init__(self, remote):
        super().__init__(remote)
        self.calls: dict = {}

    def __getattribute__(self, name):
        attr = super().__getattribute__(name)
        if (not name.startswith("_") and callable(attr)
                and name not in ("calls",)
                and asyncio.iscoroutinefunction(attr)):
            calls = super().__getattribute__("calls")

            async def counted(*a, **kw):
                calls[name] = calls.get(name, 0) + 1
                return await attr(*a, **kw)

            return counted
        return attr


def test_quiet_steady_state_cycle_is_listing_only():
    """The run_checks idle-cycle gate: a quiet tenant's steady-state
    cycle performs ZERO XLA compiles, ZERO state H2D bytes, ZERO
    storage calls beyond the listing probes — and honestly counts
    itself as a no-op, one per tenant."""
    obs_runtime.track_recompiles()

    async def go():
        tenants = 4
        spies, served = [], []
        for t in range(tenants):
            remote = MemoryRemote()
            writer = await Core.open(make_opts(MemoryStorage(remote)))
            await _write_orset(writer, 15, b"t%d" % t)
            spy = SpyStorage(remote)
            spies.append(spy)
            served.append(
                await Core.open(make_opts(spy, delta=True))
            )
        service = FoldService(served)
        await service.run_cycle()  # active: fold + seal + stamp
        await service.run_cycle()  # first quiet: settles bookkeeping

        for spy in spies:
            spy.calls.clear()
        trace.reset()
        results = await service.run_cycle()  # THE quiet cycle
        assert all(r.path == "empty" and not r.sealed for r in results)
        c = counters()
        assert c.get("serve_noop_cycles") == tenants
        assert not c.get("jax_compiles")
        assert not c.get("h2d_bytes")
        assert not c.get("delta_device_cuts")
        for spy in spies:
            beyond = {
                k: v for k, v in spy.calls.items()
                if k not in SpyStorage.LISTING
            }
            assert not beyond, beyond

    run(go())


def test_noop_skip_off_is_the_reseal_arm():
    """``ServeConfig(noop_skip=False)`` restores the O(state) steady
    state the bench compares against: every quiet cycle re-seals."""

    async def go():
        remote = MemoryRemote()
        writer = await Core.open(make_opts(MemoryStorage(remote)))
        await _write_orset(writer, 15, b"a")
        served = await Core.open(make_opts(MemoryStorage(remote)))
        service = FoldService([served], ServeConfig(noop_skip=False))
        await service.run_cycle()
        trace.reset()
        (r,) = await service.run_cycle()  # quiet, but re-seals
        assert r.path == "empty" and r.sealed
        assert not counters().get("serve_noop_cycles")

    run(go())


# -------------------------------------------------- CI trend gate


def test_idle_cycle_metric_rides_the_trend_gate():
    """The committed ``--e2e-idle-cycle`` record is a first-class
    ``obs_report trend`` config: ≥10x at 1% active on a 256-tenant
    fleet, and the ``--fail-on-regression`` gate math applies to it."""
    import pathlib

    from crdt_enc_tpu.obs import fleet, sink

    bench_local = pathlib.Path(__file__).parent.parent / "BENCH_LOCAL.jsonl"
    records = sink.read_records(str(bench_local))
    trend = fleet.bench_trend(records, metric="idle_cycle_speedup")
    assert trend, "committed BENCH_LOCAL carries no idle-cycle record"
    cfg = trend[0]
    assert cfg["shape"]["tenants"] >= 256
    assert cfg["latest"] >= 10.0  # the ISSUE-16 bar
    rec = next(r for r in records if r.get("metric") == "idle_cycle_speedup")
    one_pct = [r for r in rec["continuation"]
               if r["active_fraction"] == 0.01][0]
    assert one_pct["jax_compiles"] == 0
    assert one_pct["delta_base_bytes"] == 0
    assert one_pct["serve_noop_cycles"] > 0
    assert rec["byte_identical"] is True
    regressed = dict(rec, value=cfg["best"] / 2)
    t2 = fleet.bench_trend(
        records + [regressed], metric="idle_cycle_speedup"
    )
    assert fleet.trend_regressions(t2, 10)

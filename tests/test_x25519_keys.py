"""Recipient-keyed (X25519+Ed25519) key cryptor: the asymmetric backend the
reference's gpgme plugin stubbed out (its PGP calls are commented out,
crdt-enc-gpgme/src/lib.rs:131-175).  No shared secret: each replica holds a
private identity; readability is membership in a signed recipient roster,
and hostile storage can neither tamper, forge, nor poison the roster."""

import asyncio

import pytest

pytest.importorskip(
    "cryptography",
    reason="x25519_keys backend needs the cryptography wheel",
)

from crdt_enc_tpu.backends import FsStorage, XChaChaCryptor
from crdt_enc_tpu.backends.x25519_keys import (
    NotARecipient,
    UntrustedSigner,
    X25519KeyCryptor,
    generate_identity,
    unwrap_blob,
    wrap_blob,
)
from crdt_enc_tpu.core import Core, CoreError, OpenOptions, orset_adapter
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.utils import codec
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


# ---- wrap/unwrap primitives ----------------------------------------------


def test_wrap_unwrap_roundtrip_multi_recipient():
    priv_a, pub_a = generate_identity()
    priv_b, pub_b = generate_identity()
    blob = wrap_blob(b"the keys crdt", [pub_a, pub_b], priv_a)
    trusted = {pub_a, pub_b}
    clear_a, roster_a, signer_a = unwrap_blob(priv_a, blob, trusted)
    clear_b, roster_b, signer_b = unwrap_blob(priv_b, blob, trusted)
    assert clear_a == clear_b == b"the keys crdt"
    assert set(roster_a) == set(roster_b) == trusted
    assert signer_a == signer_b == pub_a


def test_non_recipient_rejected():
    priv_a, pub_a = generate_identity()
    priv_eve, pub_eve = generate_identity()
    blob = wrap_blob(b"secret", [pub_a], priv_a)
    with pytest.raises(NotARecipient):
        # eve trusts A (knows the real roster) but is not sealed to
        unwrap_blob(priv_eve, blob, {pub_a, pub_eve})


def test_forged_blob_rejected():
    """Hostile storage can build a valid-looking blob (sealing needs only
    public keys) — but it cannot sign as a trusted identity."""
    priv_a, pub_a = generate_identity()
    priv_eve, pub_eve = generate_identity()
    forged = wrap_blob(b"attacker keys", [pub_a, pub_eve], priv_eve)
    with pytest.raises(UntrustedSigner):
        unwrap_blob(priv_a, forged, {pub_a})


def test_tampered_roster_rejected():
    """Appending an attacker identity to the wraps/roster breaks the
    signature — the roster-poisoning vector the signing exists to close."""
    priv_a, pub_a = generate_identity()
    _, pub_eve = generate_identity()
    blob = wrap_blob(b"secret", [pub_a], priv_a)
    body, signer_pub, sig = codec.unpack(blob)
    eph_pub, sealed, roster, wraps = codec.unpack(bytes(body))
    roster = [bytes(r) for r in roster] + [pub_eve]
    tampered_body = codec.pack([bytes(eph_pub), bytes(sealed), roster, wraps])
    tampered = codec.pack([tampered_body, signer_pub, sig])
    with pytest.raises(UntrustedSigner):
        unwrap_blob(priv_a, tampered, {pub_a})


def test_tampered_bytes_rejected():
    priv_a, pub_a = generate_identity()
    blob = bytearray(wrap_blob(b"secret", [pub_a], priv_a))
    blob[-1] ^= 0x01
    with pytest.raises((UntrustedSigner, NotARecipient)):
        unwrap_blob(priv_a, bytes(blob), {pub_a})


def test_fresh_ephemeral_per_write():
    priv_a, pub_a = generate_identity()
    assert wrap_blob(b"x", [pub_a], priv_a) != wrap_blob(b"x", [pub_a], priv_a)


# ---- through the core -----------------------------------------------------


def make_opts(tmp_path, name, priv, recipients, create=True, **kc_kw):
    return OpenOptions(
        storage=FsStorage(str(tmp_path / name), str(tmp_path / "remote")),
        cryptor=XChaChaCryptor(),
        key_cryptor=X25519KeyCryptor(priv, recipients, **kc_kw),
        adapter=orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
    )


def test_two_recipient_replicas_converge(tmp_path):
    priv_a, pub_a = generate_identity()
    priv_b, pub_b = generate_identity()
    roster = [pub_a, pub_b]

    async def go():
        c1 = await Core.open(make_opts(tmp_path, "a", priv_a, roster))
        await c1.update(lambda s: s.add_ctx(c1.actor_id, b"x"))
        c2 = await Core.open(make_opts(tmp_path, "b", priv_b, roster))
        await c2.read_remote()
        assert c2.with_state(lambda s: s.contains(b"x"))
        # key material converged without any shared secret
        k1 = c1._data.keys.latest_key()
        k2 = c2._data.keys.latest_key()
        assert k1.id == k2.id and k1.material == k2.material
        assert c1.with_state(canonical_bytes) == c2.with_state(canonical_bytes)

    run(go())


def test_outsider_cannot_join(tmp_path):
    priv_a, pub_a = generate_identity()
    priv_eve, pub_eve = generate_identity()

    async def go():
        c1 = await Core.open(make_opts(tmp_path, "a", priv_a, [pub_a]))
        await c1.update(lambda s: s.add_ctx(c1.actor_id, b"x"))
        # eve knows the roster but her identity is not sealed to: she never
        # obtains a data key
        with pytest.raises((NotARecipient, CoreError)):
            await Core.open(make_opts(tmp_path, "eve", priv_eve, [pub_a]))

    run(go())


def test_rotation_under_recipient_keys(tmp_path):
    priv_a, pub_a = generate_identity()
    priv_b, pub_b = generate_identity()
    roster = [pub_a, pub_b]

    async def go():
        c1 = await Core.open(make_opts(tmp_path, "a", priv_a, roster))
        await c1.update(lambda s: s.add_ctx(c1.actor_id, b"old"))
        await c1.rotate_key()
        await c1.update(lambda s: s.add_ctx(c1.actor_id, b"new"))
        c2 = await Core.open(make_opts(tmp_path, "b", priv_b, roster))
        await c2.read_remote()
        assert set(c2.with_state(lambda s: s.members())) == {b"old", b"new"}

    run(go())


def test_stale_roster_writer_cannot_lock_out_peers(tmp_path):
    """Regression: a device restarted with a stale roster must not seal
    future key material away from peers an earlier writer admitted — the
    roster converges grow-only from every VERIFIED blob it opens."""
    priv_a, pub_a = generate_identity()
    priv_b, pub_b = generate_identity()

    async def go():
        # A knows both devices; writes the initial key metadata
        c_a = await Core.open(make_opts(tmp_path, "a", priv_a, [pub_a, pub_b]))
        await c_a.update(lambda s: s.add_ctx(c_a.actor_id, b"x"))

        # A restarts with a STALE roster (only itself) and rotates
        kc = X25519KeyCryptor(priv_a, [])  # stale: B missing
        opts = make_opts(tmp_path, "a2", priv_a, [])
        opts.key_cryptor = kc
        c_a2 = await Core.open(opts)
        # opening ingested A's old (self-signed, trusted) blob → roster
        # converged to include B
        assert pub_b in kc.recipients
        await c_a2.rotate_key()
        await c_a2.update(lambda s: s.add_ctx(c_a2.actor_id, b"y"))

        # B can still read everything, including post-rotation writes
        c_b = await Core.open(make_opts(tmp_path, "b", priv_b, [pub_a, pub_b]))
        await c_b.read_remote()
        assert set(c_b.with_state(lambda s: s.members())) == {b"x", b"y"}

    run(go())


def test_pinned_roster_revocation(tmp_path):
    """pin_recipients=True is the deliberate revocation path: after a
    rotation under a pinned roster, the revoked device cannot read keys
    sealed from then on."""
    priv_a, pub_a = generate_identity()
    priv_b, pub_b = generate_identity()

    async def go():
        c_a = await Core.open(make_opts(tmp_path, "a", priv_a, [pub_a, pub_b]))
        await c_a.update(lambda s: s.add_ctx(c_a.actor_id, b"x"))

        # revoke B: pinned roster without B, then rotate
        opts = make_opts(tmp_path, "a2", priv_a, [])
        opts.key_cryptor = X25519KeyCryptor(priv_a, [pub_a], pin_recipients=True)
        c_a2 = await Core.open(opts)
        await c_a2.rotate_key()

        with pytest.raises((NotARecipient, CoreError)):
            await Core.open(make_opts(tmp_path, "b", priv_b, [pub_a, pub_b]))

    run(go())


def test_unreadable_concurrent_value_tolerated(tmp_path):
    """A register holding one value this replica can open and one it
    cannot (signed by a trusted peer but sealed only to that peer — a
    stale concurrent writer) must still decode — per-value tolerance,
    not all-or-nothing (DECODE_TOLERATES)."""
    import uuid as uuidm

    from crdt_enc_tpu.core.core import RemoteMeta
    from crdt_enc_tpu.core.key_cryptor import Key, Keys
    from crdt_enc_tpu.models import MVReg
    from crdt_enc_tpu.utils import VersionBytes
    from crdt_enc_tpu.utils.mvreg_codec import encode_version_bytes_mvreg
    from crdt_enc_tpu.utils.versions import CURRENT_CONTAINER_VERSION

    priv_a, pub_a = generate_identity()
    priv_b, pub_b = generate_identity()

    async def go():
        c_a = await Core.open(make_opts(tmp_path, "a", priv_a, [pub_a, pub_b]))
        await c_a.update(lambda s: s.add_ctx(c_a.actor_id, b"x"))

        # B (trusted by A) concurrently writes key metadata sealed ONLY to
        # itself — craft the register value directly, as a stale process
        # that never read A's metadata would produce it
        kc_b = X25519KeyCryptor(priv_b, [pub_b], pin_recipients=True)
        keys_b = Keys()
        keys_b.insert_latest_key(
            uuidm.uuid4().bytes,
            Key.new(VersionBytes(DEFAULT_DATA_VERSION_1, b"\x00" * 32)),
        )
        reg = MVReg()
        await encode_version_bytes_mvreg(
            reg, keys_b, uuidm.uuid4().bytes, kc_b.META_VERSION,
            transform=kc_b._protect,
        )
        inj = FsStorage(str(tmp_path / "inj"), str(tmp_path / "remote"))
        rm = RemoteMeta(key_cryptor=reg)
        await inj.store_remote_meta(
            VersionBytes(CURRENT_CONTAINER_VERSION, codec.pack(rm.to_obj())).serialize()
        )

        # A re-reads: the register now holds A's value (readable) and B's
        # (trusted signer, but A is not a recipient) — must not raise, and
        # A's own key material must survive
        await c_a.read_remote()
        assert c_a.with_state(lambda s: s.contains(b"x"))
        assert c_a._data.keys.latest_key() is not None
        await c_a.update(lambda s: s.add_ctx(c_a.actor_id, b"y"))

    run(go())


def test_roster_trust_growth_reaches_fixpoint(monkeypatch):
    """Two concurrent register values: one signed by A (trusted), whose
    roster introduces B; one signed by B carrying a rotated latest key.
    The decode must recover B's key material REGARDLESS of MVReg value
    order — a single-pass decode tolerate-skipped B's value whenever it
    was processed before A's roster introduced B, silently dropping the
    rotated latest key (advisor finding, round 1)."""
    from crdt_enc_tpu.core.key_cryptor import Key, Keys
    from crdt_enc_tpu.models import MVReg
    from crdt_enc_tpu.utils import VersionBytes

    priv_a, pub_a = generate_identity()
    priv_b, pub_b = generate_identity()
    priv_c, pub_c = generate_identity()
    roster = [pub_a, pub_b, pub_c]

    actor_a, actor_b = b"A" * 16, b"B" * 16
    key1 = Key.new(VersionBytes(DEFAULT_DATA_VERSION_1, b"\x01" * 32))
    key2 = Key.new(VersionBytes(DEFAULT_DATA_VERSION_1, b"\x02" * 32))
    keys_a = Keys()
    keys_a.insert_latest_key(actor_a, key1)
    keys_b = Keys.from_obj(keys_a.to_obj())
    keys_b.insert_latest_key(actor_b, key2)  # B rotated the latest key

    def reg_value(keys, signer_priv):
        blob = wrap_blob(codec.pack(keys.to_obj()), roster, signer_priv)
        return VersionBytes(X25519KeyCryptor.META_VERSION, blob).to_obj()

    reg_a, reg_b = MVReg(), MVReg()
    reg_a.apply(reg_a.write_ctx(actor_a, reg_value(keys_a, priv_a)))
    reg_b.apply(reg_b.write_ctx(actor_b, reg_value(keys_b, priv_b)))
    reg_a.merge(reg_b)
    assert len(reg_a.read().values) == 2  # genuinely concurrent

    class CoreStub:
        keys = None

        def set_keys(self, keys):
            self.keys = keys

    async def decode_with_order(reverse: bool):
        kc = X25519KeyCryptor(priv_c, [pub_a])  # trusts only A (+ itself)
        stub = CoreStub()
        await kc.init(stub)
        if reverse:
            orig_read = MVReg.read

            def rev_read(self):
                ctx = orig_read(self)
                ctx.values = list(reversed(ctx.values))
                return ctx

            monkeypatch.setattr(MVReg, "read", rev_read)
        try:
            await kc.set_remote_meta(MVReg.from_obj(reg_a.to_obj()))
        finally:
            monkeypatch.undo()
        return stub.keys

    # both iteration orders must converge to the same full key set
    for reverse in (False, True):
        got = run(decode_with_order(reverse))
        assert got is not None
        assert got.get_key(key1.id) is not None
        assert got.get_key(key2.id) is not None, (
            f"rotated key lost to decode order (reverse={reverse})"
        )
        assert got.latest_key().id == key2.id

"""Recipient-keyed (X25519) key cryptor: the asymmetric backend the
reference's gpgme plugin stubbed out (its PGP calls are commented out,
crdt-enc-gpgme/src/lib.rs:131-175).  No shared secret: each replica holds a
private key; readability is membership in the recipient set."""

import asyncio

import pytest

from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    NotARecipient,
    X25519KeyCryptor,
    XChaChaCryptor,
    generate_keypair,
)
from crdt_enc_tpu.backends.x25519_keys import unwrap_blob, wrap_blob
from crdt_enc_tpu.core import Core, CoreError, OpenOptions, orset_adapter
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


# ---- wrap/unwrap primitives ----------------------------------------------


def test_wrap_unwrap_roundtrip_multi_recipient():
    priv_a, pub_a = generate_keypair()
    priv_b, pub_b = generate_keypair()
    blob = wrap_blob(b"the keys crdt", [pub_a, pub_b])
    clear_a, seen_a = unwrap_blob(priv_a, blob)
    clear_b, seen_b = unwrap_blob(priv_b, blob)
    assert clear_a == clear_b == b"the keys crdt"
    # the blob carries its recipient set, enabling roster convergence
    assert set(seen_a) == set(seen_b) == {pub_a, pub_b}


def test_non_recipient_rejected():
    _, pub_a = generate_keypair()
    priv_eve, _ = generate_keypair()
    blob = wrap_blob(b"secret", [pub_a])
    with pytest.raises(NotARecipient):
        unwrap_blob(priv_eve, blob)


def test_tampered_blob_rejected():
    priv_a, pub_a = generate_keypair()
    blob = bytearray(wrap_blob(b"secret", [pub_a]))
    blob[-1] ^= 0x01
    with pytest.raises(NotARecipient):
        unwrap_blob(priv_a, bytes(blob))


def test_fresh_ephemeral_per_write():
    priv_a, pub_a = generate_keypair()
    assert wrap_blob(b"x", [pub_a]) != wrap_blob(b"x", [pub_a])


# ---- through the core -----------------------------------------------------


def make_opts(tmp_path, name, priv, recipients, create=True):
    return OpenOptions(
        storage=FsStorage(str(tmp_path / name), str(tmp_path / "remote")),
        cryptor=XChaChaCryptor(),
        key_cryptor=X25519KeyCryptor(priv, recipients),
        adapter=orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
    )


def test_two_recipient_replicas_converge(tmp_path):
    priv_a, pub_a = generate_keypair()
    priv_b, pub_b = generate_keypair()
    roster = [pub_a, pub_b]

    async def go():
        c1 = await Core.open(make_opts(tmp_path, "a", priv_a, roster))
        await c1.update(lambda s: s.add_ctx(c1.actor_id, b"x"))
        c2 = await Core.open(make_opts(tmp_path, "b", priv_b, roster))
        await c2.read_remote()
        assert c2.with_state(lambda s: s.contains(b"x"))
        # key material converged without any shared secret
        k1 = c1._data.keys.latest_key()
        k2 = c2._data.keys.latest_key()
        assert k1.id == k2.id and k1.material == k2.material
        assert c1.with_state(canonical_bytes) == c2.with_state(canonical_bytes)

    run(go())


def test_outsider_cannot_join(tmp_path):
    priv_a, pub_a = generate_keypair()
    priv_eve, _pub_eve = generate_keypair()

    async def go():
        c1 = await Core.open(make_opts(tmp_path, "a", priv_a, [pub_a]))
        await c1.update(lambda s: s.add_ctx(c1.actor_id, b"x"))
        # eve's public key is not in the roster: the keys blob must refuse
        # to open, so she never obtains a data key
        with pytest.raises((NotARecipient, CoreError)):
            await Core.open(make_opts(tmp_path, "eve", priv_eve, [pub_a]))

    run(go())


def test_rotation_under_recipient_keys(tmp_path):
    priv_a, pub_a = generate_keypair()
    priv_b, pub_b = generate_keypair()
    roster = [pub_a, pub_b]

    async def go():
        c1 = await Core.open(make_opts(tmp_path, "a", priv_a, roster))
        await c1.update(lambda s: s.add_ctx(c1.actor_id, b"old"))
        await c1.rotate_key()
        await c1.update(lambda s: s.add_ctx(c1.actor_id, b"new"))
        c2 = await Core.open(make_opts(tmp_path, "b", priv_b, roster))
        await c2.read_remote()
        assert set(c2.with_state(lambda s: s.members())) == {b"old", b"new"}

    run(go())


def test_stale_roster_writer_cannot_lock_out_peers(tmp_path):
    """Regression: a device restarted with a stale roster must not seal
    future key material away from peers an earlier writer admitted — the
    roster converges grow-only from every blob it opens."""
    priv_a, pub_a = generate_keypair()
    priv_b, pub_b = generate_keypair()

    async def go():
        # A knows both devices; writes the initial key metadata
        c_a = await Core.open(make_opts(tmp_path, "a", priv_a, [pub_a, pub_b]))
        await c_a.update(lambda s: s.add_ctx(c_a.actor_id, b"x"))

        # A restarts with a STALE roster (only itself) and rotates
        kc = X25519KeyCryptor(priv_a, [])  # stale: B missing
        opts = make_opts(tmp_path, "a2", priv_a, [])
        opts.key_cryptor = kc
        c_a2 = await Core.open(opts)
        # opening ingested the old blob → roster converged to include B
        assert pub_b in kc.recipients
        await c_a2.rotate_key()
        await c_a2.update(lambda s: s.add_ctx(c_a2.actor_id, b"y"))

        # B can still read everything, including post-rotation writes
        c_b = await Core.open(make_opts(tmp_path, "b", priv_b, [pub_a, pub_b]))
        await c_b.read_remote()
        assert set(c_b.with_state(lambda s: s.members())) == {b"x", b"y"}

    run(go())


def test_pinned_roster_revocation(tmp_path):
    """pin_recipients=True is the deliberate revocation path: after a
    rotation under a pinned roster, the revoked device cannot read keys
    sealed from then on."""
    priv_a, pub_a = generate_keypair()
    priv_b, pub_b = generate_keypair()

    async def go():
        c_a = await Core.open(make_opts(tmp_path, "a", priv_a, [pub_a, pub_b]))
        await c_a.update(lambda s: s.add_ctx(c_a.actor_id, b"x"))

        # revoke B: pinned roster without B, then rotate
        opts = make_opts(tmp_path, "a2", priv_a, [])
        opts.key_cryptor = X25519KeyCryptor(priv_a, [pub_a], pin_recipients=True)
        c_a2 = await Core.open(opts)
        await c_a2.rotate_key()

        with pytest.raises((NotARecipient, CoreError)):
            await Core.open(make_opts(tmp_path, "b", priv_b, [pub_a, pub_b]))

    run(go())

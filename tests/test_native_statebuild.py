"""The native fresh-state sparse fold (native/statebuild.cpp) must be
byte-identical to the numpy/Python sparse fold it replaces on the
streaming path (ops/columnar.py orset_fold_sparse_host).

The native path engages only for empty-entries states (the streaming
shape — one combined fold into a fresh replica, BASELINE config 5);
differential coverage here forces both paths over the same inputs,
including pre-existing clocks (fresh entries, non-empty history) and
the int32/packed-sort fallback edges.
"""

from __future__ import annotations

import numpy as np
import pytest

from crdt_enc_tpu import native
from crdt_enc_tpu.models import ORSet
from crdt_enc_tpu.models.vclock import VClock
from crdt_enc_tpu.ops import columnar as C
from crdt_enc_tpu.utils import codec


def _gen(N, E, R, seed, rm=0.3, pad=0.05, maxc=500):
    rng = np.random.default_rng(seed)
    kind = (rng.random(N) < rm).astype(np.int8)
    member = rng.integers(0, E, N, dtype=np.int32)
    actor = rng.integers(0, R, N, dtype=np.int32)
    actor = np.where(rng.random(N) < pad, R, actor)
    counter = rng.integers(1, maxc, N, dtype=np.int32)
    return kind, member, actor, counter


def _fold_both(state_fn, kind, member, actor, counter, E, R, actors):
    outs = []
    for force_python in (False, True):
        st = state_fn()
        mem_v, rep_v = C.Vocab(range(E)), C.Vocab(actors)
        if force_python:
            orig = C._orset_fresh_fold_native
            C._orset_fresh_fold_native = lambda *a, **k: None
            try:
                r = C.orset_fold_sparse_host(
                    st, kind, member, actor, counter, mem_v, rep_v
                )
            finally:
                C._orset_fresh_fold_native = orig
        else:
            r = C.orset_fold_sparse_host(
                st, kind, member, actor, counter, mem_v, rep_v
            )
        outs.append(codec.pack(r.to_obj()))
    assert outs[0] == outs[1]


@pytest.mark.parametrize("seed", range(12))
def test_differential_random(seed):
    rng = np.random.default_rng(seed)
    N = int(rng.integers(1, 3000))
    E = int(rng.integers(1, 200))
    R = int(rng.integers(1, 500))
    actors = [b"a%06d" % i for i in range(R)]
    kind, member, actor, counter = _gen(N, E, R, seed)

    # fresh entries but a pre-existing clock: the replay gate and the
    # deferred-horizon filter must use it identically.  Drawn ONCE so
    # both paths fold from the same state.
    cl = {}
    if seed % 3 == 0:
        cl = {
            actors[int(i)]: int(c)
            for i, c in zip(rng.integers(0, R, 20), rng.integers(1, 100, 20))
        }

    def fresh():
        s = ORSet()
        s.clock = VClock(dict(cl))
        return s

    _fold_both(fresh, kind, member, actor, counter, E, R, actors)


def test_all_padding_and_empty():
    E, R = 8, 8
    actors = [b"a%d" % i for i in range(R)]
    kind = np.zeros(64, np.int8)
    member = np.zeros(64, np.int32)
    actor = np.full(64, R, np.int32)  # every row padding
    counter = np.ones(64, np.int32)
    _fold_both(ORSet, kind, member, actor, counter, E, R, actors)


def test_equal_horizon_kills_add():
    # strict >: an add whose counter equals the remove horizon dies
    E, R = 2, 2
    actors = [b"x", b"y"]
    kind = np.array([0, 1], np.int8)
    member = np.array([0, 0], np.int32)
    actor = np.array([0, 0], np.int32)
    counter = np.array([5, 5], np.int32)
    _fold_both(ORSet, kind, member, actor, counter, E, R, actors)
    st = ORSet()
    mem_v, rep_v = C.Vocab(range(E)), C.Vocab(actors)
    r = C.orset_fold_sparse_host(st, kind, member, actor, counter, mem_v, rep_v)
    assert not r.entries  # the add died on its own horizon


def test_int64_clock_falls_back():
    # a pre-existing clock past int32 must route to the Python path —
    # narrowing it would re-open the replay gate for stale ops
    E, R = 2, 2
    actors = [b"x", b"y"]
    st = ORSet()
    st.clock = VClock({b"x": 2 ** 40})
    kind = np.array([0], np.int8)
    member = np.array([0], np.int32)
    actor = np.array([0], np.int32)
    counter = np.array([7], np.int32)  # stale: 7 <= 2**40
    mem_v, rep_v = C.Vocab(range(E)), C.Vocab(actors)
    r = C.orset_fold_sparse_host(st, kind, member, actor, counter, mem_v, rep_v)
    assert not r.entries  # the stale add must NOT replay
    assert r.clock.get(b"x") == 2 ** 40


def test_int64_counter_falls_back():
    # counters past int32 must take the Python path, not corrupt
    E, R = 4, 4
    actors = [b"a%d" % i for i in range(R)]
    kind = np.array([0, 0], np.int8)
    member = np.array([1, 2], np.int32)
    actor = np.array([0, 1], np.int32)
    counter = np.array([2 ** 40, 7], np.int64)
    st = ORSet()
    mem_v, rep_v = C.Vocab(range(E)), C.Vocab(actors)
    r = C.orset_fold_sparse_host(st, kind, member, actor, counter, mem_v, rep_v)
    assert r.entries[1][b"a0"] == 2 ** 40
    assert r.entries[2][b"a1"] == 7
    # the merged clock must not wrap through an int32 narrowing (this
    # silently corrupted before round 4 — clock.astype(np.int32))
    assert r.clock.get(b"a0") == 2 ** 40


def test_bytes_lens_join_capacity_bound():
    """ADVICE r5 (medium) regression: the join pass is bounded by
    ``out_capacity`` — a blobs list that grew between the lengths pass
    and the join pass (pure Python runs between the two ctypes calls)
    returns -1 BEFORE writing past the buffer, and a clean join returns
    exactly the expected total so callers can detect staleness."""
    from crdt_enc_tpu import native

    try:
        slib = native.load_state()
    except RuntimeError as e:
        pytest.skip(f"native state library unavailable: {e}")
    blobs = [b"abc", b"defg", b"hi"]
    n = len(blobs)
    lens = np.zeros(n, np.uint64)
    total = int(slib.bytes_lens_join(
        blobs, lens.ctypes.data_as(native.u64p), None, 0, n
    ))
    assert total == 9 and lens.tolist() == [3, 4, 2]
    # join with exactly-sized capacity succeeds and fills the buffer
    out = np.zeros(total, np.uint8)
    assert int(slib.bytes_lens_join(
        blobs, lens.ctypes.data_as(native.u64p),
        out.ctypes.data_as(native.u8p), total, n,
    )) == total
    assert out.tobytes() == b"abcdefghi"
    # a list that GREW after sizing: rejected by the element-count bound
    # BEFORE any lens[] write (the lens array was sized for n) — and even
    # with the count unchecked (expected_n=-1) the join stops at the
    # capacity and reports -1, leaving the canary past the buffer's
    # logical end untouched
    blobs.append(b"overflow-blob")
    lens2 = np.zeros(len(blobs), np.uint64)
    guard = np.full(total + 1, 0xAB, np.uint8)
    assert int(slib.bytes_lens_join(
        blobs, lens2.ctypes.data_as(native.u64p),
        guard.ctypes.data_as(native.u8p), total, n,
    )) == -1
    assert int(slib.bytes_lens_join(
        blobs, lens2.ctypes.data_as(native.u64p),
        guard.ctypes.data_as(native.u8p), total, -1,
    )) == -1
    assert guard[total] == 0xAB
    # non-bytes element: -1 without touching the output
    assert int(slib.bytes_lens_join(
        [b"x", 7], lens2.ctypes.data_as(native.u64p), None, 0, 2
    )) == -1


def test_decrypt_blobs_packed_survives_blob_list_mutation():
    """End-to-end pin of the hardened join path: the bulk decrypt's
    lengths-pass → capacity-bounded join → verified-total sequence
    roundtrips correctly (the mutation fallback itself is pinned at the
    native layer in test_bytes_lens_join_capacity_bound — list mutation
    between the two passes cannot be scripted deterministically from
    here, but the -1/short-return it produces is)."""
    import secrets

    from crdt_enc_tpu.backends import xchacha

    try:
        native.load()
    except RuntimeError as e:
        pytest.skip(f"native crypto library unavailable: {e}")
    key = secrets.token_bytes(32)
    blobs = [xchacha.encrypt_blob(key, b"v%d" % i) for i in range(24)]
    out = xchacha.decrypt_blobs(key, blobs)
    assert [bytes(v) for v in out] == [b"v%d" % i for i in range(24)]
